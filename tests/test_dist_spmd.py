"""Cross-process (2 real processes) coverage for TP, ring-attention SP,
MoE EP, pipeline, and cross-process row-sharded PS tables — VERDICT r3
item 3: these previously ran only in-process on the virtual mesh. Each
test launches tests/dist_spmd_worker.py through the real launcher
(paddle_tpu.distributed.launch --simulate_cpu: gloo CPU collectives +
jax.distributed rendezvous) and compares against a single-process
reference computed here.

Reference pattern: tests/unittests/test_dist_base.py:506."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield


def _free_port_pair():
    import random
    import socket

    for _ in range(128):
        base = random.randint(20000, 60000)
        try:
            with socket.socket() as a, socket.socket() as b:
                a.bind(("127.0.0.1", base))
                b.bind(("127.0.0.1", base + 1))
            return base
        except OSError:
            continue
    raise RuntimeError("no free port pair found")


def _launch(mode, tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [
            sys.executable, "-m", "paddle_tpu.distributed.launch",
            "--nproc_per_node=2", f"--started_port={_free_port_pair()}",
            "--simulate_cpu",
            os.path.join(HERE, "dist_spmd_worker.py"), mode, str(tmp_path),
        ],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=540,
    )
    assert proc.returncode == 0, f"stdout:{proc.stdout}\nstderr:{proc.stderr}"


def test_tp_two_process_matches_single(tmp_path):
    """4-way BERT tensor parallelism across 2 processes (gspmd) matches the
    unsharded single-process loss trajectory."""
    from paddle_tpu.models import BertConfig, bert_pretrain

    _launch("tp", tmp_path)
    l0 = json.load(open(tmp_path / "losses_0.json"))
    l1 = json.load(open(tmp_path / "losses_1.json"))
    np.testing.assert_allclose(l0, l1, rtol=1e-6)

    b, s = 4, 64
    cfg = BertConfig(
        vocab_size=512, hidden_size=256, num_layers=2, num_heads=4,
        intermediate_size=1024, max_position=128,
    )
    rng = np.random.RandomState(0)
    feed = {
        "ids": rng.randint(0, cfg.vocab_size, (b, s)).astype("int64"),
        "types": rng.randint(0, 2, (b, s)).astype("int64"),
        "mask": np.ones((b, s), "float32"),
        "labels": rng.randint(0, cfg.vocab_size, (b, s)).astype("int64"),
    }
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        ids = fluid.data("ids", [b, s], "int64")
        types = fluid.data("types", [b, s], "int64")
        mask = fluid.data("mask", [b, s], "float32")
        labels = fluid.data("labels", [b, s], "int64")
        loss = bert_pretrain(ids, types, mask, labels, cfg, is_test=True)
        fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        ref = []
        for _ in range(3):
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
            ref.append(float(np.asarray(lv).reshape(-1)[0]))
    np.testing.assert_allclose(l0, ref, rtol=2e-4)


def test_ring_attention_two_process_matches_dense(tmp_path):
    """sp=4 ring attention across 2 processes, each feeding only its half
    of the sequence, reassembles to the dense attention output."""
    _launch("sp", tmp_path)
    b, h, s, d = 2, 2, 64, 8
    rng = np.random.RandomState(1)
    q = rng.randn(b, h, s, d).astype(np.float32)
    k = rng.randn(b, h, s, d).astype(np.float32)
    v = rng.randn(b, h, s, d).astype(np.float32)
    # dense causal reference
    scores = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    mask = np.tril(np.ones((s, s), bool))
    scores = np.where(mask[None, None], scores, -1e30)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    expect = np.einsum("bhqk,bhkd->bhqd", p, v)

    got = np.zeros_like(expect)
    seen = np.zeros(s, bool)
    for rank in (0, 1):
        z = np.load(tmp_path / f"out_{rank}.npz")
        for start, chunk in z.items():
            st = int(start)
            got[:, :, st:st + chunk.shape[2]] = chunk
            seen[st:st + chunk.shape[2]] = True
    assert seen.all(), "sequence shards from the 2 processes do not cover S"
    np.testing.assert_allclose(got, expect, rtol=2e-4, atol=2e-4)


def test_moe_two_process_matches_dense(tmp_path):
    """ep=4 expert parallelism across 2 processes equals the dense
    (unsharded) MoE layer output."""
    _launch("moe", tmp_path)
    b, s, h, e, f = 1, 16, 8, 8, 16
    rng = np.random.RandomState(0)
    x_np = rng.randn(b, s, h).astype("float32")
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        x = fluid.data("x", [b, s, h], "float32")
        out, _aux = layers.moe_ffn(
            x, num_experts=e, hidden_dim=f, axis_name="ep",
            param_attr_prefix="m0",
        )
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        (dense,) = exe.run(main, feed={"x": x_np}, fetch_list=[out],
                           scope=scope)
    for rank in (0, 1):
        got = np.load(tmp_path / f"out_{rank}.npy")
        np.testing.assert_allclose(got, np.asarray(dense), rtol=2e-5,
                                   atol=2e-5)


def test_pipeline_two_process_matches_plain(tmp_path):
    """pp=2 pipeline with one stage per PROCESS (boundary activations
    cross hosts) tracks plain single-process training."""
    _launch("pipe", tmp_path)
    l0 = json.load(open(tmp_path / "losses_0.json"))
    l1 = json.load(open(tmp_path / "losses_1.json"))
    np.testing.assert_allclose(l0, l1, rtol=1e-6)

    b, steps = 16, 4
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        x = fluid.data("x", [b, 8])
        y = fluid.data("y", [b, 1])
        hh = layers.fc(x, 16, act="relu",
                       param_attr=fluid.ParamAttr(name="w0"),
                       bias_attr=fluid.ParamAttr(name="b0"))
        pred = layers.fc(hh, 1,
                         param_attr=fluid.ParamAttr(name="w1"),
                         bias_attr=fluid.ParamAttr(name="b1"))
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        ref = []
        for i in range(steps):
            rngf = np.random.RandomState(i)
            xv = rngf.randn(b, 8).astype(np.float32)
            yv = (xv @ rngf.randn(8, 1)).astype(np.float32)
            (lv,) = exe.run(main, feed={"x": xv, "y": yv},
                            fetch_list=[loss], scope=scope)
            ref.append(float(np.asarray(lv).reshape(-1)[0]))
    # each step draws a fresh random target, so the trajectory is not
    # monotone — the step-for-step match above is the assertion
    np.testing.assert_allclose(l0, ref, rtol=2e-5)


def test_pstable_two_process_matches_single(tmp_path):
    """ps=4 row-sharded table across 2 processes — the
    stage_global(local_is_full=True) multi-host state path — trains to the
    same losses as the single-process run."""
    _launch("pstable", tmp_path)
    l0 = json.load(open(tmp_path / "losses_0.json"))
    l1 = json.load(open(tmp_path / "losses_1.json"))
    np.testing.assert_allclose(l0, l1, rtol=1e-6)

    vocab, dim, b, steps = 64, 8, 16, 3
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        ids = fluid.data("ids", [b], "int64")
        out = layers.sparse_embedding(
            ids, [vocab, dim], param_attr=fluid.ParamAttr(name="table"),
            pad_to_multiple=8,
        )
        loss = layers.reduce_mean(layers.square(out))
        fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        ref = []
        for i in range(steps):
            rngf = np.random.RandomState(10 + i)
            idv = rngf.randint(0, vocab, b).astype(np.int64)
            (lv,) = exe.run(main, feed={"ids": idv}, fetch_list=[loss],
                            scope=scope)
            ref.append(float(np.asarray(lv).reshape(-1)[0]))
    # random id draws per step: the trajectory is not monotone; the
    # step-for-step match above is the assertion
    np.testing.assert_allclose(l0, ref, rtol=2e-5)
