"""Serving subsystem tests: freeze parity, bucket padding, KV-cache decode
parity, warmup compile coverage, and graceful drain.

The small-classifier fixtures share one Scope/Executor per module so the
XLA compiles amortize across tests (the executor cache is keyed per
(program, feed-shapes, fetch-set) — exactly the digest the serving warmup
satellite is about)."""

import os
import signal
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, observability
from paddle_tpu.framework.scope import Scope, scope_guard
from paddle_tpu.serving import (
    GPTGenerator,
    Server,
    freeze_program,
)
from paddle_tpu.serving.router import (
    Endpoint,
    EndpointConfig,
    ServerDrainingError,
)


# ---------------------------------------------------------------------------
# fixtures: a trained-ish tiny classifier, frozen
# ---------------------------------------------------------------------------


class _Classifier:
    def __init__(self):
        self.scope = Scope()
        self.main, self.startup = fluid.Program(), fluid.Program()
        self.main.random_seed = self.startup.random_seed = 7
        with fluid.program_guard(self.main, self.startup):
            x = fluid.data("x", [-1, 16])
            lab = fluid.data("lab", [-1, 1], "int64")
            h = layers.fc(x, 32, act="relu")
            logits = layers.fc(h, 4)
            self.prob = layers.softmax(logits)
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, lab)
            )
            fluid.optimizer.Adam(1e-3).minimize(loss, self.startup)
        self.loss = loss
        self.exe = fluid.Executor()
        with scope_guard(self.scope):
            self.exe.run(self.startup, scope=self.scope)
            # a couple of real train steps so freeze sees trained state
            rng = np.random.RandomState(0)
            for _ in range(2):
                self.exe.run(
                    self.main,
                    feed={
                        "x": rng.randn(4, 16).astype(np.float32),
                        "lab": rng.randint(0, 4, (4, 1)).astype(np.int64),
                    },
                    fetch_list=[loss],
                    scope=self.scope,
                )
        self.frozen = freeze_program(
            self.main, [self.prob], feed_names=("x",)
        )


@pytest.fixture(scope="module")
def clf():
    return _Classifier()


# ---------------------------------------------------------------------------
# freeze
# ---------------------------------------------------------------------------


def test_freeze_drops_training_ops(clf):
    from paddle_tpu.analysis.structural import is_training_only_op

    ops = [op.type for op in clf.frozen.program.global_block.ops]
    assert not any(is_training_only_op(t) for t in ops), ops
    assert "softmax" in ops
    assert clf.frozen.meta["ops_pruned"] > 0
    assert clf.frozen.program._is_inference


def test_freeze_default_feeds_exclude_training_inputs(clf):
    """Without explicit feed_names the contract is the data vars the
    PRUNED graph reads — the label input must not survive into it (a
    router request would otherwise need a label array per submit)."""
    fm = freeze_program(clf.main, [clf.prob])
    assert fm.feed_names == ("x",), fm.feed_names


def test_generate_runner_rejects_mismatched_buckets():
    from paddle_tpu.errors import InvalidArgumentError
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.serving.generate import GPTGenerateRunner

    cfg = GPTConfig.tiny()
    cfg.use_fused_attention = False
    gen = GPTGenerator(cfg, batch=1, context_len=8, max_len=16)
    runner = GPTGenerateRunner(gen, max_new_tokens=4)
    with pytest.raises(InvalidArgumentError):
        Endpoint("gen", runner, EndpointConfig(buckets=(1, 2)))
    with pytest.raises(InvalidArgumentError):
        gen.generate(np.zeros((1, 8), np.int64), 0)


def test_freeze_parity_bitwise(clf):
    """Frozen outputs == clone(for_test=True) outputs, bitwise.

    The reference graph still CONTAINS the optimizer ops (fetch only
    selects outputs; the whole block executes), so it runs in a COPY of
    the scope — running it in clf.scope would silently train the shared
    fixture params (the exact hazard freeze_program removes)."""
    xa = np.random.RandomState(3).randn(4, 16).astype(np.float32)
    with scope_guard(clf.scope):
        (frozen_out,) = clf.exe.run(
            clf.frozen.program, feed={"x": xa},
            fetch_list=list(clf.frozen.fetch_names), scope=clf.scope,
        )
    ref_scope = Scope()
    for name in clf.scope.local_var_names():
        # host-copy: the reference run's optimizer ops DONATE their param
        # buffers; sharing arrays would invalidate clf.scope's copies
        ref_scope.set_var(
            name, np.array(np.asarray(clf.scope.find_var(name)))
        )
    test_prog = clf.main.clone(for_test=True)
    with scope_guard(ref_scope):
        (ref_out,) = clf.exe.run(
            test_prog,
            feed={"x": xa, "lab": np.zeros((4, 1), np.int64)},
            fetch_list=[clf.prob.name], scope=ref_scope,
        )
    np.testing.assert_array_equal(frozen_out, ref_out)


def test_freeze_strict_verify(clf):
    """A frozen program compiles under PADDLE_TPU_VERIFY=strict."""
    from paddle_tpu.analysis import set_verify_mode

    set_verify_mode("strict")
    try:
        scope = Scope()
        exe = fluid.Executor()
        with scope_guard(scope):
            exe.run(clf.startup, scope=scope)
            exe.run(
                clf.frozen.program,
                feed={"x": np.zeros((2, 16), np.float32)},
                fetch_list=list(clf.frozen.fetch_names), scope=scope,
            )
    finally:
        set_verify_mode(None)


def test_training_op_in_inference_finding():
    """The structural verifier flags training ops ONLY in programs marked
    as frozen inference graphs."""
    from paddle_tpu.analysis import verify_program
    from paddle_tpu.analysis.findings import TRAINING_OP_IN_INFERENCE

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [-1, 4])
        pred = layers.fc(x, 2)
        loss = layers.mean(pred)
        fluid.optimizer.SGD(0.1).minimize(loss, startup)
    report = verify_program(main, ("x",), (loss.name,))
    assert not report.by_category(TRAINING_OP_IN_INFERENCE)

    main._is_inference = True
    main._bump()  # invalidate the verify cache
    report = verify_program(main, ("x",), (loss.name,))
    found = report.by_category(TRAINING_OP_IN_INFERENCE)
    assert found and found[0].severity.name == "ERROR"
    assert any(f.op_type == "sgd" for f in found)


def test_freeze_refuses_training_fetch(clf):
    """Fetching a var produced by the optimizer keeps the update op in the
    slice; freeze must refuse, not silently serve a mutating graph."""
    from paddle_tpu.errors import ProgramVerifyError

    w = clf.main.global_block.all_parameters()[0]
    with pytest.raises(ProgramVerifyError):
        freeze_program(clf.main, [w.name], feed_names=("x", "lab"))


def test_freeze_int8_leg(clf):
    """int8_scales bakes fixed-scale qdq chains into the frozen graph and
    the graph still runs (outputs close to the fp32 freeze)."""
    xa = np.random.RandomState(5).randn(4, 16).astype(np.float32)
    with scope_guard(clf.scope):
        (ref,) = clf.exe.run(
            clf.frozen.program, feed={"x": xa},
            fetch_list=list(clf.frozen.fetch_names), scope=clf.scope,
        )
    # calibrated activation scales for every quantizable-op input
    scales = {}
    blk = clf.main.clone(for_test=True).global_block
    for op in blk.ops:
        if op.type in ("mul", "matmul"):
            for n in op.input_names():
                scales.setdefault(n, 4.0)
    fm8 = freeze_program(
        clf.main, [clf.prob], feed_names=("x",), int8_scales=scales
    )
    assert fm8.int8
    qdq = [
        op.type for op in fm8.program.global_block.ops
        if "quantize" in op.type
    ]
    assert qdq, "INT8 freeze inserted no quant-dequant ops"
    with scope_guard(clf.scope):
        (q_out,) = clf.exe.run(
            fm8.program, feed={"x": xa},
            fetch_list=list(fm8.fetch_names), scope=clf.scope,
        )
    np.testing.assert_allclose(q_out, ref, atol=0.15)


# ---------------------------------------------------------------------------
# router: bucketing, padding, warmup
# ---------------------------------------------------------------------------


def test_bucket_padding_row_correctness(clf):
    """Row b of a padded bucket run equals the same request served alone
    (the acceptance contract for zero-padding into buckets)."""
    server = Server()
    server.add_endpoint(
        "clf", None,
        EndpointConfig(buckets=(1, 2, 4), max_wait_ms=2.0),
        frozen=clf.frozen, executor=clf.exe, scope=clf.scope,
    )
    server.warmup()
    rng = np.random.RandomState(11)
    samples = [rng.randn(16).astype(np.float32) for _ in range(3)]
    futs = [server.submit("clf", {"x": s}) for s in samples]
    got = [f.result(timeout=10)[0] for f in futs]
    server.drain(timeout=5)
    for s, row in zip(samples, got):
        with scope_guard(clf.scope):
            (alone,) = clf.exe.run(
                clf.frozen.program, feed={"x": s[None]},
                fetch_list=list(clf.frozen.fetch_names), scope=clf.scope,
            )
        np.testing.assert_allclose(row, alone[0], rtol=1e-5, atol=1e-6)


def test_warmup_covers_every_bucket_and_fetch_set(clf):
    """Regression for the per-fetch-set executable digest: after warmup,
    NO latency-measured request may trace — a cold (bucket, fetch-set)
    pair would push a multi-second compile into a request."""
    server = Server()
    server.add_endpoint(
        "clf", None,
        EndpointConfig(buckets=(1, 2, 4, 8), max_wait_ms=1.0),
        frozen=clf.frozen, executor=clf.exe, scope=clf.scope,
    )
    server.warmup()
    c0 = observability.get_counters().get("executor.compile_count", 0)
    rng = np.random.RandomState(0)
    # hit every bucket size: 1, 2, 4, 8 and a padded 3->4
    for n in (1, 2, 3, 8):
        futs = [
            server.submit("clf", {"x": rng.randn(16).astype(np.float32)})
            for _ in range(n)
        ]
        for f in futs:
            f.result(timeout=10)
    c1 = observability.get_counters().get("executor.compile_count", 0)
    server.drain(timeout=5)
    assert c1 == c0, (
        f"{c1 - c0} compile(s) inside latency-measured requests — warmup "
        "missed a (bucket-shape, fetch-set) pair"
    )
    # negative control: the SAME bucket shape with a DIFFERENT fetch set
    # is a different executable digest (the bug the warmup must mirror)
    with scope_guard(clf.scope):
        clf.exe.run(
            clf.frozen.program,
            feed={"x": np.zeros((8, 16), np.float32)},
            fetch_list=[], scope=clf.scope,
        )
    c2 = observability.get_counters().get("executor.compile_count", 0)
    assert c2 == c1 + 1, "fetch-set change did not re-key the executable"


class _StubRunner:
    """Executor-free runner: doubles its input, optional per-batch delay.
    Lets the queue/batcher/drain machinery run without XLA in the loop."""

    feed_names = ("x",)

    def __init__(self, delay=0.0):
        self.delay = delay
        self.batches = []

    def sample_spec(self, name):
        return (2,), "float32"

    def run(self, feed):
        if self.delay:
            time.sleep(self.delay)
        self.batches.append(feed["x"].shape[0])
        return [feed["x"] * 2.0]


def test_router_continuous_batching_metrics():
    runner = _StubRunner()
    ep = Endpoint(
        "stub", runner, EndpointConfig(buckets=(2, 4), max_wait_ms=20.0)
    )
    futs = [
        ep.submit({"x": np.full(2, i, np.float32)}) for i in range(4)
    ]
    got = [f.result(timeout=5)[0] for f in futs]
    ep.drain(timeout=5)
    for i, row in enumerate(got):
        np.testing.assert_array_equal(row, np.full(2, 2.0 * i))
    c = observability.get_counters()
    assert c.get("serving.requests_served", 0) >= 4
    assert c.get("serving.batches", 0) >= 1
    h = observability.get_histograms()
    assert h["serving.request_latency"]["count"] >= 4
    assert h["serving.batch_fill"]["count"] >= 1


def test_router_rejects_on_full_queue():
    from paddle_tpu.errors import PreconditionNotMetError

    runner = _StubRunner(delay=0.2)
    ep = Endpoint(
        "tiny", runner,
        EndpointConfig(buckets=(1,), max_wait_ms=0.0, max_queue=2),
    )
    futs, rejected = [], 0
    for i in range(12):
        try:
            futs.append(ep.submit({"x": np.zeros(2, np.float32)}))
        except PreconditionNotMetError:
            rejected += 1
    assert rejected > 0, "queue bound never shed load"
    for f in futs:
        f.result(timeout=20)
    ep.drain(timeout=20)
    assert observability.get_counters().get("serving.rejected", 0) > 0


# ---------------------------------------------------------------------------
# KV-cache decode
# ---------------------------------------------------------------------------


def _np_ref_cache_attention(q, k, v, pos, nh, scale, prob_scale=1.0):
    b, t, h = q.shape
    s = k.shape[1]
    dh = h // nh
    qh = q.reshape(b, t, nh, dh).transpose(0, 2, 1, 3)
    kh = k.reshape(b, s, nh, dh).transpose(0, 2, 3, 1)
    scores = (qh @ kh) * scale
    qpos = pos - (t - 1) + np.arange(t)
    mask = np.arange(s)[None, None, None, :] <= qpos[None, None, :, None]
    scores = np.where(mask, scores, -1e9)
    e = np.exp(scores - scores.max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True) * prob_scale
    vh = v.reshape(b, s, nh, dh).transpose(0, 2, 1, 3)
    return (probs @ vh).transpose(0, 2, 1, 3).reshape(b, t, h)


def test_kv_cache_op_goldens():
    import jax.numpy as jnp

    from paddle_tpu.framework.registry import OpView
    from paddle_tpu.ops.kv_cache import (_kv_cache_attention,
                                         _kv_cache_write)

    rng = np.random.RandomState(0)
    cache = rng.randn(2, 8, 12).astype(np.float32)
    rows = rng.randn(2, 1, 12).astype(np.float32)
    out = _kv_cache_write(
        None, OpView("kv_cache_write", {}),
        {"Cache": [jnp.asarray(cache)], "X": [jnp.asarray(rows)],
         "Pos": [jnp.asarray([3])]},
    )["Out"][0]
    want = cache.copy()
    want[:, 3:4, :] = rows
    np.testing.assert_allclose(np.asarray(out), want)

    q = rng.randn(2, 1, 12).astype(np.float32)
    attn = _kv_cache_attention(
        None,
        OpView("kv_cache_attention",
               {"num_heads": 3, "scale": 0.5, "prob_scale": 0.9}),
        {"Q": [jnp.asarray(q)], "CacheK": [jnp.asarray(cache)],
         "CacheV": [jnp.asarray(cache)], "Pos": [jnp.asarray([5])]},
    )["Out"][0]
    ref = _np_ref_cache_attention(q, cache, cache, 5, 3, 0.5, 0.9)
    np.testing.assert_allclose(np.asarray(attn), ref, rtol=1e-5, atol=1e-6)


def test_kv_decode_parity_with_full_recompute():
    """Cached generation matches full-context recompute token-for-token
    (and the cached path reuses ONE decode executable across steps)."""
    from paddle_tpu.models.gpt import GPTConfig

    cfg = GPTConfig.tiny()
    cfg.use_fused_attention = False
    gen = GPTGenerator(cfg, batch=2, context_len=12, max_len=24)
    gen.init_params(seed=11)
    rng = np.random.RandomState(0)
    ctx = rng.randint(0, cfg.vocab_size, size=(2, 12)).astype(np.int64)
    cached = gen.generate(ctx, 8)
    full = gen.generate_full_recompute(ctx, 8)
    np.testing.assert_array_equal(cached, full)
    c = observability.get_counters()
    assert c.get("serving.decode_steps", 0) >= 7
    # second generation must add zero compiles (shapes static)
    c0 = observability.get_counters().get("executor.compile_count", 0)
    cached2 = gen.generate(ctx, 8)
    np.testing.assert_array_equal(cached2, cached)
    c1 = observability.get_counters().get("executor.compile_count", 0)
    assert c1 == c0, "decode path recompiled despite static shapes"


# ---------------------------------------------------------------------------
# drain
# ---------------------------------------------------------------------------


def test_drain_completes_all_admitted_requests():
    """SIGTERM during load: every admitted request completes, late
    admissions are refused, serving.drained fires exactly once."""
    from paddle_tpu.serving import install_preemption_handler

    runner = _StubRunner(delay=0.01)
    server = Server()
    server.add_endpoint(
        "stub", runner, EndpointConfig(buckets=(4,), max_wait_ms=50.0)
    )
    old = install_preemption_handler(server, exit_on_drain=False)
    try:
        futs = [
            server.submit("stub", {"x": np.full(2, i, np.float32)})
            for i in range(30)
        ]
        os.kill(os.getpid(), signal.SIGTERM)
        assert server.wait_drained(timeout=30), "drain never completed"
        done = [f.result(timeout=5)[0] for f in futs]
        assert len(done) == 30
        for i, row in enumerate(done):
            np.testing.assert_array_equal(row, np.full(2, 2.0 * i))
        with pytest.raises(ServerDrainingError):
            server.submit("stub", {"x": np.zeros(2, np.float32)})
        c = observability.get_counters()
        assert c.get("serving.drained", 0) == 1
        assert c.get("serving.requests_served", 0) >= 30
    finally:
        signal.signal(signal.SIGTERM, old)


def test_ingest_fault_is_retried():
    """An injected fault on the ingestion seam is retried (the
    dataloader.fetch-style chaos contract): the request still serves."""
    from paddle_tpu.resilience import faults

    runner = _StubRunner()
    ep = Endpoint(
        "chaos", runner, EndpointConfig(buckets=(1,), max_wait_ms=0.0)
    )
    faults.inject("serving.ingest", "io", prob=1.0, seed=0, max_fires=2)
    futs = [
        ep.submit({"x": np.full(2, i, np.float32)}) for i in range(3)
    ]
    got = [f.result(timeout=5)[0] for f in futs]
    ep.drain(timeout=5)
    for i, row in enumerate(got):
        np.testing.assert_array_equal(row, np.full(2, 2.0 * i))
    c = observability.get_counters()
    assert c.get("resilience.faults_injected", 0) >= 2
    assert c.get("resilience.retries", 0) >= 2
    assert c.get("serving.requests", 0) == 3


@pytest.mark.slow
def test_drain_worker_exits_75():
    """Full preemption contract in a subprocess: SIGTERM during load ->
    all in-flight requests complete -> exit PREEMPTION_EXIT_CODE."""
    import json
    import subprocess
    import sys
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        proc = subprocess.Popen(
            [sys.executable,
             os.path.join(os.path.dirname(__file__),
                          "serving_drain_worker.py"),
             d],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        ready = os.path.join(d, "ready")
        for _ in range(600):
            if os.path.exists(ready):
                break
            if proc.poll() is not None:
                raise AssertionError(
                    f"worker died early: {proc.stderr.read().decode()}"
                )
            time.sleep(0.1)
        else:
            proc.kill()
            raise AssertionError("worker never became ready")
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
        assert rc == 75, (
            f"expected PREEMPTION_EXIT_CODE 75, got {rc}: "
            f"{proc.stderr.read().decode()}"
        )
        with open(os.path.join(d, "result.json")) as f:
            result = json.load(f)
        assert result["dropped"] == 0, result
        # every admitted request RESOLVED: served, or typed expired/shed
        # for the deadline/priority slice (the r15 drain contract)
        assert (result["served"] + result["expired"] + result["shed"]
                == result["admitted"]), result
        assert result["served"] > 0, result
        assert result["drained_counter"] == 1, result
