"""Worker for the 2-process dygraph DataParallel test (VERDICT r2 item 6):
eager training with scale_loss + apply_collective_grads across REAL
processes; per-step losses written per rank. The single-process baseline
on the concatenated global batch must match step for step (the reference's
test_dist_base.py:506 criterion for imperative DP)."""

import json
import os
import sys

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import dygraph
from paddle_tpu.dygraph import DataParallel, Linear, to_variable
from paddle_tpu.dygraph.tracer import trace_op
from paddle_tpu.fleet.role_maker import PaddleCloudRoleMaker
from paddle_tpu.optimizer import SGD


def make_feed(rank, step, b_local):
    rng = np.random.RandomState(200 + step)
    xg = rng.randn(2 * b_local, 4).astype(np.float32)
    w = np.arange(4, dtype=np.float32).reshape(4, 1)
    yg = xg @ w
    lo = rank * b_local
    return xg[lo:lo + b_local], yg[lo:lo + b_local]


def build_model(seed=23):
    import paddle_tpu.framework.unique_name as unique_name  # noqa

    np.random.seed(seed)
    return Linear(4, 1)


def train(rank, nranks, steps=5, b_local=8, parallel=True):
    losses = []
    with dygraph.guard():
        fluid.default_main_program().random_seed = 23
        model = build_model()
        if parallel:
            model = DataParallel(model)
            model._strategy.nranks = nranks
        opt = SGD(0.1, parameter_list=model.parameters())
        params = list(model.parameters())
        for step in range(steps):
            if parallel:
                xv, yv = make_feed(rank, step, b_local)
            else:
                x0, y0 = make_feed(0, step, b_local)
                x1, y1 = make_feed(1, step, b_local)
                xv, yv = np.concatenate([x0, x1]), np.concatenate([y0, y1])
            x = to_variable(xv)
            y = to_variable(yv)
            pred = model(x)
            diff = trace_op("elementwise_sub", {"X": [pred], "Y": [y]}, {})
            sq = trace_op("square", {"X": [diff]}, {})
            loss = trace_op("reduce_mean", {"X": [sq]},
                            {"dim": None, "keep_dim": False})
            if parallel:
                loss = model.scale_loss(loss)
            loss.backward()
            if parallel:
                model.apply_collective_grads()
            opt.minimize(loss, parameter_list=params)
            for p in params:
                p._grad = None
            # report the GLOBAL loss (parallel loss is the local-mean/nranks)
            lv = float(np.asarray(loss.value).reshape(-1)[0])
            losses.append(lv * nranks if parallel else lv)
    return losses


def main():
    out_dir = sys.argv[1]
    role = PaddleCloudRoleMaker()
    role.generate_role()
    rank, nranks = role.worker_index(), role.worker_num()
    losses = train(rank, nranks)
    with open(os.path.join(out_dir, f"dyg_losses_{rank}.json"), "w") as f:
        json.dump(losses, f)


if __name__ == "__main__":
    main()
