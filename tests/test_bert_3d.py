"""Composed-parallelism (ERNIE-style 3D) tests: ONE program stacking
dp × mp × pp + recompute + AMP + vocab-sharded embeddings must train
step-for-step like its meshless degrade (collectives identity, pipeline
sequential) — the strategies must COMPOSE, not just work as five separate
demos. Reference capability: meta-optimizer stacking
(optimizer.py:3556/3858 + incubate/fleet/collective/__init__.py:384).
"""

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu.framework.scope import Scope
from paddle_tpu.models import BertConfig
from paddle_tpu.models.bert_3d import (bert_3d_shardings, build_bert_3d,
                                       example_feed_3d)
from paddle_tpu.parallel import make_mesh, shard_program


def _cfg():
    cfg = BertConfig(
        vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
        intermediate_size=128, max_position=64,
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    return cfg


def _train(main, startup, loss, feed, steps=3):
    exe = fluid.Executor()
    scope = Scope()
    exe.run(startup, scope=scope)
    out = []
    for _ in range(steps):
        (lv,) = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
        out.append(float(np.asarray(lv).reshape(-1)[0]))
    return out, scope


def test_uniform_3d_matches_meshless():
    """dp2 x mp2 x pp2 hybrid vs the same composed program run meshless:
    the losses must track step for step (bf16 AMP tolerance)."""
    cfg = _cfg()
    B, S, M = 8, 16, 2
    feed = example_feed_3d(cfg, B, S)

    main0, startup0, loss0 = build_bert_3d(
        cfg, B, S, num_stages=2, microbatches=M, dp=1
    )
    base, _ = _train(main0, startup0, loss0, feed)

    main1, startup1, loss1 = build_bert_3d(
        cfg, B // 2, S, num_stages=2, microbatches=M, dp=2
    )
    mesh = make_mesh({"dp": 2, "mp": 2, "pp": 2}, jax.devices()[:8])
    shard_program(main1, mesh, bert_3d_shardings(cfg, num_stages=2),
                  mode="hybrid", manual_axes=("dp", "pp"))
    sharded, scope = _train(main1, startup1, loss1, feed)

    assert base[-1] < base[0], base  # actually trains
    np.testing.assert_allclose(base, sharded, rtol=2e-3, atol=2e-3)

    # the memory claim is real: stage stacks shard over pp AND mp, and the
    # Adam moments follow (spec_for _accum_of inheritance) — each device
    # holds 1/(pp*mp) of every layer weight
    w = scope.find_var("bert_l0_ffn_in_w@STACK")
    assert tuple(w.shape) == (2, 64, 128)
    assert {s.data.shape for s in w.addressable_shards} == {(1, 64, 64)}
    moments = [
        n for n in scope.local_var_names()
        if n.startswith("bert_l0_ffn_in_w@STACK_moment1")
    ]
    assert moments, "adam moment for the stack not found"
    m = scope.find_var(moments[0])
    assert {s.data.shape for s in m.addressable_shards} == {(1, 64, 64)}
    # vocab-sharded input embedding
    emb = scope.find_var("word_embedding")
    assert {s.data.shape for s in emb.addressable_shards} == {(128, 64)}


def test_uniform_3d_structure():
    """The composed program really contains every strategy: bf16 casts in
    the stage block, remat flag, stacked pp-sharded params, pp allreduces
    for outside params placed before AMP bookkeeping, dp grad allreduce."""
    cfg = _cfg()
    main, _, _ = build_bert_3d(cfg, 4, 16, num_stages=2, microbatches=2,
                               dp=2)
    gb = main.global_block
    pipe = [op for op in gb.ops if op.type == "pipeline_uniform"]
    assert len(pipe) == 1
    op = pipe[0]
    assert op.attr("remat") is True
    # AMP reached the stages (casts inside); the boundary stays f32 — a
    # bf16 carry + mp-sharded weights trips an XLA partitioner bug (see
    # fp16_utils pipeline_uniform branch)
    assert op.attr("boundary_dtype") == "float32"
    stage_ops = main.blocks[op.attr("stage_block")].ops
    assert any(o.type == "cast" for o in stage_ops)
    assert [o for o in gb.ops if o.type == "pipeline_gate_loss"]
    gtypes = [o.type for o in gb.ops]
    assert gtypes.index("c_allreduce_sum") < gtypes.index(
        "check_finite_and_unscale"
    )
    # stacks annotated over pp; outside params (emb/head) are not stacked
    stacked = set(op.inputs["Stacked"])
    assert all(main._sharding[n][0] == "pp" for n in stacked)
    assert "word_embedding" not in stacked


@pytest.mark.slow  # ~42s on the CI CPU (heaviest tier-1 case after the
# PR-5 marks); ci.sh's unfiltered pytest still runs it
def test_blocks_pipeline_composes_amp_recompute_dp():
    """Reference-parity heterogeneous pipeline (device_guard stages) also
    stacks with AMP + recompute + dp in hybrid mode (no mp — lax.switch
    branches must stay collective-free)."""
    cfg = _cfg()
    B, S, M = 8, 16, 2
    feed = example_feed_3d(cfg, B, S)
    main0, startup0, loss0 = build_bert_3d(
        cfg, B, S, num_stages=2, microbatches=M, dp=1,
        pipeline_mode="blocks",
    )
    base, _ = _train(main0, startup0, loss0, feed)

    main1, startup1, loss1 = build_bert_3d(
        cfg, B // 2, S, num_stages=2, microbatches=M, dp=2,
        pipeline_mode="blocks",
    )
    mesh = make_mesh({"dp": 2, "pp": 2}, jax.devices()[:4])
    sh = {k: (("dp",) if k in ("ids", "types", "mask", "labels") else v)
          for k, v in bert_3d_shardings(cfg).items()
          if "mp" not in tuple(v)}
    shard_program(main1, mesh, sh, mode="hybrid", manual_axes=("dp", "pp"))
    sharded, _ = _train(main1, startup1, loss1, feed)
    np.testing.assert_allclose(base, sharded, rtol=2e-3, atol=2e-3)


def test_uniform_pipeline_rng_and_determinism():
    """Same seeds -> identical losses on rebuild (structural seeding holds
    through the stacked-param startup rewrite)."""
    cfg = _cfg()
    feed = example_feed_3d(cfg, 4, 16)
    r1, _ = _train(*build_bert_3d(cfg, 4, 16, num_stages=2, microbatches=2),
                   feed, steps=2)
    r2, _ = _train(*build_bert_3d(cfg, 4, 16, num_stages=2, microbatches=2),
                   feed, steps=2)
    np.testing.assert_allclose(r1, r2, rtol=0, atol=0)
