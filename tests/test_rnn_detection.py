"""LSTM/GRU layers and detection ops vs numpy references.

Reference suites: test_lstm_op.py / test_gru_op.py (gate math vs numpy),
test_iou_similarity_op.py, test_box_coder_op.py, test_yolo_box_op.py,
test_multiclass_nms_op.py.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


def _run(fetch, feed):
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    return [np.asarray(v) for v in exe.run(feed=feed, fetch_list=fetch)]


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def test_lstm_matches_numpy_and_masks_padding():
    B, T, D, H = 2, 4, 3, 5
    x = fluid.data("x", [B, T, D])
    lens = fluid.data("lens", [B], "int64")
    out, last_h, last_c = layers.lstm(
        x, H, sequence_length=lens,
        param_attr=fluid.ParamAttr(name="wih"),
    )
    rng = np.random.RandomState(0)
    xv = rng.randn(B, T, D).astype(np.float32)
    lv = np.asarray([4, 2], np.int64)
    ov, hv, cv = _run([out, last_h, last_c], {"x": xv, "lens": lv})

    scope = fluid.framework.scope.global_scope()
    wih = np.asarray(scope.find_var("wih"))
    whh = np.asarray(scope.find_var("wih_hh"))
    b = np.asarray(scope.find_var("wih_bias"))

    h = np.zeros((B, H), np.float32)
    c = np.zeros((B, H), np.float32)
    want = np.zeros((B, T, H), np.float32)
    for t in range(T):
        gates = xv[:, t] @ wih.T + b + h @ whh.T
        i, f, g, o = np.split(gates, 4, axis=-1)
        i, f, o = _sigmoid(i), _sigmoid(f), _sigmoid(o)
        g = np.tanh(g)
        c_new = f * c + i * g
        h_new = o * np.tanh(c_new)
        m = (t < lv).astype(np.float32)[:, None]
        h = m * h_new + (1 - m) * h
        c = m * c_new + (1 - m) * c
        want[:, t] = h
    np.testing.assert_allclose(ov, want, rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(hv, h, rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(cv, c, rtol=2e-4, atol=1e-5)
    # padded steps carry the last real state through
    np.testing.assert_allclose(ov[1, 2], ov[1, 1], rtol=1e-6)


def test_lstm_trains():
    B, T, D, H = 8, 6, 4, 8
    x = fluid.data("x", [B, T, D])
    y = fluid.data("y", [B, H])
    out, last_h, _ = layers.lstm(x, H, num_layers=2)
    loss = layers.mean(layers.square_error_cost(last_h, y))
    fluid.optimizer.Adam(0.02).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(1)
    feed = {"x": rng.randn(B, T, D).astype(np.float32),
            "y": np.tanh(rng.randn(B, H)).astype(np.float32) * 0.5}
    losses = [
        float(np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])
              .reshape(-1)[0])
        for _ in range(80)
    ]
    assert losses[-1] < losses[0] * 0.2


def test_gru_matches_numpy():
    B, T, D, H = 2, 3, 3, 4
    x = fluid.data("x", [B, T, D])
    out, last_h = layers.gru(x, H, param_attr=fluid.ParamAttr(name="gwih"))
    rng = np.random.RandomState(0)
    xv = rng.randn(B, T, D).astype(np.float32)
    ov, hv = _run([out, last_h], {"x": xv})
    scope = fluid.framework.scope.global_scope()
    wih = np.asarray(scope.find_var("gwih"))
    whh = np.asarray(scope.find_var("gwih_hh"))
    b = np.asarray(scope.find_var("gwih_bias"))
    w_u, w_r, w_c = np.split(whh, 3, axis=0)
    h = np.zeros((B, H), np.float32)
    for t in range(T):
        xp = xv[:, t] @ wih.T + b
        xu, xr, xc = np.split(xp, 3, axis=-1)
        u = _sigmoid(xu + h @ w_u.T)
        r = _sigmoid(xr + h @ w_r.T)
        cand = np.tanh(xc + (r * h) @ w_c.T)
        h = u * h + (1 - u) * cand
    np.testing.assert_allclose(hv, h, rtol=2e-4, atol=1e-5)


# -- detection --------------------------------------------------------------


def test_iou_similarity():
    a = fluid.data("a", [2, 4])
    b = fluid.data("b", [2, 4])
    out = layers.iou_similarity(a, b)
    av = np.asarray([[0, 0, 2, 2], [0, 0, 1, 1]], np.float32)
    bv = np.asarray([[1, 1, 3, 3], [0, 0, 1, 1]], np.float32)
    (got,) = _run([out], {"a": av, "b": bv})
    assert got[0, 0] == pytest.approx(1 / 7)  # inter 1, union 7
    assert got[1, 1] == pytest.approx(1.0)
    assert got[1, 0] == pytest.approx(0.0)


def test_box_coder_encode_decode_roundtrip():
    prior = fluid.data("prior", [3, 4])
    target = fluid.data("target", [2, 4])
    enc = layers.box_coder(prior, None, target, "encode_center_size")
    dec = layers.box_coder(prior, None, enc, "decode_center_size")
    rng = np.random.RandomState(0)
    pv = np.sort(rng.rand(3, 2, 2), axis=1).reshape(3, 4).astype(np.float32)
    tv = np.sort(rng.rand(2, 2, 2), axis=1).reshape(2, 4).astype(np.float32)
    # ensure nonzero extents
    pv[:, 2:] += 0.1
    tv[:, 2:] += 0.1
    e, d = _run([enc, dec], {"prior": pv, "target": tv})
    # decode(encode(t)) == t for every prior column
    for m in range(3):
        np.testing.assert_allclose(d[:, m], tv, rtol=1e-4, atol=1e-5)


def test_yolo_box_shapes_and_center():
    B, A, C, Hh, Ww = 1, 2, 3, 2, 2
    x = fluid.data("x", [B, A * (5 + C), Hh, Ww])
    img = fluid.data("img", [B, 2], "int64")
    boxes, scores = layers.yolo_box(
        x, img, anchors=[10, 14, 23, 27], class_num=C, downsample_ratio=32
    )
    xv = np.zeros((B, A * (5 + C), Hh, Ww), np.float32)
    (bv, sv) = _run(
        [boxes, scores], {"x": xv, "img": np.asarray([[64, 64]], np.int64)}
    )
    assert bv.shape == (B, A * Hh * Ww, 4)
    assert sv.shape == (B, A * Hh * Ww, C)
    # zero logits: center of cell (0,0) is at 0.5/W * img -> box center 16
    cx = (bv[0, 0, 0] + bv[0, 0, 2]) / 2
    assert cx == pytest.approx(16.0, abs=1e-3)


def test_multiclass_nms_suppresses_overlaps():
    boxes = fluid.data("boxes", [1, 4, 4])
    scores = fluid.data("scores", [1, 1, 4])
    out, num = layers.multiclass_nms(
        boxes, scores, score_threshold=0.05, nms_threshold=0.5,
        nms_top_k=4, keep_top_k=4,
    )
    bv = np.asarray([[
        [0, 0, 10, 10],
        [1, 1, 10.5, 10.5],   # heavy overlap with box 0 -> suppressed
        [20, 20, 30, 30],     # separate -> kept
        [0, 0, 1, 1],         # low score -> below threshold
    ]], np.float32)
    sv = np.asarray([[[0.9, 0.8, 0.7, 0.01]]], np.float32)
    ov, nv = _run([out, num], {"boxes": bv, "scores": sv})
    assert int(nv[0]) == 2
    kept = ov[0][ov[0, :, 0] >= 0]
    assert kept.shape[0] == 2
    np.testing.assert_allclose(kept[0, 1], 0.9)  # best box first
    np.testing.assert_allclose(kept[1, 2:], [20, 20, 30, 30])


def _np_beam_search(logps, beam, end_id):  # freeze from step 1 on, like the op
    """Full numpy beam search over per-step log-prob tables.
    logps: list of T arrays, step t giving [n_states, V] where rows are the
    current beam entries (here V-conditioned only on last token id for
    simplicity: logps[t][id] -> [V])."""
    B = 1
    K = beam
    pre_ids = np.zeros((B, K), np.int64)
    pre_sc = np.full((B, K), 0.0, np.float32)
    pre_sc[:, 1:] = -1e9  # only beam 0 is live initially
    all_ids, all_par = [], []
    for t, table in enumerate(logps):
        total = np.zeros((B, K, table.shape[1]), np.float32)
        for k in range(K):
            if pre_ids[0, k] == end_id and t > 0:
                row = np.full(table.shape[1], -1e9, np.float32)
                row[end_id] = pre_sc[0, k]
                total[0, k] = row
            else:
                total[0, k] = pre_sc[0, k] + table[pre_ids[0, k]]
        flat = total.reshape(B, -1)
        idx = np.argsort(-flat[0], kind="stable")[:K]
        par = idx // table.shape[1]
        ids = idx % table.shape[1]
        sc = flat[0, idx]
        all_ids.append(ids.copy())
        all_par.append(par.copy())
        pre_ids = ids[None].astype(np.int64)
        pre_sc = sc[None].astype(np.float32)
    # backtrack
    seqs = []
    for k in range(K):
        ptr, seq = k, []
        for t in range(len(logps) - 1, -1, -1):
            seq.append(all_ids[t][ptr])
            ptr = all_par[t][ptr]
        seqs.append(seq[::-1])
    return np.asarray(seqs), pre_sc[0]


def test_beam_search_matches_numpy():
    """3-step beam decode over a fixed Markov log-prob table, compared
    against a reference numpy beam search (reference test_beam_search_op
    + test_beam_search_decode_op combined)."""
    V, K, T, END = 5, 3, 3, 1
    rng = np.random.RandomState(0)
    table_np = np.log(
        rng.dirichlet(np.ones(V), size=V).astype(np.float32)
    )  # [V, V]: row = conditional log-probs given last id

    table = fluid.data("table", [V, V])
    pre_ids = fluid.data("pre_ids", [1, K], "int64")
    pre_sc = fluid.data("pre_sc", [1, K])
    step_ids, step_par = [], []
    ids_v, sc_v = pre_ids, pre_sc
    for t in range(T):
        logp = layers.reshape(
            layers.gather(table, layers.reshape(ids_v, [K])), [1, K, V]
        )
        ids_v, sc_v, par_v = layers.beam_search(
            ids_v, sc_v, None, logp, beam_size=K, end_id=END,
            is_accumulated=False,  # logp is per-step log-probs
            return_parent_idx=True, first_step=(t == 0),
        )
        step_ids.append(ids_v)
        step_par.append(par_v)
    stacked_ids = layers.stack(step_ids, axis=0)  # [T, 1, K]
    stacked_par = layers.stack(step_par, axis=0)
    sentences = layers.beam_search_decode(stacked_ids, stacked_par, end_id=END)

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    init_sc = np.full((1, K), -1e9, np.float32)
    init_sc[0, 0] = 0.0
    got_seq, got_sc = (
        np.asarray(v)
        for v in exe.run(
            feed={
                "table": table_np,
                "pre_ids": np.zeros((1, K), np.int64),
                "pre_sc": init_sc,
            },
            fetch_list=[sentences, sc_v],
        )
    )
    want_seqs, want_sc = _np_beam_search(
        [table_np] * T, K, END
    )
    np.testing.assert_array_equal(got_seq[0], want_seqs)
    np.testing.assert_allclose(got_sc[0], want_sc, rtol=1e-5)


def test_stacked_rnn_bias_not_aliased():
    """num_layers=2 with a NAMED bias_attr must create distinct per-layer
    biases (regression: layers silently shared one bias tensor)."""
    x = fluid.data("x", [2, 3, 4])
    layers.lstm(x, 5, num_layers=2,
                param_attr=fluid.ParamAttr(name="sw"),
                bias_attr=fluid.ParamAttr(name="sb"))
    names = set(fluid.default_main_program().global_block.vars)
    assert "sb" in names and "sb_l1" in names
    assert "sw" in names and "sw_l1" in names
