"""Core framework tests: program construction, executor, backward, optimizers.

Modeled on the reference's framework/behavior unittests
(python/paddle/fluid/tests/unittests/test_executor_*, test_backward*,
tests/book/test_fit_a_line.py).
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.framework import unique_name


@pytest.fixture(autouse=True)
def fresh_programs():
    """Isolate each test in its own programs + scope."""
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


def test_program_build():
    x = fluid.data("x", [-1, 4])
    y = fluid.layers.fc(x, 8, act="relu")
    assert y.shape == (-1, 8)
    main = fluid.default_main_program()
    assert [op.type for op in main.global_block.ops] == [
        "mul", "elementwise_add", "relu",
    ]
    assert len(main.all_parameters()) == 2


def test_executor_forward():
    x = fluid.data("x", [-1, 4])
    y = fluid.layers.fc(x, 3)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    xv = np.random.rand(5, 4).astype(np.float32)
    (out,) = exe.run(feed={"x": xv}, fetch_list=[y])
    assert out.shape == (5, 3)


def test_backward_matches_numeric():
    x = fluid.data("x", [2, 3])
    w_init = np.random.rand(3, 4).astype(np.float32)
    y = fluid.layers.fc(
        x, 4, param_attr=fluid.ParamAttr(
            name="w0", initializer=fluid.initializer.NumpyArrayInitializer(w_init)
        ),
        bias_attr=False,
    )
    loss = fluid.layers.mean(fluid.layers.square(y))
    pairs = fluid.append_backward(loss)
    assert len(pairs) == 1
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    xv = np.random.rand(2, 3).astype(np.float32)
    (gw,) = exe.run(feed={"x": xv}, fetch_list=[pairs[0][1]])
    # analytic: d/dw mean((xw)^2) = 2 x^T (xw) / numel
    ref = 2.0 * xv.T @ (xv @ w_init) / (2 * 4)
    np.testing.assert_allclose(gw, ref, rtol=1e-5)


def test_grad_accumulation_multi_use():
    """A var consumed twice must receive summed gradient contributions."""
    x = fluid.data("x", [3])
    x.stop_gradient = False
    a = fluid.layers.scale(x, scale=2.0)
    b = fluid.layers.elementwise_add(a, a)  # uses `a` twice
    loss = fluid.layers.mean(b)
    grads = fluid.gradients(loss, [x])
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    (gx,) = exe.run(feed={"x": np.ones(3, np.float32)}, fetch_list=[grads[0]])
    np.testing.assert_allclose(gx, np.full(3, 4.0 / 3.0), rtol=1e-6)


def test_fit_a_line_converges():
    """End-to-end: linear regression must converge (reference:
    tests/book/test_fit_a_line.py)."""
    np.random.seed(0)
    true_w = np.array([[2.0], [-3.4]], np.float32)
    true_b = 4.2

    x = fluid.data("x", [-1, 2])
    label = fluid.data("label", [-1, 1])
    pred = fluid.layers.fc(x, 1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, label))
    opt = fluid.optimizer.SGD(learning_rate=0.1)
    opt.minimize(loss)

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    losses = []
    for _ in range(400):
        xv = np.random.rand(16, 2).astype(np.float32)
        yv = xv @ true_w + true_b + 0.01 * np.random.randn(16, 1).astype(np.float32)
        (lv,) = exe.run(feed={"x": xv, "label": yv}, fetch_list=[loss])
        losses.append(float(lv[0]))
    assert losses[-1] < 0.01, f"did not converge: {losses[::80]}"


def test_adam_and_accumulators():
    x = fluid.data("x", [-1, 4])
    y = fluid.layers.fc(x, 2, bias_attr=False)
    loss = fluid.layers.mean(fluid.layers.square(y))
    opt = fluid.optimizer.Adam(learning_rate=0.01)
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    scope = fluid.framework.scope.current_scope()
    p = fluid.default_main_program().all_parameters()[0]
    first = np.asarray(scope.find_var(p.name)).copy()
    for _ in range(3):
        exe.run(feed={"x": np.random.rand(4, 4).astype(np.float32)},
                fetch_list=[loss])
    after = np.asarray(scope.find_var(p.name))
    assert not np.allclose(first, after)


def test_dropout_train_vs_test():
    x = fluid.data("x", [100, 100])
    out = fluid.layers.dropout(x, 0.5, dropout_implementation="upscale_in_train")
    main = fluid.default_main_program()
    test_prog = main.clone(for_test=True)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    xv = np.ones((100, 100), np.float32)
    (train_out,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
    zeros = (train_out == 0).mean()
    assert 0.3 < zeros < 0.7
    (test_out,) = exe.run(test_prog, feed={"x": xv}, fetch_list=[out])
    np.testing.assert_allclose(test_out, xv)


def test_seeded_dropout_varies_per_step_but_reruns_deterministically():
    """A fixed random_seed pins the run *sequence*, not a single frozen mask:
    step k of run A == step k of run B, while step 0 != step 1 within a run
    (the reference advances its generator every execution)."""

    def run_twice():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 7
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [64, 64])
            out = fluid.layers.dropout(
                x, 0.5, dropout_implementation="upscale_in_train"
            )
        exe = fluid.Executor()
        xv = np.ones((64, 64), np.float32)
        a = exe.run(main, feed={"x": xv}, fetch_list=[out])[0]
        b = exe.run(main, feed={"x": xv}, fetch_list=[out])[0]
        return a, b

    a0, a1 = run_twice()
    b0, b1 = run_twice()
    assert not np.allclose(a0, a1), "dropout mask frozen across steps"
    np.testing.assert_allclose(a0, b0)
    np.testing.assert_allclose(a1, b1)


def test_batch_norm_updates_stats():
    x = fluid.data("x", [8, 3, 4, 4])
    y = fluid.layers.batch_norm(x)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    scope = fluid.framework.scope.current_scope()
    mean_name = [
        v.name for v in fluid.default_main_program().global_block.vars.values()
        if "bn_mean" in v.name
    ][0]
    xv = (5.0 + np.random.randn(8, 3, 4, 4)).astype(np.float32)
    exe.run(feed={"x": xv}, fetch_list=[y])
    m = np.asarray(scope.find_var(mean_name))
    assert np.all(m > 0.1), m  # moved toward batch mean of ~5


def test_mnist_mlp_converges():
    """Small classification net on synthetic separable data (reference:
    tests/book/test_recognize_digits.py shape)."""
    np.random.seed(1)
    img = fluid.data("img", [-1, 64])
    label = fluid.data("label", [-1, 1], dtype="int64")
    h = fluid.layers.fc(img, 32, act="relu")
    logits = fluid.layers.fc(h, 4)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label)
    )
    acc = fluid.layers.accuracy(fluid.layers.softmax(logits), label)
    opt = fluid.optimizer.Adam(learning_rate=0.01)
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    centers = np.random.randn(4, 64).astype(np.float32) * 3
    accs = []
    for _ in range(60):
        lbl = np.random.randint(0, 4, (32, 1))
        xv = centers[lbl[:, 0]] + np.random.randn(32, 64).astype(np.float32)
        lv, av = exe.run(
            feed={"img": xv.astype(np.float32), "label": lbl.astype(np.int64)},
            fetch_list=[loss, acc],
        )
        accs.append(float(av))
    assert np.mean(accs[-10:]) > 0.9, accs[::10]
