"""End-to-end causal tracing (ISSUE 13): TraceContext propagation across
threads (serving scheduler, AsyncCheckpointer publisher, embedding
Prefetcher worker) and ranks (heartbeat stamps), per-step
compute-vs-wait attribution, the live watcher's structured findings, and
the trace_report reconstruction tooling — plus the unified
PADDLE_TPU_MONITOR kill-switch across metrics, spans AND traces."""

import importlib.util
import os
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, observability as obs
from paddle_tpu.framework import unique_name
from paddle_tpu.observability import trace, watch
from paddle_tpu.resilience import faults
from paddle_tpu.resilience.health import Heartbeat

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

HANG_ENV = "PADDLE_TPU_FAULT_HANG_SECONDS"


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_ROOT, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def fresh_metrics():
    obs.reset()
    obs.set_enabled(True)
    faults.clear()
    old = os.environ.pop(HANG_ENV, None)
    yield
    faults.clear()
    if old is not None:
        os.environ[HANG_ENV] = old
    obs.reset()
    obs.set_enabled(None)


@pytest.fixture
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


def _traced_spans():
    return [s for s in obs.get_spans() if "trace_id" in s]


def _by_name(name):
    return [s for s in _traced_spans() if s["name"] == name]


# -- context primitives ------------------------------------------------------


def test_span_nesting_builds_parent_chain():
    tr = trace.new_trace()
    with trace.activate(tr):
        with obs.span("outer") as outer:
            with obs.span("inner"):
                pass
    inner, = _by_name("inner")
    outer_rec, = _by_name("outer")
    assert outer_rec["trace_id"] == inner["trace_id"] == tr.trace_id
    assert outer_rec["parent_id"] is None
    assert inner["parent_id"] == outer_rec["span_id"] == outer.span_id


def test_activate_none_masks_outer_context():
    with trace.activate(trace.new_trace()):
        with trace.activate(None):
            with obs.span("masked"):
                pass
        with obs.span("visible"):
            pass
    assert not _by_name("masked")
    assert _by_name("visible")


def test_record_retrospective_span():
    tr = trace.new_trace()
    sid = obs.record("retro", 0.25, ctx=tr, args={"k": 1})
    rec, = _by_name("retro")
    assert rec["span_id"] == sid and rec["trace_id"] == tr.trace_id
    assert rec["dur"] == pytest.approx(0.25e6)
    # ts was back-dated by the duration
    assert rec["ts"] <= time.time_ns() / 1e3 - 0.24e6


def test_capture_activate_across_thread():
    import threading

    with trace.activate(trace.new_trace()):
        with obs.span("producer") as prod:
            ctx = trace.capture()

            def worker():
                with trace.activate(ctx):
                    with obs.span("consumer"):
                        pass

            t = threading.Thread(target=worker)
            t.start()
            t.join()
    cons, = _by_name("consumer")
    assert cons["parent_id"] == prod.span_id
    assert cons["tid"] != _by_name("producer")[0]["tid"]


def test_chrome_export_carries_trace_ids():
    import json

    with trace.activate(trace.new_trace()):
        with obs.span("exported"):
            pass
    events = json.loads(obs.chrome_trace())["traceEvents"]
    xs = [e for e in events if e.get("ph") == "X"]
    assert xs and "trace_id" in xs[0]["args"] and "span_id" in xs[0]["args"]


# -- the unified kill-switch (satellite bugfix) ------------------------------


def test_kill_switch_disables_spans_and_traces():
    obs.set_enabled(False)
    assert trace.new_trace() is None
    # even under a pre-captured live context, nothing records
    obs.set_enabled(True)
    tr = trace.new_trace()
    obs.reset()  # drop the traces_started bump from the line above
    obs.set_enabled(False)
    with trace.activate(tr):
        with obs.span("dead"):
            pass
        assert obs.record("dead.retro", 0.1) is None
    w = watch.Watcher()
    assert w.poll() == []
    snap = obs.snapshot()
    assert snap["span_count"] == 0
    assert snap["counters"] == {}
    # PR 16: the same switch silences the telemetry plane — no publisher
    # or flight-recorder thread starts, not one journal/bundle file lands
    import tempfile

    from paddle_tpu.observability import recorder, timeline

    with tempfile.TemporaryDirectory() as d:
        pub = timeline.TelemetryPublisher(
            directory=d, rank=0, interval=0.01
        ).start(register=False)
        rec = recorder.FlightRecorder(directory=d, rank=0,
                                      interval=0.01).start(register=False)
        assert pub._thread is None and rec._thread is None
        assert pub.publish() is None and rec.dump("exception") is None
        assert os.listdir(d) == []


# -- serving: request traces across the scheduler handoff --------------------


class _ToyRunner:
    feed_names = ("x",)

    def sample_spec(self, name):
        return ((2,), "float32")

    def run(self, feed):
        with obs.span("runner.work"):
            return [np.asarray(feed["x"]) * 2]


def _drain_endpoint(ep, n=3):
    futs = [ep.submit({"x": np.ones(2, np.float32)}) for _ in range(n)]
    for f in futs:
        f.result(timeout=30)
    ep.drain(timeout=10)


def test_serving_request_trace_is_complete_and_cross_thread():
    from paddle_tpu.serving.router import Endpoint, EndpointConfig

    ep = Endpoint("toy", _ToyRunner(),
                  EndpointConfig(buckets=(1, 2), max_wait_ms=2.0))
    _drain_endpoint(ep, n=3)
    traces = {}
    for s in _traced_spans():
        traces.setdefault(s["trace_id"], []).append(s)
    assert len(traces) == 3  # one trace per request
    for ss in traces.values():
        names = {s["name"] for s in ss}
        assert {"serving.ingest", "serving.queue_wait",
                "serving.dispatch"} <= names
        ids = {s["span_id"] for s in ss}
        assert all(
            s["parent_id"] in ids for s in ss if s["parent_id"]
        ), "orphan span in request trace"
        # ingest on the caller thread, scheduling on the scheduler thread
        assert len({s["tid"] for s in ss}) >= 2
        ingest, = [s for s in ss if s["name"] == "serving.ingest"]
        qw, = [s for s in ss if s["name"] == "serving.queue_wait"]
        assert qw["parent_id"] == ingest["span_id"]


def test_serving_joins_callers_active_trace():
    from paddle_tpu.serving.router import Endpoint, EndpointConfig

    ep = Endpoint("toy2", _ToyRunner(),
                  EndpointConfig(buckets=(1,), max_wait_ms=1.0))
    tr = trace.new_trace()
    with trace.activate(tr), obs.span("client.request"):
        fut = ep.submit({"x": np.ones(2, np.float32)})
    fut.result(timeout=30)
    ep.drain(timeout=10)
    ingest, = _by_name("serving.ingest")
    client, = _by_name("client.request")
    assert ingest["trace_id"] == tr.trace_id
    assert ingest["parent_id"] == client["span_id"]


def test_gpt_generator_decode_spans_under_request_trace():
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.serving import GPTGenerator

    cfg = GPTConfig(
        vocab_size=32, hidden_size=16, num_layers=1, num_heads=2,
        intermediate_size=32, max_position=12, use_fused_attention=False,
    )
    gen = GPTGenerator(cfg, batch=1, context_len=4, max_len=12)
    gen.init_params(seed=3)
    tr = trace.new_trace()
    with trace.activate(tr):
        gen.generate(np.zeros((1, 4), np.int64), 3)
    prefill, = _by_name("serving.prefill")
    decode, = _by_name("serving.decode_loop")
    assert prefill["trace_id"] == decode["trace_id"] == tr.trace_id
    # executor steps nested under the decode loop
    steps = [s for s in _by_name("executor.step")
             if s["parent_id"] == decode["span_id"]]
    assert len(steps) == 2  # 3 tokens -> 2 decode dispatches


# -- async checkpointer: publish parents to the SURVIVING save ---------------


def _build_sgd_model():
    x = fluid.data("x", [-1, 4])
    y = fluid.data("y", [-1, 1])
    pred = layers.fc(x, 1)
    loss = layers.mean(layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(0.05).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    return exe, loss


def _fleet():
    from paddle_tpu.fleet import collective as fc
    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker

    f = fc.Fleet()
    f.init(UserDefinedRoleMaker())
    return f


def _step(exe, loss, rng):
    xa = rng.randn(8, 4).astype(np.float32)
    exe.run(feed={"x": xa, "y": xa @ np.ones((4, 1), np.float32)},
            fetch_list=[loss])


def test_async_publish_span_joins_saving_step_trace(
    tmp_path, fresh_programs
):
    from paddle_tpu.fleet import collective as fc

    exe, loss = _build_sgd_model()
    fleet = _fleet()
    rng = np.random.RandomState(0)
    with fc.AsyncCheckpointer(fleet, str(tmp_path / "ck"),
                              executor=exe) as saver:
        _step(exe, loss, rng)
        tr = trace.new_trace()
        with trace.activate(tr), obs.span("train.step"):
            handle = saver.save(fc.TrainStatus(0, global_step=1))
        assert handle.result(timeout=30) == 0
        saver.wait(timeout=30)
    snap_span, = _by_name("checkpoint.snapshot")
    pub_span, = _by_name("checkpoint.publish")
    step_span, = _by_name("train.step")
    assert snap_span["trace_id"] == pub_span["trace_id"] == tr.trace_id
    assert snap_span["parent_id"] == step_span["span_id"]
    # cross-thread: publish on the publisher thread, parented under the
    # step thread's snapshot span
    assert pub_span["parent_id"] == snap_span["span_id"]
    assert pub_span["tid"] != snap_span["tid"]


def test_coalesced_publish_parents_to_surviving_save_trace(
    tmp_path, fresh_programs
):
    from paddle_tpu.fleet import collective as fc

    exe, loss = _build_sgd_model()
    fleet = _fleet()
    rng = np.random.RandomState(0)
    os.environ[HANG_ENV] = "0.4"
    saver = fc.AsyncCheckpointer(fleet, str(tmp_path / "ck"),
                                 executor=exe,
                                 remain_all_checkpoint=True)
    try:
        # first publish is slowed; saves 2 and 3 land behind it, so 2 is
        # superseded by 3 — its trace must never own a publish span
        faults.inject("checkpoint.publish", "hang", 1.0, 0, 1)
        handles, traces = [], []
        for i in range(3):
            _step(exe, loss, rng)
            tr = trace.new_trace()
            traces.append(tr)
            with trace.activate(tr):
                handles.append(
                    saver.save(fc.TrainStatus(i, global_step=i + 1))
                )
        for h in handles:
            h.result(timeout=30)
        saver.wait(timeout=30)
    finally:
        saver.close()
    assert obs.get_counters().get("checkpoint.coalesced", 0) >= 1
    pub_traces = [s["trace_id"] for s in _by_name("checkpoint.publish")]
    assert traces[0].trace_id in pub_traces  # the in-flight save
    assert traces[2].trace_id in pub_traces  # the survivor
    assert traces[1].trace_id not in pub_traces  # superseded: no publish


def test_liveness_pulse_span_under_publish_trace(tmp_path, fresh_programs):
    from paddle_tpu.fleet import collective as fc

    exe, loss = _build_sgd_model()
    fleet = _fleet()
    hb = Heartbeat(str(tmp_path / "hb"), rank=0)
    os.environ[HANG_ENV] = "0.6"
    saver = fc.AsyncCheckpointer(fleet, str(tmp_path / "ck"),
                                 executor=exe, heartbeat=hb)
    try:
        _step(exe, loss, np.random.RandomState(0))
        tr = trace.new_trace()
        faults.inject("fs.upload", "hang", 1.0, 0, 1)
        with trace.activate(tr):
            saver.save(fc.TrainStatus(0, global_step=1)).result(timeout=30)
        saver.wait(timeout=30)
    finally:
        saver.close()
    pub, = _by_name("checkpoint.publish")
    pulses = [s for s in _by_name("health.pulse")
              if s["trace_id"] == tr.trace_id]
    assert pulses, "liveness pulse did not record under the save trace"
    # the pulse runs on its own thread, parented under the publish span
    assert pulses[0]["parent_id"] == pub["span_id"]
    assert len({pub["tid"], pulses[0]["tid"],
                _by_name("checkpoint.snapshot")[0]["tid"]}) == 3


# -- prefetcher worker handoff + restart-after-error -------------------------


class _PlanEngine:
    def __init__(self, fail_at=None):
        self.fail_at = fail_at
        self.calls = 0

    def plan(self, feed):
        self.calls += 1
        if self.fail_at is not None and self.calls == self.fail_at:
            raise RuntimeError("seeded plan failure")
        return {"plan_for": feed["i"]}

    def apply(self, plans, feed, scope):
        return feed


def test_prefetcher_plan_spans_join_constructing_trace():
    from paddle_tpu.embedding.prefetch import Prefetcher

    tr = trace.new_trace()
    with trace.activate(tr), obs.span("driver") as driver:
        pf = Prefetcher(_PlanEngine(), [{"i": i} for i in range(3)],
                        scope=None)
    got = list(pf)
    assert [f["i"] for f in got] == [0, 1, 2]
    plans = _by_name("embedding.prefetch_plan")
    assert len(plans) == 3
    main_tid = driver.span_id and _by_name("driver")[0]["tid"]
    for p in plans:
        assert p["trace_id"] == tr.trace_id
        assert p["parent_id"] == driver.span_id
        assert p["tid"] != main_tid  # recorded on the worker thread


def test_prefetcher_restart_after_error_rejoins_trace():
    from paddle_tpu.embedding.prefetch import Prefetcher

    feeds = [{"i": i} for i in range(4)]
    tr = trace.new_trace()
    with trace.activate(tr):
        pf = Prefetcher(_PlanEngine(fail_at=2), feeds, scope=None)
        got = []
        with pytest.raises(RuntimeError, match="seeded plan failure"):
            for f in pf:
                got.append(f["i"])
        pf.close()
        # restart: a fresh prefetcher over the remaining feeds re-captures
        # the (still active) trace — the restarted worker's spans rejoin it
        pf2 = Prefetcher(_PlanEngine(), feeds[len(got):], scope=None)
        rest = [f["i"] for f in pf2]
    assert got + rest == [0, 1, 2, 3]
    plans = _by_name("embedding.prefetch_plan")
    assert len(plans) >= 1 + len(rest)
    assert {p["trace_id"] for p in plans} == {tr.trace_id}


# -- cross-rank: heartbeat trace stamps --------------------------------------


def test_heartbeat_stamps_active_trace(tmp_path):
    from paddle_tpu.resilience.health import read_beat

    hb = Heartbeat(str(tmp_path), rank=1)
    tr = trace.new_trace()
    with trace.activate(tr), obs.span("train.step") as sp:
        hb.beat(step=7)
    beat = read_beat(hb.path)
    assert beat["step"] == 7
    assert beat["trace_id"] == tr.trace_id
    assert beat["span_id"] == sp.span_id
    # outside any trace the stamp is absent (no stale ids)
    hb.beat(step=8)
    assert "trace_id" not in read_beat(hb.path)


# -- per-step attribution ----------------------------------------------------


def test_step_attribution_on_dp_mesh(fresh_programs):
    from paddle_tpu.parallel import make_mesh, shard_program

    main, startup, scope = fresh_programs
    fluid.data("x", [8, 4], "float32")
    blk = main.global_block
    blk.create_var(name="out", shape=(8, 4), dtype="float32")
    blk.append_op("c_allreduce_sum", inputs={"X": ["x"]},
                  outputs={"Out": ["out"]}, attrs={"axis_name": "dp"})
    shard_program(main, make_mesh({"dp": 8}),
                  {"x": ("dp",), "out": ("dp",)})
    exe = fluid.Executor()
    data = np.arange(32, dtype="float32").reshape(8, 4)
    for _ in range(3):
        exe.run(main, feed={"x": data}, fetch_list=["out"], scope=scope)
    snap = obs.snapshot()
    g = snap["gauges"]
    fracs = {k: g[k] for k in ("perf.wait_fraction.collective",
                               "perf.wait_fraction.host",
                               "perf.wait_fraction.compute")}
    assert all(0.0 <= v <= 1.0 for v in fracs.values()), fracs
    assert sum(fracs.values()) == pytest.approx(1.0, abs=1e-6)
    table = snap["tables"]["perf.step_attribution"]
    # collective-only program: the cost model attributes ALL device
    # roofline to the wire, and the emitters recorded wire bytes
    assert table["est_wait_fraction"] == pytest.approx(1.0)
    assert table["est_wire_seconds"] > 0
    assert table["collective_wait_seconds"] > 0
    assert table["traced_wire_bytes"] > 0
    assert snap["histograms"]["perf.collective_wait_seconds"]["count"] >= 1
    assert snap["histograms"]["perf.host_stall_seconds"]["count"] >= 1


def test_attribution_without_collectives_reports_zero_wait(fresh_programs):
    main, startup, scope = fresh_programs
    x = fluid.data("x", [4, 4])
    y = layers.fc(x, 4)
    exe = fluid.Executor()
    exe.run(startup, scope=scope)
    for _ in range(3):
        exe.run(main, feed={"x": np.ones((4, 4), "float32")},
                fetch_list=[y], scope=scope)
    snap = obs.snapshot()
    assert snap["gauges"]["perf.wait_fraction.collective"] == 0.0
    table = snap["tables"]["perf.step_attribution"]
    assert table["est_wire_seconds"] == 0.0
    assert table["compute_seconds"] > 0


def test_attribution_table_dropped_on_executable_switch(fresh_programs):
    """A snapshot right after an executable switch must not pair the OLD
    executable's attribution split with the new program (same staleness
    contract as the perf.* gauges)."""
    main, startup, scope = fresh_programs
    x = fluid.data("x", [4, 4])
    y = layers.fc(x, 4)
    exe = fluid.Executor()
    exe.run(startup, scope=scope)
    for _ in range(3):
        exe.run(main, feed={"x": np.ones((4, 4), "float32")},
                fetch_list=[y], scope=scope)
    assert "perf.step_attribution" in obs.snapshot()["tables"]
    other = fluid.Program()
    with fluid.program_guard(other, fluid.Program()):
        z = fluid.data("z", [2, 2])
        w = layers.scale(z, scale=2.0)
    # compile-carrying run of ANOTHER executable: gauges AND table drop
    exe.run(other, feed={"z": np.ones((2, 2), "float32")},
            fetch_list=[w], scope=scope)
    snap = obs.snapshot()
    assert "perf.step_attribution" not in snap.get("tables", {})
    assert "perf.wait_fraction.collective" not in snap["gauges"]


def test_attribution_skipped_on_pipelined_no_numpy_path(fresh_programs):
    """return_numpy=False callers (bench.py's pipelined timing loops)
    rely on async dispatch — those runs must neither block on the device
    nor publish an attribution sample."""
    main, startup, scope = fresh_programs
    x = fluid.data("x", [4, 4])
    y = layers.fc(x, 4)
    exe = fluid.Executor()
    exe.run(startup, scope=scope)
    for _ in range(3):
        exe.run(main, feed={"x": np.ones((4, 4), "float32")},
                fetch_list=[y], scope=scope, return_numpy=False)
    snap = obs.snapshot()
    assert "perf.step_attribution" not in snap.get("tables", {})
    assert "perf.wait_fraction.collective" not in snap["gauges"]
    # the rest of the perf surface still publishes
    assert "perf.mfu" in snap["gauges"]


# -- live watcher ------------------------------------------------------------


def test_watcher_flags_straggling_rank(tmp_path):
    d = str(tmp_path)
    Heartbeat(d, rank=0).beat(step=10)
    Heartbeat(d, rank=1).beat(step=3)
    w = watch.Watcher(heartbeat_dir=d, skew_steps=2)
    findings = w.poll()
    assert [f["kind"] for f in findings] == ["straggler"]
    assert findings[0]["detail"]["lagging_ranks"] == [1]
    assert findings[0]["detail"]["skew_steps"] == 7
    # latched: same excursion raises once
    assert w.poll() == []
    # recovery re-arms, a new excursion fires again
    Heartbeat(d, rank=1).beat(step=10)
    assert w.poll() == []
    Heartbeat(d, rank=1).beat(step=10)
    Heartbeat(d, rank=0).beat(step=20)
    assert [f["kind"] for f in w.poll()] == ["straggler"]
    c = obs.get_counters()
    assert c["watch.findings.straggler"] == 2
    assert c["watch.polls"] == 4
    assert "watch.findings" in obs.snapshot()["tables"]


def test_watcher_flags_step_time_regression():
    w = watch.Watcher(min_window=4, drift_tolerance=0.25)
    for _ in range(4):
        obs.observe("executor.step_latency", 0.010)
    assert w.poll() == []  # first poll only anchors the window
    for _ in range(4):
        obs.observe("executor.step_latency", 0.010)
    assert w.poll() == []  # establishes the best window
    for _ in range(4):
        obs.observe("executor.step_latency", 0.050)
    findings = w.poll()
    assert [f["kind"] for f in findings] == ["step_regression"]
    assert findings[0]["detail"]["ratio"] == pytest.approx(5.0, rel=0.01)
    assert obs.get_gauges()["watch.step_time_ratio"] > 1.25


def test_watcher_flags_slo_breach_and_rearms():
    w = watch.Watcher(slo_p99_s=0.1)
    for _ in range(10):
        obs.observe("serving.request_latency", 0.02)
    assert w.poll() == []
    for _ in range(5):
        obs.observe("serving.request_latency", 0.8)
    findings = w.poll()
    assert [f["kind"] for f in findings] == ["slo_breach"]
    assert findings[0]["severity"] == "error"
    assert findings[0]["detail"]["p99_s"] >= 0.8
    # back under the SLO -> re-armed
    for _ in range(50):
        obs.observe("serving.request_latency", 0.01)
    assert w.poll() == []
    for _ in range(5):
        obs.observe("serving.request_latency", 0.9)
    assert [f["kind"] for f in w.poll()] == ["slo_breach"]


# -- trace_report reconstruction ---------------------------------------------


def test_trace_report_check_passes_on_cross_thread_export(tmp_path):
    from paddle_tpu.serving.router import Endpoint, EndpointConfig

    ep = Endpoint("toy3", _ToyRunner(),
                  EndpointConfig(buckets=(1, 2), max_wait_ms=2.0))
    _drain_endpoint(ep, n=2)
    path = str(tmp_path / "trace_rank0.json")
    obs.save_chrome_trace(path)
    tr_tool = _load_tool("trace_report")
    rc = tr_tool.main([path, "--check", "--min-threads", "2",
                       "--require-span", "serving.ingest", "--quiet"])
    assert rc == 0
    # a bar no export meets must fail
    rc = tr_tool.main([path, "--check", "--min-threads", "7", "--quiet"])
    assert rc != 0


def test_trace_report_broken_fixture_exits_nonzero():
    tr_tool = _load_tool("trace_report")
    assert tr_tool.main(["--broken-fixture"]) != 0
