"""Fleet telemetry plane (ISSUE 16): delta journals that outlive their
process (TelemetryPublisher -> line-atomic JSONL shards, bitwise replay),
the crash flight recorder and its trigger hooks, fleet_report's
cross-process merge, the Watcher's remote-journal mode, the shared
windowed-p99 helper, and the PADDLE_TPU_MONITOR kill-switch across all
of it."""

import importlib.util
import json
import os
import sys
import threading
import time

import pytest

from paddle_tpu import observability as obs
from paddle_tpu.observability import metrics, recorder, timeline, watch
from paddle_tpu.resilience import faults
from paddle_tpu.resilience.guard import TrainGuard
from paddle_tpu.resilience.health import Heartbeat, StepWatchdog
from paddle_tpu.serving import brownout as brownout_mod
from paddle_tpu.serving.replica import ReplicaSet
from paddle_tpu.serving.router import Server

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_ROOT, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def fresh_metrics():
    obs.reset()
    obs.set_enabled(True)
    faults.clear()
    yield
    recorder.uninstall()
    pub = timeline.current_publisher()
    if pub is not None:
        pub.stop()
    faults.clear()
    obs.reset()
    obs.set_enabled(None)


def _churn(i):
    """One round of representative registry traffic."""
    obs.add("guard.steps")
    obs.add("serving.goodput", 2)
    obs.add("serving.requests_served", 3)
    obs.observe("executor.step_latency", 0.002 * (i + 1))
    obs.observe("serving.request_latency", 0.01 * ((i % 7) + 1))
    obs.set_gauge("perf.mfu", 0.1 + 0.01 * i)
    obs.set_table("perf.step_attribution", {"step_seconds": 0.002 * i})


def _snap_core(snap):
    """snapshot() minus span_count (the journal doesn't carry spans)."""
    core = {k: snap[k] for k in ("counters", "gauges", "histograms")}
    core["tables"] = snap.get("tables", {})
    return core


# ---------------------------------------------------------------------------
# the journal: delta encoding, replay, rotation
# ---------------------------------------------------------------------------


def test_delta_roundtrip_replays_final_snapshot_bitwise(tmp_path):
    pub = timeline.TelemetryPublisher(
        directory=str(tmp_path), rank=0, interval=99
    ).start(register=False)
    for i in range(25):
        _churn(i)
        if i % 3 == 0:
            pub.publish()
    obs.drop_gauges("perf.mfu")  # exercise the gauge-removal delta
    obs.drop_tables("perf.")
    pub.stop()
    replayed = timeline.replay_journal(pub.path).snapshot()
    live = _snap_core(obs.snapshot())
    assert replayed["counters"] == live["counters"]
    assert replayed["gauges"] == live["gauges"]
    assert replayed["histograms"] == live["histograms"]
    assert replayed.get("tables", {}) == live["tables"]
    # bitwise: identical through JSON too (float repr round-trip exact)
    assert json.dumps(replayed, sort_keys=True) == json.dumps(
        dict(live, tables=live["tables"]) if live["tables"]
        else {k: live[k] for k in ("counters", "gauges", "histograms")},
        sort_keys=True,
    )


def test_journal_records_are_deltas_not_snapshots(tmp_path):
    pub = timeline.TelemetryPublisher(
        directory=str(tmp_path), rank=0, interval=99
    ).start(register=False)
    obs.add("big.counter", 1000)
    pub.publish()
    obs.add("big.counter")  # +1
    pub.publish()
    pub.stop()
    records = timeline.read_records(pub.path)
    kinds = [r["kind"] for r in records]
    assert kinds[0] == "base" and "delta" in kinds
    deltas = [r for r in records if r["kind"] == "delta"
              and "big.counter" in (r.get("counters") or {})]
    assert deltas and deltas[0]["counters"]["big.counter"] == 1
    # idle publishes carry ONLY the plane's self-telemetry (the
    # publishes counter / journal-bytes gauge the replay contract needs)
    # — no user metric reappears without having changed
    n = len(records)
    pub2 = timeline.TelemetryPublisher(
        directory=str(tmp_path), rank=1, interval=99
    ).start(register=False)
    pub2.publish()
    pub2.publish()
    idle = timeline.read_records(pub2.path)[-1]
    assert idle["kind"] == "delta"
    for section in ("counters", "gauges"):
        keys = set(idle.get(section) or {})
        assert keys and all(k.startswith("telemetry.") for k in keys), idle
    assert not idle.get("hists") and not idle.get("tables")
    pub2.stop()
    assert len(timeline.read_records(pub.path)) == n  # stopped = frozen


def test_metrics_reset_rebases_the_journal(tmp_path):
    pub = timeline.TelemetryPublisher(
        directory=str(tmp_path), rank=0, interval=99
    ).start(register=False)
    _churn(0)
    pub.publish()
    obs.reset()  # counters run BACKWARD: a delta would be nonsense
    obs.add("after.reset", 7)
    pub.publish()
    pub.stop()
    replayed = timeline.replay_journal(pub.path).snapshot()
    live = _snap_core(obs.snapshot())
    assert replayed["counters"] == live["counters"]
    assert replayed["histograms"] == live["histograms"]
    # the rebase is visible as a second base record
    kinds = [r["kind"] for r in timeline.read_records(pub.path)]
    assert kinds.count("base") >= 2


def test_rotation_cap_honored_and_current_shard_self_contained(tmp_path):
    cap = 1500
    pub = timeline.TelemetryPublisher(
        directory=str(tmp_path), rank=0, interval=99, max_bytes=cap
    ).start(register=False)
    for i in range(80):
        _churn(i)
        pub.publish()
    pub.stop()
    # cap + one record of slack: rotation happens after the append
    assert os.path.getsize(pub.path) <= cap + 800
    assert os.path.exists(pub.path + ".1")
    assert metrics.get_counters()["telemetry.rotations"] >= 1
    # the CURRENT shard alone (no predecessor) replays the final state:
    # every shard file opens with a full base record
    replayed = timeline.replay_journal(
        pub.path, include_rotated=False
    ).snapshot()
    live = _snap_core(obs.snapshot())
    assert replayed["counters"] == live["counters"]
    assert replayed["histograms"] == live["histograms"]


def test_torn_tail_is_skipped_not_fatal(tmp_path):
    pub = timeline.TelemetryPublisher(
        directory=str(tmp_path), rank=0, interval=99
    ).start(register=False)
    _churn(0)
    pub.publish()
    expected = timeline.replay_journal(pub.path).snapshot()
    # SIGKILL mid-write: a half-record with no trailing newline
    with open(pub.path, "a") as f:
        f.write('{"kind":"delta","seq":99,"counters":{"torn"')
    replayed = timeline.replay_journal(pub.path).snapshot()
    assert replayed == expected
    pub.stop()


def test_heartbeat_stamps_journal_offset(tmp_path):
    pub = timeline.TelemetryPublisher(
        directory=str(tmp_path), rank=3, interval=99
    ).start()  # registered: journal_stamp() sees it
    _churn(0)
    pub.publish()
    hb = Heartbeat(directory=str(tmp_path / "hb"), rank=3)
    payload = hb.beat()
    assert payload["telemetry_shard"] == "telemetry_rank3.jsonl"
    seq, off = pub.offset()
    assert payload["telemetry_seq"] == seq > 0
    assert payload["telemetry_offset"] == off > 0
    # the stamp is in the published file too (what a fleet reader sees)
    on_disk = json.load(open(hb.path))
    assert on_disk["telemetry_seq"] == seq
    pub.stop()
    assert timeline.journal_stamp() is None


# ---------------------------------------------------------------------------
# the flight recorder: every trigger kind dumps a bundle
# ---------------------------------------------------------------------------


def _bundle(tmp_path, rank, trigger):
    path = os.path.join(str(tmp_path), f"flight_rank{rank}.{trigger}.json")
    assert os.path.exists(path), os.listdir(str(tmp_path))
    return json.load(open(path))


def test_flight_dump_exception_trigger(tmp_path):
    rec = recorder.FlightRecorder(directory=str(tmp_path), rank=0,
                                  interval=99).start()
    with obs.span("doomed.work"):
        time.sleep(0.01)
    tr = obs.new_trace()
    with obs.activate(tr), obs.span("traced.work"):
        pass
    try:
        raise ValueError("boom")
    except ValueError as e:
        recorder.flight_dump("exception", exc=e)
    b = _bundle(tmp_path, 0, "exception")
    assert b["trigger"] == "exception"
    assert b["exception"]["type"] == "ValueError"
    assert any(s["name"] == "doomed.work" for s in b["spans"])
    assert tr.trace_id in b["trace_ids"]
    assert metrics.get_counters()["telemetry.flight_dumps.exception"] == 1
    rec.stop()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_excepthook_chains_and_dumps(tmp_path):
    rec = recorder.FlightRecorder(directory=str(tmp_path), rank=0,
                                  interval=99).start()
    recorder.install_excepthook()
    seen = []
    prev, sys.excepthook = sys.excepthook, None
    try:
        sys.excepthook = prev  # restore: install chained the REAL prev
        err = RuntimeError("unhandled")
        # fire a thread whose exception flows through threading.excepthook
        t = threading.Thread(
            target=lambda: (_ for _ in ()).throw(err), name="crashy"
        )
        t.start()
        t.join()
    finally:
        rec.stop()
    b = _bundle(tmp_path, 0, "exception")
    assert b["exception"]["message"] == "unhandled"
    assert b["detail"]["thread"] == "crashy"
    assert not seen  # the chained previous hook ran harmlessly


def test_watchdog_stall_trigger(tmp_path):
    rec = recorder.FlightRecorder(directory=str(tmp_path), rank=0,
                                  interval=99).start()
    with StepWatchdog(timeout=0.05, poll_interval=0.02, name="t16") as wd:
        deadline = time.time() + 5.0
        while wd.stalls == 0 and time.time() < deadline:
            time.sleep(0.02)
    rec.stop()
    b = _bundle(tmp_path, 0, "watchdog_stall")
    assert b["detail"]["name"] == "t16"
    assert b["detail"]["stalled_s"] > 0.05


def test_train_rollback_and_preempt_drain_triggers(tmp_path):
    class _StubFleet:
        def has_check_point(self, d, fs=None):
            return True

        def load_check_point(self, exe, d, main_program=None, fs=None):
            return None

    rec = recorder.FlightRecorder(directory=str(tmp_path), rank=0,
                                  interval=99).start()
    g = TrainGuard(
        executor=object(), fleet=_StubFleet(), checkpoint_dir="ckpt",
        max_bad_steps=1, exit_on_preempt=False, snapshot=False,
    )
    g._skip_bad_step(None)  # streak hits the cap -> rollback branch
    assert g.rollbacks == 1
    b = _bundle(tmp_path, 0, "train_rollback")
    assert b["detail"]["rollbacks"] == 1
    g2 = TrainGuard(executor=object(), exit_on_preempt=False)
    g2._finalize_preemption()
    b = _bundle(tmp_path, 0, "preempt_drain")
    assert b["trigger"] == "preempt_drain"
    rec.stop()


def test_breaker_open_and_serving_drain_triggers(tmp_path):
    from paddle_tpu import errors

    class _Runner:
        feed_names = ("x",)

        def sample_spec(self, name):
            return (2,), "float32"

        def run(self, feed):
            raise errors.UnavailableError("replica died")

    rec = recorder.FlightRecorder(directory=str(tmp_path), rank=0,
                                  interval=99).start()
    rs = ReplicaSet({"a": _Runner(), "b": _Runner()}, breaker_threshold=1,
                    cooldown_s=60)
    import numpy as np

    with pytest.raises(errors.UnavailableError):
        rs.run({"x": np.zeros((1, 2), np.float32)}, request_ids=[1])
    b = _bundle(tmp_path, 0, "breaker_open")
    assert b["detail"]["replica"] in ("a", "b")
    assert "UnavailableError" in b["detail"]["error"]
    server = Server()
    server.drain(timeout=1)
    b = _bundle(tmp_path, 0, "serving_drain")
    assert b["trigger"] == "serving_drain" and b["detail"]["clean"]
    rec.stop()


def test_black_box_survives_without_a_trigger(tmp_path):
    """The periodic bundle is the SIGKILL story: no hook ever fires, yet
    the window before death is on disk."""
    rec = recorder.FlightRecorder(directory=str(tmp_path), rank=2,
                                  interval=0.02).start()
    with obs.span("pre.death"):
        _churn(0)
    deadline = time.time() + 5.0
    while not os.path.exists(rec.path) and time.time() < deadline:
        time.sleep(0.02)
    # simulate the kill: no stop(), no dump() — just read what the black
    # box already published
    b = json.load(open(rec.path))
    assert b["trigger"] == "periodic"
    assert any(s["name"] == "pre.death" for s in b["spans"])
    rec.stop()


# ---------------------------------------------------------------------------
# fleet aggregation + remote-journal watcher
# ---------------------------------------------------------------------------


def _write_shard(tmp_path, rank, steps, latency_s, publishes=4,
                 torn_tail=False):
    """Journal one synthetic rank: `steps` guard steps, request latencies
    at `latency_s`, spread over `publishes` records. Resets the registry
    first so each shard carries an independent process's state."""
    obs.reset()
    pub = timeline.TelemetryPublisher(
        directory=str(tmp_path), rank=rank, interval=99
    ).start(register=False)
    per = max(1, -(-steps // publishes))  # ceil: journal EVERY step
    done = 0
    for _ in range(publishes):
        for _ in range(min(per, steps - done)):
            obs.add("guard.steps")
            obs.add("serving.requests_served")
            obs.add("serving.goodput")
            obs.observe("executor.step_latency", latency_s)
            obs.observe("serving.request_latency", latency_s)
            done += 1
        pub.publish()
    pub.stop()
    if torn_tail:  # mid-run death: half a record after the good ones
        with open(pub.path, "a") as f:
            f.write('{"kind":"delta","seq":999,"coun')
    return pub.path


def test_fleet_report_merges_shards_with_mid_run_death(tmp_path):
    _write_shard(tmp_path, 0, steps=40, latency_s=0.01)
    _write_shard(tmp_path, 1, steps=38, latency_s=0.02)
    # rank 2 dies mid-run: fewer steps journaled, torn final write
    _write_shard(tmp_path, 2, steps=9, latency_s=0.5, torn_tail=True)
    fleet_report = _load_tool("fleet_report")
    report = fleet_report.build_report(str(tmp_path))
    assert len(report["shards"]) == 3
    by_rank = {s["rank"]: s for s in report["shards"]}
    # the dead rank's last steps are reconstructed from its journal alone
    assert by_rank[2]["last_step"] == 9
    assert by_rank[0]["last_step"] == 40
    fleet = report["fleet"]
    assert fleet["goodput_total"] == 40 + 38 + 9
    strag = fleet["straggler"]
    assert strag["max_gap_steps"] == 40 - 9
    assert strag["per_rank_last_step"]["2"] == 9
    # cross-process p99 reconstructed from merged bucket deltas: rank 2's
    # 0.5s latencies must pull the fleet p99 above the fast ranks' 0.02
    p99s = [e["p99_s"] for e in fleet["timeline"] if "p99_s" in e]
    assert p99s and max(p99s) >= 0.5
    # per-rank step-time curves replayed out of the journals
    assert set(fleet["step_time"]) == {"0", "1", "2"}
    assert fleet["step_time"]["2"][-1][1] == pytest.approx(0.5)
    # the CLI gate: 3 shards expected and found
    assert fleet_report.main([str(tmp_path), "--expect-ranks", "3"]) == 0
    assert fleet_report.main([str(tmp_path), "--expect-ranks", "4"]) == 2


def test_watcher_raises_findings_from_remote_journals(tmp_path):
    _write_shard(tmp_path, 0, steps=50, latency_s=3.0)
    _write_shard(tmp_path, 1, steps=10, latency_s=0.01)
    obs.reset()  # the LOCAL registry is empty: no shared memory
    w = watch.Watcher(journal_dir=str(tmp_path), slo_p99_s=0.5)
    found = w.poll()
    kinds = sorted(f["kind"] for f in found)
    assert kinds == ["slo_breach", "straggler"]
    for f in found:
        assert f["detail"]["source"] == "journal"
    strag, = [f for f in found if f["kind"] == "straggler"]
    assert strag["detail"]["lagging_ranks"] == [1]
    assert strag["detail"]["steps"] == {"0": 50, "1": 10}
    breach, = [f for f in found if f["kind"] == "slo_breach"]
    assert breach["detail"]["p99_s"] > 0.5
    # latched: a second poll with no new journal records stays quiet
    assert w.poll() == []
    # incremental: the slow rank catching up re-arms the straggler latch
    obs.reset()
    pub = timeline.TelemetryPublisher(
        directory=str(tmp_path), rank=1, interval=99
    ).start(register=False)
    obs.add("guard.steps", 49)
    pub.publish()
    pub.stop()
    w.poll()
    assert not w._journal_straggling


# ---------------------------------------------------------------------------
# the shared windowed-p99 helper (satellite)
# ---------------------------------------------------------------------------


def _legacy_window_p99(prev_buckets, cur_buckets):
    """The pre-extraction watch.py implementation, verbatim — the golden
    reference proving the shared helper did not change behavior."""
    prev = {str(le): c for le, c in (prev_buckets or [])}
    deltas = [(le, cum - prev.get(str(le), 0)) for le, cum in cur_buckets]
    total = deltas[-1][1] if deltas else 0
    if total <= 0:
        return None
    target = 0.99 * total
    finite = [float(le) for le, _ in deltas if not isinstance(le, str)]
    for le, cum_d in deltas:
        if cum_d >= target:
            if isinstance(le, str):
                return (max(finite) * 2.0) if finite else float("inf")
            return float(le)
    return (max(finite) * 2.0) if finite else float("inf")


def test_window_p99_golden_against_legacy_implementation():
    import random

    rng = random.Random(16)
    cases = [(None, [["+Inf", 0]]), (None, []), (None, [[0.1, 5],
                                                        ["+Inf", 5]])]
    for _ in range(200):
        bounds = sorted(rng.sample([0.001, 0.01, 0.05, 0.1, 0.5, 1.0,
                                    5.0], rng.randint(1, 5)))
        prev_counts, cur = [], []
        run = 0
        for le in bounds:
            run += rng.randint(0, 10)
            prev_counts.append([le, run])
        prev_counts.append(["+Inf", run + rng.randint(0, 5)])
        for (le, c) in prev_counts:
            cur.append([le, c + rng.randint(0, 20)])
        # cumulative monotonicity for the cur side
        for i in range(1, len(cur)):
            cur[i][1] = max(cur[i][1], cur[i - 1][1])
        cases.append((prev_counts if rng.random() < 0.7 else None, cur))
    for prev, cur in cases:
        assert metrics.window_p99(prev, cur) == _legacy_window_p99(
            prev, cur
        ), (prev, cur)
    # the watch-module alias IS the shared helper (call sites unchanged)
    assert watch._window_p99 is metrics.window_p99


def test_brownout_fallback_computes_p99_via_shared_helper():
    class _Server:
        def endpoints(self):
            return {}

    ctl = brownout_mod.BrownoutController(
        _Server(), slo_p99_s=0.05, escalate_after=1, recover_after=99
    )
    # no watcher, no watch.request_p99_s gauge: the controller must see
    # the breach from the latency histogram's bucket deltas itself
    for _ in range(40):
        obs.observe("serving.request_latency", 0.4)
    level = ctl.poll()
    assert level == 1  # escalated off its own windowed p99
    # with a watcher gauge present the gauge wins (caller unchanged)
    obs.set_gauge("watch.request_p99_s", 0.001)
    w = watch.Watcher()  # attached watcher -> gauge path
    ctl2 = brownout_mod.BrownoutController(
        _Server(), slo_p99_s=0.05, watcher=w, escalate_after=1
    )
    for _ in range(40):
        obs.observe("serving.request_latency", 0.4)
    assert ctl2.poll() == 0  # gauge says healthy: no self-computation


# ---------------------------------------------------------------------------
# the kill-switch (satellite, alongside the PR-13 test)
# ---------------------------------------------------------------------------


def test_kill_switch_no_threads_no_files(tmp_path):
    obs.set_enabled(False)
    pub = timeline.TelemetryPublisher(directory=str(tmp_path), rank=0,
                                      interval=0.01).start()
    rec = recorder.FlightRecorder(directory=str(tmp_path), rank=0,
                                  interval=0.01).start()
    assert pub._thread is None and rec._thread is None
    assert pub.publish() is None
    assert rec.dump("exception") is None
    assert recorder.flight_dump("exception") is None
    assert timeline.journal_stamp() is None
    os.environ["PADDLE_TPU_TELEMETRY_DIR"] = str(tmp_path)
    try:
        assert timeline.ensure_publisher() is None
    finally:
        del os.environ["PADDLE_TPU_TELEMETRY_DIR"]
    time.sleep(0.05)
    assert os.listdir(str(tmp_path)) == []  # not one file, not one thread
    obs.set_enabled(True)


def test_ensure_publisher_one_env_var_opt_in(tmp_path):
    os.environ["PADDLE_TPU_TELEMETRY_DIR"] = str(tmp_path)
    os.environ["PADDLE_TRAINER_ID"] = "5"
    try:
        pub = timeline.ensure_publisher()
        assert pub is not None and pub.rank == 5
        assert timeline.ensure_publisher() is pub  # idempotent
        assert recorder.get_recorder() is not None
        _churn(0)
        pub.publish()
        assert os.path.exists(timeline.shard_path(str(tmp_path), 5))
    finally:
        del os.environ["PADDLE_TPU_TELEMETRY_DIR"]
        del os.environ["PADDLE_TRAINER_ID"]
        rec = recorder.get_recorder()
        if rec is not None:
            rec.stop()
