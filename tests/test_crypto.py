"""Model-file crypto: both AES cores (native C++ and pure-Python fallback)
against the FIPS-197 / NIST SP 800-38A known-answer vectors, the
encrypt-then-MAC wire format, and an encrypted save/load round trip."""


import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, native
from paddle_tpu.crypto import (
    AESCipher,
    CipherFactory,
    CipherUtils,
    _py_block_encrypt,
    _py_ctr_crypt,
)
from paddle_tpu.framework import unique_name

# FIPS-197 appendix C.1 (AES-128) and C.3 (AES-256)
_PT = bytes.fromhex("00112233445566778899aabbccddeeff")
_K128 = bytes.fromhex("000102030405060708090a0b0c0d0e0f")
_CT128 = bytes.fromhex("69c4e0d86a7b0430d8cdb78070b4c55a")
_K256 = bytes.fromhex(
    "000102030405060708090a0b0c0d0e0f101112131415161718191a1b1c1d1e1f"
)
_CT256 = bytes.fromhex("8ea2b7ca516745bfeafc49904b496089")

# NIST SP 800-38A F.5.1 CTR-AES128
_CTR_KEY = bytes.fromhex("2b7e151628aed2a6abf7158809cf4f3c")
_CTR_IV = bytes.fromhex("f0f1f2f3f4f5f6f7f8f9fafbfcfdfeff")
_CTR_PT = bytes.fromhex(
    "6bc1bee22e409f96e93d7e117393172a"
    "ae2d8a571e03ac9c9eb76fac45af8e51"
)
_CTR_CT = bytes.fromhex(
    "874d6191b620e3261bef6864990db6ce"
    "9806f66b7970fdff8617187bb9fffdff"
)


def test_python_core_known_answers():
    assert _py_block_encrypt(_K128, _PT) == _CT128
    assert _py_block_encrypt(_K256, _PT) == _CT256
    assert _py_ctr_crypt(_CTR_KEY, _CTR_IV, _CTR_PT) == _CTR_CT
    # CTR is its own inverse
    assert _py_ctr_crypt(_CTR_KEY, _CTR_IV, _CTR_CT) == _CTR_PT


@pytest.mark.skipif(not native.native_available(), reason="no C++ toolchain")
def test_native_core_known_answers():
    assert native.aes_block_encrypt(_K128, _PT) == _CT128
    assert native.aes_block_encrypt(_K256, _PT) == _CT256
    assert native.aes_ctr_crypt(_CTR_KEY, _CTR_IV, _CTR_PT) == _CTR_CT
    # native and fallback agree on an odd-length (non-block) payload
    data = bytes(range(256)) * 3 + b"tail"
    assert native.aes_ctr_crypt(_K128, _CTR_IV, data) == _py_ctr_crypt(
        _K128, _CTR_IV, data
    )


def test_cipher_roundtrip_and_tamper_detection(tmp_path):
    cipher = AESCipher()
    key = CipherUtils.gen_key(256)
    msg = b"model bytes \x00\x01" * 1000
    blob = cipher.encrypt(msg, key)
    assert len(blob) == 16 + len(msg) + 16
    assert cipher.decrypt(blob, key) == msg
    # flip one ciphertext byte -> authentication failure
    bad = bytearray(blob)
    bad[20] ^= 1
    with pytest.raises(ValueError, match="authentication failed"):
        cipher.decrypt(bytes(bad), key)
    # wrong key -> authentication failure
    with pytest.raises(ValueError, match="authentication failed"):
        cipher.decrypt(blob, CipherUtils.gen_key(256))
    # file helpers
    p = tmp_path / "m.enc"
    cipher.encrypt_to_file(msg, key, str(p))
    assert cipher.decrypt_from_file(key, str(p)) == msg


def test_cipher_factory_and_key_files(tmp_path):
    cfg = tmp_path / "cipher.conf"
    cfg.write_text("# comment\ncipher_name=AES_CTR_NoPadding\ntag_size=16\n")
    cipher = CipherFactory.create_cipher(str(cfg))
    assert isinstance(cipher, AESCipher)
    keyfile = tmp_path / "k.bin"
    key = CipherUtils.gen_key_to_file(128, str(keyfile))
    assert CipherUtils.read_key_from_file(str(keyfile)) == key
    assert len(key) == 16


def test_encrypted_model_roundtrip(tmp_path):
    """Encrypt a saved model payload, decrypt, reload, same predictions —
    the reference's model-protection flow (pybind/crypto.cc users)."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 1
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        x = fluid.data("x", [4, 8])
        y = layers.fc(x, 3, param_attr=fluid.ParamAttr(name="w"))
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        model_dir = tmp_path / "model"
        fluid.io.save_inference_model(
            str(model_dir), ["x"], [y], exe, main_program=main,
            model_filename="model", params_filename="params.npz",
        )
        feed = np.random.RandomState(0).randn(4, 8).astype("float32")
        (ref,) = exe.run(main, feed={"x": feed}, fetch_list=[y], scope=scope)

    cipher = AESCipher()
    key = CipherUtils.gen_key(256)
    for fn in ("model", "params.npz"):
        path = model_dir / fn
        cipher.encrypt_to_file(path.read_bytes(), key, str(path) + ".enc")
        path.unlink()
    # decrypt and reload
    for fn in ("model", "params.npz"):
        path = model_dir / fn
        path.write_bytes(
            cipher.decrypt_from_file(key, str(path) + ".enc")
        )
    scope2 = fluid.framework.scope.Scope()
    with fluid.scope_guard(scope2):
        exe2 = fluid.Executor()
        prog, feeds, fetches = fluid.io.load_inference_model(
            str(model_dir), exe2, model_filename="model",
            params_filename="params.npz",
        )
        (out,) = exe2.run(
            prog, feed={"x": feed}, fetch_list=fetches, scope=scope2
        )
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=1e-6)
