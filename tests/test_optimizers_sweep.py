"""Optimizer sweep (reference test_optimizer.py role): every fluid
optimizer class — including the round-3 ProximalGD/ProximalAdagrad —
reduces fit-a-line loss; proximal L1 shrinks weights toward sparsity."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name

OPTS = [
    ("SGD", lambda: fluid.optimizer.SGD(0.05)),
    ("Momentum", lambda: fluid.optimizer.Momentum(0.02, 0.9)),
    ("Adam", lambda: fluid.optimizer.Adam(0.05)),
    ("AdamW", lambda: fluid.optimizer.AdamW(0.05)),
    ("Adamax", lambda: fluid.optimizer.Adamax(0.05)),
    ("Adagrad", lambda: fluid.optimizer.Adagrad(0.2)),
    ("DecayedAdagrad", lambda: fluid.optimizer.DecayedAdagrad(0.2)),
    # adadelta's update ratio warms up from ~0 (rho=0.95 running
    # averages), so it gets more steps and a looser bar
    ("Adadelta", lambda: fluid.optimizer.Adadelta(8.0)),
    ("RMSProp", lambda: fluid.optimizer.RMSProp(0.02)),
    ("Ftrl", lambda: fluid.optimizer.Ftrl(0.2)),
    ("Lamb", lambda: fluid.optimizer.Lamb(0.05)),
    ("ProximalGD", lambda: fluid.optimizer.ProximalGD(0.05)),
    ("ProximalAdagrad", lambda: fluid.optimizer.ProximalAdagrad(0.2)),
]


@pytest.fixture(autouse=True)
def fresh():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield


@pytest.mark.parametrize("name,mk", OPTS, ids=[o[0] for o in OPTS])
def test_optimizer_converges(name, mk):
    x = fluid.data("x", [16, 4])
    y = fluid.data("y", [16, 1])
    loss = layers.mean(layers.square_error_cost(layers.fc(x, 1), y))
    mk().minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(0)
    xv = rng.randn(16, 4).astype(np.float32)
    yv = (xv @ np.arange(4, dtype=np.float32).reshape(4, 1)).astype(
        np.float32)
    steps, bar = (150, 0.85) if name == "Adadelta" else (40, 0.7)
    losses = [
        float(np.asarray(exe.run(feed={"x": xv, "y": yv},
                                 fetch_list=[loss])[0]).reshape(-1)[0])
        for _ in range(steps)
    ]
    assert losses[-1] < losses[0] * bar, (name, losses[0], losses[-1])


def test_proximal_l1_drives_weights_to_zero():
    """With zero gradient signal and strong L1, the proximal operator is
    pure soft-thresholding: weights shrink toward exactly zero."""
    x = fluid.data("x", [8, 4])
    y = fluid.data("y", [8, 1])
    pred = layers.fc(x, 1, param_attr=fluid.ParamAttr(name="pw"),
                     bias_attr=False)
    loss = layers.mean(layers.square_error_cost(pred, y))
    fluid.optimizer.ProximalGD(0.1, l1_regularization_strength=1.0) \
        .minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    feed = {"x": np.zeros((8, 4), np.float32),
            "y": np.zeros((8, 1), np.float32)}
    scope = fluid.framework.scope.global_scope()
    w0 = np.abs(np.asarray(scope.find_var("pw"))).sum()
    for _ in range(30):
        exe.run(feed=feed, fetch_list=[loss])
    w1 = np.abs(np.asarray(scope.find_var("pw"))).sum()
    assert w1 < w0 * 0.05, (w0, w1)
