"""Pipeline parallelism: GPipe schedule over the pp mesh axis.

Done-bar from VERDICT item 5: a 2-stage model on the virtual CPU mesh
matches single-device losses. Modeled on the reference's
test_pipeline.py (which compared pipelined vs plain training loss).
"""

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name
from paddle_tpu.models import BertConfig, bert_pretrain
from paddle_tpu.parallel import PipelineOptimizer, shard_program
from paddle_tpu.parallel.mesh import make_mesh


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


def _build_mlp(b):
    x = fluid.data("x", [b, 8])
    y = fluid.data("y", [b, 1])
    with fluid.device_guard("pipeline:0"):
        h = layers.fc(x, 16, act="relu",
                      param_attr=fluid.ParamAttr(name="w0"),
                      bias_attr=fluid.ParamAttr(name="b0"))
    with fluid.device_guard("pipeline:1"):
        pred = layers.fc(h, 1,
                         param_attr=fluid.ParamAttr(name="w1"),
                         bias_attr=fluid.ParamAttr(name="b1"))
        loss = layers.mean(layers.square_error_cost(pred, y))
    return x, y, loss


def _mlp_feed(b, seed=0):
    rng = np.random.RandomState(seed)
    xv = rng.randn(b, 8).astype(np.float32)
    yv = (xv @ rng.randn(8, 1)).astype(np.float32)
    return {"x": xv, "y": yv}


def test_pipeline_matches_plain_training():
    """2-stage pipelined MLP on a pp=2 mesh tracks a plain single-device
    run step for step (same seeds => same init => same losses)."""
    b, steps = 16, 6

    # --- plain reference run ---
    plain_losses = []
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        x, y, loss = _build_mlp(b)
        fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        for i in range(steps):
            (lv,) = exe.run(feed=_mlp_feed(b, i), fetch_list=[loss])
            plain_losses.append(float(np.asarray(lv).reshape(-1)[0]))

    # --- pipelined run on pp=2 ---
    pipe_losses = []
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        x, y, loss = _build_mlp(b)
        opt = PipelineOptimizer(fluid.optimizer.SGD(0.1), num_microbatches=4)
        opt.minimize(loss)
        mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
        shard_program(main, mesh)
        exe = fluid.Executor()
        exe.run(startup)
        for i in range(steps):
            (lv,) = exe.run(feed=_mlp_feed(b, i), fetch_list=[loss])
            pipe_losses.append(float(np.asarray(lv).reshape(-1)[0]))

    np.testing.assert_allclose(plain_losses, pipe_losses, rtol=2e-5)


def test_pipeline_single_device_degrade_matches():
    """Without a mesh the pipeline_block runs stages sequentially with
    identical numerics (nranks==1 degrade)."""
    b = 8
    x, y, loss = _build_mlp(b)
    opt = PipelineOptimizer(fluid.optimizer.SGD(0.1), num_microbatches=2)
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    feed = _mlp_feed(b, 0)  # fixed feed: loss must strictly decrease
    losses = []
    for i in range(5):
        (lv,) = exe.run(feed=feed, fetch_list=[loss])
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.5


def test_pipeline_validates_cuts():
    b = 8
    x = fluid.data("x", [b, 4])
    with fluid.device_guard("pipeline:0"):
        h1 = layers.fc(x, 4)
        h2 = layers.fc(x, 4)
    with fluid.device_guard("pipeline:1"):
        # two boundary vars cross the cut -> must be rejected
        out = layers.mean(h1 + h2)
    opt = PipelineOptimizer(fluid.optimizer.SGD(0.1), num_microbatches=2)
    with pytest.raises(ValueError, match="more than"):
        opt.minimize(out)


def test_pipeline_bert_two_stages():
    """2-stage BERT-tiny on pp=2: trains, and the first-step loss matches
    the unpipelined program (dropout disabled for determinism)."""
    cfg = BertConfig.tiny()
    cfg.hidden_dropout = cfg.attention_dropout = 0.0
    b, s = 8, 16

    def build_loss(cfg):
        ids = fluid.data("ids", [b, s], "int64")
        types = fluid.data("types", [b, s], "int64")
        mask = fluid.data("mask", [b, s], "float32")
        labels = fluid.data("labels", [b, s], "int64")
        from paddle_tpu.models import bert as bert_mod

        with fluid.device_guard("pipeline:0"):
            emb_half = bert_mod.bert_encoder(
                ids, types, mask, cfg, is_test=False, num_layers=1
            )
        with fluid.device_guard("pipeline:1"):
            seq = bert_mod.bert_encoder_layers(
                emb_half, mask, cfg, start=1, is_test=False
            )
            loss = bert_mod.bert_mlm_head(seq, labels, cfg)
        return loss

    rng = np.random.RandomState(0)
    lab = rng.randint(0, cfg.vocab_size, (b, s)).astype("int32")
    # equal masked count per ROW so per-microbatch masked-mean denominators
    # match and the GPipe uniform-mean objective equals the plain one
    # (see pipeline.py objective-semantics note)
    for r in range(b):
        keep = rng.choice(s, size=3, replace=False)
        row = np.full(s, -100, np.int32)
        row[keep] = lab[r, keep]
        lab[r] = row
    feed = {
        "ids": rng.randint(0, cfg.vocab_size, (b, s)).astype("int32"),
        "types": rng.randint(0, 2, (b, s)).astype("int32"),
        "mask": np.ones((b, s), "float32"),
        "labels": lab,
    }

    # plain
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        loss = build_loss(cfg)
        fluid.optimizer.SGD(0.05).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        plain = [
            float(np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])
                  .reshape(-1)[0])
            for _ in range(3)
        ]

    # pipelined
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        loss = build_loss(cfg)
        opt = PipelineOptimizer(fluid.optimizer.SGD(0.05), num_microbatches=2)
        opt.minimize(loss)
        mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
        shard_program(main, mesh)
        exe = fluid.Executor()
        exe.run(startup)
        piped = [
            float(np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])
                  .reshape(-1)[0])
            for _ in range(3)
        ]

    np.testing.assert_allclose(plain, piped, rtol=5e-5)
