"""Sequence-parallel (ring/Ulysses) and expert-parallel (MoE) tests on the
8-device CPU mesh: sharded runs must match the dense single-device math."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.parallel import make_mesh, shard_program


def _dense_attention(q, k, v, causal=False):
    d = q.shape[-1]
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    if causal:
        n = s.shape[-1]
        mask = np.tril(np.ones((n, n), bool))
        s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


def _run_attention(op_name, causal, sharded):
    b, h, s, d = 2, 8, 32, 16
    rng = np.random.RandomState(0)
    q = rng.randn(b, h, s, d).astype("float32")
    k = rng.randn(b, h, s, d).astype("float32")
    v = rng.randn(b, h, s, d).astype("float32")

    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        qv = fluid.data("q", [b, h, s, d], "float32")
        kv = fluid.data("k", [b, h, s, d], "float32")
        vv = fluid.data("v", [b, h, s, d], "float32")
        fn = getattr(layers, op_name)
        out = fn(qv, kv, vv, axis_name="sp", causal=causal)
    if sharded:
        mesh = make_mesh({"sp": 8})
        shard_program(
            main,
            mesh,
            {
                "q": (None, None, "sp"),
                "k": (None, None, "sp"),
                "v": (None, None, "sp"),
                out.name: (None, None, "sp"),
            },
        )
    exe = fluid.Executor()
    (res,) = exe.run(
        main, feed={"q": q, "k": k, "v": v}, fetch_list=[out]
    )
    expect = _dense_attention(q, k, v, causal)
    np.testing.assert_allclose(res, expect, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_sharded_matches_dense(causal):
    _run_attention("ring_attention", causal, sharded=True)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_single_device(causal):
    _run_attention("ring_attention", causal, sharded=False)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_sharded_matches_dense(causal):
    _run_attention("ulysses_attention", causal, sharded=True)


def test_ring_attention_backward_under_sp():
    """Train through ring attention on the sp mesh: grads flow through
    ppermute and loss decreases."""
    from paddle_tpu.optimizer import SGD

    b, h, s, d = 1, 2, 16, 8
    rng = np.random.RandomState(0)
    x_np = rng.randn(b, h, s, d).astype("float32")

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [b, h, s, d], "float32")
        q = layers.fc(x, size=d, num_flatten_dims=3)
        k = layers.fc(x, size=d, num_flatten_dims=3)
        v = layers.fc(x, size=d, num_flatten_dims=3)
        o = layers.ring_attention(q, k, v, axis_name="sp", causal=True)
        loss = layers.reduce_mean(layers.square(o))
        SGD(0.5).minimize(loss, startup)
    mesh = make_mesh({"sp": 8})
    shard_program(main, mesh, {"x": (None, None, "sp")})
    exe = fluid.Executor()
    scope = fluid.framework.scope.Scope()
    exe.run(startup, scope=scope)
    vals = []
    for _ in range(4):
        (lv,) = exe.run(main, feed={"x": x_np}, fetch_list=[loss], scope=scope)
        vals.append(float(np.asarray(lv).reshape(-1)[0]))
    assert vals[-1] < vals[0] and np.isfinite(vals).all()


def test_moe_dense_vs_expert_parallel():
    """The same MoE layer must produce identical outputs dense (no mesh) and
    expert-parallel (experts sharded over ep)."""
    b, s, h, e, f = 1, 16, 8, 8, 16
    rng = np.random.RandomState(0)
    x_np = rng.randn(b, s, h).astype("float32")

    def build():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 9
        with fluid.program_guard(main, startup):
            x = fluid.data("x", [b, s, h], "float32")
            out, aux = layers.moe_ffn(
                x, num_experts=e, hidden_dim=f, axis_name="ep",
                param_attr_prefix="m0",
            )
            tot = layers.reduce_mean(layers.square(out))
        return main, startup, tot, out

    main1, st1, tot1, out1 = build()
    exe = fluid.Executor()
    sc1 = fluid.framework.scope.Scope()
    exe.run(st1, scope=sc1)
    (dense,) = exe.run(main1, feed={"x": x_np}, fetch_list=[out1], scope=sc1)

    main2, st2, tot2, out2 = build()
    mesh = make_mesh({"ep": 8})
    sh = layers.moe_shardings("m0", axis="ep")
    shard_program(main2, mesh, sh)
    sc2 = fluid.framework.scope.Scope()
    exe.run(st2, scope=sc2)
    (ep,) = exe.run(main2, feed={"x": x_np}, fetch_list=[out2], scope=sc2)

    np.testing.assert_allclose(dense, ep, rtol=2e-5, atol=2e-5)
    # routing actually spreads load: output nonzero
    assert np.abs(dense).sum() > 0


@pytest.mark.parametrize("backend", ["pallas", "jnp"])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_backward_grads_match_dense_autodiff(backend, causal):
    """The hand-written custom_vjp ring backward (review r5): BOTH shard
    backends' dq/dk/dv must match plain autodiff of dense attention.
    h*d=128 so ring_supports passes and 'pallas' really runs the
    kernels/ring_block.py backward kernels (interpret mode on CPU)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.parallel import ring_attention as ra

    b, h, s, d = 1, 8, 32, 16
    n = 8
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(b, h, s, d).astype("float32"))
    k = jnp.asarray(rng.randn(b, h, s, d).astype("float32"))
    v = jnp.asarray(rng.randn(b, h, s, d).astype("float32"))
    w = jnp.asarray(rng.randn(b, h, s, d).astype("float32"))
    scale = 1.0 / np.sqrt(d)
    mesh = Mesh(np.array(jax.devices()[:n]), ("sp",))

    def ring_loss(q, k, v):
        def local(q, k, v):
            return ra._ring_core(q, k, v, "sp", n, causal, float(scale),
                                 backend, True)

        out = jax.shard_map(
            local, mesh=mesh, in_specs=(P(None, None, "sp"),) * 3,
            out_specs=P(None, None, "sp"), check_vma=False,
        )(q, k, v)
        return jnp.sum(out * w)  # weighted sum probes every component

    def dense_loss(q, k, v):
        return jnp.sum(ra._attention_fallback(q, k, v, causal, scale) * w)

    got = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for g, e, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(e), rtol=2e-4, atol=2e-4,
            err_msg=f"d{name} mismatch ({backend}, causal={causal})",
        )


def test_ring_attention_jnp_backend_matches_dense(monkeypatch):
    """The default sharded path above runs the Pallas ring-block kernels
    (interpret mode on this CPU mesh); this forces the chunked-jnp shard
    backend so BOTH backends are equivalence-tested against dense."""
    from paddle_tpu.parallel import ring_attention as ra

    monkeypatch.setattr(ra, "_FORCE_JNP", True)
    for causal in (False, True):
        _run_attention("ring_attention", causal, sharded=True)
    # hand-written ring backward through the jnp shard blocks
    test_ring_attention_backward_under_sp()


def test_ring_attention_kv_chunked_matches_dense(monkeypatch):
    """r4: shards larger than _KV_CHUNK stream the keys through a
    lax.scan of chunk-sized online-softmax blocks — force a tiny chunk so
    the scan path runs at test sizes, both causal branches. (The chunk
    streaming lives in the jnp shard backend; the Pallas backend tiles in
    VMEM instead, so the jnp backend is forced here.)"""
    from paddle_tpu.parallel import ring_attention as ra

    monkeypatch.setattr(ra, "_FORCE_JNP", True)
    # chunk=1: every local shard (s_local=4 fwd, 2 bwd on the sp=8 mesh)
    # is strictly larger, so the scan path MUST run (chunk=8 exceeded the
    # shard lengths and silently tested the dense fallback)
    monkeypatch.setattr(ra, "_KV_CHUNK", 1)
    for causal in (False, True):
        _run_attention("ring_attention", causal, sharded=True)
    # backward streams the same chunks (hand-written flash backward)
    test_ring_attention_backward_under_sp()
    # chunk=3 on shard length 4: one scan chunk + a tail block of 1
    monkeypatch.setattr(ra, "_KV_CHUNK", 3)
    _run_attention("ring_attention", True, sharded=True)
    # the BACKWARD tail branch too (s_local=4 with chunk=3: restitched
    # scan chunks + concatenated tail grads) — against dense autodiff
    for causal in (False, True):
        test_ring_backward_grads_match_dense_autodiff("jnp", causal)
    # ulysses streams its full-sequence local attention the same way
    # (chunk=3 on the full S: scan chunks + tail)
    for causal in (False, True):
        _run_attention("ulysses_attention", causal, sharded=True)
