"""AnalysisConfig knobs that ACT (VERDICT r3 item 5): bf16 inference mode,
batch bucketing, persistent optim cache, AOT executable serialize/reload,
zero-copy run. Reference: inference/api/paddle_analysis_config.h,
analysis_predictor.cc, details/zero_copy_tensor.cc."""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name
from paddle_tpu.inference import (AnalysisConfig, PaddleTensor,
                                  create_paddle_predictor)


def _save_model(tmp_path, batch=4):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        x = fluid.data("x", [batch, 8])
        y = layers.fc(x, 5, act="tanh")
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        model_dir = str(tmp_path / "model")
        fluid.io.save_inference_model(model_dir, ["x"], [y], exe,
                                      main_program=main)
        feed = np.linspace(-0.5, 0.5, batch * 8,
                           dtype=np.float32).reshape(batch, 8)
        (ref,) = exe.run(main, feed={"x": feed}, fetch_list=[y],
                         scope=scope)
    return model_dir, feed, np.asarray(ref)


def test_bf16_mode_rewrites_and_runs(tmp_path):
    model_dir, feed, ref = _save_model(tmp_path)
    cfg = AnalysisConfig(model_dir)
    cfg.enable_bf16()
    pred = create_paddle_predictor(cfg)
    # the rewrite must actually insert casts (stub check: VERDICT r3 #5)
    ops = [op.type for op in pred._program.global_block.ops]
    assert "cast" in ops, ops
    (out,) = pred.run([PaddleTensor(feed, "x")])
    got = out.as_ndarray().astype(np.float32)
    # bf16 matmul: ~1e-2 relative agreement with the fp32 reference
    np.testing.assert_allclose(got, ref, rtol=3e-2, atol=3e-2)


def test_batch_bucketing_pads_and_slices(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        x = fluid.data("x", [-1, 8])
        y = layers.fc(x, 5, act="tanh")
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        model_dir = str(tmp_path / "m2")
        fluid.io.save_inference_model(model_dir, ["x"], [y], exe,
                                      main_program=main)
    cfg = AnalysisConfig(model_dir)
    cfg.set_batch_buckets([4, 16])
    pred = create_paddle_predictor(cfg)
    rng = np.random.RandomState(0)
    for b in (1, 3, 4, 7, 16):
        feed = rng.randn(b, 8).astype(np.float32)
        (out,) = pred.run([PaddleTensor(feed, "x")])
        assert out.as_ndarray().shape == (b, 5)
    # only two bucket shapes should have been compiled
    sigs = {k[2] for k in pred._exe._cache}
    batches = {dict((n, s) for n, s, _ in sig)["x"][0] for sig in sigs}
    assert batches <= {4, 16}, batches
    with pytest.raises(Exception, match="largest configured bucket"):
        pred.run([PaddleTensor(rng.randn(32, 8).astype(np.float32), "x")])


def test_optim_cache_dir_persists_compiles(tmp_path):
    model_dir, feed, ref = _save_model(tmp_path)
    cache = tmp_path / "xla_cache"
    cfg = AnalysisConfig(model_dir)
    cfg.set_optim_cache_dir(str(cache))
    pred = create_paddle_predictor(cfg)
    (out,) = pred.run([PaddleTensor(feed, "x")])
    np.testing.assert_allclose(out.as_ndarray(), ref, rtol=1e-5, atol=1e-6)
    assert cache.exists() and any(cache.iterdir()), (
        "persistent compilation cache produced no entries"
    )


def test_aot_serialize_and_reload(tmp_path):
    """Serialize in this process; reload + serve in a FRESH process (the
    deployment shape: the serving process never invokes XLA compilation).
    XLA:CPU registers compiled-function names process-globally, so
    deserializing into the compiling process is not the supported path —
    cross-process is."""
    import subprocess
    import sys

    model_dir, feed, ref = _save_model(tmp_path)
    cfg = AnalysisConfig(model_dir)
    pred = create_paddle_predictor(cfg)
    aot = str(tmp_path / "model.aotexe")
    pred.save_executable(aot, [PaddleTensor(feed, "x")])
    assert os.path.getsize(aot) > 0

    feed_file = str(tmp_path / "feed.npy")
    np.save(feed_file, feed)
    script = (
        "import os; os.environ.pop('XLA_FLAGS', None)\n"
        "import numpy as np\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "from paddle_tpu.inference import (AnalysisConfig, PaddleTensor,\n"
        "                                  create_paddle_predictor)\n"
        f"cfg = AnalysisConfig({model_dir!r})\n"
        f"cfg.set_aot_executable_path({aot!r})\n"
        "pred = create_paddle_predictor(cfg)\n"
        f"feed = np.load({feed_file!r})\n"
        "(out,) = pred.run([PaddleTensor(feed, 'x')])\n"
        "(out2,) = pred.run([PaddleTensor(feed, 'x')])\n"
        "assert np.allclose(out.as_ndarray(), out2.as_ndarray())\n"
        f"np.save({str(tmp_path / 'out.npy')!r}, out.as_ndarray())\n"
        "print('AOT_OK')\n"
    )
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # serialized for 1 device, not the 8-dev mesh
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True, env=env,
                          timeout=300)
    assert proc.returncode == 0 and "AOT_OK" in proc.stdout, (
        proc.stdout + proc.stderr
    )
    got = np.load(str(tmp_path / "out.npy"))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_aot_signature_mismatch_raises(tmp_path):
    from paddle_tpu import errors

    model_dir, feed, ref = _save_model(tmp_path)
    cfg = AnalysisConfig(model_dir)
    pred = create_paddle_predictor(cfg)
    aot = str(tmp_path / "model.aotexe")
    pred.save_executable(aot, [PaddleTensor(feed, "x")])
    with pytest.raises(errors.InvalidArgumentError, match="was built for"):
        pred._exe.load_executable(
            aot, pred._program,
            feed={"x": np.zeros((2, 8), np.float32)},
            fetch_list=pred._fetch_vars, scope=pred._scope,
        )


def test_run_zero_copy_returns_predictor_owned_buffers(tmp_path):
    model_dir, feed, ref = _save_model(tmp_path)
    pred = create_paddle_predictor(AnalysisConfig(model_dir))
    names, arrays = pred.run_zero_copy([PaddleTensor(feed, "x")])
    assert names == pred.get_output_names()
    np.testing.assert_allclose(arrays[0], ref, rtol=1e-5, atol=1e-6)
    # buffers are kept alive on the predictor (C API reads them in place)
    assert pred._last_outputs is not None
    assert pred._last_outputs[0].ctypes.data == arrays[0].ctypes.data
