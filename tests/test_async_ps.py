"""Async / Geo PS modes (VERDICT r2 item 5): the host-side async update
engine (fleet/communicator.py) trains DeepFM to within tolerance of the
sync path; geo delta-sync converges single-process (the 2-process geo run
is tests/test_geo_launch.py over the real launcher)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.framework import unique_name
from paddle_tpu.models import DeepFMConfig, deepfm


@pytest.fixture(autouse=True)
def fresh():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


def _build(cfg, b, mode, lr=0.25):
    from paddle_tpu.fleet import parameter_server as ps

    ids = fluid.data("feat_ids", [b, cfg.num_fields], "int64")
    label = fluid.data("label", [b, 1], "float32")
    loss, _ = deepfm(ids, label, cfg)
    fleet = ps.ParameterServerFleet().init()
    strategy = ps.DistributedStrategy(mode, send_queue_size=4, merge_size=2)
    opt = fleet.distributed_optimizer(fluid.optimizer.SGD(lr), strategy)
    opt.minimize(loss)
    return fleet, loss


def _feeds(cfg, b, n=6):
    rng = np.random.RandomState(0)
    feeds = []
    for _ in range(n):
        idv = rng.randint(0, cfg.vocab_size, (b, cfg.num_fields))
        lab = (idv[:, :1] % 2 == 0).astype(np.float32)
        feeds.append({"feat_ids": idv.astype(np.int64), "label": lab})
    return feeds


def _train(mode, epochs=25):
    cfg = DeepFMConfig(vocab_size=512, num_fields=4, embed_dim=4,
                       mlp_sizes=(16,))
    b = 16
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        fleet, loss = _build(cfg, b, mode)
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        comm = fleet.init_worker(scope=scope, exe=exe, lr=0.25)
        feeds = _feeds(cfg, b)
        losses = []
        for _ in range(epochs):
            for f in feeds:
                if comm is not None and hasattr(comm, "train_step"):
                    (lv,) = comm.train_step(exe, main, f, [loss],
                                            scope=scope)
                else:
                    (lv,) = exe.run(main, feed=f, fetch_list=[loss],
                                    scope=scope)
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
        fleet.stop_worker()
    return losses


def test_async_converges_within_tolerance_of_sync():
    sync = _train("sync")
    async_ = _train("async")
    assert async_[-1] < async_[0] * 0.8, (async_[0], async_[-1])
    # bounded staleness: final loss within 25% of the sync path's
    assert async_[-1] < max(sync[-1] * 1.25, sync[-1] + 0.1), (
        sync[-1], async_[-1]
    )


def test_half_async_barrier_mode():
    losses = _train("half_async", epochs=6)
    assert losses[-1] < losses[0], (losses[0], losses[-1])


def test_async_transpile_removes_table_updates():
    from paddle_tpu.fleet.communicator import async_ps_transpile

    cfg = DeepFMConfig(vocab_size=256, num_fields=4, embed_dim=4,
                       mlp_sizes=(8,))
    b = 8
    ids = fluid.data("feat_ids", [b, cfg.num_fields], "int64")
    label = fluid.data("label", [b, 1], "float32")
    loss, _ = deepfm(ids, label, cfg)
    fluid.optimizer.SGD(0.1).minimize(loss)
    prog = fluid.default_main_program()
    tables = ["deepfm_w1", "deepfm_emb"]
    before = [op for op in prog.global_block.ops
              if op.inputs.get("Param", [None])[0] in tables]
    assert before
    grad_of = async_ps_transpile(prog, tables)
    after = [op for op in prog.global_block.ops
             if op.inputs.get("Param", [None])[0] in tables]
    assert not after
    assert set(grad_of) == set(tables)


def test_geo_single_process_sync_is_identity_rebase():
    """With one worker, geo sync must leave tables unchanged (delta summed
    over one process) and rebase the snapshot."""
    from paddle_tpu.fleet.communicator import GeoCommunicator

    cfg = DeepFMConfig(vocab_size=256, num_fields=4, embed_dim=4,
                       mlp_sizes=(8,))
    b = 8
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        ids = fluid.data("feat_ids", [b, cfg.num_fields], "int64")
        label = fluid.data("label", [b, 1], "float32")
        loss, _ = deepfm(ids, label, cfg)
        fluid.optimizer.SGD(0.05).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        comm = GeoCommunicator(["deepfm_w1", "deepfm_emb"], scope, exe,
                               update_frequency=3)
        feeds = _feeds(cfg, b, n=3)
        synced = 0
        for f in feeds * 2:
            exe.run(main, feed=f, fetch_list=[loss], scope=scope)
            before = np.asarray(scope.find_var("deepfm_emb")).copy()
            if comm.maybe_sync():
                synced += 1
                after = np.asarray(scope.find_var("deepfm_emb"))
                np.testing.assert_allclose(after, before, rtol=1e-5,
                                           atol=1e-6)
        assert synced == 2


def test_geo_two_process_delta_sync(tmp_path):
    """2 real processes (gloo CPU): divergent local training, periodic
    table-delta allreduce — after the step-15 sync both ranks hold
    IDENTICAL tables (VERDICT r2 item 5's 2-process done-bar)."""
    import json
    import os
    import subprocess
    import sys as _sys

    HERE = os.path.dirname(os.path.abspath(__file__))
    REPO = os.path.dirname(HERE)
    _sys.path.insert(0, HERE)
    try:
        from test_launch import _free_port_pair
    finally:
        _sys.path.pop(0)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [
            _sys.executable, "-m", "paddle_tpu.distributed.launch",
            "--nproc_per_node=2", f"--started_port={_free_port_pair()}",
            "--simulate_cpu",
            os.path.join(HERE, "dist_geo_worker.py"), str(tmp_path),
        ],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=540,
    )
    assert proc.returncode == 0, f"stdout:{proc.stdout}\nstderr:{proc.stderr}"
    r0 = json.load(open(tmp_path / "geo_0.json"))
    r1 = json.load(open(tmp_path / "geo_1.json"))
    # step 15 ends on a sync: tables identical across ranks
    assert abs(r0["emb_sum"] - r1["emb_sum"]) < 1e-4, (r0, r1)
    assert abs(r0["emb_absmax"] - r1["emb_absmax"]) < 1e-4
    # both ranks learned their local task
    assert r0["losses"][-1] < r0["losses"][0]
    assert r1["losses"][-1] < r1["losses"][0]


def test_dygraph_dp_two_process_matches_single(tmp_path):
    """2-process dygraph DataParallel (scale_loss + apply_collective_grads
    with make_array_from_process_local_data) reproduces the single-process
    global-batch run step for step (VERDICT r2 item 6)."""
    import json
    import os
    import subprocess
    import sys as _sys

    HERE = os.path.dirname(os.path.abspath(__file__))
    REPO = os.path.dirname(HERE)
    _sys.path.insert(0, HERE)
    try:
        from test_launch import _free_port_pair
        from dist_dygraph_worker import train as dyg_train
    finally:
        _sys.path.pop(0)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [
            _sys.executable, "-m", "paddle_tpu.distributed.launch",
            "--nproc_per_node=2", f"--started_port={_free_port_pair()}",
            "--simulate_cpu",
            os.path.join(HERE, "dist_dygraph_worker.py"), str(tmp_path),
        ],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=540,
    )
    assert proc.returncode == 0, f"stdout:{proc.stdout}\nstderr:{proc.stderr}"
    l0 = json.load(open(tmp_path / "dyg_losses_0.json"))
    l1 = json.load(open(tmp_path / "dyg_losses_1.json"))
    baseline = dyg_train(0, 1, parallel=False)
    # each rank's parameters follow the global-batch trajectory, so the
    # AVERAGE of the two ranks' local losses equals the global loss
    avg = [(a + b) / 2 for a, b in zip(l0, l1)]
    np.testing.assert_allclose(avg, baseline, rtol=2e-4)
    assert baseline[-1] < baseline[0]
