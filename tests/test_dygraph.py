"""Eager-mode tests (reference test_imperative_* suite shape): autograd
correctness vs numpy, Layer zoo, eager-vs-static equivalence, TracedLayer."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import dygraph
from paddle_tpu.dygraph import (
    BatchNorm,
    Conv2D,
    DataParallel,
    Embedding,
    LayerNorm,
    Linear,
    Sequential,
    TracedLayer,
    to_variable,
)
from paddle_tpu.optimizer import Adam, SGD


def test_basic_autograd_matches_numpy():
    with dygraph.guard():
        x = to_variable(np.array([[1.0, 2.0], [3.0, 4.0]], "float32"))
        x.stop_gradient = False
        y = x * x + 3.0 * x
        loss = dygraph.VarBase(y.value.sum())
        # route sum through an op so it lands on the tape
        from paddle_tpu.dygraph.tracer import trace_op

        loss = trace_op("reduce_sum", {"X": [y]}, {"dim": None, "keep_dim": False})
        loss.backward()
        np.testing.assert_allclose(
            x.gradient(), 2 * x.numpy() + 3.0, rtol=1e-6
        )


def test_linear_relu_chain_grads():
    with dygraph.guard():
        lin = Linear(4, 3)
        x = to_variable(np.random.RandomState(0).randn(2, 4).astype("float32"))
        x.stop_gradient = False
        from paddle_tpu.dygraph.tracer import trace_op

        h = trace_op("relu", {"X": [lin(x)]}, {})
        loss = trace_op("reduce_mean", {"X": [h]}, {"dim": None, "keep_dim": False})
        loss.backward()
        assert lin.weight.gradient() is not None
        assert lin.bias.gradient() is not None
        assert lin.weight.gradient().shape == (4, 3)


def test_mnist_style_training_loss_drops():
    rng = np.random.RandomState(0)
    xs = rng.randn(16, 1, 8, 8).astype("float32")
    ys = rng.randint(0, 10, (16, 1)).astype("int64")
    with dygraph.guard():
        from paddle_tpu.dygraph.tracer import trace_op, trace_op_multi

        class Net(dygraph.Layer):
            def __init__(self):
                super().__init__()
                self.conv = Conv2D(1, 8, 3, padding=1)
                self.bn = BatchNorm(8)
                self.fc = Linear(8 * 8 * 8, 10)

            def forward(self, x):
                h = self.conv(x)
                h = self.bn(h)
                h = trace_op("relu", {"X": [h]}, {})
                h = trace_op(
                    "reshape2", {"X": [h]}, {"shape": [-1, 8 * 8 * 8]}
                )
                return self.fc(h)

        net = Net()
        opt = Adam(1e-2, parameter_list=net.parameters())
        losses = []
        for step in range(5):
            x, y = to_variable(xs), to_variable(ys)
            logits = net(x)
            loss_full = trace_op_multi(
                "softmax_with_cross_entropy",
                {"Logits": [logits], "Label": [y]},
                {},
            )["Loss"][0]
            loss = trace_op(
                "reduce_mean", {"X": [loss_full]}, {"dim": None, "keep_dim": False}
            )
            loss.backward()
            opt.minimize(loss, parameter_list=net.parameters())
            net.clear_gradients()
            losses.append(float(loss.numpy().reshape(-1)[0]))
        assert losses[-1] < losses[0]


def test_layer_state_dict_roundtrip():
    with dygraph.guard():
        net = Sequential(Linear(4, 8), Linear(8, 2))
        sd = net.state_dict()
        assert len(sd) == 4  # 2 weights + 2 biases
        net2 = Sequential(Linear(4, 8), Linear(8, 2))
        net2.set_dict(sd)
        for (k1, v1), (k2, v2) in zip(
            sorted(net.state_dict().items()), sorted(net2.state_dict().items())
        ):
            np.testing.assert_array_equal(v1, v2)


def test_embedding_layernorm_shapes():
    with dygraph.guard():
        emb = Embedding([50, 16])
        ln = LayerNorm(16)
        ids = to_variable(np.array([[1, 2, 3]], "int32"))
        out = ln(emb(ids))
        assert out.shape == (1, 3, 16)


def test_save_load_dygraph(tmp_path):
    with dygraph.guard():
        net = Linear(4, 2)
        path = str(tmp_path / "m")
        dygraph.save_dygraph(net.state_dict(), path)
        params, opt = dygraph.load_dygraph(path)
        net2 = Linear(4, 2)
        net2.set_dict(params)
        np.testing.assert_array_equal(
            net.weight.numpy(), net2.weight.numpy()
        )


def test_traced_layer_matches_eager():
    with dygraph.guard():
        net = Sequential(Linear(4, 8), Linear(8, 2))
        x = to_variable(np.random.RandomState(0).randn(3, 4).astype("float32"))
        eager_out = net(x)
        outs, traced = TracedLayer.trace(net, [x])
        np.testing.assert_allclose(
            eager_out.numpy(), outs[0].numpy(), rtol=1e-6
        )
        again = traced([x])
        np.testing.assert_allclose(eager_out.numpy(), again[0].numpy(), rtol=1e-6)


def test_data_parallel_single_process_identity():
    with dygraph.guard():
        net = DataParallel(Linear(4, 2))
        x = to_variable(np.ones((2, 4), "float32"))
        from paddle_tpu.dygraph.tracer import trace_op

        loss = trace_op(
            "reduce_mean", {"X": [net(x)]}, {"dim": None, "keep_dim": False}
        )
        scaled = net.scale_loss(loss)
        scaled.backward()
        net.apply_collective_grads()  # nranks==1: no-op
        assert net._layers.weight.gradient() is not None


def test_eager_matches_static_linear():
    """Same weights, same input -> same loss in both modes."""
    rng = np.random.RandomState(3)
    w = rng.randn(4, 2).astype("float32")
    b = rng.randn(2).astype("float32")
    x_np = rng.randn(5, 4).astype("float32")

    # static
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        from paddle_tpu import layers
        from paddle_tpu.initializer import NumpyArrayInitializer
        from paddle_tpu.param_attr import ParamAttr

        xv = fluid.data("x", [5, 4], "float32")
        out = layers.fc(
            xv, 2,
            param_attr=ParamAttr(name="w0", initializer=NumpyArrayInitializer(w)),
            bias_attr=ParamAttr(name="b0", initializer=NumpyArrayInitializer(b)),
        )
        loss = layers.reduce_mean(out)
    exe = fluid.Executor()
    scope = fluid.framework.scope.Scope()
    exe.run(startup, scope=scope)
    (static_loss,) = exe.run(main, feed={"x": x_np}, fetch_list=[loss], scope=scope)

    # eager
    with dygraph.guard():
        import jax.numpy as jnp

        lin = Linear(4, 2)
        lin.weight.set_value(jnp.asarray(w))
        lin.bias.set_value(jnp.asarray(b))
        from paddle_tpu.dygraph.tracer import trace_op

        e_loss = trace_op(
            "reduce_mean", {"X": [lin(to_variable(x_np))]},
            {"dim": None, "keep_dim": False},
        )
    np.testing.assert_allclose(
        np.asarray(static_loss).reshape(-1),
        e_loss.numpy().reshape(-1),
        rtol=1e-5,
    )


def test_explicit_seed_dropout_distinct_per_occurrence():
    """ADVICE r4 (medium): the jit-cached tracer pinned __uid__=0, so two
    explicit-seed dropouts in one step drew the IDENTICAL mask and diverged
    from the uncached path. With an explicit seed the real uid must stay in
    the trace (and in the cache key) so occurrences get distinct streams."""

    def run_step(force_uncached=False):
        with dygraph.guard():
            from paddle_tpu.dygraph import tracer as tr_mod
            from paddle_tpu.dygraph.tracer import trace_op_multi

            tr = tr_mod._current()
            if force_uncached:
                tr._cache_key = lambda *a, **k: None
            x = to_variable(np.ones((64, 64), "float32"))
            attrs = {"dropout_prob": 0.5, "seed": 7,
                     "dropout_implementation": "upscale_in_train"}
            m1 = trace_op_multi("dropout", {"X": [x]}, dict(attrs))
            m2 = trace_op_multi("dropout", {"X": [x]}, dict(attrs))
            return (np.asarray(m1["Mask"][0].value),
                    np.asarray(m2["Mask"][0].value))

    a1, a2 = run_step()
    # distinct masks for distinct occurrences even with a shared seed
    assert not np.array_equal(a1, a2)
    # deterministic across steps (explicit seed semantics preserved)
    b1, b2 = run_step()
    np.testing.assert_array_equal(a1, b1)
    np.testing.assert_array_equal(a2, b2)
    # cached path matches the uncached fallback stream exactly
    u1, u2 = run_step(force_uncached=True)
    np.testing.assert_array_equal(a1, u1)
    np.testing.assert_array_equal(a2, u2)
