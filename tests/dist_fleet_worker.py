"""Worker script for the multi-process fleet DP test (launched by
paddle_tpu.distributed.launch; reference pattern: dist_mnist.py +
TestDistRunnerBase, tests/unittests/test_dist_base.py:62).

Trains fit-a-line with fleet collective DP; rank-dependent data slices;
writes per-step (globally averaged) losses to <out_dir>/losses_<rank>.json.
"""

import json
import os
import sys

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.fleet import collective as fleet_mod


def make_feed(rank, step, b_local):
    """Deterministic slice: global batch = concat over ranks."""
    rng = np.random.RandomState(100 + step)
    xg = rng.randn(2 * b_local, 4).astype(np.float32)
    w = np.arange(4, dtype=np.float32).reshape(4, 1)
    yg = xg @ w
    lo = rank * b_local
    return {"x": xg[lo:lo + b_local], "y": yg[lo:lo + b_local]}


def main():
    out_dir = sys.argv[1]
    steps, b_local = 5, 8

    fleet = fleet_mod.fleet
    fleet.init()
    rank = fleet.worker_index()

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 17
    with fluid.program_guard(main_prog, startup):
        x = fluid.data("x", [b_local, 4])
        y = fluid.data("y", [b_local, 1])
        pred = layers.fc(x, 1, param_attr=fluid.ParamAttr(name="w"),
                         bias_attr=fluid.ParamAttr(name="b"))
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = fleet.distributed_optimizer(fluid.optimizer.SGD(0.1))
        opt.minimize(loss)

    exe = fluid.Executor()
    exe.run(startup)
    losses = []
    for step in range(steps):
        (lv,) = exe.run(
            main_prog, feed=make_feed(rank, step, b_local), fetch_list=[loss]
        )
        losses.append(float(np.asarray(lv).reshape(-1)[0]))

    with open(os.path.join(out_dir, f"losses_{rank}.json"), "w") as f:
        json.dump(losses, f)


if __name__ == "__main__":
    main()
