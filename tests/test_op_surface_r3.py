"""Round-3 op-surface tests: the long-tail emitters added to close the
reference coverage gap (VERDICT r2 item 1). Each op is exercised directly
through its registered emitter; numeric checks mirror the reference
kernels (paddle/fluid/operators/, per-op files cited in the op modules).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu  # noqa: F401  (registers emitters)
from paddle_tpu.framework.registry import EmitContext, get_op_def


class _FakeOp:
    def __init__(self, type, attrs):
        self.type, self.attrs, self.uid = type, attrs, 7

    def attr(self, k, d=None):
        return self.attrs.get(k, d)


@pytest.fixture
def run():
    ctx = EmitContext()
    ctx.key_for = lambda uid, t: jax.random.key(uid)

    def _run(t, attrs, ins):
        return get_op_def(t).emit(ctx, _FakeOp(t, attrs), ins)

    return _run


@pytest.fixture
def rng():
    return np.random.RandomState(0)


# --- tensor surface -------------------------------------------------------


def test_v1_shape_aliases(run):
    x = jnp.arange(12.0).reshape(3, 4)
    assert run("reshape", {"shape": [4, 3]}, {"X": [x]})["Out"][0].shape == (4, 3)
    assert run("transpose", {"axis": [1, 0]}, {"X": [x]})["Out"][0].shape == (4, 3)
    assert run("squeeze", {"axes": []}, {"X": [x[None]]})["Out"][0].shape == (3, 4)
    assert run("unsqueeze", {"axes": [0]}, {"X": [x]})["Out"][0].shape == (1, 3, 4)
    o = run("unbind", {"axis": 0}, {"X": [x]})["Out"]
    assert len(o) == 3 and o[0].shape == (4,)
    o = run("reverse", {"axis": [0]}, {"X": [x]})["Out"][0]
    assert float(o[0, 0]) == 8.0


def test_crop_diag_fill(run):
    x = jnp.arange(12.0).reshape(3, 4)
    o = run("crop", {"shape": [2, 2], "offsets": [1, 1]}, {"X": [x]})["Out"][0]
    assert float(o[0, 0]) == 5.0
    o = run("crop_tensor", {"shape": [2, 2], "offsets": [0, 1]}, {"X": [x]})["Out"][0]
    assert float(o[0, 0]) == 1.0
    assert run("diag", {}, {"Diagonal": [jnp.ones(3)]})["Out"][0].shape == (3, 3)
    o = run("fill", {"value": [1.0, 2.0, 3.0, 4.0], "shape": [2, 2],
                     "dtype": "float32"}, {})["Out"][0]
    assert float(o[1, 1]) == 4.0
    assert not bool(run("is_empty", {}, {"X": [x]})["Out"][0])


def test_frobenius_partial_unfold(run):
    x = jnp.arange(12.0).reshape(3, 4)
    o = run("frobenius_norm", {"reduce_all": True}, {"X": [x]})["Out"][0]
    assert np.allclose(float(o), np.linalg.norm(np.arange(12.0).reshape(3, 4)))
    xs = [jnp.ones((2, 5)), 2 * jnp.ones((2, 5))]
    o = run("partial_concat", {"start_index": 1, "length": 2}, {"X": xs})["Out"][0]
    assert o.shape == (2, 4)
    o = run("partial_sum", {"start_index": 1, "length": 2}, {"X": xs})["Out"][0]
    assert float(o[0, 0]) == 3.0
    xi = jnp.arange(16.0).reshape(1, 1, 4, 4)
    o = run("unfold", {"kernel_sizes": [2, 2], "strides": [1, 1],
                       "paddings": [0, 0, 0, 0], "dilations": [1, 1]},
            {"X": [xi]})["Y"][0]
    assert o.shape == (1, 4, 9)
    assert np.allclose(np.asarray(o[0, :, 0]), [0, 1, 4, 5])


def test_unique_static_size_contract(run):
    u = jnp.array([3, 1, 3, 2])
    o = run("unique", {}, {"X": [u]})
    out, idx = np.asarray(o["Out"][0]), np.asarray(o["Index"][0])
    assert np.allclose(out[idx], np.asarray(u))
    o = run("unique_with_counts", {}, {"X": [u]})
    pos = int(np.argmax(np.asarray(o["Out"][0]) == 3))
    assert int(np.asarray(o["Count"][0])[pos]) == 2


def test_scatter_nd_add_hash_conv_shift(run):
    o = run("scatter_nd_add", {}, {
        "X": [jnp.zeros((3, 3))],
        "Index": [jnp.array([[0, 0], [1, 2]])],
        "Updates": [jnp.array([5.0, 7.0])],
    })["Out"][0]
    assert float(o[0, 0]) == 5.0 and float(o[1, 2]) == 7.0
    ids = jnp.array([[1], [2], [3]], dtype=jnp.int32)
    o = run("hash", {"num_hash": 2, "mod_by": 1000}, {"X": [ids]})["Out"][0]
    assert o.shape == (3, 2, 1) and int(jnp.max(o)) < 1000
    o = run("conv_shift", {}, {"X": [jnp.ones((2, 8))], "Y": [jnp.ones((2, 3))]})["Out"][0]
    assert np.allclose(np.asarray(o), 3.0)


def test_batch_size_like_rng_ops(run):
    x = jnp.zeros((3, 4))
    o = run("uniform_random_batch_size_like",
            {"shape": [0, 5], "dtype": "float32"}, {"Input": [x]})["Out"][0]
    assert o.shape == (3, 5)
    o = run("gaussian_random_batch_size_like",
            {"shape": [0, 5], "dtype": "float32"}, {"Input": [x]})["Out"][0]
    assert o.shape == (3, 5)
    o = run("sampling_id", {}, {"X": [jnp.ones((4, 6)) / 6.0]})["Out"][0]
    assert o.shape == (4,)


# --- nn surface -----------------------------------------------------------


def test_prelu_modes(run, rng):
    x = jnp.asarray(rng.randn(2, 3, 4, 4).astype(np.float32))
    a = jnp.asarray([0.1, 0.2, 0.3])
    o = run("prelu", {"mode": "channel"}, {"X": [x], "Alpha": [a]})["Out"][0]
    ref = np.where(np.asarray(x) > 0, np.asarray(x),
                   np.asarray(x) * np.array([0.1, 0.2, 0.3]).reshape(1, 3, 1, 1))
    assert np.allclose(np.asarray(o), ref, atol=1e-6)


def test_data_norm_stats(run, rng):
    xd = jnp.asarray(rng.randn(4, 6).astype(np.float32))
    o = run("data_norm", {}, {
        "X": [xd], "BatchSize": [jnp.full((6,), 10.0)],
        "BatchSum": [jnp.full((6,), 5.0)],
        "BatchSquareSum": [jnp.full((6,), 40.0)],
    })
    assert np.allclose(np.asarray(o["Means"][0]), 0.5)
    assert np.allclose(np.asarray(o["Scales"][0]), 0.5)


def test_spectral_norm_unit_sigma(run, rng):
    w = jnp.asarray(rng.randn(4, 5).astype(np.float32))
    o = run("spectral_norm", {"dim": 0, "power_iters": 30}, {
        "Weight": [w],
        "U": [jnp.asarray(rng.randn(4).astype(np.float32))],
        "V": [jnp.asarray(rng.randn(5).astype(np.float32))],
    })["Out"][0]
    top = np.linalg.svd(np.asarray(o), compute_uv=False)[0]
    assert abs(top - 1.0) < 1e-3


def test_pool3d_family(run, rng):
    x3 = jnp.asarray(rng.randn(1, 2, 4, 4, 4).astype(np.float32))
    o = run("pool3d", {"ksize": [2, 2, 2], "strides": [2, 2, 2],
                       "pooling_type": "avg"}, {"X": [x3]})["Out"][0]
    assert o.shape == (1, 2, 2, 2, 2)
    o = run("max_pool3d_with_index", {"ksize": [2, 2, 2], "strides": [2, 2, 2]},
            {"X": [x3]})
    xf = np.asarray(x3).reshape(1, 2, -1)
    idx = np.asarray(o["Mask"][0]).reshape(1, 2, -1)
    assert np.allclose(np.take_along_axis(xf, idx, axis=2),
                       np.asarray(o["Out"][0]).reshape(1, 2, -1))


def test_unpool_roundtrip(run, rng):
    x2 = jnp.asarray(rng.randn(1, 2, 4, 4).astype(np.float32))
    p = run("max_pool2d_with_index", {"ksize": [2, 2], "strides": [2, 2]},
            {"X": [x2]})
    up = run("unpool", {"ksize": [2, 2], "strides": [2, 2],
                        "unpooled_height": 4, "unpooled_width": 4},
             {"X": [p["Out"][0]], "Indices": [p["Mask"][0]]})["Out"][0]
    # unpooled map contains each pooled max at its argmax position
    assert np.allclose(np.asarray(up).sum(), np.asarray(p["Out"][0]).sum())


def test_spp_non_divisible_dims(run):
    # 5x5 map with pyramid_height=3 (4x4 bins): adaptive bins never empty
    x = jnp.ones((1, 2, 5, 5))
    for ptype in ("max", "avg"):
        o = run("spp", {"pyramid_height": 3, "pooling_type": ptype},
                {"X": [x]})["Out"][0]
        assert o.shape == (1, 2 * (1 + 4 + 16))
        assert np.all(np.isfinite(np.asarray(o)))
        assert np.allclose(np.asarray(o), 1.0)


def test_similarity_focus_greedy_one_per_row_col(run):
    # slice [[3,2],[1,0]]: greedy tags (0,0) then (1,1) — not row|col maxima
    x = jnp.asarray(np.array([[[[3.0, 2.0], [1.0, 0.0]]]], np.float32))
    o = run("similarity_focus", {"axis": 1, "indexes": [0]}, {"X": [x]})["Out"][0]
    assert np.allclose(np.asarray(o)[0, 0], [[1.0, 0.0], [0.0, 1.0]])


def test_tdm_child_trailing_dim(run):
    info = np.zeros((7, 5), np.int32)
    info[1] = [0, 1, 0, 2, 3]
    info[2] = [10, 2, 1, 0, 0]
    info[3] = [11, 2, 1, 0, 0]
    o = run("tdm_child", {"child_nums": 2}, {
        "X": [jnp.asarray([[1, 2, 3], [1, 1, 1]])],
        "TreeInfo": [jnp.asarray(info)],
    })
    assert o["Child"][0].shape == (2, 6)


def test_interp_modes(run, rng):
    x1d = jnp.asarray(rng.randn(2, 3, 8).astype(np.float32))
    assert run("linear_interp", {"out_w": 16}, {"X": [x1d]})["Out"][0].shape == (2, 3, 16)
    x = jnp.asarray(rng.randn(2, 3, 8, 8).astype(np.float32))
    assert run("bicubic_interp", {"out_h": 16, "out_w": 16}, {"X": [x]})["Out"][0].shape == (2, 3, 16, 16)
    x5 = jnp.asarray(rng.randn(1, 2, 4, 4, 4).astype(np.float32))
    assert run("trilinear_interp", {"out_d": 8, "out_h": 8, "out_w": 8},
               {"X": [x5]})["Out"][0].shape == (1, 2, 8, 8, 8)


def test_affine_grid_identity(run):
    theta = jnp.asarray(np.tile(np.array([[1., 0., 0.], [0., 1., 0.]],
                                         np.float32), (2, 1, 1)))
    g = run("affine_grid", {"output_shape": [2, 1, 4, 5]},
            {"Theta": [theta], "OutputShape": [None]})["Output"][0]
    assert g.shape == (2, 4, 5, 2)
    assert np.allclose(np.asarray(g)[0, 0, 0], [-1, -1])
    assert np.allclose(np.asarray(g)[0, -1, -1], [1, 1])


def test_deformable_conv_zero_offset_matches_conv2d(run, rng):
    xc = jnp.asarray(rng.randn(1, 4, 6, 6).astype(np.float32))
    wc = jnp.asarray(rng.randn(8, 4, 3, 3).astype(np.float32))
    off = jnp.zeros((1, 2 * 9, 6, 6), jnp.float32)
    mask = jnp.ones((1, 9, 6, 6), jnp.float32)
    attrs = {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1],
             "groups": 1, "deformable_groups": 1}
    o = run("deformable_conv", attrs,
            {"Input": [xc], "Offset": [off], "Mask": [mask], "Filter": [wc]})["Output"][0]
    ref = run("conv2d", attrs, {"Input": [xc], "Filter": [wc]})["Output"][0]
    assert np.allclose(np.asarray(o), np.asarray(ref), atol=1e-4)


def test_psroi_prroi_shapes(run, rng):
    xp = jnp.asarray(rng.randn(1, 8, 8, 8).astype(np.float32))
    rois = jnp.asarray(np.array([[0., 0., 4., 4.], [2., 2., 6., 6.]], np.float32))
    o = run("psroi_pool", {"pooled_height": 2, "pooled_width": 2,
                           "output_channels": 2, "spatial_scale": 1.0},
            {"X": [xp], "ROIs": [rois], "RoisNum": [jnp.asarray([2])]})["Out"][0]
    assert o.shape == (2, 2, 2, 2)
    xc = jnp.asarray(rng.randn(1, 4, 8, 8).astype(np.float32))
    o = run("prroi_pool", {"pooled_height": 2, "pooled_width": 2,
                           "spatial_scale": 1.0},
            {"X": [xc], "ROIs": [rois], "BatchRoINums": [jnp.asarray([2])]})["Out"][0]
    assert o.shape == (2, 4, 2, 2)


def test_lstmp_attention_lstm(run, rng):
    xl = jnp.asarray(rng.randn(2, 5, 4).astype(np.float32))
    o = run("lstmp", {}, {
        "X": [xl],
        "WIH": [jnp.asarray(rng.randn(24, 4).astype(np.float32))],
        "WHH": [jnp.asarray(rng.randn(24, 3).astype(np.float32))],
        "ProjWeight": [jnp.asarray(rng.randn(6, 3).astype(np.float32))],
        "Bias": [None], "H0": [None], "C0": [None], "SeqLen": [None],
    })
    assert o["Projection"][0].shape == (2, 5, 3)
    o = run("attention_lstm", {}, {
        "X": [xl], "C0": [jnp.zeros((2, 6))], "H0": [None],
        "AttentionWeight": [jnp.asarray(rng.randn(10, 1).astype(np.float32))],
        "AttentionBias": [None], "AttentionScalar": [None],
        "AttentionScalarBias": [None],
        "LSTMWeight": [jnp.asarray(rng.randn(10, 24).astype(np.float32))],
        "LSTMBias": [None], "SeqLen": [None],
    })
    assert o["Hidden"][0].shape == (2, 5, 6)


# --- losses ---------------------------------------------------------------


def test_nce_hsigmoid_finite(run, rng):
    x = jnp.asarray(rng.randn(4, 8).astype(np.float32))
    o = run("nce", {"num_total_classes": 20, "num_neg_samples": 5}, {
        "Input": [x], "Label": [jnp.asarray(rng.randint(0, 20, (4, 1)))],
        "Weight": [jnp.asarray(rng.randn(20, 8).astype(np.float32))],
        "Bias": [jnp.asarray(rng.randn(20).astype(np.float32))],
        "SampleWeight": [None],
    })
    assert np.all(np.isfinite(np.asarray(o["Cost"][0])))
    o = run("hierarchical_sigmoid", {"num_classes": 10}, {
        "X": [x], "Label": [jnp.asarray(rng.randint(0, 10, (4,)))],
        "W": [jnp.asarray(rng.randn(9, 8).astype(np.float32))],
        "Bias": [jnp.asarray(rng.randn(9).astype(np.float32))],
        "PathTable": [None], "PathCode": [None],
    })
    assert np.all(np.asarray(o["Out"][0]) > 0)


def test_teacher_student_exact(run):
    xs = jnp.asarray(np.array([[0.5], [-0.5]], np.float32))
    o = run("teacher_student_sigmoid_loss", {}, {
        "X": [xs], "Label": [jnp.asarray(np.array([[-2.0], [-1.0]], np.float32))],
    })
    y = np.asarray(o["Y"][0]).ravel()
    assert np.allclose(y, [0.5 + np.log1p(np.exp(-0.5)),
                           0.5 + np.log1p(np.exp(-0.5))], atol=1e-5)


def test_warpctc_uniform_exact(run):
    # B=1, T=3, C=3, label=[1], uniform logits: 6 valid paths of prob (1/3)^3
    o = run("warpctc", {"blank": 0}, {
        "Logits": [jnp.zeros((1, 3, 3))], "Label": [jnp.asarray([[1]])],
        "LogitsLength": [jnp.asarray([3])], "LabelLength": [jnp.asarray([1])],
    })
    assert abs(float(np.asarray(o["Loss"][0])[0, 0]) + np.log(6 * (1 / 3) ** 3)) < 1e-3


def test_ctc_align_and_edit_distance(run):
    o = run("ctc_align", {"blank": 0}, {
        "Input": [jnp.asarray(np.array([[0, 1, 1, 0, 2, 2, 0]], np.int32))],
        "InputLength": [None],
    })
    out = np.asarray(o["Output"][0])[0]
    assert list(out[:2]) == [1, 2] and np.all(out[2:] == -1)

    def enc(s, L):
        return [ord(c) for c in s] + [0] * (L - len(s))

    o = run("edit_distance", {"normalized": False}, {
        "Hyps": [jnp.asarray([enc("kitten", 7)], jnp.int32)],
        "Refs": [jnp.asarray([enc("sitting", 7)], jnp.int32)],
        "HypsLength": [jnp.asarray([6])], "RefsLength": [jnp.asarray([7])],
    })
    assert float(np.asarray(o["Out"][0])[0, 0]) == 3.0


def test_chunk_eval_iob(run):
    lab = jnp.asarray([[0, 1, 4, 2]], jnp.int32)
    o = run("chunk_eval", {"chunk_scheme": "IOB", "num_chunk_types": 3},
            {"Inference": [lab], "Label": [lab], "SeqLength": [jnp.asarray([4])]})
    assert float(np.asarray(o["F1-Score"][0])) == 1.0
    o = run("chunk_eval", {"chunk_scheme": "IOB", "num_chunk_types": 3},
            {"Inference": [jnp.asarray([[0, 0, 4, 2]], jnp.int32)],
             "Label": [lab], "SeqLength": [jnp.asarray([4])]})
    assert float(np.asarray(o["Precision"][0])) < 1.0


def test_chunk_eval_outside_labels_not_chunks(run):
    # all-O sequence (label == num_chunk_types * 2): zero chunks
    o = run("chunk_eval", {"chunk_scheme": "IOB", "num_chunk_types": 1},
            {"Inference": [jnp.asarray([[2, 2, 2, 2]], jnp.int32)],
             "Label": [jnp.asarray([[2, 2, 2, 2]], jnp.int32)],
             "SeqLength": [jnp.asarray([4])]})
    assert int(np.asarray(o["NumLabelChunks"][0])) == 0
    assert float(np.asarray(o["F1-Score"][0])) == 0.0
    # B-x O B-x: two chunks split by the O
    o = run("chunk_eval", {"chunk_scheme": "IOB", "num_chunk_types": 1},
            {"Inference": [jnp.asarray([[0, 2, 0]], jnp.int32)],
             "Label": [jnp.asarray([[0, 2, 0]], jnp.int32)],
             "SeqLength": [jnp.asarray([3])]})
    assert int(np.asarray(o["NumLabelChunks"][0])) == 2
    assert float(np.asarray(o["F1-Score"][0])) == 1.0


def test_detection_map_accumulation(run):
    det = jnp.asarray(np.array([[0, 0.9, 0, 0, 10, 10],
                                [0, 0.8, 50, 50, 60, 60]], np.float32))
    gt = jnp.asarray(np.array([[0, 0, 0, 10, 10]], np.float32))
    attrs = {"class_num": 1, "overlap_threshold": 0.5}
    none_ins = {"HasState": [None], "PosCount": [None],
                "TruePos": [None], "FalsePos": [None]}
    o1 = run("detection_map", attrs, {"DetectRes": [det], "Label": [gt], **none_ins})
    # feed accumulators back: same batch again -> same mAP, doubled counts
    o2 = run("detection_map", attrs, {
        "DetectRes": [det], "Label": [gt],
        "HasState": [jnp.asarray([1])],
        "PosCount": [o1["AccumPosCount"][0]],
        "TruePos": [o1["AccumTruePos"][0]],
        "FalsePos": [o1["AccumFalsePos"][0]],
    })
    assert int(np.asarray(o2["AccumPosCount"][0])[0, 0]) == 2
    assert abs(float(np.asarray(o2["MAP"][0])[0])
               - float(np.asarray(o1["MAP"][0])[0])) < 1e-5


def test_precision_recall_micro(run):
    o = run("precision_recall", {"class_number": 3}, {
        "MaxProbs": [jnp.ones((6, 1))],
        "Indices": [jnp.asarray([[0], [1], [2], [0], [1], [2]])],
        "Labels": [jnp.asarray([[0], [1], [1], [0], [2], [2]])],
        "Weights": [None], "StatesInfo": [None],
    })
    bm = np.asarray(o["BatchMetrics"][0])
    assert abs(bm[3] - 4 / 6) < 1e-6  # micro precision


def test_positive_negative_pair(run):
    o = run("positive_negative_pair", {}, {
        "Score": [jnp.asarray([0.9, 0.1, 0.8, 0.2])],
        "Label": [jnp.asarray([1.0, 0.0, 1.0, 0.0])],
        "QueryID": [jnp.asarray([1, 1, 2, 2])],
        "Weight": [None], "AccumulatePositivePair": [None],
        "AccumulateNegativePair": [None], "AccumulateNeutralPair": [None],
    })
    assert float(np.asarray(o["PositivePair"][0])[0]) == 2.0


def test_detection_map_perfect(run):
    det = jnp.asarray(np.array([[0, 0.9, 0, 0, 10, 10],
                                [1, 0.8, 20, 20, 30, 30]], np.float32))
    gt = jnp.asarray(np.array([[0, 0, 0, 10, 10],
                               [1, 20, 20, 30, 30]], np.float32))
    o = run("detection_map", {"class_num": 2, "overlap_threshold": 0.5}, {
        "DetectRes": [det], "Label": [gt], "HasState": [None],
        "PosCount": [None], "TruePos": [None], "FalsePos": [None],
    })
    assert abs(float(np.asarray(o["MAP"][0])[0]) - 1.0) < 1e-5


# --- quantization ---------------------------------------------------------


def test_fake_quant_family(run, rng):
    x = jnp.asarray(rng.randn(4, 8).astype(np.float32))
    o = run("fake_quantize_abs_max", {"bit_length": 8}, {"X": [x]})
    assert abs(float(np.asarray(o["OutScale"][0])[0])
               - np.abs(np.asarray(x)).max()) < 1e-5
    o = run("fake_channel_wise_quantize_abs_max",
            {"bit_length": 8, "quant_axis": 0}, {"X": [x]})
    assert o["OutScale"][0].shape == (4,)
    o = run("fake_quantize_range_abs_max", {"bit_length": 8},
            {"X": [x], "InScale": [jnp.asarray([100.0])], "Iter": [None]})
    assert float(np.asarray(o["OutScale"][0])[0]) >= 100.0
    o = run("fake_dequantize_max_abs", {"max_range": 127.0},
            {"X": [jnp.asarray([[127.0]])], "Scale": [jnp.asarray([2.0])]})
    assert abs(float(np.asarray(o["Out"][0])[0, 0]) - 2.0) < 1e-6


def test_int8_pipeline(run):
    o = run("quantize", {"Scale": 127.0}, {"Input": [jnp.asarray([[0.5]])]})
    assert int(np.asarray(o["Output"][0])[0, 0]) == 64
    o = run("dequantize", {"Scale": 127.0},
            {"Input": [jnp.asarray([[64]], np.int8)]})
    assert abs(float(np.asarray(o["Output"][0])[0, 0]) - 64 / 127) < 1e-6
    o = run("dequantize_log", {}, {
        "X": [jnp.asarray([[5], [-4]], np.int8)], "Dict": [jnp.arange(128.0)],
    })
    out = np.asarray(o["Out"][0]).ravel()
    assert out[0] == 5.0 and out[1] == -124.0


# --- control flow / ps / optimizer ---------------------------------------


def test_tensor_array_ops(run):
    xa = jnp.asarray([1.0, 2.0])
    arr = run("write_to_array", {"capacity": 4},
              {"X": [xa], "I": [jnp.asarray(1)], "Array": [None]})["Out"][0]
    assert arr.shape == (4, 2) and float(arr[1, 0]) == 1.0
    o = run("read_from_array", {}, {"X": [arr], "I": [jnp.asarray(1)]})
    assert np.allclose(np.asarray(o["Out"][0]), [1.0, 2.0])
    o = run("tensor_array_to_tensor", {"axis": 0, "use_stack": False}, {"X": [arr]})
    assert o["Out"][0].shape == (8,)


def test_select_ops(run):
    o = run("select_input", {}, {
        "X": [jnp.asarray([1.0]), jnp.asarray([2.0])], "Mask": [jnp.asarray(1)],
    })
    assert float(np.asarray(o["Out"][0])[0]) == 2.0
    o = run("select_output", {"num_branches": 2},
            {"X": [jnp.asarray([3.0])], "Mask": [jnp.asarray(0)]})
    assert float(np.asarray(o["Out"][0])[0]) == 3.0
    assert float(np.asarray(o["Out"][1])[0]) == 0.0


def test_proximal_ops(run):
    p = jnp.asarray([1.0, -1.0])
    g = jnp.asarray([0.5, 0.5])
    o = run("proximal_gd", {"l1": 0.1, "l2": 0.1},
            {"Param": [p], "Grad": [g], "LearningRate": [jnp.asarray([0.1])]})
    prox = np.asarray(p) - 0.1 * np.asarray(g)
    exp = np.sign(prox) * np.maximum(np.abs(prox) - 0.01, 0) / 1.01
    assert np.allclose(np.asarray(o["ParamOut"][0]), exp, atol=1e-6)


def test_average_accumulates_state_machine(run):
    s = jnp.zeros((3,))
    o = run("average_accumulates",
            {"average_window": 0.5, "max_average_window": 100,
             "min_average_window": 2},
            {"param": [jnp.ones((3,))], "in_sum_1": [s], "in_sum_2": [s],
             "in_sum_3": [s],
             "in_num_accumulates": [jnp.asarray([0], np.int64)],
             "in_old_num_accumulates": [jnp.asarray([0], np.int64)],
             "in_num_updates": [jnp.asarray([0], np.int64)]})
    assert np.allclose(np.asarray(o["out_sum_1"][0]), 1.0)
    assert int(np.asarray(o["out_num_updates"][0])[0]) == 1


def test_tdm_and_instag(run, rng):
    info = np.zeros((7, 5), np.int32)
    info[1] = [0, 1, 0, 2, 3]
    info[2] = [10, 2, 1, 0, 0]
    info[3] = [11, 2, 1, 0, 0]
    o = run("tdm_child", {"child_nums": 2},
            {"X": [jnp.asarray([[1], [2]])], "TreeInfo": [jnp.asarray(info)]})
    ch = np.asarray(o["Child"][0])
    assert list(ch[0].ravel()) == [2, 3] and list(ch[1].ravel()) == [0, 0]

    rows = jnp.asarray(rng.randn(3, 4).astype(np.float32))
    tags = jnp.asarray(np.array([[1, -1], [2, 3], [5, -1]], np.int64))
    o = run("filter_by_instag", {}, {
        "Ins": [rows], "Ins_tag": [tags],
        "Filter_tag": [jnp.asarray([2, 5], np.int64)],
    })
    assert list(np.asarray(o["LossWeight"][0]).ravel()) == [0.0, 1.0, 1.0]


def test_coverage_target_reached():
    """The checker itself is the acceptance test for VERDICT r2 item 1."""
    import subprocess
    import sys
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "check_op_surface.py")],
        capture_output=True, text=True, env={**os.environ, "JAX_PLATFORMS": "cpu"},
    ).stdout
    import re

    # r4 headline splits real emitters from documented subsumptions; the
    # acceptance bar is (a) every reference op covered one way or the
    # other, (b) a real-emitter share that keeps "covered" meaningful
    m = re.search(
        r"reference fwd ops: (\d+); (\d+) with real emitters \((\d+)%\) \+ "
        r"(\d+) documented subsumptions = (\d+) covered",
        out,
    )
    assert m, out.splitlines()[0]
    total, emitters, pct, subsumed, covered = map(int, m.groups())
    assert covered == total, out.splitlines()[0]
    assert pct >= 70, out.splitlines()[0]
