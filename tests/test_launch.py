"""Multi-process launcher + fleet DP across REAL processes.

Reference pattern: TestDistBase launches trainers as subprocesses on
localhost and asserts distributed losses match single-process losses
(tests/unittests/test_dist_base.py:506, _run_cluster_nccl2 :847).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _free_port_pair():
    """A base port with base+1 also free (the launcher binds consecutive
    ports for nproc_per_node=2). Random high ports, both bind-tested."""
    import random
    import socket

    for _ in range(128):
        base = random.randint(20000, 60000)
        try:
            with socket.socket() as a, socket.socket() as b:
                a.bind(("127.0.0.1", base))
                b.bind(("127.0.0.1", base + 1))
            return base
        except OSError:
            continue
    raise RuntimeError("no free port pair found")


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield


def _single_process_baseline(steps=5, b_local=8):
    """Same model on the full (2x) batch in one process."""
    sys.path.insert(0, HERE)
    try:
        from dist_fleet_worker import make_feed
    finally:
        sys.path.pop(0)
    b = 2 * b_local
    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 17
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main_prog, startup), \
            fluid.scope_guard(scope), unique_name.guard():
        x = fluid.data("x", [b, 4])
        y = fluid.data("y", [b, 1])
        pred = layers.fc(x, 1, param_attr=fluid.ParamAttr(name="w"),
                         bias_attr=fluid.ParamAttr(name="b"))
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        losses = []
        for step in range(steps):
            f0 = make_feed(0, step, b_local)
            f1 = make_feed(1, step, b_local)
            feed = {k: np.concatenate([f0[k], f1[k]]) for k in f0}
            (lv,) = exe.run(main_prog, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    return losses


def test_launch_two_process_fleet_dp(tmp_path):
    """2 real processes (gloo CPU collectives) match the single-process
    global-batch run step for step."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [
            sys.executable, "-m", "paddle_tpu.distributed.launch",
            "--nproc_per_node=2", f"--started_port={_free_port_pair()}",
            "--simulate_cpu",
            os.path.join(HERE, "dist_fleet_worker.py"), str(tmp_path),
        ],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=540,
    )
    assert proc.returncode == 0, f"stdout:{proc.stdout}\nstderr:{proc.stderr}"
    l0 = json.load(open(tmp_path / "losses_0.json"))
    l1 = json.load(open(tmp_path / "losses_1.json"))
    # the fetched loss is globally averaged: both ranks see the same value
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    baseline = _single_process_baseline()
    np.testing.assert_allclose(l0, baseline, rtol=2e-4)
    assert baseline[-1] < baseline[0]  # fixed w target: loss decreases


def test_launcher_aborts_pod_on_child_failure(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import sys, os\nsys.exit(3 if os.environ['PADDLE_TRAINER_ID']=='1' else 0)\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [
            sys.executable, "-m", "paddle_tpu.distributed.launch",
            "--nproc_per_node=2", f"--started_port={_free_port_pair()}",
            str(bad), "x",
        ],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode != 0
    assert "pod aborted" in proc.stderr


def test_localsgd_two_process_averaging(tmp_path):
    """LocalSGD: ranks train independently, the averaging program brings
    parameters to the cross-rank mean (VERDICT: previously untested)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [
            sys.executable, "-m", "paddle_tpu.distributed.launch",
            "--nproc_per_node=2", f"--started_port={_free_port_pair()}",
            "--simulate_cpu",
            os.path.join(HERE, "dist_localsgd_worker.py"), str(tmp_path),
        ],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=540,
    )
    assert proc.returncode == 0, f"stdout:{proc.stdout}\nstderr:{proc.stderr}"
    r0 = json.load(open(tmp_path / "localsgd_0.json"))
    r1 = json.load(open(tmp_path / "localsgd_1.json"))
    pre0, pre1 = np.asarray(r0["pre"]), np.asarray(r1["pre"])
    assert np.abs(pre0 - pre1).max() > 1e-4  # genuinely diverged
    want = (pre0 + pre1) / 2
    np.testing.assert_allclose(np.asarray(r0["post"]), want, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(r1["post"]), want, rtol=1e-5)
