"""ROI ops (reference roi_align_op.h, roi_pool_op.cc,
detection/anchor_generator_op.h, detection/box_clip_op.cc) against literal
numpy ports of the reference kernels, plus gradient flow through
roi_align."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


def _np_roi_align(x, rois, bidx, ph, pw, scale, s):
    """Literal port of roi_align_op.h with fixed sampling grid s."""
    N, C, H, W = x.shape
    R = rois.shape[0]
    out = np.zeros((R, C, ph, pw), np.float64)
    for r in range(R):
        xm, ym, xM, yM = rois[r] * scale
        rw = max(xM - xm, 1.0)
        rh = max(yM - ym, 1.0)
        bw, bh = rw / pw, rh / ph
        for c in range(C):
            for py in range(ph):
                for px in range(pw):
                    acc = 0.0
                    for iy in range(s):
                        y = ym + py * bh + (iy + 0.5) * bh / s
                        for ix in range(s):
                            xx = xm + px * bw + (ix + 0.5) * bw / s
                            if y < -1.0 or y > H or xx < -1.0 or xx > W:
                                continue
                            y_ = max(y, 0.0)
                            x_ = max(xx, 0.0)
                            yl, xl = int(y_), int(x_)
                            if yl >= H - 1:
                                yl = yh = H - 1
                                y_ = float(yl)
                            else:
                                yh = yl + 1
                            if xl >= W - 1:
                                xl = xh = W - 1
                                x_ = float(xl)
                            else:
                                xh = xl + 1
                            ly, lx = y_ - yl, x_ - xl
                            hy, hx = 1 - ly, 1 - lx
                            m = x[bidx[r], c]
                            acc += (
                                hy * hx * m[yl, xl] + hy * lx * m[yl, xh]
                                + ly * hx * m[yh, xl] + ly * lx * m[yh, xh]
                            )
                    out[r, c, py, px] = acc / (s * s)
    return out


def _np_roi_pool(x, rois, bidx, ph, pw, scale):
    N, C, H, W = x.shape
    R = rois.shape[0]
    out = np.zeros((R, C, ph, pw), np.float64)
    for r in range(R):
        # std::round semantics (half away from zero), not Python's banker's
        xm = int(np.floor(rois[r, 0] * scale + 0.5))
        ym = int(np.floor(rois[r, 1] * scale + 0.5))
        xM = int(np.floor(rois[r, 2] * scale + 0.5))
        yM = int(np.floor(rois[r, 3] * scale + 0.5))
        rh = max(yM - ym + 1, 1)
        rw = max(xM - xm + 1, 1)
        for py in range(ph):
            hs = min(max(ym + py * rh // ph, 0), H)
            he = min(max(ym + ((py + 1) * rh + ph - 1) // ph, 0), H)
            for px in range(pw):
                ws = min(max(xm + px * rw // pw, 0), W)
                we = min(max(xm + ((px + 1) * rw + pw - 1) // pw, 0), W)
                for c in range(C):
                    region = x[bidx[r], c, hs:he, ws:we]
                    out[r, c, py, px] = region.max() if region.size else 0.0
    return out


def test_roi_align_matches_reference_port():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 16, 16).astype("float32")
    rois = np.array(
        [[2.0, 2.0, 20.0, 24.0], [0.0, 0.0, 30.0, 30.0],
         [8.0, 4.0, 14.0, 30.0]], np.float32,
    )
    rois_num = np.array([2, 1], np.int32)
    bidx = [0, 0, 1]
    ref = _np_roi_align(x, rois, bidx, 4, 4, 0.5, 2)

    xv = fluid.data("x", [2, 3, 16, 16])
    rv = fluid.data("rois", [3, 4])
    nv = fluid.data("rn", [2], "int32")
    out = layers.roi_align(
        xv, rv, pooled_height=4, pooled_width=4, spatial_scale=0.5,
        sampling_ratio=2, rois_num=nv,
    )
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    (got,) = exe.run(
        feed={"x": x, "rois": rois, "rn": rois_num}, fetch_list=[out]
    )
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-5, atol=1e-5)


def test_roi_align_gradients_flow():
    rng = np.random.RandomState(1)
    x = rng.randn(1, 2, 8, 8).astype("float32")
    rois = np.array([[0.0, 0.0, 7.0, 7.0]], np.float32)
    xv = fluid.data("x", [1, 2, 8, 8])
    xv.stop_gradient = False
    rv = fluid.data("rois", [1, 4])
    out = layers.roi_align(xv, rv, 2, 2, 1.0, 2)
    loss = layers.reduce_sum(out)
    grads = fluid.framework.backward.gradients([loss], [xv])
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    (g,) = exe.run(feed={"x": x, "rois": rois}, fetch_list=[grads[0]])
    g = np.asarray(g)
    # sum of bilinear scatter weights per output bin is 1 -> grad sums to
    # n_bins * channels
    np.testing.assert_allclose(g.sum(), 2 * 2 * 2, rtol=1e-5)
    assert (np.abs(g) > 0).sum() > 8


def test_roi_pool_matches_reference_port():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 2, 12, 12).astype("float32")
    rois = np.array(
        [[0.0, 0.0, 11.0, 11.0], [4.0, 4.0, 10.0, 8.0]], np.float32
    )
    rois_num = np.array([1, 1], np.int32)
    ref = _np_roi_pool(x, rois, [0, 1], 3, 3, 1.0)
    xv = fluid.data("x", [2, 2, 12, 12])
    rv = fluid.data("rois", [2, 4])
    nv = fluid.data("rn", [2], "int32")
    out = layers.roi_pool(xv, rv, 3, 3, 1.0, rois_num=nv)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    (got,) = exe.run(
        feed={"x": x, "rois": rois, "rn": rois_num}, fetch_list=[out]
    )
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-5, atol=1e-5)


def test_anchor_generator_matches_reference_port():
    H, W = 3, 4
    sizes, ars = [32.0, 64.0], [0.5, 1.0]
    sw = sh = 16.0
    offset = 0.5
    # literal port of anchor_generator_op.h:52-85
    A = len(sizes) * len(ars)
    ref = np.zeros((H, W, A, 4), np.float32)
    for hi in range(H):
        for wi in range(W):
            xc = wi * sw + offset * (sw - 1)
            yc = hi * sh + offset * (sh - 1)
            i = 0
            for ar in ars:
                bw = round(np.sqrt(sw * sh / ar))
                bh = round(bw * ar)
                for size in sizes:
                    aw = size / sw * bw
                    ah = size / sh * bh
                    ref[hi, wi, i] = [
                        xc - 0.5 * (aw - 1), yc - 0.5 * (ah - 1),
                        xc + 0.5 * (aw - 1), yc + 0.5 * (ah - 1),
                    ]
                    i += 1

    feat = fluid.data("feat", [1, 8, H, W])
    anchors, variances = layers.anchor_generator(
        feat, anchor_sizes=sizes, aspect_ratios=ars, stride=[sw, sh],
        offset=offset,
    )
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    a, v = exe.run(
        feed={"feat": np.zeros((1, 8, H, W), np.float32)},
        fetch_list=[anchors, variances],
    )
    np.testing.assert_allclose(np.asarray(a), ref, rtol=1e-5, atol=1e-4)
    assert np.asarray(v).shape == (H, W, A, 4)


def test_box_clip():
    boxes = np.array(
        [[[-5.0, -3.0, 120.0, 80.0], [10.0, 10.0, 50.0, 50.0]]], np.float32
    )
    im_info = np.array([[100.0, 200.0, 1.0]], np.float32)  # h, w, scale
    bv = fluid.data("b", [1, 2, 4])
    iv = fluid.data("i", [1, 3])
    out = layers.box_clip(bv, iv)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    (got,) = exe.run(feed={"b": boxes, "i": im_info}, fetch_list=[out])
    got = np.asarray(got)
    np.testing.assert_allclose(
        got[0, 0], [0.0, 0.0, 120.0, 80.0], atol=1e-6
    )  # clipped to [0, w-1=199] x [0, h-1=99]
    assert got[0, 0, 2] <= 199.0 and got[0, 0, 3] <= 99.0
    np.testing.assert_allclose(got[0, 1], boxes[0, 1], atol=1e-6)


def test_sigmoid_focal_loss_matches_numpy():
    rng = np.random.RandomState(5)
    N, C = 10, 4
    x = rng.randn(N, C).astype("f4")
    lab = rng.randint(0, C + 1, (N, 1)).astype("i4")  # 0 = background
    fg = np.array([max((lab > 0).sum(), 1)], "i4")
    gamma, alpha = 2.0, 0.25
    t = (lab == np.arange(1, C + 1)[None, :]).astype("f4")
    p = 1 / (1 + np.exp(-x))
    ce = np.maximum(x, 0) - x * t + np.log1p(np.exp(-np.abs(x)))
    p_t = p * t + (1 - p) * (1 - t)
    a_t = alpha * t + (1 - alpha) * (1 - t)
    ref = a_t * (1 - p_t) ** gamma * ce / fg[0]

    xv = fluid.data("x", [N, C])
    lv = fluid.data("l", [N, 1], "int32")
    fv = fluid.data("f", [1], "int32")
    out = layers.sigmoid_focal_loss(xv, lv, fv, gamma=gamma, alpha=alpha)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    (got,) = exe.run(feed={"x": x, "l": lab, "f": fg}, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-4, atol=1e-6)


def test_density_prior_box_shapes_and_center_box():
    H, W = 2, 2
    feat = fluid.data("feat", [1, 4, H, W])
    img = fluid.data("img", [1, 3, 32, 32])
    boxes, vars_ = layers.density_prior_box(
        feat, img, densities=[2], fixed_sizes=[8.0], fixed_ratios=[1.0],
        steps=[16.0, 16.0],
    )
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    b, v = exe.run(
        feed={"feat": np.zeros((1, 4, H, W), np.float32),
              "img": np.zeros((1, 3, 32, 32), np.float32)},
        fetch_list=[boxes, vars_],
    )
    b = np.asarray(b)
    assert b.shape == (H, W, 4, 4)  # density^2 = 4 priors per cell
    # cell (0,0): center 8,8; step_average=16, shift=8 -> offsets +-4;
    # first box center (4,4), half-size 4 (density_prior_box_op.h grid)
    np.testing.assert_allclose(
        b[0, 0, 0] * 32, [0, 0, 8, 8], atol=1e-4
    )


def test_generate_proposals_small_case():
    """3 anchors on a 1x1 map, one image: NMS keeps the two non-overlapping
    high scorers, padded to post_nms_top_n."""
    anchors = np.array(
        [[0, 0, 9, 9], [1, 1, 10, 10], [20, 20, 29, 29]], np.float32
    ).reshape(3, 1, 1, 4).transpose(1, 0, 2, 3)  # -> [A=3,1,1,4] layout
    anchors = anchors.reshape(3, 1, 1, 4)
    var = np.full_like(anchors, 1.0)
    scores = np.array([0.9, 0.8, 0.7], np.float32).reshape(1, 3, 1, 1)
    deltas = np.zeros((1, 12, 1, 1), np.float32)
    im_info = np.array([[40.0, 40.0, 1.0]], np.float32)

    sv = fluid.data("s", [1, 3, 1, 1])
    dv = fluid.data("d", [1, 12, 1, 1])
    iv = fluid.data("i", [1, 3])
    av = fluid.data("a", [3, 1, 1, 4])
    vv = fluid.data("v", [3, 1, 1, 4])
    rois, probs, num = layers.generate_proposals(
        sv, dv, iv, av, vv, pre_nms_top_n=3, post_nms_top_n=4,
        nms_thresh=0.5, min_size=0.0,
    )
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    r, p, n = exe.run(
        feed={"s": scores, "d": deltas, "i": im_info, "a": anchors,
              "v": var},
        fetch_list=[rois, probs, num],
    )
    r, p, n = np.asarray(r), np.asarray(p), np.asarray(n)
    assert int(n[0]) == 2  # box 1 suppressed by box 0 (IoU ~0.65)
    np.testing.assert_allclose(r[0, 0], [0, 0, 9, 9], atol=1e-4)
    np.testing.assert_allclose(r[0, 1], [20, 20, 29, 29], atol=1e-4)
    np.testing.assert_allclose(p[0, :2, 0], [0.9, 0.7], atol=1e-5)
    assert (r[0, 2:] == 0).all()
