"""PR-11 embedding engine: fused multi-table lookup, hot-row cache tiers,
async prefetch, sharded/quantized exchanges.

Parity bars mirror the seed's sparse contract: fused/cached paths are
BITWISE against the per-slot baseline; mesh-sharded training matches to
tight tolerance (the grad psum's n-way summation order is the only
difference, same as the pre-engine path — the forward lookup VALUES stay
bitwise even sharded)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu import layers, observability
from paddle_tpu.embedding import EmbeddingEngine, Prefetcher, fuse_lookups
from paddle_tpu.framework import unique_name
from paddle_tpu.framework.scope import Scope
from paddle_tpu.models.deepfm import DeepFMConfig, deepfm
from paddle_tpu.parallel import (
    ShardedWeightUpdate,
    quantize_embedding_grads,
    shard_program,
    shard_sparse_tables,
)
from paddle_tpu.parallel.mesh import make_mesh


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    scope = Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


CFG = DeepFMConfig(vocab_size=256, num_fields=6, embed_dim=8,
                   mlp_sizes=(16,))
B = 16


def _feeds(n, vocab=None, b=B, fields=None, seed=0):
    vocab = vocab or CFG.vocab_size
    fields = fields or CFG.num_fields
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        idv = (vocab * rng.power(0.4, (b, fields))).astype(np.int64)
        out.append({"feat_ids": idv,
                    "label": (idv[:, :1] % 2 == 0).astype(np.float32)})
    return out


def _build_deepfm(per_slot=False, fused=False, hot_rows=None, shard=None,
                  quant=None, opt="sgd", seed=3, cfg=CFG, b=B):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    scope = Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        ids = fluid.data("feat_ids", [b, cfg.num_fields], "int64")
        label = fluid.data("label", [b, 1], "float32")
        loss, pred = deepfm(ids, label, cfg, per_slot=per_slot)
        if fused:
            fuse_lookups(main)
        engine = None
        if hot_rows:
            engine = EmbeddingEngine(main, startup, hot_rows=hot_rows)
        optimizer = (fluid.optimizer.SGD(0.1) if opt == "sgd"
                     else fluid.optimizer.Momentum(0.05, 0.9))
        optimizer.minimize(loss)
        if shard:
            shard_sparse_tables(main, partition=shard)
            if quant:
                quantize_embedding_grads(main, quant)
            shard_program(main, make_mesh({"ps": 8}))
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        if engine:
            engine.attach(scope)
    return main, startup, scope, exe, loss, pred, engine


def _train(main, scope, exe, loss, feeds, engine=None):
    losses = []
    for f in feeds:
        ff = engine.prepare_feed(f, scope) if engine else f
        (lv,) = exe.run(main, feed=ff, fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    return losses


# ---------------------------------------------------------------------------
# fused multi-table lookup
# ---------------------------------------------------------------------------


def test_fuse_lookups_coalesces_per_slot_graph():
    main, *_ = _build_deepfm(per_slot=True, fused=True)[:1]
    singles = [op for op in main.global_block.ops
               if op.type == "distributed_lookup_table"]
    fused = [op for op in main.global_block.ops
             if op.type == "fused_lookup_table"]
    assert not singles
    # one fused site per table width: [V, 1] (w1) and [V, D] (emb)
    assert len(fused) == 2
    for op in fused:
        # every slot reads the SHARED table: the W slot carries it ONCE
        # and slot_table_idx maps all F slots onto its key segment (so
        # the same id dedups ACROSS slots and the gather operand is one
        # table, not F aliases of it)
        assert len(op.inputs["W"]) == 1
        assert op.attr("slot_table_idx") == [0] * CFG.num_fields
        assert len(op.inputs["Ids"]) == CFG.num_fields
        assert len(op.outputs["Out"]) == CFG.num_fields


def test_fused_training_parity_across_layouts():
    """Training losses agree across the three layouts. Two-table vs
    per-slot vs fused accumulate a repeated id's row gradient in different
    orders (one segment-sum vs F partial sums), so cross-LAYOUT parity is
    tight-allclose; the first step (identical params, forward-only
    difference) is bitwise."""
    feeds = _feeds(5)
    ref = _train(*_pick(_build_deepfm(per_slot=False)), feeds)
    per_slot = _train(*_pick(_build_deepfm(per_slot=True)), feeds)
    fused = _train(*_pick(_build_deepfm(per_slot=True, fused=True)), feeds)
    assert ref[0] == per_slot[0] == fused[0]
    np.testing.assert_allclose(ref, per_slot, rtol=1e-5)
    np.testing.assert_allclose(per_slot, fused, rtol=1e-5)


def _pick(built):
    main, _startup, scope, exe, loss, _pred, _eng = built
    return main, scope, exe, loss


def test_fused_forward_values_bitwise():
    """The fused gather returns exactly the rows the per-slot gathers
    return, slot for slot."""
    b, f, v, d = 8, 4, 64, 8
    rng = np.random.RandomState(1)
    idv = rng.randint(0, v, (b, f)).astype(np.int64)
    outs = {}
    for fused in (False, True):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 5
        scope = Scope()
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), unique_name.guard():
            ids = fluid.data("ids", [b, f], "int64")
            parts = []
            for i in range(f):
                si = layers.slice(ids, [1], [i], [i + 1])
                parts.append(layers.sparse_embedding(
                    si, [v, d], param_attr=fluid.ParamAttr(name="tab"),
                ))
            if fused:
                assert fuse_lookups(main) == 1
            exe = fluid.Executor()
            exe.run(startup, scope=scope)
            vals = exe.run(main, feed={"ids": idv},
                           fetch_list=list(parts), scope=scope)
            outs[fused] = [np.asarray(x) for x in vals]
    for a, b_ in zip(outs[False], outs[True]):
        np.testing.assert_array_equal(a, b_)


def test_fuse_respects_intermediate_readers():
    """A consumer between two lookups pins the first group: fusing past it
    would feed the consumer an output produced later."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), unique_name.guard():
        ids = fluid.data("ids", [8, 1], "int64")
        a = layers.sparse_embedding(
            ids, [32, 4], param_attr=fluid.ParamAttr(name="t1"))
        consumed = layers.scale(a, scale=2.0)  # reads a before lookup 2
        b_ = layers.sparse_embedding(
            ids, [32, 4], param_attr=fluid.ParamAttr(name="t2"))
        _ = consumed + b_
    assert fuse_lookups(main) == 0
    assert all(op.type != "fused_lookup_table"
               for op in main.global_block.ops)


def test_fuse_groups_by_width_and_dtype():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), unique_name.guard():
        ids = fluid.data("ids", [8, 1], "int64")
        outs = [
            layers.sparse_embedding(
                ids, [32, 4], param_attr=fluid.ParamAttr(name="a4")),
            layers.sparse_embedding(
                ids, [32, 8], param_attr=fluid.ParamAttr(name="a8")),
            layers.sparse_embedding(
                ids, [64, 4], param_attr=fluid.ParamAttr(name="b4")),
            layers.sparse_embedding(
                ids, [32, 8], param_attr=fluid.ParamAttr(name="b8")),
        ]
        _ = layers.concat([layers.reshape(o, [8, -1]) for o in outs],
                          axis=1)
    assert fuse_lookups(main) == 2  # width-4 pair + width-8 pair
    fused = [op for op in main.global_block.ops
             if op.type == "fused_lookup_table"]
    widths = sorted(
        main.global_block.var(op.inputs["W"][0]).shape[1] for op in fused
    )
    assert widths == [4, 8]


# ---------------------------------------------------------------------------
# single-table dedup (the satellite bugfix)
# ---------------------------------------------------------------------------


def test_dedup_golden_parity_vs_legacy_path():
    """dedup=True (unique -> gather -> scatter-back) must be bitwise
    identical to the legacy gather-per-occurrence path, forward and
    training, on a batch dense with repeats."""
    b, v, d = 32, 16, 4  # 32 ids over 16 rows: guaranteed repeats
    rng = np.random.RandomState(0)
    idv = rng.randint(0, v, b).astype(np.int64)
    runs = {}
    for dedup in (False, True):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 2
        scope = Scope()
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), unique_name.guard():
            ids = fluid.data("ids", [b], "int64")
            out = layers.sparse_embedding(
                ids, [v, d], param_attr=fluid.ParamAttr(name="table"),
                dedup=dedup,
            )
            loss = layers.reduce_sum(layers.square(out))
            fluid.optimizer.SGD(0.05).minimize(loss)
            exe = fluid.Executor()
            exe.run(startup, scope=scope)
            (fwd,) = exe.run(main, feed={"ids": idv}, fetch_list=[out],
                             scope=scope)
            losses = []
            for _ in range(4):
                (lv,) = exe.run(main, feed={"ids": idv},
                                fetch_list=[loss], scope=scope)
                losses.append(float(np.asarray(lv).reshape(-1)[0]))
            (g,) = exe.run(main, feed={"ids": idv},
                           fetch_list=["table@GRAD"], scope=scope)
        runs[dedup] = (np.asarray(fwd), losses, np.asarray(g))
    np.testing.assert_array_equal(runs[False][0], runs[True][0])
    # the backward segment-sum accumulates repeated rows in a different
    # order than the legacy per-occurrence scatter — tight allclose, and
    # the repeated-row grads must really have accumulated (not last-wins)
    np.testing.assert_allclose(runs[False][2], runs[True][2], rtol=1e-5)
    np.testing.assert_allclose(runs[False][1], runs[True][1], rtol=1e-6)


# ---------------------------------------------------------------------------
# sharded tables: row/col partition, quantized grad exchange, ZeRO compose
# ---------------------------------------------------------------------------


def test_sharded_fused_lookup_values_bitwise():
    feeds = _feeds(1)
    ref = _build_deepfm(per_slot=True, fused=True)
    sharded = _build_deepfm(per_slot=True, fused=True, shard="row")
    for built in (ref, sharded):
        main, _s, scope, exe, _l, pred, _e = built
        (pv,) = exe.run(main, feed=feeds[0], fetch_list=[pred],
                        scope=scope)
        built_out = np.asarray(pv)
        if built is ref:
            ref_out = built_out
    np.testing.assert_array_equal(ref_out, built_out)


def test_sharded_vs_replicated_training_loss_parity_row():
    feeds = _feeds(5)
    ref = _train(*_pick(_build_deepfm(per_slot=True, fused=True)), feeds)
    got = _train(
        *_pick(_build_deepfm(per_slot=True, fused=True, shard="row")),
        feeds,
    )
    np.testing.assert_allclose(ref, got, rtol=1e-5)


def test_sharded_vs_replicated_training_loss_parity_col():
    """Column partition ([V, D/n] Megatron split) needs every table width
    divisible by the mesh — a fused embedding-only tower here (deepfm's
    [V, 1] first-order table cannot column-shard over ps=8)."""
    b, f, v, d = 8, 4, 64, 16
    rng = np.random.RandomState(2)
    idv = rng.randint(0, v, (b, f)).astype(np.int64)

    def run(shard):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 4
        scope = Scope()
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), unique_name.guard():
            ids = fluid.data("ids", [b, f], "int64")
            parts = [
                layers.sparse_embedding(
                    layers.slice(ids, [1], [i], [i + 1]), [v, d],
                    param_attr=fluid.ParamAttr(name="tab"),
                )
                for i in range(f)
            ]
            assert fuse_lookups(main) == 1
            stacked = layers.concat(
                [layers.reshape(p, [b, 1, d]) for p in parts], axis=1
            )
            loss = layers.reduce_sum(layers.square(stacked))
            fluid.optimizer.SGD(0.01).minimize(loss)
            if shard:
                shard_sparse_tables(main, partition="col")
                shard_program(main, make_mesh({"ps": 8}))
            exe = fluid.Executor()
            exe.run(startup, scope=scope)
            out = []
            for _ in range(4):
                (lv,) = exe.run(main, feed={"ids": idv},
                                fetch_list=[loss], scope=scope)
                out.append(float(np.asarray(lv).reshape(-1)[0]))
        return out

    np.testing.assert_allclose(run(False), run(True), rtol=1e-5)


def test_quantized_grad_exchange_fp32_is_bitwise_noop():
    """quant='none' must keep the exact pre-engine psum path."""
    feeds = _feeds(4)
    plain = _train(
        *_pick(_build_deepfm(per_slot=True, fused=True, shard="row")),
        feeds,
    )
    # explicit quant="none" stamp (exercises the stamping path)
    built = _build_deepfm(per_slot=True, fused=True, shard="row")
    quantize_embedding_grads(built[0], None)
    noop = _train(*_pick(built), feeds)
    assert plain == noop


def test_quantized_grad_exchange_int8_trains_close():
    feeds = _feeds(5)
    plain = _train(
        *_pick(_build_deepfm(per_slot=True, fused=True, shard="row")),
        feeds,
    )
    q = _train(
        *_pick(_build_deepfm(per_slot=True, fused=True, shard="row",
                             quant="int8")),
        feeds,
    )
    assert q != plain  # the int8 wire really engaged
    np.testing.assert_allclose(plain, q, rtol=0.05, atol=0.02)


def test_quant_refuses_col_partition_and_unknown_strings():
    built = _build_deepfm(per_slot=True, fused=True, shard="col")
    with pytest.raises(NotImplementedError):
        quantize_embedding_grads(built[0], "int8")
    with pytest.raises(ValueError):
        quantize_embedding_grads(built[0], "int4")
    # order-independent: quant stamped FIRST, col partition second must
    # refuse too (it would silently drop the opted-in compression)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), unique_name.guard():
        ids = fluid.data("ids", [8], "int64")
        _ = layers.sparse_embedding(
            ids, [32, 8], param_attr=fluid.ParamAttr(name="t"))
        quantize_embedding_grads(main, "int8")
        with pytest.raises(NotImplementedError):
            shard_sparse_tables(main, partition="col")


def test_zero_sharded_dense_composes_with_sharded_sparse_tables():
    """ONE training program: dense params under the ZeRO dp weight-update
    shard, sparse tables row-sharded over ps — trains on a dp=2 x ps=4
    mesh with loss parity vs the replicated build."""
    cfg = DeepFMConfig(vocab_size=128, num_fields=4, embed_dim=8,
                       mlp_sizes=(16,))
    feeds = _feeds(4, vocab=cfg.vocab_size, fields=cfg.num_fields, b=8)

    def build(compose):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 9
        scope = Scope()
        with fluid.program_guard(main, startup), \
                fluid.scope_guard(scope), unique_name.guard():
            ids = fluid.data("feat_ids", [8, cfg.num_fields], "int64")
            label = fluid.data("label", [8, 1], "float32")
            loss, _p = deepfm(ids, label, cfg, per_slot=True)
            fuse_lookups(main)
            opt = fluid.optimizer.Momentum(0.05, 0.9)
            pgs = opt.minimize(loss)
            if compose:
                params_grads = pgs[1] if isinstance(pgs, tuple) else pgs
                ShardedWeightUpdate(2, axis_name="dp").transpile(
                    main, startup, params_grads
                )
                shard_sparse_tables(main, axis="ps")
                shard_program(main, make_mesh({"dp": 2, "ps": 4}))
            exe = fluid.Executor()
            exe.run(startup, scope=scope)
            return _train(main, scope, exe, loss, feeds)

    ref = build(False)
    got = build(True)
    np.testing.assert_allclose(ref, got, rtol=1e-4)


def test_zero_transpile_skips_sparse_tables():
    """The ZeRO pass must leave ps-sharded tables (and their state) out of
    the flat dp shard — no @ZERO_SHARD twin for a lookup table."""
    cfg = DeepFMConfig(vocab_size=128, num_fields=4, embed_dim=8,
                       mlp_sizes=(16,))
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), unique_name.guard():
        ids = fluid.data("feat_ids", [8, cfg.num_fields], "int64")
        label = fluid.data("label", [8, 1], "float32")
        loss, _p = deepfm(ids, label, cfg, per_slot=True)
        fuse_lookups(main)
        pgs = fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)
        params_grads = pgs[1] if isinstance(pgs, tuple) else pgs
        ShardedWeightUpdate(2, axis_name="dp").transpile(
            main, startup, params_grads
        )
    shards = [n for n in main.global_block.vars if "@ZERO_SHARD" in n]
    assert shards, "dense params should have been ZeRO-sharded"
    assert not any(n.startswith(("deepfm_w1", "deepfm_emb"))
                   for n in shards), shards
    # the dense MLP weights DID shard
    assert any("deepfm_mlp" in n or "deepfm_out" in n for n in shards)


# ---------------------------------------------------------------------------
# cache tier: capacity, eviction/refetch, checkpoint
# ---------------------------------------------------------------------------


def test_cached_training_bitwise_vs_full_table():
    """hot tier = vocab/2: misses, evictions and write-backs all fire, and
    the run stays BITWISE equal to the full-table run seeded with the same
    host-store init (SGD: absent rows are exact no-ops)."""
    feeds = _feeds(8)
    main, _s, scope, exe, loss, _p, engine = _build_deepfm(
        per_slot=True, fused=True, hot_rows=CFG.vocab_size // 2
    )
    host_init = {
        t: g.host[t].copy() for g in engine.groups for t in g.table_names
    }
    cached = _train(main, scope, exe, loss, feeds, engine)
    snap = observability.snapshot()["counters"]
    assert snap.get("embedding.cache_evictions", 0) > 0
    assert snap.get("embedding.cache_writebacks", 0) > 0

    fmain, _fs, fscope, fexe, floss, _fp, _fe = _build_deepfm(
        per_slot=True, fused=True
    )
    for name, arr in host_init.items():
        fscope.set_var(name, jnp.asarray(arr))
    full = _train(fmain, fscope, fexe, floss, feeds)
    assert cached == full


def test_cache_capacity_exceeds_device_tier():
    main, _s, scope, exe, loss, _p, engine = _build_deepfm(
        per_slot=True, fused=True, hot_rows=CFG.vocab_size // 4
    )
    g = engine.groups[0]
    assert g.hot_rows * 4 == CFG.vocab_size
    # the device-resident table really is hot-tier sized
    table = scope.find_var("deepfm_emb")
    assert table.shape[0] == g.hot_rows
    assert g.host["deepfm_emb"].shape[0] == CFG.vocab_size
    assert g.host_bytes() > g.device_bytes()
    gauges = observability.get_gauges()
    assert gauges[f"embedding.vocab_rows.{g.name}"] == CFG.vocab_size
    assert gauges[f"embedding.hot_rows.{g.name}"] == g.hot_rows


def test_cache_hit_rate_and_histograms_recorded():
    feeds = _feeds(6)
    main, _s, scope, exe, loss, _p, engine = _build_deepfm(
        per_slot=True, fused=True, hot_rows=CFG.vocab_size // 2
    )
    _train(main, scope, exe, loss, feeds, engine)
    gauges = observability.get_gauges()
    hists = observability.get_histograms()
    name = engine.groups[0].name
    assert 0.0 < gauges[f"embedding.hot_hit_rate.{name}"] <= 1.0
    assert hists["embedding.unique_ids_per_batch"]["count"] == len(feeds)
    assert hists["embedding.dedup_ratio"]["count"] == len(feeds)
    assert hists["embedding.dedup_ratio"]["max"] < 1.0  # dedup active
    assert hists["embedding.host_fetch_latency"]["count"] > 0


def test_cache_refuses_batch_larger_than_hot_tier():
    main, _s, scope, exe, loss, _p, engine = _build_deepfm(
        per_slot=True, fused=True, hot_rows=8
    )
    from paddle_tpu.errors import PreconditionNotMetError

    with pytest.raises(PreconditionNotMetError):
        engine.prepare_feed(_feeds(1)[0], scope)


def test_engine_requires_feed_level_ids():
    """Ids computed in-graph (not derivable from a feed) must refuse at
    engine construction, naming the table."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), unique_name.guard():
        x = fluid.data("x", [8, 1], "float32")
        ids = layers.cast(layers.scale(x, scale=100.0), "int64")
        _ = layers.sparse_embedding(
            ids, [32, 4], param_attr=fluid.ParamAttr(name="t"))
    from paddle_tpu.errors import InvalidArgumentError

    with pytest.raises(InvalidArgumentError, match="computed in-graph"):
        EmbeddingEngine(main, startup, hot_rows=16)


def test_cached_checkpoint_resume_bitwise(tmp_path):
    """state_dict + persistables round trip: a rebuilt engine resumes the
    training stream bitwise (Momentum: residency itself is state)."""
    feeds = _feeds(6)

    def build():
        return _build_deepfm(per_slot=True, fused=True,
                             hot_rows=CFG.vocab_size // 2, opt="momentum")

    main, _s, scope, exe, loss, _p, engine = build()
    control = _train(main, scope, exe, loss, feeds, engine)

    main, _s, scope, exe, loss, _p, engine = build()
    got = _train(main, scope, exe, loss, feeds[:3], engine)
    from paddle_tpu.framework.scope import scope_guard

    ckpt = str(tmp_path / "ck")
    engine.flush(scope)
    with scope_guard(scope):
        fluid.io.save_persistables(exe, ckpt, main_program=main)
    np.savez(str(tmp_path / "estate.npz"), **engine.state_dict(scope))
    rng_state = main.rng_state()

    main, _s, scope, exe, loss, _p, engine = build()
    with scope_guard(scope):
        fluid.io.load_persistables(exe, ckpt, main_program=main)
    engine.load_state_dict(
        dict(np.load(str(tmp_path / "estate.npz"))), scope
    )
    main.set_rng_state(rng_state)
    got += _train(main, scope, exe, loss, feeds[3:], engine)
    assert got == control


# ---------------------------------------------------------------------------
# async prefetch
# ---------------------------------------------------------------------------


def test_prefetcher_bitwise_and_overlap_recorded():
    feeds = _feeds(8)
    main, _s, scope, exe, loss, _p, engine = _build_deepfm(
        per_slot=True, fused=True, hot_rows=CFG.vocab_size // 2
    )
    sync = _train(main, scope, exe, loss, feeds, engine)

    main, _s, scope, exe, loss, _p, engine = _build_deepfm(
        per_slot=True, fused=True, hot_rows=CFG.vocab_size // 2
    )
    pre = []
    for f in Prefetcher(engine, feeds, scope):
        (lv,) = exe.run(main, feed=f, fetch_list=[loss], scope=scope)
        pre.append(float(np.asarray(lv).reshape(-1)[0]))
    assert pre == sync
    hists = observability.get_histograms()
    assert hists["embedding.prefetch_overlap"]["count"] == len(feeds)
    counters = observability.get_counters()
    assert counters["embedding.prefetch_batches"] >= len(feeds)


def test_prefetcher_propagates_worker_errors():
    main, _s, scope, exe, loss, _p, engine = _build_deepfm(
        per_slot=True, fused=True, hot_rows=CFG.vocab_size // 2
    )
    bad = [{"feat_ids": np.full((B, CFG.num_fields), 10 ** 6, np.int64),
            "label": np.zeros((B, 1), np.float32)}]
    from paddle_tpu.errors import InvalidArgumentError

    with pytest.raises(InvalidArgumentError, match="outside"):
        for _ in Prefetcher(engine, bad, scope):
            pass


def test_multi_feed_group_translates_each_feed_once():
    """A table keyed by TWO feeds (ids concatenated in-graph) forms one
    multi-feed group: one plan covers both feeds and each is translated
    exactly once (the regression was one plan PER feed, whose first apply
    pass translated the other feed before its rows were resident)."""
    b, v, d = 8, 64, 4
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    scope = Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        user = fluid.data("user_ids", [b, 1], "int64")
        item = fluid.data("item_ids", [b, 1], "int64")
        both = layers.concat([user, item], axis=0)  # [2B, 1]
        out = layers.sparse_embedding(
            both, [v, d], param_attr=fluid.ParamAttr(name="t"))
        loss = layers.reduce_sum(layers.square(out))
        engine = EmbeddingEngine(main, startup, hot_rows=32)
        fluid.optimizer.SGD(0.05).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        engine.attach(scope)
        assert sorted(engine.groups[0].feeds) == ["item_ids", "user_ids"]
        rng = np.random.RandomState(0)
        for _ in range(3):
            feed = {
                "user_ids": rng.randint(0, v, (b, 1)).astype(np.int64),
                "item_ids": rng.randint(0, v, (b, 1)).astype(np.int64),
            }
            ff = engine.prepare_feed(feed, scope)
            # translated slot ids are in hot-tier range, originals untouched
            assert ff["user_ids"].max() < 32 and ff["item_ids"].max() < 32
            (lv,) = exe.run(main, feed=ff, fetch_list=[loss], scope=scope)
            assert np.isfinite(np.asarray(lv)).all()


def test_prefetcher_close_stops_feed_consumption():
    """close() after an early exit must halt the worker — it must NOT keep
    draining the feed source behind the caller's back."""
    import time as _time

    main, _s, scope, exe, loss, _p, engine = _build_deepfm(
        per_slot=True, fused=True, hot_rows=CFG.vocab_size // 2
    )
    consumed = []

    def src():
        for f in _feeds(100):
            consumed.append(1)
            yield f

    pf = Prefetcher(engine, src(), scope, depth=1)
    next(pf)
    pf.close()
    n = len(consumed)
    _time.sleep(0.3)
    assert len(consumed) <= n + 1, "worker kept consuming after close()"
    assert not pf._thread.is_alive()


def test_prefetcher_pipelines_a_dataloader():
    """Composition: DataLoader workers parse, the prefetcher stages rows."""
    from paddle_tpu.dataloader.dataset import Dataset

    class DS(Dataset):
        def __getitem__(self, i):
            rng = np.random.RandomState(i)
            idv = (CFG.vocab_size * rng.power(0.4, CFG.num_fields))
            return idv.astype(np.int64), np.float32([i % 2])

        def __len__(self):
            return 4 * B

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    scope = Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        ids = fluid.data("feat_ids", [-1, CFG.num_fields], "int64")
        label = fluid.data("label", [-1, 1], "float32")
        loss, _p = deepfm(ids, label, CFG, per_slot=True)
        fuse_lookups(main)
        engine = EmbeddingEngine(main, startup,
                                 hot_rows=CFG.vocab_size // 2)
        fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        engine.attach(scope)
        loader = fluid.DataLoader(
            DS(), feed_list=[ids, label], batch_size=B,
            use_buffer_reader=False,
        )
        n = 0
        for f in Prefetcher(engine, loader, scope):
            (lv,) = exe.run(main, feed=f, fetch_list=[loss], scope=scope)
            assert np.isfinite(np.asarray(lv)).all()
            n += 1
        assert n == 4
