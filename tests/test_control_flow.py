"""Control flow: While / cond / case / switch_case / Switch / StaticRNN.

Modeled on the reference's test_while_op.py, test_cond.py, test_case.py,
test_switch.py, test_recurrent_op.py — including the StaticRNN
train-and-match-numpy requirement (VERDICT item 4: a StaticRNN-style loop
model trains and matches a numpy reference).
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


def _run(fetch, feed=None):
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    return exe.run(feed=feed or {}, fetch_list=fetch)


# -- While ------------------------------------------------------------------


def test_while_sums_to_ten():
    i = layers.fill_constant([1], "int32", 0)
    n = layers.fill_constant([1], "int32", 10)
    acc = layers.fill_constant([1], "float32", 0.0)
    cond = layers.less_than(i, n)
    w = layers.While(cond)
    with w.block():
        layers.assign(acc + 1.0, acc)
        layers.increment(i)
        layers.assign(layers.less_than(i, n), cond)
    (out,) = _run([acc])
    np.testing.assert_allclose(np.asarray(out), [10.0])


def test_while_requires_cond_update():
    i = layers.fill_constant([1], "int32", 0)
    n = layers.fill_constant([1], "int32", 10)
    cond = layers.less_than(i, n)
    w = layers.While(cond)
    with pytest.raises(ValueError, match="condition variable"):
        with w.block():
            layers.increment(i)


def test_while_data_dependent_trip_count():
    """Trip count depends on a fed value — the thing static unrolling
    cannot do and lax.while_loop exists for."""
    limit = fluid.data("limit", [1], "int32")
    i = layers.fill_constant([1], "int32", 0)
    acc = layers.fill_constant([1], "float32", 1.0)
    cond = layers.less_than(i, limit)
    w = layers.While(cond)
    with w.block():
        layers.assign(acc * 2.0, acc)
        layers.increment(i)
        layers.assign(layers.less_than(i, limit), cond)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    for k in (3, 7):
        (out,) = exe.run(
            feed={"limit": np.asarray([k], np.int32)}, fetch_list=[acc]
        )
        assert float(np.asarray(out).reshape(-1)[0]) == 2.0 ** k


# -- cond / case / switch ---------------------------------------------------


def test_cond_selects_branch():
    x = fluid.data("x", [1], "float32")
    big = layers.greater_than(x, layers.fill_constant([1], "float32", 0.0))
    out = layers.cond(big, lambda: x * 2.0, lambda: x - 5.0)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    (a,) = exe.run(feed={"x": np.asarray([3.0], np.float32)}, fetch_list=[out])
    (b,) = exe.run(feed={"x": np.asarray([-1.0], np.float32)}, fetch_list=[out])
    assert float(np.asarray(a)[0]) == 6.0
    assert float(np.asarray(b)[0]) == -6.0


def test_cond_is_differentiable():
    """grad flows through the taken branch only (lax.cond vjp)."""
    x = fluid.data("x", [1], "float32")
    x.stop_gradient = False
    pred = layers.greater_than(x, layers.fill_constant([1], "float32", 0.0))
    y = layers.cond(pred, lambda: x * 3.0, lambda: x * 7.0)
    loss = layers.reduce_sum(y)
    (gx,) = fluid.gradients(loss, [x])
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    (g,) = exe.run(feed={"x": np.asarray([2.0], np.float32)}, fetch_list=[gx])
    assert float(np.asarray(g)[0]) == 3.0
    (g,) = exe.run(feed={"x": np.asarray([-2.0], np.float32)}, fetch_list=[gx])
    assert float(np.asarray(g)[0]) == 7.0


def test_case_and_switch_case():
    x = fluid.data("x", [1], "float32")
    one = layers.fill_constant([1], "float32", 1.0)
    two = layers.fill_constant([1], "float32", 2.0)
    out = layers.case(
        [
            (layers.less_than(x, one), lambda: x * 10.0),
            (layers.less_than(x, two), lambda: x * 100.0),
        ],
        default=lambda: x * 1000.0,
    )
    idx = fluid.data("idx", [1], "int32")
    sw = layers.switch_case(
        idx, {0: lambda: x + 1.0, 2: lambda: x + 3.0},
        default=lambda: x + 9.0,
    )
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())

    def f(xv, iv=0):
        a, b = exe.run(
            feed={"x": np.asarray([xv], np.float32),
                  "idx": np.asarray([iv], np.int32)},
            fetch_list=[out, sw],
        )
        return float(np.asarray(a)[0]), float(np.asarray(b)[0])

    assert f(0.5)[0] == 5.0
    assert f(1.5)[0] == 150.0
    assert f(5.0)[0] == 5000.0
    assert f(1.0, 0)[1] == 2.0
    assert f(1.0, 2)[1] == 4.0
    assert f(1.0, 1)[1] == 10.0


def test_switch_context_manager():
    lr = layers.fill_constant([1], "float32", 0.0)
    step = fluid.data("step", [1], "float32")
    thresh = layers.fill_constant([1], "float32", 100.0)
    with layers.Switch() as sw:
        with sw.case(layers.less_than(step, thresh)):
            layers.assign(layers.fill_constant([1], "float32", 0.1), lr)
        with sw.default():
            layers.assign(layers.fill_constant([1], "float32", 0.01), lr)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    (a,) = exe.run(feed={"step": np.asarray([5.0], np.float32)}, fetch_list=[lr])
    (b,) = exe.run(feed={"step": np.asarray([500.0], np.float32)}, fetch_list=[lr])
    assert float(np.asarray(a)[0]) == pytest.approx(0.1)
    assert float(np.asarray(b)[0]) == pytest.approx(0.01)


# -- StaticRNN --------------------------------------------------------------


def test_static_rnn_forward_matches_numpy():
    T, B, D = 5, 2, 3
    x = fluid.data("x", [T, B, D], "float32")
    h0 = fluid.data("h0", [B, D], "float32")
    rnn = layers.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(x)
        h_prev = rnn.memory(init=h0)
        h = layers.tanh(x_t + h_prev)
        rnn.update_memory(h_prev, h)
        rnn.step_output(h)
    out = rnn()
    rng = np.random.RandomState(0)
    xv = rng.randn(T, B, D).astype(np.float32)
    h0v = rng.randn(B, D).astype(np.float32)
    (got,) = _run([out], feed={"x": xv, "h0": h0v})
    want = []
    h = h0v
    for t in range(T):
        h = np.tanh(xv[t] + h)
        want.append(h)
    np.testing.assert_allclose(np.asarray(got), np.stack(want), rtol=2e-5)


def test_static_rnn_trains_and_matches_numpy():
    """An Elman RNN regression trained by BPTT through scan_block matches a
    hand-written numpy forward; loss decreases (VERDICT item 4 done-bar)."""
    T, B, D = 4, 8, 3
    x = fluid.data("x", [T, B, D], "float32")
    y = fluid.data("y", [B, D], "float32")
    rnn = layers.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(x)
        h_prev = rnn.memory(shape=[B, D])
        h = layers.tanh(
            layers.fc(x_t, D, bias_attr=False,
                      param_attr=fluid.ParamAttr(name="w_x"))
            + layers.fc(h_prev, D, bias_attr=False,
                        param_attr=fluid.ParamAttr(name="w_h"))
        )
        rnn.update_memory(h_prev, h)
        rnn.step_output(h)
    outs = rnn()
    last = layers.squeeze(
        layers.slice(outs, [0], [T - 1], [T]), [0]
    )  # [B, D] final step
    loss = layers.reduce_mean(layers.square_error_cost(last, y))
    fluid.optimizer.Adam(0.05).minimize(loss)

    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    scope = fluid.framework.scope.global_scope()
    rng = np.random.RandomState(1)
    xv = rng.randn(T, B, D).astype(np.float32)
    # teacher targets from a ground-truth RNN => realizable, converges to ~0
    twx = rng.randn(D, D).astype(np.float32) * 0.5
    twh = rng.randn(D, D).astype(np.float32) * 0.5
    ht = np.zeros((B, D), np.float32)
    for t in range(T):
        ht = np.tanh(xv[t] @ twx + ht @ twh)
    yv = ht

    # numpy forward with the *initialized* weights must match the graph
    wx = np.asarray(scope.find_var("w_x"))
    wh = np.asarray(scope.find_var("w_h"))
    h = np.zeros((B, D), np.float32)
    for t in range(T):
        h = np.tanh(xv[t] @ wx + h @ wh)
    (first_loss,) = exe.run(
        feed={"x": xv, "y": yv}, fetch_list=[loss]
    )
    np.testing.assert_allclose(
        float(np.asarray(first_loss).reshape(-1)[0]),
        np.mean((h - yv) ** 2),
        rtol=1e-4,
    )

    losses = [float(np.asarray(first_loss).reshape(-1)[0])]
    for _ in range(150):
        (lv,) = exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])


def test_static_rnn_last_memory_and_multiple_outputs():
    T, B = 3, 2
    x = fluid.data("x", [T, B], "float32")
    rnn = layers.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(x)
        s = rnn.memory(shape=[B])
        new_s = s + x_t
        rnn.update_memory(s, new_s)
        rnn.step_output(new_s)
        rnn.step_output(x_t * 2.0)
    o1, o2 = rnn()
    xv = np.arange(T * B, dtype=np.float32).reshape(T, B)
    (g1, g2) = _run([o1, o2], feed={"x": xv})
    np.testing.assert_allclose(np.asarray(g1), np.cumsum(xv, axis=0))
    np.testing.assert_allclose(np.asarray(g2), xv * 2)


def test_cond_pass_through_output():
    """A branch returning a captured var untouched must still work
    (regression: pass-through names missing from the capture list)."""
    x = fluid.data("x", [1], "float32")
    pred = layers.greater_than(x, layers.fill_constant([1], "float32", 0.0))
    out = layers.cond(pred, lambda: x, lambda: x * 2.0)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    (a,) = exe.run(feed={"x": np.asarray([3.0], np.float32)}, fetch_list=[out])
    (b,) = exe.run(feed={"x": np.asarray([-3.0], np.float32)}, fetch_list=[out])
    assert float(np.asarray(a)[0]) == 3.0
    assert float(np.asarray(b)[0]) == -6.0


def test_cond_rejects_outer_writes():
    flag = layers.fill_constant([1], "float32", 0.0)
    x = fluid.data("x", [1], "float32")
    pred = layers.greater_than(x, layers.fill_constant([1], "float32", 0.0))
    with pytest.raises(ValueError, match="functional"):
        layers.cond(
            pred,
            lambda: layers.assign(
                layers.fill_constant([1], "float32", 1.0), flag
            ),
            lambda: flag,
        )


def test_static_rnn_step_body_error_propagates():
    x = fluid.data("x", [3, 2], "float32")
    rnn = layers.StaticRNN()
    with pytest.raises(KeyError, match="user bug"):
        with rnn.step():
            rnn.step_input(x)
            raise KeyError("user bug")


# -- bounded (differentiable) While ---------------------------------------


def _build_bounded_loop(n_val, max_iters=8):
    """s = sum_{i<n} w*x through a While(max_iters=...) loop."""
    x = fluid.data("x", [4])
    n = fluid.data("n", [1], dtype="int32")
    from paddle_tpu.layers.helper import LayerHelper

    w = LayerHelper("loop").create_parameter(
        fluid.ParamAttr(name="loop_w",
                        initializer=fluid.initializer.Constant(2.0)),
        [4], "float32",
    )
    i = layers.fill_constant([1], "int32", 0)
    s = layers.fill_constant([4], "float32", 0.0)
    cond = layers.less_than(i, n)
    loop = layers.While(cond, max_iters=max_iters)
    with loop.block():
        layers.assign(s + w * x, s)
        layers.increment(i, value=1)
        layers.assign(layers.less_than(i, n), cond)
    return x, n, s, w


def test_bounded_while_matches_python_loop():
    x, n, s, w = _build_bounded_loop(3)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    xv = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
    for n_val in (0, 3, 8):
        (sv,) = exe.run(feed={"x": xv, "n": np.array([n_val], np.int32)},
                        fetch_list=[s])
        np.testing.assert_allclose(
            np.asarray(sv), n_val * 2.0 * xv, rtol=1e-6
        )


def test_bounded_while_backprop_through_data_dependent_length():
    """d(sum(s))/dw = n * x — the gradient depends on the RUNTIME trip
    count (reference while_grad capability, while_op.cc)."""
    from paddle_tpu.framework.backward import append_backward

    x, n, s, w = _build_bounded_loop(3)
    loss = layers.reduce_sum(s)
    append_backward(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    xv = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
    from paddle_tpu.framework.program import grad_var_name

    for n_val in (1, 3, 6):
        (gw,) = exe.run(
            feed={"x": xv, "n": np.array([n_val], np.int32)},
            fetch_list=[grad_var_name("loop_w")],
        )
        np.testing.assert_allclose(
            np.asarray(gw), n_val * xv, rtol=1e-5,
            err_msg=f"n={n_val}",
        )


def test_bounded_while_trains():
    """SGD through the bounded While drives w toward zero on
    loss = sum((sum_{i<n} w*x)^2)."""
    x, n, s, w = _build_bounded_loop(4)
    loss = layers.reduce_sum(layers.square(s))
    fluid.optimizer.SGD(0.01).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    xv = np.array([1.0, 0.5, 0.25, 1.0], np.float32)
    feed = {"x": xv, "n": np.array([4], np.int32)}
    losses = [
        float(np.asarray(exe.run(feed=feed, fetch_list=[loss])[0])
              .reshape(-1)[0])
        for _ in range(20)
    ]
    assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])
