"""Worker for the sharded-weight-update golden equivalence test
(tests/test_zero_sharding.py): a real 2-process gloo run — the MULTICHIP
dryrun path — training one tiny MLP three ways:

  baseline      per-grad c_allreduce_sum (GradAllReduce transpile)
  sharded       ZeRO reduce-scatter + 1/N shard update + all-gather, fp32
  sharded_int8  same, with int8 block-quantized collective payloads

The dp=2 mesh spans BOTH processes (one device from each), each process
feeds its half of the global batch (the make_array_from_process_local_data
convention), and the loss fetch is the dp-allreduced global mean — so the
recorded loss trajectory and final weights are directly comparable across
modes. Each rank writes result_<rank>.json (losses + observability
counters/gauges) and params_<rank>.npz (trainable weights).

argv: mode out_dir
"""

import json
import os
import sys

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers, observability
from paddle_tpu.fleet import collective as fleet_mod
from paddle_tpu.framework import unique_name
from paddle_tpu.parallel import make_mesh, shard_program
from paddle_tpu.parallel.transpiler import GradAllReduce, ShardedWeightUpdate

B, D, H, STEPS = 8, 16, 32, 6


def pick_devices(per_proc):
    import jax

    by_proc = {}
    for d in jax.devices():
        by_proc.setdefault(d.process_index, []).append(d)
    assert len(by_proc) == 2, f"expected 2 processes, saw {sorted(by_proc)}"
    devs = []
    for p in sorted(by_proc):
        devs.extend(sorted(by_proc[p], key=lambda d: d.id)[:per_proc])
    return devs


def main():
    mode, out_dir = sys.argv[1], sys.argv[2]
    fleet = fleet_mod.fleet
    fleet.init()  # jax.distributed rendezvous
    rank = fleet.worker_index()
    half = B // 2

    main_prog, startup = fluid.Program(), fluid.Program()
    main_prog.random_seed = startup.random_seed = 7
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main_prog, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        x = fluid.data("x", [B, D])
        y = fluid.data("y", [B, 1])
        h = layers.fc(x, H, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        _, pg = fluid.optimizer.Adam(0.01).minimize(loss, startup)
        blk = main_prog.global_block
        if mode == "baseline":
            GradAllReduce(2).transpile(main_prog, pg)
        else:
            ShardedWeightUpdate(
                2, quant="int8" if mode == "sharded_int8" else None
            ).transpile(main_prog, startup, pg)
        blk.append_op("scale", {"X": [loss.name]}, {"Out": [loss.name]},
                      {"scale": 0.5, "bias": 0.0})
        blk.append_op("c_allreduce_sum", {"X": [loss.name]},
                      {"Out": [loss.name]}, {"axis_name": "dp"})
        shard_program(
            main_prog, make_mesh({"dp": 2}, pick_devices(1)),
            {"x": ("dp",), "y": ("dp",)},
        )
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        losses = []
        for i in range(STEPS):
            rng = np.random.RandomState(100 + i)
            xv = rng.randn(B, D).astype(np.float32)
            yv = rng.randn(B, 1).astype(np.float32)
            lo = rank * half
            (lv,) = exe.run(
                main_prog,
                feed={"x": xv[lo:lo + half], "y": yv[lo:lo + half]},
                fetch_list=[loss], scope=scope, return_numpy=False,
            )
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
        params = {}
        for v in main_prog.all_parameters():
            if not getattr(v, "trainable", False):
                continue
            val = scope.find_var(v.name)
            if val is None:
                continue
            if getattr(val, "is_fully_addressable", True):
                params[v.name] = np.asarray(val)
            else:
                # replicated across the 2-process mesh: this process's
                # local replica IS the full value
                params[v.name] = np.asarray(val.addressable_shards[0].data)

    snap = observability.snapshot()
    with open(os.path.join(out_dir, f"result_{rank}.json"), "w") as f:
        json.dump({
            "mode": mode,
            "losses": losses,
            "counters": snap["counters"],
            "gauges": snap["gauges"],
        }, f)
    np.savez(os.path.join(out_dir, f"params_{rank}.npz"), **params)


if __name__ == "__main__":
    main()
