"""Tiled flash-attention kernel vs the jnp reference (VERDICT r2 item 4:
the KV-tiled online-softmax kernel that removes the whole-row MAX_SEQ
cap). Interpret mode on CPU; dropout=0 (interpreter PRNG is a stub, same
restriction as the round-2 whole-row kernel tests)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.kernels import flash_attention as fa
from paddle_tpu.kernels.flash_tiled import (
    flash_tiled, flash_tiled_fwd, supports_tiled,
)

B, S, H, D = 1, 1024, 2, 64  # 2x2 tiles at BQ=BK=512


def _setup(seed=0):
    rng = np.random.RandomState(seed)
    qkv = jnp.asarray(rng.randn(B, S, 3 * H * D).astype(np.float32) * 0.3)
    bias = jnp.asarray(rng.randn(B, S).astype(np.float32) * 0.5)
    return qkv, bias


def _statics(causal):
    return dict(scale=0.125, rate=0.0, is_test=True, upscale=False,
                causal=causal)


@pytest.mark.parametrize("causal", [False, True])
def test_tiled_forward_matches_reference(causal):
    assert supports_tiled(S, H, D, jnp.float32)
    qkv, bias = _setup()
    statics = _statics(causal)
    seed = jnp.zeros((2,), jnp.uint32)
    out, lse = flash_tiled_fwd(qkv, bias, seed, H, D, statics,
                               interpret=True)
    ref = fa._reference_qkv(qkv, bias, jax.random.key(0), H, **statics)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-5), (
        np.abs(np.asarray(out) - np.asarray(ref)).max()
    )
    # lse finite on every row
    assert np.all(np.isfinite(np.asarray(lse)))


@pytest.mark.parametrize("causal", [False, True])
def test_tiled_grads_match_reference(causal):
    qkv, bias = _setup(1)
    statics = _statics(causal)
    seed = jnp.zeros((2,), jnp.uint32)

    def f_tiled(qkv_, bias_):
        out = flash_tiled(qkv_, bias_, seed, H, D,
                          tuple(statics.items()), True)
        return jnp.sum(out * jnp.cos(out * 0.1))

    def f_ref(qkv_, bias_):
        out = fa._reference_qkv(qkv_, bias_, jax.random.key(0), H, **statics)
        return jnp.sum(out * jnp.cos(out * 0.1))

    g_t = jax.grad(f_tiled, argnums=(0, 1))(qkv, bias)
    g_r = jax.grad(f_ref, argnums=(0, 1))(qkv, bias)
    for a, b_ in zip(g_t, g_r):
        err = np.abs(np.asarray(a) - np.asarray(b_)).max()
        scale = np.abs(np.asarray(b_)).max() + 1e-6
        assert err / scale < 2e-4, err / scale


def test_adaptive_tile_sizes_fwd_bwd():
    """r4: S need only be a multiple of 128 (adaptive BQ/BK) and causal
    tiles above the diagonal are skipped — fwd+bwd vs dense reference at a
    non-512-multiple S."""
    import jax

    from paddle_tpu.kernels import flash_attention as fa
    from paddle_tpu.kernels.flash_tiled import (flash_tiled, flash_tiled_fwd,
                                                supports_tiled)

    rng = np.random.RandomState(7)
    H, D, S = 4, 64, 1280
    assert supports_tiled(S, H, D, jnp.float32)
    assert supports_tiled(384, H, D, jnp.float32)
    qkv = jnp.asarray(rng.randn(1, S, 3 * H * D).astype(np.float32)) * 0.3
    bias = jnp.zeros((1, S), jnp.float32)
    st = dict(scale=0.125, rate=0.0, is_test=True, upscale=False,
              causal=True)
    out, _ = flash_tiled_fwd(qkv, bias, jnp.zeros(2, jnp.uint32), H, D, st,
                             interpret=True)
    ref = fa._reference_qkv(qkv, bias, jax.random.key(0), H, **st)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    w = jnp.asarray(rng.randn(1, S, H * D).astype(np.float32))
    stt = tuple(st.items())
    g = jax.grad(lambda x: jnp.sum(flash_tiled(
        x, bias, jnp.zeros(2, jnp.uint32), H, D, stt, True) * w))(qkv)
    gr = jax.grad(lambda x: jnp.sum(fa._reference_qkv(
        x, bias, jax.random.key(0), H, **st) * w))(qkv)
    scale = np.abs(np.asarray(gr)).max()
    np.testing.assert_allclose(np.asarray(g) / scale, np.asarray(gr) / scale,
                               atol=1e-4)


def test_saved_lse_wired_into_grad_op(monkeypatch):
    """r4: when the build-time predicate says the tiled kernel will run,
    the grad maker wires the forward's saved (Out, Lse) into the
    dedicated grad op so the backward skips its forward re-run. The
    predicate is TPU-only, so force it here and assert graph structure."""
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.framework import unique_name
    from paddle_tpu.ops import fused as fused_ops

    monkeypatch.setattr(fused_ops, "_qkv_tiled_at_build",
                        lambda op, block: True)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), unique_name.guard():
        x = fluid.data("x", [1, 2048, 64], "float32")
        qkv = layers.fc(x, 3 * 8 * 64, num_flatten_dims=2)  # param -> grads
        out = layers.fused_qkv_attention(qkv, 8, causal=True)
        loss = layers.reduce_mean(out)
        fluid.optimizer.SGD(0.1).minimize(loss, startup)
    grad_ops = [op for op in main.global_block.ops
                if op.type == "fused_qkv_attention_grad"]
    assert grad_ops, "no dedicated grad op emitted"
    g = grad_ops[0]
    assert g.inputs.get("Out") and g.inputs.get("Lse"), g.inputs
    fwd = [op for op in main.global_block.ops
           if op.type == "fused_qkv_attention"][0]
    assert g.inputs["Lse"] == fwd.outputs["Lse"]
    assert g.inputs["Out"] == fwd.outputs["Out"]
