"""Linear-chain CRF: negative log likelihood and Viterbi against
brute-force enumeration over all label sequences (the gold oracle), plus
a label_semantic_roles-style book test (reference
tests/book/test_label_semantic_roles.py): embedding + LSTM + CRF trained
until Viterbi decoding recovers a deterministic tagging rule."""

import itertools

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name

B, T, D = 3, 5, 4


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 13
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope


def _score(em, trans, tags):
    start, end, w = trans[0], trans[1], trans[2:]
    s = start[tags[0]] + end[tags[-1]]
    for t, tag in enumerate(tags):
        s += em[t, tag]
    for t in range(1, len(tags)):
        s += w[tags[t - 1], tags[t]]
    return s


def _brute(em, trans, label, L):
    """(neg log likelihood, viterbi path) by enumerating D^L sequences."""
    scores = {
        tags: _score(em[:L], trans, tags)
        for tags in itertools.product(range(D), repeat=L)
    }
    logz = np.logaddexp.reduce(np.array(list(scores.values())))
    nll = logz - scores[tuple(label[:L])]
    best = max(scores, key=scores.get)
    return nll, list(best)


def test_crf_nll_and_viterbi_match_enumeration():
    rng = np.random.RandomState(0)
    em = rng.randn(B, T, D).astype("float32")
    trans = rng.randn(D + 2, D).astype("float32") * 0.5
    label = rng.randint(0, D, (B, T)).astype("int64")
    lengths = np.array([5, 3, 4], np.int32)

    e = fluid.data("e", [B, T, D])
    lab = fluid.data("lab", [B, T], "int64")
    ln = fluid.data("ln", [B], "int32")
    nll = layers.linear_chain_crf(
        e, lab, param_attr=fluid.ParamAttr(name="crf_w"), length=ln
    )
    path = layers.crf_decoding(
        e, param_attr=fluid.ParamAttr(name="crf_w"), length=ln
    )
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    scope = fluid.framework.scope.global_scope()
    scope.set_var("crf_w", trans)
    fluid.default_main_program()._bump()
    got_nll, got_path = exe.run(
        feed={"e": em, "lab": label, "ln": lengths}, fetch_list=[nll, path]
    )
    got_nll = np.asarray(got_nll).reshape(-1)
    got_path = np.asarray(got_path)
    for b in range(B):
        L = int(lengths[b])
        ref_nll, ref_path = _brute(em[b], trans, label[b], L)
        np.testing.assert_allclose(got_nll[b], ref_nll, rtol=1e-4,
                                   err_msg=f"nll seq {b}")
        assert list(got_path[b, :L]) == ref_path, f"viterbi seq {b}"
        assert (got_path[b, L:] == 0).all()


def test_crf_decoding_label_mask():
    rng = np.random.RandomState(1)
    em = rng.randn(B, T, D).astype("float32")
    e = fluid.data("e", [B, T, D])
    lab = fluid.data("lab", [B, T], "int64")
    path = layers.crf_decoding(
        e, param_attr=fluid.ParamAttr(name="crf_w2")
    )
    mask = layers.crf_decoding(
        e, param_attr=fluid.ParamAttr(name="crf_w2"), label=lab
    )
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    label = rng.randint(0, D, (B, T)).astype("int64")
    p, m = exe.run(feed={"e": em, "lab": label}, fetch_list=[path, mask])
    np.testing.assert_array_equal(
        np.asarray(m), (np.asarray(p) == label).astype(np.int64)
    )


def test_label_semantic_roles_book():
    """Sequence tagging: tag[t] = (word[t] + word[t-1]) % D — needs context,
    which the LSTM+CRF stack provides (reference book test shape)."""
    V, H, NB, NT, ND = 30, 32, 8, 8, 4
    words = fluid.data("words", [NB, NT], "int64")
    target = fluid.data("target", [NB, NT], "int64")
    emb = layers.embedding(words, size=[V, H])
    hidden, _, _ = layers.lstm(emb, H)
    emission = layers.fc(hidden, ND, num_flatten_dims=2)
    nll = layers.linear_chain_crf(
        emission, target, param_attr=fluid.ParamAttr(name="crf_book")
    )
    loss = layers.mean(nll)
    path = layers.crf_decoding(
        emission, param_attr=fluid.ParamAttr(name="crf_book")
    )
    fluid.optimizer.Adam(0.01).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    rng = np.random.RandomState(2)
    w = rng.randint(0, V, (NB, NT)).astype("int64")
    prev = np.concatenate([np.zeros((NB, 1), np.int64), w[:, :-1]], 1)
    tags = ((w + prev) % ND).astype("int64")
    feed = {"words": w, "target": tags}
    vals = []
    for _ in range(120):
        (lv,) = exe.run(feed=feed, fetch_list=[loss])
        vals.append(float(np.asarray(lv).reshape(-1)[0]))
    assert vals[-1] < 0.3 * vals[0], (vals[0], vals[-1])
    (decoded,) = exe.run(feed=feed, fetch_list=[path])
    acc = (np.asarray(decoded) == tags).mean()
    assert acc > 0.9, acc
