"""Typed error taxonomy (reference platform/error_codes.proto Code enum,
enforce.h:282 EnforceNotMet, pybind/exception.cc BindException): exception
type + error code + op provenance + builtin-base compatibility."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import errors, layers
from paddle_tpu.framework.scope import Scope


def test_taxonomy_codes_and_builtin_bases():
    assert errors.InvalidArgumentError.code == errors.ErrorCode.INVALID_ARGUMENT
    assert errors.NotFoundError.code == errors.ErrorCode.NOT_FOUND
    assert errors.UnimplementedError.code == errors.ErrorCode.UNIMPLEMENTED
    # every class is an EnforceNotMet AND the natural builtin
    assert issubclass(errors.InvalidArgumentError, ValueError)
    assert issubclass(errors.OutOfRangeError, IndexError)
    assert issubclass(errors.ResourceExhaustedError, MemoryError)
    assert issubclass(errors.UnimplementedError, NotImplementedError)
    assert issubclass(errors.FatalError, SystemError)
    assert issubclass(errors.ExternalError, OSError)
    for n in ("AlreadyExistsError", "PreconditionNotMetError",
              "PermissionDeniedError", "ExecutionTimeoutError",
              "UnavailableError", "EOFException"):
        assert issubclass(getattr(errors, n), errors.EnforceNotMet)
    # proto numbering preserved (error_codes.proto:19-80)
    assert int(errors.ErrorCode.EXTERNAL) == 12
    assert int(errors.ErrorCode.INVALID_ARGUMENT) == 1


def test_unregistered_op_is_unimplemented():
    from paddle_tpu.framework.registry import get_op_def

    with pytest.raises(errors.UnimplementedError, match="not registered"):
        get_op_def("definitely_not_an_op")
    # pre-taxonomy catch still works
    with pytest.raises(NotImplementedError):
        get_op_def("definitely_not_an_op")


def test_missing_feed_is_not_found_with_message():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [2, 3], "float32")
        y = layers.scale(x, scale=2.0)
    exe = fluid.Executor()
    scope = Scope()
    exe.run(startup, scope=scope)
    with pytest.raises(errors.NotFoundError, match="feed variable 'x'"):
        exe.run(main, feed={}, fetch_list=[y], scope=scope)


def test_uninitialized_scope_is_precondition_not_met():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [2, 2], "float32")
        y = layers.fc(x, 2)
    exe = fluid.Executor()
    scope = Scope()  # startup NOT run
    with pytest.raises(errors.PreconditionNotMetError, match="startup"):
        exe.run(main, feed={"x": np.zeros((2, 2), "float32")},
                fetch_list=[y], scope=scope)
    # legacy handlers catching RuntimeError still work
    with pytest.raises(RuntimeError):
        exe.run(main, feed={"x": np.zeros((2, 2), "float32")},
                fetch_list=[y], scope=scope)


def test_block_var_not_found():
    main = fluid.Program()
    with pytest.raises(errors.NotFoundError, match="not found in block"):
        main.global_block.var("nope")


def test_op_provenance_attached():
    e = errors.InvalidArgumentError("bad shape", op=None, loc="model.py:10")
    assert e.user_loc == "model.py:10"
    assert "model.py:10" in str(e)
    assert "INVALID_ARGUMENT" in str(e)


def test_nan_check_is_precondition_not_met():
    from paddle_tpu import set_flags

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [2], "float32")
        y = layers.log(x)  # log(-1) -> NaN
    exe = fluid.Executor()
    scope = Scope()
    exe.run(startup, scope=scope)
    set_flags({"FLAGS_check_nan_inf": 1})
    try:
        with pytest.raises(errors.PreconditionNotMetError, match="NaN/Inf"):
            exe.run(main, feed={"x": np.array([-1.0, 1.0], "float32")},
                    fetch_list=[y], scope=scope)
    finally:
        set_flags({"FLAGS_check_nan_inf": 0})
