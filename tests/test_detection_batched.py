"""Seeded golden-parity tests for the cross-image batched detection ops
(ISSUE 6 tentpole): every rank-lifted op must produce, for image b of a
batched [B, ...] run, exactly what the legacy per-image form produces for
that image alone.

RNG contract (ops/_helpers.op_key + the batched dispatch blocks): a
batched sampling op splits its op key into B per-image keys with
``jax.random.split(key, B)``, so image b of a batched run is bitwise
reproduced by a single-image run seeded with ``split(key, B)[b]``. The
deterministic ops (roi family, proposals, NMS, FPN routing, mask labels)
need no key plumbing and parity is exact; the sampling ops
(rpn_target_assign, generate_proposal_labels) are exact under the split
key and tolerance-bounded only where fp summation order differs.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu  # noqa: F401  (registers the op emitters)
from paddle_tpu.framework.registry import EmitContext, get_op_def

BASE_KEY = jax.random.key(42)


class _FakeOp:
    def __init__(self, type, attrs):
        self.type, self.attrs, self.uid = type, attrs, 7

    def attr(self, k, d=None):
        return self.attrs.get(k, d)


def _run(op_type, attrs, ins, key=BASE_KEY):
    ctx = EmitContext()
    ctx.key_for = lambda uid, t: key
    return get_op_def(op_type).emit(ctx, _FakeOp(op_type, attrs), ins)


def _grid_anchors(h, w, stride=16, size=31):
    out = []
    for y in range(h):
        for x in range(w):
            out.append([x * stride, y * stride,
                        x * stride + size, y * stride + size])
    return jnp.asarray(np.array(out, np.float32))


def _rand_boxes(rng, *shape_prefix, span=30.0, min_wh=8.0):
    b = rng.rand(*shape_prefix, 4).astype("float32") * span
    b[..., 2:] = b[..., :2] + min_wh + b[..., 2:] / 2
    return jnp.asarray(b)


def test_roi_align_and_pool_batched_parity():
    rng = np.random.RandomState(0)
    B, C, H, W, R = 2, 2, 16, 16, 5
    x = jnp.asarray(rng.rand(B, C, H, W).astype("float32"))
    rois = _rand_boxes(rng, B, R, span=10.0 * 16, min_wh=16.0)
    attrs = {"pooled_height": 3, "pooled_width": 3, "spatial_scale": 1 / 4.0,
             "sampling_ratio": 2}
    ob = _run("roi_align", attrs,
              {"X": [x], "ROIs": [rois], "RoisNum": [None]})
    assert np.asarray(ob["Out"][0]).shape == (B, R, C, 3, 3)
    for b in range(B):
        os_ = _run("roi_align", attrs,
                   {"X": [x[b:b + 1]], "ROIs": [rois[b]], "RoisNum": [None]})
        np.testing.assert_array_equal(
            np.asarray(ob["Out"][0][b]), np.asarray(os_["Out"][0]))

    ob = _run("roi_pool", attrs,
              {"X": [x], "ROIs": [rois], "RoisNum": [None]})
    assert np.asarray(ob["Out"][0]).shape == (B, R, C, 3, 3)
    for b in range(B):
        os_ = _run("roi_pool", attrs,
                   {"X": [x[b:b + 1]], "ROIs": [rois[b]], "RoisNum": [None]})
        np.testing.assert_array_equal(
            np.asarray(ob["Out"][0][b]), np.asarray(os_["Out"][0]))
        np.testing.assert_array_equal(
            np.asarray(ob["Argmax"][0][b]), np.asarray(os_["Argmax"][0]))


def test_greedy_nms_blocked_matches_single_block():
    from paddle_tpu.ops.detection import _greedy_nms

    rng = np.random.RandomState(1)
    k = 40
    boxes = np.asarray(_rand_boxes(rng, k, span=60.0, min_wh=10.0))
    keep = rng.rand(k) > 0.2
    # block=64 takes the fully static single-block path; block=8 the
    # scan-over-blocks path — identical suppression semantics required
    ref = np.asarray(_greedy_nms(jnp.asarray(boxes), jnp.asarray(keep),
                                 0.5, block=64))
    blk = np.asarray(_greedy_nms(jnp.asarray(boxes), jnp.asarray(keep),
                                 0.5, block=8))
    np.testing.assert_array_equal(ref, blk)


def test_generate_proposals_batched_parity():
    rng = np.random.RandomState(2)
    B, A, H, W = 2, 3, 4, 4
    anchors = jnp.tile(_grid_anchors(H, W)[:, None, :], (1, A, 1)) \
        .reshape(-1, 4) + jnp.asarray(
            np.repeat(np.arange(A, dtype=np.float32)[None] * 2, H * W, 0)
        ).reshape(-1)[:, None]
    scores = jnp.asarray(rng.rand(B, A, H, W).astype("float32"))
    deltas = jnp.asarray(
        (rng.rand(B, A * 4, H, W).astype("float32") - 0.5) * 0.2)
    im_info = jnp.asarray(np.tile([[64.0, 64.0, 1.0]], (B, 1)))
    var = jnp.ones_like(anchors)
    attrs = {"pre_nms_topN": 24, "post_nms_topN": 8, "nms_thresh": 0.7,
             "min_size": 1.0}
    ob = _run("generate_proposals", attrs,
              {"Scores": [scores], "BboxDeltas": [deltas],
               "ImInfo": [im_info], "Anchors": [anchors],
               "Variances": [var]})
    assert np.asarray(ob["RpnRois"][0]).shape == (B, 8, 4)
    for b in range(B):
        os_ = _run("generate_proposals", attrs,
                   {"Scores": [scores[b:b + 1]],
                    "BboxDeltas": [deltas[b:b + 1]],
                    "ImInfo": [im_info[b:b + 1]], "Anchors": [anchors],
                    "Variances": [var]})
        for k in ("RpnRois", "RpnRoiProbs", "RpnRoisNum"):
            np.testing.assert_array_equal(
                np.asarray(ob[k][0][b]), np.asarray(os_[k][0][0]))


def test_multiclass_nms_batched_parity():
    rng = np.random.RandomState(3)
    B, C, N = 2, 4, 20
    boxes = _rand_boxes(rng, B, N, span=50.0, min_wh=6.0)
    scores = jnp.asarray(rng.rand(B, C, N).astype("float32"))
    attrs = {"score_threshold": 0.3, "nms_threshold": 0.4, "nms_top_k": 12,
             "keep_top_k": 6, "background_label": 0}
    ob = _run("multiclass_nms", attrs, {"BBoxes": [boxes],
                                        "Scores": [scores]})
    assert np.asarray(ob["Out"][0]).shape == (B, 6, 6)
    for b in range(B):
        os_ = _run("multiclass_nms", attrs,
                   {"BBoxes": [boxes[b:b + 1]], "Scores": [scores[b:b + 1]]})
        np.testing.assert_array_equal(
            np.asarray(ob["Out"][0][b]), np.asarray(os_["Out"][0][0]))
        np.testing.assert_array_equal(
            np.asarray(ob["NmsRoisNum"][0][b]),
            np.asarray(os_["NmsRoisNum"][0][0]))


def test_rpn_target_assign_batched_parity():
    rng = np.random.RandomState(4)
    B, G = 2, 3
    anchors = _grid_anchors(4, 4)
    gt = _rand_boxes(rng, B, G, min_wh=20.0)
    crowd = jnp.zeros((B, G), jnp.int32)
    info = jnp.asarray(np.tile([[64.0, 64.0, 1.0]], (B, 1)))
    attrs = {"rpn_batch_size_per_im": 8, "rpn_positive_overlap": 0.7,
             "rpn_negative_overlap": 0.3, "rpn_fg_fraction": 0.5}
    ob = _run("rpn_target_assign", attrs,
              {"Anchor": [anchors], "GtBoxes": [gt], "IsCrowd": [crowd],
               "ImInfo": [info]})
    keys = jax.random.split(BASE_KEY, B)
    for b in range(B):
        os_ = _run("rpn_target_assign", attrs,
                   {"Anchor": [anchors], "GtBoxes": [gt[b]],
                    "IsCrowd": [crowd[b]], "ImInfo": [info[b:b + 1]]},
                   key=keys[b])
        for k in ob:
            got = np.asarray(ob[k][0][b])
            np.testing.assert_array_equal(
                got, np.asarray(os_[k][0]).reshape(got.shape),
                err_msg=f"{k} image {b}")


@pytest.mark.slow
def test_retinanet_target_assign_batched_parity():
    # same _anchor_assign core as the tier-1 rpn_target_assign case;
    # slow-marked purely for tier-1 budget (ci.sh's unfiltered run keeps it)
    rng = np.random.RandomState(5)
    B, G = 2, 2
    anchors = _grid_anchors(4, 4)
    gt = _rand_boxes(rng, B, G, min_wh=24.0)
    labels = jnp.asarray(rng.randint(1, 5, (B, G, 1)).astype("int32"))
    crowd = jnp.zeros((B, G), jnp.int32)
    info = jnp.asarray(np.tile([[64.0, 64.0, 1.0]], (B, 1)))
    attrs = {"positive_overlap": 0.5, "negative_overlap": 0.4}
    ob = _run("retinanet_target_assign", attrs,
              {"Anchor": [anchors], "GtBoxes": [gt], "GtLabels": [labels],
               "IsCrowd": [crowd], "ImInfo": [info]})
    keys = jax.random.split(BASE_KEY, B)
    for b in range(B):
        os_ = _run("retinanet_target_assign", attrs,
                   {"Anchor": [anchors], "GtBoxes": [gt[b]],
                    "GtLabels": [labels[b]], "IsCrowd": [crowd[b]],
                    "ImInfo": [info[b:b + 1]]}, key=keys[b])
        for k in ob:
            got = np.asarray(ob[k][0][b])
            np.testing.assert_array_equal(
                got, np.asarray(os_[k][0]).reshape(got.shape),
                err_msg=f"{k} image {b}")


def _proposal_labels(rng, B, R, G):
    rois = _rand_boxes(rng, B, R, min_wh=15.0)
    gt = _rand_boxes(rng, B, G, min_wh=20.0)
    gcls = jnp.asarray(rng.randint(1, 4, (B, G)).astype("int32"))
    crowd = jnp.zeros((B, G), jnp.int32)
    info = jnp.asarray(np.tile([[64.0, 64.0, 1.0]], (B, 1)))
    attrs = {"batch_size_per_im": 8, "fg_fraction": 0.5, "fg_thresh": 0.5,
             "bg_thresh_hi": 0.5, "bg_thresh_lo": 0.0, "class_nums": 4}
    ins = {"RpnRois": [rois], "GtClasses": [gcls], "IsCrowd": [crowd],
           "GtBoxes": [gt], "ImInfo": [info], "RpnRoisNum": [None]}
    return attrs, ins


def test_generate_proposal_labels_batched_parity():
    rng = np.random.RandomState(6)
    B, R, G = 2, 6, 3
    attrs, ins = _proposal_labels(rng, B, R, G)
    ob = _run("generate_proposal_labels", attrs, ins)
    keys = jax.random.split(BASE_KEY, B)
    for b in range(B):
        single = {
            "RpnRois": [ins["RpnRois"][0][b]],
            "GtClasses": [ins["GtClasses"][0][b]],
            "IsCrowd": [ins["IsCrowd"][0][b]],
            "GtBoxes": [ins["GtBoxes"][0][b]],
            "ImInfo": [ins["ImInfo"][0][b:b + 1]],
            "RpnRoisNum": [None],
        }
        os_ = _run("generate_proposal_labels", attrs, single, key=keys[b])
        for k in ob:
            got = np.asarray(ob[k][0][b])
            np.testing.assert_allclose(
                got, np.asarray(os_[k][0]).reshape(got.shape), atol=1e-5,
                err_msg=f"{k} image {b}")


def test_generate_mask_labels_batched_parity():
    rng = np.random.RandomState(7)
    B, R, G = 2, 6, 3
    attrs, ins = _proposal_labels(rng, B, R, G)
    pl = _run("generate_proposal_labels", attrs, ins)
    segms = jnp.asarray((rng.rand(B, G, 32, 32) > 0.5).astype("float32"))
    mattrs = {"resolution": 4, "num_classes": 4}
    mins = {"ImInfo": ins["ImInfo"], "GtClasses": ins["GtClasses"],
            "IsCrowd": ins["IsCrowd"], "GtSegms": [segms],
            "Rois": [pl["Rois"][0]], "LabelsInt32": [pl["LabelsInt32"][0]]}
    ob = _run("generate_mask_labels", mattrs, mins)
    for b in range(B):
        single = {
            "ImInfo": [ins["ImInfo"][0][b:b + 1]],
            "GtClasses": [ins["GtClasses"][0][b]],
            "IsCrowd": [ins["IsCrowd"][0][b]],
            "GtSegms": [segms[b]],
            "Rois": [pl["Rois"][0][b]],
            "LabelsInt32": [pl["LabelsInt32"][0][b]],
        }
        os_ = _run("generate_mask_labels", mattrs, single)
        for k in ob:
            got = np.asarray(ob[k][0][b])
            np.testing.assert_array_equal(
                got, np.asarray(os_[k][0]).reshape(got.shape),
                err_msg=f"{k} image {b}")


def test_distribute_and_collect_fpn_batched_parity():
    rng = np.random.RandomState(8)
    B, R = 2, 8
    rois = _rand_boxes(rng, B, R, span=120.0, min_wh=10.0)
    dattrs = {"min_level": 2, "max_level": 5, "refer_level": 4,
              "refer_scale": 224}
    ob = _run("distribute_fpn_proposals", dattrs,
              {"FpnRois": [rois], "RoisNum": [None]})
    L = 4
    for b in range(B):
        os_ = _run("distribute_fpn_proposals", dattrs,
                   {"FpnRois": [rois[b]], "RoisNum": [None]})
        for i in range(L):
            np.testing.assert_array_equal(
                np.asarray(ob["MultiFpnRois"][i][b]),
                np.asarray(os_["MultiFpnRois"][i]))
            np.testing.assert_array_equal(
                np.asarray(ob["MultiLevelRoIsNum"][i][b]),
                np.asarray(os_["MultiLevelRoIsNum"][i])[0])
        np.testing.assert_array_equal(
            np.asarray(ob["RestoreIndex"][0][b]).ravel(),
            np.asarray(os_["RestoreIndex"][0]).ravel())

    # collect: feed the distributed levels back with per-level scores
    scores = [jnp.asarray(rng.rand(B, R, 1).astype("float32"))
              for _ in range(L)]
    cattrs = {"post_nms_topN": 6}
    cb = _run("collect_fpn_proposals", cattrs,
              {"MultiLevelRois": list(ob["MultiFpnRois"]),
               "MultiLevelScores": scores,
               "MultiLevelRoIsNum": list(ob["MultiLevelRoIsNum"])})
    assert np.asarray(cb["FpnRois"][0]).shape == (B, 6, 4)
    for b in range(B):
        os_ = _run("collect_fpn_proposals", cattrs,
                   {"MultiLevelRois": [r[b] for r in ob["MultiFpnRois"]],
                    "MultiLevelScores": [s[b] for s in scores],
                    "MultiLevelRoIsNum": [
                        n[b].reshape(1) for n in ob["MultiLevelRoIsNum"]]})
        np.testing.assert_array_equal(
            np.asarray(cb["FpnRois"][0][b]), np.asarray(os_["FpnRois"][0]))
        np.testing.assert_array_equal(
            np.asarray(cb["RoisNum"][0][b]),
            np.asarray(os_["RoisNum"][0])[0])


def test_detection_counters_and_roi_stats():
    """Observability satellite: batched instantiations bump detection.*
    counters, and record_roi_stats exports the padding-waste gauge +
    rois-per-image histogram through the shared registry."""
    from paddle_tpu import observability
    from paddle_tpu.ops.detection_stats import record_roi_stats

    rng = np.random.RandomState(9)
    x = jnp.asarray(rng.rand(2, 2, 8, 8).astype("float32"))
    rois = _rand_boxes(rng, 2, 3, span=20.0, min_wh=4.0)
    _run("roi_align", {"pooled_height": 2, "pooled_width": 2,
                       "spatial_scale": 1.0},
         {"X": [x], "ROIs": [rois], "RoisNum": [None]})
    snap = observability.snapshot()
    c = snap["counters"]
    assert c.get("detection.roi_align.instantiations", 0) >= 1
    assert c.get("detection.roi_align.batched_instantiations", 0) >= 1

    waste = record_roi_stats(np.array([4, 8]), cap=8)
    assert waste == pytest.approx(1.0 - 12 / 16)
    snap = observability.snapshot()
    assert snap["gauges"]["detection.padding_waste"] == pytest.approx(waste)
    assert snap["histograms"]["detection.rois_per_image"]["count"] >= 2
    assert snap["counters"]["detection.roi_batches_recorded"] >= 1


@pytest.mark.slow
def test_mask_rcnn_batched_loss_parity():
    """Model-level acceptance: the batched [B, ...] train graph's losses
    match the mean of the legacy per-image graphs' losses on the same
    data and init seed. Sampling RNG streams differ between the two
    program shapes (different op uids), so the bound is a tolerance on
    the per-image-normalized losses, not bitwise equality; the
    deterministic components (RPN/head cls, bbox reg at init) agree to a
    few percent and the total to ~15%."""
    from paddle_tpu.framework.scope import Scope
    from paddle_tpu.models import mask_rcnn

    import paddle_tpu as fluid

    cfg = mask_rcnn.MaskRCNNConfig.tiny()
    size, G, B = 64, 2, 2
    rng = np.random.RandomState(0)
    boxes = rng.rand(B, G, 4).astype("float32") * (size / 2)
    boxes[..., 2:] = boxes[..., :2] + 8 + boxes[..., 2:] / 2
    imgs = rng.rand(B, 3, size, size).astype("float32")
    cls = rng.randint(1, cfg.class_num, (B, G)).astype("int32")
    segs = (rng.rand(B, G, size, size) > 0.5).astype("float32")
    info = np.tile([[size, size, 1.0]], (B, 1)).astype("float32")

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        im = fluid.data("images", [B, 3, size, size])
        gb = fluid.data("gt_boxes", [B, G, 4])
        gc = fluid.data("gt_classes", [B, G], dtype="int32")
        ic = fluid.data("is_crowd", [B, G], dtype="int32")
        gs = fluid.data("gt_segms", [B, G, size, size])
        ii = fluid.data("im_info", [B, 3])
        losses, _aux = mask_rcnn.mask_rcnn_train_batched(
            im, gb, gc, ic, gs, ii, cfg)
    scope = Scope()
    exe = fluid.Executor()
    exe.run(startup, scope=scope)
    feed = {"images": jnp.asarray(imgs), "gt_boxes": jnp.asarray(boxes),
            "gt_classes": jnp.asarray(cls),
            "is_crowd": jnp.asarray(np.zeros((B, G), "int32")),
            "gt_segms": jnp.asarray(segs), "im_info": jnp.asarray(info)}
    vals = exe.run(main, feed=feed, fetch_list=list(losses), scope=scope)
    batched = np.array([float(np.asarray(v).reshape(-1)[0]) for v in vals])

    legacy = []
    for b in range(B):
        m2, s2 = fluid.Program(), fluid.Program()
        m2.random_seed = s2.random_seed = 7
        with fluid.program_guard(m2, s2):
            im = fluid.data("image", [1, 3, size, size])
            gb = fluid.data("gt_boxes", [G, 4])
            gc = fluid.data("gt_classes", [G], dtype="int32")
            ic = fluid.data("is_crowd", [G], dtype="int32")
            gs = fluid.data("gt_segms", [G, size, size])
            ii = fluid.data("im_info", [1, 3])
            l2 = mask_rcnn.mask_rcnn_train(im, gb, gc, ic, gs, ii, cfg)
        sc2 = Scope()
        exe.run(s2, scope=sc2)
        f2 = {"image": jnp.asarray(imgs[b:b + 1]),
              "gt_boxes": jnp.asarray(boxes[b]),
              "gt_classes": jnp.asarray(cls[b]),
              "is_crowd": jnp.asarray(np.zeros((G,), "int32")),
              "gt_segms": jnp.asarray(segs[b]),
              "im_info": jnp.asarray(info[b:b + 1])}
        v2 = exe.run(m2, feed=f2, fetch_list=list(l2), scope=sc2)
        legacy.append([float(np.asarray(v).reshape(-1)[0]) for v in v2])
    legacy_mean = np.mean(legacy, axis=0)

    assert np.all(np.isfinite(batched)) and np.all(np.isfinite(legacy_mean))
    # total loss within 15%; every component within 0.5 absolute (the
    # sampling-dependent RPN reg term carries the largest jitter)
    np.testing.assert_allclose(batched[0], legacy_mean[0], rtol=0.15)
    np.testing.assert_allclose(batched, legacy_mean, atol=0.5)
