"""Profiler / flags / nan-inf mode / error provenance.

Reference: platform/profiler.h, platform/flags.cc, nan_inf_utils_detail.cc,
framework/op_call_stack.cc.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import unique_name


@pytest.fixture(autouse=True)
def fresh_programs():
    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        yield main, startup, scope
    fluid.set_flags({"FLAGS_check_nan_inf": False})


def test_flags_roundtrip_and_unknown():
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    assert fluid.get_flags("check_nan_inf")["FLAGS_check_nan_inf"] is True
    fluid.set_flags({"FLAGS_check_nan_inf": False})
    with pytest.raises(ValueError, match="unknown flag"):
        fluid.set_flags({"FLAGS_does_not_exist": 1})


def test_check_nan_inf_names_offending_op():
    x = fluid.data("x", [2, 2])
    y = layers.log(x)  # log of a negative -> NaN
    z = layers.relu(y)
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    with pytest.raises(RuntimeError, match=r"NaN/Inf.*'log'"):
        exe.run(feed={"x": np.full((2, 2), -1.0, np.float32)},
                fetch_list=[z])
    # clean inputs pass
    out = exe.run(feed={"x": np.ones((2, 2), np.float32)}, fetch_list=[z])
    np.testing.assert_allclose(np.asarray(out[0]), 0.0, atol=1e-6)


def test_op_provenance_in_error():
    """A trace-time failure names the op type and the creating user line."""
    x = fluid.data("x", [4, 4])
    w = layers.fill_constant([3, 3], "float32", 1.0)
    # hand-append a shape-incompatible matmul: fails inside the emitter
    blk = fluid.default_main_program().global_block
    blk.create_var(name="dead", shape=[4, 3], dtype="float32")
    blk.append_op("matmul", {"X": [x.name], "Y": [w.name]}, {"Out": ["dead"]})
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    with pytest.raises(Exception, match=r"matmul.*test_observability"):
        exe.run(feed={"x": np.ones((4, 4), np.float32)},
                fetch_list=["dead"])


def test_profiler_captures_device_ops():
    import paddle_tpu.profiler as prof

    x = fluid.data("x", [32, 32])
    y = layers.matmul(x, x)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    feed = {"x": np.ones((32, 32), np.float32)}
    exe.run(feed=feed, fetch_list=[y])  # compile outside the profile
    d = prof.start_profiler()
    for _ in range(3):
        exe.run(feed=feed, fetch_list=[y])
    out_dir = prof.stop_profiler()
    table = prof.summary(out_dir)
    assert table, "no device ops captured"
    assert sum(c for _, _, c in table) >= 3


def test_record_event_context():
    import paddle_tpu.profiler as prof

    with prof.RecordEvent("custom_span"):
        pass  # must not raise outside an active trace


def test_check_nan_inf_sees_sharded_state():
    """A NaN confined to one shard of a row-sharded table must still trip
    the check (flags pmax over mesh axes)."""
    from paddle_tpu.parallel import shard_program, shard_sparse_tables
    from paddle_tpu.parallel.mesh import make_mesh

    ids = fluid.data("ids", [4], "int64")
    out = layers.sparse_embedding(
        ids, [32, 4], param_attr=fluid.ParamAttr(name="ntable"),
        pad_to_multiple=8,
    )
    loss = layers.reduce_sum(out)
    fluid.optimizer.SGD(0.1).minimize(loss)
    shard_sparse_tables(fluid.default_main_program())
    shard_program(fluid.default_main_program(), make_mesh({"ps": 8}))
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    scope = fluid.framework.scope.global_scope()
    tbl = np.array(scope.find_var("ntable"))  # writable copy
    tbl[25, 0] = np.nan  # row owned by shard 6 of 8
    scope.set_var("ntable", tbl)
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    with pytest.raises(RuntimeError, match="NaN/Inf"):
        exe.run(feed={"ids": np.asarray([25], np.int64).repeat(4)},
                fetch_list=[loss])


# -- round 4: monitor counters + graphviz dump + install_check ---------------


def test_monitor_counters_count_runs_and_compiles():
    import paddle_tpu as fluid
    from paddle_tpu import layers, monitor
    from paddle_tpu.framework.scope import Scope

    monitor.reset()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [2, 2])
        y = layers.scale(x, scale=3.0)
    exe = fluid.Executor()
    scope = Scope()
    exe.run(startup, scope=scope)
    for _ in range(3):
        exe.run(main, feed={"x": np.zeros((2, 2), "float32")},
                fetch_list=[y], scope=scope)
    stats = monitor.get_int_stats()
    assert stats["executor.run_steps"] == 4  # startup + 3 steps
    # 3 identical steps share ONE compile (startup is the other)
    assert stats["executor.compile_count"] == 2
    monitor.set_float("test.gauge", 1.5)
    assert monitor.get_float_stats()["test.gauge"] == 1.5
    monitor.reset()


def test_draw_block_graphviz(tmp_path):
    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.debugger import draw_block_graphviz

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [2, 4])
        y = layers.fc(x, 3, act="relu")
    path = draw_block_graphviz(main.global_block,
                               highlights=[y.name],
                               path=str(tmp_path / "g.dot"))
    dot = open(path).read()
    assert dot.startswith("digraph G {") and dot.rstrip().endswith("}")
    assert '"mul"' in dot and '"relu"' in dot
    assert "yellow" in dot  # highlighted output var
    assert "lightgrey" in dot  # parameter node


def test_install_check_run_check(capsys):
    from paddle_tpu.install_check import run_check

    assert run_check() is True
    out = capsys.readouterr().out
    assert "installed successfully" in out
