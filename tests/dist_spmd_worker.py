"""Worker for the cross-process SPMD tests (TP / ring-attention SP / MoE
EP / pipeline / sharded PS table) — VERDICT r3 item 3: these strategies
previously ran only on the in-process 8-device virtual mesh.

Launched 2-process by paddle_tpu.distributed.launch --simulate_cpu (gloo
CPU collectives + jax.distributed rendezvous via fleet.init). Each process
inherits XLA_FLAGS=--xla_force_host_platform_device_count=8 from the
pytest env, so the global device set is 16; meshes below span BOTH
processes (2 devices from each), which is what makes these tests exercise
the multi-host code paths: make_array_from_process_local_data feed
assembly, stage_global(..., local_is_full=True) state slicing, and
cross-process collectives.

Reference pattern: tests/unittests/test_dist_base.py:506 (subprocess
trainers, distributed-vs-local loss comparison).
"""

import json
import os
import sys

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.fleet import collective as fleet_mod
from paddle_tpu.framework import unique_name
from paddle_tpu.parallel import (PipelineOptimizer, shard_program,
                                 shard_sparse_tables)
from paddle_tpu.parallel.mesh import make_mesh


def pick_devices(per_proc):
    """2 devices from EACH process — a mesh that genuinely spans hosts."""
    import jax

    by_proc = {}
    for d in jax.devices():
        by_proc.setdefault(d.process_index, []).append(d)
    assert len(by_proc) == 2, f"expected 2 processes, saw {sorted(by_proc)}"
    devs = []
    for p in sorted(by_proc):
        devs.extend(sorted(by_proc[p], key=lambda d: d.id)[:per_proc])
    return devs


def run_tp(out_dir, rank):
    """BERT tensor parallelism (gspmd) over mp=4 across 2 processes."""
    from paddle_tpu.models import BertConfig, bert_pretrain
    from paddle_tpu.models.bert import bert_tp_shardings

    b, s = 4, 64
    cfg = BertConfig(
        vocab_size=512, hidden_size=256, num_layers=2, num_heads=4,
        intermediate_size=1024, max_position=128,
    )
    rng = np.random.RandomState(0)
    feed = {
        "ids": rng.randint(0, cfg.vocab_size, (b, s)).astype("int64"),
        "types": rng.randint(0, 2, (b, s)).astype("int64"),
        "mask": np.ones((b, s), "float32"),
        "labels": rng.randint(0, cfg.vocab_size, (b, s)).astype("int64"),
    }
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        ids = fluid.data("ids", [b, s], "int64")
        types = fluid.data("types", [b, s], "int64")
        mask = fluid.data("mask", [b, s], "float32")
        labels = fluid.data("labels", [b, s], "int64")
        loss = bert_pretrain(ids, types, mask, labels, cfg, is_test=True)
        fluid.optimizer.SGD(0.1).minimize(loss)
        shard_program(
            main, make_mesh({"mp": 4}, pick_devices(2)),
            shardings=bert_tp_shardings(cfg), mode="gspmd",
        )
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        losses = []
        for _ in range(3):
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss],
                            scope=scope, return_numpy=False)
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    with open(os.path.join(out_dir, f"losses_{rank}.json"), "w") as f:
        json.dump(losses, f)


def run_sp(out_dir, rank):
    """Ring attention with the sequence axis sharded across processes:
    each process FEEDS ONLY ITS HALF of the sequence (the dp/sp input
    convention of make_array_from_process_local_data)."""
    b, h, s, d = 2, 2, 64, 8
    rng = np.random.RandomState(1)
    q = rng.randn(b, h, s, d).astype(np.float32)
    k = rng.randn(b, h, s, d).astype(np.float32)
    v = rng.randn(b, h, s, d).astype(np.float32)
    half = s // 2
    lo = rank * half
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()), unique_name.guard():
        qv = fluid.data("q", [b, h, s, d], "float32")
        kv = fluid.data("k", [b, h, s, d], "float32")
        vv = fluid.data("v", [b, h, s, d], "float32")
        out = layers.ring_attention(qv, kv, vv, axis_name="sp", causal=True)
        shard_program(
            main, make_mesh({"sp": 4}, pick_devices(2)),
            {
                "q": (None, None, "sp"),
                "k": (None, None, "sp"),
                "v": (None, None, "sp"),
                out.name: (None, None, "sp"),
            },
        )
        exe = fluid.Executor()
        (res,) = exe.run(
            main,
            feed={
                "q": q[:, :, lo:lo + half],
                "k": k[:, :, lo:lo + half],
                "v": v[:, :, lo:lo + half],
            },
            fetch_list=[out],
            return_numpy=False,
        )
    # save this process's addressable sequence shards with their offsets
    shards = {}
    for sh in res.addressable_shards:
        start = sh.index[2].start or 0
        shards[str(start)] = np.asarray(sh.data)
    np.savez(os.path.join(out_dir, f"out_{rank}.npz"), **shards)


def run_moe(out_dir, rank):
    """Expert-parallel MoE over ep=4 across processes; x replicated."""
    b, s, h, e, f = 1, 16, 8, 8, 16
    rng = np.random.RandomState(0)
    x_np = rng.randn(b, s, h).astype("float32")
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        x = fluid.data("x", [b, s, h], "float32")
        out, _aux = layers.moe_ffn(
            x, num_experts=e, hidden_dim=f, axis_name="ep",
            param_attr_prefix="m0",
        )
        sh = layers.moe_shardings("m0", axis="ep")
        shard_program(main, make_mesh({"ep": 4}, pick_devices(2)), sh)
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        (res,) = exe.run(main, feed={"x": x_np}, fetch_list=[out],
                         scope=scope, return_numpy=False)
    np.save(os.path.join(out_dir, f"out_{rank}.npy"), np.asarray(res))


def run_pipe(out_dir, rank):
    """2-stage pipeline with stage 0 on process 0 and stage 1 on process 1
    (one device each) — boundary activations cross hosts via ppermute."""
    b, steps = 16, 4
    devs = pick_devices(1)  # 1 per process -> pp=2 spans both
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        x = fluid.data("x", [b, 8])
        y = fluid.data("y", [b, 1])
        with fluid.device_guard("pipeline:0"):
            hh = layers.fc(x, 16, act="relu",
                           param_attr=fluid.ParamAttr(name="w0"),
                           bias_attr=fluid.ParamAttr(name="b0"))
        with fluid.device_guard("pipeline:1"):
            pred = layers.fc(hh, 1,
                             param_attr=fluid.ParamAttr(name="w1"),
                             bias_attr=fluid.ParamAttr(name="b1"))
            loss = layers.mean(layers.square_error_cost(pred, y))
        opt = PipelineOptimizer(fluid.optimizer.SGD(0.1),
                                num_microbatches=4)
        opt.minimize(loss)
        shard_program(main, make_mesh({"pp": 2}, devs))
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        losses = []
        for i in range(steps):
            rngf = np.random.RandomState(i)
            xv = rngf.randn(b, 8).astype(np.float32)
            yv = (xv @ rngf.randn(8, 1)).astype(np.float32)
            (lv,) = exe.run(main, feed={"x": xv, "y": yv},
                            fetch_list=[loss], scope=scope,
                            return_numpy=False)
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    with open(os.path.join(out_dir, f"losses_{rank}.json"), "w") as f:
        json.dump(losses, f)


def run_pstable(out_dir, rank):
    """Row-sharded embedding table over ps=4 ACROSS PROCESSES: startup
    initializes the full table locally on each process, and
    stage_global(..., local_is_full=True) (parallel/spmd.py) slices each
    process's rows out — the multi-host state path VERDICT r3 item 3
    names. Trains 3 SGD steps."""
    vocab, dim, b, steps = 64, 8, 16, 3
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    scope = fluid.framework.scope.Scope()
    with fluid.program_guard(main, startup), fluid.scope_guard(scope), \
            unique_name.guard():
        ids = fluid.data("ids", [b], "int64")
        out = layers.sparse_embedding(
            ids, [vocab, dim], param_attr=fluid.ParamAttr(name="table"),
            pad_to_multiple=8,
        )
        loss = layers.reduce_mean(layers.square(out))
        fluid.optimizer.SGD(0.1).minimize(loss)
        shard_sparse_tables(main)
        shard_program(main, make_mesh({"ps": 4}, pick_devices(2)))
        exe = fluid.Executor()
        exe.run(startup, scope=scope)
        losses = []
        for i in range(steps):
            rngf = np.random.RandomState(10 + i)
            idv = rngf.randint(0, vocab, b).astype(np.int64)
            (lv,) = exe.run(main, feed={"ids": idv}, fetch_list=[loss],
                            scope=scope, return_numpy=False)
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
    with open(os.path.join(out_dir, f"losses_{rank}.json"), "w") as f:
        json.dump(losses, f)


MODES = {
    "tp": run_tp,
    "sp": run_sp,
    "moe": run_moe,
    "pipe": run_pipe,
    "pstable": run_pstable,
}


def main():
    mode, out_dir = sys.argv[1], sys.argv[2]
    fleet = fleet_mod.fleet
    fleet.init()  # jax.distributed rendezvous (role_maker.py)
    rank = fleet.worker_index()
    MODES[mode](out_dir, rank)


if __name__ == "__main__":
    main()
