// Go inference client over the paddle_tpu C API (reference
// go/paddle/predictor.go + config.go + tensor.go, which wrap the C++
// AnalysisPredictor through paddle_c_api.h the same way).
//
// Build: the cgo directives below link libpaddle_tpu_capi.so — build it
// once with `python -c "from paddle_tpu.inference_capi import build_capi;
// print(build_capi())"` and point CGO_LDFLAGS at its directory. NOTE: the
// build image for this repo carries no Go toolchain, so this package is
// compile-checked against the C header contract only (tests/test_capi.py
// exercises the identical PD_* calls from C); treat it as the reference
// treats its Go client — a thin shipped binding, not a tested surface.

package paddle_tpu

// #cgo CFLAGS: -I${SRCDIR}/../../paddle_tpu/inference_capi
// #cgo LDFLAGS: -L${SRCDIR}/../../paddle_tpu/inference_capi -lpaddle_tpu_capi
// #include <stdbool.h>
// #include <stdlib.h>
// #include "paddle_tpu_capi.h"
import "C"

import (
	"runtime"
	"unsafe"
)

type DType C.PD_DataType

const (
	Float32 DType = C.PD_FLOAT32
	Int32   DType = C.PD_INT32
	Int64   DType = C.PD_INT64
	Uint8   DType = C.PD_UINT8
)

// AnalysisConfig mirrors the reference go/paddle/config.go surface.
type AnalysisConfig struct {
	c *C.PD_AnalysisConfig
}

func NewAnalysisConfig() *AnalysisConfig {
	cfg := &AnalysisConfig{c: C.PD_NewAnalysisConfig()}
	runtime.SetFinalizer(cfg, (*AnalysisConfig).finalize)
	return cfg
}

func (cfg *AnalysisConfig) finalize() { C.PD_DeleteAnalysisConfig(cfg.c) }

func (cfg *AnalysisConfig) SetModel(modelDir, paramsFile string) {
	cDir := C.CString(modelDir)
	defer C.free(unsafe.Pointer(cDir))
	var cParams *C.char
	if paramsFile != "" {
		cParams = C.CString(paramsFile)
		defer C.free(unsafe.Pointer(cParams))
	}
	C.PD_SetModel(cfg.c, cDir, nil, cParams)
}

// Tensor is the host-side value crossing the boundary (PD_TensorC).
type Tensor struct {
	Name  string
	Dtype DType
	Shape []int64
	Data  []byte
}

type Predictor struct {
	c *C.PD_Predictor
}

func NewPredictor(cfg *AnalysisConfig) *Predictor {
	p := C.PD_NewPredictor(cfg.c)
	if p == nil {
		return nil
	}
	pred := &Predictor{c: p}
	runtime.SetFinalizer(pred, (*Predictor).finalize)
	return pred
}

func (p *Predictor) finalize() { C.PD_DeletePredictor(p.c) }

func (p *Predictor) GetInputNum() int  { return int(C.PD_GetInputNum(p.c)) }
func (p *Predictor) GetOutputNum() int { return int(C.PD_GetOutputNum(p.c)) }

func (p *Predictor) GetInputName(i int) string {
	return C.GoString(C.PD_GetInputName(p.c, C.int(i)))
}

func (p *Predictor) GetOutputName(i int) string {
	return C.GoString(C.PD_GetOutputName(p.c, C.int(i)))
}

func LastError() string { return C.GoString(C.PD_GetLastError()) }

func toC(ts []Tensor, pin []*C.char) []C.PD_TensorC {
	ins := make([]C.PD_TensorC, len(ts))
	for i, t := range ts {
		pin[i] = C.CString(t.Name)
		ins[i].name = pin[i]
		ins[i].dtype = C.PD_DataType(t.Dtype)
		// rank-0 tensors / empty buffers: pass nil, the C side tolerates
		// a null pointer with rank 0 / byte_size 0 (indexing [0] on an
		// empty Go slice would panic)
		if len(t.Shape) > 0 {
			ins[i].shape = (*C.int64_t)(unsafe.Pointer(&t.Shape[0]))
		}
		ins[i].rank = C.int(len(t.Shape))
		if len(t.Data) > 0 {
			ins[i].data = unsafe.Pointer(&t.Data[0])
		}
		ins[i].byte_size = C.size_t(len(t.Data))
	}
	return ins
}

func fromC(outs *C.PD_TensorC, n C.int, copyData bool) []Tensor {
	res := make([]Tensor, int(n))
	sz := unsafe.Sizeof(C.PD_TensorC{})
	for i := 0; i < int(n); i++ {
		o := (*C.PD_TensorC)(unsafe.Pointer(
			uintptr(unsafe.Pointer(outs)) + uintptr(i)*sz))
		rank := int(o.rank)
		shape := make([]int64, rank)
		for d := 0; d < rank; d++ {
			shape[d] = int64(*(*C.int64_t)(unsafe.Pointer(
				uintptr(unsafe.Pointer(o.shape)) + uintptr(d)*8)))
		}
		data := C.GoBytes(o.data, C.int(o.byte_size))
		_ = copyData // GoBytes always copies; zero-copy callers keep C ptrs
		res[i] = Tensor{
			Name:  C.GoString(o.name),
			Dtype: DType(o.dtype),
			Shape: shape,
			Data:  data,
		}
	}
	return res
}

// Run mirrors reference Predictor.Run: copies outputs into Go memory.
func (p *Predictor) Run(inputs []Tensor) ([]Tensor, bool) {
	pin := make([]*C.char, len(inputs))
	defer func() {
		for _, s := range pin {
			if s != nil {
				C.free(unsafe.Pointer(s))
			}
		}
	}()
	ins := toC(inputs, pin)
	var outs *C.PD_TensorC
	var n C.int
	ok := bool(C.PD_PredictorRun(p.c, &ins[0], C.int(len(ins)), &outs, &n))
	if !ok {
		return nil, false
	}
	res := fromC(outs, n, true)
	C.PD_FreeOutputs(outs, n)
	return res, true
}

// ZeroCopyRun mirrors the reference ZeroCopy API: inputs are read in
// place, outputs borrow predictor-owned buffers (valid until next run);
// the returned Go slices are copies of those buffers for memory safety
// at the Go boundary (the C caller may instead hold the raw pointers).
func (p *Predictor) ZeroCopyRun(inputs []Tensor) ([]Tensor, bool) {
	pin := make([]*C.char, len(inputs))
	defer func() {
		for _, s := range pin {
			if s != nil {
				C.free(unsafe.Pointer(s))
			}
		}
	}()
	ins := toC(inputs, pin)
	var outs *C.PD_TensorC
	var n C.int
	ok := bool(C.PD_ZeroCopyRun(p.c, &ins[0], C.int(len(ins)), &outs, &n))
	if !ok {
		return nil, false
	}
	res := fromC(outs, n, true)
	C.PD_FreeZeroCopyOutputs(outs, n)
	return res, true
}
