"""Model persistence (reference: python/paddle/fluid/io.py — save_params
:372, save_persistables :597, load_persistables :902, save_inference_model
:1093, load_inference_model :1303, unified fluid.save/load :1598/:1662).

TPU-native storage: parameters are jax Arrays in the Scope; serialization is
one .npz per directory (save_params/persistables) or a single pickled
payload (save/load), fetched through a single host sync. The reference runs
generated save/load *ops* through the Executor; here persistence is pure
host-side IO — there is nothing device-specific about a checkpoint.
"""

from __future__ import annotations

import os
import pickle

import numpy as np

from .framework.program import Parameter, Program, default_main_program
from .framework.scope import global_scope

__all__ = [
    "save_params",
    "save_persistables",
    "load_params",
    "load_persistables",
    "save",
    "load",
    "save_inference_model",
    "load_inference_model",
    "prune",
]


def _collect(program, scope, predicate):
    out = {}
    for var in program.list_vars():
        if not predicate(var):
            continue
        val = scope.find_var(var.name)
        if val is not None:
            out[var.name] = np.asarray(val)
    return out


def _is_persistable(v):
    return bool(getattr(v, "persistable", False)) and not getattr(v, "is_data", False)


def _is_parameter(v):
    return isinstance(v, Parameter)


def save_params(executor, dirname, main_program=None, filename=None):
    _save_vars(dirname, main_program, _is_parameter, filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    _save_vars(dirname, main_program, _is_persistable, filename)


def _save_vars(dirname, main_program, predicate, filename):
    program = main_program or default_main_program()
    scope = global_scope()
    arrays = _collect(program, scope, predicate)
    os.makedirs(dirname, exist_ok=True)
    np.savez(os.path.join(dirname, filename or "__params__.npz"), **arrays)


def load_params(executor, dirname, main_program=None, filename=None):
    _load_vars(dirname, main_program, filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    _load_vars(dirname, main_program, filename)


def _load_vars(dirname, main_program, filename):
    import jax.numpy as jnp

    scope = global_scope()
    path = os.path.join(dirname, filename or "__params__.npz")
    with np.load(path, allow_pickle=False) as data:
        for name in data.files:
            scope.set_var(name, jnp.asarray(data[name]))


def save(program, model_path):
    """fluid.save parity (io.py:1598): one combined file with params +
    optimizer state (all persistables), plus the serialized program."""
    scope = global_scope()
    payload = {
        "params": _collect(program, scope, _is_parameter),
        "opt": _collect(
            program, scope, lambda v: _is_persistable(v) and not _is_parameter(v)
        ),
    }
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(payload, f, protocol=4)
    with open(model_path + ".pdmodel", "wb") as f:
        pickle.dump(program, f, protocol=4)


def load(program, model_path, var_list=None):
    """fluid.load parity (io.py:1662)."""
    import jax.numpy as jnp

    scope = global_scope()
    with open(model_path + ".pdparams", "rb") as f:
        payload = pickle.load(f)
    wanted = {v.name for v in var_list} if var_list else None
    for group in ("params", "opt"):
        for name, arr in payload.get(group, {}).items():
            if wanted is None or name in wanted:
                scope.set_var(name, jnp.asarray(arr))


def prune(program, targets, feeds=()):
    """Backward-slice the program to ops needed for `targets`
    (reference framework/prune.cc + Executor prune-by-fetch)."""
    target_names = {t.name if hasattr(t, "name") else str(t) for t in targets}
    feed_names = set(feeds)
    block = program.global_block
    needed = set(target_names)
    keep = []
    for op in reversed(block.ops):
        outs = [n for n in op.output_names() if n]
        if any(n in needed for n in outs):
            # an op whose only outputs are feeds exists to *produce* the feed
            # (e.g. a reader); the caller will supply it, so cut it out
            if outs and all(n in feed_names for n in outs):
                continue
            keep.append(op)
            # the slice stops at feed variables: their producers are replaced
            # by the runtime feed, exactly like the reference's prune.cc
            needed.update(
                n for n in op.input_names() if n and n not in feed_names
            )
    keep.reverse()

    pruned = program.clone()
    pblock = pruned.global_block
    keep_ids = {id(op) for op in keep}
    # ops were deep-copied in clone; map by position
    pblock.ops = [
        pop
        for op, pop in zip(block.ops, pblock.ops)
        if id(op) in keep_ids
    ]
    pruned._bump()
    return pruned


def save_inference_model(
    dirname,
    feeded_var_names,
    target_vars,
    executor=None,
    main_program=None,
    model_filename=None,
    params_filename=None,
):
    """Prune to the feed→fetch subgraph in test mode and save program+params
    (reference io.py:1093)."""
    program = main_program or default_main_program()
    test_prog = program.clone(for_test=True)
    # names survive clone, so prune on the cloned program
    targets = [
        test_prog.global_block.var(v.name if hasattr(v, "name") else str(v))
        for v in target_vars
    ]
    pruned = prune(test_prog, targets, feeds=feeded_var_names)
    os.makedirs(dirname, exist_ok=True)
    meta = {
        "program": pruned,
        "feed_names": list(feeded_var_names),
        "fetch_names": [t.name for t in targets],
    }
    with open(os.path.join(dirname, model_filename or "__model__"), "wb") as f:
        pickle.dump(meta, f, protocol=4)
    scope = global_scope()
    arrays = _collect(pruned, scope, _is_persistable)
    np.savez(
        os.path.join(dirname, params_filename or "__params__.npz"), **arrays
    )
    return [t.name for t in targets]


def load_inference_model(dirname, executor=None, model_filename=None,
                         params_filename=None):
    """Returns (program, feed_names, fetch_names); params land in the global
    scope (reference io.py:1303)."""
    import jax.numpy as jnp

    with open(os.path.join(dirname, model_filename or "__model__"), "rb") as f:
        meta = pickle.load(f)
    scope = global_scope()
    path = os.path.join(dirname, params_filename or "__params__.npz")
    with np.load(path, allow_pickle=False) as data:
        for name in data.files:
            scope.set_var(name, jnp.asarray(data[name]))
    return meta["program"], meta["feed_names"], meta["fetch_names"]
