"""Model persistence (reference: python/paddle/fluid/io.py — save_params
:372, save_persistables :597, load_persistables :902, save_inference_model
:1093, load_inference_model :1303, unified fluid.save/load :1598/:1662).

TPU-native storage: parameters are jax Arrays in the Scope; serialization is
one .npz per directory (save_params/persistables) or a single pickled
payload (save/load), fetched through a single host sync. The reference runs
generated save/load *ops* through the Executor; here persistence is pure
host-side IO — there is nothing device-specific about a checkpoint.

Durability contract (the reference's fault-tolerant save/load_check_point
discipline, generalized to every writer here):

* every file lands via write-to-temp + flush + fsync + ``os.replace`` (and
  a best-effort directory fsync), so a crash mid-save leaves either the old
  complete file or a stray ``*.tmp.*`` — never a torn checkpoint under the
  real name;
* each payload gets a sibling ``manifest.json`` recording per-array CRC32 +
  shape + dtype; load paths verify BEFORE mutating the scope and raise
  :class:`~paddle_tpu.errors.CheckpointCorruptionError` on any mismatch or
  undecodable container (pre-manifest checkpoints still load, container
  errors are still typed);
* ``fault_point("io.save")`` / ``fault_point("io.load")`` seams let the
  resilience fault registry chaos-test every caller;
* the writers are ENOSPC-safe (the storage fault domain, PR 19): an
  optional ``estimated_size=`` preflights the target volume's free bytes
  before any byte is written, ``ENOSPC``/``EDQUOT`` from the filesystem
  maps to the typed :class:`~paddle_tpu.errors.StorageExhaustedError`
  (retryable after GC — see ``resilience/storage.py``), the
  ``fault_point("fs.write")`` seam fires after the temp file exists so
  injected disk-full always exercises the unlink path, and
  :func:`sweep_stale_tmp` gives every durable root a startup sweep for
  ``*.tmp.*`` residue of crashed writers.
"""

from __future__ import annotations

import errno as _errno
import json
import os
import pickle
import tempfile
import zlib

import numpy as np

from .errors import CheckpointCorruptionError, StorageExhaustedError
from .framework.program import Parameter, Program, default_main_program
from .framework.scope import global_scope
from .resilience.faults import fault_point

__all__ = [
    "save_params",
    "save_persistables",
    "load_params",
    "load_persistables",
    "save",
    "load",
    "save_inference_model",
    "load_inference_model",
    "prune",
    "verify_checkpoint_dir",
    "snapshot_persistables",
    "save_arrays",
    "read_persistables",
    "apply_persistables",
    "merge_checkpoint_arrays",
    "sweep_stale_tmp",
]

MANIFEST_NAME = "manifest.json"

#: npz-key suffixes of one row-level delta entry: ``<var>@@rows`` holds
#: only the dim-0 rows that changed since the chain's previous save,
#: ``<var>@@ridx`` their indices into the full array. Written by the
#: async checkpointer's tiered-delta path (fleet/collective.py) when a
#: row oracle — e.g. the embedding cache's write-back tick — can name the
#: dirty rows; :func:`merge_checkpoint_arrays` scatters them back.
ROW_VAL_MARK = "@@rows"
ROW_IDX_MARK = "@@ridx"


# -- durable write/verify helpers -------------------------------------------
def _fsync_dir(path):
    """Best-effort directory fsync so the rename itself is durable (POSIX;
    silently skipped where directories cannot be opened)."""
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


#: Preflight knobs: ``PADDLE_TPU_STORAGE_PREFLIGHT=0`` disables the
#: free-space check; the slack keeps a near-full volume from being filled
#: to its very last byte by a "fitting" payload (manifests, commit
#: records, and sibling writers need room too).
PREFLIGHT_ENV = "PADDLE_TPU_STORAGE_PREFLIGHT"
PREFLIGHT_SLACK_ENV = "PADDLE_TPU_STORAGE_PREFLIGHT_SLACK"
_DEFAULT_PREFLIGHT_SLACK = 1 << 20  # 1 MiB


def _free_bytes(dirname):
    """Free bytes on `dirname`'s volume — through the storage fault
    domain when a monitor with a byte-budgeted root covers the path
    (deterministic tests/CI fill a BUDGET, not the real disk), else a
    plain statvfs. None when unknowable."""
    try:
        from .resilience import storage as _storage

        return _storage.free_bytes(dirname)
    except Exception:
        try:
            st = os.statvfs(dirname)
            return st.f_bavail * st.f_frsize
        except (OSError, AttributeError):
            return None


def _storage_preflight(dirname, estimated_size):
    if os.environ.get(PREFLIGHT_ENV, "1").lower() in ("0", "false", "off"):
        return
    free = _free_bytes(dirname)
    if free is None:
        return
    try:
        slack = int(os.environ.get(
            PREFLIGHT_SLACK_ENV, _DEFAULT_PREFLIGHT_SLACK))
    except ValueError:
        slack = _DEFAULT_PREFLIGHT_SLACK
    if int(estimated_size) + slack > free:
        from . import observability as _obs

        _obs.add("storage.preflight_rejects")
        raise StorageExhaustedError(
            f"durable write into {dirname!r} refused by preflight: "
            f"~{int(estimated_size)} byte payload (+{slack} slack) vs "
            f"{free} free bytes — run retention GC (or free space) and "
            "retry"
        )


def _map_storage_error(exc, path):
    """OSError carrying ENOSPC/EDQUOT -> typed StorageExhaustedError
    (anything else passes through unchanged). The temp file is already
    unlinked by the time this runs — a full disk never keeps the garbage
    that filled it."""
    if isinstance(exc, StorageExhaustedError):
        return exc
    if isinstance(exc, OSError) and exc.errno in (
        _errno.ENOSPC, getattr(_errno, "EDQUOT", _errno.ENOSPC)
    ):
        from . import observability as _obs

        _obs.add("storage.enospc_errors")
        return StorageExhaustedError(
            f"durable write of {path!r} hit "
            f"{_errno.errorcode.get(exc.errno, exc.errno)}: {exc} — "
            "retryable after retention GC frees space"
        )
    return None


def _atomic_write(path, write_fn, estimated_size=None):
    """Run `write_fn(file_obj)` against a temp file in `path`'s directory,
    fsync it, and publish with os.replace — the torn-write guarantee.
    With `estimated_size` the write preflights the volume's free bytes
    and refuses (typed) before creating anything; an ENOSPC/EDQUOT from
    the filesystem mid-write surfaces as the same typed
    :class:`StorageExhaustedError`, temp already unlinked."""
    dirname = os.path.dirname(os.path.abspath(path))
    if estimated_size is not None:
        _storage_preflight(dirname, estimated_size)
    fd, tmp = tempfile.mkstemp(
        dir=dirname, prefix=os.path.basename(path) + ".tmp."
    )
    try:
        with os.fdopen(fd, "wb") as f:
            # the storage chaos seam: AFTER the temp exists, BEFORE any
            # payload byte — every fired kind walks the unlink path
            fault_point("fs.write")
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(dirname)
    except BaseException as e:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        mapped = _map_storage_error(e, path)
        if mapped is not None and mapped is not e:
            raise mapped from e
        raise


def sweep_stale_tmp(dirname, prefix=None, recursive=False):
    """Unlink stale ``*.tmp.*`` residue of crashed atomic writers under
    `dirname` (every mkstemp here and in the observability writers names
    its temp ``<target>.tmp.<rand>``). `prefix` restricts the sweep to
    one writer's files — multi-writer roots (a telemetry dir shared by
    ranks) must only sweep names the restarting process owns, since a
    LIVE sibling may be mid-publish. Returns the bytes reclaimed and
    counts ``storage.stale_tmp_swept``. Never raises."""
    freed = 0
    swept = 0
    try:
        walker = (
            os.walk(dirname) if recursive
            else ((dirname, (), os.listdir(dirname)),)
        )
        for root, _dirs, files in walker:
            for name in files:
                if ".tmp." not in name:
                    continue
                if prefix is not None and not name.startswith(prefix):
                    continue
                p = os.path.join(root, name)
                try:
                    freed += os.path.getsize(p)
                    os.unlink(p)
                    swept += 1
                except OSError:
                    continue
    except OSError:
        return 0
    if swept:
        from . import observability as _obs

        _obs.add("storage.stale_tmp_swept", swept)
        _obs.add("storage.stale_tmp_bytes", freed)
    return freed


def _private_host_copy(val):
    """Host ndarray of `val` guaranteed not to alias caller-visible
    memory — the snapshot-immutability contract shared by every staging
    path (replicated payload, per-rank shard, aux). np.asarray of a jax
    array already materializes a fresh host buffer unless it returns a
    zero-copy view; numpy inputs come back as themselves; both aliasing
    shapes get an explicit copy."""
    arr = np.asarray(val)
    if arr is val or getattr(arr, "base", None) is not None:
        arr = arr.copy()
    return arr


def _array_entry(arr):
    a = np.asarray(arr)
    return {
        "crc32": zlib.crc32(np.ascontiguousarray(a).tobytes()) & 0xFFFFFFFF,
        "shape": list(a.shape),
        "dtype": str(a.dtype),
    }


def _write_manifest(path, payload_file, arrays):
    manifest = {
        "format": 1,
        "file": os.path.basename(payload_file),
        "arrays": {name: _array_entry(a) for name, a in arrays.items()},
    }
    _atomic_write(
        path, lambda f: f.write(json.dumps(manifest, indent=1).encode())
    )


def _read_manifest(path):
    """Manifest dict, or None when absent (pre-durability checkpoint)."""
    if not os.path.exists(path):
        return None
    try:
        with open(path, "rb") as f:
            return json.loads(f.read().decode())
    except (OSError, ValueError) as e:
        raise CheckpointCorruptionError(
            f"unreadable checkpoint manifest {path!r}: {e}"
        ) from e


def _verify_arrays(arrays, manifest, origin):
    if manifest is None:
        return
    want = manifest.get("arrays", {})
    missing = sorted(set(want) - set(arrays))
    if missing:
        raise CheckpointCorruptionError(
            f"checkpoint {origin!r} is missing arrays {missing} listed in "
            "its manifest"
        )
    for name, entry in want.items():
        got = _array_entry(arrays[name])
        for field in ("shape", "dtype", "crc32"):
            if got[field] != entry[field]:
                raise CheckpointCorruptionError(
                    f"checkpoint {origin!r} array {name!r}: {field} mismatch "
                    f"(manifest {entry[field]!r}, file {got[field]!r})"
                )


def _load_npz_verified(path, manifest_path=None):
    """Read every array of an .npz into host memory and verify it against
    the sibling manifest; all corruption surfaces as the typed error and
    nothing is returned partially."""
    manifest = _read_manifest(
        manifest_path
        if manifest_path is not None
        else os.path.join(os.path.dirname(path), MANIFEST_NAME)
    )
    if manifest is not None and manifest.get("file") != os.path.basename(path):
        # the dir-level manifest describes a different payload (e.g.
        # save_params + save_persistables into one dir under two
        # filenames); it cannot vouch for this one
        manifest = None
    try:
        with np.load(path, allow_pickle=False) as data:
            arrays = {name: data[name] for name in data.files}
    except FileNotFoundError:
        if manifest is not None:
            raise CheckpointCorruptionError(
                f"checkpoint payload {path!r} is missing but its manifest "
                "exists (torn publish)"
            ) from None
        raise
    except Exception as e:  # zipfile.BadZipFile, zlib.error, OSError, ...
        raise CheckpointCorruptionError(
            f"undecodable checkpoint payload {path!r}: {e}"
        ) from e
    _verify_arrays(arrays, manifest, path)
    return arrays


def verify_checkpoint_dir(dirname, filename=None):
    """Full readback verification of a persistables checkpoint dir (payload
    decodes, every manifest array present with matching shape/dtype/CRC)
    WITHOUT touching any scope. Raises CheckpointCorruptionError on any
    defect — `Fleet.save_check_point` runs this against the checkpoint it
    just published before rotating predecessors away, so a bad publish can
    never leave zero loadable checkpoints behind."""
    path = os.path.join(dirname, filename or "__params__.npz")
    _load_npz_verified(path)


def _collect(program, scope, predicate, exclude=frozenset(), progress=None,
             copy=False, reuse_cache=None):
    """`progress`: zero-arg callable invoked once per collected var — the
    sync checkpoint path threads a heartbeat touch through it so a save
    big enough to span a watchdog timeout still reads as alive.
    `copy`: force a private host buffer even for numpy-backed scope values
    (the snapshot stage's immutability contract; jax arrays already
    materialize a fresh host copy under np.asarray).
    `reuse_cache`: caller-owned ``{name: (scope value, host copy)}`` map;
    a var whose scope value is still the IDENTICAL object as at the last
    snapshot reuses that host copy instead of re-copying — sound because
    the framework replaces values via ``scope.set_var`` (jax arrays are
    immutable) rather than mutating them in place, and it makes repeated
    snapshots O(changed bytes): untouched cold state (sharded embedding
    tiers, frozen towers) costs nothing per save."""
    out = {}
    skipped = []
    for var in program.list_vars():
        if not predicate(var) or var.name in exclude:
            continue
        if progress is not None:
            progress()
        val = scope.find_var(var.name)
        if val is None:
            continue
        if reuse_cache is not None:
            ent = reuse_cache.get(var.name)
            if ent is not None and ent[0] is val:
                out[var.name] = ent[1]
                continue
        if not _is_fully_addressable(val):
            # multi-process array: a REPLICATED value is recoverable from
            # the local replica; a genuinely cross-process-sharded value
            # (ZeRO weight-update state) cannot be materialized here and
            # must travel via Fleet.save_check_point(local_vars=...)
            rep = _local_full_replica(val)
            if rep is not None:
                out[var.name] = rep
            else:
                skipped.append(var.name)
            continue
        arr = _private_host_copy(val) if copy else np.asarray(val)
        out[var.name] = arr
        if reuse_cache is not None:
            reuse_cache[var.name] = (val, arr)
    if skipped:
        import warnings

        from . import observability as _obs

        _obs.add("io.nonaddressable_vars_skipped", len(skipped))
        warnings.warn(
            f"save skipped {len(skipped)} cross-process-sharded "
            f"persistable(s) {skipped[:5]}{'...' if len(skipped) > 5 else ''}"
            " this process cannot materialize; pass them as local_vars= to "
            "Fleet.save_check_point so each rank persists its own slice — "
            "otherwise they will NOT be restored on resume",
            stacklevel=3,
        )
    return out


def _is_fully_addressable(val):
    """Whether this process holds every shard of `val` (plain numpy and
    single-process jax arrays: yes; multi-host-sharded jax arrays: no)."""
    return bool(getattr(val, "is_fully_addressable", True))


def _local_full_replica(val):
    """np.ndarray of `val` if some addressable shard spans the WHOLE
    array (i.e. the value is replicated over the processes this one can
    see), else None."""
    for sh in val.addressable_shards:
        if all(
            isinstance(s, slice)
            and s.start in (None, 0) and s.stop in (None, int(dim))
            for s, dim in zip(sh.index, val.shape)
        ):
            return np.asarray(sh.data)
    return None


def _is_persistable(v):
    return bool(getattr(v, "persistable", False)) and not getattr(v, "is_data", False)


def _is_parameter(v):
    return isinstance(v, Parameter)


def save_params(executor, dirname, main_program=None, filename=None):
    _save_vars(dirname, main_program, _is_parameter, filename)


def save_persistables(executor, dirname, main_program=None, filename=None,
                      exclude=None, progress=None, compress=False):
    """`exclude`: var names to leave out of the payload — the per-rank
    checkpoint machinery passes its `local_vars` here so state that each
    rank persists in its own shard is not duplicated (or warned about)
    in the replicated payload."""
    _save_vars(dirname, main_program, _is_persistable, filename,
               exclude=exclude, progress=progress, compress=compress)


def _save_vars(dirname, main_program, predicate, filename, exclude=None,
               progress=None, compress=False):
    fault_point("io.save")
    program = main_program or default_main_program()
    scope = global_scope()
    arrays = _collect(program, scope, predicate,
                      exclude=frozenset(exclude or ()), progress=progress)
    save_arrays(dirname, arrays, filename=filename, compress=compress)


def snapshot_persistables(main_program=None, scope=None, exclude=None,
                          progress=None, reuse_cache=None):
    """The snapshot half of a save: device→host copies of every
    scope-resident persistable, returned as a private ``{name: ndarray}``
    staging dict — later training steps cannot alter it, so a background
    publisher can serialize/CRC/fsync it entirely off the step loop
    (the async checkpoint pipeline's only on-loop cost). With a
    `reuse_cache` (AsyncCheckpointer keeps one per pipeline), values the
    scope still holds by identity since the last snapshot are not
    re-copied — the steady-state snapshot stall is O(changed bytes)."""
    program = main_program or default_main_program()
    scope = scope if scope is not None else global_scope()
    return _collect(program, scope, _is_persistable,
                    exclude=frozenset(exclude or ()), progress=progress,
                    copy=True, reuse_cache=reuse_cache)


def save_arrays(dirname, arrays, filename=None, compress=False,
                manifest_name=None):
    """The serialize half of a save: write a pre-collected host payload as
    a durable CRC-manifested dir (temp+fsync+``os.replace``). `compress`
    swaps ``np.savez`` for ``np.savez_compressed`` (zlib DEFLATE inside
    the zip container); manifest CRCs cover the raw array bytes, so
    verification is compression-agnostic. Returns the payload path."""
    os.makedirs(dirname, exist_ok=True)
    path = os.path.join(dirname, filename or "__params__.npz")
    writer = np.savez_compressed if compress else np.savez
    # preflight estimate: raw payload bytes + per-member container
    # overhead — an upper bound for the compressed writer too, and the
    # bound is what ENOSPC-safety wants
    est = sum(int(np.asarray(a).nbytes) for a in arrays.values())
    est += 1024 * (len(arrays) + 1)
    _atomic_write(path, lambda f: writer(f, **arrays), estimated_size=est)
    _write_manifest(os.path.join(dirname, manifest_name or MANIFEST_NAME),
                    path, arrays)
    return path


def read_persistables(dirname, filename=None):
    """Verified host arrays of a checkpoint dir — no scope mutation (the
    read half of :func:`load_persistables`; delta-chain loads read every
    chain link this way, merge, then apply once)."""
    fault_point("io.load")
    path = os.path.join(dirname, filename or "__params__.npz")
    return _load_npz_verified(path)


def apply_persistables(arrays, main_program=None, scope=None):
    """Write pre-verified host arrays into the scope (the apply half of
    :func:`load_persistables`), then re-derive any ZeRO shards."""
    import jax.numpy as jnp

    program = main_program or default_main_program()
    scope = scope if scope is not None else global_scope()
    for name, arr in arrays.items():
        scope.set_var(name, jnp.asarray(arr))
    _rederive_zero_shards(program, scope, set(arrays))


def merge_checkpoint_arrays(acc, arrays, origin):
    """Overlay one checkpoint payload onto the accumulated chain state
    (delta-chain reconstruction, oldest→newest): plain names replace
    outright; a row-delta pair (``<name>@@rows`` + ``<name>@@ridx``)
    scatters the changed rows onto the base value, which must already be
    in `acc` from an earlier link. Returns `acc`."""
    for name in arrays:
        if name.endswith(ROW_IDX_MARK):
            continue
        arr = arrays[name]
        if name.endswith(ROW_VAL_MARK):
            base_name = name[: -len(ROW_VAL_MARK)]
            idx = arrays.get(base_name + ROW_IDX_MARK)
            if idx is None:
                raise CheckpointCorruptionError(
                    f"delta payload {origin!r}: {name!r} has no matching "
                    f"{base_name + ROW_IDX_MARK!r} index array"
                )
            base = acc.get(base_name)
            if base is None:
                raise CheckpointCorruptionError(
                    f"delta payload {origin!r}: row delta for {base_name!r} "
                    "has no base array earlier in the chain (was the base "
                    "checkpoint rotated away?)"
                )
            base = np.array(base, copy=True)
            base[np.asarray(idx, dtype=np.int64)] = arr
            acc[base_name] = base
        else:
            acc[name] = arr
    return acc


def load_params(executor, dirname, main_program=None, filename=None):
    _load_vars(dirname, main_program, filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    _load_vars(dirname, main_program, filename)


def _load_vars(dirname, main_program, filename):
    # verify the WHOLE payload before the first scope write: a corrupt
    # checkpoint must never leave the scope half-overwritten
    arrays = read_persistables(dirname, filename)
    apply_persistables(arrays, main_program)


def _rederive_zero_shards(program, scope, loaded_names):
    """Warm-start bridge for the sharded weight update: when a value is
    loaded from a NON-sharded layout (plain params, or a replicated-era
    checkpoint's full moments) but the program's update runs on a
    ``<name>@ZERO_SHARD`` flat master (parallel/transpiler.py), the shard
    still holds its startup init — the first ``zero_all_gather`` would
    silently revert the loaded weights. Re-derive such shards from the
    freshly loaded value. A shard that was itself in the payload (saved
    from a sharded run) is authoritative and left alone."""
    import jax.numpy as jnp

    shards_of = {
        v._zero_shard_of: (sname, v)
        for sname, v in program.global_block.vars.items()
        if getattr(v, "_zero_shard_of", None) is not None
    }
    rederived = 0
    for name in loaded_names & set(shards_of):
        sname, v = shards_of[name]
        if sname in loaded_names:
            continue
        loaded = scope.find_var(name)
        if loaded is None or not _is_fully_addressable(loaded):
            continue
        full = np.asarray(loaded).reshape(-1)
        pad = int(v.shape[0])
        flat = np.zeros(pad, dtype=full.dtype)
        flat[: full.size] = full
        scope.set_var(sname, jnp.asarray(flat))
        rederived += 1
        if not program.global_block.has_var(name):
            # a full-size accumulator from a replicated-era checkpoint:
            # its program var was deleted by the sharded transpile, so
            # after the copy into the shard nothing ever reads it — drop
            # it instead of stranding 2x-params of host memory
            scope.erase(name)
    if rederived:
        from . import observability as _obs

        _obs.add("collective.zero_shards_rederived", rederived)


def save(program, model_path):
    """fluid.save parity (io.py:1598): one combined file with params +
    optimizer state (all persistables), plus the serialized program.
    All three files (.pdparams/.pdmodel/.manifest.json) publish atomically."""
    fault_point("io.save")
    scope = global_scope()
    payload = {
        "params": _collect(program, scope, _is_parameter),
        "opt": _collect(
            program, scope, lambda v: _is_persistable(v) and not _is_parameter(v)
        ),
    }
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    _atomic_write(
        model_path + ".pdparams", lambda f: pickle.dump(payload, f, protocol=4)
    )
    _atomic_write(
        model_path + ".pdmodel", lambda f: pickle.dump(program, f, protocol=4)
    )
    _write_manifest(
        model_path + ".manifest.json",
        model_path + ".pdparams",
        {
            **{f"params/{k}": v for k, v in payload["params"].items()},
            **{f"opt/{k}": v for k, v in payload["opt"].items()},
        },
    )


def load(program, model_path, var_list=None):
    """fluid.load parity (io.py:1662). Verifies the payload against its
    manifest (when present) before any scope mutation."""
    import jax.numpy as jnp

    fault_point("io.load")
    scope = global_scope()
    path = model_path + ".pdparams"
    manifest = _read_manifest(model_path + ".manifest.json")
    try:
        with open(path, "rb") as f:
            payload = pickle.load(f)
    except FileNotFoundError:
        if manifest is not None:
            raise CheckpointCorruptionError(
                f"checkpoint payload {path!r} is missing but its manifest "
                "exists (torn publish)"
            ) from None
        raise
    except Exception as e:  # truncated/garbled pickle: EOFError, Unpickling..
        raise CheckpointCorruptionError(
            f"undecodable checkpoint payload {path!r}: {e}"
        ) from e
    flat = {
        f"{group}/{name}": arr
        for group in ("params", "opt")
        for name, arr in payload.get(group, {}).items()
    }
    _verify_arrays(flat, manifest, path)
    wanted = {v.name for v in var_list} if var_list else None
    for group in ("params", "opt"):
        for name, arr in payload.get(group, {}).items():
            if wanted is None or name in wanted:
                scope.set_var(name, jnp.asarray(arr))


def prune(program, targets, feeds=()):
    """Backward-slice the program to ops needed for `targets`
    (reference framework/prune.cc + Executor prune-by-fetch)."""
    target_names = {t.name if hasattr(t, "name") else str(t) for t in targets}
    feed_names = set(feeds)
    block = program.global_block
    needed = set(target_names)
    keep = []
    for op in reversed(block.ops):
        outs = [n for n in op.output_names() if n]
        if any(n in needed for n in outs):
            # an op whose only outputs are feeds exists to *produce* the feed
            # (e.g. a reader); the caller will supply it, so cut it out
            if outs and all(n in feed_names for n in outs):
                continue
            keep.append(op)
            # the slice stops at feed variables: their producers are replaced
            # by the runtime feed, exactly like the reference's prune.cc
            needed.update(
                n for n in op.input_names() if n and n not in feed_names
            )
    keep.reverse()

    pruned = program.clone()
    pblock = pruned.global_block
    keep_ids = {id(op) for op in keep}
    # ops were deep-copied in clone; map by position
    pblock.ops = [
        pop
        for op, pop in zip(block.ops, pblock.ops)
        if id(op) in keep_ids
    ]
    pruned._bump()
    return pruned


def save_inference_model(
    dirname,
    feeded_var_names,
    target_vars,
    executor=None,
    main_program=None,
    model_filename=None,
    params_filename=None,
):
    """Prune to the feed→fetch subgraph in test mode and save program+params
    (reference io.py:1093)."""
    program = main_program or default_main_program()
    test_prog = program.clone(for_test=True)
    # names survive clone, so prune on the cloned program
    targets = [
        test_prog.global_block.var(v.name if hasattr(v, "name") else str(v))
        for v in target_vars
    ]
    pruned = prune(test_prog, targets, feeds=feeded_var_names)
    pruned._is_inference = True
    fault_point("io.save")
    os.makedirs(dirname, exist_ok=True)
    meta = {
        "program": pruned,
        "feed_names": list(feeded_var_names),
        "fetch_names": [t.name for t in targets],
    }
    _atomic_write(
        os.path.join(dirname, model_filename or "__model__"),
        lambda f: pickle.dump(meta, f, protocol=4),
    )
    scope = global_scope()
    arrays = _collect(pruned, scope, _is_persistable)
    params_path = os.path.join(dirname, params_filename or "__params__.npz")
    _atomic_write(params_path, lambda f: np.savez(f, **arrays))
    _write_manifest(os.path.join(dirname, MANIFEST_NAME), params_path, arrays)
    return [t.name for t in targets]


def load_inference_model(dirname, executor=None, model_filename=None,
                         params_filename=None):
    """Returns (program, feed_names, fetch_names); params land in the global
    scope (reference io.py:1303)."""
    import jax.numpy as jnp

    fault_point("io.load")
    model_path = os.path.join(dirname, model_filename or "__model__")
    try:
        with open(model_path, "rb") as f:
            meta = pickle.load(f)
    except FileNotFoundError:
        raise
    except Exception as e:
        raise CheckpointCorruptionError(
            f"undecodable inference model {model_path!r}: {e}"
        ) from e
    scope = global_scope()
    path = os.path.join(dirname, params_filename or "__params__.npz")
    arrays = _load_npz_verified(path)
    for name, arr in arrays.items():
        scope.set_var(name, jnp.asarray(arr))
    program = meta["program"]
    # a loaded inference model is a frozen graph: the executor traces it
    # in test mode and the static verifier rejects surviving training ops
    # (serving freeze contract; older exports predate the flag)
    program._is_inference = True
    return program, meta["feed_names"], meta["fetch_names"]
