"""Parameter initializers, emitted as startup-program ops.

Reference parity: python/paddle/fluid/initializer.py (Constant, Uniform,
Normal, TruncatedNormal, Xavier, MSRA/Kaiming, Bilinear, NumpyArrayInitializer)
— each appends one init op (fill_constant / gaussian_random / uniform_random)
to the startup block, exactly the reference's pattern.
"""

from __future__ import annotations

import math

import numpy as np


_np_rng = np.random.RandomState(90210)


class Initializer:
    def __call__(self, block, name, shape, dtype):
        raise NotImplementedError

    def numpy_init(self, shape, dtype):
        """Eager-mode init: materialize the value host-side (dygraph Layers
        create parameters immediately instead of emitting startup ops)."""
        raise NotImplementedError

    def _rng(self):
        return np.random.RandomState(self.seed) if getattr(self, "seed", 0) else _np_rng


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, block, name, shape, dtype):
        block.append_op(
            "fill_constant",
            {},
            {"Out": [name]},
            {"shape": list(shape), "dtype": dtype, "value": float(self.value)},
        )

    def numpy_init(self, shape, dtype):
        return np.full(shape, self.value, dtype=dtype)


class Normal(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, block, name, shape, dtype):
        block.append_op(
            "gaussian_random",
            {},
            {"Out": [name]},
            {
                "shape": list(shape),
                "dtype": dtype,
                "mean": self.loc,
                "std": self.scale,
                "seed": self.seed,
            },
        )

    def numpy_init(self, shape, dtype):
        return self._rng().normal(self.loc, self.scale, shape).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, block, name, shape, dtype):
        block.append_op(
            "truncated_gaussian_random",
            {},
            {"Out": [name]},
            {
                "shape": list(shape),
                "dtype": dtype,
                "mean": self.loc,
                "std": self.scale,
                "seed": self.seed,
            },
        )

    def numpy_init(self, shape, dtype):
        r = self._rng()
        vals = r.normal(self.loc, self.scale, shape)
        lo, hi = self.loc - 2 * self.scale, self.loc + 2 * self.scale
        bad = (vals < lo) | (vals > hi)
        while bad.any():
            vals[bad] = r.normal(self.loc, self.scale, bad.sum())
            bad = (vals < lo) | (vals > hi)
        return vals.astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, block, name, shape, dtype):
        block.append_op(
            "uniform_random",
            {},
            {"Out": [name]},
            {
                "shape": list(shape),
                "dtype": dtype,
                "min": self.low,
                "max": self.high,
                "seed": self.seed,
            },
        )

    def numpy_init(self, shape, dtype):
        return self._rng().uniform(self.low, self.high, shape).astype(dtype)


def _fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    fan_in = shape[1] * receptive if len(shape) > 2 else shape[0]
    fan_out = shape[0] * receptive if len(shape) > 2 else shape[1]
    return fan_in, fan_out


class Xavier(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform = uniform
        self.fan_in, self.fan_out, self.seed = fan_in, fan_out, seed

    def __call__(self, block, name, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            Uniform(-limit, limit, self.seed)(block, name, shape, dtype)
        else:
            std = math.sqrt(2.0 / (fi + fo))
            Normal(0.0, std, self.seed)(block, name, shape, dtype)

    def numpy_init(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            return Uniform(-limit, limit, self.seed).numpy_init(shape, dtype)
        return Normal(0.0, math.sqrt(2.0 / (fi + fo)), self.seed).numpy_init(
            shape, dtype
        )


class MSRA(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, block, name, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            Uniform(-limit, limit, self.seed)(block, name, shape, dtype)
        else:
            std = math.sqrt(2.0 / fi)
            Normal(0.0, std, self.seed)(block, name, shape, dtype)

    def numpy_init(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            return Uniform(-limit, limit, self.seed).numpy_init(shape, dtype)
        return Normal(0.0, math.sqrt(2.0 / fi), self.seed).numpy_init(
            shape, dtype
        )


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, block, name, shape, dtype):
        block.append_op(
            "assign_value",
            {},
            {"Out": [name]},
            {
                "shape": list(self.value.shape),
                "dtype": dtype,
                "values": self.value.reshape(-1).tolist(),
            },
        )

    def numpy_init(self, shape, dtype):
        return self.value.astype(dtype)


ConstantInitializer = Constant
NormalInitializer = Normal
UniformInitializer = Uniform
XavierInitializer = Xavier
MSRAInitializer = MSRA
TruncatedNormalInitializer = TruncatedNormal
