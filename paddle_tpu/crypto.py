"""Model-file encryption (reference paddle/fluid/framework/io/crypto/:
Cipher/AESCipher in cipher.h + aes_cipher.cc, key helpers in
cipher_utils.cc, pybind surface in pybind/crypto.cc).

Scheme: AES-CTR (native C++ core, native/crypto.cpp; pure-Python AES
fallback when no toolchain) with encrypt-then-MAC HMAC-SHA256 truncated to
16 bytes. The reference uses cryptopp AES-GCM; this image vendors no crypto
library, so CTR+HMAC provides the same confidentiality+integrity contract
from first principles — wire format: iv(16) || ciphertext || tag(16).
Both AES cores are validated against the FIPS-197 and NIST SP 800-38A
known-answer vectors (tests/test_crypto.py).
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import os

from . import native

_SBOX = None


def _sbox():
    """Compute the AES S-box (multiplicative inverse in GF(2^8) + affine
    transform) — table-free construction for the fallback core."""
    global _SBOX
    if _SBOX is not None:
        return _SBOX
    # build inverse table via exp/log over generator 3
    exp, log = [0] * 510, [0] * 256
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x ^= (x << 1) ^ (0x11B if x & 0x80 else 0)
        x &= 0xFF
    for i in range(255, 510):
        exp[i] = exp[i - 255]
    sbox = [0] * 256
    for v in range(256):
        inv = 0 if v == 0 else exp[255 - log[v]]
        b = inv
        r = inv
        for _ in range(4):
            b = ((b << 1) | (b >> 7)) & 0xFF
            r ^= b
        sbox[v] = r ^ 0x63
    _SBOX = sbox
    return sbox


def _xtime(b):
    return ((b << 1) ^ 0x1B) & 0xFF if b & 0x80 else b << 1


def _expand_key(key):
    sbox = _sbox()
    nk = len(key) // 4
    rounds = nk + 6
    w = [list(key[4 * i:4 * i + 4]) for i in range(nk)]
    rcon = 1
    for i in range(nk, 4 * (rounds + 1)):
        t = list(w[i - 1])
        if i % nk == 0:
            t = [sbox[t[1]] ^ rcon, sbox[t[2]], sbox[t[3]], sbox[t[0]]]
            rcon = _xtime(rcon)
        elif nk > 6 and i % nk == 4:
            t = [sbox[b] for b in t]
        w.append([a ^ b for a, b in zip(w[i - nk], t)])
    return w, rounds


def _py_block_encrypt(key, block, _sched=None):
    sbox = _sbox()
    w, rounds = _sched if _sched is not None else _expand_key(key)
    s = [block[i] ^ w[i // 4][i % 4] for i in range(16)]
    for rnd in range(1, rounds + 1):
        t = [0] * 16
        for c in range(4):
            for r in range(4):
                t[4 * c + r] = sbox[s[4 * ((c + r) & 3) + r]]
        if rnd < rounds:
            s = [0] * 16
            for c in range(4):
                a = t[4 * c:4 * c + 4]
                x = a[0] ^ a[1] ^ a[2] ^ a[3]
                for r in range(4):
                    s[4 * c + r] = a[r] ^ x ^ _xtime(a[r] ^ a[(r + 1) & 3])
        else:
            s = t
        rk = w[4 * rnd:4 * rnd + 4]
        s = [s[i] ^ rk[i // 4][i % 4] for i in range(16)]
    return bytes(s)


def _py_ctr_crypt(key, iv, data):
    out = bytearray(data)
    ctr = int.from_bytes(iv, "big")
    sched = _expand_key(key)  # hoisted: dominates per-block cost otherwise
    for off in range(0, len(data), 16):
        ks = _py_block_encrypt(key, ctr.to_bytes(16, "big"), _sched=sched)
        ctr = (ctr + 1) % (1 << 128)
        for i in range(min(16, len(data) - off)):
            out[off + i] ^= ks[i]
    return bytes(out)


def _ctr_crypt(key, iv, data):
    got = native.aes_ctr_crypt(key, iv, data)
    return got if got is not None else _py_ctr_crypt(key, iv, data)


class CipherUtils:
    """Key management (reference cipher_utils.h:25)."""

    AES_DEFAULT_IV_SIZE = 16
    AES_DEFAULT_TAG_SIZE = 16

    @staticmethod
    def gen_key(length):
        """length in bits (reference GenKey semantics)."""
        if length % 8:
            raise ValueError("key length must be a multiple of 8 bits")
        return os.urandom(length // 8)

    @staticmethod
    def gen_key_to_file(length, filename):
        key = CipherUtils.gen_key(length)
        with open(filename, "wb") as f:
            f.write(key)
        return key

    @staticmethod
    def read_key_from_file(filename):
        with open(filename, "rb") as f:
            return f.read()


class Cipher:
    def encrypt(self, plaintext, key):
        raise NotImplementedError

    def decrypt(self, ciphertext, key):
        raise NotImplementedError

    def encrypt_to_file(self, plaintext, key, filename):
        with open(filename, "wb") as f:
            f.write(self.encrypt(plaintext, key))

    def decrypt_from_file(self, key, filename):
        with open(filename, "rb") as f:
            return self.decrypt(f.read(), key)


class AESCipher(Cipher):
    """AES-CTR + HMAC-SHA256(16B tag), iv || ct || tag on the wire."""

    def __init__(self, iv_size=16, tag_size=16):
        if iv_size != 16:
            raise ValueError("AES-CTR iv must be 16 bytes")
        tag_size = int(tag_size)
        if not 12 <= tag_size <= 32:
            # <12 bytes lets a config silently weaken forgery resistance
            # (1 byte = 2^-8); >32 exceeds the HMAC-SHA256 digest and
            # could never verify (advisor r2)
            raise ValueError("tag_size must be in [12, 32] bytes")
        self.iv_size = iv_size
        self.tag_size = tag_size

    def _mac_key(self, key):
        return hashlib.sha256(b"paddle_tpu-mac|" + key).digest()

    def _check_key(self, key):
        if not isinstance(key, (bytes, bytearray)):
            raise TypeError("key must be bytes")
        if len(key) not in (16, 24, 32):
            raise ValueError("AES key must be 16/24/32 bytes")
        return bytes(key)

    def encrypt(self, plaintext, key):
        key = self._check_key(key)
        if isinstance(plaintext, str):
            plaintext = plaintext.encode()
        iv = os.urandom(self.iv_size)
        ct = _ctr_crypt(key, iv, plaintext)
        tag = _hmac.new(
            self._mac_key(key), iv + ct, hashlib.sha256
        ).digest()[: self.tag_size]
        return iv + ct + tag

    def decrypt(self, ciphertext, key):
        key = self._check_key(key)
        n = len(ciphertext)
        if n < self.iv_size + self.tag_size:
            raise ValueError("ciphertext too short")
        iv = ciphertext[: self.iv_size]
        ct = ciphertext[self.iv_size: n - self.tag_size]
        tag = ciphertext[n - self.tag_size:]
        want = _hmac.new(
            self._mac_key(key), iv + ct, hashlib.sha256
        ).digest()[: self.tag_size]
        if not _hmac.compare_digest(tag, want):
            raise ValueError(
                "model file authentication failed: wrong key or corrupted "
                "ciphertext"
            )
        return _ctr_crypt(key, iv, ct)


class CipherFactory:
    """create_cipher(config_file) (reference cipher.h:44). The config is a
    properties file: `cipher_name=AES_CTR_NoPadding`, optional
    `iv_size`/`tag_size` in bytes; no file -> defaults."""

    @staticmethod
    def create_cipher(config_file=None):
        cfg = {}
        if config_file:
            with open(config_file) as f:
                for line in f:
                    line = line.strip()
                    if line and not line.startswith("#") and "=" in line:
                        k, v = line.split("=", 1)
                        cfg[k.strip()] = v.strip()
        name = cfg.get("cipher_name", "AES_CTR_NoPadding")
        if not name.startswith("AES_CTR"):
            # refuse e.g. the reference's AES_GCM_NoPadding(128) rather than
            # silently producing an incompatible CTR+HMAC file
            raise ValueError(f"unsupported cipher {name!r}")
        return AESCipher(
            iv_size=int(cfg.get("iv_size", 16)),
            tag_size=int(cfg.get("tag_size", 16)),
        )
