"""Structural (dataflow) verification of a Program block.

Mirrors the classification the executor performs in
``executor._analyze_block`` — inputs not produced earlier and not fed are
pulled from the Scope — but turns each way that pull can go wrong into a
named finding BEFORE the trace:

* use-before-def: a non-persistable, non-feed temp read before any op
  produces it. At run time this is a PreconditionNotMet from
  ``Executor._from_scope`` (or stale data from a previous program — worse).
* undeclared-var / undeclared-write: an op references a name with no
  Variable metadata anywhere in the block chain. The env-based emitter
  loop tolerates it, but shape inference, persistable write-back and
  sharding specs are all blind to such names.
* unknown-op: op type absent from the registry — the trace would raise
  UnimplementedError mid-compile; here it is caught with provenance.
* redefinition: ``Block.create_var``/``create_parameter`` silently
  overwrote an existing entry (recorded by program.py at build time).
* dead-op / unreachable-var: ops whose outputs can never reach a fetch or
  a persistable, and vars no op touches. XLA DCEs them, but they usually
  indicate a model-construction bug (e.g. a metric built and never
  fetched).
"""

from __future__ import annotations

from ..framework.registry import _REGISTRY
from .findings import (
    DEAD_OP,
    MISSING_FEED,
    REDEFINITION,
    TRAINING_OP_IN_INFERENCE,
    UNDECLARED_VAR,
    UNDECLARED_WRITE,
    UNKNOWN_OP,
    UNREACHABLE_VAR,
    USE_BEFORE_DEF,
    Finding,
    Severity,
    finding_for_op,
)

# Op types that must never survive in a frozen inference program
# (serving/freeze.py is the canonical producer of such programs; it marks
# them with ``program._is_inference``). Parameter-update ops mutate
# persistables, grad ops recompute backward work per request, and the AMP
# loss-scaling automaton corrupts its state when stepped outside training.
OPTIMIZER_UPDATE_OPS = frozenset({
    "sgd", "momentum", "lars_momentum", "adam", "adamw", "lamb", "adagrad",
    "decayed_adagrad", "adadelta", "rmsprop", "ftrl", "adamax", "dpsgd",
})
AMP_TRAINING_OPS = frozenset({
    "amp_check_finite_and_scale", "check_finite_and_unscale",
    "update_loss_scaling",
})


def is_training_only_op(op_type: str) -> bool:
    """True for ops with no business in a frozen inference graph:
    parameter updates, explicit grad kernels, the generic ``__vjp__``
    backward replay, and the AMP loss-scale automaton."""
    return (
        op_type in OPTIMIZER_UPDATE_OPS
        or op_type in AMP_TRAINING_OPS
        or op_type == "__vjp__"
        or op_type.endswith("_grad")
    )

# ops that are live regardless of dataflow (side effects / control
# structure); their sub-blocks are not part of the global-block dataflow
_SUB_BLOCK_ATTRS = (
    "sub_block", "true_block", "false_block", "stage_block", "stage_blocks",
)


def _sub_block_indices(op):
    out = []
    for a in _SUB_BLOCK_ATTRS:
        v = op.attr(a) if hasattr(op, "attr") else None
        if v is None:
            continue
        out.extend(v if isinstance(v, (list, tuple)) else [v])
    return out


def analyze_structural(program, feed_names=(), fetch_names=()):
    findings = []
    feed_names = set(feed_names or ())
    fetch_names = tuple(fetch_names or ())
    block = program.global_block

    # --- training-only ops in frozen inference programs -------------------
    # (only when the program is marked as an inference freeze — training
    # graphs legitimately carry these ops)
    if getattr(program, "_is_inference", False):
        for blk in program.blocks:
            for i, op in enumerate(blk.ops):
                if is_training_only_op(op.type):
                    findings.append(finding_for_op(
                        Severity.ERROR, TRAINING_OP_IN_INFERENCE,
                        f"training-only op {op.type!r} survived a freeze "
                        "into an inference program — it would mutate "
                        "parameters/loss-scale state or recompute backward "
                        "work per request; re-freeze from the training "
                        "graph (serving.freeze_program)",
                        op=op, op_index=i, block_idx=blk.idx,
                    ))

    # --- unknown ops + undeclared reads/writes, every block ---------------
    for blk in program.blocks:
        for i, op in enumerate(blk.ops):
            if op.type not in _REGISTRY:
                findings.append(finding_for_op(
                    Severity.ERROR, UNKNOWN_OP,
                    f"op type {op.type!r} is not registered; the trace "
                    "would raise UnimplementedError",
                    op=op, op_index=i, block_idx=blk.idx,
                ))
            for n in op.output_names():
                if n and blk._find_var_recursive(n) is None:
                    findings.append(finding_for_op(
                        Severity.WARNING, UNDECLARED_WRITE,
                        f"op writes to {n!r} which is not declared in any "
                        "reachable block; shape inference, persistable "
                        "write-back and sharding cannot see this name",
                        op=op, op_index=i, block_idx=blk.idx, names=(n,),
                    ))

    # --- use-before-def over the global block's execution order -----------
    produced = set()
    producer_index = {}
    for i, op in enumerate(block.ops):
        for n in op.output_names():
            if n and n not in producer_index:
                producer_index[n] = i
    for i, op in enumerate(block.ops):
        for n in op.input_names():
            if not n or n in produced or n in feed_names:
                continue
            v = block._find_var_recursive(n)
            if v is None:
                findings.append(finding_for_op(
                    Severity.ERROR, UNDECLARED_VAR,
                    f"op reads {n!r} which is not declared in any "
                    "reachable block",
                    op=op, op_index=i, names=(n,),
                ))
            elif v.persistable:
                pass  # legal: read from scope (params / optimizer state)
            elif v.is_data:
                # a feed var: legal unless an explicit feed set was given
                # and it is missing from it
                if feed_names:
                    findings.append(finding_for_op(
                        Severity.ERROR, MISSING_FEED,
                        f"data variable {n!r} is read but missing from the "
                        f"feed set {sorted(feed_names)}",
                        op=op, op_index=i, names=(n,),
                    ))
            else:
                later = producer_index.get(n)
                detail = (
                    f"; it is only produced later by op #{later}"
                    if later is not None and later > i
                    else "; no op in this block produces it"
                )
                findings.append(finding_for_op(
                    Severity.ERROR, USE_BEFORE_DEF,
                    f"op reads non-persistable temp {n!r} before any op "
                    f"produces it{detail} — at run time this is an "
                    "uninitialized-scope error",
                    op=op, op_index=i, names=(n,),
                ))
        produced.update(n for n in op.output_names() if n)

    # --- silent redefinitions recorded at build time ----------------------
    for blk in program.blocks:
        for ev in getattr(blk, "_redefinitions", ()):
            sev = Severity.WARNING if ev["spec_changed"] else Severity.INFO
            findings.append(Finding(
                severity=sev,
                category=REDEFINITION,
                message=(
                    f"variable {ev['name']!r} was silently redefined "
                    f"({ev['detail']}); the old Variable object is now "
                    "orphaned but ops may still reference it"
                ),
                block_idx=blk.idx,
                names=(ev["name"],),
                loc=ev.get("loc"),
            ))

    # --- dead ops / unreachable vars (global block, needs a fetch set) ----
    if fetch_names:
        live = set(fetch_names)
        live_ops = [False] * len(block.ops)
        for i in range(len(block.ops) - 1, -1, -1):
            op = block.ops[i]
            outs = [n for n in op.output_names() if n]
            is_live = (
                not outs  # pure side-effect op: keep
                or bool(_sub_block_indices(op))  # control flow: keep
                or any(n in live for n in outs)
            )
            if not is_live:
                for n in outs:
                    v = block._find_var_recursive(n)
                    if v is not None and v.persistable:
                        is_live = True  # state write-back
                        break
            if is_live:
                live_ops[i] = True
                live.update(n for n in op.input_names() if n)
        for i, (op, alive) in enumerate(zip(block.ops, live_ops)):
            if not alive:
                findings.append(finding_for_op(
                    Severity.INFO, DEAD_OP,
                    "op output feeds no fetch, persistable, or control "
                    "flow; XLA will DCE it — if it was meant to be "
                    "observed, add it to fetch_list",
                    op=op, op_index=i, names=tuple(op.output_names()),
                ))

    # vars no op in ANY block reads or writes (and that are neither
    # feeds, persistables, nor fetches): construction leftovers
    touched = set()
    for blk in program.blocks:
        for op in blk.ops:
            touched.update(op.input_names())
            touched.update(op.output_names())
    touched.update(fetch_names)
    for blk in program.blocks:
        for name, v in blk.vars.items():
            if name in touched or v.persistable or v.is_data:
                continue
            findings.append(Finding(
                severity=Severity.INFO,
                category=UNREACHABLE_VAR,
                message=(
                    f"variable {name!r} is declared but no op reads or "
                    "writes it"
                ),
                block_idx=blk.idx,
                names=(name,),
            ))
    return findings
