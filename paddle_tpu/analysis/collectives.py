"""Cross-rank collective-schedule lint: the build-time deadlock detector.

Under shard_map SPMD every rank runs ONE program, so collective sites are
normally rank-uniform by construction — except where the program encodes
*per-rank divergence*: ``pipeline_block`` dispatches a different stage
sub-block per rank via ``lax.switch(lax.axis_index("pp"))``, and ``cond``
branches may hide collectives behind a predicate that is not guaranteed
replicated. A collective present on one rank's path but not another's is
the classic mismatched-collective deadlock: every rank blocks on an ICI
exchange its peers never enter, and only a watchdog (PR 3) can kill the
pod 40 minutes later. This pass simulates the per-rank op streams and
rejects the mismatch at build time, with op provenance.

Simulation model:
* every collective-bearing op contributes one `Site` (kind, axis) to the
  stream — inner repetition counts (ring steps, microbatch ticks, scan
  trips) are rank-uniform, so one site per op suffices for comparison;
* ``pipeline_block``: per-rank stage sub-block (the ONLY rank-divergent
  construct in the IR), bracketed by the schedule's ppermute/psum;
  unbound axis = the sequential degrade, which runs every stage;
* ``pipeline_uniform``: one shared stage body — rank-uniform, still
  recursed for the axis checks;
* ``cond``/``conditional_block``: both branches are traced on every rank;
  branches whose collective streams disagree are flagged (the predicate
  cannot be proven replicated at build time);
* ``while``/``scan_block``/sub-blocks: body recursed once.

Axis checks ride the same walk: a collective whose axis the attached Mesh
does not name (or that hybrid mode leaves unbound) silently degrades to
identity — almost always a typo'd ``axis_name`` — and is flagged.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass

from .findings import (
    COLLECTIVE_BRANCH_DIVERGENCE,
    COLLECTIVE_DIVERGENCE,
    UNKNOWN_MESH_AXIS,
    Severity,
    finding_for_op,
)

# op type -> default axis_name (matching each emitter's op.attr default)
ATTR_AXIS_OPS = {
    "c_allreduce_sum": "dp",
    "c_allreduce_max": "dp",
    "c_allreduce_min": "dp",
    "c_allreduce_prod": "dp",
    "allreduce": "dp",
    "mp_allreduce_sum": "dp",
    "c_broadcast": "dp",
    "c_allgather": "dp",
    "c_reducescatter": "dp",
    "alltoall": "dp",
    "collective_permute": "dp",
    "barrier": "dp",
    "c_allreduce_any": "dp",
    "zero_reduce_scatter": "dp",
    "zero_bucket_reduce_scatter": "dp",
    "c_bucket_allreduce_sum": "dp",
    "zero_all_gather": "dp",
    "dgc_momentum_step": "dp",
    "distributed_lookup_table": "ps",
    "fused_lookup_table": "ps",
    "moe_ffn": "ep",
    "ring_attention": "sp",
    "ulysses_attention": "sp",
    "pipeline_gate_loss": "pp",
}

# ops whose emitter reduces over a FIXED axis when it is bound
FIXED_AXIS_OPS = {"sync_batch_norm": "dp"}

_PIPELINE_OPS = ("pipeline_block", "pipeline_uniform")
_BRANCH_OPS = ("cond", "conditional_block", "conditional_block_infer")
_BODY_ATTRS = ("sub_block",)  # while / scan_block / bounded_while

# bound on enumerated rank combinations (product of pipeline-axis sizes);
# beyond it the tail is skipped — a 128-stage pipeline is not a test mesh
MAX_RANK_COMBOS = 128


# collectives whose WIRE FORMAT is part of the site kind: an int8-quantized
# exchange on one rank paired with a full-precision one on another is a
# payload-size mismatch — the exchange deadlocks (or corrupts) exactly like
# a kind mismatch, so the lint must distinguish the quantized variants.
# The embedding lookups joined in PR 11: their backward row-gradient
# exchange (all_to_all + all_gather when quantized, psum otherwise) runs a
# different collective SEQUENCE per wire format, and the column partition
# runs an all-gather instead of a psum — both are part of the site kind.
_QUANT_KIND_OPS = frozenset({
    "zero_reduce_scatter", "zero_all_gather", "zero_bucket_reduce_scatter",
    "distributed_lookup_table", "fused_lookup_table",
})
_LOOKUP_KIND_OPS = frozenset({
    "distributed_lookup_table", "fused_lookup_table",
})
# bucketed collectives: MEMBERSHIP AND ORDER are part of the cross-rank
# wire contract — two ranks disagreeing on which grads share a bucket (or
# on their order inside it) exchange different payload layouts on the same
# collective slot, which deadlocks or silently corrupts exactly like a
# kind mismatch. The per-member size list therefore joins the site kind,
# so a rank-divergent bucketing is a build-time COLLECTIVE_DIVERGENCE
# ERROR, not a pod hang. op type -> attr carrying the member sizes.
_BUCKET_KIND_OPS = {
    "zero_bucket_reduce_scatter": "pad_lens",
    "c_bucket_allreduce_sum": "bucket_numels",
}


def _site_kind(op, t):
    kind = t
    if t in _LOOKUP_KIND_OPS and op.attr("partition", "row") == "col":
        kind = f"{t}:col"
    if t in _BUCKET_KIND_OPS:
        sizes = op.attr(_BUCKET_KIND_OPS[t]) or ()
        kind = f"{kind}[{','.join(str(int(s)) for s in sizes)}]"
    if t in _QUANT_KIND_OPS:
        quant = op.attr("quant", "none")
        if quant and quant != "none":
            return f"{kind}:{quant}"
    return kind


def collective_axis(op):
    """(axis_name, kind) if `op` is collective-bearing, else (None, None).
    For quantized sharded-update collectives the kind carries the wire
    format (e.g. ``zero_reduce_scatter:int8``)."""
    t = op.type
    if t in ATTR_AXIS_OPS:
        return op.attr("axis_name", ATTR_AXIS_OPS[t]), _site_kind(op, t)
    if t in FIXED_AXIS_OPS:
        return FIXED_AXIS_OPS[t], t
    if t in _PIPELINE_OPS:
        return op.attr("axis_name", "pp"), t
    return None, None


@dataclass(frozen=True)
class Site:
    kind: str
    axis: str

    def __str__(self):
        return f"{self.kind}@{self.axis}"


class _Walker:
    def __init__(self, program, bound_axes, findings):
        self.program = program
        self.bound = frozenset(bound_axes)
        self.findings = findings
        self.first_rank = True  # branch findings reported once, not per rank

    def stream(self, coords):
        out = []
        self._walk(self.program.global_block.ops, coords, out, 0)
        self.first_rank = False
        return out

    def _walk(self, ops, coords, out, block_idx, depth=0):
        if depth > 16:  # cyclic sub-block refs cannot hang the verifier
            return
        for i, op in enumerate(ops):
            t = op.type
            if t in _PIPELINE_OPS:
                self._walk_pipeline(op, i, coords, out, block_idx, depth)
                continue
            if t in _BRANCH_OPS:
                self._walk_branch(op, i, coords, out, block_idx, depth)
                continue
            if t == "recompute_segment":
                # embedded ops live in the `sub_ops` attr, not a sub-block;
                # a collective folded into a rematerialized span still
                # executes (twice, but uniformly) on every rank
                from ..framework.registry import OpView

                views = [
                    OpView(ot, oattrs, oins, oouts)
                    for ot, oins, oouts, oattrs in op.attr("sub_ops", ())
                ]
                self._walk(views, coords, out, block_idx, depth + 1)
                continue
            body = None
            for a in _BODY_ATTRS:
                if op.attr(a) is not None:
                    body = self.program.blocks[op.attr(a)]
                    break
            if body is not None:
                self._walk(body.ops, coords, out, body.idx, depth + 1)
                continue
            ax, kind = collective_axis(op)
            if ax is not None and ax in self.bound:
                out.append((Site(kind, ax), op, i, block_idx))

    def _walk_pipeline(self, op, i, coords, out, block_idx, depth):
        ax = op.attr("axis_name", "pp")
        if op.type == "pipeline_uniform":
            body = self.program.blocks[op.attr("stage_block")]
            if ax in self.bound:
                out.append((Site("pipeline_uniform.ppermute", ax), op, i,
                            block_idx))
            self._walk(body.ops, coords, out, body.idx, depth + 1)
            if ax in self.bound:
                out.append((Site("pipeline_uniform.psum", ax), op, i,
                            block_idx))
            return
        stage_blocks = list(op.attr("stage_blocks") or ())
        if ax not in self.bound:
            # sequential degrade runs every stage on every rank, in order
            for bi in stage_blocks:
                blk = self.program.blocks[bi]
                self._walk(blk.ops, coords, out, blk.idx, depth + 1)
            return
        out.append((Site("pipeline_block.ppermute", ax), op, i, block_idx))
        stage = min(coords.get(ax, 0), len(stage_blocks) - 1)
        blk = self.program.blocks[stage_blocks[stage]]
        self._walk(blk.ops, coords, out, blk.idx, depth + 1)
        out.append((Site("pipeline_block.psum", ax), op, i, block_idx))

    def _walk_branch(self, op, i, coords, out, block_idx, depth):
        branches = []
        for attr in ("true_block", "false_block", "sub_block"):
            bi = op.attr(attr)
            if bi is not None:
                branches.append(self.program.blocks[bi])
        streams = []
        for blk in branches:
            s = []
            self._walk(blk.ops, coords, s, blk.idx, depth + 1)
            streams.append(s)
        if len(streams) > 1 and self.first_rank:
            a = [site for site, *_ in streams[0]]
            b = [site for site, *_ in streams[1]]
            if a != b:
                self.findings.append(finding_for_op(
                    Severity.WARNING, COLLECTIVE_BRANCH_DIVERGENCE,
                    f"branches of {op.type!r} emit different collective "
                    f"streams ({[str(s) for s in a]} vs "
                    f"{[str(s) for s in b]}); if the predicate is not "
                    "replicated across ranks this deadlocks",
                    op=op, op_index=i, block_idx=block_idx,
                ))
        if streams:
            out.extend(streams[0])


def analyze_collectives(program):
    findings = []
    mesh = getattr(program, "_mesh", None)
    mode = getattr(program, "_spmd_mode", "shard_map")
    if mesh is None or mode not in ("shard_map", "hybrid"):
        # no mesh: collectives degrade to identity by design (nranks==1);
        # gspmd: axes are never bound, XLA derives comms from shardings
        return findings
    mesh_axes = tuple(mesh.axis_names)
    bound = (
        mesh_axes if mode == "shard_map"
        else tuple(getattr(program, "_manual_axes", ()))
    )
    axis_sizes = dict(mesh.shape)

    # --- axis existence / binding, every block ----------------------------
    for blk in program.blocks:
        for i, op in enumerate(blk.ops):
            ax, kind = collective_axis(op)
            if ax is None or op.type in FIXED_AXIS_OPS:
                continue  # fixed-axis emitters guard themselves
            if ax not in mesh_axes:
                findings.append(finding_for_op(
                    Severity.WARNING, UNKNOWN_MESH_AXIS,
                    f"collective {kind!r} names mesh axis {ax!r} but the "
                    f"program's mesh only binds {list(mesh_axes)}; the op "
                    "degrades to identity (likely a typo'd axis_name)",
                    op=op, op_index=i, block_idx=blk.idx, names=(ax,),
                ))
            elif ax not in bound:
                findings.append(finding_for_op(
                    Severity.WARNING, UNKNOWN_MESH_AXIS,
                    f"collective {kind!r} names axis {ax!r} which hybrid "
                    f"mode leaves non-manual (manual axes: {list(bound)}); "
                    "explicit collectives over auto axes degrade to "
                    "identity",
                    op=op, op_index=i, block_idx=blk.idx, names=(ax,),
                ))

    # --- per-rank stream simulation ---------------------------------------
    walker = _Walker(program, bound, findings)
    affecting = sorted({
        op.attr("axis_name", "pp")
        for blk in program.blocks
        for op in blk.ops
        if op.type == "pipeline_block"
        and op.attr("axis_name", "pp") in bound
    })
    combos = itertools.product(
        *(range(int(axis_sizes.get(a, 1))) for a in affecting)
    )
    streams = []
    for combo in itertools.islice(combos, MAX_RANK_COMBOS):
        coords = dict(zip(affecting, combo))
        streams.append((coords, walker.stream(coords)))
    if len(streams) < 2:
        return findings
    base_coords, base = streams[0]
    for coords, cur in streams[1:]:
        for k, (a, b) in enumerate(itertools.zip_longest(base, cur)):
            if a is not None and b is not None and a[0] == b[0]:
                continue
            # anchor the finding on the concrete divergent collective —
            # prefer a real collective op over a pipeline schedule bracket
            if a is not None and b is not None:
                pick, pick_coords = (
                    (a, base_coords)
                    if not a[0].kind.startswith("pipeline_") else (b, coords)
                )
                detail = (
                    f"rank {base_coords} issues {a[0]} while rank "
                    f"{coords} issues {b[0]}"
                )
            else:
                pick, pick_coords = (a, base_coords) if a else (b, coords)
                longer, shorter = (
                    (base_coords, coords) if a else (coords, base_coords)
                )
                detail = (
                    f"rank {longer} issues {pick[0]} but rank {shorter}'s "
                    "stream has already ended"
                )
            site, op, op_idx, blk_idx = pick
            findings.append(finding_for_op(
                Severity.ERROR, COLLECTIVE_DIVERGENCE,
                f"rank-divergent collective order at schedule position "
                f"{k}: {detail} — every rank must issue the same "
                f"collectives in the same order over axis {site.axis!r} "
                "or the exchange deadlocks",
                op=op, op_index=op_idx, block_idx=blk_idx,
                names=(site.axis,),
            ))
            break
    return findings
