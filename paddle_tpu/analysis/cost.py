"""Per-op cost attribution: analytic FLOPs / bytes-moved / roofline latency
over the Program IR — the fourth ``analysis/`` family (ROADMAP item 3).

Every perf win through r6 came from hand-probing: bench.py hard-coded a
per-model FLOPs closed form, MFU was computed offline per leg, and "which
ops eat the step" meant reading XLA dumps. Learned TPU cost models
(arXiv:2008.01040) and TVM's cost-model-driven search (arXiv:1802.04799)
both start from exactly the feature this pass extracts: per-op compute and
traffic at concrete shapes. The model here is analytic (closed forms per
op family, not learned) because the IR is coarse enough — matmul/conv/
attention dominate — and because the runtime cross-check against XLA's own
``cost_analysis()`` (``Executor.flops``) keeps it honest; the planned
autotuner consumes :meth:`Program.estimate` as its objective function.

Walk model (mirrors the collective-schedule walker, collectives.py):

* every op contributes one :class:`OpCost` (flops, bytes, roofline
  latency) computed from *declared* Variable shapes — no tracing, no
  ``eval_shape``, so estimating a BERT-base training program is
  milliseconds;
* ``__vjp__`` grad ops are attributed to their forward op's family at
  2x the forward cost (dx and dW are each a forward-sized contraction;
  XLA CSE merges the replayed forward, so it is not counted) — 3x when
  the forward is a ``recompute_segment``, whose backward re-runs the
  segment under ``jax.checkpoint`` before the vjp;
* ``pipeline_block`` stage sub-blocks are walked once at graph-build
  shapes: M microbatches at B/M each sum to the declared-[B] cost;
* ``recompute_segment`` forward walks its folded ``sub_ops``;
* ``cond`` branches contribute the costlier branch; loop bodies
  (``while``/``scan_block``) are counted once per trip when the op
  carries a static trip count, else once (recorded in ``assumptions``);
* -1 (batch) dims are pinned by ``feed_shapes`` when given, else by the
  leading dim of any feed, else 1 — every such pin is recorded.

Roofline: ``latency = max(flops/peak_flops, bytes/peak_bandwidth)`` with
peaks from ``PADDLE_TPU_PEAK_TFLOPS`` / ``PADDLE_TPU_PEAK_GBPS``
(defaults: TPU v5e bf16 197 TFLOP/s, 819 GB/s HBM). The same peak feeds
the executor's live ``perf.mfu`` gauge, so offline and live MFU agree by
construction. README §Cost attribution & perf telemetry documents the
contract.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass, field

import numpy as np

from ..core.dtypes import to_numpy_dtype

# TPU v5e per-chip peaks: bf16 matmul throughput and HBM bandwidth.
DEFAULT_PEAK_TFLOPS = 197.0
DEFAULT_PEAK_GBPS = 819.0


def peak_flops() -> float:
    """Peak FLOP/s the MFU gauge and rooflines are measured against
    (``PADDLE_TPU_PEAK_TFLOPS``, default TPU v5e bf16)."""
    try:
        return float(
            os.environ.get("PADDLE_TPU_PEAK_TFLOPS", DEFAULT_PEAK_TFLOPS)
        ) * 1e12
    except ValueError:
        return DEFAULT_PEAK_TFLOPS * 1e12


def peak_bandwidth() -> float:
    """Peak bytes/s for the roofline's memory leg
    (``PADDLE_TPU_PEAK_GBPS``, default TPU v5e HBM)."""
    try:
        return float(
            os.environ.get("PADDLE_TPU_PEAK_GBPS", DEFAULT_PEAK_GBPS)
        ) * 1e9
    except ValueError:
        return DEFAULT_PEAK_GBPS * 1e9


# ---------------------------------------------------------------------------
# op families
# ---------------------------------------------------------------------------

MATMUL_OPS = frozenset({
    "mul", "matmul", "bmm", "dot", "addmm", "batch_fc",
    "bilinear_tensor_product", "match_matrix_tensor",
})
CONV_OPS = frozenset({
    "conv2d", "conv3d", "depthwise_conv2d", "conv2d_transpose",
    "conv3d_transpose", "depthwise_conv2d_transpose", "deformable_conv",
    "deformable_conv_v1", "var_conv_2d", "row_conv", "conv_shift",
})
ATTENTION_OPS = frozenset({
    "fused_qkv_attention", "fused_qkv_attention_grad",
    "fused_multihead_attention", "fused_multihead_attention_grad",
    "ring_attention", "ulysses_attention",
})
NORM_OPS = frozenset({
    "batch_norm", "sync_batch_norm", "layer_norm", "layer_norm_grad",
    "group_norm", "instance_norm", "data_norm", "inplace_abn",
    "fused_dropout_add_ln", "fused_dropout_add_ln_grad", "lrn",
    "spectral_norm",
})
EMBED_OPS = frozenset({
    "lookup_table", "lookup_table_v2", "lookup_table_dequant",
    "lookup_sparse_table", "distributed_lookup_table",
    "fused_lookup_table", "gather",
    "gather_nd", "index_select", "index_sample", "take_along_axis",
    "scatter", "scatter_nd_add", "shuffle_batch", "pyramid_hash",
})
# the engine's lookup ops get dedicated closed forms (unique-row gather
# bytes forward, segment-sum scatter backward, quantized exchange wire)
SPARSE_LOOKUP_OPS = frozenset({
    "distributed_lookup_table", "fused_lookup_table",
})
OPTIMIZER_OPS = {
    # op type -> flops per Param element (rough update-rule arithmetic)
    "sgd": 2.0, "momentum": 4.0, "lars_momentum": 8.0, "adam": 12.0,
    "adamw": 14.0, "lamb": 16.0, "adagrad": 6.0, "decayed_adagrad": 7.0,
    "adadelta": 8.0, "rmsprop": 8.0, "ftrl": 8.0, "adamax": 10.0,
    "dpsgd": 4.0, "proximal_gd": 3.0, "proximal_adagrad": 6.0,
    "dgc_momentum_step": 6.0,
}
# zero-FLOP data movement: layout/shape/copy ops (bytes still counted)
DATA_OPS = frozenset({
    "reshape", "reshape2", "transpose", "transpose2", "squeeze",
    "squeeze2", "unsqueeze", "unsqueeze2", "flatten", "flatten2",
    "concat", "split", "stack", "unstack", "unbind", "slice",
    "strided_slice", "assign", "cast", "expand", "expand_as", "tile",
    "pad", "pad2d", "pad_constant_like", "reverse", "flip", "roll",
    "fill_constant", "fill_any_like", "fill_zeros_like",
    "fill_zeros_like2", "fill", "fill_constant_batch_size_like",
    "gaussian_random", "uniform_random", "truncated_gaussian_random",
    "gaussian_random_batch_size_like", "uniform_random_batch_size_like",
    "randint", "randperm", "range", "linspace", "eye", "one_hot",
    "one_hot_v2", "shape", "size", "shard_index", "sampling_id", "seed",
    "c_identity", "c_sync_calc_stream", "c_sync_comm_stream",
    "share_data", "space_to_depth", "pixel_shuffle", "shuffle_channel",
    "write_to_array", "read_from_array", "tensor_array_to_tensor",
    "select_input", "select_output", "assign_value",
})
# per-element flop weights for compute ops that are not matrix contractions
ELEMENTWISE_WEIGHTS = {
    "softmax": 4.0, "log_softmax": 4.0,
    "softmax_with_cross_entropy": 5.0,
    "cross_entropy": 3.0, "cross_entropy2": 3.0, "nll_loss": 2.0,
    "sigmoid_cross_entropy_with_logits": 4.0, "bce_loss": 4.0,
    "dropout": 2.0, "gelu": 8.0, "tanh": 1.0, "sigmoid": 2.0,
    "silu": 3.0, "swish": 3.0, "mish": 6.0, "erf": 1.0, "exp": 1.0,
    "square_error_cost": 3.0, "smooth_l1_loss": 4.0, "huber_loss": 4.0,
    "isfinite": 1.0, "check_finite_and_unscale": 2.0,
    "amp_check_finite_and_scale": 2.0, "update_loss_scaling": 2.0,
    "clip_by_norm": 3.0, "squared_l2_norm": 2.0, "l1_norm": 2.0,
    "frobenius_norm": 2.0, "p_norm": 3.0, "norm": 3.0,
}
# gather-like EMBED_OPS: the named slot is a table read SPARSELY — only
# the gathered rows (~output-sized) actually move, not the whole table
# (a criteo-sized vocab would otherwise dominate every byte rollup)
_GATHER_TABLE_SLOTS = {
    "lookup_table": "W", "lookup_table_v2": "W",
    "lookup_table_dequant": "W", "lookup_sparse_table": "W",
    "distributed_lookup_table": "W",
    "gather": "X", "gather_nd": "X", "index_select": "X",
    "index_sample": "X", "take_along_axis": "Input",
}
# ops whose cost is ~1 pass over the INPUT (output is reduced/small)
REDUCE_OPS = frozenset({
    "reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
    "reduce_prod", "reduce_all", "reduce_any", "mean", "arg_max",
    "arg_min", "argsort", "top_k", "cumsum", "trace", "unique",
    "unique_with_counts", "accuracy", "auc",
})

# interconnect payload factor per collective kind: ring-algorithm wire
# bytes as a multiple of the payload (n = axis size)
_COLLECTIVE_FACTORS = {
    "c_allreduce_sum": lambda n: 2.0 * (n - 1) / n,
    "c_allreduce_max": lambda n: 2.0 * (n - 1) / n,
    "c_allreduce_min": lambda n: 2.0 * (n - 1) / n,
    "c_allreduce_prod": lambda n: 2.0 * (n - 1) / n,
    "allreduce": lambda n: 2.0 * (n - 1) / n,
    "mp_allreduce_sum": lambda n: 2.0 * (n - 1) / n,
    "c_allgather": lambda n: float(n - 1) / n,
    "c_reducescatter": lambda n: float(n - 1) / n,
    "alltoall": lambda n: float(n - 1) / n,
    "c_broadcast": lambda n: 1.0,
    "collective_permute": lambda n: 1.0,
    "barrier": lambda n: 0.0,
    # sharded weight update (ZeRO): reduce-scatter and all-gather each move
    # (n-1)/n of the payload; found-inf any-reduce is a [1]-element
    # allreduce
    "zero_reduce_scatter": lambda n: float(n - 1) / n,
    "zero_all_gather": lambda n: float(n - 1) / n,
    "c_allreduce_any": lambda n: 2.0 * (n - 1) / n,
    # bucketed overlap schedule (ROADMAP item 4): a bucket moves the same
    # ring bytes as its members' individual collectives would — the win is
    # dispatch count and firing position, not payload
    "c_bucket_allreduce_sum": lambda n: 2.0 * (n - 1) / n,
    "zero_bucket_reduce_scatter": lambda n: float(n - 1) / n,
}

#: int8 block quantization (ops/collective.py): effective bytes per
#: payload element = 1 int8 + one fp32 scale per `quant_block` elements.
def _quant_elem_bytes(quant, block, fp_itemsize):
    if quant and quant != "none":
        return 1.0 + 4.0 / max(int(block or 256), 1)
    return float(fp_itemsize)


def family_of(op_type: str) -> str:
    """Coarse op family used for attribution gauges and by-family rollups."""
    if op_type in MATMUL_OPS:
        return "matmul"
    if op_type in CONV_OPS:
        return "conv"
    if op_type in ATTENTION_OPS:
        return "attention"
    if op_type in NORM_OPS:
        return "normalization"
    if op_type in EMBED_OPS:
        return "embedding"
    if op_type in OPTIMIZER_OPS:
        return "optimizer"
    if op_type in _COLLECTIVE_FACTORS:
        return "collective"
    if op_type in DATA_OPS:
        return "data_movement"
    return "elementwise"


# ---------------------------------------------------------------------------
# cost table
# ---------------------------------------------------------------------------


@dataclass
class OpCost:
    """Total cost of one IR op site (already scaled by execution count)."""

    op_type: str
    family: str
    flops: float
    bytes: float
    latency: float
    count: int = 1
    block_idx: int = 0
    op_index: int = 0
    loc: str = ""

    def to_dict(self):
        return {
            "op_type": self.op_type, "family": self.family,
            "flops": self.flops, "bytes": self.bytes,
            "latency": self.latency, "count": self.count,
            "block_idx": self.block_idx, "op_index": self.op_index,
            "loc": self.loc,
        }


@dataclass
class CostTable:
    """Per-op cost attribution for one Program at concrete shapes."""

    ops: list = field(default_factory=list)
    assumptions: list = field(default_factory=list)
    peak_flops: float = 0.0
    peak_bandwidth: float = 0.0
    #: overlap-aware step-time estimate (seconds), set by
    #: :func:`estimate_program` ONLY for programs whose collective
    #: schedule was restructured for overlap (``program._overlap_schedule``
    #: — bucketed grad collectives / prefetched all-gathers): a
    #: two-resource simulation where collectives run on the wire channel
    #: concurrently with compute, and compute blocks only when it consumes
    #: a collective's output — max(compute, wire) per overlap segment
    #: instead of a global sum. None = serialized schedule: the step
    #: estimate is ``total_latency``.
    scheduled_latency: float = None
    #: static HBM plan from the memory analysis family (set by
    #: :func:`estimate_program`): peak live bytes (resident persistables
    #: + feeds + transient live-set max), the resident portion alone, and
    #: the full :class:`~paddle_tpu.analysis.memory.MemoryTable` (the
    #: watermark op, timeline, per-stage peaks). Cross-checked against
    #: XLA's compiled ``memory_analysis`` by ``Executor.memory_analysis``
    #: / ``tools/perf_report.py --check-memory``.
    peak_bytes: float = None
    resident_bytes: float = None
    memory: object = field(default=None, repr=False)

    @property
    def total_flops(self):
        return sum(e.flops for e in self.ops)

    @property
    def total_bytes(self):
        return sum(e.bytes for e in self.ops)

    @property
    def total_latency(self):
        """Sum of per-op rooflines: a LOWER bound on the step (assumes
        perfect overlap within each op, none across ops)."""
        return sum(e.latency for e in self.ops)

    @property
    def wire_latency(self):
        """Roofline latency of the collective family alone — the wire
        time a fully SERIALIZED schedule pays."""
        return sum(e.latency for e in self.ops if e.family == "collective")

    @property
    def step_latency(self):
        """Best step-time estimate under the program's actual collective
        schedule: :attr:`scheduled_latency` when the schedule is
        overlap-structured, else the serialized ``total_latency``."""
        return (
            self.scheduled_latency if self.scheduled_latency is not None
            else self.total_latency
        )

    @property
    def wire_exposed_latency(self):
        """Wire seconds the schedule can NOT hide behind compute: the
        part of :attr:`wire_latency` still on the critical path. Equals
        ``wire_latency`` for a serialized schedule."""
        wire = self.wire_latency
        compute = self.total_latency - wire
        return min(wire, max(0.0, self.step_latency - compute))

    @property
    def overlap_ratio(self):
        """Wire seconds hidden / total wire seconds (0 = fully
        serialized, 1 = the wire disappears behind the math)."""
        wire = self.wire_latency
        if wire <= 0:
            return 0.0
        return max(0.0, min(1.0, 1.0 - self.wire_exposed_latency / wire))

    def by_family(self):
        fams = {}
        for e in self.ops:
            f = fams.setdefault(
                e.family, {"flops": 0.0, "bytes": 0.0, "latency": 0.0,
                           "ops": 0}
            )
            f["flops"] += e.flops
            f["bytes"] += e.bytes
            f["latency"] += e.latency
            f["ops"] += e.count
        return fams

    def by_op_type(self):
        kinds = {}
        for e in self.ops:
            k = kinds.setdefault(
                e.op_type, {"flops": 0.0, "bytes": 0.0, "latency": 0.0,
                            "ops": 0}
            )
            k["flops"] += e.flops
            k["bytes"] += e.bytes
            k["latency"] += e.latency
            k["ops"] += e.count
        return kinds

    def top(self, k=10):
        """Top-k op sites by roofline latency (the "which ops eat the
        step" view)."""
        return sorted(self.ops, key=lambda e: -e.latency)[:k]

    def mfu_at(self, step_seconds: float) -> float:
        """Model FLOPs utilization of one step measured at
        ``step_seconds``, against this table's peak."""
        if step_seconds <= 0 or self.peak_flops <= 0:
            return 0.0
        return self.total_flops / step_seconds / self.peak_flops

    def to_dict(self, top=50):
        return {
            "total_flops": self.total_flops,
            "total_bytes": self.total_bytes,
            "total_latency": self.total_latency,
            "scheduled_latency": self.scheduled_latency,
            "wire_latency": self.wire_latency,
            "wire_exposed_latency": self.wire_exposed_latency,
            "overlap_ratio": self.overlap_ratio,
            "peak_flops": self.peak_flops,
            "peak_bandwidth": self.peak_bandwidth,
            "peak_bytes": self.peak_bytes,
            "resident_bytes": self.resident_bytes,
            "memory": (
                self.memory.to_dict() if self.memory is not None else None
            ),
            "by_family": self.by_family(),
            "ops": [e.to_dict() for e in self.top(top)],
            "assumptions": list(self.assumptions),
        }

    def format(self, top=10):
        """Human-readable table (program_lint --cost, perf_report)."""
        lines = [
            f"estimated step: {self.total_flops / 1e9:.3f} GFLOP, "
            f"{self.total_bytes / 1e6:.3f} MB moved, roofline >= "
            f"{self.total_latency * 1e3:.3f} ms "
            f"(peak {self.peak_flops / 1e12:.0f} TFLOP/s, "
            f"{self.peak_bandwidth / 1e9:.0f} GB/s)"
        ]
        if self.scheduled_latency is not None:
            lines.append(
                f"overlap schedule: step >= "
                f"{self.scheduled_latency * 1e3:.3f} ms "
                f"(wire {self.wire_latency * 1e3:.3f} ms, exposed "
                f"{self.wire_exposed_latency * 1e3:.3f} ms, "
                f"{self.overlap_ratio:.0%} hidden behind compute)"
            )
        if self.memory is not None:
            lines.append(self.memory.format(top=3))
        fams = sorted(self.by_family().items(),
                      key=lambda kv: -kv[1]["latency"])
        tot_lat = self.total_latency or 1.0
        lines.append("-- by family --")
        for fam, agg in fams:
            lines.append(
                f"  {fam:<14} {agg['flops'] / 1e9:>10.3f} GFLOP "
                f"{agg['bytes'] / 1e6:>10.3f} MB "
                f"{agg['latency'] / tot_lat:>6.1%} of roofline "
                f"({agg['ops']} ops)"
            )
        lines.append(f"-- top {top} op sites by roofline latency --")
        for e in self.top(top):
            lines.append(
                f"  {e.op_type:<28} {e.flops / 1e9:>10.3f} GFLOP "
                f"{e.bytes / 1e6:>9.3f} MB {e.latency * 1e6:>9.1f} us"
                f"  b{e.block_idx}#{e.op_index}"
                + (f"  {e.loc}" if e.loc else "")
            )
        if self.assumptions:
            lines.append("-- assumptions --")
            for a in self.assumptions:
                lines.append(f"  {a}")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# per-op formulas
# ---------------------------------------------------------------------------


def _nelem(spec):
    return int(math.prod(spec[0])) if spec else 0


def _nbytes(spec):
    return _nelem(spec) * spec[1] if spec else 0


def _first(specs, slot):
    vals = specs.get(slot) or []
    for v in vals:
        if v is not None:
            return v
    return None


def _all_bytes(*spec_dicts):
    total = 0
    for specs in spec_dicts:
        for vals in specs.values():
            for v in vals:
                if v is not None:
                    total += _nbytes(v)
    return total


def _flops_matmul(op, ins, outs):
    out = _first(outs, "Out")
    x = _first(ins, "X")
    if out is None or x is None:
        return 0.0
    t = op.type
    if t == "dot":
        return 2.0 * _nelem(x)
    if t == "mul":
        xnc = int(op.attr("x_num_col_dims", 1))
        k = math.prod(x[0][xnc:]) or 1
    elif t == "matmul":
        k = x[0][-2] if op.attr("transpose_X", False) and len(x[0]) > 1 \
            else x[0][-1]
    else:  # bmm / addmm / batch_fc / bilinear-ish: contract x's last dim
        k = x[0][-1] if x[0] else 1
    return 2.0 * _nelem(out) * int(k)


def _per_dim(value, n, default=1):
    """Normalize a conv attr (scalar | [n] | [2n] begin/end pairs) to one
    BEGIN value per spatial dim."""
    if value is None:
        return [default] * n
    if not isinstance(value, (list, tuple)):
        return [int(value)] * n
    v = [int(x) for x in value]
    if len(v) >= 2 * n:
        return [v[2 * i] for i in range(n)]
    if len(v) >= n:
        return v[:n]
    return (v * n)[:n] if v else [default] * n


def _axis_taps(h_in, h_out, k, stride, pad, dil):
    """Valid (non-padding) kernel taps summed over output positions along
    one spatial dim — XLA counts only real multiplies, and at small
    spatial extents (deep resnet stages, 3x3 on 2x2) the padding share
    dominates."""
    total = 0
    for o in range(h_out):
        start = o * stride - pad
        total += sum(1 for t in range(k) if 0 <= start + t * dil < h_in)
    return total


def _conv_tap_factor(op, x, out, filt):
    """Fraction of kernel taps that land on real input (1.0 = no padding
    loss), separable per spatial dim."""
    spatial = len(x[0]) - 2
    if spatial < 1 or len(out[0]) != len(x[0]) or len(filt[0]) < 2 + spatial:
        return 1.0
    strides = _per_dim(op.attr("strides"), spatial)
    dils = _per_dim(op.attr("dilations"), spatial)
    algo = str(op.attr("padding_algorithm", "EXPLICIT")).upper()
    factor = 1.0
    for d in range(spatial):
        h_in, h_out = int(x[0][2 + d]), int(out[0][2 + d])
        k = int(filt[0][2 + d])
        if k <= 1 or h_out <= 0:
            continue
        if algo == "VALID":
            pad = 0
        elif algo == "SAME":
            pad = max(
                0, (h_out - 1) * strides[d] + (k - 1) * dils[d] + 1 - h_in
            ) // 2
        else:
            pad = _per_dim(op.attr("paddings"), spatial, default=0)[d]
        if pad == 0:
            continue
        factor *= _axis_taps(h_in, h_out, k, strides[d], pad, dils[d]) / (
            h_out * k
        )
    return factor


def _flops_conv(op, ins, outs):
    t = op.type
    filt = _first(ins, "Filter") or _first(ins, "W")
    if t.endswith("_transpose"):
        # filter [in_c, out_c/g, k...]: each INPUT element hits the whole
        # filter tail
        x = _first(ins, "Input") or _first(ins, "X")
        if x is None or filt is None:
            return 0.0
        return 2.0 * _nelem(x) * math.prod(filt[0][1:])
    out = _first(outs, "Output") or _first(outs, "Out")
    if out is None or filt is None:
        return 0.0
    # filter [out_c, in_c/g, k...]: every output element is a dot over the
    # filter tail (in_c/groups * prod(k)), discounted by padding taps
    full = 2.0 * _nelem(out) * math.prod(filt[0][1:])
    x = _first(ins, "Input") or _first(ins, "X")
    if x is None or len(x[0]) < 3:
        return full
    return full * _conv_tap_factor(op, x, out, filt)


def _flops_attention(op, ins, outs):
    causal = 0.5 if op.attr("causal", False) else 1.0
    t = op.type
    if t.startswith("fused_qkv_attention"):
        qkv = _first(ins, "QKV")
        if qkv is None:
            return 0.0
        b, s = qkv[0][0], qkv[0][1]
        e = qkv[0][-1] // 3
        fwd = 4.0 * b * s * s * e * causal
    else:  # q/k/v [B, H, S, D] (ring/ulysses share the layout)
        q = _first(ins, "Q")
        if q is None:
            return 0.0
        b, h, s, d = (list(q[0]) + [1, 1, 1, 1])[:4]
        fwd = 4.0 * b * h * s * s * d * causal
    # flash backward: dQ/dK/dV are 4 score-sized contractions plus the
    # in-kernel probability recompute ~ 2.5x the forward kernel
    return fwd * 2.5 if t.endswith("_grad") else fwd


def _flops_pool(op, ins, outs):
    ksize = op.attr("ksize")
    if op.attr("global_pooling", False) or op.attr("adaptive", False) \
            or not isinstance(ksize, (list, tuple)):
        # one pass over the input (global/adaptive reduce)
        return float(_nelem(_first(ins, "X")))
    return float(_nelem(_first(outs, "Out"))) * math.prod(ksize)


def _flops_norm(op, ins, outs):
    x = _first(ins, "X")
    n = _nelem(x)
    t = op.type
    if t.endswith("_grad"):
        return 14.0 * n
    if t in ("fused_dropout_add_ln",):
        return 10.0 * n
    if t in ("batch_norm", "sync_batch_norm", "inplace_abn"):
        return (4.0 if op.attr("is_test", False) else 6.0) * n
    return 8.0 * n


def _flops_optimizer(op, ins, outs):
    p = _first(ins, "Param")
    return OPTIMIZER_OPS.get(op.type, 4.0) * _nelem(p)


def _lookup_exchange_axis(op, axis_sizes):
    ax = op.attr("axis_name", "ps")
    n = int(axis_sizes.get(ax, 1))
    return n if n > 1 else 1


def _lookup_wire_elem_bytes(op, itemsize):
    return _quant_elem_bytes(
        op.attr("quant", "none"), op.attr("quant_block", 256), itemsize
    )


def _lookup_cost(op, ins, outs, axis_sizes):
    """Forward closed form for the engine's lookup ops
    (distributed_lookup_table / fused_lookup_table): ids read + output
    write + the UNIQUE-row gather — batch dedup means at most
    min(total ids, total table rows) rows actually stream from the table —
    plus the row-assembly exchange wire when the table is mesh-partitioned
    (psum of the masked [ids, D] rows ~ allreduce factor; the col
    partition's all-gather moves (n-1)/n of the assembled rows)."""
    ids_bytes = sum(
        _nbytes(v) for v in ins.get("Ids", ()) if v is not None
    )
    out_bytes = sum(
        _nbytes(v) for v in outs.get("Out", ()) if v is not None
    )
    tables = [v for v in ins.get("W", ()) if v is not None]
    table_rows = sum(v[0][0] for v in tables if v[0])
    dim = tables[0][0][-1] if tables and tables[0][0] else 1
    itemsize = tables[0][1] if tables else 4
    total_ids = sum(
        _nelem(v) for v in ins.get("Ids", ()) if v is not None
    )
    unique_rows = min(total_ids, table_rows) if table_rows else total_ids
    gather_bytes = (
        unique_rows * dim * itemsize
        if bool(op.attr("dedup", True)) else out_bytes
    )
    nbytes = ids_bytes + out_bytes + gather_bytes
    n = _lookup_exchange_axis(op, axis_sizes)
    if n > 1:
        row_payload = float(total_ids * dim)
        if op.attr("partition", "row") == "col":
            nbytes += row_payload * itemsize * (n - 1) / n
        else:
            # forward psum of the masked rows: allreduce ring factor at
            # full precision (quantization applies to the BACKWARD grad
            # exchange only; see _lookup_grad_cost)
            nbytes += row_payload * itemsize * 2.0 * (n - 1) / n
    return 0.0, nbytes


def _lookup_grad_cost(fwd_op, fwd_ins, fwd_outs, axis_sizes):
    """Backward closed form: ONE segment-sum scatter per table — each
    gathered row's cotangent is read once and accumulated into its unique
    row (flops ~= out grad elems), moving grad-rows in and unique table
    rows out — plus the id->owner grad all-to-all + all-gather at the
    (possibly int8 block-quantized) wire element size when row-sharded."""
    out_bytes = sum(
        _nbytes(v) for v in fwd_outs.get("Out", ()) if v is not None
    )
    out_elems = sum(
        _nelem(v) for v in fwd_outs.get("Out", ()) if v is not None
    )
    tables = [v for v in fwd_ins.get("W", ()) if v is not None]
    table_rows = sum(v[0][0] for v in tables if v[0])
    dim = tables[0][0][-1] if tables and tables[0][0] else 1
    itemsize = tables[0][1] if tables else 4
    total_ids = sum(
        _nelem(v) for v in fwd_ins.get("Ids", ()) if v is not None
    )
    unique_rows = min(total_ids, table_rows) if table_rows else total_ids
    nbytes = 2.0 * out_bytes + unique_rows * dim * itemsize
    flops = float(out_elems)
    n = _lookup_exchange_axis(fwd_op, axis_sizes)
    if n > 1 and fwd_op.attr("partition", "row") != "col":
        elem = _lookup_wire_elem_bytes(fwd_op, itemsize)
        # reduce-scatter (all_to_all) + all-gather legs over the grad rows
        nbytes += float(total_ids * dim) * elem * 2.0 * (n - 1) / n
        flops += float(total_ids * dim)  # fp32 accumulation of the shards
    return flops, nbytes


def _collective_cost(op, ins, outs, axis_sizes):
    """(flops, wire_bytes) for a collective op given bound axis sizes."""
    from .collectives import collective_axis

    payload = _first(ins, "X")
    nbytes = _nbytes(payload)
    # per-op emitter axis defaults live in collectives.py (dp/sp/pp/ps…)
    ax, _kind = collective_axis(op)
    if ax is None:
        ax = op.attr("axis_name", "dp")
    n = int(axis_sizes.get(ax, 1))
    if n <= 1:
        return 0.0, 0.0  # unbound axis: the emitter degrades to identity
    factor = _COLLECTIVE_FACTORS.get(op.type, lambda n: 1.0)(n)
    if op.type in ("zero_reduce_scatter", "zero_all_gather",
                   "zero_bucket_reduce_scatter"):
        # the wire payload is the PADDED flat vector at the (possibly
        # quantized) element size, not the declared input tensor:
        # pad_len * (1B + 4B/quant_block) int8, pad_len * itemsize fp.
        # A bucket's payload is the sum of its members' pads.
        if op.type == "zero_bucket_reduce_scatter":
            pad = int(sum(int(p) for p in (op.attr("pad_lens") or ())))
            if not pad:
                pad = sum(
                    _nelem(v) for v in ins.get("X", ()) if v is not None
                )
        else:
            pad = int(op.attr("pad_len") or _nelem(payload))
        elem = _quant_elem_bytes(
            op.attr("quant", "none"), op.attr("quant_block", 256),
            payload[1] if payload else 4,
        )
        # reduce-scatter sums n contributions per received element
        flops = float(pad) if op.type != "zero_all_gather" else 0.0
        return flops, pad * elem * factor
    if op.type == "c_bucket_allreduce_sum":
        elems = sum(_nelem(v) for v in ins.get("X", ()) if v is not None)
        itemsize = payload[1] if payload else 4
        return float(elems), elems * itemsize * factor
    flops = float(_nelem(payload)) if "allreduce" in op.type else 0.0
    return flops, nbytes * factor


def op_cost(op, in_specs, out_specs, axis_sizes=None):
    """(flops, bytes) for ONE execution of `op` at the given specs.

    in_specs/out_specs: {slot: [(shape, itemsize) | None, ...]}.
    """
    t = op.type
    generic_bytes = _all_bytes(in_specs, out_specs)
    if t in _COLLECTIVE_FACTORS:
        return _collective_cost(op, in_specs, out_specs, axis_sizes or {})
    if t in SPARSE_LOOKUP_OPS:
        return _lookup_cost(op, in_specs, out_specs, axis_sizes or {})
    if t in MATMUL_OPS:
        return _flops_matmul(op, in_specs, out_specs), generic_bytes
    if t in CONV_OPS:
        return _flops_conv(op, in_specs, out_specs), generic_bytes
    if t in ATTENTION_OPS:
        return _flops_attention(op, in_specs, out_specs), generic_bytes
    if t in NORM_OPS:
        return _flops_norm(op, in_specs, out_specs), generic_bytes
    if t in OPTIMIZER_OPS:
        return _flops_optimizer(op, in_specs, out_specs), generic_bytes
    if t in ("pool2d", "pool3d", "max_pool2d_with_index",
             "max_pool3d_with_index", "unpool", "spp"):
        return _flops_pool(op, in_specs, out_specs), generic_bytes
    if t in DATA_OPS or t in EMBED_OPS:
        slot = _GATHER_TABLE_SLOTS.get(t)
        table = _first(in_specs, slot) if slot else None
        if table is not None:
            out_bytes = sum(
                _nbytes(v)
                for vals in out_specs.values() for v in vals if v is not None
            )
            return 0.0, generic_bytes - _nbytes(table) + out_bytes
        return 0.0, generic_bytes
    if t in REDUCE_OPS:
        x = _first(in_specs, "X")
        return float(_nelem(x)), generic_bytes
    if t == "sum":  # n-ary accumulate
        out = _first(out_specs, "Out")
        n_in = sum(1 for v in in_specs.get("X", []) if v is not None)
        return float(max(n_in - 1, 1) * _nelem(out)), generic_bytes
    weight = ELEMENTWISE_WEIGHTS.get(t, 1.0)
    # elementwise default: weight flops per OUTPUT element
    out_elems = sum(
        _nelem(v)
        for vals in out_specs.values() for v in vals if v is not None
    )
    if out_elems == 0:
        out_elems = sum(
            _nelem(v)
            for vals in in_specs.values() for v in vals if v is not None
        )
    return weight * out_elems, generic_bytes


# ---------------------------------------------------------------------------
# overlap-aware schedule simulation
# ---------------------------------------------------------------------------


def _scheduled_latency(entries):
    """Two-resource step-time simulation over the walk-order cost entries
    ``(latency, is_wire, reads, writes)``: compute executes ops in program
    order on one timeline; a collective occupies the wire channel (one
    collective in flight at a time — the ICI serializes) starting when its
    inputs exist and the channel is free, WITHOUT blocking compute; a
    compute op that READS a collective's output waits for that collective
    to land. The result is max(compute, wire) per overlap segment instead
    of the serialized global sum — the latency-hiding-scheduler model the
    bucketed/prefetched transpile is shaped for."""
    t_c = 0.0  # compute timeline
    wire_free = 0.0  # when the wire channel is next available
    pending = {}  # var name -> completion time of the collective writing it
    for lat, is_wire, reads, writes in entries:
        if is_wire:
            dep = max(
                (pending[r] for r in reads if r in pending), default=0.0
            )
            start = max(t_c, wire_free, dep)
            end = start + lat
            wire_free = end
            for w in writes:
                pending[w] = end
        else:
            for r in reads:
                if r in pending:
                    t_c = max(t_c, pending.pop(r))
            for w in writes:
                pending.pop(w, None)  # overwritten: the wire result is dead
            t_c += lat
    return max(t_c, wire_free)


# ---------------------------------------------------------------------------
# the walker
# ---------------------------------------------------------------------------

_SKIP_OPS = frozenset({
    "feed", "fetch", "print", "assert", "py_func", "delete_var",
    "c_comm_init_all", "get_places", "is_empty",
})


class _Estimator:
    def __init__(self, program, feed_shapes, table):
        self.program = program
        self.table = table
        self.feed_shapes = {
            k: tuple(int(d) for d in v)
            for k, v in (feed_shapes or {}).items()
        }
        self.batch_hint = next(
            (s[0] for s in self.feed_shapes.values() if s), 1
        )
        self.pinned = set()  # distinct (var name, dim index) pins
        self.unknown_ops = {}
        # walk-order (latency, is_wire, reads, writes) entries feeding the
        # overlap-aware schedule simulation (_scheduled_latency)
        self.sched = []
        mesh = getattr(program, "_mesh", None)
        self.axis_sizes = dict(mesh.shape) if mesh is not None else {}

    # -- shape resolution --------------------------------------------------
    def _spec(self, block, name):
        if not name:
            return None
        v = block._find_var_recursive(name)
        if name in self.feed_shapes:
            shape = self.feed_shapes[name]
            dtype = v.dtype if v is not None and v.dtype else "float32"
            return shape, np.dtype(to_numpy_dtype(dtype)).itemsize
        if v is None or v.shape is None:
            return None
        shape = []
        for di, d in enumerate(v.shape):
            if d in (-1, None):
                shape.append(self.batch_hint)
                self.pinned.add((name, di))
            else:
                shape.append(int(d))
        try:
            itemsize = np.dtype(to_numpy_dtype(v.dtype or "float32")).itemsize
        except Exception:
            itemsize = 4
        return tuple(shape), itemsize

    def _specs(self, block, slot_names):
        return {
            slot: [self._spec(block, n) for n in names]
            for slot, names in (slot_names or {}).items()
        }

    # -- op dispatch -------------------------------------------------------
    def walk_block(self, block, count=1, depth=0):
        if depth > 16:
            return
        for i, op in enumerate(block.ops):
            self.visit(op, block, i, count, depth)

    def visit(self, op, block, op_index, count, depth):
        t = op.type
        if t in _SKIP_OPS:
            return
        if t == "__vjp__":
            self._visit_vjp(op, block, op_index, count)
            return
        if t in ("pipeline_block", "pipeline_uniform"):
            self._visit_pipeline(op, block, op_index, count, depth)
            return
        if t == "recompute_segment":
            self._visit_recompute(op, block, op_index, count, depth,
                                  grad=False)
            return
        if t in ("cond", "conditional_block", "conditional_block_infer"):
            self._visit_branch(op, block, op_index, count, depth)
            return
        sub = op.attr("sub_block")
        if sub is not None and t in ("while", "scan_block", "bounded_while"):
            # bounded_while lowers onto lax.scan over a STATIC max_iters
            # bound; scan_block's trip count is its SeqIn leading dim
            trips = op.attr("max_iters", None)
            if trips is None and t == "scan_block":
                seq_names = (op.inputs or {}).get("SeqIn") or []
                seq = self._spec(block, seq_names[0]) if seq_names else None
                if seq:
                    trips = seq[0][0]
            mult = int(trips) if trips else 1
            if not trips:
                self.table.assumptions.append(
                    f"loop body of {t!r} (block {sub}) counted once "
                    "(no static trip count)"
                )
            self.walk_block(self.program.blocks[sub], count * mult,
                            depth + 1)
            return
        from ..framework.registry import _REGISTRY

        if t not in _REGISTRY:
            self.unknown_ops[t] = self.unknown_ops.get(t, 0) + 1
            return
        ins = self._specs(block, op.inputs)
        outs = self._specs(block, op.outputs)
        flops, nbytes = op_cost(op, ins, outs, self.axis_sizes)
        self._record(op, t, flops, nbytes, count, block.idx, op_index)

    _SUB_BLOCK_FWD = frozenset({
        "while", "bounded_while", "scan_block", "cond",
        "conditional_block", "pipeline_block", "pipeline_uniform",
    })

    def _visit_vjp(self, op, block, op_index, count):
        from ..framework.registry import OpView

        fwd_type = op.attr("fwd_type")
        if fwd_type in self._SUB_BLOCK_FWD:
            # replaying a looped/branched body's vjp is not modeled yet;
            # recording the omission beats silently costing it as a
            # near-zero elementwise op
            self.table.assumptions.append(
                f"backward of sub-block op {fwd_type!r} not modeled "
                "(cost omitted)"
            )
            return
        fwd_op = OpView(fwd_type, op.attr("fwd_attrs"))
        fwd_ins = {
            slot[len("FwdIn:"):]: [self._spec(block, n) for n in names]
            for slot, names in op.inputs.items()
            if slot.startswith("FwdIn:")
        }
        # the forward op's OUTPUT shapes arrive as this op's OutGrad inputs
        fwd_outs = {
            slot[len("OutGrad:"):]: [self._spec(block, n) for n in names]
            for slot, names in op.inputs.items()
            if slot.startswith("OutGrad:")
        }
        if fwd_type == "recompute_segment":
            self._visit_recompute(fwd_op, block, op_index, count, 0,
                                  grad=True)
            return
        if fwd_type in SPARSE_LOOKUP_OPS:
            # one segment-sum scatter per table + the (possibly quantized)
            # grad exchange — NOT 2x the forward gather
            flops, nbytes = _lookup_grad_cost(
                fwd_op, fwd_ins, fwd_outs, self.axis_sizes
            )
            self._record(op, f"{fwd_type}_grad", flops, nbytes, count,
                         block.idx, op_index)
            return
        flops, nbytes = op_cost(fwd_op, fwd_ins, fwd_outs, self.axis_sizes)
        # each WANTED input grad of a contraction is one forward-sized
        # contraction (dX and dW of a matmul/conv are each 2MNK; a
        # first-layer conv never computes dX) — the replayed forward
        # itself is CSE-merged with the original, so not counted
        wanted = sum(
            1 for slot, names in op.outputs.items()
            if slot.startswith("InGrad:") and any(names)
        )
        fam = family_of(fwd_type)
        if fam in ("matmul", "conv", "attention"):
            mult = float(max(wanted, 1))
        elif fam == "normalization":
            mult = 1.75  # d(norm) re-reduces once whatever grads are wanted
        else:
            mult = float(min(max(wanted, 1), 2))
        self._record(op, f"{fwd_type}_grad", mult * flops, mult * nbytes,
                     count, block.idx, op_index)

    def _visit_recompute(self, op, block, op_index, count, depth, grad):
        from ..framework.registry import OpView

        mult = 3.0 if grad else 1.0  # bwd = re-run fwd + 2x-fwd vjp
        for ot, oins, oouts, oattrs in op.attr("sub_ops", ()):
            view = OpView(ot, oattrs, oins, oouts)
            ins = self._specs(block, oins)
            outs = self._specs(block, oouts)
            flops, nbytes = op_cost(view, ins, outs, self.axis_sizes)
            self._record(
                view, ot + ("_grad" if grad else ""), mult * flops,
                mult * nbytes, count, block.idx, op_index,
                loc=op.attr("__loc__", ""),
            )

    def _visit_pipeline(self, op, block, op_index, count, depth):
        # M microbatches at B/M each sum to the declared-[B] cost, so each
        # stage block is walked once at graph-build shapes
        if op.type == "pipeline_uniform":
            body = op.attr("stage_block")
            if body is not None:
                self.walk_block(self.program.blocks[body], count, depth + 1)
            return
        for bi in op.attr("stage_blocks") or ():
            self.walk_block(self.program.blocks[bi], count, depth + 1)

    def _visit_branch(self, op, block, op_index, count, depth):
        # both branches are traced but one executes: charge the costlier
        best, best_sub = -1.0, None
        for attr in ("true_block", "false_block", "sub_block"):
            bi = op.attr(attr)
            if bi is None:
                continue
            sub = _Estimator(self.program, self.feed_shapes, CostTable(
                peak_flops=self.table.peak_flops,
                peak_bandwidth=self.table.peak_bandwidth,
            ))
            sub.axis_sizes = self.axis_sizes
            sub.batch_hint = self.batch_hint
            sub.walk_block(self.program.blocks[bi], count, depth + 1)
            lat = sub.table.total_latency
            if lat > best:
                best, best_sub = lat, sub
        if best_sub is not None:
            self.table.ops.extend(best_sub.table.ops)
            self.sched.extend(best_sub.sched)
            # pins / skipped ops inside the charged branch must still
            # surface in the parent's assumptions
            self.table.assumptions.extend(best_sub.table.assumptions)
            self.pinned |= best_sub.pinned
            for t, n in best_sub.unknown_ops.items():
                self.unknown_ops[t] = self.unknown_ops.get(t, 0) + n

    def _record(self, op, op_type, flops, nbytes, count, block_idx,
                op_index, loc=None):
        flops *= count
        nbytes *= count
        lat = max(
            flops / self.table.peak_flops if self.table.peak_flops else 0.0,
            nbytes / self.table.peak_bandwidth
            if self.table.peak_bandwidth else 0.0,
        )
        family = family_of(
            op_type[:-5] if op_type.endswith("_grad") else op_type
        )
        reads = tuple(
            n for names in (getattr(op, "inputs", None) or {}).values()
            for n in names if n
        )
        writes = tuple(
            n for names in (getattr(op, "outputs", None) or {}).values()
            for n in names if n
        )
        if not hasattr(self, "sched"):  # bare _Estimator (tests) tolerated
            self.sched = []
        self.sched.append((lat, family == "collective", reads, writes))
        self.table.ops.append(OpCost(
            op_type=op_type, family=family,
            flops=flops, bytes=float(nbytes), latency=lat, count=count,
            block_idx=block_idx, op_index=op_index,
            loc=loc if loc is not None else str(
                op.attr("__loc__", "") or ""
            ),
        ))


def estimate_program(program, feed_shapes=None, peak_tflops=None,
                     peak_gbps=None) -> CostTable:
    """Analytic per-op cost table for ONE step of `program`.

    feed_shapes: {var name: shape} pinning -1 (batch) dims — pass the
    shapes of the batch you will actually feed (``Program.estimate``
    forwards them). Unpinned -1 dims fall back to the leading dim of any
    feed, else 1, and are recorded in ``table.assumptions``.
    """
    table = CostTable(
        peak_flops=(
            peak_tflops * 1e12 if peak_tflops is not None else peak_flops()
        ),
        peak_bandwidth=(
            peak_gbps * 1e9 if peak_gbps is not None else peak_bandwidth()
        ),
    )
    est = _Estimator(program, feed_shapes, table)
    est.walk_block(program.global_block)
    if getattr(program, "_overlap_schedule", False):
        # the transpiler restructured the collective schedule for overlap
        # (bucketed grad collectives / prefetched all-gathers): estimate
        # the step as the two-resource simulation instead of the
        # serialized sum, and record the modeling choice
        table.scheduled_latency = _scheduled_latency(est.sched)
        table.assumptions.append(
            "overlap schedule: step estimated as max(compute, wire) per "
            "overlap segment (collectives on a concurrent wire channel)"
        )
    if est.pinned:
        table.assumptions.append(
            f"pinned {len(est.pinned)} unknown (-1) dims to batch hint "
            f"{est.batch_hint}"
        )
    for t, n in sorted(est.unknown_ops.items()):
        table.assumptions.append(
            f"unregistered op type {t!r} x{n} skipped"
        )
    try:
        from .memory import plan_memory

        # budget=None: the oom-risk gate belongs to the verifier; the
        # estimate just reports the plan
        mem = plan_memory(program, feed_shapes=feed_shapes, budget=None)
        table.memory = mem
        table.peak_bytes = mem.peak_bytes
        table.resident_bytes = mem.resident_bytes
    except Exception as exc:  # the cost table must survive a planner bug
        table.assumptions.append(f"static memory plan unavailable: {exc!r}")
    return table
