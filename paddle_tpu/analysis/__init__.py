"""Static program analysis: pre-compile verification + cross-rank
collective lint over the Program IR.

Every Program otherwise goes straight from graph construction into one
``jax.jit`` trace (framework/executor.py:_compile), where a malformed
graph surfaces as an opaque XLA error with no op attribution — or, for a
mismatched collective, as a silent multi-rank hang. Pass-based IR
verification is standard in tensor compilers (TVM, arXiv:1802.04799), and
whole-block fusion (arXiv:2301.13062) makes *pre-trace* the only point
where per-op source provenance still exists. This package runs four
analysis families and returns structured :class:`Finding`\\ s:

* structural  — use-before-def vs feeds/persistables/scope, undeclared
  reads/writes, silent name redefinition, unknown op types, dead ops and
  unreachable variables (structural.py);
* shape/dtype — per-op replay of ``registry.infer_shapes`` cross-checked
  against every declared Variable, with -1/BATCH_SENTINEL handling
  (shapes.py);
* collective schedule — per-rank simulation of the op streams the
  SPMD/pipeline transpilers produce; order/kind/axis must agree across
  ranks and every axis must exist in the Program's mesh (collectives.py);
* memory/liveness — per-op live-interval simulation producing the static
  peak-HBM plan (resident persistables, transient peak, watermark op),
  the donation/aliasing verifier (use-after-donate, missed-donation,
  recompute-no-savings), and the ``PADDLE_TPU_HBM_BYTES`` oom-risk gate
  (memory.py).

Wired into ``Executor._compile`` behind ``PADDLE_TPU_VERIFY``
(``strict`` | ``warn`` (default) | ``0``); ``tools/program_lint.py``
lints every bundled model from the command line. README §Static analysis
documents categories and severity semantics.
"""

from __future__ import annotations

from .findings import (  # noqa: F401
    COLLECTIVE_BRANCH_DIVERGENCE,
    COLLECTIVE_DIVERGENCE,
    DEAD_OP,
    DTYPE_DESYNC,
    MISSED_DONATION,
    MISSING_FEED,
    OOM_RISK,
    RECOMPUTE_NO_SAVINGS,
    REDEFINITION,
    SHAPE_DESYNC,
    STRICT_ESCALATIONS,
    UNDECLARED_VAR,
    UNDECLARED_WRITE,
    UNKNOWN_MESH_AXIS,
    UNKNOWN_OP,
    UNREACHABLE_VAR,
    USE_AFTER_DONATE,
    USE_BEFORE_DEF,
    Finding,
    Report,
    Severity,
)
from .collectives import analyze_collectives, collective_axis  # noqa: F401
from .cost import (  # noqa: F401
    CostTable,
    OpCost,
    estimate_program,
    family_of,
    op_cost,
    peak_flops,
)
from .memory import (  # noqa: F401
    MemoryTable,
    analyze_memory,
    hbm_budget,
    plan_memory,
)
from .shapes import analyze_shapes  # noqa: F401
from .structural import analyze_structural  # noqa: F401
from .verify import (  # noqa: F401
    check_before_compile,
    set_verify_mode,
    verify_mode,
    verify_program,
)
