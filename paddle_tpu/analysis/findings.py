"""Structured findings produced by the pre-compile program verifier.

A Finding is one defect (or observation) anchored to an op: severity,
a stable category slug (tests and CI grep these), the offending names,
and the user source frame the Operator captured at build time
(program.py:_user_frame / the ``__loc__`` attr) — so a build-time lint
names the Python line that created the bad op, which no post-trace XLA
error can do (the whole-block jit erases op boundaries).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class Severity(enum.IntEnum):
    """INFO: stylistic/dead code — never fails a build.
    WARNING: suspicious but runnable (silent redefinition, a collective
    over an axis the mesh does not bind, ...).
    ERROR: the program will fail to trace, compute garbage, or deadlock
    a multi-rank run; strict mode refuses to compile it."""

    INFO = 0
    WARNING = 1
    ERROR = 2


# categories (stable slugs; tests/test_program_analysis.py keys on these)
USE_BEFORE_DEF = "use-before-def"
UNDECLARED_VAR = "undeclared-var"
UNDECLARED_WRITE = "undeclared-write"
REDEFINITION = "redefinition"
UNKNOWN_OP = "unknown-op"
DEAD_OP = "dead-op"
UNREACHABLE_VAR = "unreachable-var"
SHAPE_DESYNC = "shape-desync"
DTYPE_DESYNC = "dtype-desync"
TRAINING_OP_IN_INFERENCE = "training-op-in-inference"
COLLECTIVE_DIVERGENCE = "collective-divergence"
COLLECTIVE_BRANCH_DIVERGENCE = "collective-branch-divergence"
UNKNOWN_MESH_AXIS = "unknown-mesh-axis"
MISSING_FEED = "missing-feed"
OOM_RISK = "oom-risk"
USE_AFTER_DONATE = "use-after-donate"
MISSED_DONATION = "missed-donation"
RECOMPUTE_NO_SAVINGS = "recompute-no-savings"

# WARNING findings in these categories count as errors under strict
# verify (the redefinition satellite: "warn; error under strict";
# oom-risk: an over-HBM-budget program is refused pre-compile)
STRICT_ESCALATIONS = frozenset({REDEFINITION, OOM_RISK})


@dataclass
class Finding:
    severity: Severity
    category: str
    message: str
    block_idx: int = 0
    op_index: int | None = None
    op_type: str | None = None
    names: tuple = ()
    loc: str | None = None  # user source frame that created the op/var

    def to_dict(self) -> dict:
        """Stable machine-readable form (``program_lint --json`` emits
        these; downstream dashboards key on the field names)."""
        return {
            "severity": self.severity.name,
            "category": self.category,
            "message": self.message,
            "block_idx": self.block_idx,
            "op_index": self.op_index,
            "op_type": self.op_type,
            "names": list(self.names),
            "loc": self.loc,
        }

    def format(self) -> str:
        where = []
        if self.op_index is not None:
            where.append(f"op #{self.op_index}")
        if self.op_type:
            where.append(f"{self.op_type!r}")
        if self.block_idx:
            where.append(f"block {self.block_idx}")
        if self.loc:
            where.append(f"created at {self.loc}")
        suffix = f"  [{', '.join(where)}]" if where else ""
        return f"{self.severity.name}[{self.category}] {self.message}{suffix}"


def finding_for_op(severity, category, message, op=None, op_index=None,
                   block_idx=0, names=()):
    """Build a Finding anchored to an Operator, pulling its ``__loc__``."""
    return Finding(
        severity=severity,
        category=category,
        message=message,
        block_idx=block_idx,
        op_index=op_index,
        op_type=getattr(op, "type", None),
        names=tuple(names),
        loc=op.attr("__loc__") if hasattr(op, "attr") else None,
    )


@dataclass
class Report:
    """The verifier's output: an ordered finding list plus helpers."""

    findings: list = field(default_factory=list)

    def add(self, finding: Finding) -> None:
        self.findings.append(finding)

    def extend(self, findings) -> None:
        self.findings.extend(findings)

    @property
    def errors(self):
        return [f for f in self.findings if f.severity == Severity.ERROR]

    @property
    def warnings(self):
        return [f for f in self.findings if f.severity == Severity.WARNING]

    @property
    def infos(self):
        return [f for f in self.findings if f.severity == Severity.INFO]

    def by_category(self, category):
        return [f for f in self.findings if f.category == category]

    def strict_errors(self):
        """Errors under strict mode: ERROR findings plus WARNING findings
        in the escalated categories (silent redefinition)."""
        return [
            f for f in self.findings
            if f.severity == Severity.ERROR
            or (f.severity == Severity.WARNING
                and f.category in STRICT_ESCALATIONS)
        ]

    @property
    def ok(self) -> bool:
        return not self.errors

    def render(self, min_severity=Severity.INFO,
               max_per_severity=25) -> str:
        """Human rendering, capped at ``max_per_severity`` findings per
        severity so a detection-sized program doesn't flood the single
        ``ProgramVerifyWarning`` — the elided tail is summarized per
        category; the full list stays on the Report / exception object.
        ``max_per_severity=None`` renders everything."""
        picked = [
            f for f in sorted(
                self.findings, key=lambda f: -int(f.severity)
            )
            if f.severity >= min_severity
        ]
        if not picked:
            return "program verifier: clean bill (no findings)"
        head = (
            f"program verifier: {len(self.errors)} error(s), "
            f"{len(self.warnings)} warning(s), {len(self.infos)} info"
        )
        lines = [head]
        for sev in (Severity.ERROR, Severity.WARNING, Severity.INFO):
            group = [f for f in picked if f.severity == sev]
            shown = group if max_per_severity is None else (
                group[:max_per_severity]
            )
            lines.extend("  " + f.format() for f in shown)
            hidden = group[len(shown):]
            if hidden:
                by_cat = {}
                for f in hidden:
                    by_cat[f.category] = by_cat.get(f.category, 0) + 1
                cats = ", ".join(
                    f"{c} x{n}" for c, n in sorted(by_cat.items())
                )
                lines.append(
                    f"  … +{len(hidden)} more {sev.name} "
                    f"finding(s) ({cats})"
                )
        return "\n".join(lines)
