"""Static peak-HBM planner + donation/liveness verifier — the memory
analysis family (ROADMAP items 1 and 5 both need an analytic peak-memory
footing; the paged-KV concurrency math consumes :func:`plan_memory`).

The planner answers *will this Program fit in HBM?* before any trace:
a per-op live-interval simulation over the IR, with shapes resolved the
same way the cost walker resolves them (declared Variable shapes, -1
batch dims pinned by ``feed_shapes`` / the batch hint). Producer-consumer
liveness is exactly the picture whole-block XLA fusion optimizes
(arXiv:2301.13062); modeling it per-op at IR level is the pre-execution
resource model arXiv:2008.01040 learns, in closed form.

Accounting model:

* **resident** — every *persistable* the program references (parameters,
  optimizer state, KV caches), counted once, sharding-aware: a var whose
  ``program._sharding`` spec names mesh axes is divided by those axis
  sizes (ZeRO ``[pad]`` shards, row/col-partitioned embedding tables);
  hot-tier-shrunk tables need no special case because the embedding
  engine rewrites the *declared* shape in place.
* **feeds** — input buffers, live for the whole step (XLA holds
  non-donated arguments until the executable returns).
* **transients** — everything else lives from first def to last use; the
  peak of ``resident + feeds + live transient set`` over the op walk is
  ``peak_bytes``, and the op where it happens is the **watermark**
  (anchored to its ``__loc__`` source frame).
* ``recompute_segment`` interiors die at the segment boundary (that is
  the point of checkpointing) and are re-materialized as the backward
  op's working set; a segment whose interior set is empty saves nothing
  → ``recompute-no-savings`` INFO.
* ``pipeline_block`` stage sub-blocks report per-stage transient peaks
  (each stage's activations live on its own device).
* ``cond`` branches charge the branch with the larger transient peak;
  loop bodies are walked once (one iteration's live set — XLA double
  buffering is not modeled; recorded in assumptions).

On top of the intervals, the donation verifier: an op whose
:class:`~paddle_tpu.framework.registry.OpDef` declares ``mutates``
aliases an output over an input buffer (``kv_cache_write``, the
optimizer write-backs). Reading the donated input *after* the donating
write observes a dead buffer under the executor's donation contract →
``use-after-donate`` ERROR. The inverse — a persistable whose last read
feeds a same-shape/dtype write through a non-mutating op — is a missed
aliasing opportunity → ``missed-donation`` INFO.

``oom-risk`` (WARNING, escalated to an error under strict verify) fires
when ``peak_bytes`` exceeds ``PADDLE_TPU_HBM_BYTES`` (plain bytes, or
``"16G"``-style binary suffixes). README §Static analysis documents the
finding catalog and when the estimate is trusted vs XLA's own
``memory_analysis`` (``Executor.memory_analysis``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import numpy as np

from ..core.dtypes import to_numpy_dtype
from .cost import _SKIP_OPS, family_of
from .findings import (
    MISSED_DONATION,
    OOM_RISK,
    RECOMPUTE_NO_SAVINGS,
    USE_AFTER_DONATE,
    Finding,
    Severity,
)

_SUFFIXES = {"k": 2 ** 10, "m": 2 ** 20, "g": 2 ** 30, "t": 2 ** 40}

# missed-donation only surfaces buffers worth aliasing; scalar
# bookkeeping (learning rate, beta pows) is noise below this
_MISSED_DONATION_MIN_BYTES = 64 * 2 ** 10


def hbm_budget():
    """Per-device HBM budget in bytes from ``PADDLE_TPU_HBM_BYTES``
    (plain float bytes, or a ``K``/``M``/``G``/``T`` binary suffix:
    ``"16G"`` = 16 GiB). ``None`` when unset or unparseable."""
    raw = os.environ.get("PADDLE_TPU_HBM_BYTES", "").strip().lower()
    if not raw:
        return None
    mult = 1.0
    if raw[-1] in _SUFFIXES:
        mult = _SUFFIXES[raw[-1]]
        raw = raw[:-1]
    try:
        val = float(raw) * mult
    except ValueError:
        return None
    return val if val > 0 else None


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit, size in (("GiB", 2 ** 30), ("MiB", 2 ** 20), ("KiB", 2 ** 10)):
        if n >= size:
            return f"{n / size:.2f} {unit}"
    return f"{n:.0f} B"


# ---------------------------------------------------------------------------
# tables
# ---------------------------------------------------------------------------


@dataclass
class MemoryTable:
    """The planner's output: byte totals, the watermark op, the per-op
    live-set timeline, per-pipeline-stage peaks, and the memory-family
    findings the walk produced."""

    resident_bytes: float = 0.0
    feed_bytes: float = 0.0
    transient_peak_bytes: float = 0.0
    peak_bytes: float = 0.0
    budget_bytes: float | None = None
    watermark: dict | None = None
    timeline: list = field(default_factory=list)
    stage_peaks: dict = field(default_factory=dict)
    residents: list = field(default_factory=list)  # (name, bytes) desc
    assumptions: list = field(default_factory=list)
    findings: list = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "resident_bytes": float(self.resident_bytes),
            "feed_bytes": float(self.feed_bytes),
            "transient_peak_bytes": float(self.transient_peak_bytes),
            "peak_bytes": float(self.peak_bytes),
            "budget_bytes": (
                float(self.budget_bytes)
                if self.budget_bytes is not None else None
            ),
            "watermark": dict(self.watermark) if self.watermark else None,
            "stage_peaks": {
                int(k): float(v) for k, v in sorted(self.stage_peaks.items())
            },
            "top_residents": [
                {"name": n, "bytes": float(b)} for n, b in self.residents[:10]
            ],
            "timeline_ops": len(self.timeline),
            "assumptions": list(self.assumptions),
            "findings": [
                {"severity": f.severity.name, "category": f.category}
                for f in self.findings
            ],
        }

    def format(self, top: int = 5) -> str:
        lines = [
            "static memory: resident "
            f"{_fmt_bytes(self.resident_bytes)} + feeds "
            f"{_fmt_bytes(self.feed_bytes)} + transient peak "
            f"{_fmt_bytes(self.transient_peak_bytes)} = peak "
            f"{_fmt_bytes(self.peak_bytes)}"
        ]
        if self.budget_bytes is not None:
            verdict = "OVER" if self.peak_bytes > self.budget_bytes else "ok"
            lines.append(
                f"  budget {_fmt_bytes(self.budget_bytes)} "
                f"(PADDLE_TPU_HBM_BYTES): {verdict}"
            )
        wm = self.watermark
        if wm:
            where = f"op #{wm['op_index']} {wm['op_type']!r}"
            if wm.get("block_idx"):
                where += f" block {wm['block_idx']}"
            if wm.get("loc"):
                where += f", created at {wm['loc']}"
            lines.append(
                f"  watermark: {where}  "
                f"live {_fmt_bytes(wm['live_bytes'])}"
            )
            for name, b in (wm.get("top_live") or [])[:top]:
                lines.append(f"    live: {name}  {_fmt_bytes(b)}")
        for s, b in sorted(self.stage_peaks.items()):
            lines.append(
                f"  pipeline stage {s}: transient peak {_fmt_bytes(b)}"
            )
        for a in self.assumptions:
            lines.append(f"  assuming: {a}")
        return "\n".join(lines)


class _Event:
    """One flattened walk step: the names it reads/writes, where it came
    from, its donation pairs, and any op-local working set (bytes that are
    live only while the op runs — the recompute-backward rematerialized
    interiors)."""

    __slots__ = ("op_type", "reads", "writes", "block_idx", "op_index",
                 "loc", "stage", "extra_bytes", "donations", "reuse")

    def __init__(self, op_type, reads, writes, block_idx, op_index, loc,
                 stage=None, extra_bytes=0.0, donations=(), reuse=False):
        self.op_type = op_type
        self.reads = reads
        self.writes = writes
        self.block_idx = block_idx
        self.op_index = op_index
        self.loc = loc
        self.stage = stage
        self.extra_bytes = extra_bytes
        self.donations = donations
        self.reuse = reuse


class _VarInfo:
    __slots__ = ("nbytes", "shape", "dtype", "persistable", "is_data")

    def __init__(self, nbytes, shape, dtype, persistable, is_data):
        self.nbytes = nbytes
        self.shape = shape
        self.dtype = dtype
        self.persistable = persistable
        self.is_data = is_data


# ---------------------------------------------------------------------------
# the planner
# ---------------------------------------------------------------------------


class _MemoryPlanner:
    def __init__(self, program, feed_names, fetch_names, feed_shapes):
        self.program = program
        self.feed_shapes = {
            k: tuple(int(d) for d in v)
            for k, v in (feed_shapes or {}).items()
        }
        self.batch_hint = next(
            (s[0] for s in self.feed_shapes.values() if s), 1
        )
        self.fetch_names = tuple(fetch_names or ())
        if feed_names:
            self.feed_names = tuple(feed_names)
        else:
            self.feed_names = tuple(
                v.name
                for v in program.global_block.vars.values()
                if getattr(v, "is_data", False)
            )
        mesh = getattr(program, "_mesh", None)
        self.axis_sizes = dict(mesh.shape) if mesh is not None else {}
        self.sharding = dict(getattr(program, "_sharding", None) or {})
        self.events = []
        self.vars = {}  # name -> _VarInfo (first block that resolved it)
        self.pinned = set()
        self.assumptions = []
        self.findings = []
        self.segments = []  # (op, block_idx, op_index, interior_bytes, n_sub)
        self.saw_backward = False

    # -- shape / byte resolution -------------------------------------------
    def _info(self, block, name):
        info = self.vars.get(name)
        if info is not None:
            return info
        v = block._find_var_recursive(name)
        if name in self.feed_shapes:
            shape = self.feed_shapes[name]
            dtype = (v.dtype if v is not None and v.dtype else "float32")
        elif v is None or v.shape is None:
            return None
        else:
            shape = []
            for di, d in enumerate(v.shape):
                if d in (-1, None):
                    shape.append(self.batch_hint)
                    self.pinned.add((name, di))
                else:
                    shape.append(int(d))
            shape = tuple(shape)
            dtype = v.dtype or "float32"
        try:
            itemsize = np.dtype(to_numpy_dtype(dtype)).itemsize
        except Exception:
            itemsize = 4
        elems = 1.0
        spec = self.sharding.get(name)
        for di, d in enumerate(shape):
            d = float(max(int(d), 0))
            if spec is not None and di < len(spec) and spec[di]:
                axes = spec[di]
                if not isinstance(axes, (tuple, list)):
                    axes = (axes,)
                for ax in axes:
                    size = self.axis_sizes.get(ax)
                    if size:
                        d = float(-(-int(d) // int(size)))  # ceil shard
            elems *= d
        info = _VarInfo(
            nbytes=elems * itemsize,
            shape=shape,
            dtype=str(dtype),
            persistable=bool(v is not None and v.persistable),
            is_data=bool(v is not None and getattr(v, "is_data", False)),
        )
        self.vars[name] = info
        return info

    def _bytes(self, block, name):
        info = self._info(block, name)
        return info.nbytes if info is not None else 0.0

    # -- flattening --------------------------------------------------------
    def _names(self, slot_names):
        return tuple(
            n for names in (slot_names or {}).values() for n in names if n
        )

    def _donations(self, op_type, op_ins, op_outs):
        from ..framework.registry import _REGISTRY

        op_def = _REGISTRY.get(op_type)
        if op_def is None or not op_def.mutates:
            return ()
        pairs = []
        for out_slot, in_slot in op_def.mutates:
            onames = (op_outs or {}).get(out_slot) or []
            inames = (op_ins or {}).get(in_slot) or []
            for iname, oname in zip(inames, onames):
                if iname and oname and iname != oname:
                    pairs.append((iname, oname))
        return tuple(pairs)

    def _emit(self, block, op_type, op_ins, op_outs, block_idx, op_index,
              loc, stage, extra_bytes=0.0, fwd_type=None):
        reads = self._names(op_ins)
        writes = self._names(op_outs)
        for n in reads + writes:
            self._info(block, n)  # resolve byte sizes eagerly
        # XLA buffer assignment lets an elementwise(-fused) op write over
        # an input buffer that dies at that op, so input and output are
        # never both live — model that reuse for the during-op window.
        # data_movement qualifies too: assign/reshape/cast outputs alias
        # (or copy-elide onto) an input that dies at the op, the pattern
        # autodiff's grad-accumulation renames produce in bulk
        reuse = family_of(fwd_type or op_type) in (
            "elementwise", "normalization", "data_movement"
        )
        self.events.append(_Event(
            op_type, reads, writes, block_idx, op_index, loc, stage,
            extra_bytes, self._donations(op_type, op_ins, op_outs), reuse,
        ))

    def walk_block(self, block, depth=0, stage=None):
        if depth > 16:
            return
        for i, op in enumerate(block.ops):
            self.visit(op, block, i, depth, stage)

    def visit(self, op, block, op_index, depth, stage):
        t = op.type
        if t in _SKIP_OPS:
            return
        loc = str(op.attr("__loc__", "") or "")
        if t == "__vjp__":
            self._visit_vjp(op, block, op_index, stage, loc)
            return
        if t in ("pipeline_block", "pipeline_uniform"):
            self._visit_pipeline(op, block, depth)
            return
        if t == "recompute_segment":
            self._visit_recompute(op, block, op_index, depth, stage, loc)
            return
        if t in ("cond", "conditional_block", "conditional_block_infer"):
            self._visit_branch(op, block, op_index, depth, stage)
            return
        sub = op.attr("sub_block")
        if sub is not None and t in ("while", "scan_block", "bounded_while"):
            self.assumptions.append(
                f"loop body of {t!r} (block {sub}) walked once — one "
                "iteration's live set (double buffering not modeled)"
            )
            self.walk_block(self.program.blocks[sub], depth + 1, stage)
            return
        self._emit(block, t, op.inputs, op.outputs, block.idx, op_index,
                   loc, stage)

    def _visit_vjp(self, op, block, op_index, stage, loc):
        self.saw_backward = True
        extra = 0.0
        if op.attr("fwd_type") == "recompute_segment":
            # the backward re-runs the segment under jax.checkpoint: its
            # interiors re-materialize as this op's working set
            fwd_attrs = op.attr("fwd_attrs") or {}
            extra = self._segment_interior_bytes(
                block, fwd_attrs.get("sub_ops", ()),
                fwd_attrs.get("out_names", ()),
            )[0]
        self._emit(block, "__vjp__", op.inputs, op.outputs, block.idx,
                   op_index, loc, stage, extra_bytes=extra,
                   fwd_type=op.attr("fwd_type"))

    def _segment_interior_bytes(self, block, sub_ops, out_names):
        outs = set(out_names or ())
        interior, seen = 0.0, set()
        for _ot, _oins, oouts, _oattrs in sub_ops or ():
            for names in (oouts or {}).values():
                for n in names:
                    if n and n not in outs and n not in seen:
                        seen.add(n)
                        interior += self._bytes(block, n)
        return interior, len(seen)

    def _visit_recompute(self, op, block, op_index, depth, stage, loc):
        sub_ops = op.attr("sub_ops", ())
        out_names = op.attr("out_names", ())
        for ot, oins, oouts, oattrs in sub_ops:
            self._emit(block, ot, oins, oouts, block.idx, op_index, loc,
                       stage)
        # interiors die here — only segment outputs (and persistables)
        # survive the boundary; jax.checkpoint re-makes the rest in the
        # backward. A later read of an interior would make it a segment
        # output by construction (_segment_io), so intervals need no cap,
        # but record the segment so the savings check can run post-walk.
        interior, n_interior = self._segment_interior_bytes(
            block, sub_ops, out_names
        )
        self.segments.append((op, block.idx, op_index, interior, n_interior,
                              len(tuple(sub_ops)), loc))

    def _visit_pipeline(self, op, block, depth):
        if op.type == "pipeline_uniform":
            body = op.attr("stage_block")
            if body is not None:
                self.walk_block(self.program.blocks[body], depth + 1,
                                stage=0)
            return
        for si, bi in enumerate(op.attr("stage_blocks") or ()):
            self.walk_block(self.program.blocks[bi], depth + 1, stage=si)

    def _visit_branch(self, op, block, op_index, depth, stage):
        # both branches are traced but one executes: charge the one with
        # the larger transient footprint
        best, best_events = -1.0, None
        for attr in ("true_block", "false_block", "sub_block"):
            bi = op.attr(attr)
            if bi is None:
                continue
            saved, self.events = self.events, []
            self.walk_block(self.program.blocks[bi], depth + 1, stage)
            captured, self.events = self.events, saved
            peak = _simulate(captured, self, base=0.0)[0]
            if peak > best:
                best, best_events = peak, captured
        if best_events:
            self.events.extend(best_events)
            self.assumptions.append(
                f"cond at block {block.idx} op #{op_index}: charged the "
                "branch with the larger transient peak"
            )

    # -- verification passes ----------------------------------------------
    def _verify_donations(self):
        donated = {}  # name -> (event idx, donor event)
        for i, ev in enumerate(self.events):
            for r in ev.reads:
                hit = donated.get(r)
                if hit is not None and hit[0] < i:
                    donor = hit[1]
                    self.findings.append(Finding(
                        severity=Severity.ERROR,
                        category=USE_AFTER_DONATE,
                        message=(
                            f"'{r}' is read after op "
                            f"#{donor.op_index} {donor.op_type!r} donated "
                            "its buffer (the output aliases it in-place); "
                            "the read observes a dead buffer under the "
                            "executor's donation contract"
                        ),
                        block_idx=ev.block_idx,
                        op_index=ev.op_index,
                        op_type=ev.op_type,
                        names=(r,),
                        loc=ev.loc or None,
                    ))
            for w in ev.writes:
                donated.pop(w, None)  # redefined: a fresh buffer
            for iname, _oname in ev.donations:
                donated[iname] = (i, ev)

    def _verify_missed_donations(self, last_read):
        from ..framework.registry import _REGISTRY

        for i, ev in enumerate(self.events):
            if ev.donations or ev.op_type == "__vjp__":
                continue
            op_def = _REGISTRY.get(ev.op_type)
            if op_def is None or op_def.mutates:
                continue
            for r in ev.reads:
                info = self.vars.get(r)
                if (info is None or not info.persistable
                        or info.nbytes < _MISSED_DONATION_MIN_BYTES
                        or r in self.feed_names or last_read.get(r) != i
                        # a same-name write IS the in-place update — the
                        # executor's write-back donation already aliases it
                        or r in ev.writes):
                    continue
                for w in ev.writes:
                    if w == r:
                        continue
                    winfo = self.vars.get(w)
                    if (winfo is not None and winfo.shape == info.shape
                            and winfo.dtype == info.dtype):
                        self.findings.append(Finding(
                            severity=Severity.INFO,
                            category=MISSED_DONATION,
                            message=(
                                f"last read of persistable '{r}' feeds a "
                                f"same-shape/dtype write '{w}' — the "
                                "buffer could alias (register the op "
                                "with mutates=(), or reuse the name) to "
                                f"save {_fmt_bytes(info.nbytes)}"
                            ),
                            block_idx=ev.block_idx,
                            op_index=ev.op_index,
                            op_type=ev.op_type,
                            names=(r, w),
                            loc=ev.loc or None,
                        ))
                        break

    def _verify_recompute(self):
        for op, block_idx, op_index, interior, n_interior, n_sub, loc in (
                self.segments):
            if interior > 0 and self.saw_backward:
                continue
            if not self.saw_backward:
                why = (
                    "no backward consumes it — checkpointing only adds "
                    "recompute cost in a forward-only program"
                )
            else:
                why = (
                    f"every one of its {n_sub} folded op(s)' outputs is a "
                    "segment output, so nothing is freed at the boundary"
                )
            self.findings.append(Finding(
                severity=Severity.INFO,
                category=RECOMPUTE_NO_SAVINGS,
                message=f"recompute segment saves no liveness: {why}",
                block_idx=block_idx,
                op_index=op_index,
                op_type="recompute_segment",
                loc=loc or None,
            ))


def _simulate(events, planner, base=0.0, fetch_names=(), track=False):
    """Live-interval simulation over flattened events. Returns
    ``(transient_peak, watermark, timeline, stage_peaks)`` — watermark /
    timeline / stage_peaks only populated when ``track``."""
    vars_ = planner.vars
    feed_set = set(planner.feed_names)

    def transient(name):
        info = vars_.get(name)
        if info is None:
            return None
        if info.persistable or info.is_data or name in feed_set:
            return None
        return info.nbytes

    last_use = {}
    for i, ev in enumerate(events):
        for n in ev.reads:
            last_use[n] = i
        for n in ev.writes:
            last_use.setdefault(n, i)
    end = len(events) - 1
    for n in fetch_names:
        if n in last_use:
            last_use[n] = end

    alive = {}
    cur_sum = 0.0
    peak, watermark = 0.0, None
    timeline = [] if track else None
    stage_peaks = {}
    for i, ev in enumerate(events):
        newly = 0.0
        for n in ev.writes + ev.reads:
            if n not in alive:
                b = transient(n)
                if b:
                    alive[n] = b
                    cur_sum += b
                    if n in ev.writes:
                        newly += b
        cur = base + cur_sum + ev.extra_bytes
        if ev.reuse and newly:
            dying = sum(
                alive[n] for n in set(ev.reads)
                if n in alive and n not in ev.writes
                and last_use.get(n) == i
            )
            cur -= min(dying, newly)
        if cur > peak:
            peak = cur
            if track:
                top = sorted(alive.items(), key=lambda kv: -kv[1])[:8]
                watermark = {
                    "block_idx": ev.block_idx,
                    "op_index": ev.op_index,
                    "op_type": ev.op_type,
                    "loc": ev.loc or None,
                    "live_bytes": cur,
                    "top_live": [(n, float(b)) for n, b in top],
                }
        if track:
            timeline.append({
                "block_idx": ev.block_idx,
                "op_index": ev.op_index,
                "op_type": ev.op_type,
                "live_bytes": cur,
                "n_live": len(alive),
            })
            if ev.stage is not None:
                prev = stage_peaks.get(ev.stage, 0.0)
                stage_peaks[ev.stage] = max(prev, cur_sum + ev.extra_bytes)
        for n in ev.reads + ev.writes:
            if last_use.get(n) == i and n in alive:
                cur_sum -= alive.pop(n)
    return peak, watermark, timeline, stage_peaks


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

_UNSET = object()


def plan_memory(program, feed_names=None, fetch_names=(), feed_shapes=None,
                budget=_UNSET) -> MemoryTable:
    """Static peak-HBM plan for ONE step of `program`.

    feed_shapes pins -1 (batch) dims exactly like ``Program.estimate``;
    feed_names defaults to the program's declared data vars. budget
    defaults to :func:`hbm_budget` (``PADDLE_TPU_HBM_BYTES``); pass
    ``None`` to skip the oom-risk check."""
    if budget is _UNSET:
        budget = hbm_budget()
    planner = _MemoryPlanner(program, feed_names, fetch_names, feed_shapes)
    planner.walk_block(program.global_block)

    # resident: referenced persistables, once each; feeds live throughout
    resident = 0.0
    residents = []
    for name, info in planner.vars.items():
        if info.persistable and name not in planner.feed_names:
            resident += info.nbytes
            residents.append((name, info.nbytes))
    residents.sort(key=lambda kv: -kv[1])
    feed_bytes = 0.0
    for name in planner.feed_names:
        info = planner.vars.get(name)
        if info is None:
            info = planner._info(program.global_block, name)
        if info is not None:
            feed_bytes += info.nbytes

    base = resident + feed_bytes
    peak, watermark, timeline, stage_peaks = _simulate(
        planner.events, planner, base=base,
        fetch_names=planner.fetch_names, track=True,
    )
    peak = max(peak, base)  # an op-free program still holds its state

    planner._verify_donations()
    last_read = {}
    for i, ev in enumerate(planner.events):
        for n in ev.reads:
            last_read[n] = i
    planner._verify_missed_donations(last_read)
    planner._verify_recompute()

    table = MemoryTable(
        resident_bytes=resident,
        feed_bytes=feed_bytes,
        transient_peak_bytes=max(peak - base, 0.0),
        peak_bytes=peak,
        budget_bytes=budget,
        watermark=watermark,
        timeline=timeline or [],
        stage_peaks=stage_peaks,
        residents=residents,
        assumptions=list(planner.assumptions),
        findings=list(planner.findings),
    )
    if planner.pinned:
        table.assumptions.append(
            f"pinned {len(planner.pinned)} unknown (-1) dims to batch "
            f"hint {planner.batch_hint}"
        )
    if budget is not None and peak > budget:
        wm = watermark or {}
        table.findings.append(Finding(
            severity=Severity.WARNING,
            category=OOM_RISK,
            message=(
                f"estimated peak HBM {_fmt_bytes(peak)} exceeds the "
                f"{_fmt_bytes(budget)} budget (PADDLE_TPU_HBM_BYTES); "
                f"resident {_fmt_bytes(resident)} + feeds "
                f"{_fmt_bytes(feed_bytes)} + transients peak at op "
                f"#{wm.get('op_index')} {wm.get('op_type')!r}"
            ),
            block_idx=wm.get("block_idx", 0) or 0,
            op_index=wm.get("op_index"),
            op_type=wm.get("op_type"),
            names=tuple(n for n, _ in (wm.get("top_live") or [])[:3]),
            loc=wm.get("loc"),
        ))
    return table


def analyze_memory(program, feed_names=(), fetch_names=()):
    """The verify-family entry: memory findings only (use-after-donate,
    missed-donation, recompute-no-savings, oom-risk against the
    ``PADDLE_TPU_HBM_BYTES`` budget when set)."""
    return plan_memory(
        program, feed_names or None, fetch_names
    ).findings
