"""Verifier orchestration: run the analysis families (structural, shape/
dtype, collective schedule, memory/liveness) over a Program and act on
the result per ``PADDLE_TPU_VERIFY``.

Modes (env var, overridable per-process with :func:`set_verify_mode`):
* ``strict`` — ERROR findings (plus escalated WARNINGs, e.g. silent
  redefinition) abort compilation with a typed
  :class:`~paddle_tpu.errors.ProgramVerifyError` carrying the findings.
* ``warn`` (default) — ERROR/WARNING findings surface as one
  :class:`~paddle_tpu.errors.ProgramVerifyWarning`; compilation proceeds.
* ``0`` / ``off`` — the executor hook is a no-op.

The pass is cached per (program version, feed set, fetch set): re-compiles
of the same program at new feed shapes (the executor's per-shape cache
misses) do not re-verify. Telemetry rides the PR-1 observability layer:
``analysis.programs_verified``, ``analysis.findings.{error,warning,info}``
counters and the ``analysis.verify_latency`` histogram.
"""

from __future__ import annotations

import os
import warnings

from .collectives import analyze_collectives
from .findings import Report, Severity
from .memory import analyze_memory
from .shapes import analyze_shapes
from .structural import analyze_structural

_MODES = ("strict", "warn", "off")
_mode_override = None


def verify_mode() -> str:
    """Resolve the active mode: programmatic override, else env, else warn."""
    if _mode_override is not None:
        return _mode_override
    raw = os.environ.get("PADDLE_TPU_VERIFY", "warn").strip().lower()
    if raw in ("0", "off", "false", "no", "none", ""):
        return "off"
    if raw == "strict":
        return "strict"
    return "warn"


def set_verify_mode(mode) -> None:
    """Override ``PADDLE_TPU_VERIFY`` for this process; ``None`` re-reads
    the environment on the next call."""
    global _mode_override
    if mode is not None:
        mode = str(mode).lower()
        if mode not in _MODES:
            raise ValueError(f"verify mode must be one of {_MODES}")
    _mode_override = mode


FAMILIES = ("structural", "shapes", "collectives", "memory")

# check_before_compile result cache entries kept per Program (distinct
# (version, feeds, fetches, families) keys; stale versions evict in
# insertion order)
_VERIFY_CACHE_CAPACITY = 8


def verify_program(program, feed_names=(), fetch_names=(),
                   families=FAMILIES) -> Report:
    """Run the requested analysis families; return the full Report
    (no raising). Default: all four."""
    from .. import observability as _obs

    with _obs.timed("analysis.verify_latency"):
        report = Report()
        if "structural" in families:
            report.extend(
                analyze_structural(program, feed_names, fetch_names)
            )
        if "shapes" in families:
            report.extend(analyze_shapes(program))
        if "collectives" in families:
            report.extend(analyze_collectives(program))
        if "memory" in families:
            report.extend(
                analyze_memory(program, feed_names, fetch_names)
            )
    _obs.add("analysis.programs_verified")
    for sev, bucket in (
        (Severity.ERROR, "error"),
        (Severity.WARNING, "warning"),
        (Severity.INFO, "info"),
    ):
        n = sum(1 for f in report.findings if f.severity == sev)
        if n:
            _obs.add(f"analysis.findings.{bucket}", n)
    return report


def check_before_compile(program, feed_names=(), fetch_names=()):
    """The Executor._compile hook: verify once per program version and
    enforce the active mode. Returns the Report (or None when off).

    warn mode runs the graph-walk families only (structural +
    collective-schedule + memory — O(ops) python, microseconds to low
    ms); the shape/dtype family replays ``infer_shapes`` per op, seconds
    on detection-sized programs, so at compile time it rides only the
    opt-in strict mode. ``verify_program`` / ``tools/program_lint.py``
    always run all families.

    The pass is cached per (version, feeds, fetches, families) in a small
    bounded dict — a program compiled alternately with two feed/fetch
    sets (train loss + eval metric) verifies once per set, not once per
    compile."""
    mode = verify_mode()
    if mode == "off":
        return None
    families = (
        FAMILIES if mode == "strict"
        else ("structural", "collectives", "memory")
    )
    key = (
        program._version,
        tuple(sorted(feed_names or ())),
        tuple(fetch_names or ()),
        families,
    )
    cache = program.__dict__.get("_verify_cache")
    if not isinstance(cache, dict):
        cache = {}
        program.__dict__["_verify_cache"] = cache
    report = cache.get(key)
    if report is None:
        report = verify_program(
            program, feed_names, fetch_names, families=families
        )
        while len(cache) >= _VERIFY_CACHE_CAPACITY:
            cache.pop(next(iter(cache)))
        cache[key] = report

    if mode == "strict":
        strict = report.strict_errors()
        if strict:
            from ..errors import ProgramVerifyError

            first = strict[0]
            raise ProgramVerifyError(
                "program verification failed under PADDLE_TPU_VERIFY="
                "strict — refusing to compile:\n"
                + "\n".join("  " + f.format() for f in strict),
                findings=report.findings,
                loc=first.loc,
                op=first.op_type,
            )
    elif report.errors or report.warnings:
        from ..errors import ProgramVerifyWarning

        warnings.warn(
            report.render(min_severity=Severity.WARNING),
            ProgramVerifyWarning,
            stacklevel=3,
        )
    return report
