"""Shape/dtype desync detection: replay ``registry.infer_shapes`` per op
and cross-check every declared Variable against what the emitter would
actually produce.

Most layers create their outputs through ``Block.infer_and_create_output``
so declaration and emitter agree *at build time* — but ops appended with
explicit outputs (optimizers, transpilers, hand-built graphs, programs
deserialized from older saves) carry declarations the emitter never saw.
When the two drift, ``jax.eval_shape``/the trace explodes mid-compile with
no op attribution, or — worse — a downstream op silently broadcasts. This
pass catches the drift pre-trace, per op, with build provenance.

-1 (batch) dims are compared as wildcards on either side: the declared
graph-build shape pins them at feed time, so only *concrete* disagreements
are desyncs. Replay reuses the registry's BATCH_SENTINEL machinery —
``infer_shapes`` maps -1 through the prime sentinel and back.
"""

from __future__ import annotations

from ..core.dtypes import convert_dtype
from ..framework.registry import _REGISTRY, infer_shapes
from .findings import (
    DTYPE_DESYNC,
    SHAPE_DESYNC,
    Severity,
    finding_for_op,
)

# ops never replayed:
#   __vjp__     — machine-generated grad replay; its outputs are created
#                 from the forward var's declaration (backward._ensure_var)
#                 so they cannot drift, and replaying doubles verify cost;
#   feed/fetch  — no emitter semantics of their own.
SKIP_OPS = frozenset({"__vjp__", "feed", "fetch"})


def _shapes_match(declared, inferred):
    if len(declared) != len(inferred):
        return False
    for d, i in zip(declared, inferred):
        if d == -1 or i == -1:
            continue  # batch wildcard: pinned at feed time
        if int(d) != int(i):
            return False
    return True


def analyze_shapes(program):
    findings = []
    for blk in program.blocks:
        for i, op in enumerate(blk.ops):
            if op.type in SKIP_OPS or op.type not in _REGISTRY:
                continue
            # skip ops whose input declarations are unknown — replaying
            # them would infer from garbage and report phantom desyncs
            skip = False
            for n in op.input_names():
                v = blk._find_var_recursive(n) if n else None
                if n and (v is None or v.shape is None):
                    skip = True
                    break
            if skip:
                continue
            try:
                out_specs = infer_shapes(op.type, blk, op.inputs, op.attrs)
            except Exception:
                # inference itself failed (op needs runtime-only context);
                # structural analysis already covers undeclared names
                continue
            for slot, names in op.outputs.items():
                specs = out_specs.get(slot, [])
                for j, n in enumerate(names):
                    if not n or j >= len(specs):
                        continue
                    shape, dtype = specs[j]
                    if shape is None:
                        continue
                    v = blk._find_var_recursive(n)
                    if v is None:
                        continue  # undeclared-write finding covers it
                    if v.shape is not None and not _shapes_match(
                        tuple(v.shape), tuple(shape)
                    ):
                        findings.append(finding_for_op(
                            Severity.ERROR, SHAPE_DESYNC,
                            f"output {n!r} declared with shape "
                            f"{tuple(v.shape)} but the {op.type!r} emitter "
                            f"produces {tuple(shape)}",
                            op=op, op_index=i, block_idx=blk.idx,
                            names=(n,),
                        ))
                    if dtype is not None and convert_dtype(
                        v.dtype
                    ) != convert_dtype(dtype):
                        findings.append(finding_for_op(
                            Severity.ERROR, DTYPE_DESYNC,
                            f"output {n!r} declared as {v.dtype} but the "
                            f"{op.type!r} emitter produces "
                            f"{convert_dtype(dtype)}",
                            op=op, op_index=i, block_idx=blk.idx,
                            names=(n,),
                        ))
    return findings
