"""Profiler (reference platform/profiler.h:126 RecordEvent,
EnableProfiler/DisableProfiler :208-211, fluid/profiler.py:255 context
manager, tools/timeline.py Chrome-trace conversion).

TPU-native: jax.profiler captures BOTH host events and device (TPU) events
into an xplane trace — the role CUPTI's DeviceTracer played for CUDA.
`profiler()` wraps start/stop; `RecordEvent` annotates host spans that show
up inline with device ops; `summary()` aggregates the captured xplane into
the reference's per-op time table (EnableProfiler's table) without needing
TensorBoard.
"""

from __future__ import annotations

import contextlib
import glob
import os
import re
import tempfile

_active_dir = None


def start_profiler(state="All", tracer_option="Default", log_dir=None):
    """reference fluid.profiler.start_profiler(:131). state/tracer_option
    accepted for parity; jax.profiler always captures host+device."""
    global _active_dir
    import jax

    _active_dir = log_dir or tempfile.mkdtemp(prefix="paddle_tpu_prof_")
    jax.profiler.start_trace(_active_dir)
    return _active_dir


def stop_profiler(sorted_key=None, profile_path=None):
    """reference fluid.profiler.stop_profiler(:198): stop + print summary."""
    global _active_dir
    import jax

    # clear _active_dir BEFORE stop_trace: if the runtime raises mid-stop,
    # a later start_profiler must not see a phantom active session
    out_dir, _active_dir = _active_dir, None
    jax.profiler.stop_trace()
    table = summary(out_dir)
    if table:
        print(_format_table(table))
    if profile_path:
        import shutil

        os.makedirs(os.path.dirname(profile_path) or ".", exist_ok=True)
        shutil.copytree(out_dir, profile_path, dirs_exist_ok=True)
    return out_dir


@contextlib.contextmanager
def profiler(state="All", sorted_key="total", profile_path=None,
             tracer_option="Default", log_dir=None):
    """reference fluid.profiler.profiler context (:255)."""
    start_profiler(state, tracer_option, log_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def RecordEvent(name):
    """Host-span annotation visible in the trace (platform/profiler.h:126)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


record_event = RecordEvent


def cuda_profiler(*args, **kwargs):  # pragma: no cover - API parity shim
    raise RuntimeError(
        "cuda_profiler is CUDA-only (reference profiler.py:39); use "
        "profiler()/start_profiler on TPU"
    )


def _op_kind(name):
    """Base op kind of an xplane event name: the leading identifier chars —
    digits included, so `fusion.2`, `all-reduce.1` and names *starting* with
    a digit all aggregate by base kind (XLA's `.<id>` instance suffix stops
    at the dot); anything unmatched falls back to 24-char truncation."""
    m = re.match(r"%?([a-zA-Z0-9\-_]+)", name)
    return m.group(1) if m else name[:24]


def summary(trace_dir):
    """Aggregate device-op time from the xplane capture: returns
    [(op_kind, total_ms, count)] sorted by time (the reference's
    per-op-type profile table)."""
    from jax.profiler import ProfileData

    files = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True)
    )
    if not files:
        return []
    with open(files[-1], "rb") as f:
        pd = ProfileData.from_serialized_xspace(f.read())

    def collect(planes_lines):
        agg = {}
        for plane, line in planes_lines:
            for ev in line.events:
                kind = _op_kind(ev.name)
                t, c = agg.get(kind, (0, 0))
                agg[kind] = (t + ev.duration_ns, c + 1)
        return agg

    device = [
        (p_, l)
        for p_ in pd.planes
        if p_.name.startswith("/device:")
        for l in p_.lines
        if l.name == "XLA Ops"
    ]
    agg = collect(device)
    if not agg:
        # CPU backend emits no per-op device events; fall back to the host
        # PJRT-client executable spans so the table still shows activity
        host = [
            (p_, l)
            for p_ in pd.planes
            if p_.name == "/host:CPU"
            for l in p_.lines
            if l.name != "python"
        ]
        agg = collect(host)
    return sorted(
        ((k, ns / 1e6, c) for k, (ns, c) in agg.items()),
        key=lambda kv: -kv[1],
    )


def _format_table(table):
    lines = ["-------- device op profile --------",
             f"{'op kind':<32}{'total ms':>12}{'count':>8}"]
    for kind, ms, count in table[:30]:
        lines.append(f"{kind:<32}{ms:>12.3f}{count:>8}")
    return "\n".join(lines)
