"""Optimizer zoo: op-emitting optimizers, fluid-style.

Reference parity: python/paddle/fluid/optimizer.py (4,304 LoC; SGD :842,
Momentum :936, Adagrad :1600, Adam :1716, Adamax :1982, Dpsgd :2154,
DecayedAdagrad :2249, Adadelta :2359, RMSProp :2478, Ftrl :2666, Lamb :2825,
LarsMomentum :1486). Each optimizer emits one update op per parameter into
the main program; minimize() = append_backward + regularization + clip +
update ops — identical pipeline shape to the reference's
Optimizer.minimize (optimizer.py:796) / apply_gradients (:683).

The meta-optimizers (Recompute/Pipeline/DGC/EMA/ModelAverage/Lookahead) live
in incubate modules and wrap these.
"""

from __future__ import annotations

import numpy as np

from .framework import unique_name
from .framework.backward import append_backward
from .framework.program import (
    Variable,
    default_main_program,
    default_startup_program,
)
from .initializer import Constant


class Optimizer:
    def __init__(
        self,
        learning_rate,
        parameter_list=None,
        regularization=None,
        grad_clip=None,
        name=None,
    ):
        self._learning_rate = learning_rate
        self._parameter_list = parameter_list
        self.regularization = regularization
        self._grad_clip = grad_clip
        self._name = name
        self._lr_var = None
        self._accumulators = {}  # (acc_name, param_name) -> Variable
        self.type = type(self).__name__.lower()

    # -- learning rate ----------------------------------------------------
    def _create_lr(self, block):
        if isinstance(self._learning_rate, Variable):
            return self._learning_rate
        if self._lr_var is not None:
            return self._lr_var
        name = unique_name.generate("learning_rate")
        main = block.program.global_block
        startup = default_startup_program().global_block
        self._lr_var = main.create_parameter(
            name, [1], "float32", trainable=False
        )
        self._lr_var.stop_gradient = True
        startup.create_parameter(name, [1], "float32", trainable=False)
        Constant(float(self._learning_rate))(startup, name, [1], "float32")
        return self._lr_var

    def set_lr(self, value, scope=None):
        """Runtime LR override (dygraph/static parity helper)."""
        from .framework.scope import global_scope
        import jax.numpy as jnp

        self._learning_rate = float(value)
        if self._lr_var is not None:
            (scope or global_scope()).set_var(
                self._lr_var.name, jnp.full([1], float(value), dtype=jnp.float32)
            )

    # -- accumulators ------------------------------------------------------
    def _add_accumulator(self, name, param, fill_value=0.0, shape=None, dtype=None):
        key = (name, param.name)
        if key in self._accumulators:
            return self._accumulators[key]
        param_shaped = shape is None
        shape = list(shape if shape is not None else param.shape)
        dtype = dtype or "float32"
        vname = unique_name.generate(f"{param.name}_{name}")
        main = param.block.program.global_block
        startup = default_startup_program().global_block
        v = main.create_parameter(vname, shape, dtype, trainable=False)
        v.stop_gradient = True
        # tag for sharding bookkeeping: parallel/sparse.shard_sparse_tables
        # row-shards exactly the accumulators of sharded tables
        v._accum_of = param.name
        # elementwise (param-shaped) state shards 1/N under the ZeRO
        # weight-update transpile; explicitly-shaped state (beta-pow
        # scalars) is broadcast into the update and must stay replicated
        v._accum_elementwise = param_shaped
        startup.create_parameter(vname, shape, dtype, trainable=False)
        Constant(fill_value)(startup, vname, shape, dtype)
        self._accumulators[key] = v
        return v

    def _get_accumulator(self, name, param):
        return self._accumulators[(name, param.name)]

    # -- pipeline ----------------------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        return append_backward(
            loss, parameter_list or self._parameter_list, no_grad_set
        )

    def apply_gradients(self, params_grads):
        if params_grads:
            # anchor to the params' own program, not the ambient default
            block = params_grads[0][0].block.program.global_block
        else:
            block = default_main_program().global_block
        if self._grad_clip is not None:
            params_grads = self._grad_clip.apply(params_grads, block)
        processed = []
        for p, g in params_grads:
            reg = getattr(p, "regularizer", None) or self.regularization
            if reg is not None:
                g = reg.append_regularization_op(p, g, block)
            processed.append((p, g))
        self._create_accumulators(block, [p for p, _ in processed])
        ops = [self._append_optimize_op(block, pg) for pg in processed]
        return ops

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(
        self, loss, startup_program=None, parameter_list=None, no_grad_set=None
    ):
        from .dygraph.varbase import VarBase

        if isinstance(loss, VarBase):
            # eager mode: loss.backward() has populated param._grad; apply
            # updates in place (reference dygraph minimize semantics)
            return self._eager_minimize(parameter_list), []

        # ops must land in the loss's program even if minimize() is called
        # outside its program_guard (fluid wraps minimize the same way)
        from .framework.program import program_guard

        with program_guard(
            loss.block.program, startup_program or default_startup_program()
        ):
            params_grads = self.backward(
                loss, startup_program, parameter_list, no_grad_set
            )
            ops = self.apply_gradients(params_grads)
        return ops, params_grads

    # -- eager (dygraph) path ---------------------------------------------
    def _eager_lr(self):
        # a schedule callable advances its step on every call, so it must be
        # invoked once per minimize (cached below), not once per parameter
        cached = getattr(self, "_eager_lr_value", None)
        if cached is not None:
            return cached
        lr = self._learning_rate
        return float(lr() if callable(lr) else lr)

    def _eager_acc(self, name, p, fill=0.0, shape=None):
        import jax.numpy as jnp

        key = (name, p.name)
        store = self.__dict__.setdefault("_eager_accs", {})
        if key not in store:
            shp = list(shape if shape is not None else p.shape)
            store[key] = jnp.full(shp, fill, dtype=jnp.float32)
        return store[key]

    def _set_eager_acc(self, name, p, value):
        self._eager_accs[(name, p.name)] = value

    def _eager_minimize(self, parameter_list=None):
        params = parameter_list or self._parameter_list or []
        updated = []
        self._eager_lr_value = None
        self._eager_lr_value = self._eager_lr()  # advance schedule ONCE
        try:
            for p in params:
                if not getattr(p, "trainable", True) or p._grad is None:
                    continue
                g = p._grad
                reg = getattr(p, "regularizer", None) or self.regularization
                if reg is not None and getattr(reg, "_coeff", 0.0):
                    g = g + reg._coeff * p.value
                self._eager_update(p, g)
                updated.append(p)
        finally:
            self._eager_lr_value = None
        return updated

    def _eager_update(self, p, g):
        raise NotImplementedError(
            f"{type(self).__name__} has no eager-mode update yet; "
            "use the static-graph path"
        )

    # -- per-optimizer hooks ----------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError


class SGDOptimizer(Optimizer):
    def _append_optimize_op(self, block, pg):
        p, g = pg
        lr = self._create_lr(block)
        return block.append_op(
            "sgd",
            {"Param": [p.name], "Grad": [g.name], "LearningRate": [lr.name]},
            {"ParamOut": [p.name]},
            {},
        )

    def _eager_update(self, p, g):
        p.set_value(p.value - self._eager_lr() * g)


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum=0.9, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        v = self._get_accumulator("velocity", p)
        lr = self._create_lr(block)
        return block.append_op(
            "momentum",
            {
                "Param": [p.name],
                "Grad": [g.name],
                "Velocity": [v.name],
                "LearningRate": [lr.name],
            },
            {"ParamOut": [p.name], "VelocityOut": [v.name]},
            {"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )

    def _eager_update(self, p, g):
        lr = self._eager_lr()
        v = self._eager_acc("velocity", p)
        v_new = self._momentum * v + g
        if self._use_nesterov:
            p.set_value(p.value - lr * (g + self._momentum * v_new))
        else:
            p.set_value(p.value - lr * v_new)
        self._set_eager_acc("velocity", p, v_new)


class LarsMomentumOptimizer(Optimizer):
    def __init__(
        self, learning_rate, momentum=0.9, lars_coeff=0.001,
        lars_weight_decay=0.0005, **kw,
    ):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        v = self._get_accumulator("velocity", p)
        lr = self._create_lr(block)
        return block.append_op(
            "lars_momentum",
            {
                "Param": [p.name],
                "Grad": [g.name],
                "Velocity": [v.name],
                "LearningRate": [lr.name],
            },
            {"ParamOut": [p.name], "VelocityOut": [v.name]},
            {
                "mu": self._momentum,
                "lars_coeff": self._lars_coeff,
                "lars_weight_decay": self._lars_weight_decay,
            },
        )


class _AdamBase(Optimizer):
    op_type = "adam"

    def __init__(
        self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kw
    ):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow", p, self._beta1, shape=[1])
            self._add_accumulator("beta2_pow", p, self._beta2, shape=[1])

    def _extra_attrs(self):
        return {}

    def _append_optimize_op(self, block, pg):
        p, g = pg
        lr = self._create_lr(block)
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow", p)
        b2p = self._get_accumulator("beta2_pow", p)
        return block.append_op(
            self.op_type,
            {
                "Param": [p.name],
                "Grad": [g.name],
                "LearningRate": [lr.name],
                "Moment1": [m1.name],
                "Moment2": [m2.name],
                "Beta1Pow": [b1p.name],
                "Beta2Pow": [b2p.name],
            },
            {
                "ParamOut": [p.name],
                "Moment1Out": [m1.name],
                "Moment2Out": [m2.name],
                "Beta1PowOut": [b1p.name],
                "Beta2PowOut": [b2p.name],
            },
            {
                "beta1": self._beta1,
                "beta2": self._beta2,
                "epsilon": self._epsilon,
                **self._extra_attrs(),
            },
        )


class AdamOptimizer(_AdamBase):
    op_type = "adam"


def _adam_eager(opt, p, g, weight_decay=0.0):
    import jax.numpy as jnp

    lr = opt._eager_lr()
    b1, b2, eps = opt._beta1, opt._beta2, opt._epsilon
    m1 = opt._eager_acc("moment1", p)
    m2 = opt._eager_acc("moment2", p)
    b1p = opt._eager_acc("beta1_pow", p, opt._beta1, shape=[1])
    b2p = opt._eager_acc("beta2_pow", p, opt._beta2, shape=[1])
    m1 = b1 * m1 + (1 - b1) * g
    m2 = b2 * m2 + (1 - b2) * g * g
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    upd = lr_t * m1 / (jnp.sqrt(m2) + eps)
    if weight_decay:
        upd = upd + lr * weight_decay * p.value
    p.set_value(p.value - upd.reshape(p.value.shape))
    opt._set_eager_acc("moment1", p, m1)
    opt._set_eager_acc("moment2", p, m2)
    opt._set_eager_acc("beta1_pow", p, b1p * b1)
    opt._set_eager_acc("beta2_pow", p, b2p * b2)


_AdamBase._eager_update = lambda self, p, g: _adam_eager(
    self, p, g, getattr(self, "_weight_decay", 0.0)
)


class AdamWOptimizer(_AdamBase):
    op_type = "adamw"

    def __init__(self, learning_rate=0.001, weight_decay=0.01, **kw):
        super().__init__(learning_rate, **kw)
        self._weight_decay = weight_decay

    def _extra_attrs(self):
        return {"weight_decay": self._weight_decay}


class LambOptimizer(_AdamBase):
    op_type = "lamb"

    def __init__(
        self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
        beta2=0.999, epsilon=1e-6, **kw,
    ):
        super().__init__(learning_rate, beta1, beta2, epsilon, **kw)
        self._weight_decay = lamb_weight_decay

    def _extra_attrs(self):
        return {"weight_decay": self._weight_decay}


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, initial_accumulator_value=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, self._init_acc)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        m = self._get_accumulator("moment", p)
        lr = self._create_lr(block)
        return block.append_op(
            "adagrad",
            {
                "Param": [p.name], "Grad": [g.name], "Moment": [m.name],
                "LearningRate": [lr.name],
            },
            {"ParamOut": [p.name], "MomentOut": [m.name]},
            {"epsilon": self._epsilon},
        )


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        m = self._get_accumulator("moment", p)
        lr = self._create_lr(block)
        return block.append_op(
            "decayed_adagrad",
            {
                "Param": [p.name], "Grad": [g.name], "Moment": [m.name],
                "LearningRate": [lr.name],
            },
            {"ParamOut": [p.name], "MomentOut": [m.name]},
            {"decay": self._decay, "epsilon": self._epsilon},
        )


class RMSPropOptimizer(Optimizer):
    def __init__(
        self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
        centered=False, **kw,
    ):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("momentum_acc", p)
            self._add_accumulator("mean_square", p)
            self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        lr = self._create_lr(block)
        return block.append_op(
            "rmsprop",
            {
                "Param": [p.name],
                "Grad": [g.name],
                "Moment": [self._get_accumulator("momentum_acc", p).name],
                "MeanSquare": [self._get_accumulator("mean_square", p).name],
                "MeanGrad": [self._get_accumulator("mean_grad", p).name],
                "LearningRate": [lr.name],
            },
            {
                "ParamOut": [p.name],
                "MomentOut": [self._get_accumulator("momentum_acc", p).name],
                "MeanSquareOut": [self._get_accumulator("mean_square", p).name],
                "MeanGradOut": [self._get_accumulator("mean_grad", p).name],
            },
            {
                "decay": self._rho,
                "epsilon": self._epsilon,
                "momentum": self._momentum,
                "centered": self._centered,
            },
        )


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            "adadelta",
            {
                "Param": [p.name],
                "Grad": [g.name],
                "AvgSquaredGrad": [self._get_accumulator("avg_squared_grad", p).name],
                "AvgSquaredUpdate": [
                    self._get_accumulator("avg_squared_update", p).name
                ],
            },
            {
                "ParamOut": [p.name],
                "AvgSquaredGradOut": [
                    self._get_accumulator("avg_squared_grad", p).name
                ],
                "AvgSquaredUpdateOut": [
                    self._get_accumulator("avg_squared_update", p).name
                ],
            },
            {"rho": self._rho, "epsilon": self._epsilon},
        )


class AdamaxOptimizer(Optimizer):
    def __init__(
        self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kw
    ):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow", p, self._beta1, shape=[1])

    def _append_optimize_op(self, block, pg):
        p, g = pg
        lr = self._create_lr(block)
        return block.append_op(
            "adamax",
            {
                "Param": [p.name],
                "Grad": [g.name],
                "LearningRate": [lr.name],
                "Moment": [self._get_accumulator("moment", p).name],
                "InfNorm": [self._get_accumulator("inf_norm", p).name],
                "Beta1Pow": [self._get_accumulator("beta1_pow", p).name],
            },
            {
                "ParamOut": [p.name],
                "MomentOut": [self._get_accumulator("moment", p).name],
                "InfNormOut": [self._get_accumulator("inf_norm", p).name],
                "Beta1PowOut": [self._get_accumulator("beta1_pow", p).name],
            },
            {
                "beta1": self._beta1,
                "beta2": self._beta2,
                "epsilon": self._epsilon,
            },
        )


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        lr = self._create_lr(block)
        return block.append_op(
            "ftrl",
            {
                "Param": [p.name],
                "Grad": [g.name],
                "SquaredAccumulator": [self._get_accumulator("squared", p).name],
                "LinearAccumulator": [self._get_accumulator("linear", p).name],
                "LearningRate": [lr.name],
            },
            {
                "ParamOut": [p.name],
                "SquaredAccumOut": [self._get_accumulator("squared", p).name],
                "LinearAccumOut": [self._get_accumulator("linear", p).name],
            },
            {"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
        )


class DpsgdOptimizer(Optimizer):
    def __init__(self, learning_rate, clip=10.0, batch_size=16.0, sigma=1.0, **kw):
        super().__init__(learning_rate, **kw)
        self._clip, self._batch_size, self._sigma = clip, batch_size, sigma

    def _append_optimize_op(self, block, pg):
        p, g = pg
        lr = self._create_lr(block)
        return block.append_op(
            "dpsgd",
            {"Param": [p.name], "Grad": [g.name], "LearningRate": [lr.name]},
            {"ParamOut": [p.name]},
            {
                "clip": self._clip,
                "batch_size": self._batch_size,
                "sigma": self._sigma,
            },
        )


class ProximalGDOptimizer(Optimizer):
    """reference optimizers/proximal_gd_op.cc: SGD step + L1 soft-threshold
    + L2 shrink."""

    def __init__(self, learning_rate, l1_regularization_strength=0.0,
                 l2_regularization_strength=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._l1 = l1_regularization_strength
        self._l2 = l2_regularization_strength

    def _append_optimize_op(self, block, pg):
        p, g = pg
        lr = self._create_lr(block)
        return block.append_op(
            "proximal_gd",
            {"Param": [p.name], "Grad": [g.name],
             "LearningRate": [lr.name]},
            {"ParamOut": [p.name]},
            {"l1": self._l1, "l2": self._l2},
        )


class ProximalAdagradOptimizer(Optimizer):
    """reference optimizers/proximal_adagrad_op.cc: adagrad-scaled lr into
    the proximal update."""

    def __init__(self, learning_rate, l1_regularization_strength=0.0,
                 l2_regularization_strength=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._l1 = l1_regularization_strength
        self._l2 = l2_regularization_strength

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        m = self._get_accumulator("moment", p)
        lr = self._create_lr(block)
        return block.append_op(
            "proximal_adagrad",
            {"Param": [p.name], "Grad": [g.name], "Moment": [m.name],
             "LearningRate": [lr.name]},
            {"ParamOut": [p.name], "MomentOut": [m.name]},
            {"l1": self._l1, "l2": self._l2},
        )


class DGCMomentumOptimizer(Optimizer):
    """Deep Gradient Compression momentum (reference optimizer.py:1071):
    top-k sparsified gradient exchange with error feedback, momentum
    correction and factor masking (ops/optimizer_ops.py dgc_momentum_step).
    Under a dp mesh the exchange all_gathers (values, indices) pairs —
    2k*nranks words instead of the dense numel. `sparsity` takes the FINAL
    ratio of the reference's schedule (static shapes fix k); steps before
    `rampup_begin_step` run the dense warmup path."""

    def __init__(self, learning_rate, momentum, rampup_begin_step=0,
                 rampup_step=1, sparsity=(0.999,), use_nesterov=False,
                 num_trainers=None, **kw):
        super().__init__(learning_rate, **kw)
        if use_nesterov:
            raise NotImplementedError(
                "DGCMomentumOptimizer: use_nesterov is not implemented in "
                "the fused dgc_momentum_step op"
            )
        self._momentum = momentum
        self._rampup_begin = float(rampup_begin_step)
        self._sparsity = float(sparsity[-1] if isinstance(
            sparsity, (list, tuple)) else sparsity)
        self._nranks = num_trainers or 1

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("dgc_u", p)
            self._add_accumulator("dgc_v", p)
            if self._rampup_begin > 0:  # step counter only drives rampup
                self._add_accumulator("dgc_step", p, fill_value=0.0,
                                      shape=[1])

    def _append_optimize_op(self, block, pg):
        p, g = pg
        u = self._get_accumulator("dgc_u", p)
        v = self._get_accumulator("dgc_v", p)
        lr = self._create_lr(block)
        ins_step = {}
        if self._rampup_begin > 0:
            step = self._get_accumulator("dgc_step", p)
            block.append_op(
                "increment", {"X": [step.name]}, {"Out": [step.name]},
                {"step": 1.0},
            )
            ins_step = {"CurrentStep": [step.name]}
        # with no rampup, omitting CurrentStep selects the op's static
        # sparse-only path (no dead dense branch compiled)
        return block.append_op(
            "dgc_momentum_step",
            {"Param": [p.name], "Grad": [g.name], "U": [u.name],
             "V": [v.name], "LearningRate": [lr.name], **ins_step},
            {"ParamOut": [p.name], "UOut": [u.name], "VOut": [v.name],
             "SentRatio": [block.create_var(
                 name=f"{p.name}@DGC_RATIO", shape=[1], dtype="float32"
             ).name]},
            {"momentum": self._momentum, "sparsity": self._sparsity,
             "rampup_begin_step": self._rampup_begin,
             "nranks": self._nranks},
        )


SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
Adamax = AdamaxOptimizer
Adagrad = AdagradOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
LarsMomentum = LarsMomentumOptimizer
Dpsgd = DpsgdOptimizer
ProximalGD = ProximalGDOptimizer
ProximalAdagrad = ProximalAdagradOptimizer
DGCMomentum = DGCMomentumOptimizer


# ---------------------------------------------------------------------------
# meta-optimizers: EMA / ModelAverage / Lookahead
# (reference python/paddle/fluid/optimizer.py: ModelAverage :2997, EMA :3306,
# LookaheadOptimizer :4150)
# ---------------------------------------------------------------------------

import contextlib as _contextlib

from .framework.state import create_persistable_var, create_step_counter


def _make_counter(name_hint, init=0.0, dtype="float32"):
    return create_persistable_var(name_hint, [1], dtype, init)


def _make_state_like(param, name_hint, init=0.0, dtype=None, shape=None):
    return create_persistable_var(
        name_hint,
        list(shape if shape is not None else param.shape),
        dtype or param.dtype,
        init,
    )


class _SwappingAverager:
    """Shared apply()/restore() scope-swap machinery for EMA/ModelAverage.

    The swap phases run between train steps, off the hot path, so host-side
    scope mutation (a couple of device round-trips) is the right tool — the
    reference built dedicated apply/restore Programs instead
    (optimizer.py:3306 area)."""

    def __init__(self):
        self._backup = {}

    def _averaged_value(self, scope, pname):
        raise NotImplementedError

    @_contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        from .framework.scope import global_scope

        scope = global_scope()
        self._backup = {}
        for pname in self._param_names():
            self._backup[pname] = scope.find_var(pname)
            scope.set_var(pname, self._averaged_value(scope, pname))
        try:
            yield
        finally:
            if need_restore:
                self.restore(executor)

    def restore(self, executor=None):
        from .framework.scope import global_scope

        scope = global_scope()
        for pname, val in self._backup.items():
            scope.set_var(pname, val)
        self._backup = {}


class ExponentialMovingAverage(_SwappingAverager):
    """EMA of trainable parameters, updated in-graph each step.

    update() appends `ema = decay_t * ema + (1-decay_t) * param` ops to the
    main program (they fuse into the train step's XLA computation — the
    reference ran separate kernels, optimizer.py:3306). With thres_steps the
    decay ramps as min(decay, (1+step)/(10+step)). apply() swaps in the
    bias-corrected average ema / (1 - prod(decay_t)); restore() swaps back.
    """

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        super().__init__()
        self._decay = float(decay)
        self._thres_steps = thres_steps
        self._name = name or "ema"
        self._pairs = {}  # param_name -> ema_name
        self._decay_pow_name = None

    def _param_names(self):
        return list(self._pairs)

    def update(self):
        from .framework.program import default_main_program
        from . import layers

        main = default_main_program()
        params = [p for p in main.all_parameters() if p.trainable]
        blk = main.global_block

        if self._thres_steps is not None:
            # reference semantics (optimizer.py:3306): thres_steps is the
            # caller's step Variable and the decay ramps as
            # min(decay, (1+t)/(10+t)); a numeric thres clamps an internal
            # counter (created only for this branch)
            if isinstance(self._thres_steps, Variable):
                t = layers.cast(self._thres_steps, "float32")
            else:
                step_v = create_step_counter(self._name + "_step")
                t = layers.elementwise_min(
                    layers.cast(step_v, "float32"),
                    layers.fill_constant(
                        [1], "float32", float(self._thres_steps)
                    ),
                )
            decay_t = layers.elementwise_min(
                layers.fill_constant([1], "float32", self._decay),
                (t + 1.0) / (t + 10.0),
            )
        else:
            decay_t = layers.fill_constant([1], "float32", self._decay)

        # running product of decay_t, for bias correction at apply()
        pow_v = _make_counter(self._name + "_decay_pow", init=1.0)
        prod = layers.elementwise_mul(pow_v, decay_t)
        blk.append_op("assign", {"X": [prod.name]}, {"Out": [pow_v.name]}, {})
        self._decay_pow_name = pow_v.name

        for p in params:
            ema = _make_state_like(p, p.name + "_" + self._name)
            new = layers.elementwise_add(
                layers.elementwise_mul(ema, decay_t),
                layers.elementwise_mul(p, 1.0 - decay_t),
            )
            blk.append_op("assign", {"X": [new.name]}, {"Out": [ema.name]}, {})
            self._pairs[p.name] = ema.name

    def _averaged_value(self, scope, pname):
        pow_t = np.asarray(scope.find_var(self._decay_pow_name))
        debias = max(1.0 - float(pow_t.reshape(-1)[0]), 1e-12)
        ema_val = scope.find_var(self._pairs[pname])
        return (ema_val / debias).astype(ema_val.dtype)


class ModelAverage(_SwappingAverager):
    """Windowed average of parameters (reference optimizer.py:2997).

    Two-tier accumulation mirroring the reference's rotating partial sums:
    (sum_cur, cnt_cur) accumulate every step; when cnt_cur reaches the
    effective window clip(average_window_rate * num_updates,
    min_average_window, max_average_window) the current tier shifts to
    (sum_old, cnt_old) and restarts — so apply() always averages over at
    least one full window once warm (never a fresh-restart handful of
    samples). All in-graph mask-selects, no host control flow. apply()
    swaps params for (sum_cur+sum_old)/(cnt_cur+cnt_old).
    """

    def __init__(
        self,
        average_window_rate=0.15,
        min_average_window=10000,
        max_average_window=10000,
        name=None,
    ):
        super().__init__()
        self.average_window = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        self._name = name or "model_avg"
        self._state = {}  # param_name -> (sum_cur, cnt_cur, sum_old, cnt_old)
        self._build()

    def _param_names(self):
        return list(self._state)

    def _build(self):
        from .framework.program import default_main_program
        from . import layers

        main = default_main_program()
        blk = main.global_block
        # shared step counter: effective window scales with total updates
        # (reference semantics: window = clip(rate * num_updates, min, max))
        g = create_step_counter(self._name + "_num_updates")
        eff_window = layers.elementwise_min(
            layers.fill_constant([1], "float32", float(self.max_average_window)),
            layers.elementwise_max(
                layers.fill_constant([1], "float32", float(self.min_average_window)),
                layers.cast(g, "float32") * self.average_window,
            ),
        )
        one = layers.fill_constant([1], "int32", 1)
        for p in [q for q in main.all_parameters() if q.trainable]:
            sum_cur = _make_state_like(p, p.name + "_avg_sum", dtype="float32")
            cnt_cur = _make_counter(p.name + "_avg_cnt", dtype="int32")
            sum_old = _make_state_like(p, p.name + "_avg_sum_old", dtype="float32")
            cnt_old = _make_counter(p.name + "_avg_cnt_old", dtype="int32")
            cond = layers.greater_equal(
                layers.cast(cnt_cur, "float32"), eff_window
            )
            shift = layers.cast(cond, "float32")
            keep = 1.0 - shift
            new_sum_old = layers.elementwise_add(
                layers.elementwise_mul(sum_cur, shift, axis=0),
                layers.elementwise_mul(sum_old, keep, axis=0),
            )
            # counters stay int32 end-to-end (float32 math would stall at
            # 2^24); select with `where` instead of mask arithmetic
            new_cnt_old = layers.where(cond, cnt_cur, cnt_old)
            new_sum_cur = layers.elementwise_add(
                layers.elementwise_mul(sum_cur, keep, axis=0),
                layers.cast(p, "float32"),
            )
            zero = layers.fill_constant([1], "int32", 0)
            new_cnt_cur = layers.elementwise_add(
                layers.where(cond, zero, cnt_cur), one
            )
            for new, tgt in (
                (new_sum_old, sum_old), (new_cnt_old, cnt_old),
                (new_sum_cur, sum_cur), (new_cnt_cur, cnt_cur),
            ):
                blk.append_op("assign", {"X": [new.name]}, {"Out": [tgt.name]}, {})
            self._state[p.name] = (
                sum_cur.name, cnt_cur.name, sum_old.name, cnt_old.name
            )

    def _averaged_value(self, scope, pname):
        sc, cc, so, co = self._state[pname]
        s = scope.find_var(sc) + scope.find_var(so)
        c = int(np.asarray(scope.find_var(cc)).reshape(-1)[0]) + int(
            np.asarray(scope.find_var(co)).reshape(-1)[0]
        )
        c = max(c, 1.0)
        orig = self._backup[pname]
        return (s / c).astype(orig.dtype).reshape(orig.shape)


class LookaheadOptimizer:
    """Lookahead (k slow-weight sync, reference optimizer.py:4150): wraps an
    inner optimizer; every k steps slow += alpha*(fast-slow), fast = slow.
    The k-step condition is a mask-select in-graph (no host branch)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        assert inner_optimizer is not None
        assert 0.0 <= alpha <= 1.0
        assert k >= 1 and isinstance(k, int)
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        from . import layers
        from .framework.program import program_guard

        ops, params_grads = self.inner_optimizer.minimize(
            loss, startup_program, parameter_list, no_grad_set
        )
        main = loss.block.program
        blk = main.global_block
        startup = (startup_program or default_startup_program()).global_block

        with program_guard(main, startup_program or default_startup_program()):
            step_v = create_step_counter("lookahead_step")
            # int mod: a float32 counter would lose exactness past 2^24 steps
            kf = layers.fill_constant([1], "int32", self.k)
            rem = layers.elementwise_mod(step_v, kf)
            sync = layers.cast(
                layers.equal(rem, layers.fill_constant([1], "int32", 0)),
                "float32",
            )
            for p, _ in params_grads:
                slow = blk.create_parameter(
                    unique_name.generate(p.name + "_slow"), p.shape, p.dtype,
                    trainable=False,
                )
                slow.stop_gradient = True
                startup.create_parameter(slow.name, p.shape, p.dtype, trainable=False)
                # slow starts equal to fast: copy the initialized param value
                # (runs after the param's init ops in the startup program)
                startup.append_op("assign", {"X": [p.name]}, {"Out": [slow.name]}, {})
                merged = p * self.alpha + slow * (1.0 - self.alpha)
                new_slow = layers.elementwise_add(
                    layers.elementwise_mul(merged, sync, axis=0),
                    layers.elementwise_mul(slow, 1.0 - sync, axis=0),
                )
                new_fast = layers.elementwise_add(
                    layers.elementwise_mul(new_slow, sync, axis=0),
                    layers.elementwise_mul(p, 1.0 - sync, axis=0),
                )
                blk.append_op("assign", {"X": [new_slow.name]}, {"Out": [slow.name]}, {})
                blk.append_op("assign", {"X": [new_fast.name]}, {"Out": [p.name]}, {})
        return ops, params_grads
