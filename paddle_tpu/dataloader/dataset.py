"""Dataset abstractions (reference python/paddle/fluid/dataloader/dataset.py).

Map-style `Dataset` (indexable) and `IterableDataset` (stream), plus
`TensorDataset` and `ChainDataset` conveniences. Samples are host-side numpy
structures; device staging happens in the DataLoader's prefetcher, never here.
"""

from __future__ import annotations

import bisect

import numpy as np


class Dataset:
    """Map-style dataset: implement __getitem__ and __len__."""

    def __getitem__(self, idx):
        raise NotImplementedError(
            "'{}' not implement in class {}".format(
                "__getitem__", self.__class__.__name__
            )
        )

    def __len__(self):
        raise NotImplementedError(
            "'{}' not implement in class {}".format(
                "__len__", self.__class__.__name__
            )
        )


class IterableDataset(Dataset):
    """Stream dataset: implement __iter__; no random access, no len."""

    def __iter__(self):
        raise NotImplementedError(
            "'{}' not implement in class {}".format(
                "__iter__", self.__class__.__name__
            )
        )

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no random access")

    def __len__(self):
        # TypeError so list(ds) treats it as "no length hint" instead of
        # propagating out of operator.length_hint
        raise TypeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    """Wrap equal-length arrays; sample i = tuple of row i of each array."""

    def __init__(self, tensors):
        self.tensors = [np.asarray(t) for t in tensors]
        n = len(self.tensors[0])
        for t in self.tensors:
            if len(t) != n:
                raise ValueError("all tensors must have the same first dim")

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = []
        total = 0
        for d in self.datasets:
            total += len(d)
            self.cum.append(total)

    def __len__(self):
        return self.cum[-1] if self.cum else 0

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        di = bisect.bisect_right(self.cum, idx)
        prev = self.cum[di - 1] if di else 0
        return self.datasets[di][idx - prev]


class ChainDataset(IterableDataset):
    """Chain several iterable datasets end to end."""

    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, seed=None):
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths must equal dataset length")
    rng = np.random.RandomState(seed)
    perm = rng.permutation(len(dataset))
    out, off = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[off:off + n].tolist()))
        off += n
    return out
