from .batch_sampler import (  # noqa: F401
    BatchSampler,
    DistributedBatchSampler,
    RandomSampler,
    Sampler,
    SequenceSampler,
)
from .dataset import (  # noqa: F401
    ChainDataset,
    ConcatDataset,
    Dataset,
    IterableDataset,
    Subset,
    TensorDataset,
    random_split,
)
from .dataloader_iter import default_collate_fn  # noqa: F401
