"""DataLoader iterators: worker prefetch + device double-buffering.

Reference design (python/paddle/fluid/dataloader/dataloader_iter.py:200 and
C++ operators/reader/buffered_reader.cc:70): subprocess workers parse
samples into shared memory, and a buffered reader asynchronously stages the
next batch onto the GPU while the current one computes.

TPU-native re-design:
  * Workers are THREADS, not subprocesses. Collation is numpy-bound and
    releases the GIL; forking a process that holds a PJRT client wedges the
    TPU runtime, and spawn would re-acquire the chip per worker. The
    reference needed processes because its Python-side decoding was
    GIL-bound CPU work.
  * Device staging: the prefetcher calls jax.device_put on the *next* batch
    while the caller's current step is still executing (dispatch is async),
    which is exactly buffered_reader.cc's double buffer with XLA's own
    transfer stream in place of the CUDA copy stream.
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from .dataset import IterableDataset


def default_collate_fn(batch):
    """List of samples -> batched numpy structure (reference
    dataloader_iter.py default_collate_fn semantics)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.number)):
        return np.asarray(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        return [default_collate_fn(list(items)) for items in zip(*batch)]
    return np.asarray(batch)


def stage_to_device(tree):
    """Host numpy structure -> device arrays (async h2d; overlaps compute).
    Single definition shared by DataLoader iterators and GeneratorLoader —
    the buffered_reader.cc:70 double-buffer role."""
    import jax

    return jax.tree.map(
        lambda a: jax.device_put(np.ascontiguousarray(a))
        if isinstance(a, np.ndarray)
        else a,
        tree,
    )


class _EndOfEpoch:
    pass


_END = _EndOfEpoch()


class _WorkerPool:
    """Thread workers pulling batch-index lists from a task queue, pushing
    collated batches to an output slot keyed by batch index so ordering is
    preserved regardless of worker completion order."""

    def __init__(self, fetch, num_workers, capacity, worker_init_fn=None):
        self._fetch = fetch
        self._tasks = queue.Queue()
        self._done = {}
        self._done_lock = threading.Condition()
        self._capacity = capacity
        self._shutdown = False
        self._threads = [
            threading.Thread(target=self._work, args=(i,), daemon=True)
            for i in range(num_workers)
        ]
        self._worker_init_fn = worker_init_fn
        for t in self._threads:
            t.start()

    def submit(self, batch_id, indices):
        self._tasks.put((batch_id, indices))

    def _work(self, worker_id):
        if self._worker_init_fn is not None:
            self._worker_init_fn(worker_id)
        while True:
            item = self._tasks.get()
            if item is None:
                return
            batch_id, indices = item
            try:
                out = self._fetch(indices)
            except BaseException as e:  # surfaced on the consumer side
                out = e
            with self._done_lock:
                while (
                    len(self._done) >= self._capacity and not self._shutdown
                ):
                    self._done_lock.wait(0.1)
                if self._shutdown:
                    return
                self._done[batch_id] = out
                self._done_lock.notify_all()

    def get(self, batch_id):
        with self._done_lock:
            while batch_id not in self._done:
                self._done_lock.wait()
            out = self._done.pop(batch_id)
            self._done_lock.notify_all()
        if isinstance(out, BaseException):
            raise out
        return out

    def depth(self):
        """Ready batches currently buffered (observability queue gauge)."""
        with self._done_lock:
            return len(self._done)

    def close(self):
        self._shutdown = True
        for _ in self._threads:
            self._tasks.put(None)
        with self._done_lock:
            self._done_lock.notify_all()


class _DataLoaderIterBase:
    def __init__(self, loader):
        self._loader = loader
        self._collate = loader.collate_fn or default_collate_fn
        self._to_device = loader.use_buffer_reader

    def _stage(self, batch):
        return stage_to_device(batch) if self._to_device else batch


class _SingleProcessIter(_DataLoaderIterBase):
    """num_workers=0: synchronous fetch, still device-double-buffered."""

    def __init__(self, loader):
        super().__init__(loader)
        ds = loader.dataset
        if isinstance(ds, IterableDataset):
            src = iter(ds)

            def gen():
                batch = []
                for sample in src:
                    batch.append(sample)
                    if len(batch) == loader.batch_size:
                        yield self._collate(batch)
                        batch = []
                if batch and not loader.drop_last:
                    yield self._collate(batch)

            self._it = gen()
        else:
            self._it = (
                self._collate([ds[i] for i in indices])
                for indices in iter(loader.batch_sampler)
            )
        self._ahead = None  # staged next batch

    def __iter__(self):
        return self

    def __next__(self):
        t0 = time.perf_counter()
        if self._ahead is not None:
            out = self._ahead
            self._ahead = None
        else:
            out = self._stage(next(self._it))  # StopIteration ends the epoch
        try:
            self._ahead = self._stage(next(self._it))  # stage one ahead
        except StopIteration:
            self._ahead = None
        from .. import observability as _obs

        # wait = time the consumer blocked in this __next__: producing the
        # current batch PLUS the synchronous fetch/collate of the look-ahead
        # (only its device staging is async dispatch)
        _obs.observe("dataloader.batch_wait", time.perf_counter() - t0)
        _obs.add("dataloader.batches")  # once per DELIVERED batch
        return out


class _MultiWorkerIter(_DataLoaderIterBase):
    """num_workers>0: thread pool fetches batches ahead, in order."""

    def __init__(self, loader):
        super().__init__(loader)
        ds = loader.dataset
        if isinstance(ds, IterableDataset):
            raise ValueError(
                "IterableDataset requires num_workers=0 (streams have no "
                "random access to parallelize; reference splits streams per "
                "worker instead — use several datasets + ChainDataset)"
            )
        self._pool = _WorkerPool(
            fetch=lambda idxs: self._collate([ds[i] for i in idxs]),
            num_workers=loader.num_workers,
            capacity=max(2, loader.prefetch_factor * loader.num_workers),
            worker_init_fn=loader.worker_init_fn,
        )
        self._batches = list(iter(loader.batch_sampler))
        self._n = len(self._batches)
        self._next_submit = 0
        self._next_out = 0
        self._ahead = None
        for _ in range(min(self._n, loader.prefetch_factor * loader.num_workers)):
            self._pool.submit(self._next_submit, self._batches[self._next_submit])
            self._next_submit += 1

    def _pull(self):
        if self._next_out >= self._n:
            return None
        from .. import observability as _obs

        t0 = time.perf_counter()
        out = self._pool.get(self._next_out)
        _obs.observe("dataloader.batch_wait", time.perf_counter() - t0)
        # depth of the ready-batch slot AFTER the pop: 0 means the consumer
        # is outrunning the workers (input-pipeline stall territory)
        _obs.set_gauge("dataloader.queue_depth", self._pool.depth())
        self._next_out += 1
        if self._next_submit < self._n:
            self._pool.submit(self._next_submit, self._batches[self._next_submit])
            self._next_submit += 1
        return self._stage(out)

    def __iter__(self):
        return self

    def __next__(self):
        if self._ahead is None:
            self._ahead = self._pull()
        out = self._ahead
        self._ahead = self._pull()
        if out is None:
            self._pool.close()
            raise StopIteration
        from .. import observability as _obs

        _obs.add("dataloader.batches")  # once per DELIVERED batch
        return out

    def __del__(self):
        try:
            self._pool.close()
        except Exception:
            pass
