"""DataLoader iterators: worker prefetch + device double-buffering.

Reference design (python/paddle/fluid/dataloader/dataloader_iter.py:200 and
C++ operators/reader/buffered_reader.cc:70): subprocess workers parse
samples into shared memory, and a buffered reader asynchronously stages the
next batch onto the GPU while the current one computes.

TPU-native re-design:
  * Workers are THREADS, not subprocesses. Collation is numpy-bound and
    releases the GIL; forking a process that holds a PJRT client wedges the
    TPU runtime, and spawn would re-acquire the chip per worker. The
    reference needed processes because its Python-side decoding was
    GIL-bound CPU work.
  * Device staging: the prefetcher calls jax.device_put on the *next* batch
    while the caller's current step is still executing (dispatch is async),
    which is exactly buffered_reader.cc's double buffer with XLA's own
    transfer stream in place of the CUDA copy stream.
"""

from __future__ import annotations

import os
import queue
import threading
import time

import numpy as np

from .dataset import IterableDataset


def default_collate_fn(batch):
    """List of samples -> batched numpy structure (reference
    dataloader_iter.py default_collate_fn semantics)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.number)):
        return np.asarray(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        return [default_collate_fn(list(items)) for items in zip(*batch)]
    return np.asarray(batch)


def stage_to_device(tree):
    """Host numpy structure -> device arrays (async h2d; overlaps compute).
    Single definition shared by DataLoader iterators and GeneratorLoader —
    the buffered_reader.cc:70 double-buffer role."""
    import jax

    return jax.tree.map(
        lambda a: jax.device_put(np.ascontiguousarray(a))
        if isinstance(a, np.ndarray)
        else a,
        tree,
    )


class _EndOfEpoch:
    pass


_END = _EndOfEpoch()


class _WorkerPool:
    """Thread workers pulling batch-index lists from a task queue, pushing
    collated batches to an output slot keyed by batch index so ordering is
    preserved regardless of worker completion order.

    Hang-proofing contract (this pool feeds a step loop that must never
    wedge silently): ``get()`` raises RuntimeError instead of blocking
    forever once the pool is closed or every worker thread has died; a
    worker thread that DIES (BaseException past the fetch guard — e.g.
    SystemExit, interpreter teardown) with a batch in flight gets that
    batch resubmitted once to the surviving workers before any error
    surfaces. Ordinary fetch exceptions still flow to the consumer through
    the output slot, attributed to their batch."""

    def __init__(self, fetch, num_workers, capacity, worker_init_fn=None):
        self._fetch = fetch
        self._tasks = queue.Queue()
        self._done = {}
        self._done_lock = threading.Condition()
        self._capacity = capacity
        self._shutdown = False
        self._inflight = {}  # worker_id -> (batch_id, indices)
        self._resubmitted = set()  # batch_ids given their one second chance
        self._threads = [
            threading.Thread(target=self._work, args=(i,), daemon=True)
            for i in range(num_workers)
        ]
        self._worker_init_fn = worker_init_fn
        for t in self._threads:
            t.start()

    def submit(self, batch_id, indices):
        self._tasks.put((batch_id, indices))

    def _work(self, worker_id):
        if self._worker_init_fn is not None:
            self._worker_init_fn(worker_id)
        while True:
            item = self._tasks.get()
            if item is None:
                return
            batch_id, indices = item
            with self._done_lock:
                self._inflight[worker_id] = item
            try:
                out = self._fetch(indices)
            except Exception as e:  # surfaced on the consumer side
                out = e
            # BaseException (SystemExit, KeyboardInterrupt) kills the
            # worker; get() notices the dead thread and resubmits _inflight
            with self._done_lock:
                while (
                    len(self._done) >= self._capacity and not self._shutdown
                ):
                    self._done_lock.wait(0.1)
                self._inflight.pop(worker_id, None)
                if self._shutdown:
                    return
                self._done[batch_id] = out
                self._done_lock.notify_all()

    def _reap_dead_workers(self, batch_id):
        """Called under the lock. Resubmit (once) the in-flight batch of any
        dead worker; raise when the awaited batch can no longer arrive."""
        dead = [
            i for i, t in enumerate(self._threads)
            if not t.is_alive() and i in self._inflight
        ]
        for i in dead:
            bid, indices = self._inflight.pop(i)
            if bid in self._done:
                continue
            if bid not in self._resubmitted:
                self._resubmitted.add(bid)
                from .. import observability as _obs

                _obs.add("resilience.worker_resubmits")
                self._tasks.put((bid, indices))
            else:
                self._done[bid] = RuntimeError(
                    f"dataloader worker died twice fetching batch {bid}"
                )
                self._done_lock.notify_all()
        if batch_id in self._done:
            # the awaited batch's result (or its attributed died-twice
            # error) just landed — deliver that, not a generic failure
            return
        if not any(t.is_alive() for t in self._threads):
            raise RuntimeError(
                "all dataloader workers are dead; cannot produce batch "
                f"{batch_id} (check worker_init_fn / dataset __getitem__)"
            )

    def get(self, batch_id, timeout=None):
        """Next ready batch; raises RuntimeError on a closed pool or when
        every worker died, ExecutionTimeoutError past `timeout` seconds."""
        deadline = None if not timeout else time.monotonic() + timeout
        with self._done_lock:
            while batch_id not in self._done:
                if self._shutdown:
                    raise RuntimeError(
                        "dataloader worker pool is closed (get() after "
                        "close() would hang forever)"
                    )
                self._reap_dead_workers(batch_id)
                if deadline is not None and time.monotonic() >= deadline:
                    from ..errors import ExecutionTimeoutError

                    raise ExecutionTimeoutError(
                        f"dataloader batch {batch_id} not produced within "
                        f"{timeout}s"
                    )
                self._done_lock.wait(0.1)
            out = self._done.pop(batch_id)
            self._done_lock.notify_all()
        if isinstance(out, BaseException):
            raise out
        return out

    def depth(self):
        """Ready batches currently buffered (observability queue gauge)."""
        with self._done_lock:
            return len(self._done)

    def close(self):
        self._shutdown = True
        for _ in self._threads:
            self._tasks.put(None)
        with self._done_lock:
            self._done_lock.notify_all()


class _DataLoaderIterBase:
    def __init__(self, loader):
        self._loader = loader
        self._collate = loader.collate_fn or default_collate_fn
        self._to_device = loader.use_buffer_reader

    def _stage(self, batch):
        return stage_to_device(batch) if self._to_device else batch


class _SingleProcessIter(_DataLoaderIterBase):
    """num_workers=0: synchronous fetch, still device-double-buffered."""

    def __init__(self, loader):
        super().__init__(loader)
        ds = loader.dataset
        if isinstance(ds, IterableDataset):
            src = iter(ds)

            def gen():
                batch = []
                for sample in src:
                    batch.append(sample)
                    if len(batch) == loader.batch_size:
                        yield self._collate(batch)
                        batch = []
                if batch and not loader.drop_last:
                    yield self._collate(batch)

            self._it = gen()
        else:
            self._it = (
                self._collate([ds[i] for i in indices])
                for indices in iter(loader.batch_sampler)
            )
        self._ahead = None  # staged next batch

    def __iter__(self):
        return self

    def __next__(self):
        t0 = time.perf_counter()
        if self._ahead is not None:
            out = self._ahead
            self._ahead = None
        else:
            out = self._stage(next(self._it))  # StopIteration ends the epoch
        try:
            self._ahead = self._stage(next(self._it))  # stage one ahead
        except StopIteration:
            self._ahead = None
        from .. import observability as _obs

        # wait = time the consumer blocked in this __next__: producing the
        # current batch PLUS the synchronous fetch/collate of the look-ahead
        # (only its device staging is async dispatch)
        _obs.observe("dataloader.batch_wait", time.perf_counter() - t0)
        _obs.add("dataloader.batches")  # once per DELIVERED batch
        return out


class _MultiWorkerIter(_DataLoaderIterBase):
    """num_workers>0: thread pool fetches batches ahead, in order."""

    def __init__(self, loader):
        super().__init__(loader)
        ds = loader.dataset
        if isinstance(ds, IterableDataset):
            raise ValueError(
                "IterableDataset requires num_workers=0 (streams have no "
                "random access to parallelize; reference splits streams per "
                "worker instead — use several datasets + ChainDataset)"
            )
        from ..resilience import fault_point, retry

        def _fetch(idxs):
            fault_point("dataloader.fetch")
            return self._collate([ds[i] for i in idxs])

        # transient fetch failures (flaky remote storage, injected chaos
        # faults) retry in the worker before the consumer ever sees them
        try:
            attempts = int(
                os.environ.get("PADDLE_TPU_DATALOADER_RETRIES", "3")
            )
        except ValueError:  # malformed env must not break training startup
            attempts = 3
        self._pool = _WorkerPool(
            fetch=retry(
                max_attempts=max(1, attempts), base_delay=0.01, max_delay=0.5,
                name="dataloader.fetch",
            )(_fetch),
            num_workers=loader.num_workers,
            capacity=max(2, loader.prefetch_factor * loader.num_workers),
            worker_init_fn=loader.worker_init_fn,
        )
        self._timeout = getattr(loader, "timeout", 0) or None
        self._batches = list(iter(loader.batch_sampler))
        self._n = len(self._batches)
        self._next_submit = 0
        self._next_out = 0
        self._ahead = None
        for _ in range(min(self._n, loader.prefetch_factor * loader.num_workers)):
            self._pool.submit(self._next_submit, self._batches[self._next_submit])
            self._next_submit += 1

    def _pull(self):
        if self._next_out >= self._n:
            return None
        from .. import observability as _obs

        t0 = time.perf_counter()
        out = self._pool.get(self._next_out, timeout=self._timeout)
        _obs.observe("dataloader.batch_wait", time.perf_counter() - t0)
        # depth of the ready-batch slot AFTER the pop: 0 means the consumer
        # is outrunning the workers (input-pipeline stall territory)
        _obs.set_gauge("dataloader.queue_depth", self._pool.depth())
        self._next_out += 1
        if self._next_submit < self._n:
            self._pool.submit(self._next_submit, self._batches[self._next_submit])
            self._next_submit += 1
        return self._stage(out)

    def __iter__(self):
        return self

    def __next__(self):
        if self._ahead is None:
            self._ahead = self._pull()
        out = self._ahead
        self._ahead = self._pull()
        if out is None:
            self._pool.close()
            raise StopIteration
        from .. import observability as _obs

        _obs.add("dataloader.batches")  # once per DELIVERED batch
        return out

    def __del__(self):
        try:
            self._pool.close()
        except Exception:
            pass
