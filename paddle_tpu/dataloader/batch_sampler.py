"""Samplers (reference python/paddle/fluid/dataloader/batch_sampler.py:24).

BatchSampler yields lists of dataset indices per batch; Sequence/Random
samplers yield single indices. DistributedBatchSampler shards batches across
data-parallel ranks (the reference kept this in incubate; here it is the
front door for multi-host input pipelines — each host feeds its own shard,
matching the per-process feed model of jax.distributed).
"""

from __future__ import annotations

import numpy as np


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator  # np.random.RandomState or seed int

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def _rng(self):
        g = self.generator
        if isinstance(g, np.random.RandomState):
            return g
        return np.random.RandomState(g)  # None -> OS entropy

    def __iter__(self):
        n = len(self.data_source)
        rng = self._rng()
        if self.replacement:
            return iter(rng.randint(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """Groups sampler indices into batches (reference :97 signature)."""

    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        if bool(dataset is None) == bool(sampler is None):
            raise ValueError("provide exactly one of dataset / sampler")
        if sampler is not None:
            self.sampler = sampler
        else:
            self.sampler = (
                RandomSampler(dataset) if shuffle else SequenceSampler(dataset)
            )
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        self.batch_size = int(batch_size)
        self.drop_last = bool(drop_last)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Each rank sees a disjoint 1/nranks slice of every epoch
    (reference incubate distributed batch sampler semantics)."""

    def __init__(self, dataset, batch_size, nranks=None, rank=None,
                 shuffle=False, drop_last=False, seed=0):
        import os

        self.nranks = nranks if nranks is not None else int(
            os.environ.get("PADDLE_TRAINERS_NUM", 1)
        )
        self.rank = rank if rank is not None else int(
            os.environ.get("PADDLE_TRAINER_ID", 0)
        )
        self.dataset = dataset
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        super().__init__(
            sampler=SequenceSampler(dataset), batch_size=batch_size,
            drop_last=drop_last,
        )

    def set_epoch(self, epoch):
        self.epoch = int(epoch)

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            order = np.random.RandomState(self.seed + self.epoch).permutation(n)
        else:
            order = np.arange(n)
        # pad so every rank gets the same number of samples
        per_rank = (n + self.nranks - 1) // self.nranks
        padded = np.resize(order, per_rank * self.nranks)
        mine = padded[self.rank::self.nranks]
        batch = []
        for idx in mine.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.dataset)
        per_rank = (n + self.nranks - 1) // self.nranks
        if self.drop_last:
            return per_rank // self.batch_size
        return (per_rank + self.batch_size - 1) // self.batch_size
