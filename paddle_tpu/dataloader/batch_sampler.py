"""Samplers (reference python/paddle/fluid/dataloader/batch_sampler.py:24).

BatchSampler yields lists of dataset indices per batch; Sequence/Random
samplers yield single indices. DistributedBatchSampler shards batches across
data-parallel ranks (the reference kept this in incubate; here it is the
front door for multi-host input pipelines — each host feeds its own shard,
matching the per-process feed model of jax.distributed).

Exact-resume cursor: BatchSampler and DistributedBatchSampler carry a
``state_dict()/load_state_dict()`` cursor — the epoch plus the number of
batches already consumed — and the next ``__iter__`` after a
``load_state_dict`` fast-skips to it (index arithmetic only; no sample is
fetched for the skipped prefix). ``advance()`` is called by the DataLoader
once per batch it DELIVERS to the training loop, so a checkpoint taken
after step K resumes at batch K+1: nothing replayed, nothing skipped.
RandomSampler is deterministically seeded per instance (an explicit
per-epoch ``np.random.RandomState``, never global numpy state), so the
skipped prefix is bitwise the prefix the dead run already consumed — and
ranks that fork with different global numpy state still shuffle
identically.
"""

from __future__ import annotations

import numpy as np


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    """Shuffled indices from an explicit, capturable RNG.

    ``generator`` may be an int seed, an ``np.random.RandomState`` (legacy:
    caller-managed, not exactly resumable), or None — which now draws ONE
    per-instance seed from OS entropy instead of consuming global numpy
    state on every epoch. Seeded instances reshuffle per epoch via
    ``set_epoch`` (the enclosing BatchSampler drives it) yet are fully
    deterministic given (seed, epoch) — the property exact resume needs."""

    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator  # np.random.RandomState or seed int
        self._epoch = 0
        # standalone unseeded instances auto-reshuffle each __iter__ (the
        # old OS-entropy behavior, now deterministic given the instance
        # seed); an external set_epoch/load_state_dict pins the epoch for
        # that iteration instead (the BatchSampler / exact-resume path)
        self._epoch_pinned = False
        self._drawn = False
        if generator is None:
            import random as _random

            self._seed = _random.SystemRandom().getrandbits(31)
        elif isinstance(generator, (int, np.integer)):
            self._seed = int(generator)
        else:
            self._seed = None  # explicit RandomState: stateful, caller-owned

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def set_epoch(self, epoch):
        self._epoch = int(epoch)
        self._epoch_pinned = True

    def state_dict(self):
        return {"seed": self._seed, "epoch": self._epoch}

    def load_state_dict(self, state):
        if not state:
            return
        if "seed" in state and state["seed"] is None:
            # the cursor was captured from a caller-managed RandomState:
            # its stream position is not capturable, so the skipped prefix
            # cannot be proven to match — refuse, don't diverge
            from ..errors import ResumeMismatchError

            raise ResumeMismatchError(
                "sampler cursor was saved from a RandomSampler driven by a "
                "caller-managed np.random.RandomState; that stream is not "
                "capturable — seed the sampler (int or None generator) for "
                "exact resume"
            )
        if state.get("seed") is not None:
            self._seed = int(state["seed"])
        self._epoch = int(state.get("epoch", 0))
        self._epoch_pinned = True

    def _rng(self):
        if self._seed is None:
            return self.generator  # legacy RandomState passthrough
        # fresh per-epoch stream: replaying an epoch replays its permutation
        return np.random.RandomState((self._seed + 1_000_003 * self._epoch)
                                     % (2 ** 32))

    def __iter__(self):
        if (self.generator is None and not self._epoch_pinned
                and self._drawn):
            self._epoch += 1  # standalone unseeded: reshuffle per epoch
        self._epoch_pinned = False
        self._drawn = True
        n = len(self.data_source)
        rng = self._rng()
        if self.replacement:
            return iter(rng.randint(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """Groups sampler indices into batches (reference :97 signature)."""

    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        if bool(dataset is None) == bool(sampler is None):
            raise ValueError("provide exactly one of dataset / sampler")
        if sampler is not None:
            self.sampler = sampler
        else:
            self.sampler = (
                RandomSampler(dataset) if shuffle else SequenceSampler(dataset)
            )
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        self.batch_size = int(batch_size)
        self.drop_last = bool(drop_last)
        self._epoch = 0
        self._consumed = 0  # batches DELIVERED this epoch (DataLoader-driven)
        self._resume_skip = None  # armed by load_state_dict, one-shot
        self._iterated = False

    # -- exact-resume cursor ----------------------------------------------
    def state_dict(self):
        """Cursor = (epoch, batches consumed). Consumption is advanced by
        the DataLoader on DELIVERY, so prefetched-but-undelivered batches
        are (correctly) not counted — they re-fetch on resume."""
        st = {
            "version": 1,
            "epoch": self._epoch,
            "batches_consumed": self._consumed,
            "batch_size": self.batch_size,
            "num_samples": self._source_len(),
        }
        sub = getattr(self.sampler, "state_dict", None)
        if callable(sub):
            st["sampler"] = sub()
        return st

    def _source_len(self):
        try:
            return len(self.sampler)
        except TypeError:
            return None

    def _check_cursor_compat(self, state):
        """A cursor counts BATCHES over a specific permutation: skipping N
        batches of a different batch_size — or of a shuffle over a
        dataset whose size changed — lands on a different example prefix
        than the dead run consumed. Refuse, don't diverge."""
        from ..errors import ResumeMismatchError

        saved = state.get("batch_size")
        if saved is not None and int(saved) != self.batch_size:
            raise ResumeMismatchError(
                f"sampler cursor was saved with batch_size={saved} but "
                f"this sampler has batch_size={self.batch_size}; "
                "fast-skipping would land on a different example prefix "
                "than the dead run consumed"
            )
        saved_n, n = state.get("num_samples"), self._source_len()
        if saved_n is not None and n is not None and int(saved_n) != n:
            raise ResumeMismatchError(
                f"sampler cursor was saved over {saved_n} samples but the "
                f"dataset now has {n}; the shuffle permutation (and so the "
                "consumed prefix) would differ — re-shard/restart the "
                "epoch instead of fast-skipping"
            )

    def load_state_dict(self, state):
        """Arm the next ``__iter__`` to replay `state`'s epoch and skip its
        consumed prefix (index arithmetic only — no data is fetched)."""
        if not state:
            return
        self._check_cursor_compat(state)
        self._epoch = int(state.get("epoch", 0))
        self._consumed = int(state.get("batches_consumed", 0))
        self._resume_skip = self._consumed
        sub = state.get("sampler")
        if sub and hasattr(self.sampler, "load_state_dict"):
            self.sampler.load_state_dict(sub)

    def advance(self, n=1):
        self._consumed += n

    def _begin_epoch(self, bump_epoch=True):
        """Start-of-iteration bookkeeping shared with the distributed
        subclass: consume a one-shot resume skip, else open a fresh epoch
        (with `bump_epoch`, advancing the epoch so seeded samplers
        reshuffle — the distributed subclass passes False: its epoch is
        user-driven via set_epoch). Returns the number of leading batches
        to skip."""
        if self._resume_skip is not None:
            skip, self._resume_skip = self._resume_skip, None
        else:
            if bump_epoch and self._iterated:
                self._epoch += 1
            skip = 0
        self._iterated = True
        self._consumed = skip
        set_epoch = getattr(self.sampler, "set_epoch", None)
        if callable(set_epoch):
            set_epoch(self._epoch)
        return skip

    def __iter__(self):
        skip = self._begin_epoch()
        emitted = 0
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                if emitted >= skip:
                    yield batch
                emitted += 1
                batch = []
        if batch and not self.drop_last and emitted >= skip:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Each rank sees a disjoint 1/nranks slice of every epoch
    (reference incubate distributed batch sampler semantics).

    The epoch is user-driven via ``set_epoch`` (never auto-bumped — the
    reference contract), and the resume cursor fast-skips by slicing the
    precomputed per-rank index array, so skip-to-cursor costs O(1) extra
    regardless of how deep into the epoch the checkpoint was."""

    def __init__(self, dataset, batch_size, nranks=None, rank=None,
                 shuffle=False, drop_last=False, seed=0):
        import os

        self.nranks = nranks if nranks is not None else int(
            os.environ.get("PADDLE_TRAINERS_NUM", 1)
        )
        self.rank = rank if rank is not None else int(
            os.environ.get("PADDLE_TRAINER_ID", 0)
        )
        self.dataset = dataset
        self.shuffle = shuffle
        self.seed = seed
        super().__init__(
            sampler=SequenceSampler(dataset), batch_size=batch_size,
            drop_last=drop_last,
        )

    # the public `epoch` attribute IS the base cursor's epoch, so the
    # shared _begin_epoch/state bookkeeping sees user-driven set_epoch
    @property
    def epoch(self):
        return self._epoch

    @epoch.setter
    def epoch(self, value):
        self._epoch = int(value)

    def set_epoch(self, epoch):
        self.epoch = int(epoch)

    def _source_len(self):
        return len(self.dataset)

    def state_dict(self):
        return {
            "version": 1,
            "epoch": self.epoch,
            "batches_consumed": self._consumed,
            "batch_size": self.batch_size,
            "num_samples": self._source_len(),
            "seed": self.seed,
            "rank": self.rank,
            "nranks": self.nranks,
        }

    def load_state_dict(self, state):
        if not state:
            return
        self._check_cursor_compat(state)
        # the skipped prefix is only the consumed prefix if the shuffle
        # stream and the rank slicing are the ones the dead run used:
        # restore the seed, and refuse a silently different world shape
        # (an elastically resized pod must re-shard, not fast-skip)
        if state.get("seed") is not None:
            self.seed = state["seed"]
        for field, mine in (("rank", self.rank), ("nranks", self.nranks)):
            if state.get(field) is not None and state[field] != mine:
                from ..errors import ResumeMismatchError

                raise ResumeMismatchError(
                    f"sampler cursor was saved by {field}="
                    f"{state[field]} but this sampler has {field}={mine}; "
                    "fast-skipping would replay a different example "
                    "prefix than the dead run consumed"
                )
        self.set_epoch(state.get("epoch", 0))
        self._consumed = int(state.get("batches_consumed", 0))
        self._resume_skip = self._consumed

    def __iter__(self):
        skip = self._begin_epoch(bump_epoch=False)
        n = len(self.dataset)
        if self.shuffle:
            order = np.random.RandomState(self.seed + self.epoch).permutation(n)
        else:
            order = np.arange(n)
        # pad so every rank gets the same number of samples
        per_rank = (n + self.nranks - 1) // self.nranks
        padded = np.resize(order, per_rank * self.nranks)
        mine = padded[self.rank::self.nranks]
        batch = []
        # fast skip-to-cursor: drop the consumed prefix before fetching
        for idx in mine[skip * self.batch_size:].tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.dataset)
        per_rank = (n + self.nranks - 1) // self.nranks
        if self.drop_last:
            return per_rank // self.batch_size
        return (per_rank + self.batch_size - 1) // self.batch_size
