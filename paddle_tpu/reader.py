"""DataLoader (reference python/paddle/fluid/reader.py: DataLoader :100,
from_generator :360, GeneratorLoader :952).

Two front doors, same as the reference:
  * DataLoader(dataset, ...) — map/iterable Dataset + BatchSampler +
    worker prefetch + device double-buffer (dataloader/dataloader_iter.py).
  * DataLoader.from_generator(feed_list, capacity) — the fluid-style loader
    bound to feed Variables; set_sample_generator / set_sample_list_generator
    / set_batch_generator, then iterate to get feed dicts for Executor.run.

The reference's non-iterable mode injected a create_py_reader op and a
blocking queue into the program (reader.py:952, operators/reader/py_reader);
under whole-block XLA compilation the program stays pure and feeding is the
host's job, so both modes here yield feed dicts — `iterable=False` only
changes start()/reset() bookkeeping for API compatibility.
"""

from __future__ import annotations

import queue
import threading

import numpy as np

from .reader_decorators import (  # noqa: F401  (paddle.reader decorators
    batch,  # live under fluid.reader here: one package serves both the
    buffered,  # fluid.reader module and the paddle.reader namespace)
    cache,
    chain,
    compose,
    firstn,
    map_readers,
    shuffle,
    xmap_readers,
)
from .dataloader import BatchSampler, Dataset, IterableDataset
from .dataloader.dataloader_iter import (
    _MultiWorkerIter,
    _SingleProcessIter,
    default_collate_fn,
)


class DataLoader:
    def __init__(
        self,
        dataset,
        feed_list=None,
        places=None,
        return_list=False,
        batch_sampler=None,
        batch_size=1,
        shuffle=False,
        drop_last=False,
        collate_fn=None,
        num_workers=0,
        use_buffer_reader=True,
        use_shared_memory=False,  # accepted for parity; threads share memory
        timeout=0,
        worker_init_fn=None,
    ):
        if not isinstance(dataset, Dataset):
            raise TypeError("dataset must be a paddle_tpu Dataset")
        self.dataset = dataset
        self.feed_list = feed_list
        self.return_list = return_list
        self.collate_fn = collate_fn
        self.num_workers = int(num_workers)
        self.use_buffer_reader = use_buffer_reader
        self.prefetch_factor = 2
        self.worker_init_fn = worker_init_fn
        # 0/None = wait forever for a batch (still hang-proof: a closed
        # pool or all-dead workers raise instead of blocking)
        self.timeout = float(timeout or 0)

        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
            self.drop_last = getattr(batch_sampler, "drop_last", drop_last)
        elif isinstance(dataset, IterableDataset):
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        else:
            self.batch_sampler = BatchSampler(
                dataset=dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last,
            )
            self.batch_size = batch_size
            self.drop_last = drop_last

    def __len__(self):
        if self.batch_sampler is None:
            # TypeError so list(loader) treats it as "no length hint"
            raise TypeError("IterableDataset loader has no len()")
        return len(self.batch_sampler)

    # -- exact-resume cursor ----------------------------------------------
    def state_dict(self):
        """The input-pipeline cursor (epoch + batches consumed + sampler
        RNG identity), capturable at any point of an epoch. Stored inside
        TrainStatus v2 so `load_state_dict` on a fresh process fast-skips
        to exactly the first batch the dead run never consumed."""
        if self.batch_sampler is None:
            raise TypeError(
                "IterableDataset loaders have no resumable cursor (a stream "
                "has no random access to skip into); use a map-style "
                "Dataset for exact resume"
            )
        if not hasattr(self.batch_sampler, "state_dict"):
            raise TypeError(
                f"{type(self.batch_sampler).__name__} has no "
                "state_dict/load_state_dict cursor; derive it from "
                "BatchSampler (or implement the pair) for exact resume"
            )
        return self.batch_sampler.state_dict()

    def load_state_dict(self, state):
        """Arm the next ``__iter__`` to resume from `state` (one-shot).
        Without a prior load_state_dict, iteration behavior is unchanged —
        every ``__iter__`` starts a fresh epoch."""
        if self.batch_sampler is None or not hasattr(
            self.batch_sampler, "load_state_dict"
        ):
            raise TypeError(
                "this loader's batch sampler has no resumable cursor"
            )
        self.batch_sampler.load_state_dict(state or {})

    def _track(self, it):
        """Advance the sampler cursor once per DELIVERED batch — the
        consumption notion a mid-epoch checkpoint needs (prefetched but
        undelivered batches re-fetch on resume)."""
        advance = getattr(self.batch_sampler, "advance", None)
        for b in it:
            if advance is not None:
                advance(1)
            yield b

    def __iter__(self):
        if self.num_workers > 0:
            it = _MultiWorkerIter(self)
        else:
            it = _SingleProcessIter(self)
        if self.feed_list and not self.return_list:
            names = [
                v if isinstance(v, str) else v.name for v in self.feed_list
            ]

            def as_feed(batch):
                # a single-array collate is ONE column, not an iterable of
                # columns — wrap so zip pairs names with whole batches
                cols = (
                    list(batch) if isinstance(batch, (list, tuple)) else [batch]
                )
                if len(cols) != len(names):
                    raise ValueError(
                        f"feed_list has {len(names)} variables but each "
                        f"sample yields {len(cols)} columns"
                    )
                return dict(zip(names, cols))

            return self._track(as_feed(b) for b in it)
        if self.batch_sampler is None:
            return it  # IterableDataset: no cursor to maintain
        return self._track(it)

    def __call__(self):
        return self.__iter__()

    @staticmethod
    def from_generator(
        feed_list=None,
        capacity=64,
        use_double_buffer=True,
        iterable=True,
        return_list=False,
        use_multiprocess=False,
        drop_last=True,
    ):
        return GeneratorLoader(
            feed_list=feed_list,
            capacity=capacity,
            use_double_buffer=use_double_buffer,
            iterable=iterable,
            return_list=return_list,
            drop_last=drop_last,
        )

    @staticmethod
    def from_dataset(dataset, places=None, drop_last=True):
        """Thin adaptor for paddle_tpu.dataset.* (PS-style datasets)."""
        return dataset


class GeneratorLoader:
    """fluid GeneratorLoader parity (reader.py:952): bind feed Variables,
    feed from a python generator with a background prefetch thread +
    device staging."""

    def __init__(self, feed_list, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False, drop_last=True):
        self._feed_list = feed_list or []
        self._names = [
            v if isinstance(v, str) else v.name for v in self._feed_list
        ]
        self._capacity = int(capacity)
        self._use_double_buffer = use_double_buffer
        self._iterable = iterable
        self._return_list = return_list
        self._drop_last = drop_last
        self._source = None  # () -> iterator of batches (list/tuple per var)

    # -- data source setters (reference :1022-1095) ------------------------
    def set_sample_generator(self, reader, batch_size, drop_last=True,
                             places=None):
        def batched():
            batch = []
            for sample in reader():
                if not isinstance(sample, (list, tuple)):
                    sample = (sample,)
                batch.append(sample)
                if len(batch) == batch_size:
                    yield default_collate_fn(batch)
                    batch = []
            if batch and not drop_last:
                yield default_collate_fn(batch)

        self._source = batched
        self._drop_last = drop_last
        return self

    def set_sample_list_generator(self, reader, places=None):
        def batched():
            for sample_list in reader():
                yield default_collate_fn(
                    [tuple(s) if isinstance(s, (list, tuple)) else (s,)
                     for s in sample_list]
                )

        self._source = batched
        return self

    def set_batch_generator(self, reader, places=None):
        self._source = reader
        return self

    # -- iteration ---------------------------------------------------------
    def _stage(self, arrays):
        if not self._use_double_buffer:
            return arrays
        from .dataloader.dataloader_iter import stage_to_device

        return [stage_to_device(a) for a in arrays]

    def _prefetching_iter(self):
        if self._source is None:
            raise RuntimeError(
                "no data source: call set_sample_generator / "
                "set_sample_list_generator / set_batch_generator first"
            )
        q: queue.Queue = queue.Queue(maxsize=self._capacity)
        DONE = object()
        stop = threading.Event()

        def put(item):
            # bounded put that gives up when the consumer abandoned the
            # iteration — otherwise the thread (and its staged device
            # buffers) would be pinned forever on a full queue
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                for batch in self._source():
                    if not isinstance(batch, (list, tuple)):
                        batch = (batch,)
                    if not put(self._stage(list(batch))):
                        return
            except BaseException as e:
                put(e)
                return
            put(DONE)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is DONE:
                    return
                if isinstance(item, BaseException):
                    raise item
                if self._return_list or not self._names:
                    yield item
                else:
                    yield {n: v for n, v in zip(self._names, item)}
        finally:
            # runs on break/exception/GC of the generator: release producer
            stop.set()

    def __iter__(self):
        return self._prefetching_iter()

    def __call__(self):
        return self.__iter__()

    # non-iterable mode compatibility (reference start/reset protocol)
    _started = None

    def start(self):
        self._started = self._prefetching_iter()
        return self

    def next(self):
        if self._started is None:
            raise RuntimeError(
                "GeneratorLoader is not started: call start() first "
                "(non-iterable mode protocol, reference reader.py:952)"
            )
        return next(self._started)

    def reset(self):
        if self._started is not None:
            self._started.close()  # triggers the producer shutdown path
        self._started = None
