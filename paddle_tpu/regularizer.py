"""Weight-decay regularizers (reference: python/paddle/fluid/regularizer.py):
appended to gradients in Optimizer.apply_gradients, as in the reference."""

from __future__ import annotations


class WeightDecayRegularizer:
    def append_regularization_op(self, param, grad, block):
        raise NotImplementedError


class L2Decay(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self.coeff = regularization_coeff

    def append_regularization_op(self, param, grad, block):
        from .framework import unique_name

        scaled = block.create_var(
            name=unique_name.generate(param.name + "@L2"),
            shape=param.shape,
            dtype=param.dtype,
        )
        block.append_op(
            "scale", {"X": [param.name]}, {"Out": [scaled.name]},
            {"scale": self.coeff},
        )
        out = block.create_var(
            name=unique_name.generate(grad.name + "@REG"),
            shape=grad.shape,
            dtype=grad.dtype,
        )
        block.append_op(
            "sum", {"X": [grad.name, scaled.name]}, {"Out": [out.name]}, {}
        )
        return out


class L1Decay(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self.coeff = regularization_coeff

    def append_regularization_op(self, param, grad, block):
        from .framework import unique_name

        sign = block.create_var(
            name=unique_name.generate(param.name + "@SIGN"),
            shape=param.shape,
            dtype=param.dtype,
        )
        block.append_op("sign", {"X": [param.name]}, {"Out": [sign.name]}, {})
        scaled = block.create_var(
            name=unique_name.generate(param.name + "@L1"),
            shape=param.shape,
            dtype=param.dtype,
        )
        block.append_op(
            "scale", {"X": [sign.name]}, {"Out": [scaled.name]},
            {"scale": self.coeff},
        )
        out = block.create_var(
            name=unique_name.generate(grad.name + "@REG"),
            shape=grad.shape,
            dtype=grad.dtype,
        )
        block.append_op(
            "sum", {"X": [grad.name, scaled.name]}, {"Out": [out.name]}, {}
        )
        return out


L2DecayRegularizer = L2Decay
L1DecayRegularizer = L1Decay
