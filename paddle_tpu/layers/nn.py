"""Functional layer API — the fluid.layers surface.

Reference parity: python/paddle/fluid/layers/nn.py (fc :208, conv2d :1315,
batch_norm :2614, layer_norm :3381, softmax :1183, dropout, embedding, pool2d
...), loss.py, tensor.py. Each function appends ops to the default main
program; parameters are created via LayerHelper with their init ops in the
startup program.
"""

from __future__ import annotations

import numpy as np

from ..core.dtypes import convert_dtype
from ..framework import unique_name
from ..framework.program import default_main_program
from ..initializer import Constant, Normal, Uniform, Xavier
from .helper import LayerHelper, main_block


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=False):
    """fluid.data / layers.data: declare a feed variable.

    append_batch_size=True prepends -1 (layers/io.py `data` semantics in the
    reference); fluid.data-style full shapes are the default here."""
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    blk = default_main_program().global_block
    return blk.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        is_data=True,
        stop_gradient=True,
        lod_level=lod_level,
    )


def fc(
    input,
    size,
    num_flatten_dims=1,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    helper = LayerHelper("fc", name=name)
    in_dim = int(np.prod(input.shape[num_flatten_dims:]))
    w = helper.create_parameter(param_attr, [in_dim, size], input.dtype)
    out = helper.create_and_append(
        {"X": [input], "Y": [w]},
        {"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        op_type="mul",
    )
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [size], input.dtype, is_bias=True)
        out = helper.create_and_append(
            {"X": [out], "Y": [b]},
            {"axis": num_flatten_dims},
            op_type="elementwise_add",
        )
    return _apply_act(out, act)


def _apply_act(out, act):
    if act is None:
        return out
    helper = LayerHelper(act)
    return helper.create_and_append({"X": [out]}, {}, op_type=act)


def embedding(
    input,
    size,
    is_sparse=False,
    padding_idx=None,
    param_attr=None,
    dtype="float32",
    name=None,
):
    helper = LayerHelper("embedding", name=name)
    w = helper.create_parameter(
        param_attr, list(size), dtype, default_initializer=Xavier()
    )
    return helper.create_and_append(
        {"W": [w], "Ids": [input]},
        {"padding_idx": -1 if padding_idx is None else padding_idx},
        op_type="lookup_table_v2" if (input.shape and input.shape[-1] != 1) else "lookup_table",
    )


def conv2d(
    input,
    num_filters,
    filter_size,
    stride=1,
    padding=0,
    dilation=1,
    groups=1,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
    data_format="NCHW",
):
    helper = LayerHelper("conv2d", name=name)
    k = filter_size if isinstance(filter_size, (list, tuple)) else [filter_size] * 2
    groups = groups or 1
    num_channels = input.shape[1]
    w_shape = [num_filters, num_channels // groups, k[0], k[1]]
    std = (2.0 / (k[0] * k[1] * num_channels)) ** 0.5
    w = helper.create_parameter(
        param_attr, w_shape, input.dtype, default_initializer=Normal(0.0, std)
    )
    attrs = {
        "strides": list(stride) if isinstance(stride, (list, tuple)) else [stride] * 2,
        "paddings": list(padding) if isinstance(padding, (list, tuple)) else [padding] * 2,
        "dilations": list(dilation) if isinstance(dilation, (list, tuple)) else [dilation] * 2,
        "groups": groups,
        "padding_algorithm": "EXPLICIT",
    }
    out = helper.create_and_append(
        {"Input": [input], "Filter": [w]}, attrs, out_slots=("Output",)
    )
    if bias_attr is not False:
        b = helper.create_parameter(
            bias_attr, [num_filters], input.dtype, is_bias=True
        )
        out = helper.create_and_append(
            {"X": [out], "Y": [b]}, {"axis": 1}, op_type="elementwise_add"
        )
    return _apply_act(out, act)


def conv2d_transpose(
    input,
    num_filters,
    filter_size,
    stride=1,
    padding=0,
    groups=1,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    helper = LayerHelper("conv2d_transpose", name=name)
    k = filter_size if isinstance(filter_size, (list, tuple)) else [filter_size] * 2
    w_shape = [input.shape[1], num_filters // (groups or 1), k[0], k[1]]
    w = helper.create_parameter(param_attr, w_shape, input.dtype)
    attrs = {
        "strides": list(stride) if isinstance(stride, (list, tuple)) else [stride] * 2,
        "paddings": list(padding) if isinstance(padding, (list, tuple)) else [padding] * 2,
        "groups": groups or 1,
    }
    out = helper.create_and_append(
        {"Input": [input], "Filter": [w]}, attrs, out_slots=("Output",)
    )
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [num_filters], input.dtype, is_bias=True)
        out = helper.create_and_append(
            {"X": [out], "Y": [b]}, {"axis": 1}, op_type="elementwise_add"
        )
    return _apply_act(out, act)


def pool2d(
    input,
    pool_size=-1,
    pool_type="max",
    pool_stride=1,
    pool_padding=0,
    global_pooling=False,
    ceil_mode=False,
    exclusive=True,
    name=None,
):
    helper = LayerHelper("pool2d", name=name)
    attrs = {
        "ksize": list(pool_size) if isinstance(pool_size, (list, tuple)) else [pool_size] * 2,
        "pooling_type": pool_type,
        "strides": list(pool_stride) if isinstance(pool_stride, (list, tuple)) else [pool_stride] * 2,
        "paddings": list(pool_padding) if isinstance(pool_padding, (list, tuple)) else [pool_padding] * 2,
        "global_pooling": global_pooling,
        "exclusive": exclusive,
    }
    return helper.create_and_append({"X": [input]}, attrs)


def adaptive_pool2d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    # pool_type default follows the fluid reference (nn.py adaptive_pool2d
    # defaults to max)
    helper = LayerHelper("pool2d", name=name)
    attrs = {
        "ksize": list(pool_size) if isinstance(pool_size, (list, tuple)) else [pool_size] * 2,
        "pooling_type": pool_type,
        "adaptive": True,
    }
    return helper.create_and_append({"X": [input]}, attrs)


def batch_norm(
    input,
    act=None,
    is_test=False,
    momentum=0.9,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    data_layout="NCHW",
    name=None,
    use_global_stats=False,
    moving_mean_name=None,
    moving_variance_name=None,
):
    """moving_mean_name/moving_variance_name (fluid layers/nn.py batch_norm
    params): deterministic running-stat names so a separately built
    inference program shares the trained statistics.

    Numerics note (advisor r2): training stats use the single-pass
    E[x^2]-E[x]^2 form with fp32 accumulation (ops/nn.py batch_norm).
    Cancellation is benign for the normalized-activation inputs BN sees in
    practice, but inputs with LARGE channel means (e.g. raw unnormalized
    images at the first layer) can lose precision — normalize inputs
    upstream or standardize them before the first BN."""
    helper = LayerHelper("batch_norm", name=name)
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    dtype = input.dtype if input.dtype != "float16" else "float32"
    scale = helper.create_parameter(
        param_attr, [c], dtype, default_initializer=Constant(1.0)
    )
    bias = helper.create_parameter(bias_attr, [c], dtype, is_bias=True)
    from ..param_attr import ParamAttr

    mean = helper.create_parameter(
        ParamAttr(
            name=moving_mean_name or unique_name.generate("bn_mean"),
            trainable=False,
            initializer=Constant(0.0),
        ),
        [c],
        dtype,
    )
    var = helper.create_parameter(
        ParamAttr(
            name=moving_variance_name or unique_name.generate("bn_variance"),
            trainable=False,
            initializer=Constant(1.0),
        ),
        [c],
        dtype,
    )
    mean.stop_gradient = True
    var.stop_gradient = True

    blk = main_block()
    y = blk.create_var(
        name=unique_name.generate("batch_norm.y"), shape=input.shape, dtype=input.dtype
    )
    saved_mean = blk.create_var(
        name=unique_name.generate("batch_norm.sm"), shape=[c], dtype=dtype,
        stop_gradient=True,
    )
    saved_var = blk.create_var(
        name=unique_name.generate("batch_norm.sv"), shape=[c], dtype=dtype,
        stop_gradient=True,
    )
    blk.append_op(
        "batch_norm",
        {
            "X": [input.name],
            "Scale": [scale.name],
            "Bias": [bias.name],
            "Mean": [mean.name],
            "Variance": [var.name],
        },
        {
            "Y": [y.name],
            "MeanOut": [mean.name],
            "VarianceOut": [var.name],
            "SavedMean": [saved_mean.name],
            "SavedVariance": [saved_var.name],
        },
        {
            "momentum": momentum,
            "epsilon": epsilon,
            "is_test": is_test,
            "data_layout": data_layout,
            "use_global_stats": use_global_stats,
        },
    )
    return _apply_act(y, act)


def layer_norm(
    input,
    scale=True,
    shift=True,
    begin_norm_axis=1,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    helper = LayerHelper("layer_norm", name=name)
    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    s = (
        helper.create_parameter(
            param_attr, norm_shape, input.dtype, default_initializer=Constant(1.0)
        )
        if scale
        else None
    )
    b = (
        helper.create_parameter(bias_attr, norm_shape, input.dtype, is_bias=True)
        if shift
        else None
    )
    ins = {"X": [input]}
    if s is not None:
        ins["Scale"] = [s]
    if b is not None:
        ins["Bias"] = [b]
    y, _, _ = helper.create_and_append(
        ins,
        {"begin_norm_axis": begin_norm_axis, "epsilon": epsilon},
        out_slots=("Y", "Mean", "Variance"),
    )
    return _apply_act(y, act)


def dropout(
    x,
    dropout_prob,
    is_test=False,
    seed=None,
    dropout_implementation="downgrade_in_infer",
    name=None,
):
    helper = LayerHelper("dropout", name=name)
    out, _ = helper.create_and_append(
        {"X": [x]},
        {
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "dropout_implementation": dropout_implementation,
            "seed": seed or 0,
        },
        out_slots=("Out", "Mask"),
    )
    return out


# ---------------------------------------------------------------------------
# losses & metrics
# ---------------------------------------------------------------------------


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    return helper.create_and_append(
        {"X": [input], "Label": [label]},
        {"soft_label": soft_label, "ignore_index": ignore_index},
        out_slots=("Y",),
    )


def softmax_with_cross_entropy(
    logits, label, soft_label=False, ignore_index=-100, axis=-1,
    return_softmax=False,
):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax, loss = helper.create_and_append(
        {"Logits": [logits], "Label": [label]},
        {"soft_label": soft_label, "ignore_index": ignore_index, "axis": axis},
        out_slots=("Softmax", "Loss"),
    )
    return (loss, softmax) if return_softmax else loss


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    return helper.create_and_append({"X": [input], "Y": [label]}, {})


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, normalize=False):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits")
    return helper.create_and_append(
        {"X": [x], "Label": [label]},
        {"ignore_index": ignore_index, "normalize": normalize},
    )


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    return helper.create_and_append({"X": [x]}, {})


def accuracy(input, label, k=1):
    helper = LayerHelper("accuracy")
    topk_out, topk_idx = helper.create_and_append(
        {"X": [input]}, {"k": k}, op_type="top_k", out_slots=("Out", "Indices"),
        stop_gradient=True,
    )
    acc, _, _ = helper.create_and_append(
        {"Out": [topk_out], "Indices": [topk_idx], "Label": [label]},
        {},
        op_type="accuracy",
        out_slots=("Accuracy", "Correct", "Total"),
        stop_gradient=True,
    )
    return acc


def sparse_embedding(
    input, size, param_attr=None, dtype="float32", axis="ps",
    pad_to_multiple=8, is_sparse=True, dedup=True,
):
    """Mesh-sharded (huge) embedding lookup — the PS-table capability
    (reference distributed_lookup_table_op.cc / fluid sparse embedding).
    `size=[vocab, dim]`; vocab is padded up so any mesh axis size dividing
    `pad_to_multiple` shards evenly. `dedup` (default on) batch-uniques the
    ids before the gather so repeated ids read their row once and the
    backward is one segment-sum scatter. Same-width lookups coalesce into
    one ``fused_lookup_table`` under ``embedding.fuse_lookups``; row/col
    partition and the quantized grad exchange are selected by
    ``parallel.shard_sparse_tables`` / ``parallel.quantize_embedding_grads``.
    See ops/sparse.py + parallel/sparse.py + paddle_tpu/embedding/.
    """
    vocab, dim = size
    padded = ((vocab + pad_to_multiple - 1) // pad_to_multiple) * pad_to_multiple
    helper = LayerHelper("sparse_embedding")
    w = helper.create_parameter(
        param_attr, [padded, dim], dtype, default_initializer=Xavier()
    )
    return helper.create_and_append(
        {"Ids": [input], "W": [w]},
        {"axis_name": axis, "dedup": bool(dedup)},
        op_type="distributed_lookup_table",
    )


def resize_nearest(input, out_shape=None, scale=None, name=None):
    """Nearest-neighbor upsampling (reference layers/nn.py resize_nearest ->
    nearest_interp_op.cc); out_shape [H, W] or a scale factor."""
    helper = LayerHelper("nearest_interp", name=name)
    attrs = _resize_attrs(out_shape, scale)
    return helper.create_and_append({"X": [input]}, attrs,
                                    op_type="nearest_interp")


def _resize_attrs(out_shape, scale):
    if out_shape is None and scale is None:
        raise ValueError("one of out_shape and scale must be set")
    attrs = {}
    if out_shape is not None:
        attrs["out_h"], attrs["out_w"] = int(out_shape[0]), int(out_shape[1])
    if scale is not None:
        attrs["scale"] = float(scale)
    return attrs


def resize_bilinear(input, out_shape=None, scale=None, name=None):
    """Bilinear resize (reference layers/nn.py resize_bilinear)."""
    helper = LayerHelper("bilinear_interp", name=name)
    attrs = _resize_attrs(out_shape, scale)
    return helper.create_and_append({"X": [input]}, attrs,
                                    op_type="bilinear_interp")


def linear_chain_crf(input, label, param_attr=None, length=None, name=None):
    """CRF cost (reference fluid/layers/nn.py linear_chain_crf ->
    linear_chain_crf_op). input: emissions [B, T, D]; label [B, T] int;
    length [B] optional valid lengths. Creates the [D+2, D] transition
    parameter (row 0 start, row 1 end, rest the transition matrix) and
    returns per-sequence negative log likelihood [B, 1]."""
    helper = LayerHelper("linear_chain_crf", name=name)
    d = input.shape[-1]
    transition = helper.create_parameter(
        param_attr, [d + 2, d], input.dtype,
        default_initializer=Uniform(-0.1, 0.1),
    )
    ins = {"Emission": [input], "Transition": [transition], "Label": [label]}
    if length is not None:
        ins["Length"] = [length]
    return helper.create_and_append(ins, {}, out_slots=("LogLikelihood",))


def crf_decoding(input, param_attr=None, label=None, length=None, name=None):
    """Viterbi decode [B, T] using the SAME transition parameter as
    linear_chain_crf (pass the same param_attr name). With label, returns
    the per-position correctness mask (reference crf_decoding_op)."""
    helper = LayerHelper("crf_decoding", name=name)
    d = input.shape[-1]
    transition = helper.create_parameter(
        param_attr, [d + 2, d], input.dtype,
        default_initializer=Uniform(-0.1, 0.1),
    )
    ins = {"Emission": [input], "Transition": [transition]}
    if label is not None:
        ins["Label"] = [label]
    if length is not None:
        ins["Length"] = [length]
    return helper.create_and_append(ins, {}, out_slots=("ViterbiPath",))
