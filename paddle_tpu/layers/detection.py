"""Detection layer wrappers (reference fluid/layers/detection.py) over
ops/detection.py."""

from __future__ import annotations

from .tensor import _simple


def iou_similarity(x, y, name=None):
    return _simple("iou_similarity", {"X": [x], "Y": [y]}, {})


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, name=None):
    return _simple(
        "box_coder",
        {"PriorBox": [prior_box], "PriorBoxVar": [prior_box_var],
         "TargetBox": [target_box]},
        {"code_type": code_type, "box_normalized": box_normalized},
        out_slots=("OutputBox",),
    )


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=None,
              variance=None, flip=False, clip=False, steps=None, offset=0.5,
              name=None):
    steps = steps or [0.0, 0.0]
    return _simple(
        "prior_box",
        {"Input": [input], "Image": [image]},
        {
            "min_sizes": list(min_sizes),
            "max_sizes": list(max_sizes or []),
            "aspect_ratios": list(aspect_ratios or [1.0]),
            "variances": list(variance or [0.1, 0.1, 0.2, 0.2]),
            "flip": flip, "clip": clip,
            "step_w": steps[0], "step_h": steps[1], "offset": offset,
        },
        out_slots=("Boxes", "Variances"),
        stop_gradient=True,
    )


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, name=None):
    return _simple(
        "yolo_box",
        {"X": [x], "ImgSize": [img_size]},
        {"anchors": list(anchors), "class_num": class_num,
         "conf_thresh": conf_thresh, "downsample_ratio": downsample_ratio},
        out_slots=("Boxes", "Scores"),
        stop_gradient=True,
    )


def multiclass_nms(bboxes, scores, score_threshold=0.0, nms_top_k=64,
                   keep_top_k=16, nms_threshold=0.3, normalized=True,
                   background_label=-1, name=None):
    """background_label: class column skipped by NMS (the reference
    defaults to 0 = first column is background; -1 disables — YOLO-style
    heads have no background column)."""
    return _simple(
        "multiclass_nms",
        {"BBoxes": [bboxes], "Scores": [scores]},
        {"score_threshold": score_threshold, "nms_top_k": nms_top_k,
         "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
         "background_label": background_label},
        out_slots=("Out", "NmsRoisNum"),
        stop_gradient=True,
    )


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh=0.7, downsample_ratio=32, gt_score=None,
                use_label_smooth=True, scale_x_y=1.0, name=None):
    """YOLOv3 head loss (reference fluid/layers/detection.py yolo family ->
    detection/yolov3_loss_op.h). Returns per-image loss [N]."""
    inputs = {"X": [x], "GTBox": [gt_box], "GTLabel": [gt_label]}
    if gt_score is not None:
        inputs["GTScore"] = [gt_score]
    return _simple(
        "yolov3_loss",
        inputs,
        {
            "anchors": list(anchors),
            "anchor_mask": list(anchor_mask),
            "class_num": class_num,
            "ignore_thresh": ignore_thresh,
            "downsample_ratio": downsample_ratio,
            "use_label_smooth": use_label_smooth,
            "scale_x_y": scale_x_y,
        },
        out_slots=("Loss", "ObjectnessMask", "GTMatchMask"),
    )[0]


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, rois_num=None,
              name=None):
    """RoIAlign (reference fluid/layers: roi_align -> roi_align_op.h).
    rois [R, 4] image-coordinate corners; rois_num [N] per-image counts
    (LoD-free)."""
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_num is not None:
        inputs["RoisNum"] = [rois_num]
    return _simple(
        "roi_align",
        inputs,
        {"pooled_height": pooled_height, "pooled_width": pooled_width,
         "spatial_scale": spatial_scale, "sampling_ratio": sampling_ratio},
    )


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_num=None, name=None):
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_num is not None:
        inputs["RoisNum"] = [rois_num]
    return _simple(
        "roi_pool",
        inputs,
        {"pooled_height": pooled_height, "pooled_width": pooled_width,
         "spatial_scale": spatial_scale},
        out_slots=("Out", "Argmax"),
    )[0]


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=None, stride=None, offset=0.5, name=None):
    return _simple(
        "anchor_generator",
        {"Input": [input]},
        {"anchor_sizes": list(anchor_sizes or [64.0]),
         "aspect_ratios": list(aspect_ratios or [1.0]),
         "variances": list(variance or [0.1, 0.1, 0.2, 0.2]),
         "stride": list(stride or [16.0, 16.0]),
         "offset": offset},
        out_slots=("Anchors", "Variances"),
        stop_gradient=True,
    )


def box_clip(input, im_info, name=None):
    return _simple(
        "box_clip",
        {"Input": [input], "ImInfo": [im_info]},
        {},
        out_slots=("Output",),
    )


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25, name=None):
    return _simple(
        "sigmoid_focal_loss",
        {"X": [x], "Label": [label], "FgNum": [fg_num]},
        {"gamma": gamma, "alpha": alpha},
    )


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=None, clip=False,
                      steps=None, offset=0.5, name=None):
    steps = steps or [0.0, 0.0]
    return _simple(
        "density_prior_box",
        {"Input": [input], "Image": [image]},
        {"densities": list(densities or [1]),
         "fixed_sizes": list(fixed_sizes or [32.0]),
         "fixed_ratios": list(fixed_ratios or [1.0]),
         "variances": list(variance or [0.1, 0.1, 0.2, 0.2]),
         "clip": clip, "step_w": steps[0], "step_h": steps[1],
         "offset": offset},
        out_slots=("Boxes", "Variances"),
        stop_gradient=True,
    )


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, name=None):
    """RPN proposals ([N, post_nms_top_n, 4] padded + probs + valid
    counts; the reference emits variable-length LoD rois)."""
    return _simple(
        "generate_proposals",
        {"Scores": [scores], "BboxDeltas": [bbox_deltas],
         "ImInfo": [im_info], "Anchors": [anchors],
         "Variances": [variances]},
        {"pre_nms_topN": pre_nms_top_n, "post_nms_topN": post_nms_top_n,
         "nms_thresh": nms_thresh, "min_size": min_size},
        out_slots=("RpnRois", "RpnRoiProbs", "RpnRoisNum"),
        stop_gradient=True,
    )


def rpn_target_assign(anchor, gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    """RPN anchor sampling (reference fluid/layers rpn_target_assign over
    detection/rpn_target_assign_op.cc); fixed-size -1-padded outputs."""
    return _simple(
        "rpn_target_assign",
        {"Anchor": [anchor], "GtBoxes": [gt_boxes],
         "IsCrowd": [is_crowd], "ImInfo": [im_info]},
        {"rpn_batch_size_per_im": rpn_batch_size_per_im,
         "rpn_straddle_thresh": rpn_straddle_thresh,
         "rpn_fg_fraction": rpn_fg_fraction,
         "rpn_positive_overlap": rpn_positive_overlap,
         "rpn_negative_overlap": rpn_negative_overlap},
        out_slots=("LocationIndex", "ScoreIndex", "TargetLabel",
                   "TargetBBox", "BBoxInsideWeight"),
        stop_gradient=True,
    )


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=512,
                             fg_fraction=0.25, fg_thresh=0.5,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=81, use_random=True,
                             rois_num=None):
    return _simple(
        "generate_proposal_labels",
        {"RpnRois": [rpn_rois], "GtClasses": [gt_classes],
         "IsCrowd": [is_crowd], "GtBoxes": [gt_boxes],
         "ImInfo": [im_info], "RpnRoisNum": [rois_num]},
        {"batch_size_per_im": batch_size_per_im,
         "fg_fraction": fg_fraction, "fg_thresh": fg_thresh,
         "bg_thresh_hi": bg_thresh_hi, "bg_thresh_lo": bg_thresh_lo,
         "bbox_reg_weights": list(bbox_reg_weights),
         "class_nums": class_nums},
        out_slots=("Rois", "LabelsInt32", "BboxTargets",
                   "BboxInsideWeights", "BboxOutsideWeights", "RoisNum",
                   "MaxOverlapWithGT"),
        stop_gradient=True,
    )


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes=81, resolution=14):
    """Mask targets. gt_segms: dense per-gt binary bitmaps [G, H, W]
    (see ops/detection_ext.py for the dense-mask contract)."""
    return _simple(
        "generate_mask_labels",
        {"ImInfo": [im_info], "GtClasses": [gt_classes],
         "IsCrowd": [is_crowd], "GtSegms": [gt_segms],
         "Rois": [rois], "LabelsInt32": [labels_int32]},
        {"num_classes": num_classes, "resolution": resolution},
        out_slots=("MaskRois", "RoiHasMaskInt32", "MaskInt32"),
        stop_gradient=True,
    )


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None):
    return _simple(
        "distribute_fpn_proposals",
        {"FpnRois": [fpn_rois], "RoisNum": [rois_num]},
        {"min_level": min_level, "max_level": max_level,
         "refer_level": refer_level, "refer_scale": refer_scale},
        out_slots=("MultiFpnRois", "RestoreIndex", "MultiLevelRoIsNum"),
        stop_gradient=True,
    )


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_nums=None):
    return _simple(
        "collect_fpn_proposals",
        {"MultiLevelRois": list(multi_rois),
         "MultiLevelScores": list(multi_scores),
         "MultiLevelRoIsNum": list(rois_nums or [])},
        {"post_nms_topN": post_nms_top_n},
        out_slots=("FpnRois", "RoisNum"),
        stop_gradient=True,
    )


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip=4.135):
    return _simple(
        "box_decoder_and_assign",
        {"PriorBox": [prior_box], "PriorBoxVar": [prior_box_var],
         "TargetBox": [target_box], "BoxScore": [box_score]},
        {"box_clip": box_clip},
        out_slots=("DecodeBox", "OutputAssignBox"),
    )


def bipartite_match(dist_matrix, match_type="bipartite", dist_threshold=0.5,
                    name=None):
    return _simple(
        "bipartite_match", {"DistMat": [dist_matrix]},
        {"match_type": match_type, "dist_threshold": dist_threshold},
        out_slots=("ColToRowMatchIndices", "ColToRowMatchDist"),
        stop_gradient=True,
    )


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    return _simple(
        "target_assign",
        {"X": [input], "MatchIndices": [matched_indices],
         "NegIndices": [negative_indices]},
        {"mismatch_value": mismatch_value},
        out_slots=("Out", "OutWeight"),
        stop_gradient=True,
    )


def mine_hard_examples(cls_loss, match_indices, match_dist=None,
                       loc_loss=None, neg_pos_ratio=3.0,
                       neg_dist_threshold=0.5, sample_size=0,
                       mining_type="max_negative"):
    return _simple(
        "mine_hard_examples",
        {"ClsLoss": [cls_loss], "LocLoss": [loc_loss],
         "MatchIndices": [match_indices], "MatchDist": [match_dist]},
        {"neg_pos_ratio": neg_pos_ratio,
         "neg_dist_threshold": neg_dist_threshold,
         "sample_size": sample_size, "mining_type": mining_type},
        out_slots=("NegIndices", "UpdatedMatchIndices"),
        stop_gradient=True,
    )


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd, im_info,
                            num_classes=1, positive_overlap=0.5,
                            negative_overlap=0.4):
    return _simple(
        "retinanet_target_assign",
        {"Anchor": [anchor_box], "GtBoxes": [gt_boxes],
         "GtLabels": [gt_labels], "IsCrowd": [is_crowd],
         "ImInfo": [im_info]},
        {"positive_overlap": positive_overlap,
         "negative_overlap": negative_overlap},
        out_slots=("LocationIndex", "ScoreIndex", "TargetLabel",
                   "TargetBBox", "BBoxInsideWeight", "ForegroundNumber"),
        stop_gradient=True,
    )


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    return _simple(
        "retinanet_detection_output",
        {"BBoxes": list(bboxes), "Scores": list(scores),
         "Anchors": list(anchors), "ImInfo": [im_info]},
        {"score_threshold": score_threshold, "nms_top_k": nms_top_k,
         "keep_top_k": keep_top_k, "nms_threshold": nms_threshold},
        stop_gradient=True,
    )
