"""Detection layer wrappers (reference fluid/layers/detection.py) over
ops/detection.py."""

from __future__ import annotations

from .tensor import _simple


def iou_similarity(x, y, name=None):
    return _simple("iou_similarity", {"X": [x], "Y": [y]}, {})


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, name=None):
    return _simple(
        "box_coder",
        {"PriorBox": [prior_box], "PriorBoxVar": [prior_box_var],
         "TargetBox": [target_box]},
        {"code_type": code_type, "box_normalized": box_normalized},
        out_slots=("OutputBox",),
    )


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=None,
              variance=None, flip=False, clip=False, steps=None, offset=0.5,
              name=None):
    steps = steps or [0.0, 0.0]
    return _simple(
        "prior_box",
        {"Input": [input], "Image": [image]},
        {
            "min_sizes": list(min_sizes),
            "max_sizes": list(max_sizes or []),
            "aspect_ratios": list(aspect_ratios or [1.0]),
            "variances": list(variance or [0.1, 0.1, 0.2, 0.2]),
            "flip": flip, "clip": clip,
            "step_w": steps[0], "step_h": steps[1], "offset": offset,
        },
        out_slots=("Boxes", "Variances"),
        stop_gradient=True,
    )


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, name=None):
    return _simple(
        "yolo_box",
        {"X": [x], "ImgSize": [img_size]},
        {"anchors": list(anchors), "class_num": class_num,
         "conf_thresh": conf_thresh, "downsample_ratio": downsample_ratio},
        out_slots=("Boxes", "Scores"),
        stop_gradient=True,
    )


def multiclass_nms(bboxes, scores, score_threshold=0.0, nms_top_k=64,
                   keep_top_k=16, nms_threshold=0.3, normalized=True,
                   name=None):
    return _simple(
        "multiclass_nms",
        {"BBoxes": [bboxes], "Scores": [scores]},
        {"score_threshold": score_threshold, "nms_top_k": nms_top_k,
         "keep_top_k": keep_top_k, "nms_threshold": nms_threshold},
        out_slots=("Out", "NmsRoisNum"),
        stop_gradient=True,
    )


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh=0.7, downsample_ratio=32, gt_score=None,
                use_label_smooth=True, scale_x_y=1.0, name=None):
    """YOLOv3 head loss (reference fluid/layers/detection.py yolo family ->
    detection/yolov3_loss_op.h). Returns per-image loss [N]."""
    inputs = {"X": [x], "GTBox": [gt_box], "GTLabel": [gt_label]}
    if gt_score is not None:
        inputs["GTScore"] = [gt_score]
    return _simple(
        "yolov3_loss",
        inputs,
        {
            "anchors": list(anchors),
            "anchor_mask": list(anchor_mask),
            "class_num": class_num,
            "ignore_thresh": ignore_thresh,
            "downsample_ratio": downsample_ratio,
            "use_label_smooth": use_label_smooth,
            "scale_x_y": scale_x_y,
        },
        out_slots=("Loss", "ObjectnessMask", "GTMatchMask"),
    )[0]


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, rois_num=None,
              name=None):
    """RoIAlign (reference fluid/layers: roi_align -> roi_align_op.h).
    rois [R, 4] image-coordinate corners; rois_num [N] per-image counts
    (LoD-free)."""
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_num is not None:
        inputs["RoisNum"] = [rois_num]
    return _simple(
        "roi_align",
        inputs,
        {"pooled_height": pooled_height, "pooled_width": pooled_width,
         "spatial_scale": spatial_scale, "sampling_ratio": sampling_ratio},
    )


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_num=None, name=None):
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_num is not None:
        inputs["RoisNum"] = [rois_num]
    return _simple(
        "roi_pool",
        inputs,
        {"pooled_height": pooled_height, "pooled_width": pooled_width,
         "spatial_scale": spatial_scale},
        out_slots=("Out", "Argmax"),
    )[0]


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=None, stride=None, offset=0.5, name=None):
    return _simple(
        "anchor_generator",
        {"Input": [input]},
        {"anchor_sizes": list(anchor_sizes or [64.0]),
         "aspect_ratios": list(aspect_ratios or [1.0]),
         "variances": list(variance or [0.1, 0.1, 0.2, 0.2]),
         "stride": list(stride or [16.0, 16.0]),
         "offset": offset},
        out_slots=("Anchors", "Variances"),
        stop_gradient=True,
    )


def box_clip(input, im_info, name=None):
    return _simple(
        "box_clip",
        {"Input": [input], "ImInfo": [im_info]},
        {},
        out_slots=("Output",),
    )


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25, name=None):
    return _simple(
        "sigmoid_focal_loss",
        {"X": [x], "Label": [label], "FgNum": [fg_num]},
        {"gamma": gamma, "alpha": alpha},
    )


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=None, clip=False,
                      steps=None, offset=0.5, name=None):
    steps = steps or [0.0, 0.0]
    return _simple(
        "density_prior_box",
        {"Input": [input], "Image": [image]},
        {"densities": list(densities or [1]),
         "fixed_sizes": list(fixed_sizes or [32.0]),
         "fixed_ratios": list(fixed_ratios or [1.0]),
         "variances": list(variance or [0.1, 0.1, 0.2, 0.2]),
         "clip": clip, "step_w": steps[0], "step_h": steps[1],
         "offset": offset},
        out_slots=("Boxes", "Variances"),
        stop_gradient=True,
    )


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, name=None):
    """RPN proposals ([N, post_nms_top_n, 4] padded + probs + valid
    counts; the reference emits variable-length LoD rois)."""
    return _simple(
        "generate_proposals",
        {"Scores": [scores], "BboxDeltas": [bbox_deltas],
         "ImInfo": [im_info], "Anchors": [anchors],
         "Variances": [variances]},
        {"pre_nms_topN": pre_nms_top_n, "post_nms_topN": post_nms_top_n,
         "nms_thresh": nms_thresh, "min_size": min_size},
        out_slots=("RpnRois", "RpnRoiProbs", "RpnRoisNum"),
        stop_gradient=True,
    )
