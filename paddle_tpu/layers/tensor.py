"""Tensor-manipulation layers (fluid.layers.tensor + parts of nn).

Reference parity: python/paddle/fluid/layers/tensor.py (fill_constant, cast,
concat, assign, zeros/ones, sums, argmax...), plus reshape/transpose/etc from
layers/nn.py. Elementwise + activation wrappers are generated from the op
registry, mirroring the reference's layer_function_generator.py approach.
"""

from __future__ import annotations

import sys

from ..framework import unique_name
from .helper import LayerHelper, main_block


def _simple(op_type, ins, attrs, out_slots=("Out",), **kw):
    helper = LayerHelper(op_type)
    return helper.create_and_append(ins, attrs, out_slots=out_slots, **kw)


def fill_constant(shape, dtype, value, name=None):
    helper = LayerHelper("fill_constant", name=name)
    return helper.create_and_append(
        {}, {"shape": list(shape), "dtype": dtype, "value": float(value)},
        stop_gradient=True,
    )


def zeros(shape, dtype="float32"):
    return fill_constant(shape, dtype, 0.0)


def ones(shape, dtype="float32"):
    return fill_constant(shape, dtype, 1.0)


def zeros_like(x):
    return _simple(
        "fill_any_like", {"X": [x]}, {"value": 0.0}, stop_gradient=True
    )


def ones_like(x):
    return _simple(
        "fill_any_like", {"X": [x]}, {"value": 1.0}, stop_gradient=True
    )


def cast(x, dtype):
    return _simple("cast", {"X": [x]}, {"out_dtype": dtype})


def concat(input, axis=0, name=None):
    return _simple("concat", {"X": list(input)}, {"axis": axis})


def assign(input, output=None):
    blk = main_block()
    if output is None:
        return _simple("assign", {"X": [input]}, {})
    blk.append_op("assign", {"X": [input.name]}, {"Out": [output.name]}, {})
    return output


def sums(input, out=None):
    if out is not None:
        main_block().append_op(
            "sum", {"X": [v.name for v in input]}, {"Out": [out.name]}, {}
        )
        return out
    return _simple("sum", {"X": list(input)}, {})


def reshape(x, shape, inplace=False, name=None):
    out, _ = _simple(
        "reshape2", {"X": [x]}, {"shape": list(shape)}, out_slots=("Out", "XShape")
    )
    return out


def flatten(x, axis=1, name=None):
    out, _ = _simple(
        "flatten2", {"X": [x]}, {"axis": axis}, out_slots=("Out", "XShape")
    )
    return out


def transpose(x, perm, name=None):
    out, _ = _simple(
        "transpose2", {"X": [x]}, {"axis": list(perm)}, out_slots=("Out", "XShape")
    )
    return out


def squeeze(input, axes, name=None):
    out, _ = _simple(
        "squeeze2", {"X": [input]}, {"axes": list(axes)}, out_slots=("Out", "XShape")
    )
    return out


def unsqueeze(input, axes, name=None):
    out, _ = _simple(
        "unsqueeze2", {"X": [input]}, {"axes": list(axes)}, out_slots=("Out", "XShape")
    )
    return out


def stack(x, axis=0):
    return _simple("stack", {"X": list(x)}, {"axis": axis}, out_slots=("Y",))


def split(input, num_or_sections, dim=-1, name=None):
    if isinstance(num_or_sections, int):
        attrs = {"num": num_or_sections, "axis": dim, "sections": []}
        n = num_or_sections
    else:
        attrs = {"num": 0, "axis": dim, "sections": list(num_or_sections)}
        n = len(num_or_sections)
    helper = LayerHelper("split")
    outs = helper.create_and_append({"X": [input]}, attrs)
    return outs if isinstance(outs, (list, tuple)) else [outs]


def slice(input, axes, starts, ends):
    return _simple(
        "slice",
        {"Input": [input]},
        {"axes": list(axes), "starts": list(starts), "ends": list(ends)},
    )


def gather(input, index, overwrite=True):
    return _simple("gather", {"X": [input], "Index": [index]}, {})


def gather_nd(input, index, name=None):
    return _simple("gather_nd", {"X": [input], "Index": [index]}, {})


def scatter(input, index, updates, overwrite=True):
    return _simple(
        "scatter",
        {"X": [input], "Ids": [index], "Updates": [updates]},
        {"overwrite": overwrite},
    )


def expand(x, expand_times, name=None):
    return _simple("expand", {"X": [x]}, {"expand_times": list(expand_times)})


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    return _simple(
        "matmul",
        {"X": [x], "Y": [y]},
        {"transpose_X": transpose_x, "transpose_Y": transpose_y, "alpha": alpha},
    )


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1):
    return _simple(
        "mul",
        {"X": [x], "Y": [y]},
        {"x_num_col_dims": x_num_col_dims, "y_num_col_dims": y_num_col_dims},
    )


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    out = _simple(
        "scale",
        {"X": [x]},
        {"scale": scale, "bias": bias, "bias_after_scale": bias_after_scale},
    )
    if act:
        from .nn import _apply_act

        out = _apply_act(out, act)
    return out


def clip(x, min, max, name=None):
    return _simple("clip", {"X": [x]}, {"min": float(min), "max": float(max)})


def clip_by_norm(x, max_norm, name=None):
    return _simple("clip_by_norm", {"X": [x]}, {"max_norm": float(max_norm)})


def topk(input, k, name=None):
    return _simple(
        "top_k", {"X": [input]}, {"k": k}, out_slots=("Out", "Indices"),
        stop_gradient=True,
    )


def argmax(x, axis=-1):
    return _simple("arg_max", {"X": [x]}, {"axis": axis}, stop_gradient=True)


def argmin(x, axis=-1):
    return _simple("arg_min", {"X": [x]}, {"axis": axis}, stop_gradient=True)


def argsort(x, axis=-1, descending=False):
    return _simple(
        "argsort",
        {"X": [x]},
        {"axis": axis, "descending": descending},
        out_slots=("Out", "Indices"),
        stop_gradient=True,
    )


def one_hot(input, depth, allow_out_of_range=False):
    return _simple("one_hot_v2", {"X": [input]}, {"depth": depth})


def cumsum(x, axis=-1, exclusive=False, reverse=False):
    return _simple(
        "cumsum",
        {"X": [x]},
        {"axis": axis, "reverse": reverse, "exclusive": exclusive},
    )


def take_along_axis(x, index, axis=-1):
    return _simple(
        "take_along_axis", {"Input": [x], "Index": [index]}, {"Axis": axis},
        out_slots=("Result",),
    )


def assign_value(values, dtype="float32"):
    """Constant tensor from a python/numpy literal (assign_value op)."""
    import numpy as np

    arr = np.asarray(values)
    return _simple(
        "assign_value", {},
        {"values": arr.reshape(-1).tolist(), "shape": list(arr.shape),
         "dtype": dtype},
        stop_gradient=True,
    )


def where(condition, x, y):
    return _simple("where", {"Condition": [condition], "X": [x], "Y": [y]}, {})


def range(start, end, step, dtype):
    return _simple(
        "range", {}, {"start": start, "end": end, "step": step, "dtype": dtype},
        stop_gradient=True,
    )


def shape(input):
    return _simple("shape", {"Input": [input]}, {}, stop_gradient=True)


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_sum", input, dim, keep_dim)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_mean", input, dim, keep_dim)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_max", input, dim, keep_dim)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_min", input, dim, keep_dim)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_prod", input, dim, keep_dim)


def _reduce(op_type, input, dim, keep_dim):
    attrs = {
        "dim": [dim] if isinstance(dim, int) else (list(dim) if dim else [0]),
        "keep_dim": keep_dim,
        "reduce_all": dim is None,
    }
    return _simple(op_type, {"X": [input]}, attrs)


# --- generated elementwise / comparison wrappers ---------------------------

_THIS = sys.modules[__name__]


def _make_binary(op_type):
    def fn(x, y, axis=-1, act=None, name=None):
        out = _simple(op_type, {"X": [x], "Y": [y]}, {"axis": axis})
        if act:
            from .nn import _apply_act

            out = _apply_act(out, act)
        return out

    fn.__name__ = op_type
    return fn


for _t in [
    "elementwise_add", "elementwise_sub", "elementwise_mul", "elementwise_div",
    "elementwise_max", "elementwise_min", "elementwise_pow", "elementwise_mod",
    "elementwise_floordiv",
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal",
]:
    setattr(_THIS, _t, _make_binary(_t))


def _make_unary(op_type):
    def fn(x, name=None, **attrs):
        return _simple(op_type, {"X": [x]}, attrs)

    fn.__name__ = op_type
    return fn


for _t in [
    "relu", "sigmoid", "tanh", "sqrt", "rsqrt", "square", "abs", "exp", "log",
    "floor", "ceil", "round", "reciprocal", "sign", "sin", "cos", "gelu",
    "leaky_relu", "elu", "softplus", "softsign", "swish", "hard_swish",
    "hard_sigmoid", "logsigmoid", "relu6", "selu", "erf", "log_softmax",
    "logical_not", "silu", "mish",
]:
    setattr(_THIS, _t, _make_unary(_t))


def softmax(input, axis=-1, use_cudnn=False, name=None):
    return _simple("softmax", {"X": [input]}, {"axis": axis})


def pow(x, factor=1.0, name=None):
    return _simple("pow", {"X": [x]}, {"factor": factor})


def logical_and(x, y, name=None):
    return _simple("logical_and", {"X": [x], "Y": [y]}, {})


def logical_or(x, y, name=None):
    return _simple("logical_or", {"X": [x], "Y": [y]}, {})


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    return _simple(
        "uniform_random",
        {},
        {"shape": list(shape), "dtype": dtype, "min": min, "max": max, "seed": seed},
        stop_gradient=True,
    )


def gaussian_random(shape, dtype="float32", mean=0.0, std=1.0, seed=0):
    return _simple(
        "gaussian_random",
        {},
        {"shape": list(shape), "dtype": dtype, "mean": mean, "std": std, "seed": seed},
        stop_gradient=True,
    )


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id=1,
                is_accumulated=True, return_parent_idx=False,
                first_step=False, name=None):
    """One beam expansion step (fluid layers.beam_search signature over
    beam_search_op; see ops/beam_search.py for the static-shape design).

    scores [B, beam, V]: with is_accumulated=True (fluid default) these
    already CONTAIN the prefix scores; pass is_accumulated=False for raw
    per-step log-probs (pre_scores are then added internally). `ids`
    (candidate token ids) is accepted for fluid parity and ignored — with
    a dense [.., V] score tensor the candidate id IS the vocab index, as
    in fluid when ids is None. Returns (selected_ids, selected_scores)
    like fluid, or (+parent_idx) with return_parent_idx=True."""
    out = _simple(
        "beam_search",
        {"PreIds": [pre_ids], "PreScores": [pre_scores], "Scores": [scores]},
        {"beam_size": int(beam_size), "end_id": end_id,
         "first_step": bool(first_step),
         "is_accumulated": bool(is_accumulated)},
        out_slots=("SelectedIds", "SelectedScores", "ParentIdx"),
        stop_gradient=True,
    )
    sel_ids, sel_scores, parent = out
    if return_parent_idx:
        return sel_ids, sel_scores, parent
    return sel_ids, sel_scores


def beam_search_decode(ids, parent_idx, end_id=1, name=None):
    """Backtrack stacked [T, B, beam] selections -> [B, beam, T] sequences
    (reference layers.beam_search_decode / beam_search_decode_op).

    Static-shape contract: sequences are NOT trimmed at end_id — finished
    beams repeat end_id to full length (trim on the host if needed); the
    end_id argument is accepted for fluid parity."""
    return _simple(
        "beam_search_decode",
        {"Ids": [ids], "ParentIdx": [parent_idx]},
        {"end_id": end_id},
        out_slots=("SentenceIds",),
        stop_gradient=True,
    )


def tril(x, diagonal=0, name=None):
    return _simple("tril_triu", {"X": [x]},
                   {"diagonal": diagonal, "lower": True})


def triu(x, diagonal=0, name=None):
    return _simple("tril_triu", {"X": [x]},
                   {"diagonal": diagonal, "lower": False})
