"""fluid.layers-equivalent functional API (reference:
python/paddle/fluid/layers/ — 35k LoC across nn.py, tensor.py, loss.py...)."""

from .nn import *  # noqa: F401,F403
from .nn import _apply_act  # noqa: F401
from .attention import (  # noqa: F401
    fused_dropout_add_ln,
    fused_multihead_attention,
    fused_qkv_attention,
    moe_ffn,
    moe_shardings,
    ring_attention,
    ulysses_attention,
)
from .tensor import *  # noqa: F401,F403
from .tensor import (  # noqa: F401  (generated attrs need explicit export)
    elementwise_add,
    elementwise_sub,
    elementwise_mul,
    elementwise_div,
    elementwise_max,
    elementwise_min,
    elementwise_pow,
    elementwise_mod,
    elementwise_floordiv,
    equal,
    not_equal,
    less_than,
    less_equal,
    greater_than,
    greater_equal,
    relu,
    sigmoid,
    tanh,
    sqrt,
    square,
    exp,
    log,
    gelu,
)
from .learning_rate_scheduler import (  # noqa: F401
    cosine_decay,
    exponential_decay,
    inverse_time_decay,
    linear_lr_warmup,
    natural_exp_decay,
    noam_decay,
    piecewise_decay,
    polynomial_decay,
)
from .control_flow import (  # noqa: F401
    StaticRNN,
    Switch,
    While,
    case,
    cond,
    increment,
    switch_case,
)
from . import distributions  # noqa: F401
from .tensor import assign_value, take_along_axis  # noqa: F401
from . import sequence_lod  # noqa: F401
from .sequence_lod import (  # noqa: F401
    sequence_concat,
    sequence_conv,
    sequence_expand,
    sequence_expand_as,
    sequence_first_step,
    sequence_last_step,
    sequence_mask,
    sequence_pad,
    sequence_pool,
    sequence_reverse,
    sequence_softmax,
    sequence_unpad,
)
from . import rnn  # noqa: F401
from .rnn import dynamic_gru, dynamic_lstm, gru, lstm  # noqa: F401
from .detection import (  # noqa: F401
    anchor_generator,
    box_clip,
    box_coder,
    density_prior_box,
    generate_proposals,
    iou_similarity,
    multiclass_nms,
    prior_box,
    roi_align,
    roi_pool,
    sigmoid_focal_loss,
    yolo_box,
    yolov3_loss,
)
from .detection import (  # noqa: F401
    bipartite_match,
    box_decoder_and_assign,
    collect_fpn_proposals,
    distribute_fpn_proposals,
    generate_mask_labels,
    generate_proposal_labels,
    mine_hard_examples,
    retinanet_detection_output,
    retinanet_target_assign,
    rpn_target_assign,
    target_assign,
)
from .functional_ext import *  # noqa: F401,F403
from .control_flow import (  # noqa: F401
    array_length,
    array_read,
    array_write,
    create_array,
)
from .ssd import multi_box_head, ssd_loss  # noqa: F401
