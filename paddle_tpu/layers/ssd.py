"""SSD composite heads (reference fluid/layers/detection.py
multi_box_head :1832 and ssd_loss :1230): compositions over prior_box /
bipartite_match / target_assign / mine_hard_examples and the conv layers.
Dense re-design: gt inputs are padded [N, G, 4]/-1 and every stage keeps
fixed shapes (the matching/mining emitters are ops/detection_ext.py)."""

from __future__ import annotations

from . import tensor as t
from .nn import conv2d as _conv2d
from .nn import softmax_with_cross_entropy as _softmax_ce
from .detection import (
    bipartite_match,
    iou_similarity,
    mine_hard_examples,
    prior_box,
    target_assign,
)


def _encode_per_prior(prior, prior_var, matched):
    """Elementwise center-size encode of each prior's MATCHED gt box
    (bbox_util.h BoxToDelta semantics; the pairwise box_coder op encodes
    every (gt, prior) pair, which is not what the loc loss wants)."""
    def col(v, i):
        return t.slice(v, axes=[1], starts=[i], ends=[i + 1])

    pw = col(prior, 2) - col(prior, 0)
    ph = col(prior, 3) - col(prior, 1)
    pcx = col(prior, 0) + 0.5 * pw
    pcy = col(prior, 1) + 0.5 * ph
    gw = col(matched, 2) - col(matched, 0)
    gh = col(matched, 3) - col(matched, 1)
    gcx = col(matched, 0) + 0.5 * gw
    gcy = col(matched, 1) + 0.5 * gh
    eps = 1e-6
    enc = t.concat([
        (gcx - pcx) / (pw + eps),
        (gcy - pcy) / (ph + eps),
        t.log(t.elementwise_max(
            gw / (pw + eps), t.fill_constant([1], "float32", eps))),
        t.log(t.elementwise_max(
            gh / (ph + eps), t.fill_constant([1], "float32", eps))),
    ], axis=1)
    if prior_var is not None:
        enc = enc / prior_var
    return enc


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=None, flip=True, clip=False,
                   kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """Per-feature-map loc/conf convs + priors, concatenated (reference
    layers/detection.py:2110 multi_box_head — full keyword surface:
    per-map steps/step_w/step_h, prior variances, loc/conf conv
    kernel/pad/stride). min_max_aspect_ratios_order is accepted for
    signature parity; prior ordering here is the emitter's fixed
    (min, ratios, max) order either way. Returns (mbox_locs, mbox_confs,
    boxes, variances)."""
    n_maps = len(inputs)
    if min_sizes is None:
        min_ratio, max_ratio = min_ratio or 20, max_ratio or 90
        step = int((max_ratio - min_ratio) / max(n_maps - 2, 1))
        min_sizes, max_sizes = [], []
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.10] + min_sizes[:n_maps - 1]
        max_sizes = [base_size * 0.20] + max_sizes[:n_maps - 1]

    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, x in enumerate(inputs):
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i], (list, tuple)) \
            else [aspect_ratios[i]]
        mins = min_sizes[i] if isinstance(min_sizes[i], (list, tuple)) \
            else [min_sizes[i]]
        maxs = max_sizes[i] if isinstance(max_sizes[i], (list, tuple)) \
            else [max_sizes[i]]
        if steps is not None:
            sw = sh = steps[i]
        else:
            sw = step_w[i] if step_w else 0.0
            sh = step_h[i] if step_h else 0.0
        boxes, variances = prior_box(
            x, image, mins, maxs, ar, variance=variance, flip=flip,
            clip=clip, steps=[float(sw), float(sh)], offset=offset,
        )
        a = boxes.shape[2] if len(boxes.shape) == 4 else 1
        num_priors = 1
        for d in boxes.shape[:-1]:
            num_priors *= d
        loc = _conv2d(x, a * 4, kernel_size, padding=pad, stride=stride)
        conf = _conv2d(x, a * num_classes, kernel_size, padding=pad,
                       stride=stride)
        n = x.shape[0]
        locs.append(t.reshape(t.transpose(loc, [0, 2, 3, 1]), [n, -1, 4]))
        confs.append(t.reshape(t.transpose(conf, [0, 2, 3, 1]),
                               [n, -1, num_classes]))
        boxes_all.append(t.reshape(boxes, [-1, 4]))
        vars_all.append(t.reshape(variances, [-1, 4]))
    return (
        t.concat(locs, axis=1),
        t.concat(confs, axis=1),
        t.concat(boxes_all, axis=0),
        t.concat(vars_all, axis=0),
    )


def ssd_loss(location, confidence, gt_box, gt_label, prior_boxes,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True, sample_size=None):
    """SSD training loss (reference ssd_loss): match priors to gts,
    assign targets, mine hard negatives, smooth-L1 loc + softmax conf.
    Single-image dense contract (batch handled by vmapped callers):
    location [1, P, 4], confidence [1, P, C], gt_box [G, 4],
    gt_label [G, 1]."""
    iou = iou_similarity(gt_box, prior_boxes)  # [G, P]
    match_idx, match_dist = bipartite_match(iou, match_type, neg_overlap)
    # conf loss per prior against assigned labels
    gt_lab3 = t.reshape(t.cast(gt_label, "float32"), [1, -1, 1])
    tgt_lab, tgt_lab_w = target_assign(
        gt_lab3, match_idx, mismatch_value=background_label)
    conf2 = t.reshape(confidence, [-1, confidence.shape[-1]])
    lab2 = t.reshape(t.cast(tgt_lab, "int64"), [-1, 1])
    conf_loss_all = _softmax_ce(conf2, lab2)  # [P, 1]
    conf_loss_row = t.reshape(conf_loss_all, [1, -1])
    neg_idx, updated = mine_hard_examples(
        conf_loss_row, match_idx, match_dist=match_dist,
        neg_pos_ratio=neg_pos_ratio, neg_dist_threshold=neg_overlap,
        sample_size=sample_size or 0, mining_type=mining_type,
    )
    pos_mask = t.cast(
        t.greater_equal(t.cast(match_idx, "float32"),
                        t.fill_constant([1], "float32", 0.0)),
        "float32",
    )  # [1, P]
    neg_mask = t.cast(neg_idx, "float32")
    conf_w = pos_mask + neg_mask
    conf_loss = t.reduce_sum(conf_loss_row * conf_w)
    # loc loss on matched priors
    gt_box3 = t.reshape(gt_box, [1, -1, 4])
    tgt_box, tgt_box_w = target_assign(gt_box3, match_idx, mismatch_value=0)
    enc = _encode_per_prior(
        prior_boxes, prior_box_var, t.reshape(tgt_box, [-1, 4])
    )
    loc2 = t.reshape(location, [-1, 4])
    diff = t.abs(loc2 - enc)
    l1 = t.where(
        t.less_than(diff, t.fill_constant([1], "float32", 1.0) + diff * 0.0),
        0.5 * diff * diff, diff - 0.5,
    )
    loc_loss = t.reduce_sum(
        t.reduce_sum(l1, dim=1) * t.reshape(pos_mask, [-1])
    )
    n_pos = t.elementwise_max(
        t.reduce_sum(pos_mask), t.fill_constant([1], "float32", 1.0))
    total = (conf_loss_weight * conf_loss + loc_loss_weight * loc_loss)
    if normalize:
        total = total / n_pos
    return total
