"""Functional wrappers over the round-3 op surface (reference
python/paddle/fluid/layers/nn.py long tail + python/paddle/nn/functional/
alias targets). Thin LayerHelper bindings: one public function per
emitter, plus a few pure compositions (activation variants, dice/npair
losses) where the reference's op is itself a composition."""

from __future__ import annotations

from .helper import LayerHelper
from .tensor import _simple
from . import tensor as _t


def _unary(op_type, x, attrs=None, out_slot="Out"):
    return _simple(op_type, {"X": [x]}, attrs or {}, out_slots=(out_slot,))


# -- activations -----------------------------------------------------------


def prelu(x, mode="all", param_attr=None, name=None):
    from ..initializer import Constant

    helper = LayerHelper("prelu", name=name)
    if mode == "all":
        shape = [1]
    elif mode == "channel":
        shape = [x.shape[1]]
    else:
        shape = list(x.shape[1:])
    alpha = helper.create_parameter(
        param_attr, shape, x.dtype, default_initializer=Constant(0.25)
    )
    return _simple("prelu", {"X": [x], "Alpha": [alpha]}, {"mode": mode})


def hard_shrink(x, threshold=0.5):
    return _unary("hard_shrink", x, {"threshold": threshold})


def softshrink(x, alpha=0.5):
    # x > a: x - a; x < -a: x + a; else 0 (reference softshrink_op)
    from . import tensor as t

    pos = t.relu(x - alpha)
    neg = t.relu((0.0 - x) - alpha)
    return pos - neg


def tanh_shrink(x):
    return _unary("tanh_shrink", x)


def thresholded_relu(x, threshold=1.0):
    return _unary("thresholded_relu", x, {"threshold": threshold})


def soft_relu(x, threshold=40.0):
    from . import tensor as t

    return t.log(1.0 + t.exp(t.clip(x, -threshold, threshold)))


def brelu(x, t_min=0.0, t_max=24.0):
    from . import tensor as t

    return t.clip(x, t_min, t_max)


def stanh(x, scale_a=0.67, scale_b=1.7159):
    from . import tensor as t

    return scale_b * t.tanh(x * scale_a)


def maxout(x, groups, axis=1):
    return _simple("maxout", {"X": [x]}, {"groups": groups, "axis": axis})


def erf(x):
    return _unary("erf", x)


# -- norm / conv / pool ----------------------------------------------------


def data_norm(input, name=None, epsilon=1e-5, param_attr=None,
              batch_size_default=1e4, batch_sum_default=0.0,
              batch_square_sum_default=1e4, slot_dim=-1):
    from ..initializer import Constant

    helper = LayerHelper("data_norm", name=name)
    c = input.shape[-1]
    bsz = helper.create_parameter(
        None, [c], "float32",
        default_initializer=Constant(batch_size_default))
    bsum = helper.create_parameter(
        None, [c], "float32",
        default_initializer=Constant(batch_sum_default))
    bsq = helper.create_parameter(
        None, [c], "float32",
        default_initializer=Constant(batch_square_sum_default))
    out, _, _ = _simple(
        "data_norm",
        {"X": [input], "BatchSize": [bsz], "BatchSum": [bsum],
         "BatchSquareSum": [bsq]},
        {"epsilon": epsilon, "slot_dim": slot_dim},
        out_slots=("Y", "Means", "Scales"),
    )
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    from ..initializer import Normal

    helper = LayerHelper("spectral_norm", name=name)
    h = weight.shape[dim]
    import numpy as np

    w = int(np.prod(weight.shape)) // h
    u = helper.create_parameter(
        None, [h], "float32", default_initializer=Normal(0.0, 1.0))
    v = helper.create_parameter(
        None, [w], "float32", default_initializer=Normal(0.0, 1.0))
    u.stop_gradient = True
    v.stop_gradient = True
    return _simple(
        "spectral_norm", {"Weight": [weight], "U": [u], "V": [v]},
        {"dim": dim, "power_iters": power_iters, "eps": eps},
    )


def row_conv(input, future_context_size, param_attr=None, act=None):
    from ..initializer import Xavier

    helper = LayerHelper("row_conv")
    d = input.shape[-1]
    filt = helper.create_parameter(
        param_attr, [future_context_size + 1, d], input.dtype,
        default_initializer=Xavier(),
    )
    out = _simple("row_conv", {"X": [input], "Filter": [filt]}, {})
    if act:
        out = _unary(act, out)
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None):
    from ..initializer import Xavier

    helper = LayerHelper("conv3d", name=name)
    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size] * 3
    w = helper.create_parameter(
        param_attr,
        [num_filters, input.shape[1] // (groups or 1), *k],
        input.dtype, default_initializer=Xavier(),
    )
    out = _simple(
        "conv3d", {"Input": [input], "Filter": [w]},
        {"strides": stride if isinstance(stride, list) else [stride] * 3,
         "paddings": padding if isinstance(padding, list) else [padding] * 3,
         "dilations": dilation if isinstance(dilation, list)
         else [dilation] * 3,
         "groups": groups or 1},
        out_slots=("Output",),
    )
    if bias_attr is not False:
        b = helper.create_parameter(
            bias_attr, [num_filters], input.dtype, is_bias=True)
        out = _simple(
            "elementwise_add", {"X": [out], "Y": [b]}, {"axis": 1})
    if act:
        out = _unary(act, out)
    return out


def conv3d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     groups=1, param_attr=None, bias_attr=None, act=None):
    from ..initializer import Xavier

    helper = LayerHelper("conv3d_transpose")
    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size] * 3
    g = groups or 1
    w = helper.create_parameter(
        param_attr, [input.shape[1], num_filters // g, *k], input.dtype,
        default_initializer=Xavier(),
    )
    out = _simple(
        "conv3d_transpose", {"Input": [input], "Filter": [w]},
        {"strides": stride if isinstance(stride, list) else [stride] * 3,
         "paddings": padding if isinstance(padding, list) else [padding] * 3,
         "groups": g},
        out_slots=("Output",),
    )
    if act:
        out = _unary(act, out)
    return out


def pool3d(input, pool_size=2, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, name=None):
    return _simple(
        "pool3d", {"X": [input]},
        {"ksize": pool_size if isinstance(pool_size, list)
         else [pool_size] * 3,
         "pooling_type": pool_type,
         "strides": pool_stride if isinstance(pool_stride, list)
         else [pool_stride] * 3,
         "paddings": pool_padding if isinstance(pool_padding, list)
         else [pool_padding] * 3,
         "global_pooling": global_pooling},
    )


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def _pair(v):
        return v if isinstance(v, (list, tuple)) else [v, v]

    p = _pair(paddings)
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]
    return _simple(
        "unfold", {"X": [x]},
        {"kernel_sizes": _pair(kernel_sizes), "strides": _pair(strides),
         "paddings": p, "dilations": _pair(dilations)},
        out_slots=("Y",),
    )


def affine_grid(theta, out_shape, align_corners=True, name=None):
    return _simple(
        "affine_grid", {"Theta": [theta], "OutputShape": [None]},
        {"output_shape": list(out_shape), "align_corners": align_corners},
        out_slots=("Output",),
    )


def grid_sampler(x, grid, name=None):
    return _simple("grid_sampler", {"X": [x], "Grid": [grid]}, {})


def interpolate(input, out_shape=None, scale=None, resample="BILINEAR",
                align_corners=True, name=None):
    op = {"BILINEAR": "bilinear_interp", "NEAREST": "nearest_interp",
          "TRILINEAR": "trilinear_interp", "BICUBIC": "bicubic_interp",
          "LINEAR": "linear_interp"}[resample.upper()]
    attrs = {"align_corners": align_corners}
    if out_shape is not None:
        names = (["out_w"] if len(out_shape) == 1 else
                 ["out_h", "out_w"] if len(out_shape) == 2 else
                 ["out_d", "out_h", "out_w"])
        attrs.update(dict(zip(names, out_shape)))
    if scale is not None:
        attrs["scale"] = float(scale)
    return _simple(op, {"X": [input]}, attrs)


image_resize = interpolate


def resize_trilinear(input, out_shape=None, scale=None, align_corners=True,
                     name=None):
    return interpolate(input, out_shape, scale, "TRILINEAR", align_corners)


def resize_bicubic(input, out_shape=None, scale=None, align_corners=True,
                   name=None):
    return interpolate(input, out_shape, scale, "BICUBIC", align_corners)


def pixel_shuffle(x, upscale_factor):
    return _simple("pixel_shuffle", {"X": [x]},
                   {"upscale_factor": upscale_factor})


def space_to_depth(x, blocksize, name=None):
    return _simple("space_to_depth", {"X": [x]}, {"blocksize": blocksize})


def shuffle_channel(x, group, name=None):
    return _simple("shuffle_channel", {"X": [x]}, {"group": group})


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    return _simple("temporal_shift", {"X": [x]},
                   {"seg_num": seg_num, "shift_ratio": shift_ratio})


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    return _simple("lrn", {"X": [input]},
                   {"n": n, "k": k, "alpha": alpha, "beta": beta})


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None):
    return _simple(
        "affine_channel", {"X": [x], "Scale": [scale], "Bias": [bias]},
        {"data_layout": data_layout},
    )


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    return _simple("add_position_encoding", {"X": [input]},
                   {"alpha": alpha, "beta": beta})


def bilinear_tensor_product(x, y, size, param_attr=None, bias_attr=None,
                            act=None, name=None):
    from ..initializer import Xavier

    helper = LayerHelper("bilinear_tensor_product", name=name)
    w = helper.create_parameter(
        param_attr, [size, x.shape[1], y.shape[1]], x.dtype,
        default_initializer=Xavier(),
    )
    out = _simple(
        "bilinear_tensor_product",
        {"X": [x], "Y": [y], "Weight": [w], "Bias": [None]}, {},
    )
    if act:
        out = _unary(act, out)
    return out


# -- losses ----------------------------------------------------------------


def mse_loss(input, label):
    from . import tensor as t

    return t.reduce_mean(t.square(input - label))


def l2_normalize(x, axis=-1, epsilon=1e-12, name=None):
    from . import tensor as t

    sq = t.reduce_sum(t.square(x), dim=axis, keep_dim=True)
    return x / t.sqrt(t.elementwise_max(
        sq, t.fill_constant([1], "float32", epsilon)))


def dice_loss(input, label, epsilon=1e-5):
    from . import tensor as t

    label_f = t.cast(label, input.dtype)
    inter = t.reduce_sum(input * label_f)
    union = t.reduce_sum(input) + t.reduce_sum(label_f)
    return 1.0 - (2.0 * inter + epsilon) / (union + epsilon)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    from . import tensor as t

    sim = t.matmul(anchor, positive, transpose_y=True)  # [B, B]
    b = anchor.shape[0]
    tgt = t.reshape(labels, [b, 1])
    eq = t.cast(t.equal(tgt, t.transpose(tgt, [1, 0])), "float32")
    tgt_dist = eq / t.reduce_sum(eq, dim=1, keep_dim=True)
    ce = t.reduce_mean(
        t.reduce_sum((0.0 - tgt_dist) * t.log_softmax(sim), dim=1)
    )
    reg = l2_reg * (t.reduce_mean(t.reduce_sum(t.square(anchor), dim=1))
                    + t.reduce_mean(t.reduce_sum(t.square(positive), dim=1)))
    return ce + reg


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    return _simple("label_smooth", {"X": [label], "PriorDist": [prior_dist]},
                   {"epsilon": epsilon})


def kldiv_loss(x, target, reduction="mean", name=None):
    return _simple("kldiv_loss", {"X": [x], "Target": [target]},
                   {"reduction": reduction}, out_slots=("Loss",))


def huber_loss(input, label, delta):
    return _simple("huber_loss", {"X": [input], "Y": [label]},
                   {"delta": delta}, out_slots=("Out", "Residual"))[0]


def log_loss(input, label, epsilon=1e-4, name=None):
    return _simple("log_loss", {"Predicted": [input], "Labels": [label]},
                   {"epsilon": epsilon}, out_slots=("Loss",))


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    return _simple(
        "smooth_l1_loss", {"X": [x], "Y": [y]},
        {"sigma": sigma or 1.0}, out_slots=("Out", "Diff"),
    )[0]


def rank_loss(label, left, right, name=None):
    return _simple("rank_loss",
                   {"Label": [label], "Left": [left], "Right": [right]}, {})


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    return _simple(
        "margin_rank_loss",
        {"Label": [label], "X1": [left], "X2": [right]},
        {"margin": margin},
    )


def bpr_loss(input, label, name=None):
    return _simple("bpr_loss", {"X": [input], "Label": [label]},
                   out_slots=("Y",), attrs={})


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    from ..initializer import Normal

    helper = LayerHelper("center_loss")
    centers = helper.create_parameter(
        param_attr, [num_classes, input.shape[-1]], input.dtype,
        default_initializer=Normal(0.0, 1.0),
    )
    rate = _t.fill_constant([1], "float32", alpha)
    loss, _, _ = _simple(
        "center_loss",
        {"X": [input], "Label": [label], "Centers": [centers],
         "CenterUpdateRate": [rate]},
        {"need_update": update_center},
        out_slots=("Loss", "SampleCenterDiff", "CentersOut"),
    )
    return loss


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    return _simple("teacher_student_sigmoid_loss",
                   {"X": [input], "Label": [label]}, {}, out_slots=("Y",))


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    from ..initializer import Xavier

    helper = LayerHelper("nce", name=name)
    d = input.shape[-1]
    w = helper.create_parameter(
        param_attr, [num_total_classes, d], input.dtype,
        default_initializer=Xavier(),
    )
    b = helper.create_parameter(
        bias_attr, [num_total_classes], input.dtype, is_bias=True)
    cost, _, _ = _simple(
        "nce",
        {"Input": [input], "Label": [label], "Weight": [w], "Bias": [b],
         "SampleWeight": [sample_weight]},
        {"num_total_classes": num_total_classes,
         "num_neg_samples": num_neg_samples,
         "sampler": {"uniform": 0, "log_uniform": 1}.get(sampler, 0),
         "seed": seed},
        out_slots=("Cost", "SampleLogits", "SampleLabels"),
    )
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    from ..initializer import Xavier

    helper = LayerHelper("hsigmoid", name=name)
    d = input.shape[-1]
    w = helper.create_parameter(
        param_attr, [num_classes - 1, d], input.dtype,
        default_initializer=Xavier(),
    )
    b = helper.create_parameter(
        bias_attr, [num_classes - 1], input.dtype, is_bias=True)
    out, _ = _simple(
        "hierarchical_sigmoid",
        {"X": [input], "Label": [label], "W": [w], "Bias": [b],
         "PathTable": [path_table], "PathCode": [path_code]},
        {"num_classes": num_classes},
        out_slots=("Out", "PreOut"),
    )
    return out


def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None):
    loss, _ = _simple(
        "warpctc",
        {"Logits": [input], "Label": [label],
         "LogitsLength": [input_length], "LabelLength": [label_length]},
        {"blank": blank, "norm_by_times": norm_by_times},
        out_slots=("Loss", "WarpCTCGrad"),
    )
    return loss


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    return _simple(
        "edit_distance",
        {"Hyps": [input], "Refs": [label],
         "HypsLength": [input_length], "RefsLength": [label_length]},
        {"normalized": normalized},
        out_slots=("Out", "SequenceNum"),
    )


def sampled_softmax_with_cross_entropy(logits, label, num_samples, seed=0):
    from . import tensor as t

    _, _, sampled_logits, sampled_label = _simple(
        "sample_logits", {"Logits": [logits], "Labels": [label]},
        {"num_samples": num_samples, "seed": seed},
        out_slots=("Samples", "Probabilities", "SampledLogits",
                   "SampledLabel"),
    )
    from .nn import softmax_with_cross_entropy

    return softmax_with_cross_entropy(
        sampled_logits, t.cast(sampled_label, "int64")
    )


# -- misc ------------------------------------------------------------------


def hash(input, hash_size, num_hash=1, name=None):
    return _simple("hash", {"X": [input]},
                   {"mod_by": hash_size, "num_hash": num_hash})


def random_crop(x, shape, seed=0):
    out, _ = _simple(
        "random_crop", {"X": [x], "Seed": [None]},
        {"shape": list(shape), "seed": seed},
        out_slots=("Out", "SeedOut"),
    )
    return out


def similarity_focus(input, axis, indexes, name=None):
    return _simple("similarity_focus", {"X": [input]},
                   {"axis": axis, "indexes": list(indexes)})


def polygon_box_transform(input, name=None):
    return _simple("polygon_box_transform", {"Input": [input]}, {},
                   out_slots=("Output",))


def fsp_matrix(x, y):
    return _simple("fsp", {"X": [x], "Y": [y]}, {})


def continuous_value_model(input, cvm, use_cvm=True):
    # reference cvm op: with use_cvm the [show, click] prefix passes
    # through (log-transformed upstream); without, it is stripped
    from . import tensor as t

    if use_cvm:
        return input
    return t.slice(input, axes=[1], starts=[2], ends=[input.shape[1]])


def linear(x, weight, bias=None, name=None):
    from . import tensor as t

    out = t.matmul(x, weight)
    if bias is not None:
        out = out + bias
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    return _simple("pad", {"X": [x]},
                   {"paddings": list(paddings), "pad_value": pad_value})


def pad_constant_like(x, y, pad_value=0.0, name=None):
    return _simple("pad_constant_like", {"X": [x], "Y": [y]},
                   {"pad_value": pad_value})


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, rois_num=None, name=None):
    return _simple(
        "psroi_pool",
        {"X": [input], "ROIs": [rois], "RoisNum": [rois_num]},
        {"output_channels": output_channels, "spatial_scale": spatial_scale,
         "pooled_height": pooled_height, "pooled_width": pooled_width},
    )


def prroi_pool(input, rois, spatial_scale=1.0, pooled_height=1,
               pooled_width=1, batch_roi_nums=None, name=None):
    return _simple(
        "prroi_pool",
        {"X": [input], "ROIs": [rois], "BatchRoINums": [batch_roi_nums]},
        {"spatial_scale": spatial_scale, "pooled_height": pooled_height,
         "pooled_width": pooled_width},
    )


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0):
    return _simple(
        "roi_perspective_transform", {"X": [input], "ROIs": [rois]},
        {"transformed_height": transformed_height,
         "transformed_width": transformed_width,
         "spatial_scale": spatial_scale},
        out_slots=("Out", "Mask", "TransformMatrix"),
    )


def deformable_conv(input, offset, mask, num_filters, filter_size,
                    stride=1, padding=0, dilation=1, groups=1,
                    deformable_groups=1, im2col_step=1, param_attr=None,
                    bias_attr=None, modulated=True, name=None):
    from ..initializer import Xavier

    def _pair(v):
        return v if isinstance(v, (list, tuple)) else [v, v]

    helper = LayerHelper("deformable_conv", name=name)
    k = _pair(filter_size)
    w = helper.create_parameter(
        param_attr, [num_filters, input.shape[1] // (groups or 1), *k],
        input.dtype, default_initializer=Xavier(),
    )
    attrs = {"strides": _pair(stride), "paddings": _pair(padding),
             "dilations": _pair(dilation), "groups": groups or 1,
             "deformable_groups": deformable_groups}
    if modulated:
        return _simple(
            "deformable_conv",
            {"Input": [input], "Offset": [offset], "Mask": [mask],
             "Filter": [w]},
            attrs, out_slots=("Output",),
        )
    return _simple(
        "deformable_conv_v1",
        {"Input": [input], "Offset": [offset], "Filter": [w]},
        attrs, out_slots=("Output",),
    )


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=(1, 1),
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1, position_sensitive=False,
                           name=None):
    out, _ = _simple(
        "deformable_psroi_pooling",
        {"Input": [input], "ROIs": [rois], "Trans": [trans]},
        {"no_trans": no_trans, "spatial_scale": spatial_scale,
         "output_dim": input.shape[1] // (pooled_height * pooled_width)
         if position_sensitive else input.shape[1],
         "pooled_height": pooled_height, "pooled_width": pooled_width,
         "trans_std": trans_std},
        out_slots=("Output", "TopCount"),
    )
    return out


# -- second batch: remaining 2.0 functional surface ------------------------


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    from ..initializer import Constant

    helper = LayerHelper("group_norm", name=name)
    c = input.shape[1]
    scale = helper.create_parameter(
        param_attr, [c], input.dtype, default_initializer=Constant(1.0))
    bias = helper.create_parameter(
        bias_attr, [c], input.dtype, is_bias=True)
    out = _simple(
        "group_norm", {"X": [input], "Scale": [scale], "Bias": [bias]},
        {"groups": groups, "epsilon": epsilon},
        out_slots=("Y",),
    )
    if act:
        out = _unary(act, out)
    return out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    from ..initializer import Constant

    helper = LayerHelper("instance_norm", name=name)
    c = input.shape[1]
    scale = helper.create_parameter(
        param_attr, [c], input.dtype, default_initializer=Constant(1.0))
    bias = helper.create_parameter(
        bias_attr, [c], input.dtype, is_bias=True)
    return _simple(
        "instance_norm", {"X": [input], "Scale": [scale], "Bias": [bias]},
        {"epsilon": epsilon}, out_slots=("Y",),
    )


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    return _simple(
        "pad2d", {"X": [input]},
        {"paddings": list(paddings), "mode": mode, "pad_value": pad_value},
    )


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    return _simple("diag_embed", {"Input": [input]},
                   {"offset": offset, "dim1": dim1, "dim2": dim2})


def merge_selected_rows(x, name=None):
    return _simple("merge_selected_rows", {"X": [x]}, {})


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True,
                     out_val_if_empty=0):
    return _simple(
        "filter_by_instag",
        {"Ins": [ins], "Ins_tag": [ins_tag], "Filter_tag": [filter_tag]},
        {"out_val_if_empty": out_val_if_empty},
        out_slots=("Out", "LossWeight", "IndexMap"),
    )


def tensor_array_to_tensor(input, axis=1, use_stack=False, name=None):
    return _simple(
        "tensor_array_to_tensor", {"X": [input]},
        {"axis": axis, "use_stack": use_stack},
        out_slots=("Out", "OutIndex"),
    )


def adaptive_pool3d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    return _simple(
        "pool3d", {"X": [input]},
        {"ksize": pool_size if isinstance(pool_size, (list, tuple))
         else [pool_size] * 3,
         "pooling_type": pool_type, "adaptive": True},
    )


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    h, w = input.shape[2], input.shape[3]
    short = min(h, w)
    ratio = out_short_len / float(short)
    return interpolate(
        input, out_shape=[int(round(h * ratio)), int(round(w * ratio))],
        resample=resample,
    )


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  name=None, sequence_length=None):
    """lstmp over padded [B, T, D] (reference dynamic_lstmp over LoD)."""
    from ..initializer import Xavier

    helper = LayerHelper("lstmp", name=name)
    hidden = size // 4
    d = input.shape[-1]
    wih = helper.create_parameter(
        None, [4 * hidden, d], "float32", default_initializer=Xavier())
    whh = helper.create_parameter(
        param_attr, [4 * hidden, proj_size], "float32",
        default_initializer=Xavier())
    wproj = helper.create_parameter(
        None, [hidden, proj_size], "float32",
        default_initializer=Xavier())
    bias = helper.create_parameter(
        bias_attr, [4 * hidden], "float32", is_bias=True)
    proj, out, _, _ = _simple(
        "lstmp",
        {"X": [input], "WIH": [wih], "WHH": [whh], "ProjWeight": [wproj],
         "Bias": [bias], "H0": [None], "C0": [None],
         "SeqLen": [sequence_length]},
        {},
        out_slots=("Projection", "Out", "LastH", "LastC"),
    )
    return proj, out


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid"):
    """Single GRU step (reference gru_unit_op): runs the gru emitter on a
    length-1 sequence."""
    from . import tensor as t

    x3 = t.reshape(input, [input.shape[0], 1, input.shape[-1]])
    out, last = _t_gru(x3, size // 3, hidden, param_attr, bias_attr)
    return last, last, last


def _t_gru(x, hidden_size, h0, param_attr, bias_attr):
    from .rnn import gru

    return gru(x, hidden_size, init_h=h0, param_attr=param_attr,
               bias_attr=bias_attr)


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """Single LSTM step (reference lstm_unit): fc over [x, h] + raw cell
    math through existing ops."""
    from . import tensor as t

    concat_in = t.concat([x_t, hidden_t_prev], axis=1)
    hidden = hidden_t_prev.shape[-1]
    from .nn import fc

    gates = fc(concat_in, 4 * hidden, param_attr=param_attr,
               bias_attr=bias_attr)
    i, f, c_hat, o = t.split(gates, num_or_sections=4, dim=-1)
    f = t.sigmoid(f + forget_bias)
    cell = f * cell_t_prev + t.sigmoid(i) * t.tanh(c_hat)
    hidden_out = t.sigmoid(o) * t.tanh(cell)
    return hidden_out, cell


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """SSD decode + NMS composition (reference detection_output: box_coder
    decode_center_size then multiclass_nms)."""
    from . import tensor as t
    from .detection import box_coder, multiclass_nms

    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    scores_t = t.transpose(scores, [0, 2, 1])  # [N, C, M]
    out, _ = multiclass_nms(
        decoded if decoded.shape and len(decoded.shape) == 3
        else t.reshape(decoded, [1, *decoded.shape]),
        scores_t,
        score_threshold=score_threshold, nms_top_k=nms_top_k,
        keep_top_k=keep_top_k, nms_threshold=nms_threshold,
        background_label=background_label,
    )
    return out


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    # persistable int counter bumped in-graph by the increment emitter
    from ..framework.program import (
        default_main_program,
        default_startup_program,
    )

    blk = default_main_program().global_block
    name = counter_name or "@STEP_COUNTER@"
    if not blk.has_var(name):
        v = blk.create_parameter(name, [1], "int64", trainable=False)
        sb = default_startup_program().global_block
        if not sb.has_var(name):
            sb.create_parameter(name, [1], "int64", trainable=False)
            sb.append_op(
                "fill_constant", {}, {"Out": [name]},
                {"shape": [1], "dtype": "int64",
                 "value": float(begin - step)},
            )
    else:
        v = blk.var(name)
    blk.append_op("increment", {"X": [name]}, {"Out": [name]},
                  {"step": float(step)})
    return blk.var(name)
