"""LayerHelper: shared machinery for functional layers.

Reference parity: python/paddle/fluid/layer_helper.py — creates parameters in
both main and startup programs (init ops go to startup), creates inferred
output vars, appends the forward op to the main program.
"""

from __future__ import annotations

from ..framework import unique_name
from ..framework.program import (
    default_main_program,
    default_startup_program,
)
from ..framework.registry import infer_shapes
from ..initializer import Constant, Xavier
from ..param_attr import ParamAttr


def main_block():
    return default_main_program().current_block()


def startup_block():
    return default_startup_program().global_block


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.layer_type = layer_type
        self.kwargs = kwargs

    @property
    def name(self):
        n = self.kwargs.get("name")
        return n or unique_name.generate(self.layer_type)

    # -- parameters --------------------------------------------------------
    def create_parameter(
        self, attr, shape, dtype, is_bias=False, default_initializer=None
    ):
        attr = ParamAttr.to_attr(attr)
        if attr is False:
            return None
        init = attr.initializer or default_initializer or (
            Constant(0.0) if is_bias else Xavier()
        )
        name = attr.name or unique_name.generate(
            f"{self.layer_type}_{'b' if is_bias else 'w'}"
        )
        # parameters ALWAYS live in the global block, even when the layer is
        # built inside a control-flow sub-block (fluid layer_helper_base
        # create_parameter does the same) — so the executor state analysis
        # sees them and sub-blocks capture them as external reads
        mb, sb = default_main_program().global_block, startup_block()
        existing = mb.vars.get(name)
        if existing is not None:
            # weight sharing by name (e.g. crf_decoding reusing
            # linear_chain_crf's transition): re-creating would silently
            # drop the first declaration's regularizer/lr/trainable attrs
            if tuple(existing.shape or ()) != tuple(shape):
                from ..errors import InvalidArgumentError

                raise InvalidArgumentError(
                    f"parameter {name!r} reused with shape {shape}, but it "
                    f"was created with shape {existing.shape}"
                )
            return existing
        p = mb.create_parameter(
            name, shape, dtype, trainable=attr.trainable
        )
        p.regularizer = attr.regularizer
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        if not sb.has_var(name):
            sb.create_parameter(name, shape, dtype, trainable=attr.trainable)
            init(sb, name, shape, dtype)
        return p

    # -- outputs -----------------------------------------------------------
    def append_op(self, op_type=None, inputs=None, outputs=None, attrs=None):
        return main_block().append_op(
            op_type or self.layer_type, inputs, outputs, attrs
        )

    def create_and_append(
        self, inputs, attrs, op_type=None, out_slots=("Out",), stop_gradient=False
    ):
        """Append an op, creating one output var per slot with inferred
        shape/dtype. inputs: {slot: [Variable]}. Returns var or tuple.

        In dygraph mode the op executes eagerly through the tracer instead
        (reference parity: fluid.layers.* are usable under dygraph.guard via
        the in_dygraph_mode fast path in each layer fn, framework.py:180)."""
        op_type = op_type or self.layer_type
        from ..framework.program import _current_tracer

        tracer = _current_tracer()
        if tracer is not None:
            outs = tracer.trace_op(op_type, inputs, attrs or {})
            vals = [
                (vs[0] if len(vs) == 1 else vs)
                for slot, vs in ((s, outs.get(s, [])) for s in out_slots)
            ]
            return vals[0] if len(vals) == 1 else tuple(vals)
        blk = main_block()
        in_names = {
            slot: [v.name if v is not None else "" for v in vs]
            for slot, vs in inputs.items()
        }
        specs = infer_shapes(op_type, blk, in_names, attrs or {})
        outs = []
        out_names = {}
        for slot in out_slots:
            slot_specs = specs.get(slot, [])
            names, vars_ = [], []
            for shape, dtype in slot_specs:
                v = blk.create_var(
                    name=unique_name.generate(f"{op_type}.{slot.lower()}"),
                    shape=shape,
                    dtype=dtype,
                    stop_gradient=stop_gradient,
                )
                names.append(v.name)
                vars_.append(v)
            out_names[slot] = names
            outs.append(vars_[0] if len(vars_) == 1 else vars_)
        blk.append_op(op_type, in_names, out_names, attrs or {})
        return outs[0] if len(outs) == 1 else tuple(outs)
