"""Control-flow layer API (reference python/paddle/fluid/layers/
control_flow.py, 3,820 LoC: While :1038, cond :2334, case :2860,
switch_case :3082, StaticRNN :414, Switch :3235, increment :1497,
array_write/array_read :1560/:1682).

Builds sub-block Programs consumed by the control-flow emitters in
ops/control_flow.py (lax.cond / lax.while_loop / lax.scan lowering).
"""

from __future__ import annotations

import contextlib

from ..framework import unique_name
from ..framework.program import Variable, default_main_program
from . import tensor


def _external_reads(block, produced_extra=()):
    """Names read by block ops, resolved in an ancestor block (captures)."""
    produced = set(produced_extra)
    reads = []
    for op in block.ops:
        for n in op.input_names():
            if n and n not in produced and n not in reads:
                if n not in block.vars:  # resolved in a parent block
                    reads.append(n)
        for n in op.output_names():
            if n:
                produced.add(n)
    return reads


def _written_outer(block):
    """Names written by block ops that pre-exist OUTSIDE the sub-block
    (fluid in-place write-back semantics)."""
    out = []
    for op in block.ops:
        for n in op.output_names():
            if n and n not in block.vars and n not in out:
                out.append(n)
    return out


def increment(x, value=1.0, in_place=True):
    """reference control_flow.py increment :1497. Appends to the CURRENT
    block (x may live in an ancestor — inside a While body the op must land
    in the sub-block)."""
    blk = default_main_program().current_block()
    if in_place:
        blk.append_op(
            "increment", {"X": [x.name]}, {"Out": [x.name]}, {"step": value}
        )
        return x
    out = blk.create_var(
        name=unique_name.generate("increment"), shape=x.shape, dtype=x.dtype
    )
    blk.append_op(
        "increment", {"X": [x.name]}, {"Out": [out.name]}, {"step": value}
    )
    return out


class While:
    """fluid.layers.While parity (control_flow.py:1038).

        i = fill_constant([1], "int32", 0)
        n = fill_constant([1], "int32", 10)
        cond = less_than(i, n)
        w = While(cond)
        with w.block():
            ... ops writing loop vars ...
            increment(i)
            assign(less_than(i, n), cond)   # body must refresh cond

    Lowered to one `while` op running lax.while_loop (ops/control_flow.py).

    Differentiability (reference while_grad parity, while_op.cc +
    backward.py:843): pass `max_iters=N` to lower onto `bounded_while`
    (lax.scan over N masked steps) — training loops through the While then
    backprop, with semantics identical to the unbounded form whenever the
    true trip count stays <= N. Without max_iters the loop keeps the
    data-dependent lax.while_loop and is non-differentiable (use StaticRNN
    or max_iters for trainable loops).
    """

    def __init__(self, cond, is_test=False, name=None, max_iters=None):
        if not isinstance(cond, Variable):
            raise TypeError("While cond must be a bool Variable")
        self.cond_var = cond
        self.max_iters = max_iters
        self._prog = default_main_program()

    @contextlib.contextmanager
    def block(self):
        parent = self._prog.current_block()
        sub = self._prog.create_block()
        try:
            yield
        finally:
            self._prog.rollback()
        written = _written_outer(sub)
        if self.cond_var.name not in written:
            raise ValueError(
                "While body never writes the condition variable "
                f"{self.cond_var.name!r}; the loop would not terminate. "
                "Refresh it, e.g. layers.assign(new_cond, cond)."
            )
        carry = [n for n in written if n != self.cond_var.name]
        # captures that are only read still ride the carry unchanged
        for n in _external_reads(sub):
            if n not in carry and n != self.cond_var.name:
                carry.append(n)
        attrs = {
            "sub_block": sub.idx,
            "carry_names": list(carry),
            "cond_name": self.cond_var.name,
        }
        op_type = "while"
        in_names = list(carry)
        if self.max_iters is not None:
            op_type = "bounded_while"
            attrs["max_iters"] = int(self.max_iters)
            # the loop is differentiable: float carries the body WRITES
            # (the accumulators) must participate in backward even when
            # their initial value came from a stop_gradient producer
            # (fill_constant zeros is the idiomatic accumulator init —
            # reference while_grad treats loop outputs the same way).
            # Read-only captures keep their flags: flipping a feed var
            # would drag data gradients into every backward pass.
            for nm in written:
                if nm == self.cond_var.name:
                    continue
                v = parent._find_var_recursive(nm)
                if v is not None and str(v.dtype).startswith("float"):
                    v.stop_gradient = False
            # fluid While rebinds its outputs onto the SAME names (in-place
            # semantics) — the generic __vjp__ replays the forward later,
            # when those names hold post-loop values. Snapshot every
            # written carry so the op's inputs survive the rebinding (the
            # reference while_grad equally replays against the per-step
            # scope stack, not the mutated vars — backward.py:843).
            written_set = set(written)
            in_names = []
            for nm in carry:
                if nm in written_set:
                    v = parent._find_var_recursive(nm)
                    snap = parent.create_var(
                        name=unique_name.generate(nm + ".loop_in"),
                        shape=v.shape, dtype=v.dtype,
                    )
                    snap.stop_gradient = v.stop_gradient
                    parent.append_op(
                        "assign", {"X": [nm]}, {"Out": [snap.name]}, {}
                    )
                    in_names.append(snap.name)
                else:
                    in_names.append(nm)
        parent.append_op(
            op_type,
            {"Condition": [self.cond_var.name], "X": list(in_names)},
            {"Out": list(carry)},
            attrs,
        )


def cond(pred, true_fn=None, false_fn=None, name=None):
    """fluid.layers.cond parity (control_flow.py:2334): functional two-branch
    conditional; both branches must return matching Variables."""
    prog = default_main_program()
    parent = prog.current_block()

    def build(fn):
        sub = prog.create_block()
        try:
            out = fn() if fn is not None else None
        finally:
            prog.rollback()
        outs = (
            list(out) if isinstance(out, (list, tuple))
            else ([] if out is None else [out])
        )
        for v in outs:
            if not isinstance(v, Variable):
                raise TypeError("branch functions must return Variables")
        return sub, outs

    t_blk, t_outs = build(true_fn)
    f_blk, f_outs = build(false_fn)
    for side, blk in (("true_fn", t_blk), ("false_fn", f_blk)):
        written = _written_outer(blk)
        if written:
            raise ValueError(
                f"cond() {side} writes outer variables {written}: branches "
                "are functional (lax.cond) — return new values instead of "
                "assigning to outer vars (use layers.Switch for "
                "assignment-style branching)"
            )
    if len(t_outs) != len(f_outs):
        raise ValueError(
            f"true_fn returns {len(t_outs)} values, false_fn {len(f_outs)}"
        )
    for a, b in zip(t_outs, f_outs):
        if (tuple(a.shape or ()) != tuple(b.shape or ())
                or a.dtype != b.dtype):
            raise ValueError(
                f"branch outputs mismatch: {a.name}:{a.shape}/{a.dtype} vs "
                f"{b.name}:{b.shape}/{b.dtype} (lax.cond requires identical "
                "shapes/dtypes)"
            )

    t_in = _external_reads(t_blk)
    f_in = _external_reads(f_blk)
    # a branch may return an outer var untouched (pass-through): it is not
    # read by any in-block op, so add it to the captures explicitly
    for in_list, blk, branch_outs in (
        (t_in, t_blk, t_outs), (f_in, f_blk, f_outs)
    ):
        produced = {n for op_ in blk.ops for n in op_.output_names()}
        for v in branch_outs:
            if v.name not in produced and v.name not in in_list:
                in_list.append(v.name)
    outs = [
        parent.create_var(
            name=unique_name.generate("cond_out"),
            shape=v.shape, dtype=v.dtype,
        )
        for v in t_outs
    ]
    parent.append_op(
        "cond",
        {"Cond": [pred.name], "TrueIn": t_in, "FalseIn": f_in},
        {"Out": [v.name for v in outs]},
        {
            "true_block": t_blk.idx,
            "false_block": f_blk.idx,
            "true_in_names": t_in,
            "false_in_names": f_in,
            "true_out_names": [v.name for v in t_outs],
            "false_out_names": [v.name for v in f_outs],
        },
    )
    if not outs:
        return None
    return outs[0] if len(outs) == 1 else outs


def case(pred_fn_pairs, default=None, name=None):
    """fluid.layers.case parity (:2860): first true pred wins."""
    if not pred_fn_pairs:
        raise ValueError("pred_fn_pairs must be non-empty")
    pred, fn = pred_fn_pairs[0]
    rest = pred_fn_pairs[1:]
    if rest:
        return cond(pred, fn, lambda: case(rest, default))
    if default is None:
        # fluid: last fn is the fallback when no default given
        return cond(pred, fn, fn)
    return cond(pred, fn, default)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """fluid.layers.switch_case parity (:3082)."""
    if isinstance(branch_fns, dict):
        pairs = sorted(branch_fns.items())
    else:
        pairs = list(enumerate(branch_fns))
    preds = [
        (tensor.equal(branch_index,
                      tensor.fill_constant([1], branch_index.dtype, float(i))),
         fn)
        for i, fn in pairs
    ]
    if default is None:
        default = pairs[-1][1]
    return case(preds, default)


class Switch:
    """fluid.layers.Switch parity (:3235) — imperative-style sugar that
    collects (cond, block) pairs and lowers to nested `cond` ops. Supported
    pattern: assignments to pre-created vars via layers.assign inside each
    case block."""

    def __init__(self, name=None):
        self._cases = []  # (pred_var_or_None, sub_block)
        self._prog = default_main_program()

    @contextlib.contextmanager
    def case(self, condition):
        sub = self._prog.create_block()
        try:
            yield
        finally:
            self._prog.rollback()
        self._cases.append((condition, sub))

    @contextlib.contextmanager
    def default(self):
        sub = self._prog.create_block()
        try:
            yield
        finally:
            self._prog.rollback()
        self._cases.append((None, sub))

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            return False
        parent = self._prog.current_block()
        # first-match-wins: a running "no case matched yet" flag gates each
        # case block (reference Switch semantics, control_flow.py:3235)
        unmatched = tensor.fill_constant([1], "bool", True)
        for pred, sub in self._cases:
            written = _written_outer(sub)
            reads = _external_reads(sub)
            # cond op: true branch = the case block, false branch = identity
            # over the written vars (empty block whose inputs pass through)
            f_blk = self._prog.create_block()
            self._prog.rollback()
            outs = [
                parent.create_var(
                    name=unique_name.generate("switch_out"),
                    shape=parent.var(n).shape,
                    dtype=parent.var(n).dtype,
                )
                for n in written
            ]
            if pred is None:  # default: fires iff nothing matched before
                eff = unmatched
            else:
                eff = tensor.logical_and(unmatched, pred)
                unmatched = tensor.logical_and(
                    unmatched, tensor.logical_not(pred)
                )
            parent.append_op(
                "cond",
                {"Cond": [eff.name], "TrueIn": reads, "FalseIn": written},
                {"Out": [v.name for v in outs]},
                {
                    "true_block": sub.idx,
                    "false_block": f_blk.idx,
                    "true_in_names": reads,
                    "false_in_names": written,
                    "true_out_names": written,
                    "false_out_names": written,
                },
            )
            for n, v in zip(written, outs):
                parent.append_op("assign", {"X": [v.name]}, {"Out": [n]}, {})
        return False


class StaticRNN:
    """fluid.layers.StaticRNN parity (control_flow.py:414): fixed-length
    recurrence over axis 0 of its step inputs, lowered to one differentiable
    `scan_block` op (lax.scan; BPTT via the generic vjp machinery).

        rnn = StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)        # x: [T, B, D] -> x_t: [B, D]
            h_prev = rnn.memory(init=h0)   # or shape/value form
            h = layers.fc(concat([x_t, h_prev]), D)
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        outs = rnn()                       # [T, B, D]
    """

    def __init__(self, name=None):
        self._prog = default_main_program()
        self._sub = None
        self._seq = []  # (outer_name, inblock_var)
        self._mems = []  # (init_outer_name, mem_var, update_name)
        self._outs = []  # in-block step output vars
        self._built = False

    @contextlib.contextmanager
    def step(self):
        self._sub = self._prog.create_block()
        try:
            yield
        except BaseException:
            self._prog.rollback()
            raise  # user error from the step body, not a build problem
        else:
            self._prog.rollback()
            self._build()

    def _require_in_step(self):
        if self._sub is None or self._prog.current_block() is not self._sub:
            raise RuntimeError("call inside `with rnn.step():`")

    def step_input(self, x):
        self._require_in_step()
        if x.shape is None or len(x.shape) < 1:
            raise ValueError("step_input needs a [T, ...] variable")
        v = self._sub.create_var(
            name=unique_name.generate(x.name + "@step"),
            shape=tuple(x.shape[1:]), dtype=x.dtype,
        )
        self._seq.append((x.name, v))
        return v

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=1, dtype="float32"):
        self._require_in_step()
        if init is None:
            if shape is None:
                raise ValueError("memory() needs init= or shape=")
            # the init constant must live OUTSIDE the loop body: emit its
            # fill op into the parent block (a proper initial carry); the
            # dtype must match what update_memory will carry (lax.scan
            # requires identical init/next dtypes)
            parent = self._prog.blocks[self._sub.parent_idx]
            name = unique_name.generate("rnn_mem_init")
            init = parent.create_var(
                name=name, shape=tuple(shape), dtype=dtype
            )
            parent.append_op(
                "fill_constant",
                {},
                {"Out": [name]},
                {"shape": list(shape), "dtype": dtype,
                 "value": float(init_value)},
            )
        v = self._sub.create_var(
            name=unique_name.generate("rnn_mem"),
            shape=init.shape, dtype=init.dtype,
        )
        self._mems.append([init.name, v, None])
        return v

    def update_memory(self, mem, value):
        self._require_in_step()
        for m in self._mems:
            if m[1] is mem:
                m[2] = value.name
                return
        raise ValueError("update_memory: unknown memory variable")

    def step_output(self, o):
        self._require_in_step()
        self._outs.append(o)

    output = step_output

    def _build(self):
        for m in self._mems:
            if m[2] is None:
                raise RuntimeError(
                    f"memory {m[1].name!r} was never update_memory()'d"
                )
        if not self._seq:
            raise ValueError("StaticRNN needs at least one step_input")
        parent = self._prog.current_block()
        sub = self._sub
        inblock_produced = (
            [v.name for _, v in self._seq] + [m[1].name for m in self._mems]
        )
        caps = _external_reads(sub, produced_extra=inblock_produced)
        t_dim = parent.var(self._seq[0][0]).shape[0]
        self._result = []
        out_vars = []
        for o in self._outs:
            ov = parent.create_var(
                name=unique_name.generate("rnn_out"),
                shape=(t_dim,) + tuple(o.shape or ()),
                dtype=o.dtype,
            )
            out_vars.append(ov)
        last_mems = [
            parent.create_var(
                name=unique_name.generate("rnn_last_mem"),
                shape=m[1].shape, dtype=m[1].dtype,
            )
            for m in self._mems
        ]
        parent.append_op(
            "scan_block",
            {
                "SeqIn": [n for n, _ in self._seq],
                "InitMem": [m[0] for m in self._mems],
                "Captured": list(caps),
            },
            {
                "Out": [v.name for v in out_vars],
                "LastMem": [v.name for v in last_mems],
            },
            {
                "sub_block": sub.idx,
                "seq_names": [v.name for _, v in self._seq],
                "mem_names": [m[1].name for m in self._mems],
                "mem_update_names": [m[2] for m in self._mems],
                "out_names": [o.name for o in self._outs],
                "cap_names": list(caps),
            },
        )
        self._result = out_vars
        self._last_mems = last_mems
        self._built = True

    def __call__(self):
        if not self._built:
            raise RuntimeError("StaticRNN block was never built")
        if len(self._result) == 1:
            return self._result[0]
        return self._result


# -- tensor arrays (reference control_flow.py array_write :1560,
# array_read :1682, create_array, array_length) over the fixed-capacity
# array ops (ops/control_flow.py write_to_array/read_from_array) ----------


def create_array(dtype="float32", capacity=32):
    """Returns an (empty) array Variable; the first array_write sizes it
    [capacity, ...]. The reference LoDTensorArray grows dynamically; the
    static contract takes an explicit capacity bound."""
    blk = default_main_program().current_block()
    v = blk.create_var(
        name=unique_name.generate("tensor_array"), shape=[0], dtype=dtype
    )
    v._array_capacity = capacity
    return v


def array_write(x, i, array=None, capacity=32):
    blk = default_main_program().current_block()
    if array is None:
        array = create_array(x.dtype, capacity)
    cap = getattr(array, "_array_capacity", capacity)
    out = blk.create_var(
        name=unique_name.generate("tensor_array"),
        shape=[cap] + list(x.shape), dtype=x.dtype,
    )
    out._array_capacity = cap
    first = tuple(array.shape or ()) in ((0,), ())
    blk.append_op(
        "write_to_array",
        {"X": [x.name], "I": [i.name],
         "Array": [] if first else [array.name]},
        {"Out": [out.name]},
        {"capacity": cap},
    )
    return out


def array_read(array, i):
    blk = default_main_program().current_block()
    out = blk.create_var(
        name=unique_name.generate("array_read"),
        shape=list(array.shape[1:]), dtype=array.dtype,
    )
    blk.append_op(
        "read_from_array", {"X": [array.name], "I": [i.name]},
        {"Out": [out.name]}, {},
    )
    return out


def array_length(array):
    """Static capacity of the array (the reference returns the dynamic
    length; the fixed-capacity contract makes it the bound)."""
    return tensor.fill_constant(
        [1], "int64", float(array.shape[0] if array.shape else 0)
    )
