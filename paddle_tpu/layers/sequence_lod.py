"""Sequence ops over padded batches + lengths.

Reference: operators/sequence_ops/ (~6.1k LoC) operate on LoD tensors —
ragged batches encoded as offset vectors (lod_tensor.h:52) with per-kernel
LoD walking. XLA needs static shapes, so the TPU-native design (SURVEY §7
hard-part #1) is: sequences live as dense [B, T, ...] padded tensors plus an
integer `lengths` vector, and every sequence op is a masked dense op — which
also vectorizes on the VPU instead of looping per sequence like the
reference kernels. The LoD boundary moves to the data pipeline edge.

API mapping (reference -> here):
  sequence_pool(LoD x)        -> sequence_pool(x, pool_type, lengths)
  sequence_softmax(LoD x)     -> sequence_softmax(x, lengths)
  sequence_reverse            -> sequence_reverse(x, lengths)
  sequence_last_step/first    -> sequence_last_step(x, lengths) / first
  sequence_expand             -> sequence_expand(x, ref_lengths)
  sequence_mask (same)        -> sequence_mask(lengths, maxlen)
"""

from __future__ import annotations

from . import tensor


def sequence_mask(x_len, maxlen=None, dtype="float32"):
    """[B] lengths -> [B, maxlen] mask (reference layers/nn.py
    sequence_mask)."""
    if maxlen is None:
        raise ValueError(
            "maxlen is required (static shapes: pass the padded T)"
        )
    r = tensor.reshape(tensor.range(0, maxlen, 1, "int64"), [1, maxlen])
    lens = tensor.reshape(tensor.cast(x_len, "int64"), [-1, 1])
    return tensor.cast(tensor.less_than(r, lens), dtype)


def _mask3(x, lengths):
    """[B, T, ...] mask broadcast to x's rank."""
    b, t = x.shape[0], x.shape[1]
    m = sequence_mask(lengths, t, dtype=x.dtype)  # [B, T]
    extra = len(x.shape) - 2
    if extra:
        m = tensor.reshape(m, [b, t] + [1] * extra)
    return m


def sequence_pool(input, pool_type, lengths, pad_value=0.0):
    """[B, T, D] + lengths -> [B, D] (reference sequence_pool_op.cc:
    sum / average / max / sqrt / last / first). Rows with length 0 emit
    pad_value (reference behavior for empty sequences)."""
    pool_type = pool_type.lower()
    b, t = input.shape[0], input.shape[1]
    m = _mask3(input, lengths)
    masked = tensor.elementwise_mul(input, m)

    def empty_to_pad(out):
        nonempty = tensor.reshape(
            tensor.cast(
                tensor.greater_than(
                    tensor.cast(lengths, "int64"),
                    tensor.fill_constant([1], "int64", 0),
                ),
                out.dtype,
            ),
            [b, 1],
        )
        return tensor.elementwise_add(
            tensor.elementwise_mul(out, nonempty, axis=0),
            (1.0 - nonempty) * float(pad_value),
        )
    if pool_type == "sum":
        return empty_to_pad(tensor.reduce_sum(masked, 1))
    if pool_type == "average":
        denom = tensor.reshape(
            tensor.elementwise_max(
                tensor.cast(lengths, input.dtype),
                tensor.fill_constant([1], input.dtype, 1.0),
            ),
            [b, 1],
        )
        return empty_to_pad(
            tensor.elementwise_div(tensor.reduce_sum(masked, 1), denom)
        )
    if pool_type == "sqrt":
        denom = tensor.reshape(
            tensor.sqrt(
                tensor.elementwise_max(
                    tensor.cast(lengths, input.dtype),
                    tensor.fill_constant([1], input.dtype, 1.0),
                )
            ),
            [b, 1],
        )
        return empty_to_pad(
            tensor.elementwise_div(tensor.reduce_sum(masked, 1), denom)
        )
    if pool_type == "max":
        neg = tensor.scale(
            tensor.fill_constant([1], input.dtype, 1.0), scale=-1e9
        )
        shifted = tensor.elementwise_add(
            masked, tensor.elementwise_mul(1.0 - m, neg)
        )
        return empty_to_pad(tensor.reduce_max(shifted, 1))
    if pool_type == "last":
        return empty_to_pad(sequence_last_step(input, lengths))
    if pool_type == "first":
        return empty_to_pad(sequence_first_step(input))
    raise ValueError(f"unknown pool_type {pool_type!r}")


def sequence_first_step(input, lengths=None):
    return tensor.squeeze(tensor.slice(input, [1], [0], [1]), [1])


def sequence_last_step(input, lengths):
    """Row b -> input[b, lengths[b]-1] via a one-hot contraction (gather
    with batch-dependent index, XLA-friendly)."""
    b, t = input.shape[0], input.shape[1]
    idx = tensor.cast(lengths, "int64") - tensor.fill_constant(
        [1], "int64", 1
    )
    onehot = tensor.cast(
        tensor.equal(
            tensor.reshape(tensor.range(0, t, 1, "int64"), [1, t]),
            tensor.reshape(idx, [b, 1]),
        ),
        input.dtype,
    )  # [B, T]
    extra = len(input.shape) - 2
    oh = tensor.reshape(onehot, [b, t] + [1] * extra)
    return tensor.reduce_sum(tensor.elementwise_mul(input, oh), 1)


def sequence_softmax(input, lengths):
    """Masked softmax over the T axis of [B, T] (reference
    sequence_softmax_op.cc normalizes within each sequence)."""
    m = sequence_mask(lengths, input.shape[1], dtype=input.dtype)
    neg = (1.0 - m) * -1e9
    return tensor.softmax(tensor.elementwise_add(input, neg), axis=-1)


def sequence_reverse(x, lengths):
    """Reverse the first lengths[b] steps of each row, keep padding in
    place (reference sequence_reverse_op.h)."""
    b, t = x.shape[0], x.shape[1]
    pos = tensor.reshape(tensor.range(0, t, 1, "int64"), [1, t])
    lens = tensor.reshape(tensor.cast(lengths, "int64"), [b, 1])
    # target index: len-1-pos inside the sequence, pos outside
    inside = tensor.cast(tensor.less_than(pos, lens), "int64")
    rev_idx = (lens - pos - tensor.fill_constant([1], "int64", 1)) * inside \
        + pos * (tensor.fill_constant([1], "int64", 1) - inside)
    extra_shape = list(x.shape[2:])
    idx = tensor.reshape(rev_idx, [b, t] + [1] * len(extra_shape))
    if extra_shape:
        idx = tensor.expand(idx, [1, 1] + extra_shape)
    return tensor.take_along_axis(x, idx, axis=1)


def sequence_expand(x, ref_lengths, maxlen):
    """[B, D] -> [B, maxlen, D] rows repeated up to ref_lengths then zero
    padded (dense analog of sequence_expand_op)."""
    b = x.shape[0]
    ex = tensor.expand(tensor.unsqueeze(x, [1]), [1, maxlen, 1])
    m = sequence_mask(ref_lengths, maxlen, dtype=x.dtype)
    return tensor.elementwise_mul(ex, tensor.reshape(m, [b, maxlen, 1]))


def sequence_concat(xs, axis=1):
    """Concatenate along the time axis (padded tensors)."""
    return tensor.concat(xs, axis=axis)
