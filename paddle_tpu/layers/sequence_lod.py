"""Sequence ops over padded batches + lengths.

Reference: operators/sequence_ops/ (~6.1k LoC) operate on LoD tensors —
ragged batches encoded as offset vectors (lod_tensor.h:52) with per-kernel
LoD walking. XLA needs static shapes, so the TPU-native design (SURVEY §7
hard-part #1) is: sequences live as dense [B, T, ...] padded tensors plus an
integer `lengths` vector, and every sequence op is a masked dense op — which
also vectorizes on the VPU instead of looping per sequence like the
reference kernels. The LoD boundary moves to the data pipeline edge.

API mapping (reference -> here):
  sequence_pool(LoD x)        -> sequence_pool(x, pool_type, lengths)
  sequence_softmax(LoD x)     -> sequence_softmax(x, lengths)
  sequence_reverse            -> sequence_reverse(x, lengths)
  sequence_last_step/first    -> sequence_last_step(x, lengths) / first
  sequence_expand             -> sequence_expand(x, ref_lengths)
  sequence_mask (same)        -> sequence_mask(lengths, maxlen)
"""

from __future__ import annotations

from . import tensor


def sequence_mask(x_len, maxlen=None, dtype="float32"):
    """[B] lengths -> [B, maxlen] mask (reference layers/nn.py
    sequence_mask)."""
    if maxlen is None:
        raise ValueError(
            "maxlen is required (static shapes: pass the padded T)"
        )
    r = tensor.reshape(tensor.range(0, maxlen, 1, "int64"), [1, maxlen])
    lens = tensor.reshape(tensor.cast(x_len, "int64"), [-1, 1])
    return tensor.cast(tensor.less_than(r, lens), dtype)


def _mask3(x, lengths):
    """[B, T, ...] mask broadcast to x's rank."""
    b, t = x.shape[0], x.shape[1]
    m = sequence_mask(lengths, t, dtype=x.dtype)  # [B, T]
    extra = len(x.shape) - 2
    if extra:
        m = tensor.reshape(m, [b, t] + [1] * extra)
    return m


def sequence_pool(input, pool_type, lengths, pad_value=0.0):
    """[B, T, D] + lengths -> [B, D] (reference sequence_pool_op.cc:
    sum / average / max / sqrt / last / first). Rows with length 0 emit
    pad_value (reference behavior for empty sequences)."""
    pool_type = pool_type.lower()
    b, t = input.shape[0], input.shape[1]
    m = _mask3(input, lengths)
    masked = tensor.elementwise_mul(input, m)

    def empty_to_pad(out):
        nonempty = tensor.reshape(
            tensor.cast(
                tensor.greater_than(
                    tensor.cast(lengths, "int64"),
                    tensor.fill_constant([1], "int64", 0),
                ),
                out.dtype,
            ),
            [b, 1],
        )
        return tensor.elementwise_add(
            tensor.elementwise_mul(out, nonempty, axis=0),
            (1.0 - nonempty) * float(pad_value),
        )
    if pool_type == "sum":
        return empty_to_pad(tensor.reduce_sum(masked, 1))
    if pool_type == "average":
        denom = tensor.reshape(
            tensor.elementwise_max(
                tensor.cast(lengths, input.dtype),
                tensor.fill_constant([1], input.dtype, 1.0),
            ),
            [b, 1],
        )
        return empty_to_pad(
            tensor.elementwise_div(tensor.reduce_sum(masked, 1), denom)
        )
    if pool_type == "sqrt":
        denom = tensor.reshape(
            tensor.sqrt(
                tensor.elementwise_max(
                    tensor.cast(lengths, input.dtype),
                    tensor.fill_constant([1], input.dtype, 1.0),
                )
            ),
            [b, 1],
        )
        return empty_to_pad(
            tensor.elementwise_div(tensor.reduce_sum(masked, 1), denom)
        )
    if pool_type == "max":
        neg = tensor.scale(
            tensor.fill_constant([1], input.dtype, 1.0), scale=-1e9
        )
        shifted = tensor.elementwise_add(
            masked, tensor.elementwise_mul(1.0 - m, neg)
        )
        return empty_to_pad(tensor.reduce_max(shifted, 1))
    if pool_type == "last":
        return empty_to_pad(sequence_last_step(input, lengths))
    if pool_type == "first":
        return empty_to_pad(sequence_first_step(input))
    raise ValueError(f"unknown pool_type {pool_type!r}")


def sequence_first_step(input, lengths=None):
    return tensor.squeeze(tensor.slice(input, [1], [0], [1]), [1])


def sequence_last_step(input, lengths):
    """Row b -> input[b, lengths[b]-1] via a one-hot contraction (gather
    with batch-dependent index, XLA-friendly)."""
    b, t = input.shape[0], input.shape[1]
    idx = tensor.cast(lengths, "int64") - tensor.fill_constant(
        [1], "int64", 1
    )
    onehot = tensor.cast(
        tensor.equal(
            tensor.reshape(tensor.range(0, t, 1, "int64"), [1, t]),
            tensor.reshape(idx, [b, 1]),
        ),
        input.dtype,
    )  # [B, T]
    extra = len(input.shape) - 2
    oh = tensor.reshape(onehot, [b, t] + [1] * extra)
    return tensor.reduce_sum(tensor.elementwise_mul(input, oh), 1)


def sequence_softmax(input, lengths):
    """Masked softmax over the T axis of [B, T] (reference
    sequence_softmax_op.cc normalizes within each sequence)."""
    m = sequence_mask(lengths, input.shape[1], dtype=input.dtype)
    neg = (1.0 - m) * -1e9
    return tensor.softmax(tensor.elementwise_add(input, neg), axis=-1)


def sequence_reverse(x, lengths):
    """Reverse the first lengths[b] steps of each row, keep padding in
    place (reference sequence_reverse_op.h)."""
    b, t = x.shape[0], x.shape[1]
    pos = tensor.reshape(tensor.range(0, t, 1, "int64"), [1, t])
    lens = tensor.reshape(tensor.cast(lengths, "int64"), [b, 1])
    # target index: len-1-pos inside the sequence, pos outside
    inside = tensor.cast(tensor.less_than(pos, lens), "int64")
    rev_idx = (lens - pos - tensor.fill_constant([1], "int64", 1)) * inside \
        + pos * (tensor.fill_constant([1], "int64", 1) - inside)
    extra_shape = list(x.shape[2:])
    idx = tensor.reshape(rev_idx, [b, t] + [1] * len(extra_shape))
    if extra_shape:
        idx = tensor.expand(idx, [1, 1] + extra_shape)
    return tensor.take_along_axis(x, idx, axis=1)


def sequence_expand(x, ref_lengths, maxlen):
    """[B, D] -> [B, maxlen, D] rows repeated up to ref_lengths then zero
    padded (dense analog of sequence_expand_op)."""
    b = x.shape[0]
    ex = tensor.expand(tensor.unsqueeze(x, [1]), [1, maxlen, 1])
    m = sequence_mask(ref_lengths, maxlen, dtype=x.dtype)
    return tensor.elementwise_mul(ex, tensor.reshape(m, [b, maxlen, 1]))


def sequence_concat(xs, axis=1):
    """Concatenate along the time axis (padded tensors)."""
    return tensor.concat(xs, axis=axis)


def sequence_pad(x, pad_value=0.0, maxlen=None, lengths=None):
    """Padded-dense analog of sequence_pad_op: sequences here are ALREADY
    the padded [B, T, ...] frame, so this normalizes the pad tail to
    pad_value using `lengths` and returns (padded, lengths) like the
    reference's (Out, Length) pair."""
    from . import tensor as t

    if lengths is None:
        return x, None
    B, T = x.shape[0], x.shape[1]
    mask = sequence_mask(lengths, maxlen=T, dtype=x.dtype)  # [B, T]
    while len(mask.shape) < len(x.shape):
        mask = t.unsqueeze(mask, axes=[len(mask.shape)])
    return x * mask + (1.0 - mask) * pad_value, lengths


def sequence_unpad(x, length):
    """Inverse of sequence_pad under the dense contract: zero the tail
    beyond each row's length (the reference emits a packed LoD tensor; the
    dense frame + lengths IS this framework's unpadded form)."""
    out, _ = sequence_pad(x, 0.0, lengths=length)
    return out


def sequence_expand_as(x, y_lengths, maxlen):
    """Each row of x repeats across its target sequence's positions
    (reference sequence_expand_as over LoD): x [B, D] -> [B, maxlen, D]
    masked by y_lengths."""
    from . import tensor as t

    xe = t.unsqueeze(x, axes=[1])  # [B, 1, D]
    xe = t.expand(xe, expand_times=[1, maxlen, 1])
    mask = sequence_mask(y_lengths, maxlen=maxlen, dtype=x.dtype)
    return xe * t.unsqueeze(mask, axes=[2])


def sequence_conv(input, num_filters, filter_size=3, padding=True,
                  param_attr=None, bias_attr=None, act=None, lengths=None):
    if not padding:
        raise NotImplementedError(
            "sequence_conv: only same-padded windows are supported in the "
            "dense frame (padding=False would shrink T, breaking the "
            "static [B, T, ...] contract)"
        )
    """Window conv over time (sequence_conv_op): y_t = sum_j x_{t+j} W_j
    over a centered window. Dense form: shifted-concat + fc (one matmul on
    the MXU)."""
    from . import tensor as t
    from .helper import LayerHelper
    from ..initializer import Xavier

    B, T, D = input.shape
    half = (filter_size - 1) // 2
    shifts = []
    for j in range(-half, filter_size - half):
        if j < 0:
            sl = t.slice(input, axes=[1], starts=[0], ends=[T + j])
            pad = t.fill_constant([B, -j, D], input.dtype, 0.0)
            shifts.append(t.concat([pad, sl], axis=1))
        elif j > 0:
            sl = t.slice(input, axes=[1], starts=[j], ends=[T])
            pad = t.fill_constant([B, j, D], input.dtype, 0.0)
            shifts.append(t.concat([sl, pad], axis=1))
        else:
            shifts.append(input)
    windows = t.concat(shifts, axis=2)  # [B, T, k*D]
    helper = LayerHelper("sequence_conv")
    w = helper.create_parameter(
        param_attr, [filter_size * D, num_filters], input.dtype,
        default_initializer=Xavier(),
    )
    out = t.matmul(windows, w)
    if bias_attr is not False:
        b = helper.create_parameter(
            bias_attr, [num_filters], input.dtype, is_bias=True)
        out = out + b
    if lengths is not None:
        mask = sequence_mask(lengths, maxlen=T, dtype=input.dtype)
        out = out * t.unsqueeze(mask, axes=[2])
    if act:
        from .tensor import _simple

        out = _simple(act, {"X": [out]}, {})
    return out
