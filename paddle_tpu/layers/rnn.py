"""Recurrent layers over the lstm/gru ops (reference fluid.layers.rnn /
dynamic_lstm :X / dynamic_gru / cudnn lstm; 3,254 LoC of LoD machinery in
the reference's rnn.py — here padded [B,T,D] + lengths, ops/rnn.py)."""

from __future__ import annotations

from ..framework import unique_name
from ..initializer import Xavier
from ..param_attr import ParamAttr
from .helper import LayerHelper


def _per_layer(attr, layer):
    """Suffix a user attr's name per stacked layer (a shared name would
    silently alias one tensor across layers)."""
    import copy

    a = ParamAttr.to_attr(attr)
    if a is None or getattr(a, "name", None) is None or not layer:
        return attr
    b = copy.copy(a)
    b.name = f"{a.name}_l{layer}"
    return b


def _layer_attrs(kind, layer, param_attr):
    """(wih_attr, whh_attr, bias_attr) for one stacked layer. Names derive
    from the wih param name when one is given, so a second program (e.g. a
    decoding graph) reusing param_attr binds the SAME weights (fluid's
    shared-name parameter semantics); non-name attributes (initializer,
    learning_rate, regularizer, trainable) carry over to every layer's
    weights."""
    import copy

    attr = ParamAttr.to_attr(param_attr) if param_attr is not None else None

    def derive(name):
        if attr is None:
            return ParamAttr(name=name)
        a = copy.copy(attr)
        a.name = name
        return a

    base = getattr(attr, "name", None)
    suffix = f"_l{layer}" if layer else ""
    if base:
        wih = derive(f"{base}{suffix}") if layer else attr
        whh = derive(f"{base}{suffix}_hh")
        bias = derive(f"{base}{suffix}_bias")
    else:
        wih = attr if attr is not None else None
        whh = derive(unique_name.generate(f"{kind}_whh"))
        bias = derive(unique_name.generate(f"{kind}_b"))
    return wih, whh, bias


def lstm(
    input, hidden_size, init_h=None, init_c=None, sequence_length=None,
    num_layers=1, param_attr=None, bias_attr=None, is_bidirec=False,
    name=None,
):
    """Multi-layer LSTM over [B, T, D]; returns (out [B,T,H], last_h,
    last_c) — fluid.layers.lstm parity (cudnn_lstm_op role)."""
    if is_bidirec:
        raise NotImplementedError(
            "bidirectional lstm: run a second stack over "
            "layers.sequence_reverse(input, lengths) and concat"
        )
    helper = LayerHelper("lstm", name=name)
    x = input
    last_h = last_c = None
    d = x.shape[-1]
    for layer in range(num_layers):
        wih_attr, whh_attr, b_attr = _layer_attrs("lstm", layer, param_attr)
        wih = helper.create_parameter(
            wih_attr, [4 * hidden_size, d], "float32",
            default_initializer=Xavier(),
        )
        whh = helper.create_parameter(
            whh_attr,
            [4 * hidden_size, hidden_size], "float32",
            default_initializer=Xavier(),
        )
        b = helper.create_parameter(
            _per_layer(bias_attr, layer) if bias_attr is not None else b_attr,
            [4 * hidden_size], "float32", is_bias=True,
        )
        ins = {"X": [x], "WIH": [wih], "WHH": [whh], "Bias": [b],
               "H0": [init_h], "C0": [init_c],
               "SeqLen": [sequence_length]}
        ins = {k: v for k, v in ins.items() if v[0] is not None}
        x, last_h, last_c = helper.create_and_append(
            ins, {}, op_type="lstm", out_slots=("Out", "LastH", "LastC"),
        )
        d = hidden_size
        init_h = init_c = None  # deeper layers start from zero state
    return x, last_h, last_c


def gru(
    input, hidden_size, init_h=None, sequence_length=None, num_layers=1,
    param_attr=None, bias_attr=None, name=None,
):
    """Multi-layer GRU over [B, T, D]; returns (out, last_h)."""
    helper = LayerHelper("gru", name=name)
    x = input
    last_h = None
    d = x.shape[-1]
    for layer in range(num_layers):
        wih_attr, whh_attr, b_attr = _layer_attrs("gru", layer, param_attr)
        wih = helper.create_parameter(
            wih_attr, [3 * hidden_size, d], "float32",
            default_initializer=Xavier(),
        )
        whh = helper.create_parameter(
            whh_attr,
            [3 * hidden_size, hidden_size], "float32",
            default_initializer=Xavier(),
        )
        b = helper.create_parameter(
            _per_layer(bias_attr, layer) if bias_attr is not None else b_attr,
            [3 * hidden_size], "float32", is_bias=True,
        )
        ins = {"X": [x], "WIH": [wih], "WHH": [whh], "Bias": [b],
               "H0": [init_h], "SeqLen": [sequence_length]}
        ins = {k: v for k, v in ins.items() if v[0] is not None}
        x, last_h = helper.create_and_append(
            ins, {}, op_type="gru", out_slots=("Out", "LastH"),
        )
        d = hidden_size
        init_h = None
    return x, last_h


dynamic_lstm = lstm
dynamic_gru = gru
