"""Attention + MoE layer wrappers (sequence-parallel/expert-parallel aware).

No reference equivalent (SURVEY.md §5: long-context parallelism absent
upstream) — these are the user-facing entry points for the SP/CP/EP
machinery in paddle_tpu.parallel.
"""

from __future__ import annotations

from ..framework import unique_name
from ..initializer import Normal
from .helper import LayerHelper


def _attn(op_type, q, k, v, axis_name, causal, scale, name):
    helper = LayerHelper(op_type, name=name)
    return helper.create_and_append(
        {"Q": [q], "K": [k], "V": [v]},
        {"axis_name": axis_name, "causal": causal, "scale": scale},
    )


def fused_multihead_attention(
    q,
    k,
    v,
    key_bias=None,
    scale=None,
    dropout_prob=0.0,
    is_test=False,
    dropout_implementation="downgrade_in_infer",
    causal=False,
    name=None,
):
    """softmax(q k^T * scale + key_bias) v in one op — the Pallas flash
    kernel on TPU (kernels/flash_attention.py), jnp reference elsewhere.

    q/k/v: [B, H, S, D]; key_bias: optional additive [B, S] (0 keep /
    -1e4 mask). Dropout applies to attention probabilities with fluid
    dropout semantics. Reference: the fused CUDA attention of
    operators/fused/multihead_matmul_op.cu, generalized with mask+dropout.
    """
    helper = LayerHelper("fused_multihead_attention", name=name)
    inputs = {"Q": [q], "K": [k], "V": [v]}
    if key_bias is not None:
        inputs["KeyBias"] = [key_bias]
    attrs = {
        "dropout_prob": dropout_prob,
        "is_test": is_test,
        "dropout_implementation": dropout_implementation,
        "causal": causal,
    }
    if scale is not None:
        attrs["scale"] = float(scale)
    return helper.create_and_append(inputs, attrs)


def fused_qkv_attention(
    qkv,
    num_heads,
    key_bias=None,
    scale=None,
    dropout_prob=0.0,
    is_test=False,
    dropout_implementation="downgrade_in_infer",
    causal=False,
    name=None,
):
    """Attention directly over a packed qkv projection [B, S, 3*H*D] ->
    [B, S, H*D]. Preferred over fused_multihead_attention when the model
    computes qkv as one matmul: the Pallas kernel indexes the projection in
    place, so no head-split transposes/copies ever materialize."""
    helper = LayerHelper("fused_qkv_attention", name=name)
    inputs = {"QKV": [qkv]}
    if key_bias is not None:
        inputs["KeyBias"] = [key_bias]
    attrs = {
        "num_heads": int(num_heads),
        "dropout_prob": dropout_prob,
        "is_test": is_test,
        "dropout_implementation": dropout_implementation,
        "causal": causal,
    }
    if scale is not None:
        attrs["scale"] = float(scale)
    out, _lse = helper.create_and_append(
        inputs, attrs, out_slots=("Out", "Lse")
    )
    return out


def ring_attention(q, k, v, axis_name="sp", causal=False, scale=None,
                   name=None):
    """q,k,v: [B, H, S, D] with S sharded over `axis_name` under SPMD."""
    return _attn("ring_attention", q, k, v, axis_name, causal, scale, name)


def ulysses_attention(q, k, v, axis_name="sp", causal=False, scale=None,
                      name=None):
    return _attn("ulysses_attention", q, k, v, axis_name, causal, scale, name)


def moe_ffn(
    x,
    num_experts,
    hidden_dim,
    axis_name="ep",
    capacity_factor=2.0,
    param_attr_prefix=None,
    name=None,
):
    """Top-2 gated expert FFN over x [B,S,H]. Returns (out, aux_loss).

    Expert weights are created FULL-SIZE ([E, H, F]); annotate them over the
    "ep" axis (program._sharding[w1] = ("ep", None, None)) to shard. The
    helper `moe_shardings` below returns those annotations."""
    from ..param_attr import ParamAttr

    helper = LayerHelper("moe_ffn", name=name)
    h = x.shape[-1]
    prefix = param_attr_prefix or unique_name.generate("moe")
    mk = lambda nm, shape, init_std: helper.create_parameter(  # noqa: E731
        ParamAttr(name=f"{prefix}_{nm}", initializer=Normal(0.0, init_std)),
        list(shape),
        x.dtype,
    )
    gate_w = mk("gate_w", [h, num_experts], 0.02)
    w1 = mk("w1", [num_experts, h, hidden_dim], 0.02)
    b1 = mk("b1", [num_experts, hidden_dim], 0.0)
    w2 = mk("w2", [num_experts, hidden_dim, h], 0.02)
    b2 = mk("b2", [num_experts, h], 0.0)
    out, aux = helper.create_and_append(
        {
            "X": [x],
            "GateW": [gate_w],
            "W1": [w1],
            "B1": [b1],
            "W2": [w2],
            "B2": [b2],
        },
        {"axis_name": axis_name, "capacity_factor": capacity_factor},
        out_slots=("Out", "AuxLoss"),
    )
    return out, aux


def moe_shardings(prefix, axis="ep"):
    """GSPMD/shard_map annotations for a moe_ffn's expert weights."""
    return {
        f"{prefix}_w1": (axis, None, None),
        f"{prefix}_b1": (axis, None),
        f"{prefix}_w2": (axis, None, None),
        f"{prefix}_b2": (axis, None),
    }


def fused_dropout_add_ln(
    x,
    y,
    dropout_prob=0.0,
    is_test=False,
    dropout_implementation="downgrade_in_infer",
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    name=None,
):
    """LayerNorm(x + dropout(y)) over the LAST axis as one fused op — the
    transformer residual tail (reference role: the add+LN fusions of
    math/bert_encoder_functor.cu). x is the residual stream, y the branch
    output; LN affine params are created here (same names/shapes as an
    equivalent layers.layer_norm call, so checkpoints interoperate with
    the composed formulation)."""
    import numpy as np

    from ..initializer import Constant

    helper = LayerHelper("fused_dropout_add_ln", name=name)
    norm_shape = [int(np.prod(x.shape[-1:]))]
    s = helper.create_parameter(
        param_attr, norm_shape, x.dtype, default_initializer=Constant(1.0)
    )
    b = helper.create_parameter(bias_attr, norm_shape, x.dtype, is_bias=True)
    return helper.create_and_append(
        {"X": [x], "Y": [y], "Scale": [s], "LnBias": [b]},
        {
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "dropout_implementation": dropout_implementation,
            "epsilon": epsilon,
        },
    )
