"""Probability distributions (reference python/paddle/fluid/layers/
distributions.py: Distribution, Uniform, Normal, Categorical,
MultivariateNormalDiag — sample / entropy / log_prob / kl_divergence as
graph ops)."""

from __future__ import annotations

import math

from ..framework.program import Variable
from . import tensor


def _as_var(value, like=None, dtype="float32"):
    if isinstance(value, Variable):
        return value
    if isinstance(value, (list, tuple)):
        return tensor.assign_value(value, dtype)
    return tensor.fill_constant([1], dtype, float(value))


class Distribution:
    def sample(self, shape, seed=0):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    """U[low, high) (reference :100)."""

    def __init__(self, low, high):
        self.low = _as_var(low)
        self.high = _as_var(high)

    def sample(self, shape, seed=0):
        u = tensor.uniform_random(shape, min=0.0, max=1.0, seed=seed)
        return self.low + u * (self.high - self.low)

    def entropy(self):
        return tensor.log(self.high - self.low)

    def log_prob(self, value):
        inside = tensor.logical_and(
            tensor.greater_equal(value, self.low),
            tensor.less_than(value, self.high),
        )
        dens = tensor.cast(inside, "float32") / (self.high - self.low)
        return tensor.log(dens + 1e-30)


class Normal(Distribution):
    """N(loc, scale) (reference :260)."""

    def __init__(self, loc, scale):
        self.loc = _as_var(loc)
        self.scale = _as_var(scale)

    def sample(self, shape, seed=0):
        z = tensor.gaussian_random(shape, mean=0.0, std=1.0, seed=seed)
        return self.loc + z * self.scale

    def entropy(self):
        return 0.5 + 0.5 * math.log(2 * math.pi) + tensor.log(self.scale)

    def log_prob(self, value):
        var = self.scale * self.scale
        return (
            -1.0 * ((value - self.loc) * (value - self.loc)) / (2.0 * var)
            - tensor.log(self.scale)
            - 0.5 * math.log(2 * math.pi)
        )

    def kl_divergence(self, other):
        """KL(self || other), both Normal (reference :372)."""
        var_ratio = self.scale / other.scale
        var_ratio = var_ratio * var_ratio
        t1 = (self.loc - other.loc) / other.scale
        t1 = t1 * t1
        return 0.5 * (var_ratio + t1 - 1.0 - tensor.log(var_ratio))


class Categorical(Distribution):
    """Over unnormalized logits (reference :430)."""

    def __init__(self, logits):
        if not isinstance(logits, Variable):
            raise TypeError("Categorical expects a logits Variable")
        self.logits = logits

    def _probs(self):
        return tensor.softmax(self.logits, axis=-1)

    def entropy(self):
        p = self._probs()
        logp = tensor.log(p + 1e-30)
        return 0.0 - tensor.reduce_sum(
            tensor.elementwise_mul(p, logp), -1, keep_dim=False
        )

    def log_prob(self, value):
        logp = tensor.log(self._probs() + 1e-30)
        idx = tensor.unsqueeze(tensor.cast(value, "int32"), [-1])
        return tensor.squeeze(
            tensor.take_along_axis(logp, idx, axis=-1), [-1]
        )

    def kl_divergence(self, other):
        p = self._probs()
        return tensor.reduce_sum(
            tensor.elementwise_mul(
                p,
                tensor.log(p + 1e-30) - tensor.log(other._probs() + 1e-30),
            ),
            -1,
            keep_dim=False,
        )
