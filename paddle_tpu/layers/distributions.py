"""Probability distributions (reference python/paddle/fluid/layers/
distributions.py: Distribution, Uniform, Normal, Categorical,
MultivariateNormalDiag — sample / entropy / log_prob / kl_divergence as
graph ops)."""

from __future__ import annotations

import math

from ..framework.program import Variable
from . import tensor


def _as_var(value, like=None, dtype="float32"):
    if isinstance(value, Variable):
        return value
    if isinstance(value, (list, tuple)):
        return tensor.assign_value(value, dtype)
    return tensor.fill_constant([1], dtype, float(value))


class Distribution:
    def sample(self, shape, seed=0):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    """U[low, high) (reference :100)."""

    def __init__(self, low, high):
        self.low = _as_var(low)
        self.high = _as_var(high)

    def sample(self, shape, seed=0):
        u = tensor.uniform_random(shape, min=0.0, max=1.0, seed=seed)
        return self.low + u * (self.high - self.low)

    def entropy(self):
        return tensor.log(self.high - self.low)

    def log_prob(self, value):
        inside = tensor.logical_and(
            tensor.greater_equal(value, self.low),
            tensor.less_than(value, self.high),
        )
        dens = tensor.cast(inside, "float32") / (self.high - self.low)
        return tensor.log(dens + 1e-30)


class Normal(Distribution):
    """N(loc, scale) (reference :260)."""

    def __init__(self, loc, scale):
        self.loc = _as_var(loc)
        self.scale = _as_var(scale)

    def sample(self, shape, seed=0):
        z = tensor.gaussian_random(shape, mean=0.0, std=1.0, seed=seed)
        return self.loc + z * self.scale

    def entropy(self):
        return 0.5 + 0.5 * math.log(2 * math.pi) + tensor.log(self.scale)

    def log_prob(self, value):
        var = self.scale * self.scale
        return (
            -1.0 * ((value - self.loc) * (value - self.loc)) / (2.0 * var)
            - tensor.log(self.scale)
            - 0.5 * math.log(2 * math.pi)
        )

    def kl_divergence(self, other):
        """KL(self || other), both Normal (reference :372)."""
        var_ratio = self.scale / other.scale
        var_ratio = var_ratio * var_ratio
        t1 = (self.loc - other.loc) / other.scale
        t1 = t1 * t1
        return 0.5 * (var_ratio + t1 - 1.0 - tensor.log(var_ratio))


class Categorical(Distribution):
    """Over unnormalized logits (reference :430)."""

    def __init__(self, logits):
        if not isinstance(logits, Variable):
            raise TypeError("Categorical expects a logits Variable")
        self.logits = logits

    def _probs(self):
        return tensor.softmax(self.logits, axis=-1)

    def entropy(self):
        p = self._probs()
        logp = tensor.log(p + 1e-30)
        return 0.0 - tensor.reduce_sum(
            tensor.elementwise_mul(p, logp), -1, keep_dim=False
        )

    def log_prob(self, value):
        logp = tensor.log(self._probs() + 1e-30)
        idx = tensor.unsqueeze(tensor.cast(value, "int32"), [-1])
        return tensor.squeeze(
            tensor.take_along_axis(logp, idx, axis=-1), [-1]
        )

    def kl_divergence(self, other):
        p = self._probs()
        return tensor.reduce_sum(
            tensor.elementwise_mul(
                p,
                tensor.log(p + 1e-30) - tensor.log(other._probs() + 1e-30),
            ),
            -1,
            keep_dim=False,
        )


class MultivariateNormalDiag(Distribution):
    """N(loc, diag(scale)) (reference distributions.py:383): loc [D],
    scale a diagonal covariance given as a [D, D] matrix whose diagonal
    carries the variances' square roots (the reference passes the full
    diagonal matrix; math uses only its diagonal)."""

    def __init__(self, loc, scale):
        self.loc = _as_var(loc)
        self.scale = _as_var(scale)

    def _diag(self, mat):
        # extract the [D] diagonal of [D, D] through existing ops
        from ..tensor.creation import eye

        d = mat.shape[0]
        return tensor.reduce_sum(mat * eye(num_rows=d), dim=1)

    def sample(self, shape, seed=0):
        d = self.scale.shape[0]
        eps = tensor.gaussian_random(list(shape) + [d], seed=seed)
        return self.loc + eps * self._diag(self.scale)

    def entropy(self):
        """0.5 * (D * (1 + log(2*pi)) + log det(Sigma)) with
        Sigma = diag(scale)^2 (reference :434 — here the matrix diagonal
        carries STANDARD DEVIATIONS, so log det(Sigma) = 2*sum(log s))."""
        d = self.scale.shape[0]
        log_s = tensor.reduce_sum(tensor.log(self._diag(self.scale)))
        return 0.5 * (d * (1.0 + math.log(2.0 * math.pi))) + log_s

    def log_prob(self, value):
        s = self._diag(self.scale)
        var = tensor.square(s)
        z = tensor.square(value - self.loc) / var
        d = self.scale.shape[0]
        return (
            -0.5 * tensor.reduce_sum(z, dim=-1)
            - 0.5 * d * math.log(2.0 * math.pi)
            - tensor.reduce_sum(tensor.log(s))
        )

    def kl_divergence(self, other):
        """KL(self || other) for two diagonal MVNs (reference :451:
        0.5 * (tr(S2^-1 S1) + (m2-m1)^T S2^-1 (m2-m1) - D + ln det S2/det S1))."""
        s1 = tensor.square(self._diag(self.scale))
        s2 = tensor.square(other._diag(other.scale))
        d = self.scale.shape[0]
        diff = other.loc - self.loc
        tr = tensor.reduce_sum(s1 / s2)
        quad = tensor.reduce_sum(tensor.square(diff) / s2)
        logdet = tensor.reduce_sum(tensor.log(s2)) - tensor.reduce_sum(
            tensor.log(s1)
        )
        return 0.5 * (tr + quad - float(d) + logdet)
