"""Learning-rate schedules, built *in-graph* from existing ops.

Reference parity: python/paddle/fluid/layers/learning_rate_scheduler.py
(noam_decay :58, exponential_decay :114, natural_exp_decay :167,
inverse_time_decay :218, polynomial_decay :272, piecewise_decay :339,
cosine_decay :407, linear_lr_warmup :447) and the global step counter
(layers/tensor.py _decay_step_counter in the reference).

TPU-native design: the schedule is a handful of scalar ops appended to the
main program — they trace into the same XLA computation as the train step, so
the LR math fuses to nothing and the step counter lives on device (a [1]
float32 persistable bumped by an `increment` op). The reference instead ran
these as real kernels per step. No host round-trip, no recompile per step.
"""

from __future__ import annotations

from ..framework.program import default_main_program
from ..framework.state import create_step_counter
from . import tensor

LR_COUNTER_NAME = "@LR_DECAY_COUNTER@"


def _decay_step_counter(begin=0):
    """Global step var, bumped once per executor step (in-graph). The
    increment op precedes the decay math, so the counter is initialized to
    begin-1 and the first run observes exactly `begin`. Storage is int32
    (a float32 counter saturates at 2^24 steps, reference uses int64);
    schedulers get a float32 cast for the decay math."""
    prog = default_main_program()
    main = prog.global_block
    if not main.has_var(LR_COUNTER_NAME):
        create_step_counter(LR_COUNTER_NAME, init=float(begin) - 1.0, unique=False)
        prog._lr_counter_begin = int(begin)
    # one counter per program; schedulers composing (warmup over decay)
    # share the same step — matching the reference's single counter. A
    # scheduler whose `begin` differs from the counter's gets a constant
    # offset so e.g. noam (begin=1) after exponential (begin=0) still
    # observes 1 on the first run instead of 0 (-> inf lr).
    step = tensor.cast(main.var(LR_COUNTER_NAME), "float32")
    delta = int(begin) - getattr(prog, "_lr_counter_begin", int(begin))
    if delta:
        step = step + float(delta)
    return step


def _f(value, like=None):
    return tensor.fill_constant([1], "float32", float(value))


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    """lr = learning_rate * d_model^-0.5 * min(step^-0.5, step*warmup^-1.5)."""
    step = _decay_step_counter(begin=1)
    a = tensor.pow(step, factor=-0.5)
    b = step * float(warmup_steps) ** -1.5
    return (
        tensor.elementwise_min(a, b)
        * (float(learning_rate) * float(d_model) ** -0.5)
    )


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = tensor.floor(div)
    return float(learning_rate) * tensor.elementwise_pow(
        _f(decay_rate), div
    )


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = tensor.floor(div)
    return float(learning_rate) * tensor.exp(div * -float(decay_rate))


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = tensor.floor(div)
    denom = div * float(decay_rate) + 1.0
    return _f(learning_rate) / denom


def polynomial_decay(
    learning_rate, decay_steps, end_learning_rate=0.0001, power=1.0, cycle=False
):
    step = _decay_step_counter()
    if cycle:
        # decay_steps stretches by ceil(step / decay_steps) each cycle
        ratio = tensor.ceil(step / float(decay_steps))
        # step == 0 must give ratio 1, not 0 (reference :306-311)
        zero = tensor.cast(tensor.equal(step, _f(0.0)), "float32")
        ratio = ratio + zero
        steps = ratio * float(decay_steps)
    else:
        steps = _f(decay_steps)
        step = tensor.elementwise_min(step, steps)
    frac = tensor.pow(1.0 - step / steps, factor=float(power))
    return (float(learning_rate) - float(end_learning_rate)) * frac + float(
        end_learning_rate
    )


def piecewise_decay(boundaries, values):
    """values[i] while step < boundaries[i]; values[-1] after the last."""
    if len(values) != len(boundaries) + 1:
        raise ValueError("len(values) must be len(boundaries) + 1")
    step = _decay_step_counter()
    lr = _f(values[-1])
    # fold from the right: select(step < b_i, values[i], lr_so_far).
    # XLA folds this mask chain into a couple of selects — cheaper than the
    # reference's per-boundary cond ops (learning_rate_scheduler.py:339).
    for b, v in reversed(list(zip(boundaries, values[:-1]))):
        m = tensor.cast(tensor.less_than(step, _f(b)), "float32")
        lr = m * float(v) + (1.0 - m) * lr
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    step = _decay_step_counter()
    epoch = tensor.floor(step / float(step_each_epoch))
    import math

    return (
        0.5
        * float(learning_rate)
        * (tensor.cos(epoch * (math.pi / float(epochs))) + 1.0)
    )


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    """Linear ramp start_lr→end_lr for warmup_steps, then `learning_rate`
    (a float or another schedule's Variable) after."""
    step = _decay_step_counter()
    from ..framework.program import Variable

    if not isinstance(learning_rate, Variable):
        learning_rate = _f(learning_rate)
    ramp = float(start_lr) + (float(end_lr) - float(start_lr)) * (
        step / float(warmup_steps)
    )
    m = tensor.cast(tensor.less_than(step, _f(warmup_steps)), "float32")
    return m * ramp + (1.0 - m) * learning_rate
