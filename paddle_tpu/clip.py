"""Gradient clipping (reference: python/paddle/fluid/clip.py —
GradientClipByValue, GradientClipByNorm, GradientClipByGlobalNorm)."""

from __future__ import annotations

from .framework import unique_name


class GradientClipBase:
    def apply(self, params_grads, block):
        raise NotImplementedError


class GradientClipByValue(GradientClipBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def apply(self, params_grads, block):
        out = []
        for p, g in params_grads:
            c = block.create_var(
                name=unique_name.generate(g.name + "@CLIP"),
                shape=g.shape, dtype=g.dtype,
            )
            block.append_op(
                "clip", {"X": [g.name]}, {"Out": [c.name]},
                {"min": self.min, "max": self.max},
            )
            out.append((p, c))
        return out


class GradientClipByNorm(GradientClipBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def apply(self, params_grads, block):
        out = []
        for p, g in params_grads:
            c = block.create_var(
                name=unique_name.generate(g.name + "@CLIP"),
                shape=g.shape, dtype=g.dtype,
            )
            block.append_op(
                "clip_by_norm", {"X": [g.name]}, {"Out": [c.name]},
                {"max_norm": self.clip_norm},
            )
            out.append((p, c))
        return out


class GradientClipByGlobalNorm(GradientClipBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _tmp(self, block, key, shape, dtype="float32"):
        return block.create_var(
            name=unique_name.generate(key), shape=shape, dtype=dtype
        )

    def apply(self, params_grads, block):
        # scale = clip_norm / max(gnorm, clip_norm); g_clipped = g * scale
        sq_names = []
        for _, g in params_grads:
            full = self._tmp(block, g.name + "@SQFULL", g.shape, g.dtype)
            block.append_op("square", {"X": [g.name]}, {"Out": [full.name]})
            sq = self._tmp(block, g.name + "@SQ", [1], "float32")
            block.append_op(
                "reduce_sum", {"X": [full.name]}, {"Out": [sq.name]},
                {"reduce_all": True},
            )
            sq_names.append(sq.name)
        total = self._tmp(block, "global_norm_sq", [1])
        block.append_op("sum", {"X": sq_names}, {"Out": [total.name]}, {})
        gnorm = self._tmp(block, "global_norm", [1])
        block.append_op("sqrt", {"X": [total.name]}, {"Out": [gnorm.name]})
        max_norm = self._tmp(block, "max_norm", [1])
        block.append_op(
            "clip", {"X": [gnorm.name]}, {"Out": [max_norm.name]},
            {"min": self.clip_norm, "max": 3.4e38},
        )
        inv = self._tmp(block, "inv_max_norm", [1])
        block.append_op("reciprocal", {"X": [max_norm.name]}, {"Out": [inv.name]})
        scale_v = self._tmp(block, "clip_scale", [1])
        block.append_op(
            "scale", {"X": [inv.name]}, {"Out": [scale_v.name]},
            {"scale": self.clip_norm},
        )
        out = []
        for p, g in params_grads:
            c = self._tmp(block, g.name + "@CLIP", g.shape, g.dtype)
            block.append_op(
                "elementwise_mul",
                {"X": [g.name], "Y": [scale_v.name]},
                {"Out": [c.name]},
                {},
            )
            out.append((p, c))
        return out


ErrorClipByValue = GradientClipByValue
