"""Typed error taxonomy (reference: platform/error_codes.proto:19-80 Code
enum, platform/enforce.h:282 EnforceNotMet, platform/errors.cc factory
functions, pybind/exception.cc:20 BindException).

The reference raises `EnforceNotMet` carrying one of 12 error codes plus
the offending op and a C++ backtrace. Here every class is an
`EnforceNotMet` subclass that ALSO inherits the natural Python builtin
(InvalidArgumentError is a ValueError, OutOfRangeError an IndexError,
UnimplementedError a NotImplementedError, ...), so callers can catch
either the framework taxonomy or the builtin they already handle — and
every pre-taxonomy `except ValueError/RuntimeError` keeps working.

Raise sites attach op provenance (op type + the user line that created
the op, the `__loc__` attr) via `op=`/`loc=`; `EnforceNotMet.op_type` and
`.user_loc` expose them for programmatic handling (the reference prints
them inside the enforce message, enforce.h:282 GetErrorSumaryString).
"""

from __future__ import annotations

import enum


class ErrorCode(enum.IntEnum):
    """platform/error_codes.proto Code enum (same numbering)."""

    LEGACY = 0
    INVALID_ARGUMENT = 1
    NOT_FOUND = 2
    OUT_OF_RANGE = 3
    ALREADY_EXISTS = 4
    RESOURCE_EXHAUSTED = 5
    PRECONDITION_NOT_MET = 6
    PERMISSION_DENIED = 7
    EXECUTION_TIMEOUT = 8
    UNIMPLEMENTED = 9
    UNAVAILABLE = 10
    FATAL = 11
    EXTERNAL = 12


class EnforceNotMet(Exception):
    """Base of the taxonomy (enforce.h:282). Carries the error code and,
    when raised from an op context, the op type and the user source line
    that created the op."""

    code = ErrorCode.LEGACY

    def __init__(self, message, op=None, loc=None):
        self.op_type = getattr(op, "type", op)
        self.user_loc = loc if loc is not None else (
            op.attr("__loc__", None) if hasattr(op, "attr") else None
        )
        parts = [str(message)]
        ctx = []
        if self.op_type:
            ctx.append(f"op {self.op_type!r}")
        if self.user_loc:
            ctx.append(f"created at {self.user_loc}")
        if ctx:
            parts.append(f"  [operator context: {', '.join(ctx)}]")
        parts.append(f"  [error code: {self.code.name} ({self.code.value})]")
        self.message = message
        super().__init__("\n".join(parts))


class EOFException(EnforceNotMet):
    """Reader/queue exhaustion (platform/enforce.h EOFException,
    pybind/exception.cc:21) — the sentinel fluid readers raise when a
    blocking queue closes."""

    code = ErrorCode.LEGACY


class InvalidArgumentError(EnforceNotMet, ValueError):
    code = ErrorCode.INVALID_ARGUMENT


class NotFoundError(EnforceNotMet, RuntimeError):
    code = ErrorCode.NOT_FOUND


class OutOfRangeError(EnforceNotMet, IndexError):
    code = ErrorCode.OUT_OF_RANGE


class AlreadyExistsError(EnforceNotMet, RuntimeError):
    code = ErrorCode.ALREADY_EXISTS


class ResourceExhaustedError(EnforceNotMet, MemoryError):
    code = ErrorCode.RESOURCE_EXHAUSTED


class PreconditionNotMetError(EnforceNotMet, RuntimeError):
    code = ErrorCode.PRECONDITION_NOT_MET


class PermissionDeniedError(EnforceNotMet, RuntimeError):
    code = ErrorCode.PERMISSION_DENIED


class ExecutionTimeoutError(EnforceNotMet, RuntimeError):
    code = ErrorCode.EXECUTION_TIMEOUT


class DeadlineExceededError(ExecutionTimeoutError):
    """A serving request's deadline expired before it was dispatched: the
    scheduler dropped it ahead of batch formation (``serving.expired``), so
    stale work never pads a bucket or burns a dispatch. Non-retryable — the
    client's latency budget is spent; re-queueing the same request can only
    produce an answer nobody is waiting for."""

    code = ErrorCode.EXECUTION_TIMEOUT
    retryable = False


class UnimplementedError(EnforceNotMet, NotImplementedError):
    code = ErrorCode.UNIMPLEMENTED


class UnavailableError(EnforceNotMet, RuntimeError):
    code = ErrorCode.UNAVAILABLE


class RequestShedError(UnavailableError):
    """The serving layer shed this request under overload: either a
    higher-priority admission evicted it from a full queue, or the brownout
    ladder is refusing its priority class outright (``serving.shed``).
    Marked non-retryable at the in-process seam — an immediate retry lands
    in the same overloaded queue; clients should back off (with jitter)
    before resubmitting."""

    code = ErrorCode.UNAVAILABLE
    retryable = False


class FatalError(EnforceNotMet, SystemError):
    code = ErrorCode.FATAL


class ExternalError(EnforceNotMet, OSError):
    code = ErrorCode.EXTERNAL


class CheckpointCorruptionError(EnforceNotMet, OSError):
    """A checkpoint failed integrity verification (torn write, CRC/shape/
    dtype mismatch vs its manifest, undecodable container). Raised by
    io.py load paths BEFORE any scope mutation — never silently-wrong
    weights. An OSError so generic IO handlers still catch it, but
    explicitly non-retryable: re-reading corrupt bytes cannot help, the
    caller must fall back to an older checkpoint (Fleet.load_check_point
    does so automatically)."""

    code = ErrorCode.EXTERNAL
    retryable = False


class StorageExhaustedError(EnforceNotMet, OSError):
    """A durable write ran out of disk: the filesystem returned ``ENOSPC``/
    ``EDQUOT``, the preflight free-space check found less room than the
    payload needs, or the storage pressure ladder is at CRITICAL and
    refusing new checkpoint/publish writes outright. An OSError so generic
    IO handlers still catch it, and retryable-after-GC by design: unlike
    :class:`CheckpointCorruptionError`, retrying CAN succeed — but only
    once space is reclaimed, so the retry policies treat it as
    non-retryable in-place (``retryable = False``) and the caller is
    expected to run (or wait for) ``resilience.storage.RetentionManager``
    GC before trying again. The failed write itself is clean: io.py's
    atomic writers unlink their temp file on every failure path, so a full
    disk never accretes ``*.tmp.*`` garbage that makes itself fuller."""

    code = ErrorCode.RESOURCE_EXHAUSTED
    retryable = False


class NonFiniteError(PreconditionNotMetError):
    """A NaN/Inf reached a numeric health check: the executor's
    FLAGS_check_nan_inf per-op scan (which names the offending op via
    `op=`/`outputs=`) and TrainGuard's always-on fused fetch check both
    raise this. A PreconditionNotMetError subclass so pre-existing
    handlers keep working; non-retryable — re-running the same step on
    the same state reproduces the same NaN."""

    code = ErrorCode.PRECONDITION_NOT_MET
    retryable = False

    def __init__(self, message, op=None, loc=None, outputs=None):
        self.outputs = list(outputs) if outputs else []
        if self.outputs:
            message = f"{message}; outputs: {self.outputs}"
        super().__init__(message, op=op, loc=loc)


class ResumeMismatchError(PreconditionNotMetError):
    """On resume, a rank's view of the checkpoint is incoherent: its
    ``rank_<i>/`` state shard carries a different checkpoint number or
    global step than the checkpoint-level commit record, or a shard the
    commit record promises is missing. Loading anyway would silently
    diverge the ranks (one replays a different data prefix than the
    others), so this is typed and non-retryable — the caller must pick a
    coherent (usually older) checkpoint; ``Fleet.load_check_point`` skips
    incomplete checkpoints automatically when no explicit
    ``checkpoint_no`` was requested."""

    code = ErrorCode.PRECONDITION_NOT_MET
    retryable = False


class ProgramVerifyError(PreconditionNotMetError):
    """The pre-compile static verifier (paddle_tpu/analysis) found ERROR
    findings under ``PADDLE_TPU_VERIFY=strict``: the Program is structurally
    malformed (use-before-def, shape/dtype desync vs the emitters, a
    rank-divergent collective schedule, ...). Raised at
    ``Executor._compile`` time BEFORE any XLA trace, so the message carries
    per-op provenance instead of an opaque trace error — and a mismatched
    collective fails here instead of deadlocking the pod. ``findings``
    holds the full, structured ``analysis.Finding`` list (errors first).
    Non-retryable: the graph itself must be fixed."""

    code = ErrorCode.PRECONDITION_NOT_MET
    retryable = False

    def __init__(self, message, findings=None, op=None, loc=None):
        self.findings = list(findings or [])
        super().__init__(message, op=op, loc=loc)


class ProgramVerifyWarning(UserWarning):
    """Category for warnings emitted by the static program verifier in its
    default ``PADDLE_TPU_VERIFY=warn`` mode (and by ``Block.create_var``
    when a name is silently redefined). Filter with
    ``warnings.filterwarnings(..., category=ProgramVerifyWarning)``."""


class CostAnalysisUnavailableWarning(UserWarning):
    """The compiled executable's ``cost_analysis()`` returned no data
    (``Executor.flops``): the backend genuinely reports nothing, which is
    NOT the same as a zero-FLOP program. Callers deriving MFU from
    ``Executor.flops`` should fall back to ``Program.estimate()`` — the
    executor's live ``perf.mfu`` gauge already does. Each occurrence also
    bumps the ``perf.cost_analysis_unavailable`` counter."""


class TrainingDivergedError(EnforceNotMet, RuntimeError):
    """TrainGuard exhausted its recovery policy: K consecutive non-finite
    steps and no (remaining) checkpoint to roll back to. The run cannot
    make progress by retrying — a human (or an outer scheduler with a
    different initialization/LR) must intervene."""

    code = ErrorCode.FATAL
    retryable = False


def enforce(condition, error):
    """PADDLE_ENFORCE (enforce.h:282): raise `error` (an EnforceNotMet
    instance) unless `condition`."""
    if not condition:
        raise error
