"""Dtype taxonomy for paddle_tpu.

The reference keeps a proto-level VarType enum (framework.proto:104 in the
reference repo) plus numpy/C++ mappings. Here the single source of truth is the
numpy/JAX dtype; we keep string names compatible with the fluid API surface
("float32", "int64", ...) so user code reads the same.

TPU note: bf16 is first-class (MXU-native); fp64 is supported by XLA:CPU for
tests but discouraged on TPU.
"""

from __future__ import annotations

import numpy as np

try:  # jax is the compute backend; numpy fallback keeps module importable
    import jax.numpy as jnp

    _BF16 = jnp.bfloat16
except Exception:  # pragma: no cover
    jnp = None
    _BF16 = None

# canonical name -> numpy dtype object
_NAME_TO_NP = {
    "bool": np.dtype(np.bool_),
    "int8": np.dtype(np.int8),
    "uint8": np.dtype(np.uint8),
    "int16": np.dtype(np.int16),
    "int32": np.dtype(np.int32),
    "int64": np.dtype(np.int64),
    "float16": np.dtype(np.float16),
    "float32": np.dtype(np.float32),
    "float64": np.dtype(np.float64),
}

FLOAT_DTYPES = ("float16", "bfloat16", "float32", "float64")
INT_DTYPES = ("bool", "int8", "uint8", "int16", "int32", "int64")


def convert_dtype(dtype) -> str:
    """Normalize any dtype spec (str, np.dtype, jnp dtype) to a canonical name."""
    if dtype is None:
        return "float32"
    if isinstance(dtype, str):
        name = dtype
    else:
        name = np.dtype(dtype).name if _BF16 is None or dtype != _BF16 else "bfloat16"
    if name == "bfloat16":
        return name
    if name not in _NAME_TO_NP:
        # np.dtype handles e.g. np.float32 class objects
        name = np.dtype(dtype).name
    if name not in _NAME_TO_NP:
        raise ValueError(f"unsupported dtype: {dtype!r}")
    return name


def to_numpy_dtype(dtype):
    name = convert_dtype(dtype)
    if name == "bfloat16":
        if _BF16 is None:
            raise ValueError("bfloat16 requires jax")
        return _BF16
    return _NAME_TO_NP[name]


def is_float(dtype) -> bool:
    return convert_dtype(dtype) in FLOAT_DTYPES


def is_integer(dtype) -> bool:
    return convert_dtype(dtype) in INT_DTYPES

