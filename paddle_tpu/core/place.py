"""Device placement taxonomy.

Mirrors the capability of the reference's Place variant (platform/place.h:26-81
in the reference repo): CPUPlace / CUDAPlace / CUDAPinnedPlace. Here the
accelerator is TPU and the actual placement is delegated to JAX/XLA (PJRT);
a Place mostly selects which jax device a program executes on, and -- for
multi-chip -- which mesh.
"""

from __future__ import annotations

import functools


class Place:
    device_type = "unknown"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def __eq__(self, other):
        return (
            type(self) is type(other) and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"

    def jax_device(self):
        import jax

        devs = [d for d in jax.devices() if self._match(d)]
        if not devs:
            # fall back to default backend (e.g. CPU-only test runs)
            devs = jax.devices()
        return devs[self.device_id % len(devs)]

    def _match(self, dev) -> bool:
        return True


class CPUPlace(Place):
    device_type = "cpu"

    def _match(self, dev):
        return dev.platform == "cpu"


class TPUPlace(Place):
    device_type = "tpu"

    def _match(self, dev):
        return dev.platform != "cpu"


# Alias kept so fluid-style code written against the reference's CUDAPlace
# (platform/place.h:37) ports by search/replace; on this framework the
# accelerator is always the TPU.
CUDAPlace = TPUPlace


@functools.lru_cache(maxsize=None)
def _has_accelerator() -> bool:
    try:
        import jax

        return any(d.platform != "cpu" for d in jax.devices())
    except Exception:  # pragma: no cover
        return False


def is_compiled_with_tpu() -> bool:
    return _has_accelerator()


def default_place() -> Place:
    return TPUPlace(0) if _has_accelerator() else CPUPlace(0)


def tpu_places(device_ids=None):
    import jax

    devs = [d for d in jax.devices() if d.platform != "cpu"] or jax.devices()
    ids = range(len(devs)) if device_ids is None else device_ids
    return [TPUPlace(i) for i in ids]


def cpu_places(device_count=1):
    return [CPUPlace(i) for i in range(device_count)]
