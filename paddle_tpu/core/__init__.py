from .dtypes import convert_dtype, is_float, is_integer, to_numpy_dtype  # noqa: F401
from .place import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    Place,
    TPUPlace,
    cpu_places,
    default_place,
    is_compiled_with_tpu,
    tpu_places,
)
