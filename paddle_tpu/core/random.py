"""PRNG policy (key implementation selection for per-step streams)."""

from __future__ import annotations

import os


def prng_impl():
    """PRNG implementation for per-step keys. TPU defaults to "rbg"
    (counter-based, ~an order of magnitude cheaper than threefry for the
    per-op dropout masks and natively partitionable under SPMD); override
    with PADDLE_TPU_PRNG=threefry2x32 for threefry streams everywhere.
    The reference has no analogous contract — its dropout uses curand
    Philox per kernel launch (dropout_op.cu)."""
    import jax

    from ..flags import flag

    choice = flag("paddle_tpu_prng") or os.environ.get("PADDLE_TPU_PRNG")
    if choice:
        return choice
    return "rbg" if jax.default_backend() == "tpu" else "threefry2x32"
