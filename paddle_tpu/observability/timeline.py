"""Persistent metric timelines: per-process delta journals that outlive
their process.

Every observability surface before this module was in-process and
point-in-time — ``export.snapshot()`` reads the live registry, and when a
rank dies (the exact event the resilience layer is built to survive) its
metrics die with it. The :class:`TelemetryPublisher` fixes that by
journaling the registry to disk as it evolves:

* a daemon thread wakes every ``interval`` seconds, computes the registry
  *delta* since its last publish (counter increments, changed gauges,
  per-bucket histogram count deltas, changed tables), and appends it as
  ONE ``\\n``-terminated JSON line to a per-process shard
  ``{dir}/telemetry_rank{K}.jsonl`` — a single ``write()`` per record, so
  a reader (or a SIGKILL) never sees a torn line, only a truncated tail
  that :func:`read_records` skips;
* every shard file begins with a ``base`` record carrying the full
  cumulative state, so replaying ONE file — no predecessor, no shared
  memory — reconstructs the writer's last published snapshot exactly
  (:func:`replay_journal`); integer deltas accumulate exactly, and float
  fields (gauges, histogram sum/min/max) are journaled as absolutes so
  replay is bitwise, not drift-prone float re-accumulation;
* shards rotate at ``max_bytes`` (``{shard}.1`` keeps one predecessor;
  the fresh shard re-opens with a new ``base``), bounding disk while
  keeping the current file self-contained.

Knobs: ``PADDLE_TPU_TELEMETRY_DIR`` (no dir, no journal — also the
one-env-var opt-in :func:`ensure_publisher` keys on),
``PADDLE_TPU_TELEMETRY_INTERVAL`` (publish cadence, default 1s),
``PADDLE_TPU_TELEMETRY_MAX_BYTES`` (rotation cap, default 8 MiB). The
whole module rides the ``PADDLE_TPU_MONITOR`` kill-switch: disabled means
no thread is started and no file is touched.

Heartbeats stamp :func:`journal_stamp` — the shard name plus the latest
journal (seq, byte offset) — into their payload, so a fleet supervisor
can tell "rank alive but journal stale" from "rank gone".

Consumers: ``tools/fleet_report.py`` merges shards into fleet-wide time
series, and ``Watcher(journal_dir=...)`` raises findings off *remote*
processes' journals (:class:`JournalFollower` is the incremental-read
primitive both build on).
"""

from __future__ import annotations

import json
import os
import threading
import time

from . import metrics

__all__ = [
    "TELEMETRY_DIR_ENV",
    "TELEMETRY_INTERVAL_ENV",
    "TELEMETRY_MAX_BYTES_ENV",
    "JournalFollower",
    "ReplayState",
    "TelemetryPublisher",
    "current_publisher",
    "ensure_publisher",
    "journal_stamp",
    "read_records",
    "replay_journal",
    "shard_path",
]

TELEMETRY_DIR_ENV = "PADDLE_TPU_TELEMETRY_DIR"
TELEMETRY_INTERVAL_ENV = "PADDLE_TPU_TELEMETRY_INTERVAL"
TELEMETRY_MAX_BYTES_ENV = "PADDLE_TPU_TELEMETRY_MAX_BYTES"

_DEFAULT_INTERVAL = 1.0
_DEFAULT_MAX_BYTES = 8 * 1024 * 1024


def shard_path(directory, rank):
    """The journal shard for `rank` — the {dir}/telemetry_rank{K}.jsonl
    naming contract shared by the publisher (writer) and fleet_report /
    the Watcher's journal mode (readers)."""
    return os.path.join(directory, f"telemetry_rank{int(rank)}.jsonl")


# -- registry raw state ------------------------------------------------------
def _raw_hist(h):
    """snapshot-shaped histogram dict -> raw non-cumulative form the delta
    encoder diffs: {"bounds", "counts" (per-bucket, +Inf last), "count",
    "sum", "min", "max"}."""
    buckets = h["buckets"]
    bounds = [le for le, _ in buckets[:-1]]
    cum = [c for _, c in buckets[:-1]]
    counts = [c - p for c, p in zip(cum, [0] + cum[:-1])]
    counts.append(h["count"] - (cum[-1] if cum else 0))  # +Inf bucket
    return {
        "bounds": bounds, "counts": counts, "count": h["count"],
        "sum": h["sum"], "min": h["min"], "max": h["max"],
    }


def _registry_state():
    """One coherent-enough read of the whole registry in raw form."""
    return {
        "counters": metrics.get_counters(),
        "gauges": metrics.get_gauges(),
        "hists": {
            k: _raw_hist(h) for k, h in metrics.get_histograms().items()
        },
        "tables": metrics.get_tables(),
    }


def _empty_state():
    return {"counters": {}, "gauges": {}, "hists": {}, "tables": {}}


def _delta(prev, cur):
    """Delta record body between two raw states, or None when nothing
    changed. Integers (counters, bucket counts) are encoded as deltas —
    exact under accumulation; floats (gauges, histogram sum/min/max) as
    absolutes — replay must be bitwise, and ``base + (b - a)`` is not
    ``b`` in floating point. Returns None (regression) when a counter or
    histogram ran BACKWARD (a ``metrics.reset()`` happened): the caller
    re-bases instead of journaling a nonsense negative delta."""
    body = {}
    counters = {}
    for k, v in cur["counters"].items():
        d = v - prev["counters"].get(k, 0)
        if d < 0:
            return None, True
        if d:
            counters[k] = d
    if set(prev["counters"]) - set(cur["counters"]):
        return None, True
    if counters:
        body["counters"] = counters
    gauges = {
        k: v for k, v in cur["gauges"].items()
        if prev["gauges"].get(k, _MISSING) != v
    }
    if gauges:
        body["gauges"] = gauges
    dropped = sorted(set(prev["gauges"]) - set(cur["gauges"]))
    if dropped:
        body["gauges_dropped"] = dropped
    hists = {}
    for k, h in cur["hists"].items():
        p = prev["hists"].get(k)
        if p is None:
            hists[k] = dict(h)  # new histogram: full raw form
            continue
        if p["bounds"] != h["bounds"] or h["count"] < p["count"]:
            return None, True
        if h["count"] == p["count"] and h["sum"] == p["sum"]:
            continue
        d = {
            str(i): c - pc
            for i, (c, pc) in enumerate(zip(h["counts"], p["counts"]))
            if c != pc
        }
        hists[k] = {
            "d": d, "count": h["count"], "sum": h["sum"],
            "min": h["min"], "max": h["max"],
        }
    if set(prev["hists"]) - set(cur["hists"]):
        return None, True
    if hists:
        body["hists"] = hists
    tables = {
        k: v for k, v in cur["tables"].items()
        if prev["tables"].get(k) != v
    }
    if tables:
        body["tables"] = tables
    t_dropped = sorted(set(prev["tables"]) - set(cur["tables"]))
    if t_dropped:
        body["tables_dropped"] = t_dropped
    return (body if body else None), False


_MISSING = object()


class ReplayState:
    """Accumulate journal records back into registry state.

    ``apply()`` one record at a time (a ``base`` record REPLACES the
    state — that is how both shard self-containment and in-process
    ``metrics.reset()`` re-bases replay); ``snapshot()`` renders the
    accumulated state in the exact shape of ``export.snapshot()`` so a
    replayed journal is comparable to a live dump field-for-field.
    """

    def __init__(self):
        self.state = _empty_state()
        self.meta = {}  # rank/pid/seq/t of the newest applied record

    def apply(self, rec):
        kind = rec.get("kind")
        if kind == "base":
            self.state = _empty_state()
            for sec in ("counters", "gauges", "tables"):
                self.state[sec].update(rec.get(sec) or {})
            for k, h in (rec.get("hists") or {}).items():
                self.state["hists"][k] = {
                    "bounds": list(h["bounds"]),
                    "counts": list(h["counts"]),
                    "count": h["count"], "sum": h["sum"],
                    "min": h["min"], "max": h["max"],
                }
        elif kind == "delta":
            st = self.state
            for k, d in (rec.get("counters") or {}).items():
                st["counters"][k] = st["counters"].get(k, 0) + d
            st["gauges"].update(rec.get("gauges") or {})
            for k in rec.get("gauges_dropped") or ():
                st["gauges"].pop(k, None)
            for k, h in (rec.get("hists") or {}).items():
                cur = st["hists"].get(k)
                if cur is None or "d" not in h:
                    st["hists"][k] = {
                        "bounds": list(h["bounds"]),
                        "counts": list(h["counts"]),
                        "count": h["count"], "sum": h["sum"],
                        "min": h["min"], "max": h["max"],
                    }
                    continue
                for i, d in h["d"].items():
                    cur["counts"][int(i)] += d
                cur.update(count=h["count"], sum=h["sum"],
                           min=h["min"], max=h["max"])
            st["tables"].update(rec.get("tables") or {})
            for k in rec.get("tables_dropped") or ():
                st["tables"].pop(k, None)
        else:
            return  # unknown kind: forward-compatible skip
        for k in ("rank", "pid"):
            if k in rec:
                self.meta[k] = rec[k]
        self.meta["seq"] = rec.get("seq")
        self.meta["t"] = rec.get("t")

    def snapshot(self):
        """The accumulated state, rendered snapshot()-shaped."""
        hists = {}
        for k, h in self.state["hists"].items():
            cum, buckets = 0, []
            for le, c in zip(h["bounds"], h["counts"]):
                cum += c
                buckets.append([le, cum])
            buckets.append(["+Inf", h["count"]])
            hists[k] = {
                "count": h["count"], "sum": h["sum"],
                "min": h["min"], "max": h["max"], "buckets": buckets,
            }
        snap = {
            "counters": dict(self.state["counters"]),
            "gauges": dict(self.state["gauges"]),
            "histograms": hists,
        }
        if self.state["tables"]:
            snap["tables"] = {
                k: v for k, v in self.state["tables"].items()
            }
        return snap


def read_records(path):
    """Parse one journal file -> list of records. A torn/truncated line
    (the SIGKILL-mid-write case) is skipped, not fatal: every complete
    line before it is still good."""
    records = []
    try:
        with open(path) as f:
            for line in f:
                if not line.endswith("\n"):
                    break  # truncated tail: the write never completed
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
    except OSError:
        pass
    return records


def replay_journal(path, include_rotated=True):
    """Replay one shard (optionally its ``.1`` predecessor first) into a
    :class:`ReplayState`. The current shard alone is always sufficient
    for the FINAL state (it opens with a ``base``); the predecessor only
    adds earlier time-series records."""
    st = ReplayState()
    paths = []
    if include_rotated and os.path.exists(path + ".1"):
        paths.append(path + ".1")
    paths.append(path)
    for p in paths:
        for rec in read_records(p):
            st.apply(rec)
    return st


class JournalFollower:
    """Incremental reader of one journal shard.

    ``poll()`` returns the records appended since the last poll and folds
    them into ``.replay``; rotation (the file shrank under us) re-reads
    from the top — the fresh ``base`` record re-bases the replay, so a
    follower never double-counts across a rotation. This is the primitive
    the Watcher's journal mode and any live fleet supervisor poll.
    """

    def __init__(self, path):
        self.path = path
        self.replay = ReplayState()
        self._offset = 0

    def poll(self):
        new = []
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return new
        if size < self._offset:
            self._offset = 0  # rotated: next base record resets replay
        if size == self._offset:
            return new
        try:
            with open(self.path) as f:
                f.seek(self._offset)
                for line in f:
                    if not line.endswith("\n"):
                        break  # torn tail: re-read once it completes
                    self._offset += len(line.encode("utf-8"))
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict):
                        new.append(rec)
        except OSError:
            return new
        for rec in new:
            self.replay.apply(rec)
        return new


# -- the publisher -----------------------------------------------------------
class TelemetryPublisher:
    """Daemon thread journaling registry deltas to a per-process shard.

    ``start()`` opens the shard (rotating any stale same-name file away —
    a restart must not append deltas onto a dead process's baseline),
    writes the ``base`` record and begins the cadence; ``publish()``
    forces one delta record now (the step-loop shape: publish after each
    step instead of on the clock). ``stop()`` publishes a final delta and
    closes. Under ``PADDLE_TPU_MONITOR=0`` every one of those is a no-op:
    no thread, no file.
    """

    def __init__(self, directory=None, rank=None, interval=None,
                 max_bytes=None):
        if directory is None:
            directory = os.environ.get(TELEMETRY_DIR_ENV)
        if directory is None:
            raise ValueError(
                "TelemetryPublisher needs a directory (arg or "
                f"{TELEMETRY_DIR_ENV} env)"
            )
        if rank is None:
            rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        if interval is None:
            try:
                interval = float(os.environ.get(
                    TELEMETRY_INTERVAL_ENV, _DEFAULT_INTERVAL))
            except ValueError:
                interval = _DEFAULT_INTERVAL
        if max_bytes is None:
            try:
                max_bytes = int(os.environ.get(
                    TELEMETRY_MAX_BYTES_ENV, _DEFAULT_MAX_BYTES))
            except ValueError:
                max_bytes = _DEFAULT_MAX_BYTES
        self.directory = directory
        self.rank = int(rank)
        self.interval = float(interval)
        self.max_bytes = int(max_bytes)
        self.seq = 0
        self._last = None  # raw state at the last publish (None = rebase)
        self._f = None
        self._offset = 0
        self._paused = threading.Event()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    @property
    def path(self):
        return shard_path(self.directory, self.rank)

    @property
    def active(self):
        return self._f is not None

    def offset(self):
        """(seq, byte offset) of the newest complete record — what
        heartbeats stamp so journal staleness is detectable."""
        with self._lock:
            return self.seq, self._offset

    # -- lifecycle ---------------------------------------------------------
    def start(self, register=True):
        """Open the shard, write the base record, start the cadence
        thread. `register=False` skips installing this publisher as the
        process-global one (tests journaling multiple ranks)."""
        if not metrics.enabled():
            return self
        with self._lock:
            if self._f is None:
                self._open_locked()
        if register:
            global _active
            _active = self
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="obs-telemetry"
            )
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.interval * 4 + 1.0)
        self.publish()  # final delta: the journal ends at the registry
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None
        global _active
        if _active is self:
            _active = None

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False

    def pause(self):
        """Suspend journaling (the cadence thread idles; ``publish()``
        no-ops) without tearing the shard down — resume() re-bases
        nothing, deltas just span the gap."""
        self._paused.set()

    def resume(self):
        self._paused.clear()

    # -- publishing --------------------------------------------------------
    def publish(self):
        """Journal one record NOW: the delta since the last publish, or a
        fresh base when there is none yet (or the registry ran backward —
        a ``metrics.reset()`` re-bases the journal). Returns the record
        written, or None when nothing changed / journaling is off."""
        if not metrics.enabled() or self._paused.is_set():
            return None
        with self._lock:
            if self._f is None:
                return None
            # self-telemetry BEFORE the state read, so the record being
            # written already accounts for it and replay lands exactly on
            # the registry as of this publish
            metrics.add("telemetry.publishes")
            metrics.set_gauge("telemetry.journal_bytes", float(self._offset))
            cur = _registry_state()
            if self._last is None:
                rec = self._base_record(cur)
            else:
                body, regressed = _delta(self._last, cur)
                if regressed:
                    rec = self._base_record(cur)
                elif body is None:
                    return None
                else:
                    rec = {"kind": "delta", "seq": self.seq + 1,
                           "t": time.time()}
                    rec.update(body)
            self._write_locked(rec)
            self._last = cur
            if self._offset > self.max_bytes:
                self._rotate_locked()
            return rec

    def _base_record(self, cur):
        rec = {
            "kind": "base", "seq": self.seq + 1, "t": time.time(),
            "rank": self.rank, "pid": os.getpid(),
        }
        for sec in ("counters", "gauges", "tables"):
            if cur[sec]:
                rec[sec] = cur[sec]
        if cur["hists"]:
            rec["hists"] = cur["hists"]
        return rec

    def _write_locked(self, rec):
        # ONE write of one \n-terminated line: the append is line-atomic
        # for any reader, and a SIGKILL leaves at worst a truncated tail
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        self._f.write(line)
        self._f.flush()
        self.seq = rec["seq"]
        self._offset += len(line.encode("utf-8"))

    def _open_locked(self):
        os.makedirs(self.directory, exist_ok=True)
        # a dead predecessor's failed atomic writes (this rank's prefix
        # only — sibling ranks may be live mid-publish in the same dir)
        from .. import io as _io

        _io.sweep_stale_tmp(
            self.directory, prefix=os.path.basename(self.path)
        )
        if os.path.exists(self.path):
            # a previous process's shard: rotate it away rather than
            # appending this process's baseline behind its deltas
            os.replace(self.path, self.path + ".1")
        self._f = open(self.path, "a")
        self._offset = 0
        self._last = None

    def _rotate_locked(self):
        self._f.close()
        os.replace(self.path, self.path + ".1")
        self._f = open(self.path, "a")
        self._offset = 0
        self._last = None  # next publish opens the fresh shard with a base
        metrics.add("telemetry.rotations")

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.publish()
            except Exception:
                pass  # a broken publish must not kill the journal thread


# -- process-global wiring ---------------------------------------------------
_active: TelemetryPublisher | None = None
_ensure_lock = threading.Lock()


def current_publisher():
    return _active


def journal_stamp():
    """{"telemetry_shard", "telemetry_seq", "telemetry_offset"} of the
    process-global publisher, or None when none is journaling — the
    staleness stamp heartbeats carry."""
    pub = _active
    if pub is None or not pub.active:
        return None
    seq, off = pub.offset()
    return {
        "telemetry_shard": os.path.basename(pub.path),
        "telemetry_seq": seq,
        "telemetry_offset": off,
    }


def ensure_publisher():
    """One-env-var opt-in: when ``PADDLE_TPU_TELEMETRY_DIR`` is set (and
    monitoring is on) start the process-global publisher AND flight
    recorder once. Idempotent and cheap when the env is absent — the
    executor calls this on construction so any launched trainer joins the
    telemetry plane without code changes."""
    if _active is not None or not os.environ.get(TELEMETRY_DIR_ENV):
        return _active
    if not metrics.enabled():
        return None
    with _ensure_lock:
        if _active is not None:
            return _active
        pub = TelemetryPublisher().start()
        from . import recorder as _recorder

        if _recorder.get_recorder() is None:
            _recorder.FlightRecorder(
                directory=pub.directory, rank=pub.rank
            ).start()
            _recorder.install_excepthook()
        return pub
