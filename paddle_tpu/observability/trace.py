"""Causal trace contexts: trace_id/span_id propagation across threads/ranks.

Spans alone answer "what ran"; they cannot answer "where did THIS
request's 40 ms go" once work hops a thread (the serving scheduler, the
AsyncCheckpointer publisher, the embedding Prefetcher worker) or a rank
(heartbeat files, per-rank span exports). A :class:`TraceContext` is the
missing edge: an immutable ``(trace_id, span_id)`` pair naming a position
in one causal tree. While a context is *active* on a thread, every
``span()`` recorded there attaches ``trace_id``/``span_id``/``parent_id``
to its ring-buffer record — ``tools/trace_report.py`` reconstructs the
tree from export files alone, and ``tools/perf_report.py --merge``
stitches contexts stamped into heartbeat files across ranks.

Thread handoff is EXPLICIT (no ambient magic a worker thread could
inherit by accident): the producing thread calls :func:`capture`, ships
the context with the work item, and the consuming thread wraps the work
in ``with activate(ctx):``. Each in-flight span pushes its own child
context for the duration of its body, so nesting falls out of ordinary
``with`` scoping.

Kill-switch: the module rides the one metrics switch
(``PADDLE_TPU_MONITOR=0`` / ``set_enabled``) — when monitoring is off,
:func:`new_trace` returns ``None``, ``activate(None)`` is a no-op mask,
and spans record nothing, so tracing cannot outlive the kill-switch.
"""

from __future__ import annotations

import os
import threading

from . import metrics

_tls = threading.local()


def new_id() -> str:
    """A fresh 64-bit hex span/trace id (random: unique across ranks)."""
    return os.urandom(8).hex()


class TraceContext:
    """Immutable position in a trace: ``trace_id`` + the span to parent
    new work under (``span_id``; ``None`` = root position)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str | None = None):
        self.trace_id = trace_id
        self.span_id = span_id

    def child(self, span_id: str) -> "TraceContext":
        """The context a span's body runs under (same trace, new parent)."""
        return TraceContext(self.trace_id, span_id)

    def to_dict(self) -> dict:
        d = {"trace_id": self.trace_id}
        if self.span_id is not None:
            d["span_id"] = self.span_id
        return d

    def __eq__(self, other):
        return (
            isinstance(other, TraceContext)
            and self.trace_id == other.trace_id
            and self.span_id == other.span_id
        )

    def __hash__(self):
        return hash((self.trace_id, self.span_id))

    def __repr__(self):
        return f"TraceContext({self.trace_id!r}, {self.span_id!r})"


def new_trace() -> TraceContext | None:
    """Root context of a brand-new trace (``None`` when monitoring is
    off, so call sites can thread it through unconditionally)."""
    if not metrics.enabled():
        return None
    metrics.add("trace.traces_started")
    return TraceContext(new_id())


def current() -> TraceContext | None:
    """The calling thread's active context (None outside any trace)."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def capture() -> TraceContext | None:
    """Snapshot the active context for an explicit thread handoff: ship
    the return value with the work item and ``activate`` it on the
    consuming thread."""
    return current()


def ensure() -> TraceContext | None:
    """The active context, or a fresh trace when there is none."""
    return current() or new_trace()


def _push(ctx):
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(ctx)


def _pop():
    stack = getattr(_tls, "stack", None)
    if stack:
        stack.pop()


class _Activate:
    __slots__ = ("ctx",)

    def __init__(self, ctx):
        self.ctx = ctx

    def __enter__(self):
        _push(self.ctx)
        return self.ctx

    def __exit__(self, *exc):
        _pop()
        return False


def activate(ctx: TraceContext | None) -> _Activate:
    """Context manager installing ``ctx`` as the thread's active context
    — the consuming side of a :func:`capture` handoff. ``activate(None)``
    masks any outer context (spans inside record untraced), so handoff
    code never needs a conditional."""
    if ctx is not None and metrics.enabled():
        metrics.add("trace.activations")
    return _Activate(ctx)


#: package-level alias (``observability.current_trace()``): "current"
#: alone is too ambiguous a name to re-export from the package root
current_trace = current
