"""Host spans: named wall-clock regions in a bounded ring buffer.

`span("name")` is the app-level sibling of profiler.RecordEvent: where
RecordEvent only annotates an *active* jax.profiler capture, spans record
always (unless the monitor kill-switch is off) into a deque capped at
PADDLE_TPU_SPAN_BUFFER entries (default 4096) — old spans fall off, a
long-running trainer never grows memory.

Export goes through tools/timeline._ChromeTraceFormatter, so host spans
are ordinary Chrome-trace "X" events: load them alone (`chrome_trace()`)
or merged with a jax.profiler device capture
(`tools.timeline.Timeline(dir, include_host_spans=True)`) in one
Perfetto-loadable JSON.
"""

from __future__ import annotations

import collections
import functools
import os
import threading
import time

from . import metrics

try:
    # clamp: deque(maxlen=negative) raises; malformed env must not break
    # `import paddle_tpu`
    _MAX_SPANS = max(0, int(os.environ.get("PADDLE_TPU_SPAN_BUFFER", "4096")))
except ValueError:
    _MAX_SPANS = 4096
_lock = threading.Lock()
_spans: collections.deque = collections.deque(maxlen=_MAX_SPANS)


class _Span:
    """Context manager AND decorator recording one ring-buffer span."""

    __slots__ = ("name", "category", "args", "_wall_us", "_t0")

    def __init__(self, name, category="host", args=None):
        self.name = name
        self.category = category
        self.args = args or {}
        self._t0 = None

    def __enter__(self):
        if metrics.enabled():
            self._wall_us = time.time_ns() / 1e3
            self._t0 = time.perf_counter_ns()
        else:
            self._t0 = None
        return self

    def __exit__(self, *exc):
        if self._t0 is not None:
            dur_us = (time.perf_counter_ns() - self._t0) / 1e3
            rec = {
                "name": self.name,
                "cat": self.category,
                "ts": self._wall_us,
                "dur": dur_us,
                "tid": threading.get_ident(),
                "args": self.args,
            }
            with _lock:
                _spans.append(rec)
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with _Span(self.name, self.category, self.args):
                return fn(*args, **kwargs)

        return wrapper


def span(name: str, category: str = "host", **args) -> _Span:
    """``with span("executor.step", step=i): ...`` or ``@span("f")``."""
    return _Span(name, category, args)


def get_spans() -> list[dict]:
    with _lock:
        return list(_spans)


def span_count() -> int:
    with _lock:
        return len(_spans)


def reset() -> None:
    with _lock:
        _spans.clear()


def emit_into(fmt, pid: int = 0) -> None:
    """Write the buffered spans into a _ChromeTraceFormatter as process
    `pid`, one trace tid per host thread."""
    recs = get_spans()
    fmt.emit_pid("paddle_tpu host spans", pid)
    tids: dict[int, int] = {}
    for rec in recs:
        tid = tids.setdefault(rec["tid"], len(tids))
    for native_tid, tid in sorted(tids.items(), key=lambda kv: kv[1]):
        fmt.emit_tid(f"thread-{native_tid}", pid, tid)
    for rec in recs:
        fmt.emit_region(
            rec["ts"], rec["dur"], pid, tids[rec["tid"]], rec["cat"],
            rec["name"], rec["args"],
        )


def chrome_trace(pretty: bool = False) -> str:
    """Buffered spans alone as Chrome-trace JSON ("M" metadata + "X"
    duration events; chrome://tracing / Perfetto loadable)."""
    from ..tools.timeline import _ChromeTraceFormatter

    fmt = _ChromeTraceFormatter()
    emit_into(fmt, pid=0)
    return fmt.format_to_string(pretty)


def save_chrome_trace(path: str, pretty: bool = False) -> str:
    with open(path, "w") as f:
        f.write(chrome_trace(pretty))
    return path
