"""Host spans: named wall-clock regions in a bounded ring buffer.

`span("name")` is the app-level sibling of profiler.RecordEvent: where
RecordEvent only annotates an *active* jax.profiler capture, spans record
always (unless the monitor kill-switch is off) into a deque capped at
PADDLE_TPU_SPAN_BUFFER entries (default 4096) — old spans fall off, a
long-running trainer never grows memory.

Causal tracing (trace.py): when a TraceContext is active on the recording
thread, the span record additionally carries ``trace_id`` / ``span_id`` /
``parent_id`` and pushes its own child context while the body runs, so
nested spans — and spans on other threads holding a capture()/activate()
handoff of this context — chain into one reconstructible tree.
:func:`record` writes a span retrospectively (known duration, ended now)
for costs measured after the fact, e.g. a request's queue wait.

The kill-switch is the ONE metrics switch: every write path here consults
``metrics.enabled()`` (PADDLE_TPU_MONITOR=0 / set_enabled), never a local
flag, so spans and traces die with counters — not just when the buffer is
sized to zero.

Export goes through tools/timeline._ChromeTraceFormatter, so host spans
are ordinary Chrome-trace "X" events (trace ids ride in ``args``): load
them alone (`chrome_trace()`) or merged with a jax.profiler device
capture (`tools.timeline.Timeline(dir, include_host_spans=True)`) in one
Perfetto-loadable JSON.
"""

from __future__ import annotations

import collections
import functools
import os
import threading
import time

from . import metrics, trace

try:
    # clamp: deque(maxlen=negative) raises; malformed env must not break
    # `import paddle_tpu`
    _MAX_SPANS = max(0, int(os.environ.get("PADDLE_TPU_SPAN_BUFFER", "4096")))
except ValueError:
    _MAX_SPANS = 4096
_lock = threading.Lock()
_spans: collections.deque = collections.deque(maxlen=_MAX_SPANS)


class _Span:
    """Context manager AND decorator recording one ring-buffer span."""

    __slots__ = ("name", "category", "args", "_wall_us", "_t0", "_trace")

    def __init__(self, name, category="host", args=None):
        self.name = name
        self.category = category
        self.args = args or {}
        self._t0 = None
        self._trace = None  # (trace_id, span_id, parent_id) when traced

    @property
    def span_id(self):
        """This span's id once entered under an active TraceContext
        (None otherwise) — lets producers parent later work under it."""
        return self._trace[1] if self._trace else None

    def __enter__(self):
        self._trace = None
        if metrics.enabled():
            self._wall_us = time.time_ns() / 1e3
            self._t0 = time.perf_counter_ns()
            ctx = trace.current()
            if ctx is not None:
                sid = trace.new_id()
                self._trace = (ctx.trace_id, sid, ctx.span_id)
                trace._push(ctx.child(sid))
        else:
            self._t0 = None
        return self

    def __exit__(self, *exc):
        if self._t0 is not None:
            if self._trace is not None:
                trace._pop()
            dur_us = (time.perf_counter_ns() - self._t0) / 1e3
            rec = {
                "name": self.name,
                "cat": self.category,
                "ts": self._wall_us,
                "dur": dur_us,
                "tid": threading.get_ident(),
                "args": self.args,
            }
            if self._trace is not None:
                rec["trace_id"], rec["span_id"], rec["parent_id"] = \
                    self._trace
                metrics.add("trace.spans")
            with _lock:
                _spans.append(rec)
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with _Span(self.name, self.category, self.args):
                return fn(*args, **kwargs)

        return wrapper


def span(name: str, category: str = "host", **args) -> _Span:
    """``with span("executor.step", step=i): ...`` or ``@span("f")``."""
    return _Span(name, category, args)


def record(name, duration_s, category="host", ctx=None, args=None):
    """Retrospectively record a span that ENDED now and lasted
    ``duration_s`` — for costs only measurable after the fact (a
    request's queue wait, a batch slot's dispatch share). ``ctx`` parents
    the span (default: the thread's active context; pass a captured
    context to file it under another thread's trace). Returns the new
    span_id, or None when monitoring is off."""
    if not metrics.enabled():
        return None
    if ctx is None:
        ctx = trace.current()
    dur_us = max(0.0, float(duration_s)) * 1e6
    rec = {
        "name": name,
        "cat": category,
        "ts": time.time_ns() / 1e3 - dur_us,
        "dur": dur_us,
        "tid": threading.get_ident(),
        "args": dict(args or {}),
    }
    sid = None
    if ctx is not None:
        sid = trace.new_id()
        rec["trace_id"] = ctx.trace_id
        rec["span_id"] = sid
        rec["parent_id"] = ctx.span_id
        metrics.add("trace.spans")
    with _lock:
        _spans.append(rec)
    return sid


def get_spans() -> list[dict]:
    with _lock:
        return list(_spans)


def span_count() -> int:
    with _lock:
        return len(_spans)


def reset() -> None:
    with _lock:
        _spans.clear()


def emit_into(fmt, pid: int = 0) -> None:
    """Write the buffered spans into a _ChromeTraceFormatter as process
    `pid`, one trace tid per host thread. Trace ids (when present) ride
    in each event's args so export files alone reconstruct causality."""
    recs = get_spans()
    fmt.emit_pid("paddle_tpu host spans", pid)
    tids: dict[int, int] = {}
    for rec in recs:
        tid = tids.setdefault(rec["tid"], len(tids))
    for native_tid, tid in sorted(tids.items(), key=lambda kv: kv[1]):
        fmt.emit_tid(f"thread-{native_tid}", pid, tid)
    for rec in recs:
        args = rec["args"]
        if "trace_id" in rec:
            args = dict(args)
            args["trace_id"] = rec["trace_id"]
            args["span_id"] = rec["span_id"]
            if rec.get("parent_id") is not None:
                args["parent_id"] = rec["parent_id"]
        fmt.emit_region(
            rec["ts"], rec["dur"], pid, tids[rec["tid"]], rec["cat"],
            rec["name"], args,
        )


def chrome_trace(pretty: bool = False) -> str:
    """Buffered spans alone as Chrome-trace JSON ("M" metadata + "X"
    duration events; chrome://tracing / Perfetto loadable)."""
    from ..tools.timeline import _ChromeTraceFormatter

    fmt = _ChromeTraceFormatter()
    emit_into(fmt, pid=0)
    return fmt.format_to_string(pretty)


def save_chrome_trace(path: str, pretty: bool = False) -> str:
    with open(path, "w") as f:
        f.write(chrome_trace(pretty))
    return path
