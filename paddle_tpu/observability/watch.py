"""Live straggler / regression / SLO watcher over the telemetry stream.

A pod that is *slowly* going wrong never trips the resilience layer: a
straggling rank still beats its heartbeat, a 30% step-time regression
still converges, a serving endpoint blowing its p99 still answers. The
:class:`Watcher` follows the signals the rest of the stack already
publishes — heartbeat files (per-rank step counters), the
``executor.step_latency`` histogram, the ``serving.request_latency``
histogram — and turns excursions into structured ``watch.*`` findings
instead of log lines:

* **straggler** — the spread between the fastest and slowest rank's
  heartbeat step counter exceeds ``skew_steps`` (one finding per
  excursion; re-arms when the pod re-converges);
* **step_regression** — the mean step latency of the most recent poll
  window exceeds the best window seen so far by ``drift_tolerance``
  (catches slow decay AND sharp knees, not just absolute thresholds);
* **slo_breach** — the latency metric's per-window p99 (estimated from
  histogram bucket deltas via :func:`metrics.window_p99`) exceeds
  ``slo_p99_s``;
* **disk_pressure** — with ``storage_monitor=`` (a
  ``resilience.storage.StorageMonitor``), every per-root pressure-level
  escalation out of the monitor's hysteresis latch becomes one finding
  naming the root, the level, and the free bytes that tripped it.

With ``journal_dir=`` the watcher additionally runs in **timeline-reader
mode**: it follows the telemetry journals other processes publish
(:mod:`timeline`), replays their registry state, and raises the same
``straggler`` / ``slo_breach`` findings (detail ``source: "journal"``)
off the REMOTE state — per-rank step counters out of the journals play
the heartbeat role, and the cross-process p99 is reconstructed by
merging per-shard bucket state. No shared memory with the processes
being watched; only their shard files.

Each finding is a plain dict (kind, severity, detail, wall time) kept in
a bounded list, mirrored to the ``watch.findings`` observability table,
and counted as ``watch.findings`` / ``watch.findings.<kind>`` so a
``stats_report --require watch.`` proves the watcher was alive. Use
:meth:`Watcher.poll` from your own loop, or :meth:`start` for a daemon
polling thread. The whole module rides the metrics kill-switch.
"""

from __future__ import annotations

import os
import threading
import time

from . import metrics

__all__ = ["Watcher"]

_SEVERITY = {"straggler": "warning", "step_regression": "warning",
             "slo_breach": "error", "dead_process": "error",
             "disk_pressure": "error"}


def _hist_state(name):
    """(count, sum, cumulative buckets) of one histogram, or None."""
    h = metrics.get_histograms().get(name)
    if h is None:
        return None
    return h["count"], h["sum"], h["buckets"]


# the windowed-p99-from-bucket-deltas computation now lives in
# metrics.window_p99 (one shared helper; the brownout fallback and the
# fleet tooling call the same code) — this module-level alias keeps every
# historical call site of watch._window_p99 byte-for-byte unchanged
_window_p99 = metrics.window_p99


class Watcher:
    """Online watcher emitting structured ``watch.*`` findings.

    Pure-poll core (deterministic, testable): every :meth:`poll` reads
    the heartbeat dir + metric registry, updates ``watch.*`` gauges, and
    returns the NEW findings it raised. :meth:`start`/:meth:`stop` wrap
    poll in a daemon thread for live use.
    """

    def __init__(self, heartbeat_dir=None, skew_steps=2,
                 drift_tolerance=0.25, min_window=8, slo_p99_s=None,
                 step_metric="executor.step_latency",
                 latency_metric="serving.request_latency",
                 interval=1.0, max_findings=256, journal_dir=None,
                 dead_process_timeout=None, storage_monitor=None):
        self.heartbeat_dir = heartbeat_dir
        # storage fault domain: a resilience.storage.StorageMonitor whose
        # level-change events become disk_pressure findings (escalations
        # only — de-escalation is recovery, not a finding)
        self.storage_monitor = storage_monitor
        # timeline-reader mode: follow OTHER processes' telemetry
        # journals (timeline.TelemetryPublisher shards) and raise
        # straggler/slo_breach findings off their replayed state — no
        # shared memory with the processes being watched, only files
        self.journal_dir = journal_dir
        self.skew_steps = int(skew_steps)
        self.drift_tolerance = float(drift_tolerance)
        self.min_window = int(min_window)
        self.slo_p99_s = slo_p99_s
        self.step_metric = step_metric
        self.latency_metric = latency_metric
        self.interval = float(interval)
        self.findings: list[dict] = []
        self._max_findings = int(max_findings)
        self._lock = threading.Lock()
        # excursion latches: one finding per excursion, re-armed on recovery
        self._straggling = False
        self._breaching = False
        self._regressed = False
        self._step_prev = None  # (count, sum) at the last poll
        self._best_window_mean = None
        self._lat_prev = None  # (count, buckets) at the last poll
        # journal-mode state: one incremental follower per remote shard,
        # plus the merged-histogram window and its own excursion latches
        self._followers = {}
        self._journal_straggling = False
        self._journal_breaching = False
        self._journal_lat_prev = None
        # dead-process detection: a journal shard whose newest record
        # stamp goes stale past this threshold raises one finding
        # (latched per shard; a fresh write — the respawn — re-arms it)
        self.dead_process_timeout = (
            None if dead_process_timeout is None
            else float(dead_process_timeout)
        )
        self._dead_latched = set()
        self._thread = None
        self._stop = threading.Event()

    # -- finding plumbing --------------------------------------------------
    def _emit(self, kind, detail):
        finding = {
            "kind": kind,
            "severity": _SEVERITY.get(kind, "warning"),
            "detail": detail,
            "time": time.time(),
        }
        with self._lock:
            self.findings.append(finding)
            del self.findings[:-self._max_findings]
            table = list(self.findings[-32:])
        metrics.add("watch.findings")
        metrics.add(f"watch.findings.{kind}")
        metrics.set_table("watch.findings", {"findings": table})
        return finding

    # -- the three checks --------------------------------------------------
    def _check_straggler(self, new):
        from ..resilience.health import read_beat

        if not self.heartbeat_dir or not os.path.isdir(self.heartbeat_dir):
            return
        steps = {}
        for fn in sorted(os.listdir(self.heartbeat_dir)):
            if not fn.startswith("hb_rank") or ".tmp." in fn:
                continue
            beat = read_beat(os.path.join(self.heartbeat_dir, fn))
            if beat and "step" in beat:
                steps[int(beat.get("rank", len(steps)))] = int(beat["step"])
        if len(steps) < 2:
            return
        lead = max(steps.values())
        skew = lead - min(steps.values())
        metrics.set_gauge("watch.step_skew", skew)
        if skew > self.skew_steps:
            if not self._straggling:
                self._straggling = True
                lagging = sorted(
                    r for r, s in steps.items()
                    if lead - s > self.skew_steps
                )
                new.append(self._emit("straggler", {
                    "skew_steps": skew,
                    "lagging_ranks": lagging,
                    "steps": {str(r): s for r, s in sorted(steps.items())},
                }))
        else:
            self._straggling = False

    def _check_step_regression(self, new):
        state = _hist_state(self.step_metric)
        if state is None:
            return
        count, total, _ = state
        prev = self._step_prev
        self._step_prev = (count, total)
        if prev is None:
            return
        d_count, d_sum = count - prev[0], total - prev[1]
        if d_count < self.min_window:
            return  # not enough fresh steps for a stable window mean
        mean = d_sum / d_count
        best = self._best_window_mean
        if best is None or mean < best:
            self._best_window_mean = best = mean
        ratio = mean / best if best > 0 else 1.0
        metrics.set_gauge("watch.step_time_ratio", ratio)
        if ratio > 1.0 + self.drift_tolerance:
            if not self._regressed:
                self._regressed = True
                new.append(self._emit("step_regression", {
                    "window_mean_s": mean,
                    "best_window_mean_s": best,
                    "ratio": ratio,
                    "window_steps": d_count,
                    "metric": self.step_metric,
                }))
        else:
            self._regressed = False

    def _check_slo(self, new):
        if self.slo_p99_s is None:
            return
        state = _hist_state(self.latency_metric)
        if state is None:
            return
        count, _total, buckets = state
        prev = self._lat_prev
        self._lat_prev = (count, buckets)
        prev_buckets = prev[1] if prev else None
        prev_count = prev[0] if prev else 0
        if count - prev_count <= 0:
            return
        p99 = _window_p99(prev_buckets, buckets)
        if p99 is None:
            return
        metrics.set_gauge("watch.request_p99_s", p99)
        if p99 > float(self.slo_p99_s):
            if not self._breaching:
                self._breaching = True
                new.append(self._emit("slo_breach", {
                    "p99_s": p99,
                    "slo_p99_s": float(self.slo_p99_s),
                    "window_requests": count - prev_count,
                    "metric": self.latency_metric,
                }))
        else:
            self._breaching = False

    def _check_storage(self, new):
        """Disk-pressure findings off the storage monitor's poll: every
        per-root ESCALATION is one finding (the monitor's hysteresis is
        the latch — no event fires again until the level actually moves,
        so this check needs no latch of its own)."""
        if self.storage_monitor is None:
            return
        from ..resilience import storage as _storage

        info = self.storage_monitor.poll()
        for root, old, lvl in info["events"]:
            if lvl <= old:
                continue  # recovery: counted by the monitor, not a finding
            free = info["roots"][root]["free"]
            new.append(self._emit("disk_pressure", {
                "root": root,
                "level": _storage.LEVEL_NAMES[lvl],
                "previous": _storage.LEVEL_NAMES[old],
                "free_bytes": free,
            }))

    # -- the journal (remote-process) checks -------------------------------
    def _check_journals(self, new):
        from . import timeline

        if not self.journal_dir or not os.path.isdir(self.journal_dir):
            return
        for fn in sorted(os.listdir(self.journal_dir)):
            if not (fn.startswith("telemetry_rank")
                    and fn.endswith(".jsonl")):
                continue
            path = os.path.join(self.journal_dir, fn)
            fol = self._followers.get(path)
            if fol is None:
                fol = self._followers[path] = timeline.JournalFollower(path)
            fol.poll()
        shards = {
            os.path.basename(p): f.replay
            for p, f in self._followers.items()
            if f.replay.meta.get("seq") is not None
        }
        if not shards:
            return
        self._journal_straggler_check(shards, new)
        self._journal_slo_check(shards, new)
        self._journal_dead_check(shards, new)

    def _journal_straggler_check(self, shards, new):
        """Straggler detection with no heartbeat dir and no shared
        memory: the per-rank step counters replayed out of the remote
        journals play the heartbeat role."""
        steps = {}
        for name, replay in shards.items():
            counters = replay.state["counters"]
            step = counters.get("guard.steps",
                               counters.get("executor.run_steps"))
            if step is not None:
                steps[int(replay.meta.get("rank", len(steps)))] = int(step)
        if len(steps) < 2:
            return
        lead = max(steps.values())
        skew = lead - min(steps.values())
        metrics.set_gauge("watch.journal_step_skew", skew)
        if skew > self.skew_steps:
            if not self._journal_straggling:
                self._journal_straggling = True
                lagging = sorted(
                    r for r, s in steps.items()
                    if lead - s > self.skew_steps
                )
                new.append(self._emit("straggler", {
                    "source": "journal",
                    "skew_steps": skew,
                    "lagging_ranks": lagging,
                    "steps": {str(r): s for r, s in sorted(steps.items())},
                }))
        else:
            self._journal_straggling = False

    def _journal_slo_check(self, shards, new):
        if self.slo_p99_s is None:
            return
        per_shard = [
            replay.snapshot().get("histograms", {}).get(self.latency_metric)
            for replay in shards.values()
        ]
        per_shard = [h["buckets"] for h in per_shard if h]
        if not per_shard:
            return
        merged = metrics.merge_cumulative_buckets(per_shard)
        prev, self._journal_lat_prev = self._journal_lat_prev, merged
        count = merged[-1][1]
        prev_count = prev[-1][1] if prev else 0
        if count - prev_count <= 0:
            return
        p99 = _window_p99(prev, merged)
        if p99 is None:
            return
        metrics.set_gauge("watch.journal_p99_s", p99)
        if p99 > float(self.slo_p99_s):
            if not self._journal_breaching:
                self._journal_breaching = True
                new.append(self._emit("slo_breach", {
                    "source": "journal",
                    "p99_s": p99,
                    "slo_p99_s": float(self.slo_p99_s),
                    "window_requests": count - prev_count,
                    "metric": self.latency_metric,
                    "shards": sorted(shards),
                }))
        else:
            self._journal_breaching = False

    def _journal_dead_check(self, shards, new):
        """Dead-process detection from OUTSIDE the blast radius: the
        publisher bumps ``telemetry.publishes`` on every publish, so a
        live process's shard stamp advances every interval even when the
        workload is idle — a stamp stale past ``dead_process_timeout``
        means the process stopped, not that it went quiet. One finding
        per death (latched per shard); the respawned process reopens the
        shard fresh, the stamp advances, and the latch re-arms."""
        if self.dead_process_timeout is None:
            return
        now = time.time()
        for name in sorted(shards):
            replay = shards[name]
            t = replay.meta.get("t")
            if t is None:
                continue
            stale = now - float(t)
            if stale > self.dead_process_timeout:
                if name not in self._dead_latched:
                    self._dead_latched.add(name)
                    new.append(self._emit("dead_process", {
                        "source": "journal",
                        "shard": name,
                        "rank": replay.meta.get("rank"),
                        "pid": replay.meta.get("pid"),
                        "stale_s": stale,
                        "timeout_s": self.dead_process_timeout,
                    }))
            else:
                self._dead_latched.discard(name)
        metrics.set_gauge(
            "watch.dead_processes", float(len(self._dead_latched))
        )

    # -- public surface ----------------------------------------------------
    @property
    def breaching(self):
        """True while the SLO excursion latch is set (one ``slo_breach``
        finding was raised and p99 has not yet recovered) — the level
        signal consumers like ``serving.brownout.BrownoutController``
        need between the edge-triggered findings."""
        return self._breaching

    def poll(self):
        """Run every check once; returns the list of NEW findings."""
        if not metrics.enabled():
            return []
        metrics.add("watch.polls")
        new: list[dict] = []
        self._check_straggler(new)
        self._check_step_regression(new)
        self._check_slo(new)
        self._check_storage(new)
        self._check_journals(new)
        return new

    def start(self):
        """Poll on a daemon thread every ``interval`` seconds."""
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="obs-watcher"
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.interval * 4 + 1.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.poll()
            except Exception:
                pass  # a broken check must not kill the monitor thread
