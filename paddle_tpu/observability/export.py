"""Exporters: structured JSON snapshot and Prometheus text exposition.

`snapshot()` is the one authoritative read: every counter, gauge and
histogram plus the span-buffer depth, in plain JSON types so `dump(path)`
is loadable by anything (tools/stats_report.py pretty-prints it).
`prometheus_text()` renders the same state in the text exposition format
(metric names sanitized to [a-zA-Z0-9_:], histogram buckets cumulative
with the canonical _bucket/_sum/_count triple) for scraping.
"""

from __future__ import annotations

import json
import re

from . import metrics, spans

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    name = _NAME_RE.sub("_", name)
    return name if not name[:1].isdigit() else "_" + name


def snapshot() -> dict:
    """Structured view of every metric: {"counters", "gauges",
    "histograms", "span_count"}."""
    snap = {
        "counters": metrics.get_counters(),
        "gauges": metrics.get_gauges(),
        "histograms": metrics.get_histograms(),
        "span_count": spans.span_count(),
    }
    tables = metrics.get_tables()
    if tables:  # only present when something published one (back-compat)
        snap["tables"] = tables
    return snap


def dump(path: str, pretty: bool = True) -> str:
    """Write the JSON snapshot to `path`; returns the path."""
    with open(path, "w") as f:
        json.dump(snapshot(), f, indent=2 if pretty else None, sort_keys=True)
    return path


def prometheus_text() -> str:
    """Prometheus text exposition of the current registry state."""
    out = []
    snap = snapshot()
    for name, value in sorted(snap["counters"].items()):
        pn = _prom_name(name)
        out.append(f"# TYPE {pn} counter")
        out.append(f"{pn} {value}")
    for name, value in sorted(snap["gauges"].items()):
        pn = _prom_name(name)
        out.append(f"# TYPE {pn} gauge")
        out.append(f"{pn} {value}")
    for name, h in sorted(snap["histograms"].items()):
        pn = _prom_name(name)
        out.append(f"# TYPE {pn} histogram")
        for le, cum in h["buckets"]:
            le_s = le if isinstance(le, str) else repr(float(le))
            out.append(f'{pn}_bucket{{le="{le_s}"}} {cum}')
        out.append(f"{pn}_sum {h['sum']}")
        out.append(f"{pn}_count {h['count']}")
    return "\n".join(out) + "\n"
