"""Crash flight recorder: the last N seconds of telemetry, dumped on death.

The journal (:mod:`timeline`) answers "what were the metrics when rank 3
died"; this module answers "what was it DOING". A :class:`FlightRecorder`
keeps a rolling window of the observable state — recent spans out of the
span ring, periodic registry delta samples, the watcher's structured
findings, the trace ids active in the window — and writes it out as a
post-mortem bundle when something goes wrong:

========================  =================================================
trigger                   hook site
========================  =================================================
``exception``             :func:`install_excepthook` (sys + threading)
``watchdog_stall``        ``resilience.health.StepWatchdog`` fire path
``train_rollback``        ``resilience.guard.TrainGuard._skip_bad_step``
``preempt_drain``         ``TrainGuard._finalize_preemption`` (SIGTERM)
``serving_drain``         ``serving.router.Server.drain``
``breaker_open``          ``serving.replica.ReplicaSet._on_failure``
========================  =================================================

Each trigger writes ``{dir}/flight_rank{K}.{trigger}.json``. SIGKILL
cannot be hooked, so the recorder is ALSO a black box: a daemon thread
re-publishes the current window to ``{dir}/flight_rank{K}.json``
(through ``io._atomic_write`` — full durability contract, never torn)
every ``interval`` seconds — after a kill -9 the last
atomically-published window is still on disk, holding the spans and
findings from just before death.

Trigger dumps are a bounded ring: a long-running fleet that rolls back,
drains, and trips breakers for weeks would otherwise accrete bundles
without limit. After every dump the recorder prunes its own rank's
trigger bundles oldest-first down to ``PADDLE_TPU_FLIGHT_KEEP`` (default
8); the black box is never pruned. Under storage pressure the ladder
calls :meth:`FlightRecorder.suspend_disk` — sampling continues (the
in-memory window stays fresh for an explicit ``dump()``) but the
periodic black-box publishing stops until :meth:`resume_disk`.

Hook sites call :func:`flight_dump`, a module-level no-op until a
recorder is installed — zero cost on the default path, and the whole
module rides the ``PADDLE_TPU_MONITOR`` kill-switch (no thread, no
files when disabled).
"""

from __future__ import annotations

import collections
import json
import os
import re
import sys
import threading
import time
import traceback

from . import metrics, spans, timeline, trace

__all__ = [
    "FLIGHT_KEEP_ENV",
    "FlightRecorder",
    "flight_dump",
    "flight_keep",
    "get_recorder",
    "install",
    "install_excepthook",
    "uninstall",
]

FLIGHT_KEEP_ENV = "PADDLE_TPU_FLIGHT_KEEP"
_DEFAULT_FLIGHT_KEEP = 8


def flight_keep():
    """Trigger-bundle ring size (``PADDLE_TPU_FLIGHT_KEEP``, default 8)."""
    try:
        return max(1, int(os.environ.get(
            FLIGHT_KEEP_ENV, _DEFAULT_FLIGHT_KEEP
        )))
    except ValueError:
        return _DEFAULT_FLIGHT_KEEP


class FlightRecorder:
    """Rolling window of spans / metric deltas / findings / trace ids."""

    def __init__(self, directory=None, rank=None, window_s=30.0,
                 interval=1.0, max_samples=256):
        if directory is None:
            directory = os.environ.get(timeline.TELEMETRY_DIR_ENV)
        if directory is None:
            raise ValueError(
                "FlightRecorder needs a directory (arg or "
                f"{timeline.TELEMETRY_DIR_ENV} env)"
            )
        if rank is None:
            rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.directory = directory
        self.rank = int(rank)
        self.window_s = float(window_s)
        self.interval = float(interval)
        self.dumps = 0
        # periodic registry-delta samples: the "metric deltas" leg of the
        # window, sharing the journal's delta encoder so a bundle sample
        # and a journal record read the same
        self._samples = collections.deque(maxlen=int(max_samples))
        self._prev = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._paused = threading.Event()
        self._disk_suspended = threading.Event()
        self._thread = None

    @property
    def path(self):
        """The black-box bundle (atomically re-published every interval)."""
        return os.path.join(self.directory, f"flight_rank{self.rank}.json")

    # -- lifecycle ---------------------------------------------------------
    def start(self, register=True):
        if not metrics.enabled():
            return self
        os.makedirs(self.directory, exist_ok=True)
        # this rank's temp residue from a dead predecessor (the dir is
        # shared with sibling ranks mid-publish, hence the prefix filter)
        from .. import io as _io

        _io.sweep_stale_tmp(
            self.directory, prefix=f"flight_rank{self.rank}"
        )
        if register:
            install(self)
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="obs-flightrec"
            )
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.interval * 4 + 1.0)
        if get_recorder() is self:
            uninstall()

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False

    def pause(self):
        self._paused.set()

    def resume(self):
        self._paused.clear()

    def suspend_disk(self):
        """Storage HARD rung: keep sampling the window in memory, stop
        the periodic black-box publishing. An explicit ``dump()`` still
        writes — a CRITICAL post-mortem outranks the bytes it costs."""
        self._disk_suspended.set()

    def resume_disk(self):
        self._disk_suspended.clear()

    # -- the window --------------------------------------------------------
    def sample(self):
        """Fold one registry-delta sample into the window (called on the
        cadence thread; callable directly from a step loop too)."""
        if not metrics.enabled() or self._paused.is_set():
            return None
        cur = timeline._registry_state()
        with self._lock:
            prev, self._prev = self._prev, cur
            if prev is None:
                return None
            body, regressed = timeline._delta(prev, cur)
            if body is None and not regressed:
                return None
            rec = {"t": time.time()}
            rec.update(body or {"rebased": True})
            self._samples.append(rec)
            now = time.time()
            while self._samples and (
                now - self._samples[0]["t"] > self.window_s
            ):
                self._samples.popleft()
            return rec

    def window(self, trigger="periodic", exc=None, detail=None):
        """The current bundle dict: everything observable from the last
        ``window_s`` seconds."""
        now = time.time()
        floor_us = (now - self.window_s) * 1e6
        win_spans = [
            s for s in spans.get_spans() if s["ts"] >= floor_us
        ]
        trace_ids = sorted({
            s["trace_id"] for s in win_spans if "trace_id" in s
        })
        ctx = trace.current()
        if ctx is not None and ctx.trace_id not in trace_ids:
            trace_ids.append(ctx.trace_id)
        findings = (
            metrics.get_tables().get("watch.findings") or {}
        ).get("findings") or []
        bundle = {
            "trigger": trigger,
            "t": now,
            "rank": self.rank,
            "pid": os.getpid(),
            "window_s": self.window_s,
            "spans": win_spans,
            "trace_ids": trace_ids,
            "findings": [
                f for f in findings
                if now - f.get("time", now) <= self.window_s
            ],
            "deltas": list(self._samples),
            "counters": metrics.get_counters(),
            "gauges": metrics.get_gauges(),
        }
        stamp = timeline.journal_stamp()
        if stamp:
            bundle["journal"] = stamp
        if detail:
            bundle["detail"] = detail
        if exc is not None:
            bundle["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc),
                "traceback": traceback.format_exception(
                    type(exc), exc, exc.__traceback__
                ),
            }
        return bundle

    # -- dumping -----------------------------------------------------------
    def _publish(self, bundle, path):
        from .. import io as _io

        payload = json.dumps(bundle, default=str).encode()
        _io._atomic_write(
            path, lambda f: f.write(payload), estimated_size=len(payload)
        )
        return path

    def _prune_ring(self):
        """Drop this rank's oldest trigger bundles beyond the ring size.
        The black box (no trigger infix) is exempt; sibling ranks' files
        are theirs to prune."""
        keep = flight_keep()
        pat = re.compile(rf"^flight_rank{self.rank}\..+\.json$")
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return
        dumps = []
        for fn in entries:
            if not pat.match(fn) or ".tmp." in fn:
                continue
            p = os.path.join(self.directory, fn)
            try:
                dumps.append((os.path.getmtime(p), p))
            except OSError:
                continue
        dumps.sort(reverse=True)  # newest first
        for _mtime, p in dumps[keep:]:
            try:
                os.unlink(p)
                metrics.add("telemetry.flight_pruned")
            except OSError:
                pass

    def dump(self, trigger, exc=None, detail=None):
        """Write the post-mortem bundle for `trigger`; returns its path
        (and refreshes the black box so the two never disagree)."""
        if not metrics.enabled():
            return None
        self.sample()
        bundle = self.window(trigger=trigger, exc=exc, detail=detail)
        self.dumps += 1
        metrics.add("telemetry.flight_dumps")
        metrics.add(f"telemetry.flight_dumps.{trigger}")
        path = os.path.join(
            self.directory, f"flight_rank{self.rank}.{trigger}.json"
        )
        self._publish(bundle, path)
        self._publish(bundle, self.path)
        self._prune_ring()
        return path

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.sample()
                if not self._disk_suspended.is_set():
                    self._publish(self.window(), self.path)
            except Exception:
                pass  # a broken publish must not kill the black box


# -- process-global wiring ---------------------------------------------------
_recorder: FlightRecorder | None = None


def install(recorder):
    """Make `recorder` the process-global flight recorder the hook sites
    dump through."""
    global _recorder
    _recorder = recorder
    return recorder


def uninstall():
    global _recorder
    _recorder = None


def get_recorder():
    return _recorder


def flight_dump(trigger, exc=None, detail=None):
    """Dump the installed recorder's window for `trigger`; a safe no-op
    (None) when no recorder is installed or monitoring is off — the form
    every hook site calls so instrumented code paths never grow a hard
    dependency on the recorder being configured."""
    rec = _recorder
    if rec is None:
        return None
    try:
        return rec.dump(trigger, exc=exc, detail=detail)
    except Exception:
        return None  # a post-mortem must never mask the original failure


_hooks_installed = False


def install_excepthook():
    """Chain the unhandled-exception triggers (``sys.excepthook`` and
    ``threading.excepthook``) in front of the existing hooks. Idempotent."""
    global _hooks_installed
    if _hooks_installed:
        return
    _hooks_installed = True
    prev_sys = sys.excepthook
    prev_thread = threading.excepthook

    def _sys_hook(exc_type, exc, tb):
        flight_dump("exception", exc=exc)
        prev_sys(exc_type, exc, tb)

    def _thread_hook(args):
        flight_dump(
            "exception", exc=args.exc_value,
            detail={"thread": getattr(args.thread, "name", None)},
        )
        prev_thread(args)

    sys.excepthook = _sys_hook
    threading.excepthook = _thread_hook
