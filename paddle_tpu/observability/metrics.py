"""Metric primitives: counters, gauges, histograms, timers.

The registry is process-global and thread-safe (one lock; every public
entry point is a handful of dict ops under it). The whole subsystem is
default-on and cheap; setting ``PADDLE_TPU_MONITOR=0`` in the environment
turns every hook into an early-return no-op (the reference's STAT_ADD
macros compiled out the same way under WITH_PROFILER=OFF).

Histograms follow the Prometheus model: fixed upper-bound buckets plus
count/sum, extended with min/max because a snapshot without them cannot
answer "was there one terrible step?". Bucket edges are *inclusive*
(``value <= le`` lands in the ``le`` bucket); snapshots report cumulative
bucket counts so the Prometheus exporter is a straight dump.
"""

from __future__ import annotations

import bisect
import functools
import os
import threading
import time

# latency-oriented default edges, in seconds (sub-ms compile-cache hits up
# to multi-second cold compiles); generic value histograms can pass their own
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


def _env_enabled() -> bool:
    return os.environ.get("PADDLE_TPU_MONITOR", "1").lower() not in (
        "0", "false", "off",
    )


_enabled = _env_enabled()
_lock = threading.Lock()
_counters: dict[str, int] = {}
_gauges: dict[str, float] = {}
_histograms: dict[str, "_Histogram"] = {}
# structured tables (plain-JSON dicts, last value wins): richer artifacts a
# scalar cannot carry — e.g. the executor publishes the latest per-op cost
# attribution as "perf.cost_table" (tools/stats_report.py --top-ops)
_tables: dict[str, dict] = {}


def enabled() -> bool:
    return _enabled


def set_enabled(flag: bool | None) -> None:
    """Toggle the whole subsystem; ``None`` re-reads PADDLE_TPU_MONITOR."""
    global _enabled
    _enabled = _env_enabled() if flag is None else bool(flag)


class _Histogram:
    __slots__ = ("bounds", "bucket_counts", "count", "total", "min", "max")

    def __init__(self, buckets):
        self.bounds = tuple(sorted(float(b) for b in buckets))
        self.bucket_counts = [0] * (len(self.bounds) + 1)  # +1 = +Inf
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def observe(self, value: float) -> None:
        self.bucket_counts[bisect.bisect_left(self.bounds, value)] += 1
        self.count += 1
        self.total += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value

    def to_dict(self) -> dict:
        cum, buckets = 0, []
        for le, c in zip(self.bounds, self.bucket_counts):
            cum += c
            buckets.append([le, cum])
        buckets.append(["+Inf", self.count])
        return {
            "count": self.count,
            "sum": self.total,
            "min": self.min if self.count else None,
            "max": self.max if self.count else None,
            "buckets": buckets,
        }


# -- write side -------------------------------------------------------------
def add(name: str, value: int = 1) -> None:
    """Bump the monotonic counter `name` (reference STAT_ADD)."""
    if not _enabled:
        return
    with _lock:
        _counters[name] = _counters.get(name, 0) + int(value)


def set_gauge(name: str, value: float) -> None:
    """Write the gauge `name` (last value wins)."""
    if not _enabled:
        return
    with _lock:
        _gauges[name] = float(value)


def observe(name: str, value: float, buckets=None) -> None:
    """Record `value` into the histogram `name` (created on first use;
    `buckets` only takes effect at creation)."""
    if not _enabled:
        return
    with _lock:
        h = _histograms.get(name)
        if h is None:
            h = _histograms[name] = _Histogram(buckets or DEFAULT_BUCKETS)
        h.observe(float(value))


def drop_gauges(prefix: str) -> None:
    """Remove every gauge whose name starts with `prefix`. For publishers
    whose gauge SET varies with the source (e.g. the executor's
    per-op-family ``perf.family_time.*``): dropping before re-publishing
    keeps gauges from a previous executable from surviving as stale."""
    with _lock:
        for k in [k for k in _gauges if k.startswith(prefix)]:
            del _gauges[k]


def set_table(name: str, table: dict) -> None:
    """Publish the structured table `name` (plain JSON types; last value
    wins — snapshots carry it under "tables")."""
    if not _enabled:
        return
    with _lock:
        _tables[name] = table


def drop_tables(prefix: str) -> None:
    """Remove every table whose name starts with `prefix` — the table
    analogue of :func:`drop_gauges`, for publishers whose table describes
    ONE source (e.g. the executor's per-executable
    ``perf.step_attribution``): dropping on source switch keeps a stale
    table from being read as live for the new source."""
    with _lock:
        for k in [k for k in _tables if k.startswith(prefix)]:
            del _tables[k]


class _Timed:
    """Context manager AND decorator: wall time -> histogram `name`."""

    __slots__ = ("name", "buckets", "_t0")

    def __init__(self, name, buckets=None):
        self.name = name
        self.buckets = buckets
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter() if _enabled else None
        return self

    def __exit__(self, *exc):
        if self._t0 is not None:
            observe(self.name, time.perf_counter() - self._t0, self.buckets)
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with _Timed(self.name, self.buckets):
                return fn(*args, **kwargs)

        return wrapper


def timed(name: str, buckets=None) -> _Timed:
    """``with timed("executor.step_latency"): ...`` or ``@timed("f")``."""
    return _Timed(name, buckets)


def window_p99(prev_buckets, cur_buckets, q=0.99):
    """p99 (or `q`-quantile) upper-bound estimate from the bucket-count
    delta between two cumulative-bucket snapshots — the one shared
    windowed-quantile primitive (the Watcher's SLO check, the brownout
    controller's watcher-less fallback, fleet_report's cross-process p99
    and the Watcher's journal mode all call this, so their answers agree
    by construction). Both sides are cumulative Prometheus buckets
    (``[[le, cum], ..., ["+Inf", count]]``); per-bucket subtraction
    yields the window's cumulative counts directly; ``prev_buckets=None``
    treats the window as all of `cur_buckets`. A quantile landing in
    +Inf reports the largest finite edge x2 — an upper bound is the
    conservative answer an SLO check wants. None when the window saw no
    observations."""
    prev = {str(le): c for le, c in (prev_buckets or [])}
    deltas = [(le, cum - prev.get(str(le), 0)) for le, cum in cur_buckets]
    total = deltas[-1][1] if deltas else 0
    if total <= 0:
        return None
    target = q * total
    finite = [float(le) for le, _ in deltas if not isinstance(le, str)]
    for le, cum_d in deltas:
        if cum_d >= target:
            if isinstance(le, str):  # +Inf bucket
                return (max(finite) * 2.0) if finite else float("inf")
            return float(le)
    return (max(finite) * 2.0) if finite else float("inf")


def merge_cumulative_buckets(bucket_lists):
    """Merge cumulative Prometheus bucket lists from SEVERAL histograms
    (e.g. one per process) into one cumulative list over the union of
    their edges. Each input's cumulative count at a foreign edge is its
    count at its own largest edge <= that edge — exact for the step
    function a cumulative histogram is. The merged list feeds
    :func:`window_p99` directly: cross-process quantiles reconstructed
    from per-process bucket state."""
    lists = [b for b in bucket_lists if b]
    finite = sorted({
        float(le) for b in lists for le, _ in b if not isinstance(le, str)
    })
    merged = []
    for le in finite:
        total = 0
        for b in lists:
            cum = 0
            for ble, bcum in b:
                if isinstance(ble, str) or float(ble) > le:
                    break
                cum = bcum
            total += cum
        merged.append([le, total])
    merged.append(["+Inf", sum(b[-1][1] for b in lists)] if lists
                  else ["+Inf", 0])
    return merged


# -- read side --------------------------------------------------------------
def get_counters() -> dict[str, int]:
    with _lock:
        return dict(_counters)


def get_gauges() -> dict[str, float]:
    with _lock:
        return dict(_gauges)


def get_histograms() -> dict[str, dict]:
    with _lock:
        return {k: h.to_dict() for k, h in _histograms.items()}


def get_tables() -> dict[str, dict]:
    with _lock:
        return dict(_tables)


def reset() -> None:
    with _lock:
        _counters.clear()
        _gauges.clear()
        _histograms.clear()
        _tables.clear()
