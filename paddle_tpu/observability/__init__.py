"""Runtime observability: counters/gauges/histograms, host spans, exporters.

Grown from the seed's `monitor.py` two-counter registry (kept as a
compatible facade) into the telemetry layer a TPU training stack needs to
diagnose "fast as the hardware allows": the executor records a
step-latency histogram, per-program compile time and executable-cache
hits/misses/evictions; the dataloader records batch wait time and queue
depth; the collective/SPMD/pipeline layers record op counts and payload
bytes by kind; Pallas kernel entry points record invocation counts.

Reading it out:
  * ``snapshot()`` / ``dump(path)`` — structured JSON (pretty-print with
    ``tools/stats_report.py``);
  * ``prometheus_text()`` — text exposition for scraping;
  * ``chrome_trace()`` / ``tools.timeline.Timeline(dir,
    include_host_spans=True)`` — host spans as Chrome-trace JSON, alone or
    merged with a jax.profiler device capture;
  * the telemetry plane (PR 16) — ``TelemetryPublisher`` journals registry
    deltas to per-process shards that outlive the process
    (``PADDLE_TPU_TELEMETRY_DIR``; ``tools/fleet_report.py`` merges them),
    and ``FlightRecorder`` keeps a rolling last-N-seconds window dumped as
    a post-mortem bundle on crash triggers.

Kill-switch: ``PADDLE_TPU_MONITOR=0`` in the environment makes every hook
a no-op (``set_enabled`` flips it at runtime; ``set_enabled(None)``
re-reads the env). Per-op timing tables and traffic counters here are the
raw features learned TPU cost models consume (PAPERS.md: "A Learned
Performance Model for TPUs", "Operator Fusion in XLA").

Canonical metric names are documented in README.md §Observability.
"""

from __future__ import annotations

from . import (  # noqa: F401
    export,
    metrics,
    recorder,
    spans,
    timeline,
    trace,
    watch,
)
from .export import dump, prometheus_text, snapshot  # noqa: F401
from .recorder import (  # noqa: F401
    FlightRecorder,
    flight_dump,
    install_excepthook,
)
from .timeline import (  # noqa: F401
    JournalFollower,
    TelemetryPublisher,
    ensure_publisher,
    journal_stamp,
    replay_journal,
)
from .trace import (  # noqa: F401
    TraceContext,
    activate,
    capture,
    current_trace,
    new_trace,
)
from .watch import Watcher  # noqa: F401
from .metrics import (  # noqa: F401
    DEFAULT_BUCKETS,
    add,
    enabled,
    drop_gauges,
    drop_tables,
    get_counters,
    get_gauges,
    get_histograms,
    get_tables,
    merge_cumulative_buckets,
    observe,
    set_enabled,
    set_gauge,
    set_table,
    timed,
    window_p99,
)
from .spans import (  # noqa: F401
    chrome_trace,
    get_spans,
    record,
    save_chrome_trace,
    span,
    span_count,
)


def reset() -> None:
    """Clear every counter/gauge/histogram and the span buffer."""
    metrics.reset()
    spans.reset()
