"""Process-level flag/config system.

Reference: 26 core gflags in platform/flags.cc:33-471, initialized from
FLAGS_* env vars via core.init_gflags (pybind.cc:1529) and read/written at
runtime through global_value_getter_setter.cc, exposed to Python as
fluid.set_flags / fluid.get_flags.

Same contract here: flags declare a name + default + doc; FLAGS_<name> env
vars override defaults at import; set_flags/get_flags read-write at runtime.
Flags that controlled CUDA allocator/stream behavior have no TPU meaning
and are intentionally not declared — XLA owns memory and scheduling.
"""

from __future__ import annotations

import os

_FLAGS: dict[str, object] = {}
_DOCS: dict[str, str] = {}


def _declare(name, default, doc):
    env = os.environ.get("FLAGS_" + name)
    if env is not None:
        if isinstance(default, bool):
            value = env.lower() in ("1", "true", "yes", "on")
        elif isinstance(default, int):
            value = int(env)
        elif isinstance(default, float):
            value = float(env)
        else:
            value = env
    else:
        value = default
    _FLAGS[name] = value
    _DOCS[name] = doc


# --- declared flags (TPU-meaningful subset of platform/flags.cc) -----------
_declare(
    "check_nan_inf", False,
    "After every op, scan float outputs for NaN/Inf inside the compiled "
    "step and raise host-side naming the first offending op "
    "(reference flags.cc:44 -> details/nan_inf_utils_detail.cc).",
)
_declare(
    "op_provenance", True,
    "Record the user code location creating each op so trace-time errors "
    "name the Python line (reference framework/op_call_stack.cc).",
)
_declare(
    "paddle_tpu_prng", "",
    "PRNG implementation for per-step keys ('rbg'/'threefry2x32'); empty = "
    "rbg on TPU, threefry2x32 elsewhere (core/random.py).",
)
_declare(
    "paddle_tpu_pallas_layer_norm", False,
    "Route layer_norm through the standalone Pallas kernel "
    "(kernels/layer_norm.py). Off by default: on BERT-style models XLA's "
    "fused jnp formulation wins because the custom call blocks fusion with "
    "the residual add feeding each LN.",
)
_declare(
    "eager_delete_tensor_gb", 0.0,
    "Accepted for parity; XLA buffer assignment subsumes eager deletion "
    "(reference flags.cc eager_delete_tensor_gb).",
)
_declare(
    "benchmark", False,
    "Accepted for parity; per-op timing comes from the profiler module "
    "instead (reference flags.cc:33).",
)


def get_flags(flags):
    """fluid.get_flags parity: str or list -> {name: value}."""
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for f in flags:
        if f.startswith("FLAGS_"):
            f = f[len("FLAGS_"):]
        if f not in _FLAGS:
            raise ValueError(f"unknown flag {f!r}")
        out["FLAGS_" + f] = _FLAGS[f]
    return out


def set_flags(flags_dict):
    """fluid.set_flags parity: {\"FLAGS_name\": value}."""
    for k, v in flags_dict.items():
        name = k[len("FLAGS_"):] if k.startswith("FLAGS_") else k
        if name not in _FLAGS:
            raise ValueError(f"unknown flag {name!r}")
        _FLAGS[name] = v


def flag(name):
    return _FLAGS[name]


def flag_docs():
    return dict(_DOCS)
