"""Control-flow ops: sub-block programs lowered to XLA structured control
flow.

Reference parity: paddle/fluid/operators/controlflow/ (~3.5k LoC:
while_op.cc, conditional_block_op.cc, recurrent_op.cc) and the grad variants
(while_grad, conditional_block_grad, recurrent_grad) synthesized by
backward.py:843's sub-block recursion.

TPU-native re-design: a sub-block is not interpreted op-by-op against a
Scope — its ops are *traced into* lax.cond / lax.while_loop / lax.scan
inside the same XLA computation as the rest of the program, so the loop body
is compiled once, fused, and runs entirely on device (the reference's
while_op re-entered the C++ executor per iteration, executor.cc:432).

Gradients: `cond` and `scan_block` are ordinary differentiable emitters —
append_backward's generic __vjp__ replays them under jax.vjp, and JAX's
reverse-mode through lax.cond/lax.scan produces exactly the structured grad
programs the reference hand-built (conditional_block_grad / the
recurrent_grad backward scan). `while` (data-dependent trip count) is
non-differentiable, as reverse-mode through an unbounded while requires
taping — the reference's while_grad relied on per-iteration scope stacks;
here the differentiable-loop story is scan_block (use StaticRNN for training
loops, While for inference-style loops).

Carried-state contract (enforced by the Python layer in
layers/control_flow.py): every var written in the sub-block that pre-exists
outside it is carried; shapes/dtypes must be loop-invariant (XLA static
shapes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.registry import register_op, run_op


def _sub_block(ctx, op, attr_name="sub_block"):
    if ctx.program is None:
        raise RuntimeError(
            f"op {op.type!r} needs a Program on the EmitContext to resolve "
            "its sub-block; control flow is only available through the "
            "Executor (not the eager tracer — use python control flow there)"
        )
    return ctx.program.blocks[op.attr(attr_name)]


def _run_block(ctx, block, env):
    for sub_op in block.ops:
        run_op(ctx, sub_op, env)
    return env


def _loop_ctx(ctx, iteration):
    """Fold the iteration index into the RNG stream so dropout masks vary
    across loop iterations (the executor already folds the step)."""
    if ctx.step_key is None:
        return ctx
    return ctx.with_key(jax.random.fold_in(ctx.step_key, iteration))


def _cond_infer(block, inputs, attrs):
    prog = block.program
    tb = prog.blocks[attrs["true_block"]]
    specs = []
    for n in attrs["true_out_names"]:
        v = tb.var(n)
        specs.append((tuple(v.shape or ()), v.dtype))
    return {"Out": specs}


@register_op(
    "cond", inputs=["Cond", "TrueIn", "FalseIn"], outputs=["Out"],
    infer_shape=_cond_infer,
)
def _cond(ctx, op, ins):
    """lax.cond over two sub-blocks (reference conditional_block_op.cc).

    TrueIn/FalseIn: external reads of each branch, in attr-recorded order
    (true_in_names / false_in_names). Both branches must produce outputs of
    identical shape/dtype (checked at build time by layers.cond)."""
    pred = ins["Cond"][0].reshape(()).astype(bool)
    t_names = op.attr("true_in_names")
    f_names = op.attr("false_in_names")
    t_vals = ins.get("TrueIn", [])
    f_vals = ins.get("FalseIn", [])

    def make_branch(block_idx, in_names, out_names, vals_idx):
        blk = ctx.program.blocks[block_idx]

        def branch(operands):
            env = dict(zip(in_names, operands[vals_idx]))
            _run_block(ctx, blk, env)
            return tuple(env[n] for n in out_names)

        return branch

    true_f = make_branch(
        op.attr("true_block"), t_names, op.attr("true_out_names"), 0
    )
    false_f = make_branch(
        op.attr("false_block"), f_names, op.attr("false_out_names"), 1
    )
    outs = lax.cond(pred, true_f, false_f, (tuple(t_vals), tuple(f_vals)))
    return {"Out": list(outs)}


def _while_infer(block, inputs, attrs):
    specs = []
    for n in inputs.get("X", []):
        v = block.var(n)
        specs.append((tuple(v.shape or ()), v.dtype))
    return {"Out": specs}


@register_op(
    "while", inputs=["Condition", "X"], outputs=["Out"],
    differentiable=False, infer_shape=_while_infer,
)
def _while(ctx, op, ins):
    """lax.while_loop over a sub-block (reference while_op.cc).

    X: carried vars (attr carry_names, in-block names == outer names, fluid
    in-place semantics); Condition: bool var, recomputed by the body (the
    body must write it — layers.While enforces this). Out re-binds the same
    outer names, so ops after the loop see final values."""
    blk = _sub_block(ctx, op)
    names = op.attr("carry_names")
    cond_name = op.attr("cond_name")
    init = tuple(ins["X"])
    cond0 = ins["Condition"][0]

    def cond_fun(carry):
        i, vals, c = carry
        return c.reshape(()).astype(bool)

    def body_fun(carry):
        i, vals, c = carry
        env = dict(zip(names, vals))
        env[cond_name] = c
        _run_block(_loop_ctx(ctx, i), blk, env)
        return (i + 1, tuple(env[n] for n in names), env[cond_name])

    _, final, _ = lax.while_loop(
        cond_fun, body_fun, (jnp.zeros((), jnp.int32), init, cond0)
    )
    return {"Out": list(final)}


def _scan_infer(block, inputs, attrs):
    prog = block.program
    sb = prog.blocks[attrs["sub_block"]]
    seq_outer = inputs.get("SeqIn", [])
    t_dim = None
    if seq_outer:
        v = block.var(seq_outer[0])
        t_dim = (v.shape or (None,))[0]
    outs = []
    for n in attrs["out_names"]:
        v = sb.var(n)
        outs.append(((t_dim,) + tuple(v.shape or ()), v.dtype))
    last = []
    for n in attrs["mem_names"]:
        v = sb.var(n)
        last.append((tuple(v.shape or ()), v.dtype))
    return {"Out": outs, "LastMem": last}


@register_op(
    "scan_block",
    inputs=["SeqIn", "InitMem", "Captured"],
    outputs=["Out", "LastMem"],
    infer_shape=_scan_infer,
)
def _scan_block(ctx, op, ins):
    """lax.scan over a sub-block: the differentiable loop (reference
    recurrent_op.cc / StaticRNN). Sequence inputs are consumed along axis 0;
    memories carry across steps; step outputs stack along a new axis 0.
    jax.vjp through this emitter IS the recurrent_grad program — BPTT comes
    from the __vjp__ machinery with no sub-block backward recursion."""
    blk = _sub_block(ctx, op)
    seq_names = op.attr("seq_names")  # in-block per-step var names
    mem_names = op.attr("mem_names")  # in-block memory var names
    upd_names = op.attr("mem_update_names")  # var holding next-step memory
    out_names = op.attr("out_names")
    cap_names = op.attr("cap_names")

    seq_vals = tuple(ins.get("SeqIn", []))
    mem0 = tuple(ins.get("InitMem", []))
    caps = dict(zip(cap_names, ins.get("Captured", [])))

    def step(carry, xs):
        i, mems = carry
        env = dict(caps)
        env.update(zip(seq_names, xs))
        env.update(zip(mem_names, mems))
        _run_block(_loop_ctx(ctx, i), blk, env)
        new_mems = tuple(env[n] for n in upd_names)
        outs = tuple(env[n] for n in out_names)
        return (i + 1, new_mems), outs

    (_, last_mems), stacked = lax.scan(
        step, (jnp.zeros((), jnp.int32), mem0), seq_vals
    )
    return {"Out": list(stacked), "LastMem": list(last_mems)}


# ---------------------------------------------------------------------------
# tensor-array ops (controlflow/tensor_array_read_write_op.cc,
# tensor_array_to_tensor_op.cc). A LoDTensorArray here is a dense stacked
# tensor [capacity, ...] — writes are dynamic_update_slice at a runtime
# index, reads dynamic_slice, both differentiable (scatter/gather vjps),
# so arrays inside scan/while bodies stay on-device with static shapes.
# ---------------------------------------------------------------------------


@register_op("write_to_array", inputs=["X", "I", "Array"], outputs=["Out"])
def _write_to_array(ctx, op, ins):
    """Fixed-capacity contract: the array is [capacity, ...] (capacity
    attr, default 32) — size the capacity to the loop's trip bound. An
    out-of-range index is a host-checked error (the reference
    LoDTensorArray grows dynamically; XLA shapes cannot)."""
    x = ins["X"][0]
    i = ins["I"][0].reshape(()).astype(jnp.int32)
    arr = ins.get("Array", [None])
    arr = arr[0] if arr else None
    if arr is None or (hasattr(arr, "size") and arr.size == 0):
        cap = int(op.attr("capacity", 32))
        arr = jnp.zeros((cap,) + x.shape, x.dtype)
    if not ctx.abstract:
        cap = arr.shape[0]

        def _check(idx):
            if int(idx) >= cap or int(idx) < 0:
                raise IndexError(
                    f"write_to_array index {int(idx)} outside the fixed "
                    f"capacity {cap}; raise the op's capacity attr to the "
                    "loop's trip bound"
                )

        jax.debug.callback(_check, i)
    return {"Out": [lax.dynamic_update_slice(
        arr, x[None].astype(arr.dtype), (i,) + (0,) * x.ndim
    )]}


@register_op("read_from_array", inputs=["X", "I"], outputs=["Out"])
def _read_from_array(ctx, op, ins):
    arr = ins["X"][0]
    i = ins["I"][0].reshape(()).astype(jnp.int32)
    out = lax.dynamic_slice(
        arr, (i,) + (0,) * (arr.ndim - 1), (1,) + arr.shape[1:]
    )
    return {"Out": [out[0]]}


@register_op(
    "tensor_array_to_tensor", inputs=["X"], outputs=["Out", "OutIndex"]
)
def _tensor_array_to_tensor(ctx, op, ins):
    arr = ins["X"][0]  # [T, ...]
    axis = op.attr("axis", 0)
    if op.attr("use_stack", False):
        out = jnp.moveaxis(arr, 0, axis) if axis else arr
    else:
        parts = [arr[t] for t in range(arr.shape[0])]
        out = jnp.concatenate(parts, axis=axis)
    if op.attr("use_stack", False):
        sizes = 1
    else:
        sizes = arr.shape[1 + axis] if arr.ndim > 1 else 1
    idx = jnp.full((arr.shape[0],), sizes, jnp.int32)
    return {"Out": [out], "OutIndex": [idx]}


# ---------------------------------------------------------------------------
# conditional_block (controlflow/conditional_block_op.cc): run the
# sub-block only when Cond holds. XLA form: both lax.cond branches are
# compiled; the skip branch emits zeros of the matching shapes (shapes via
# abstract eval of the true branch — no compute).
# ---------------------------------------------------------------------------


def _conditional_block_impl(ctx, op, ins):
    blk = _sub_block(ctx, op)
    # attrs when built by our Python layer; fall back to the op's own
    # Input/Out var lists (the reference op desc carries only those, so a
    # translated program has no *_names attrs)
    in_names = op.attr("in_names", None) or op.inputs.get("Input", [])
    out_names = op.attr("out_names", None) or op.outputs.get("Out", [])
    vals = tuple(ins.get("Input", []))
    cond = ins["Cond"][0]
    if op.attr("is_scalar_condition", False):
        pred = cond.reshape(()).astype(bool)
    else:
        pred = jnp.all(cond.astype(bool))

    def true_f(operands):
        env = dict(zip(in_names, operands))
        _run_block(ctx, blk, env)
        return tuple(env[n] for n in out_names)

    shapes = jax.eval_shape(true_f, vals)

    def false_f(operands):
        return tuple(jnp.zeros(s.shape, s.dtype) for s in shapes)

    outs = lax.cond(pred, true_f, false_f, vals)
    return {"Out": list(outs)}


@register_op(
    "conditional_block", inputs=["Cond", "Input"], outputs=["Out"]
)
def _conditional_block(ctx, op, ins):
    return _conditional_block_impl(ctx, op, ins)


@register_op(
    "conditional_block_infer", inputs=["Cond", "Input"], outputs=["Out"]
)
def _conditional_block_infer(ctx, op, ins):
    # inference variant (no grad bookkeeping needed — same lowering)
    return _conditional_block_impl(ctx, op, ins)


# ---------------------------------------------------------------------------
# select_input / select_output (controlflow/select_input_op.cc — the
# case/switch-case plumbing) and get_places (operators/get_places_op.cc)
# ---------------------------------------------------------------------------


@register_op("select_input", inputs=["X", "Mask"], outputs=["Out"])
def _select_input(ctx, op, ins):
    xs = ins["X"]
    mask = ins["Mask"][0].reshape(()).astype(jnp.int32)
    if len(xs) == 2:
        out = lax.cond(mask == 0, lambda o: o[0], lambda o: o[1], tuple(xs))
    else:
        out = lax.switch(mask, [lambda o, k=k: o[k] for k in range(len(xs))],
                         tuple(xs))
    return {"Out": [out]}


@register_op("select_output", inputs=["X", "Mask"], outputs=["Out"])
def _select_output(ctx, op, ins):
    x = ins["X"][0]
    mask = ins["Mask"][0].reshape(()).astype(jnp.int32)
    n = op.attr("num_branches", 2)
    outs = [
        jnp.where(mask == k, x, jnp.zeros_like(x)) for k in range(n)
    ]
    return {"Out": outs}


@register_op("get_places", inputs=[], outputs=["Out"], differentiable=False)
def _get_places(ctx, op, ins):
    """get_places_op.cc: device enumeration for ParallelDo-era graphs.
    Returns the local device ordinals (mesh construction is
    parallel/mesh.py's job; this op exists for graph parity)."""
    n = op.attr("device_count", 0) or jax.local_device_count()
    return {"Out": [jnp.arange(n, dtype=jnp.int32)]}


def _bounded_while_infer(block, inputs, attrs):
    specs = []
    for n in inputs.get("X", []):
        v = block.var(n)
        specs.append((tuple(v.shape or ()), v.dtype))
    return {"Out": specs}


@register_op(
    "bounded_while", inputs=["Condition", "X"], outputs=["Out"],
    infer_shape=_bounded_while_infer,
)
def _bounded_while(ctx, op, ins):
    """Differentiable While (reference while_grad parity,
    controlflow/while_op.cc + backward.py:843): the data-dependent loop is
    lowered to lax.scan over a STATIC `max_iters` bound with a mask — each
    step runs the body and keeps the previous carry where the condition
    has already gone false. Reverse-mode through the scan IS the
    while_grad program (the reference re-ran the body per iteration
    against a scope stack; here BPTT falls out of jax.vjp through scan).
    Semantics identical to `while` whenever the true trip count is
    <= max_iters; the wasted masked iterations are the price of a static
    shape."""
    blk = _sub_block(ctx, op)
    names = op.attr("carry_names")
    cond_name = op.attr("cond_name")
    max_iters = int(op.attr("max_iters"))
    init = tuple(ins["X"])
    cond0 = ins["Condition"][0]

    def step(carry, i):
        vals, c = carry
        env = dict(zip(names, vals))
        env[cond_name] = c
        _run_block(_loop_ctx(ctx, i), blk, env)
        active = c.reshape(()).astype(bool)
        new_vals = tuple(
            jnp.where(active, env[n], old) for n, old in zip(names, vals)
        )
        new_c = jnp.where(active, env[cond_name].reshape(c.shape), c)
        return (new_vals, new_c), None

    (vals, _c), _ = lax.scan(
        step, (init, cond0), jnp.arange(max_iters)
    )
    return {"Out": list(vals)}
