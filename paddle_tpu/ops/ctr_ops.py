"""CTR / tree-retrieval long-tail ops (reference operators/
tdm_child_op.h, tdm_sampler_op.h, filter_by_instag_op.h,
pyramid_hash_op.cc).

Static-shape re-designs: filter_by_instag keeps the dense frame and
returns a 0/1 LossWeight instead of resizing (the reference compacts rows
via LoD); tdm_sampler draws its per-layer negatives with the counter-based
ctx RNG.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.registry import register_op


@register_op(
    "tdm_child", inputs=["X", "TreeInfo"], outputs=["Child", "LeafMask"],
    differentiable=False,
)
def _tdm_child(ctx, op, ins):
    """TreeInfo rows: [item_id, layer_id, ancestor_id, child_0..child_{N-1}]
    (tdm_child_op.h:63). Child ids of each input node; LeafMask marks
    children that are leaves (their own item_id != 0)."""
    x = ins["X"][0].astype(jnp.int32)
    info = ins["TreeInfo"][0].astype(jnp.int32)
    child_nums = op.attr("child_nums", 2)
    flat = x.reshape(-1)
    rows = info[flat]  # [N, 3 + C]
    child = rows[:, 3:3 + child_nums]  # [N, C]
    has_child = (flat != 0) & (rows[:, 3] != 0)
    child = jnp.where(has_child[:, None], child, 0)
    leaf = (info[child][:, :, 0] != 0) & (child != 0)
    # reference output shape: [..., last_dim * child_nums]
    # (tdm_child_op.cc InferShape)
    if x.ndim > 1:
        shape = x.shape[:-1] + (x.shape[-1] * child_nums,)
    else:
        shape = (x.shape[0], child_nums)
    return {
        "Child": [child.reshape(shape).astype(jnp.int64)],
        "LeafMask": [leaf.reshape(shape).astype(jnp.int64)],
    }


@register_op(
    "tdm_sampler",
    inputs=["X", "Travel", "Layer"],
    outputs=["Out", "Labels", "Mask"],
    differentiable=False,
)
def _tdm_sampler(ctx, op, ins):
    """tdm_sampler_op.h: per tree layer emit the travel-path positive plus
    `neg_samples_num_list[i]` negatives drawn from that layer's node list
    (rejection of the positive via resample-shift)."""
    x = ins["X"][0].astype(jnp.int32).reshape(-1)  # [N]
    travel = ins["Travel"][0].astype(jnp.int32)  # [item_num, L]
    layer = ins["Layer"][0].astype(jnp.int32).reshape(-1)  # flat node list
    neg_nums = op.attr("neg_samples_num_list", [1])
    layer_offsets = op.attr("layer_offset_lod", None)
    L = travel.shape[1]
    N = x.shape[0]
    from ._helpers import op_key

    key = op_key(ctx, op)
    outs, labels, masks = [], [], []
    for i in range(L):
        pos = travel[x, i]  # [N]
        valid = pos != 0
        k = int(neg_nums[i]) if i < len(neg_nums) else 1
        if layer_offsets is not None:
            lo, hi = int(layer_offsets[i]), int(layer_offsets[i + 1])
        else:
            lo, hi = 0, layer.shape[0]
        key, sub = jax.random.split(key)
        idx = jax.random.randint(sub, (N, k), lo, max(hi, lo + 1))
        neg = layer[idx]  # [N, k]
        # avoid sampling the positive: shift colliding draws by one slot
        collide = neg == pos[:, None]
        alt = layer[jnp.where(idx + 1 < hi, idx + 1, lo)]
        neg = jnp.where(collide, alt, neg)
        grp = jnp.concatenate([pos[:, None], neg], axis=1)  # [N, 1+k]
        lab = jnp.concatenate(
            [jnp.ones((N, 1), jnp.int32), jnp.zeros((N, k), jnp.int32)],
            axis=1,
        )
        m = jnp.broadcast_to(valid[:, None], grp.shape)
        outs.append(jnp.where(m, grp, 0))
        labels.append(jnp.where(m, lab, 0))
        masks.append(m.astype(jnp.int32))
    out = jnp.concatenate(outs, axis=1)
    return {
        "Out": [out.astype(jnp.int64).reshape(N, -1, 1)],
        "Labels": [
            jnp.concatenate(labels, axis=1).astype(jnp.int64).reshape(N, -1, 1)
        ],
        "Mask": [
            jnp.concatenate(masks, axis=1).astype(jnp.int64).reshape(N, -1, 1)
        ],
    }


@register_op(
    "filter_by_instag",
    inputs=["Ins", "Ins_tag", "Filter_tag"],
    outputs=["Out", "LossWeight", "IndexMap"],
)
def _filter_by_instag(ctx, op, ins):
    """filter_by_instag_op.h compacts matching rows via LoD resize; the
    static-shape contract keeps every row and zeroes the non-matching ones,
    with LossWeight carrying the 0/1 keep mask (downstream losses multiply
    by LossWeight, so training math is identical)."""
    rows = ins["Ins"][0]  # [N, D]
    tags = ins["Ins_tag"][0].astype(jnp.int64)  # [N, T] (-1 padded)
    filt = ins["Filter_tag"][0].astype(jnp.int64).reshape(-1)  # [F]
    match = (tags[:, :, None] == filt[None, None, :]) & (
        tags[:, :, None] >= 0
    )
    keep = match.any(axis=(1, 2))  # [N]
    out = jnp.where(keep[:, None], rows, 0)
    n = rows.shape[0]
    index_map = jnp.stack(
        [jnp.arange(n, dtype=jnp.int64)] * 2
        + [keep.astype(jnp.int64)], axis=1
    )
    return {
        "Out": [out],
        "LossWeight": [keep.astype(rows.dtype).reshape(n, 1)],
        "IndexMap": [index_map],
    }


@register_op(
    "pyramid_hash",
    inputs=["X", "W", "WhiteList", "BlackList"],
    outputs=["Out", "DropPos", "X_Temp_Out"],
)
def _pyramid_hash(ctx, op, ins):
    """pyramid_hash_op.cc (text n-gram hash embedding): every n-gram
    (n = 2..max_pyramid_layer) hashes into `num_hash` rows of the
    embedding blob W [space_len, emb_dim/num_hash ...]; the token's
    embedding is the mean over n-grams. Dense re-derivation with the same
    multiply-xorshift mix as our hash op (the reference uses xxhash);
    white/black lists are host-side vocabulary filters, not modeled."""
    x = ins["X"][0].astype(jnp.uint32)  # [B, T] token ids (padded 0)
    w = ins["W"][0]
    num_hash = op.attr("num_hash", 1)
    space_len = w.shape[0]
    emb = op.attr("num_emb", w.shape[-1])
    max_layer = op.attr("max_pyramid_layer", 2)
    if x.ndim == 1:
        x = x[None, :]
    B, T = x.shape
    from ._helpers import hash_mix

    total = None
    cnt = 0
    for n in range(2, max_layer + 1):
        if n > T:
            break
        # combine n consecutive ids into one key (order-sensitive mix)
        key = x[:, : T - n + 1].astype(jnp.uint32)
        for j in range(1, n):
            key = key * jnp.uint32(1000003) + x[:, j: T - n + 1 + j]
        h = hash_mix(key, num_hash)
        idx = (h % jnp.uint32(space_len)).astype(jnp.int32)  # [B, L, K]
        g = w[idx]  # [B, L, K, emb]
        g = g.mean(axis=2)  # combine hash slots
        # scatter n-gram embedding onto its first token position
        pad = jnp.zeros((B, T - g.shape[1], g.shape[-1]), g.dtype)
        total = (
            jnp.concatenate([g, pad], axis=1)
            if total is None
            else total + jnp.concatenate([g, pad], axis=1)
        )
        cnt += 1
    if total is None:
        total = jnp.zeros((B, T, emb), w.dtype)
        cnt = 1
    out = total / cnt
    return {
        "Out": [out],
        "DropPos": [jnp.ones((B, T, 1), jnp.int32)],
        "X_Temp_Out": [x.astype(jnp.int64)],
    }
