"""Detection training/assignment ops — the Mask R-CNN / RetinaNet / SSD
suite (reference paddle/fluid/operators/detection/): rpn_target_assign_op.cc,
retinanet_target_assign (same file), generate_proposal_labels_op.cc,
generate_mask_labels_op.cc, distribute_fpn_proposals_op.cc,
collect_fpn_proposals_op.cc, bipartite_match_op.cc, target_assign_op.cc,
box_decoder_and_assign_op.cc, retinanet_detection_output_op.cc,
locality_aware_nms_op.cc, mine_hard_examples_op.cc, multiclass_nms_op.cc
(multiclass_nms2), polygon_box_transform_op.cc,
roi_perspective_transform_op.cc.

TPU-native re-designs (house style of ops/detection.py):
- single-image LoD walks become fixed-size tensors with validity encoded
  as -1 padding + explicit counts; left-packing uses the cumsum-rank
  scatter (same trick as generate_proposals).
- random subsampling (fg/bg minibatch sampling) uses the counter-based ctx
  RNG: a uniform jitter added to the selection priority replaces the
  reference's std::random_shuffle, so sampling is random but reproducible.
- gt inputs are dense: GtBoxes [G, 4] padded with -1 rows; GtSegms are
  dense per-gt binary masks [G, Hs, Ws] (the reference takes LoD polygon
  lists and rasterizes on CPU, mask_util.cc — rasterization belongs in the
  data pipeline here).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.registry import register_op
from ._helpers import op_key
from .detection import _greedy_nms, _iou_matrix, _tally


def _pack_left(values, mask, fill, cap=None):
    """Left-pack rows of `values` [N, ...] where mask [N] holds, into a
    buffer of size cap (default N), padding with `fill`."""
    n = values.shape[0]
    cap = cap or n
    rank = jnp.cumsum(mask.astype(jnp.int32)) - 1
    slot = jnp.where(mask & (rank < cap), rank, cap)  # dump row
    buf = jnp.full((cap + 1,) + values.shape[1:], fill, values.dtype)
    return buf.at[slot].set(values, mode="drop")[:cap]


def _encode_boxes(anchors, gts, weights=(1.0, 1.0, 1.0, 1.0)):
    """box delta encoding (bbox_util.h BoxToDelta): anchors/gts [N,4].
    Deltas are DIVIDED by the weights (reference convention — the decoder,
    box_decoder_and_assign / box_coder with the same weights as variance,
    multiplies them back)."""
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + 0.5 * aw
    acy = anchors[:, 1] + 0.5 * ah
    gw = gts[:, 2] - gts[:, 0] + 1.0
    gh = gts[:, 3] - gts[:, 1] + 1.0
    gcx = gts[:, 0] + 0.5 * gw
    gcy = gts[:, 1] + 0.5 * gh
    wx, wy, ww, wh = weights
    return jnp.stack([
        (gcx - acx) / aw / wx,
        (gcy - acy) / ah / wy,
        jnp.log(jnp.maximum(gw / aw, 1e-6)) / ww,
        jnp.log(jnp.maximum(gh / ah, 1e-6)) / wh,
    ], axis=1)


# ---------------------------------------------------------------------------
# RPN / RetinaNet anchor target assignment
# ---------------------------------------------------------------------------


def _anchor_assign_single(anchors, gt, is_crowd, im_info, key, *, pos_thresh,
                          neg_thresh, sample_frac, batch_size, retina,
                          straddle):
    """One image's anchor->gt assignment (the reference's per-LoD-image
    walk). anchors [A, 4] shared; gt [G, 4] -1/0-pad rows; key drives the
    sampling jitter. Returns flat arrays; the op wrappers add the output
    reshapes (and the leading [B] in the vmapped batched form)."""
    A = anchors.shape[0]
    G = gt.shape[0]
    valid_gt = gt[:, 2] > gt[:, 0]
    if is_crowd is not None:
        valid_gt = valid_gt & (is_crowd.reshape(-1)[:G] == 0)

    # straddle filter (rpn_target_assign_op.cc:99-110): with
    # rpn_straddle_thresh >= 0, anchors not inside the image (within the
    # threshold) are excluded from both fg and bg sampling
    inside = jnp.ones((A,), bool)
    if not retina and im_info is not None and straddle >= 0.0:
        info = im_info.reshape(-1)
        h_im, w_im = info[0], info[1]
        inside = (
            (anchors[:, 0] >= -straddle)
            & (anchors[:, 1] >= -straddle)
            & (anchors[:, 2] < w_im + straddle)
            & (anchors[:, 3] < h_im + straddle)
        )

    iou = jnp.where(valid_gt[None, :], _iou_matrix(anchors, gt), -1.0)
    iou = jnp.where(inside[:, None], iou, -1.0)
    a_max = jnp.max(iou, axis=1)  # [A]
    a_arg = jnp.argmax(iou, axis=1)
    g_max = jnp.max(iou, axis=0)  # [G]

    fg = a_max >= pos_thresh
    # every gt's best anchor is fg (rpn_target_assign_op.cc per-gt argmax)
    is_best = jnp.any(
        (iou == g_max[None, :]) & (g_max[None, :] > 0) & valid_gt[None, :],
        axis=1,
    )
    fg = (fg | is_best) & inside
    bg = (a_max < neg_thresh) & ~fg & inside

    jitter = jax.random.uniform(key, (A,))
    if retina:
        n_fg_cap = batch_size  # all fg used; cap = buffer size
        n_fg = jnp.minimum(fg.sum(), n_fg_cap)
        fg_sel = fg
    else:
        n_fg_cap = int(batch_size * sample_frac)
        # random fg subsample: top-(cap) by (fg + jitter)
        fg_rank = jnp.argsort(-(fg.astype(jnp.float32) + jitter))
        fg_take = jnp.zeros((A,), bool).at[fg_rank[:n_fg_cap]].set(True)
        fg_sel = fg & fg_take
        n_fg = fg_sel.sum()
    bg_rank = jnp.argsort(-(bg.astype(jnp.float32) + jitter))
    n_bg = jnp.minimum(bg.sum(), batch_size - n_fg)

    # bg selection: first n_bg of the jittered bg ranking
    bg_pos = jnp.cumsum(
        bg[bg_rank].astype(jnp.int32)
    ) - 1  # rank among bg, in jittered order
    bg_take = jnp.zeros((A,), bool).at[bg_rank].set(
        bg[bg_rank] & (bg_pos < n_bg)
    )

    idx = jnp.arange(A, dtype=jnp.int32)
    loc_index = _pack_left(idx, fg_sel, -1, n_fg_cap)
    tgt = _encode_boxes(anchors, gt[a_arg])
    tgt_bbox = _pack_left(tgt, fg_sel, 0.0, n_fg_cap)
    w = jnp.where(fg_sel[:, None], 1.0, 0.0) * jnp.ones((A, 4))
    bbox_w = _pack_left(w, fg_sel, 0.0, n_fg_cap)

    both = fg_sel | bg_take
    score_index = _pack_left(idx, both, -1, batch_size)
    labels = jnp.where(fg_sel, 1, 0).astype(jnp.int32)
    tgt_label = _pack_left(labels, both, -1, batch_size)
    return (loc_index, score_index, tgt_label, tgt_bbox, bbox_w,
            jnp.maximum(n_fg, 1).astype(jnp.int32))


def _anchor_assign(ctx, op, ins, *, pos_thresh, neg_thresh, sample_frac,
                   batch_size, retina):
    """Op-facing wrapper: single image (GtBoxes [G, 4]) runs the core
    directly; the batched form (GtBoxes [B, G, 4], IsCrowd [B, G], ImInfo
    [B, 3]) vmaps it over images with per-image keys split off the op's
    stream, every output gaining a leading [B]."""
    anchors = ins["Anchor"][0].reshape(-1, 4).astype(jnp.float32)  # [A,4]
    gt = ins["GtBoxes"][0].astype(jnp.float32)  # [(B,) G, 4], pad rows
    is_crowd = ins.get("IsCrowd", [None])[0]
    im_info = ins.get("ImInfo", [None])[0]
    kw = dict(
        pos_thresh=pos_thresh, neg_thresh=neg_thresh,
        sample_frac=sample_frac, batch_size=batch_size, retina=retina,
        straddle=op.attr("rpn_straddle_thresh", -1.0),
    )
    op_name = "retinanet_target_assign" if retina else "rpn_target_assign"
    key = op_key(ctx, op)
    if gt.ndim == 3:
        _tally(ctx, op_name, batched=True)
        B, G = gt.shape[:2]
        keys = jax.random.split(key, B)
        # zeros is crowd-free == absent IsCrowd (valid_gt unchanged)
        crowd = (
            is_crowd.reshape(B, -1) if is_crowd is not None
            else jnp.zeros((B, G), jnp.int32)
        )
        has_info = im_info is not None
        info = (
            im_info.reshape(B, -1) if has_info
            else jnp.zeros((B, 3), jnp.float32)
        )

        def one(g, c, i, k):
            return _anchor_assign_single(
                anchors, g, c, i if has_info else None, k, **kw
            )

        loc, score, lbl, tbb, bw, n_fg = jax.vmap(one)(gt, crowd, info, keys)
        out = {
            "LocationIndex": [loc],
            "ScoreIndex": [score],
            "TargetLabel": [lbl[..., None]],
            "TargetBBox": [tbb],
            "BBoxInsideWeight": [bw],
        }
        if retina:
            out["ForegroundNumber"] = [n_fg.reshape(B, 1)]
        return out
    _tally(ctx, op_name, batched=False)
    loc, score, lbl, tbb, bw, n_fg = _anchor_assign_single(
        anchors, gt, is_crowd, im_info, key, **kw
    )
    out = {
        "LocationIndex": [loc],
        "ScoreIndex": [score],
        "TargetLabel": [lbl.reshape(-1, 1)],
        "TargetBBox": [tbb],
        "BBoxInsideWeight": [bw],
    }
    if retina:
        out["ForegroundNumber"] = [n_fg.reshape(1, 1)]
    return out


@register_op(
    "rpn_target_assign",
    inputs=["Anchor", "GtBoxes", "IsCrowd", "ImInfo"],
    outputs=["LocationIndex", "ScoreIndex", "TargetLabel", "TargetBBox",
             "BBoxInsideWeight"],
    differentiable=False,
)
def _rpn_target_assign(ctx, op, ins):
    """rpn_target_assign_op.cc: sample rpn_batch_size_per_im anchors
    (fg: iou >= rpn_positive_overlap or per-gt argmax; bg: iou <
    rpn_negative_overlap), emit fg regression targets + sampled indices.
    Fixed-size outputs: LocationIndex [fg_cap] / ScoreIndex [batch] are
    -1-padded; downstream losses gather with mode="fill"."""
    return _anchor_assign(
        ctx, op, ins,
        pos_thresh=op.attr("rpn_positive_overlap", 0.7),
        neg_thresh=op.attr("rpn_negative_overlap", 0.3),
        sample_frac=op.attr("rpn_fg_fraction", 0.5),
        batch_size=int(op.attr("rpn_batch_size_per_im", 256)),
        retina=False,
    )


@register_op(
    "retinanet_target_assign",
    inputs=["Anchor", "GtBoxes", "GtLabels", "IsCrowd", "ImInfo"],
    outputs=["LocationIndex", "ScoreIndex", "TargetLabel", "TargetBBox",
             "BBoxInsideWeight", "ForegroundNumber"],
    differentiable=False,
)
def _retinanet_target_assign(ctx, op, ins):
    """RetinaNet variant (same .cc file): every anchor with iou >= 0.5 is
    fg (no subsampling), iou < 0.4 bg; TargetLabel carries the gt class."""
    out = _anchor_assign(
        ctx, op, ins,
        pos_thresh=op.attr("positive_overlap", 0.5),
        neg_thresh=op.attr("negative_overlap", 0.4),
        sample_frac=1.0,
        batch_size=ins["Anchor"][0].reshape(-1, 4).shape[0],
        retina=True,
    )
    # relabel fg with gt classes (argmax over the same crowd/pad-masked iou
    # the assigner used, so cls and reg targets refer to the same gt)
    gt_labels = ins.get("GtLabels", [None])[0]
    if gt_labels is not None:
        anchors = ins["Anchor"][0].reshape(-1, 4).astype(jnp.float32)
        gt = ins["GtBoxes"][0].astype(jnp.float32)
        is_crowd = ins.get("IsCrowd", [None])[0]

        def relabel_one(gt_i, crowd_i, labels_i, si, tl):
            valid_gt = gt_i[:, 2] > gt_i[:, 0]
            if crowd_i is not None:
                valid_gt = valid_gt & (
                    crowd_i.reshape(-1)[:gt_i.shape[0]] == 0
                )
            iou = jnp.where(
                valid_gt[None, :], _iou_matrix(anchors, gt_i), -1.0
            )
            a_arg = jnp.argmax(iou, axis=1)
            cls = labels_i.reshape(-1).astype(jnp.int32)[a_arg]  # [A]
            return jnp.where(tl > 0, cls[jnp.maximum(si, 0)], tl)

        si = out["ScoreIndex"][0]
        if gt.ndim == 3:
            B, G = gt.shape[:2]
            crowd = (
                is_crowd.reshape(B, -1) if is_crowd is not None
                else jnp.zeros((B, G), jnp.int32)
            )
            tl = out["TargetLabel"][0].reshape(B, -1)
            relabel = jax.vmap(relabel_one)(
                gt, crowd, gt_labels.reshape(B, -1), si, tl
            )
            out["TargetLabel"] = [relabel[..., None]]
        else:
            tl = out["TargetLabel"][0].reshape(-1)
            relabel = relabel_one(gt, is_crowd, gt_labels, si, tl)
            out["TargetLabel"] = [relabel.reshape(-1, 1)]
    return out


# ---------------------------------------------------------------------------
# proposal -> training-target sampling (Fast R-CNN head inputs)
# ---------------------------------------------------------------------------


def _proposal_labels_single(rois, gt_cls, is_crowd, gt, key, *, B, fg_frac,
                            fg_thresh, bg_hi, bg_lo, num_classes, bbox_w):
    """One image's proposal->label sampling. rois [R, 4] (padded rows are
    degenerate boxes and score as invalid), gt [G, 4], gt_cls [G]."""
    valid_gt = gt[:, 2] > gt[:, 0]
    if is_crowd is not None:
        valid_gt = valid_gt & (is_crowd.reshape(-1)[:gt.shape[0]] == 0)

    # reference appends gt boxes to the roi set so every gt can be fg
    all_rois = jnp.concatenate([rois, gt], axis=0)
    roi_valid = jnp.concatenate([
        (rois[:, 2] > rois[:, 0]),
        valid_gt,
    ])
    R = all_rois.shape[0]
    iou = jnp.where(valid_gt[None, :], _iou_matrix(all_rois, gt), -1.0)
    max_iou = jnp.where(roi_valid, jnp.max(iou, axis=1), -1.0)  # [R]
    argmax = jnp.argmax(iou, axis=1)

    fg = max_iou >= fg_thresh
    bg = (max_iou < bg_hi) & (max_iou >= bg_lo) & roi_valid

    jitter = jax.random.uniform(key, (R,))
    fg_cap = int(B * fg_frac)
    fg_rank = jnp.argsort(-(fg.astype(jnp.float32) + jitter))
    fg_sel = fg & jnp.zeros((R,), bool).at[fg_rank[:fg_cap]].set(True)
    n_fg = fg_sel.sum()
    n_bg = B - n_fg
    bg_rank = jnp.argsort(-(bg.astype(jnp.float32) + jitter))
    bg_pos = jnp.cumsum(bg[bg_rank].astype(jnp.int32)) - 1
    bg_sel = jnp.zeros((R,), bool).at[bg_rank].set(
        bg[bg_rank] & (bg_pos < n_bg)
    )

    both = fg_sel | bg_sel
    # fg first (the mask head consumes the fg prefix)
    order_key = (
        fg_sel.astype(jnp.float32) * 2.0 + bg_sel.astype(jnp.float32)
    ) + jitter * 0.5
    order = jnp.argsort(-order_key)
    sel = both[order]
    src = order  # candidate index per packed slot

    out_rois = _pack_left(all_rois[src], sel, 0.0, B)
    labels = jnp.where(fg_sel, gt_cls[argmax], 0).astype(jnp.int32)
    out_labels = _pack_left(labels[src], sel, -1, B)
    max_ov = _pack_left(max_iou[src], sel, 0.0, B)

    tgt = _encode_boxes(all_rois, gt[argmax], tuple(bbox_w))
    tgt = jnp.where(fg_sel[:, None], tgt, 0.0)
    tgt_packed = _pack_left(tgt[src], sel, 0.0, B)  # [B, 4]
    lbl_packed = out_labels
    # per-class expansion: slot 4*c..4*c+4 of the matched class
    cls_idx = jnp.maximum(lbl_packed, 0)
    one_hot = jax.nn.one_hot(cls_idx, num_classes, dtype=jnp.float32)
    fg_row = (lbl_packed > 0).astype(jnp.float32)[:, None, None]
    targets = (one_hot[:, :, None] * tgt_packed[:, None, :] * fg_row)
    inside_w = (one_hot[:, :, None] * fg_row) * jnp.ones((1, 1, 4))
    n_live = both.sum().astype(jnp.int32)
    return (out_rois, out_labels, targets.reshape(B, num_classes * 4),
            inside_w.reshape(B, num_classes * 4), n_live, max_ov)


@register_op(
    "generate_proposal_labels",
    inputs=["RpnRois", "GtClasses", "IsCrowd", "GtBoxes", "ImInfo",
            "RpnRoisNum"],
    outputs=["Rois", "LabelsInt32", "BboxTargets", "BboxInsideWeights",
             "BboxOutsideWeights", "RoisNum", "MaxOverlapWithGT"],
    differentiable=False,
)
def _generate_proposal_labels(ctx, op, ins):
    """generate_proposal_labels_op.cc: append gts to the proposal set,
    sample batch_size_per_im rois (fg_fraction at fg_thresh, rest bg in
    [bg_thresh_lo, bg_thresh_hi)), emit class labels and per-class box
    regression targets. Output size is exactly batch_size_per_im (the
    per-image RoI cap); RoisNum counts the live rows.

    Batched contract (r6): RpnRois [B, R, 4] + GtBoxes [B, G, 4] (+
    GtClasses/IsCrowd [B, G], ImInfo [B, 3]) vmaps the single-image core
    with per-image keys -> every output gains a leading [B], RoisNum is
    [B]. RpnRoisNum is accepted but unused either way: padded proposal
    rows are degenerate (0-area) boxes that never sample as fg or bg."""
    rois = ins["RpnRois"][0].astype(jnp.float32)
    gt_cls = ins["GtClasses"][0].astype(jnp.int32)
    gt = ins["GtBoxes"][0].astype(jnp.float32)
    is_crowd = ins.get("IsCrowd", [None])[0]
    kw = dict(
        B=int(op.attr("batch_size_per_im", 512)),
        fg_frac=op.attr("fg_fraction", 0.25),
        fg_thresh=op.attr("fg_thresh", 0.5),
        bg_hi=op.attr("bg_thresh_hi", 0.5),
        bg_lo=op.attr("bg_thresh_lo", 0.0),
        num_classes=int(op.attr("class_nums", 81)),
        bbox_w=op.attr("bbox_reg_weights", [0.1, 0.1, 0.2, 0.2]),
    )
    key = op_key(ctx, op)
    cap = kw["B"]
    if gt.ndim == 3:
        _tally(ctx, "generate_proposal_labels", batched=True)
        Bimg, G = gt.shape[:2]
        keys = jax.random.split(key, Bimg)
        crowd = (
            is_crowd.reshape(Bimg, -1) if is_crowd is not None
            else jnp.zeros((Bimg, G), jnp.int32)
        )

        def one(r, gc, c, g, k):
            return _proposal_labels_single(
                r.reshape(-1, 4), gc.reshape(-1), c, g, k, **kw
            )

        (out_rois, out_labels, targets, inside_w, n_live,
         max_ov) = jax.vmap(one)(
            rois, gt_cls.reshape(Bimg, -1), crowd, gt, keys
        )
        return {
            "Rois": [out_rois],
            "LabelsInt32": [out_labels[..., None]],
            "BboxTargets": [targets],
            "BboxInsideWeights": [inside_w],
            "BboxOutsideWeights": [inside_w],
            "RoisNum": [n_live],
            "MaxOverlapWithGT": [max_ov[..., None]],
        }
    _tally(ctx, "generate_proposal_labels", batched=False)
    out_rois, out_labels, targets, inside_w, n_live, max_ov = (
        _proposal_labels_single(
            rois.reshape(-1, 4), gt_cls.reshape(-1), is_crowd, gt, key, **kw
        )
    )
    return {
        "Rois": [out_rois],
        "LabelsInt32": [out_labels.reshape(-1, 1)],
        "BboxTargets": [targets],
        "BboxInsideWeights": [inside_w],
        "BboxOutsideWeights": [inside_w],
        "RoisNum": [n_live.reshape(1)],
        "MaxOverlapWithGT": [max_ov.reshape(-1, 1)],
    }


def _mask_labels_single(gt_cls, segms, rois, labels, M, num_classes):
    """One image's mask-target generation: segms [G, Hs, Ws], rois [R, 4],
    labels [R] -> (mask_rois [R, 4], has_mask [R], mask_int32
    [R, num_classes*M*M])."""
    G, Hs, Ws = segms.shape
    R = rois.shape[0]

    # match each fg roi to the gt with max iou against the gt boxes derived
    # from the bitmaps' bounding boxes is the reference behavior; here the
    # caller passes rois produced by generate_proposal_labels whose fg
    # prefix is gt-matched, so re-derive the match by iou on bitmap bboxes
    ys = jnp.arange(Hs, dtype=jnp.float32)
    xs = jnp.arange(Ws, dtype=jnp.float32)
    any_row = segms.max(axis=2)  # [G, Hs]
    any_col = segms.max(axis=1)  # [G, Ws]
    big = 1e9
    y0 = jnp.min(jnp.where(any_row > 0, ys[None, :], big), axis=1)
    y1 = jnp.max(jnp.where(any_row > 0, ys[None, :], -big), axis=1)
    x0 = jnp.min(jnp.where(any_col > 0, xs[None, :], big), axis=1)
    x1 = jnp.max(jnp.where(any_col > 0, xs[None, :], -big), axis=1)
    gt_boxes = jnp.stack([x0, y0, x1, y1], axis=1)
    valid_gt = (x1 > x0) & (y1 > y0)
    iou = jnp.where(valid_gt[None, :], _iou_matrix(rois, gt_boxes), -1.0)
    match = jnp.argmax(iou, axis=1)  # [R]

    fg = labels > 0

    def crop_one(roi, g):
        # sample an MxM grid inside the roi from the matched bitmap
        gy = roi[1] + (roi[3] - roi[1]) * (jnp.arange(M) + 0.5) / M
        gx = roi[0] + (roi[2] - roi[0]) * (jnp.arange(M) + 0.5) / M
        yi = jnp.clip(jnp.round(gy), 0, Hs - 1).astype(jnp.int32)
        xi = jnp.clip(jnp.round(gx), 0, Ws - 1).astype(jnp.int32)
        return segms[g][yi[:, None], xi[None, :]]  # [M, M]

    crops = jax.vmap(crop_one)(rois, match)  # [R, M, M]
    cls = jnp.maximum(labels, 0)
    one_hot = jax.nn.one_hot(cls, num_classes, dtype=jnp.float32)
    # class slot gets the 0/1 mask; other slots -1 (ignore)
    tgt = jnp.where(
        one_hot[:, :, None] > 0,
        crops.reshape(R, 1, M * M),
        -1.0,
    )
    tgt = jnp.where(fg[:, None, None], tgt, -1.0)
    mask_rois = jnp.where(fg[:, None], rois, 0.0)
    return (mask_rois, fg.astype(jnp.int32),
            tgt.reshape(R, num_classes * M * M).astype(jnp.int32))


@register_op(
    "generate_mask_labels",
    inputs=["ImInfo", "GtClasses", "IsCrowd", "GtSegms", "Rois",
            "LabelsInt32"],
    outputs=["MaskRois", "RoiHasMaskInt32", "MaskInt32"],
    differentiable=False,
)
def _generate_mask_labels(ctx, op, ins):
    """generate_mask_labels_op.cc with a dense-mask contract: GtSegms is
    [G, Hs, Ws] binary bitmaps in image coordinates (the reference takes
    LoD polygon lists and rasterizes them on the CPU with mask_util.cc;
    rasterization is the data pipeline's job in this framework). Each fg
    roi crops its matched gt's bitmap and resizes to resolution^2; the
    target lands in the roi's class slot, all other class slots are -1
    (ignored by sigmoid mask loss).

    Batched contract (r6): GtSegms [B, G, Hs, Ws] with Rois [B, R, 4],
    LabelsInt32 [B, R(, 1)], GtClasses [B, G] vmaps the (RNG-free) core
    over images -> MaskRois [B, R, 4], RoiHasMaskInt32 [B, R, 1],
    MaskInt32 [B, R, num_classes*resolution^2]."""
    M = int(op.attr("resolution", 14))
    num_classes = int(op.attr("num_classes", 81))
    segms = ins["GtSegms"][0].astype(jnp.float32)
    if segms.ndim == 4:
        _tally(ctx, "generate_mask_labels", batched=True)
        B = segms.shape[0]
        gt_cls = ins["GtClasses"][0].reshape(B, -1).astype(jnp.int32)
        rois = ins["Rois"][0].reshape(B, -1, 4).astype(jnp.float32)
        labels = ins["LabelsInt32"][0].reshape(B, -1).astype(jnp.int32)
        mask_rois, has_mask, tgt = jax.vmap(
            lambda gc, sg, r, lb: _mask_labels_single(
                gc, sg, r, lb, M, num_classes
            )
        )(gt_cls, segms, rois, labels)
        return {
            "MaskRois": [mask_rois],
            "RoiHasMaskInt32": [has_mask[..., None]],
            "MaskInt32": [tgt],
        }
    _tally(ctx, "generate_mask_labels", batched=False)
    gt_cls = ins["GtClasses"][0].reshape(-1).astype(jnp.int32)
    rois = ins["Rois"][0].reshape(-1, 4).astype(jnp.float32)
    labels = ins["LabelsInt32"][0].reshape(-1).astype(jnp.int32)
    mask_rois, has_mask, tgt = _mask_labels_single(
        gt_cls, segms, rois, labels, M, num_classes
    )
    return {
        "MaskRois": [mask_rois],
        "RoiHasMaskInt32": [has_mask.reshape(-1, 1)],
        "MaskInt32": [tgt],
    }


# ---------------------------------------------------------------------------
# FPN roi routing
# ---------------------------------------------------------------------------


def _distribute_single(rois, min_level, max_level, refer_level, refer_scale):
    """One image's FPN roi routing: rois [R, 4] -> (per-level packed list
    L x [R, 4], nums [L], restore [R])."""
    R = rois.shape[0]
    L = max_level - min_level + 1

    w = jnp.maximum(rois[:, 2] - rois[:, 0], 0.0)
    h = jnp.maximum(rois[:, 3] - rois[:, 1], 0.0)
    live = (w > 0) & (h > 0)
    scale = jnp.sqrt(w * h)
    lvl = jnp.floor(
        refer_level + jnp.log2(scale / refer_scale + 1e-6)
    ).astype(jnp.int32)
    lvl = jnp.clip(lvl, min_level, max_level)

    idx = jnp.arange(R, dtype=jnp.int32)
    multi, nums, orders = [], [], []
    for lev in range(min_level, max_level + 1):
        m = live & (lvl == lev)
        multi.append(_pack_left(rois, m, 0.0, R))
        nums.append(m.sum().astype(jnp.int32))
        orders.append(_pack_left(idx, m, -1, R))
    # RestoreIndex: position in the level-major packed concat for each
    # input roi (reference restore semantics: out[restore[i]] = in[i])
    concat_src = jnp.concatenate(orders)  # [L*R] source index or -1
    # RestoreIndex contract (static-shape form): restore[i] is roi i's ROW
    # IN THE PADDED LEVEL-MAJOR CONCAT of MultiFpnRois (level lev, packed
    # slot j -> lev*R + j), which is exactly how consumers stack the
    # per-level roi_align outputs (_fpn_roi_extract). Dead rois get -1.
    live_slot = concat_src >= 0
    slots = jnp.arange(concat_src.shape[0], dtype=jnp.int32)
    restore = jnp.full((R + 1,), -1, jnp.int32).at[
        jnp.where(live_slot, concat_src, R)
    ].set(jnp.where(live_slot, slots, -1))[:R]
    return multi, jnp.stack(nums), restore


@register_op(
    "distribute_fpn_proposals",
    inputs=["FpnRois", "RoisNum"],
    outputs=["MultiFpnRois", "RestoreIndex", "MultiLevelRoIsNum"],
    differentiable=False,
)
def _distribute_fpn_proposals(ctx, op, ins):
    """distribute_fpn_proposals_op.cc: level(roi) = floor(level0 +
    log2(sqrt(area) / refer_scale + eps)) clamped to [min, max]. Each
    level's output is the full-size buffer left-packed (zero padding) with
    its live count in MultiLevelRoIsNum; RestoreIndex maps the level-major
    concat order back to the input order.

    Batched contract (r6): FpnRois [B, R, 4] packs PER IMAGE ->
    MultiFpnRois each [B, R, 4], RestoreIndex [B, R, 1] (row in image b's
    own level-major concat), MultiLevelRoIsNum each [B]."""
    min_level = int(op.attr("min_level", 2))
    max_level = int(op.attr("max_level", 5))
    refer_level = int(op.attr("refer_level", 4))
    refer_scale = float(op.attr("refer_scale", 224))
    rois = ins["FpnRois"][0].astype(jnp.float32)
    if rois.ndim == 3:
        _tally(ctx, "distribute_fpn_proposals", batched=True)
        multi, nums, restore = jax.vmap(
            lambda r: _distribute_single(
                r, min_level, max_level, refer_level, refer_scale
            )
        )(rois)  # L x [B, R, 4], [B, L], [B, R]
        return {
            "MultiFpnRois": multi,
            "RestoreIndex": [restore[..., None]],
            "MultiLevelRoIsNum": [nums[:, i] for i in range(nums.shape[1])],
        }
    _tally(ctx, "distribute_fpn_proposals", batched=False)
    multi, nums, restore = _distribute_single(
        rois.reshape(-1, 4), min_level, max_level, refer_level, refer_scale
    )
    return {
        "MultiFpnRois": multi,
        "RestoreIndex": [restore.reshape(-1, 1)],
        "MultiLevelRoIsNum": [nums[i].reshape(1) for i in range(nums.shape[0])],
    }


def _collect_single(rois_list, scores_list, nums_list, topn):
    """One image's FPN roi collection: per-level rois [k, 4] / scores [k]
    (+ optional live counts) -> (out [topn, 4], n)."""
    rois = jnp.concatenate([r.reshape(-1, 4) for r in rois_list], axis=0)
    scores = jnp.concatenate([s.reshape(-1) for s in scores_list], axis=0)
    if nums_list is not None:
        # zero out padded rows beyond each level's live count
        offs = []
        for r, n in zip(rois_list, nums_list):
            k = r.reshape(-1, 4).shape[0]
            offs.append(jnp.arange(k) < n.reshape(()))
        livem = jnp.concatenate(offs)
    else:
        livem = (rois[:, 2] > rois[:, 0])
    scores = jnp.where(livem, scores, -jnp.inf)
    topn = min(topn, rois.shape[0])
    top_s, top_i = lax.top_k(scores, topn)
    out = jnp.where((top_s > -jnp.inf)[:, None], rois[top_i], 0.0)
    n = jnp.sum(top_s > -jnp.inf).astype(jnp.int32)
    return out, n


@register_op(
    "collect_fpn_proposals",
    inputs=["MultiLevelRois", "MultiLevelScores", "MultiLevelRoIsNum"],
    outputs=["FpnRois", "RoisNum"],
    differentiable=False,
)
def _collect_fpn_proposals(ctx, op, ins):
    """collect_fpn_proposals_op.cc: concat per-level (roi, score) sets and
    keep the global post_nms_topN by score — per image. Batched contract
    (r6): per-level rois [B, k, 4] / scores [B, k(, 1)] / counts [B]
    (exactly what batched generate_proposals emits) -> FpnRois
    [B, topn, 4], RoisNum [B]."""
    topn = int(op.attr("post_nms_topN", 1000))
    rois_list = ins["MultiLevelRois"]
    nums = ins.get("MultiLevelRoIsNum", [])
    nums_list = list(nums) if (nums and nums[0] is not None) else None
    if rois_list[0].ndim == 3:
        _tally(ctx, "collect_fpn_proposals", batched=True)
        B = rois_list[0].shape[0]
        scores_list = [s.reshape(B, -1) for s in ins["MultiLevelScores"]]

        def one(rl, sl, nl):
            return _collect_single(
                rl, sl, nl if nums_list is not None else None, topn
            )

        out, n = jax.vmap(one)(
            [r.reshape(B, -1, 4) for r in rois_list],
            scores_list,
            (
                [n.reshape(B) for n in nums_list]
                if nums_list is not None
                else [jnp.zeros((B,), jnp.int32) for _ in rois_list]
            ),
        )
        return {"FpnRois": [out], "RoisNum": [n]}
    _tally(ctx, "collect_fpn_proposals", batched=False)
    out, n = _collect_single(
        rois_list, ins["MultiLevelScores"], nums_list, topn
    )
    return {"FpnRois": [out], "RoisNum": [n.reshape(1)]}


# ---------------------------------------------------------------------------
# SSD-style matching / assignment
# ---------------------------------------------------------------------------


@register_op(
    "bipartite_match",
    inputs=["DistMat"],
    outputs=["ColToRowMatchIndices", "ColToRowMatchDist"],
    differentiable=False,
)
def _bipartite_match(ctx, op, ins):
    """bipartite_match_op.cc: greedy global-max bipartite matching on the
    distance matrix; with match_type='per_prediction', unmatched columns
    whose best distance >= dist_threshold also match their argmax row.
    lax.scan over min(R,C) greedy picks."""
    dist = ins["DistMat"][0]
    batched = dist.ndim == 3
    if not batched:
        dist = dist[None]
    Bz, Rn, Cn = dist.shape
    match_type = op.attr("match_type", "bipartite")
    thresh = op.attr("dist_threshold", 0.5)

    def one(d):
        def step(carry, _):
            row_used, col_used, m_idx, m_dist = carry
            masked = jnp.where(
                row_used[:, None] | col_used[None, :], -jnp.inf, d
            )
            flat = jnp.argmax(masked)
            i, j = flat // Cn, flat % Cn
            ok = masked[i, j] > 0
            return (
                row_used.at[i].set(row_used[i] | ok),
                col_used.at[j].set(col_used[j] | ok),
                m_idx.at[j].set(jnp.where(ok, i, m_idx[j])),
                m_dist.at[j].set(jnp.where(ok, d[i, j], m_dist[j])),
            ), None

        init = (
            jnp.zeros((Rn,), bool), jnp.zeros((Cn,), bool),
            jnp.full((Cn,), -1, jnp.int32), jnp.zeros((Cn,), d.dtype),
        )
        (ru, cu, mi, md), _ = lax.scan(
            step, init, None, length=min(Rn, Cn)
        )
        if match_type == "per_prediction":
            best = jnp.max(d, axis=0)
            arg = jnp.argmax(d, axis=0).astype(jnp.int32)
            extra = (mi < 0) & (best >= thresh)
            mi = jnp.where(extra, arg, mi)
            md = jnp.where(extra, best, md)
        return mi, md

    mi, md = jax.vmap(one)(dist)
    if not batched:
        pass  # reference emits [N, C] even for one batch
    return {"ColToRowMatchIndices": [mi], "ColToRowMatchDist": [md]}


@register_op(
    "target_assign",
    inputs=["X", "MatchIndices", "NegIndices"],
    outputs=["Out", "OutWeight"],
    differentiable=False,
)
def _target_assign(ctx, op, ins):
    """target_assign_op.cc: out[i, j] = X[i, match[i, j]] where matched
    (weight 1), else mismatch_value (weight 0); rows listed in NegIndices
    get weight 1 with the mismatch value (SSD negatives). Dense contract:
    X [N, M, K], NegIndices as a 0/1 mask [N, P] (LoD index lists become
    masks here)."""
    x = ins["X"][0]
    match = ins["MatchIndices"][0].astype(jnp.int32)  # [N, P]
    neg = ins.get("NegIndices", [None])[0]
    mismatch = op.attr("mismatch_value", 0)
    if x.ndim == 2:
        x = x[None]
    N, P = match.shape
    K = x.shape[-1]
    matched = match >= 0
    gather = jnp.take_along_axis(
        x, jnp.maximum(match, 0)[:, :, None], axis=1
    )
    out = jnp.where(matched[:, :, None], gather,
                    jnp.asarray(mismatch, x.dtype))
    w = matched.astype(jnp.float32)
    if neg is not None:
        negm = neg.astype(jnp.float32).reshape(N, P)
        w = jnp.maximum(w, negm)
    return {"Out": [out], "OutWeight": [w[:, :, None]]}


@register_op(
    "mine_hard_examples",
    inputs=["ClsLoss", "LocLoss", "MatchIndices", "MatchDist"],
    outputs=["NegIndices", "UpdatedMatchIndices"],
    differentiable=False,
)
def _mine_hard_examples(ctx, op, ins):
    """mine_hard_examples_op.cc (SSD OHEM): rank unmatched priors by loss,
    keep the top neg_pos_ratio * num_pos (max_negative mining) per image.
    NegIndices is the static-shape 0/1 selection mask [N, P] (the
    reference emits LoD index lists)."""
    cls_loss = ins["ClsLoss"][0]
    loc_loss = ins.get("LocLoss", [None])[0]
    match = ins["MatchIndices"][0].astype(jnp.int32)
    match_dist = ins.get("MatchDist", [None])[0]
    ratio = op.attr("neg_pos_ratio", 3.0)
    dist_thresh = op.attr("neg_dist_threshold", 0.5)
    mining = op.attr("mining_type", "max_negative")
    sample_size = op.attr("sample_size", 0)
    loss = cls_loss
    if loc_loss is not None and mining == "hard_example":
        loss = loss + loc_loss
    N, P = match.shape
    loss = loss.reshape(N, P)
    is_neg = match < 0
    if match_dist is not None:
        is_neg = is_neg & (match_dist.reshape(N, P) < dist_thresh)
    num_pos = (match >= 0).sum(axis=1)  # [N]
    cap = jnp.where(
        sample_size > 0,
        jnp.full_like(num_pos, int(sample_size) if sample_size else 0),
        (num_pos.astype(jnp.float32) * ratio).astype(jnp.int32),
    )
    order = jnp.argsort(-jnp.where(is_neg, loss, -jnp.inf), axis=1)
    rank_in_order = jnp.argsort(order, axis=1)  # rank of each prior
    sel = is_neg & (rank_in_order < cap[:, None])
    updated = jnp.where(match >= 0, match, -1)
    return {
        "NegIndices": [sel.astype(jnp.int32)],
        "UpdatedMatchIndices": [updated],
    }


# ---------------------------------------------------------------------------
# decode / output heads
# ---------------------------------------------------------------------------


@register_op(
    "box_decoder_and_assign",
    inputs=["PriorBox", "PriorBoxVar", "TargetBox", "BoxScore"],
    outputs=["DecodeBox", "OutputAssignBox"],
)
def _box_decoder_and_assign(ctx, op, ins):
    """box_decoder_and_assign_op.cc: decode per-class deltas against the
    shared prior, then assign each roi the box of its best non-background
    class."""
    prior = ins["PriorBox"][0].astype(jnp.float32)  # [R, 4]
    var = ins["PriorBoxVar"][0].astype(jnp.float32).reshape(-1)  # [4]
    deltas = ins["TargetBox"][0]  # [R, 4*C]
    score = ins["BoxScore"][0]  # [R, C]
    clip = op.attr("box_clip", 4.135)
    R = prior.shape[0]
    C = deltas.shape[1] // 4
    d = deltas.reshape(R, C, 4)
    pw = prior[:, 2] - prior[:, 0] + 1.0
    ph = prior[:, 3] - prior[:, 1] + 1.0
    pcx = prior[:, 0] + 0.5 * pw
    pcy = prior[:, 1] + 0.5 * ph
    cx = var[0] * d[..., 0] * pw[:, None] + pcx[:, None]
    cy = var[1] * d[..., 1] * ph[:, None] + pcy[:, None]
    w = jnp.exp(jnp.minimum(var[2] * d[..., 2], clip)) * pw[:, None]
    h = jnp.exp(jnp.minimum(var[3] * d[..., 3], clip)) * ph[:, None]
    decoded = jnp.stack([
        cx - 0.5 * w, cy - 0.5 * h,
        cx + 0.5 * w - 1.0, cy + 0.5 * h - 1.0,
    ], axis=-1)  # [R, C, 4]
    best = jnp.argmax(score[:, 1:], axis=1) + 1  # skip background col 0
    assign = jnp.take_along_axis(
        decoded, best[:, None, None].repeat(4, 2), axis=1
    )[:, 0]
    return {
        "DecodeBox": [decoded.reshape(R, C * 4)],
        "OutputAssignBox": [assign],
    }


@register_op(
    "retinanet_detection_output",
    inputs=["BBoxes", "Scores", "Anchors", "ImInfo"],
    outputs=["Out"],
    differentiable=False,
)
def _retinanet_detection_output(ctx, op, ins):
    """retinanet_detection_output_op.cc: per FPN level take nms_top_k
    scoring anchors, decode deltas, then class-wise NMS over the union.
    Output rows [label, score, x1, y1, x2, y2], -1 padded (house NMS
    contract, ops/detection.py)."""
    score_thresh = op.attr("score_threshold", 0.05)
    nms_top_k = int(op.attr("nms_top_k", 1000))
    keep_top_k = int(op.attr("keep_top_k", 100))
    nms_thresh = op.attr("nms_threshold", 0.3)
    im_info = ins["ImInfo"][0].astype(jnp.float32).reshape(-1)

    all_boxes, all_scores = [], []
    for bx, sc, an in zip(ins["BBoxes"], ins["Scores"], ins["Anchors"]):
        deltas = bx.reshape(-1, 4)
        scores = sc.reshape(deltas.shape[0], -1)  # [A, C] sigmoid scores
        anchors = an.reshape(-1, 4)
        C = scores.shape[1]
        k = min(nms_top_k, deltas.shape[0])
        best = jnp.max(scores, axis=1)
        _, top_i = lax.top_k(best, k)
        d = deltas[top_i]
        a = anchors[top_i]
        s = scores[top_i]
        aw = a[:, 2] - a[:, 0] + 1.0
        ah = a[:, 3] - a[:, 1] + 1.0
        acx = a[:, 0] + 0.5 * aw
        acy = a[:, 1] + 0.5 * ah
        cx = d[:, 0] * aw + acx
        cy = d[:, 1] * ah + acy
        w = jnp.exp(jnp.minimum(d[:, 2], 10.0)) * aw
        h = jnp.exp(jnp.minimum(d[:, 3], 10.0)) * ah
        boxes = jnp.stack([
            jnp.clip(cx - 0.5 * w, 0, im_info[1] - 1),
            jnp.clip(cy - 0.5 * h, 0, im_info[0] - 1),
            jnp.clip(cx + 0.5 * w - 1, 0, im_info[1] - 1),
            jnp.clip(cy + 0.5 * h - 1, 0, im_info[0] - 1),
        ], axis=1)
        all_boxes.append(boxes)
        all_scores.append(s)
    boxes = jnp.concatenate(all_boxes, axis=0)  # [M, 4]
    scores = jnp.concatenate(all_scores, axis=0)  # [M, C]
    M, C = scores.shape
    rows = []
    for c in range(C):
        sc = jnp.where(scores[:, c] >= score_thresh, scores[:, c], -jnp.inf)
        alive = _greedy_nms(boxes, jnp.isfinite(sc), nms_thresh)
        sc = jnp.where(alive, sc, -jnp.inf)
        rows.append(jnp.concatenate([
            jnp.full((M, 1), c, jnp.float32),
            sc[:, None], boxes,
        ], axis=1))
    flat = jnp.concatenate(rows, axis=0)
    k = min(keep_top_k, flat.shape[0])
    top_s, top_i = lax.top_k(flat[:, 1], k)
    out = flat[top_i]
    out = jnp.where(jnp.isfinite(top_s)[:, None], out,
                    jnp.concatenate([jnp.full((k, 1), -1.0),
                                     jnp.zeros((k, 5))], axis=1))
    return {"Out": [out]}


@register_op(
    "locality_aware_nms",
    inputs=["BBoxes", "Scores"],
    outputs=["Out"],
    differentiable=False,
)
def _locality_aware_nms(ctx, op, ins):
    """locality_aware_nms_op.cc (EAST text detection): row-scan merge of
    consecutive overlapping boxes (score-weighted average), then standard
    class-wise NMS. lax.scan carries the running merged box."""
    boxes = ins["BBoxes"][0].reshape(-1, 4).astype(jnp.float32)  # [M, 4]
    scores = ins["Scores"][0]
    if scores.ndim == 3:
        scores = scores[0]
    scores = scores.reshape(-1, boxes.shape[0])  # [C, M]
    nms_thresh = op.attr("nms_threshold", 0.3)
    score_thresh = op.attr("score_threshold", 0.0)
    keep_top_k = int(op.attr("keep_top_k", 100))
    M = boxes.shape[0]
    C = scores.shape[0]

    def iou_one(a, b):
        lt = jnp.maximum(a[:2], b[:2])
        rb = jnp.minimum(a[2:], b[2:])
        wh = jnp.maximum(rb - lt, 0)
        inter = wh[0] * wh[1]
        area = lambda q: jnp.maximum(q[2] - q[0], 0) * jnp.maximum(
            q[3] - q[1], 0
        )
        return inter / jnp.maximum(area(a) + area(b) - inter, 1e-10)

    def merge_pass(sc):
        # scan rows in order; merge current into the running box when
        # overlapping, else emit the running box
        def step(carry, i):
            cur_box, cur_s, out_b, out_s, n_out = carry
            b, s = boxes[i], sc[i]
            live = s > score_thresh
            ov = iou_one(cur_box, b)
            do_merge = live & (ov > nms_thresh) & (cur_s > 0)
            ws = cur_s + s
            merged = (cur_box * cur_s + b * s) / jnp.maximum(ws, 1e-10)
            # emit the running box when switching to a non-overlapping one
            emit = live & ~do_merge & (cur_s > 0)
            out_b = out_b.at[n_out].set(
                jnp.where(emit, cur_box, out_b[n_out])
            )
            out_s = out_s.at[n_out].set(jnp.where(emit, cur_s, out_s[n_out]))
            n_out = n_out + emit.astype(jnp.int32)
            new_box = jnp.where(do_merge, merged,
                                jnp.where(live, b, cur_box))
            new_s = jnp.where(do_merge, ws, jnp.where(live, s, cur_s))
            return (new_box, new_s, out_b, out_s, n_out), None

        init = (
            jnp.zeros((4,)), jnp.zeros(()),
            jnp.zeros((M + 1, 4)), jnp.zeros((M + 1,)),
            jnp.zeros((), jnp.int32),
        )
        (cur_box, cur_s, out_b, out_s, n_out), _ = lax.scan(
            step, init, jnp.arange(M)
        )
        out_b = out_b.at[n_out].set(
            jnp.where(cur_s > 0, cur_box, out_b[n_out])
        )
        out_s = out_s.at[n_out].set(jnp.where(cur_s > 0, cur_s, out_s[n_out]))
        return out_b[:M], out_s[:M]

    rows = []
    for c in range(C):
        mb, ms = merge_pass(scores[c])
        alive = _greedy_nms(mb, ms > 0, nms_thresh)
        s = jnp.where(alive & (ms > 0), ms, -jnp.inf)
        rows.append(jnp.concatenate([
            jnp.full((M, 1), c, jnp.float32), s[:, None], mb,
        ], axis=1))
    flat = jnp.concatenate(rows, axis=0)
    k = min(keep_top_k, flat.shape[0])
    top_s, top_i = lax.top_k(flat[:, 1], k)
    out = flat[top_i]
    out = jnp.where(jnp.isfinite(top_s)[:, None], out,
                    jnp.concatenate([jnp.full((k, 1), -1.0),
                                     jnp.zeros((k, 5))], axis=1))
    return {"Out": [out]}


@register_op(
    "multiclass_nms2",
    inputs=["BBoxes", "Scores", "RoisNum"],
    outputs=["Out", "Index", "NmsRoisNum"],
    differentiable=False,
)
def _multiclass_nms2(ctx, op, ins):
    """multiclass_nms2 (multiclass_nms_op.cc second registration): same
    kernel plus Index — the kept box's index into the INPUT box set
    (reference contract; -1 on padded rows)."""
    from .detection import multiclass_nms_core

    out, num, in_idx = multiclass_nms_core(
        ins["BBoxes"][0], ins["Scores"][0], op.attrs
    )
    n_img, k = out.shape[:2]
    return {
        "Out": [out],
        "Index": [in_idx.reshape(n_img * k, 1)],
        "NmsRoisNum": [num],
    }


@register_op("polygon_box_transform", inputs=["Input"], outputs=["Output"])
def _polygon_box_transform(ctx, op, ins):
    """polygon_box_transform_op.cc (EAST): even geo channels are x offsets
    (out = 4*w - in), odd are y offsets (out = 4*h - in)."""
    x = ins["Input"][0]  # [N, geo, H, W]
    n, g, h, w = x.shape
    xs = jnp.arange(w, dtype=x.dtype)[None, None, None, :] * 4.0
    ys = jnp.arange(h, dtype=x.dtype)[None, None, :, None] * 4.0
    even = jnp.arange(g) % 2 == 0
    return {
        "Output": [jnp.where(even[None, :, None, None], xs - x, ys - x)]
    }


@register_op(
    "roi_perspective_transform",
    inputs=["X", "ROIs"],
    outputs=["Out", "Mask", "TransformMatrix", "Out2InIdx", "Out2InWeights"],
)
def _roi_perspective_transform(ctx, op, ins):
    """roi_perspective_transform_op.cc (OCR): warp each quadrilateral ROI
    [x1..y4] to a rectangle [transformed_height, transformed_width] via
    the quad->rect homography (solved in closed form as an 8x8 system per
    roi, batched through jnp.linalg.solve) + bilinear sampling.
    Differentiable through the sampling; the reference's Out2InIdx/
    Out2InWeights exist for its hand-written backward and are empty here
    (generic vjp)."""
    x = ins["X"][0]  # [N, C, H, W]
    rois = ins["ROIs"][0].astype(jnp.float32)  # [R, 8] 4 corner points
    out_h = int(op.attr("transformed_height", 8))
    out_w = int(op.attr("transformed_width", 8))
    scale = op.attr("spatial_scale", 1.0)
    N, Cc, H, W = x.shape
    R = rois.shape[0]

    def homography(quad):
        # map rect corners (0,0),(w-1,0),(w-1,h-1),(0,h-1) -> quad pts
        src = jnp.asarray([
            [0.0, 0.0], [out_w - 1.0, 0.0],
            [out_w - 1.0, out_h - 1.0], [0.0, out_h - 1.0],
        ])
        dst = quad.reshape(4, 2) * scale
        rowsA = []
        rhs = []
        for i in range(4):
            sx, sy = src[i, 0], src[i, 1]
            dx, dy = dst[i, 0], dst[i, 1]
            rowsA.append(jnp.stack([
                sx, sy, jnp.asarray(1.0), jnp.asarray(0.0),
                jnp.asarray(0.0), jnp.asarray(0.0), -dx * sx, -dx * sy,
            ]))
            rhs.append(dx)
            rowsA.append(jnp.stack([
                jnp.asarray(0.0), jnp.asarray(0.0), jnp.asarray(0.0),
                sx, sy, jnp.asarray(1.0), -dy * sx, -dy * sy,
            ]))
            rhs.append(dy)
        A = jnp.stack(rowsA)
        b = jnp.stack(rhs)
        h8 = jnp.linalg.solve(A + 1e-8 * jnp.eye(8), b)
        return jnp.concatenate([h8, jnp.ones((1,))]).reshape(3, 3)

    mats = jax.vmap(homography)(rois)  # [R, 3, 3]
    gy, gx = jnp.meshgrid(
        jnp.arange(out_h, dtype=jnp.float32),
        jnp.arange(out_w, dtype=jnp.float32), indexing="ij",
    )
    grid = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [h, w, 3]

    def warp_one(mat):
        uvw = jnp.einsum("hwk,jk->hwj", grid, mat)
        u = uvw[..., 0] / jnp.maximum(jnp.abs(uvw[..., 2]), 1e-8) * jnp.sign(
            uvw[..., 2]
        )
        v = uvw[..., 1] / jnp.maximum(jnp.abs(uvw[..., 2]), 1e-8) * jnp.sign(
            uvw[..., 2]
        )
        inside = (u >= 0) & (u <= W - 1) & (v >= 0) & (v <= H - 1)
        u0 = jnp.floor(u)
        v0 = jnp.floor(v)
        du = u - u0
        dv = v - v0
        acc = 0.0
        img = x[0]  # single-image contract (reference walks roi batch ids)
        for ddy, wy in ((0.0, 1 - dv), (1.0, dv)):
            for ddx, wx in ((0.0, 1 - du), (1.0, du)):
                yi = jnp.clip(v0 + ddy, 0, H - 1).astype(jnp.int32)
                xi = jnp.clip(u0 + ddx, 0, W - 1).astype(jnp.int32)
                acc = acc + img[:, yi, xi] * (wy * wx)[None]
        return acc * inside[None], inside

    outs, masks = jax.vmap(warp_one)(mats)  # [R, C, h, w], [R, h, w]
    return {
        "Out": [outs],
        "Mask": [masks[:, None].astype(jnp.int32)],
        "TransformMatrix": [mats.reshape(R, 9)],
        "Out2InIdx": [],
        "Out2InWeights": [],
    }
