"""Neural-net ops: conv2d, pooling, batch/layer/instance/group norm, embedding,
dropout, interpolation, losses.

Reference parity: operators/conv_op.cc (+conv_cudnn_op.cu), pool_op.cc,
batch_norm_op.cc, layer_norm_op.cc, dropout_op.cc, lookup_table_v2_op.cc,
cross_entropy_op.cc, softmax_with_cross_entropy_op.cc, smooth_l1_loss,
huber_loss, squared_l2 — as XLA emitters. Convs keep the fluid NCHW contract
at the op boundary but compute in NHWC internally (_nhwc_conv): XLA:TPU lowers
NCHW convs ~20x slower on v5e. BatchNorm running stats are expressed
functionally: MeanOut/VarianceOut are op outputs the Executor writes back to
the Scope (the reference mutates them in place, batch_norm_op.cc).
"""

from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.registry import get_op_def, register_op


def _pair(v):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v, v]


def _conv_pads(paddings, algorithm, ksize, strides, dilations):
    if algorithm == "SAME":
        return "SAME"
    if algorithm == "VALID":
        return "VALID"
    p = _pair(paddings)
    if len(p) == 2:
        return [(p[0], p[0]), (p[1], p[1])]
    # [top, bottom, left, right]
    return [(p[0], p[1]), (p[2], p[3])]


def _nhwc_conv(x, w_oihw, **conv_kwargs):
    """conv_general_dilated computed in NHWC: XLA:TPU lowers NCHW convs ~20x
    slower on v5e (no automatic relayout); the wrapping transposes fuse into
    neighbors. Takes/returns NCHW (the public fluid op contract), weights
    OIHW."""
    out = lax.conv_general_dilated(
        jnp.transpose(x, (0, 2, 3, 1)),
        jnp.transpose(w_oihw, (2, 3, 1, 0)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        **conv_kwargs,
    )
    return jnp.transpose(out, (0, 3, 1, 2))


@register_op("conv2d", inputs=["Input", "Filter"], outputs=["Output"])
def _conv2d(ctx, op, ins):
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = _pair(op.attr("strides", [1, 1]))
    dilations = _pair(op.attr("dilations", [1, 1]))
    pads = _conv_pads(
        op.attr("paddings", [0, 0]),
        op.attr("padding_algorithm", "EXPLICIT"),
        w.shape[2:],
        strides,
        dilations,
    )
    groups = op.attr("groups", 1) or 1
    out = _nhwc_conv(
        x,
        w,
        window_strides=strides,
        padding=pads,
        rhs_dilation=dilations,
        feature_group_count=groups,
    )
    return {"Output": [out]}


@register_op("depthwise_conv2d", inputs=["Input", "Filter"], outputs=["Output"])
def _depthwise_conv2d(ctx, op, ins):
    op.attrs.setdefault("groups", ins["Input"][0].shape[1])
    return _conv2d(ctx, op, ins)


@register_op(
    "conv2d_transpose", inputs=["Input", "Filter"], outputs=["Output"]
)
def _conv2d_transpose(ctx, op, ins):
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = _pair(op.attr("strides", [1, 1]))
    p = _pair(op.attr("paddings", [0, 0]))
    # fluid filter layout for transpose conv: [in_c, out_c/groups, kh, kw]
    g = op.attr("groups", 1) or 1
    in_c, oc_g, kh, kw = w.shape
    pads = [
        (kh - 1 - p[0], kh - 1 - p[0]),
        (kw - 1 - p[1], kw - 1 - p[1]),
    ]
    # per-group swap to OIHW: [g, in_c/g, oc/g, kh, kw] -> [oc, in_c/g, kh, kw]
    w_t = jnp.flip(w, axis=(2, 3)).reshape(g, in_c // g, oc_g, kh, kw)
    w_t = w_t.transpose(0, 2, 1, 3, 4).reshape(g * oc_g, in_c // g, kh, kw)
    out = _nhwc_conv(
        x,
        w_t,
        window_strides=[1, 1],
        padding=pads,
        lhs_dilation=strides,
        feature_group_count=g,
    )
    return {"Output": [out]}


@register_op("pool2d", inputs=["X"], outputs=["Out"])
def _pool2d(ctx, op, ins):
    x = ins["X"][0]
    ptype = op.attr("pooling_type", "max")
    if op.attr("global_pooling", False) or op.attr("adaptive", False) and op.attr(
        "ksize"
    ) == [1, 1]:
        red = jnp.max if ptype == "max" else jnp.mean
        return {"Out": [red(x, axis=(2, 3), keepdims=True)]}
    if op.attr("adaptive", False):
        oh, ow = _pair(op.attr("ksize"))
        n, c, h, wd = x.shape
        xr = x.reshape(n, c, oh, h // oh, ow, wd // ow)
        red = jnp.max if ptype == "max" else jnp.mean
        return {"Out": [red(xr, axis=(3, 5))]}
    ksize = _pair(op.attr("ksize"))
    strides = _pair(op.attr("strides", [1, 1]))
    p = _pair(op.attr("paddings", [0, 0]))
    pads = [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])]
    dims = (1, 1, ksize[0], ksize[1])
    strd = (1, 1, strides[0], strides[1])
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        out = lax.reduce_window(x, init, lax.max, dims, strd, pads)
    else:
        summed = lax.reduce_window(x, 0.0, lax.add, dims, strd, pads)
        if op.attr("exclusive", True) and (p[0] or p[1]):
            ones = jnp.ones_like(x)
            counts = lax.reduce_window(ones, 0.0, lax.add, dims, strd, pads)
            out = summed / counts
        else:
            out = summed / (ksize[0] * ksize[1])
    return {"Out": [out]}


@register_op(
    "batch_norm",
    inputs=["X", "Scale", "Bias", "Mean", "Variance"],
    outputs=["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"],
    mutates=(("MeanOut", "Mean"), ("VarianceOut", "Variance")),
)
def _batch_norm(ctx, op, ins):
    x, scale, bias, mean, var = (ins[k][0] for k in ("X", "Scale", "Bias", "Mean", "Variance"))
    eps = op.attr("epsilon", 1e-5)
    momentum = op.attr("momentum", 0.9)
    layout = op.attr("data_layout", "NCHW")
    axes = tuple(i for i in range(x.ndim) if i != (1 if layout == "NCHW" else x.ndim - 1))
    bshape = [1] * x.ndim
    bshape[1 if layout == "NCHW" else -1] = x.shape[1 if layout == "NCHW" else -1]

    if op.attr("is_test", False) or op.attr("use_global_stats", False):
        use_mean, use_var = mean, var
        mean_out, var_out = mean, var
        saved_mean = mean
        saved_var = var
    else:
        # single-pass stats: E[x^2] - E[x]^2 with fp32 ACCUMULATION but no
        # fp32 materialization of x — jnp reductions take an accumulation
        # dtype, and XLA fuses convert+square INTO the reduction, so a
        # bf16 activation is read twice (mean, m2) instead of being written
        # out as fp32 (at ResNet stage-1 shapes that fp32 temporary
        # dominated the BN cost). BN inputs are near zero-mean, so the
        # cancellation in m2 - mean^2 is benign in fp32 (the cuDNN-style
        # fused-BN formulation).
        use_mean = jnp.mean(x, axis=axes, dtype=jnp.float32)
        m2 = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axes)
        use_var = jnp.maximum(m2 - jnp.square(use_mean), 0.0)
        mean_out = mean * momentum + use_mean.astype(mean.dtype) * (1 - momentum)
        var_out = var * momentum + use_var.astype(var.dtype) * (1 - momentum)
        saved_mean = use_mean
        saved_var = use_var
    # normalize as one per-channel affine in the INPUT dtype: y = x*a + b
    # (a, b computed per-channel in fp32) — keeps the big elementwise pass
    # bf16 under AMP and fusable with neighboring activations
    inv = lax.rsqrt(use_var.astype(jnp.float32) + eps)
    a = scale.astype(jnp.float32) * inv
    bvec = bias.astype(jnp.float32) - use_mean.astype(jnp.float32) * a
    y = x * a.astype(x.dtype).reshape(bshape) + bvec.astype(x.dtype).reshape(
        bshape
    )
    return {
        "Y": [y],
        "MeanOut": [mean_out],
        "VarianceOut": [var_out],
        "SavedMean": [saved_mean],
        "SavedVariance": [saved_var],
    }


def _ln_use_pallas(ctx, x, begin):
    from ..flags import flag
    from ..kernels import layer_norm as lnk

    rows = int(np.prod(x.shape[:begin])) if begin else 1
    n = int(np.prod(x.shape[begin:]))
    gspmd_mode = (
        not ctx.mesh_axes
        and ctx.program is not None
        and getattr(ctx.program, "_mesh", None) is not None
    )
    # OFF by default: measured on BERT-base, the standalone kernel LOSES to
    # XLA's fused jnp formulation (~6% step regression) — the custom call
    # is a fusion barrier, so the residual add feeding each LN materializes
    # instead of fusing into the normalization pass. The kernel stays for
    # workloads where LN is isolated (enable with
    # FLAGS_paddle_tpu_pallas_layer_norm=1); the dedicated grad op below
    # follows the same flag via _layer_norm_grad_maker — generic vjp when
    # the flag is off.
    return (
        bool(flag("paddle_tpu_pallas_layer_norm"))
        and not gspmd_mode
        and jax.default_backend() == "tpu"
        and lnk.supports(rows, n, x.dtype)
    ), rows, n


@register_op(
    "layer_norm",
    inputs=["X", "Scale", "Bias"],
    outputs=["Y", "Mean", "Variance"],
)
def _layer_norm(ctx, op, ins):
    x = ins["X"][0]
    scale = ins["Scale"][0] if ins.get("Scale") and ins["Scale"][0] is not None else None
    bias = ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None else None
    eps = op.attr("epsilon", 1e-5)
    begin = op.attr("begin_norm_axis", 1)
    use_pallas, rows, n = _ln_use_pallas(ctx, x, begin)
    if use_pallas:
        # Pallas kernel: one read + one write per pass, fp32 stats in
        # registers — the jnp form materializes fp32 temporaries between
        # the mean/var/normalize passes. The _diff wrapper carries a
        # custom_vjp so fallback autodiff paths (generic __vjp__, dygraph
        # tape) can differentiate through the Mosaic call
        # (kernels/layer_norm.py).
        from ..kernels.layer_norm import layer_norm_fwd_diff

        y2, mean, var = layer_norm_fwd_diff(
            x.reshape(rows, n),
            scale.reshape(n) if scale is not None
            else jnp.ones((n,), jnp.float32),
            bias.reshape(n) if bias is not None
            else jnp.zeros((n,), jnp.float32),
            eps,
        )
        lead = x.shape[:begin]
        return {
            "Y": [y2.reshape(x.shape)],
            "Mean": [mean.reshape(lead)],
            "Variance": [var.reshape(lead)],
        }
    axes = tuple(range(begin, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    y = ((xf - mean) * lax.rsqrt(var + eps)).astype(x.dtype)
    if scale is not None:
        y = y * scale.reshape(x.shape[begin:]).astype(x.dtype)
    if bias is not None:
        y = y + bias.reshape(x.shape[begin:]).astype(x.dtype)
    lead = x.shape[:begin]
    return {
        "Y": [y],
        "Mean": [mean.reshape(lead)],
        "Variance": [var.reshape(lead)],
    }


def _layer_norm_grad_maker(op, block, contribs, finalize, needs_grad=None):
    """Dedicated grad op, emitted only when the Pallas LN kernel is enabled
    (a Mosaic forward must not be replayed — XLA cannot CSE custom calls).
    With the default jnp formulation the generic __vjp__ replay IS CSE'd
    and its derived backward fuses better than hand-written formulas
    (measured on BERT), so this declines. Also declines when the auxiliary
    Mean/Variance outputs carry gradients."""
    from ..flags import flag
    from ..framework import unique_name
    from ..framework.backward import _ensure_var
    from ..framework.program import grad_var_name

    if not flag("paddle_tpu_pallas_layer_norm"):
        return False
    for aux in ("Mean", "Variance"):
        names = op.outputs.get(aux) or []
        if names and names[0] in contribs:
            return False  # fall back to the generic __vjp__
    g_out = finalize(op.outputs["Y"][0])
    if g_out is None:
        return
    inputs = {"X": op.inputs["X"], "YGrad": [g_out]}
    for slot in ("Scale", "Bias"):
        if op.inputs.get(slot):
            inputs[slot] = op.inputs[slot]
    outs = {}
    for slot in ("X", "Scale", "Bias"):
        names = op.inputs.get(slot) or []
        if not names or not names[0]:
            continue
        n = names[0]
        if needs_grad is not None and n not in needs_grad:
            continue
        gname = unique_name.generate(grad_var_name(n) + "@RENAME")
        _ensure_var(block, gname, n)
        outs[slot + "Grad"] = [gname]
        contribs.setdefault(n, []).append(gname)
    if not outs:
        return
    attrs = {
        k: v for k, v in op.attrs.items() if k not in ("__uid__", "__loc__")
    }
    block.append_op("layer_norm_grad", inputs, outs, attrs)


get_op_def("layer_norm").grad_maker = _layer_norm_grad_maker


@register_op(
    "layer_norm_grad",
    inputs=["X", "Scale", "Bias", "YGrad"],
    outputs=["XGrad", "ScaleGrad", "BiasGrad"],
    differentiable=False,
)
def _layer_norm_grad(ctx, op, ins):
    x = ins["X"][0]
    scale = (
        ins["Scale"][0]
        if ins.get("Scale") and ins["Scale"][0] is not None
        else None
    )
    dy = ins["YGrad"][0]
    eps = op.attr("epsilon", 1e-5)
    begin = op.attr("begin_norm_axis", 1)
    use_pallas, rows, n = _ln_use_pallas(ctx, x, begin)
    if use_pallas:
        from ..kernels.layer_norm import layer_norm_bwd

        dx2, ds, db = layer_norm_bwd(
            x.reshape(rows, n),
            scale.reshape(n) if scale is not None else None,
            dy.reshape(rows, n),
            eps,
        )
        dx = dx2.reshape(x.shape)
    else:
        axes = tuple(range(begin, x.ndim))
        xf = x.astype(jnp.float32)
        dyf = dy.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.var(xf, axis=axes, keepdims=True)
        rstd = lax.rsqrt(var + eps)
        xhat = (xf - mean) * rstd
        sf = (
            scale.reshape(x.shape[begin:]).astype(jnp.float32)
            if scale is not None
            else 1.0
        )
        dyw = dyf * sf
        m1 = jnp.mean(dyw, axis=axes, keepdims=True)
        m2 = jnp.mean(dyw * xhat, axis=axes, keepdims=True)
        dx = (rstd * (dyw - m1 - xhat * m2)).astype(x.dtype)
        lead_axes = tuple(range(begin))
        ds = jnp.sum(dyf * xhat, axis=lead_axes).reshape(-1)
        db = jnp.sum(dyf, axis=lead_axes).reshape(-1)
    outs = {}
    if op.outputs.get("XGrad"):
        outs["XGrad"] = [dx]
    if op.outputs.get("ScaleGrad"):
        outs["ScaleGrad"] = [ds.reshape(scale.shape).astype(scale.dtype)]
    if op.outputs.get("BiasGrad"):
        b = ins["Bias"][0]
        outs["BiasGrad"] = [db.reshape(b.shape).astype(b.dtype)]
    return outs


@register_op("instance_norm", inputs=["X", "Scale", "Bias"], outputs=["Y"])
def _instance_norm(ctx, op, ins):
    x = ins["X"][0]
    eps = op.attr("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps)
    bshape = [1, x.shape[1]] + [1] * (x.ndim - 2)
    if ins.get("Scale") and ins["Scale"][0] is not None:
        y = y * ins["Scale"][0].reshape(bshape)
    if ins.get("Bias") and ins["Bias"][0] is not None:
        y = y + ins["Bias"][0].reshape(bshape)
    return {"Y": [y]}


@register_op("group_norm", inputs=["X", "Scale", "Bias"], outputs=["Y"])
def _group_norm(ctx, op, ins):
    x = ins["X"][0]
    g = op.attr("groups")
    eps = op.attr("epsilon", 1e-5)
    n, c = x.shape[0], x.shape[1]
    xr = x.reshape(n, g, c // g, *x.shape[2:])
    axes = tuple(range(2, xr.ndim))
    mean = jnp.mean(xr, axis=axes, keepdims=True)
    var = jnp.var(xr, axis=axes, keepdims=True)
    y = ((xr - mean) * lax.rsqrt(var + eps)).reshape(x.shape)
    bshape = [1, c] + [1] * (x.ndim - 2)
    if ins.get("Scale") and ins["Scale"][0] is not None:
        y = y * ins["Scale"][0].reshape(bshape)
    if ins.get("Bias") and ins["Bias"][0] is not None:
        y = y + ins["Bias"][0].reshape(bshape)
    return {"Y": [y]}


@register_op("lookup_table_v2", inputs=["W", "Ids"], outputs=["Out"])
def _lookup_table_v2(ctx, op, ins):
    w, ids = ins["W"][0], ins["Ids"][0]
    padding_idx = op.attr("padding_idx", -1)
    out = jnp.take(w, ids, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None].astype(out.dtype)
        out = out * mask
    return {"Out": [out]}


@register_op("lookup_table", inputs=["W", "Ids"], outputs=["Out"])
def _lookup_table(ctx, op, ins):
    # v1 keeps a trailing [.., 1] ids dim (lookup_table_op.cc)
    w, ids = ins["W"][0], ins["Ids"][0]
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    ins2 = {"W": [w], "Ids": [ids]}
    return _lookup_table_v2(ctx, op, ins2)


@register_op("dropout", inputs=["X"], outputs=["Out", "Mask"])
def _dropout(ctx, op, ins):
    x = ins["X"][0]
    p = op.attr("dropout_prob", 0.5)
    impl = op.attr("dropout_implementation", "downgrade_in_infer")
    if op.attr("is_test", False) or ctx.is_test or p == 0.0:
        out = x if impl == "upscale_in_train" else x * (1.0 - p)
        return {"Out": [out], "Mask": []}
    key = ctx.key_for(op.uid, op.type)
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    if impl == "upscale_in_train":
        out = jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)
    else:
        out = jnp.where(keep, x, 0.0).astype(x.dtype)
    return {"Out": [out], "Mask": [keep.astype(np.uint8)]}


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------


@register_op("cross_entropy", inputs=["X", "Label"], outputs=["Y"])
def _cross_entropy(ctx, op, ins):
    x, label = ins["X"][0], ins["Label"][0]
    eps = 1e-9
    if op.attr("soft_label", False):
        y = -jnp.sum(label * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        if label.ndim == x.ndim:
            label = label[..., 0]
        picked = jnp.take_along_axis(x, label[..., None].astype(np.int32), axis=-1)
        y = -jnp.log(picked + eps)
    return {"Y": [y]}


@register_op(
    "softmax_with_cross_entropy",
    inputs=["Logits", "Label"],
    outputs=["Softmax", "Loss"],
)
def _softmax_with_cross_entropy(ctx, op, ins):
    """Hard labels use the logsumexp-minus-picked form with fp32
    accumulation: loss = lse(logits) - logits[label]. Unlike a
    materialized log_softmax, nothing [N, V]-shaped in fp32 ever reaches
    HBM — at a GPT LM head ([B*S, 32k] logits) the log_softmax
    formulation under the old fp32 black-listing cost ~GBs of cast +
    materialize traffic per step. The op is precision-robust with bf16
    logits (max/sum reduce in fp32), so AMP no longer black-lists it.
    The Softmax output is computed lazily from the same pieces; XLA DCEs
    it when (as in every loss head) nothing consumes it."""
    logits, label = ins["Logits"][0], ins["Label"][0]
    axis = op.attr("axis", -1)
    if op.attr("soft_label", False):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
        return {"Softmax": [jnp.exp(logp)], "Loss": [loss]}
    if label.ndim == logits.ndim:
        lbl = label
    else:
        lbl = label[..., None]
    ignore = op.attr("ignore_index", -100)
    valid = lbl != ignore
    safe_lbl = jnp.where(valid, lbl, 0).astype(np.int32)
    m = jnp.max(logits, axis=axis, keepdims=True).astype(jnp.float32)
    sumexp = jnp.sum(
        jnp.exp(logits.astype(jnp.float32) - m), axis=axis, keepdims=True,
        dtype=jnp.float32,
    )
    lse = m + jnp.log(sumexp)
    picked = jnp.take_along_axis(logits, safe_lbl, axis=axis)
    loss = jnp.where(valid, lse - picked.astype(jnp.float32), 0.0)
    softmax = jnp.exp(logits.astype(jnp.float32) - lse).astype(logits.dtype)
    return {"Softmax": [softmax], "Loss": [loss]}


@register_op("square_error_cost", inputs=["X", "Y"], outputs=["Out"])
def _square_error_cost(ctx, op, ins):
    d = ins["X"][0] - ins["Y"][0]
    return {"Out": [d * d]}


@register_op("huber_loss", inputs=["X", "Y"], outputs=["Out", "Residual"])
def _huber_loss(ctx, op, ins):
    x, y = ins["X"][0], ins["Y"][0]
    delta = op.attr("delta", 1.0)
    r = y - x
    a = jnp.abs(r)
    loss = jnp.where(a <= delta, 0.5 * r * r, delta * (a - 0.5 * delta))
    return {"Out": [loss], "Residual": [r]}


@register_op("smooth_l1_loss", inputs=["X", "Y"], outputs=["Out", "Diff"])
def _smooth_l1(ctx, op, ins):
    x, y = ins["X"][0], ins["Y"][0]
    sigma = op.attr("sigma", 1.0)
    s2 = sigma * sigma
    d = x - y
    a = jnp.abs(d)
    val = jnp.where(a < 1.0 / s2, 0.5 * d * d * s2, a - 0.5 / s2)
    return {"Out": [jnp.sum(val, axis=-1, keepdims=True)], "Diff": [d]}


@register_op(
    "sigmoid_cross_entropy_with_logits", inputs=["X", "Label"], outputs=["Out"]
)
def _sigmoid_ce(ctx, op, ins):
    from ._helpers import stable_sigmoid_ce

    x, label = ins["X"][0], ins["Label"][0]
    loss = stable_sigmoid_ce(x, label)
    ignore = op.attr("ignore_index", -100)
    loss = jnp.where(label == ignore, 0.0, loss)
    if op.attr("normalize", False):
        n = jnp.maximum(jnp.sum((label != ignore).astype(x.dtype)), 1.0)
        loss = loss / n
    return {"Out": [loss]}


@register_op("log_loss", inputs=["Predicted", "Labels"], outputs=["Loss"])
def _log_loss(ctx, op, ins):
    p, l = ins["Predicted"][0], ins["Labels"][0]
    eps = op.attr("epsilon", 1e-4)
    return {"Loss": [-l * jnp.log(p + eps) - (1 - l) * jnp.log(1 - p + eps)]}


@register_op("kldiv_loss", inputs=["X", "Target"], outputs=["Loss"])
def _kldiv(ctx, op, ins):
    x, t = ins["X"][0], ins["Target"][0]
    loss = jnp.where(t > 0, t * (jnp.log(t) - x), 0.0)
    red = op.attr("reduction", "mean")
    if red == "mean":
        loss = jnp.mean(loss).reshape([1])
    elif red == "sum":
        loss = jnp.sum(loss).reshape([1])
    elif red == "batchmean":
        loss = (jnp.sum(loss) / x.shape[0]).reshape([1])
    return {"Loss": [loss]}


@register_op("nearest_interp", inputs=["X"], outputs=["Out"])
def _nearest_interp(ctx, op, ins):
    x = ins["X"][0]
    n, c, h, w = x.shape
    oh = op.attr("out_h", 0) or int(h * op.attr("scale", 1.0))
    ow = op.attr("out_w", 0) or int(w * op.attr("scale", 1.0))
    if oh % h == 0 and ow % w == 0 and not op.attr("align_corners", False):
        # integer upscale (the FPN-neck x2 case): broadcast+reshape repeat.
        # jax.image.resize's nearest gather transposes to a scatter-add on
        # TPU; the broadcast's transpose is a block reduce-sum — no scatter
        fh, fw = oh // h, ow // w
        out = jnp.broadcast_to(
            x[:, :, :, None, :, None], (n, c, h, fh, w, fw)
        ).reshape(n, c, oh, ow)
        return {"Out": [out]}
    return {
        "Out": [
            jax.image.resize(x, (n, c, oh, ow), method="nearest")
        ]
    }


@register_op("bilinear_interp", inputs=["X"], outputs=["Out"])
def _bilinear_interp(ctx, op, ins):
    x = ins["X"][0]
    n, c, h, w = x.shape
    oh = op.attr("out_h", 0) or int(h * op.attr("scale", 1.0))
    ow = op.attr("out_w", 0) or int(w * op.attr("scale", 1.0))
    return {"Out": [jax.image.resize(x, (n, c, oh, ow), method="bilinear")]}


@register_op("pad2d", inputs=["X"], outputs=["Out"])
def _pad2d(ctx, op, ins):
    x = ins["X"][0]
    p = op.attr("paddings")  # [top, bottom, left, right]
    mode = op.attr("mode", "constant")
    pairs = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    if mode == "constant":
        out = jnp.pad(x, pairs, constant_values=op.attr("pad_value", 0.0))
    else:
        out = jnp.pad(x, pairs, mode={"reflect": "reflect", "edge": "edge"}[mode])
    return {"Out": [out]}
