"""NN-surface long tail: prelu/data_norm/spectral_norm, 3-D conv/pool
family, remaining interpolation modes, deformable ops, position-sensitive
ROI pooling, distillation (fsp), CTR batched-FC ops, and text-matching
convolutions.

Reference files (paddle/fluid/operators/): prelu_op.cc, data_norm_op.cc,
spectral_norm_op.cc, row_conv_op.cc, unpool_op.cc, spp_op.cc, pool_op.cc
(pool3d), max_pool_with_index_op.cc, conv_transpose_op.cc
(conv3d_transpose / depthwise_conv2d_transpose), inplace_abn_op.cc,
sync_batch_norm_op.cu, affine_grid_op.cc, interpolate_op.cc
(linear/bicubic/trilinear), similarity_focus_op.cc, batch_fc_op.cc,
rank_attention_op.cc, fsp_op.cc, deformable_conv_op.cu,
deformable_conv_v1_op.cu, deformable_psroi_pooling_op.cu, prroi_pool_op.cc,
psroi_pool_op.cc, tree_conv_op.cc, var_conv_2d_op.cc,
match_matrix_tensor_op.cc, lstmp_op.cc, attention_lstm_op.cc.

TPU-native formulations: data-dependent loops become dense gathers +
einsums (deformable sampling, PS-ROI bins use a fixed sample grid like our
roi_align); sequence LoD contracts become padded [B, T, ...] + lengths.
"""

from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.registry import register_op
from .nn import _nhwc_conv, _pair


# ---------------------------------------------------------------------------
# activations / normalizers
# ---------------------------------------------------------------------------


@register_op("prelu", inputs=["X", "Alpha"], outputs=["Out"])
def _prelu(ctx, op, ins):
    x, alpha = ins["X"][0], ins["Alpha"][0]
    mode = op.attr("mode", "all")
    if mode == "channel":
        shape = [1] * x.ndim
        shape[1] = x.shape[1]
        alpha = alpha.reshape(shape)
    elif mode == "element":
        alpha = alpha.reshape((1,) + x.shape[1:])
    # "all": scalar broadcasts as-is
    return {"Out": [jnp.where(x > 0, x, alpha * x)]}


@register_op(
    "data_norm",
    inputs=["X", "BatchSize", "BatchSum", "BatchSquareSum"],
    outputs=["Y", "Means", "Scales"],
)
def _data_norm(ctx, op, ins):
    """data_norm_op.cc:  means = sum/size, scales = sqrt(size/square_sum);
    the CTR show-skip path (slot_dim) zeroes slots whose leading "show"
    stat is 0."""
    x = ins["X"][0]
    size = ins["BatchSize"][0]
    s = ins["BatchSum"][0]
    sq = ins["BatchSquareSum"][0]
    means = s / size
    scales = jnp.sqrt(size / sq)
    y = (x - means) * scales
    slot_dim = op.attr("slot_dim", -1)
    if slot_dim > 0:
        C = x.shape[-1]
        show = x[..., 0::slot_dim]  # leading stat of each slot
        live = (jnp.abs(show) >= 1e-7).astype(x.dtype)
        live = jnp.repeat(live, slot_dim, axis=-1)[..., :C]
        y = y * live
    return {"Y": [y], "Means": [means], "Scales": [scales]}


@register_op(
    "spectral_norm", inputs=["Weight", "U", "V"], outputs=["Out"]
)
def _spectral_norm(ctx, op, ins):
    w, u, v = ins["Weight"][0], ins["U"][0], ins["V"][0]
    dim = op.attr("dim", 0)
    power_iters = op.attr("power_iters", 1)
    eps = op.attr("eps", 1e-12)
    perm = [dim] + [i for i in range(w.ndim) if i != dim]
    wm = jnp.transpose(w, perm).reshape(w.shape[dim], -1)

    def _l2(x):
        return x / (jnp.linalg.norm(x) + eps)

    def body(i, uv):
        uu, vv = uv
        vv = _l2(wm.T @ uu)
        uu = _l2(wm @ vv)
        return uu, vv

    u, v = lax.fori_loop(0, power_iters, body, (u, v)) if power_iters else (u, v)
    sigma = u @ wm @ v
    out = jnp.transpose(
        (wm / sigma).reshape([w.shape[dim]] + [w.shape[i] for i in perm[1:]]),
        np.argsort(perm),
    )
    return {"Out": [out]}


@register_op(
    "sync_batch_norm",
    inputs=["X", "Scale", "Bias", "Mean", "Variance"],
    outputs=["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"],
    mutates=(("MeanOut", "Mean"), ("VarianceOut", "Variance")),
)
def _sync_batch_norm(ctx, op, ins):
    """Cross-replica BN (sync_batch_norm_op.cu's NCCL allreduce of the
    partial sums): under shard_map the per-device moments are psum-averaged
    over the data axis, elsewhere it is exactly batch_norm (GSPMD inserts
    the cross-device reduction itself when X is batch-sharded)."""
    x, scale, bias, mean, var = (
        ins[k][0] for k in ("X", "Scale", "Bias", "Mean", "Variance")
    )
    eps = op.attr("epsilon", 1e-5)
    momentum = op.attr("momentum", 0.9)
    layout = op.attr("data_layout", "NCHW")
    ch = 1 if layout == "NCHW" else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != ch)
    bshape = [1] * x.ndim
    bshape[ch] = x.shape[ch]
    if op.attr("is_test", False) or op.attr("use_global_stats", False):
        use_mean, use_var = mean, var
        mean_out, var_out = mean, var
    else:
        use_mean = jnp.mean(x, axis=axes, dtype=jnp.float32)
        m2 = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axes)
        from ..parallel.mesh import DATA_AXIS

        if DATA_AXIS in ctx.mesh_axes:
            use_mean = lax.pmean(use_mean, DATA_AXIS)
            m2 = lax.pmean(m2, DATA_AXIS)
        use_var = jnp.maximum(m2 - jnp.square(use_mean), 0.0)
        mean_out = mean * momentum + use_mean.astype(mean.dtype) * (1 - momentum)
        var_out = var * momentum + use_var.astype(var.dtype) * (1 - momentum)
    inv = lax.rsqrt(use_var.astype(jnp.float32) + eps)
    a = scale.astype(jnp.float32) * inv
    b = bias.astype(jnp.float32) - use_mean.astype(jnp.float32) * a
    y = x * a.astype(x.dtype).reshape(bshape) + b.astype(x.dtype).reshape(bshape)
    return {
        "Y": [y],
        "MeanOut": [mean_out],
        "VarianceOut": [var_out],
        "SavedMean": [use_mean],
        "SavedVariance": [use_var],
    }


@register_op(
    "inplace_abn",
    inputs=["X", "Scale", "Bias", "Mean", "Variance"],
    outputs=["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"],
    mutates=(("MeanOut", "Mean"), ("VarianceOut", "Variance")),
)
def _inplace_abn(ctx, op, ins):
    """inplace_abn_op.cc: BN + activation fused to save activation memory.
    In-place-ness is XLA's buffer assignment here; the fusion is free."""
    outs = _sync_batch_norm(ctx, op, ins)
    act = op.attr("activation", "identity")
    y = outs["Y"][0]
    if act in ("leaky_relu", "leakyrelu"):
        alpha = op.attr("alpha", 0.01)
        y = jnp.where(y > 0, y, alpha * y)
    elif act == "elu":
        alpha = op.attr("alpha", 1.0)
        y = jnp.where(y > 0, y, alpha * (jnp.exp(y) - 1))
    elif act == "identity":
        pass
    else:
        y = getattr(jax.nn, act)(y)
    outs["Y"] = [y]
    return outs


# ---------------------------------------------------------------------------
# row_conv (row_conv_op.cc, DeepSpeech2 lookahead convolution):
# Out[b, t, d] = sum_{j=0..k} W[j, d] * X[b, t+j, d]
# ---------------------------------------------------------------------------


@register_op("row_conv", inputs=["X", "Filter"], outputs=["Out"])
def _row_conv(ctx, op, ins):
    x, w = ins["X"][0], ins["Filter"][0]  # [B,T,D], [k+1, D]
    k = w.shape[0]
    xp = jnp.pad(x, ((0, 0), (0, k - 1), (0, 0)))
    out = jnp.zeros_like(x)
    T = x.shape[1]
    for j in range(k):  # k is small & static: unrolled adds fuse into one pass
        out = out + xp[:, j:j + T, :] * w[j]
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# 3-D conv/pool family
# ---------------------------------------------------------------------------


def _triple(v):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v, v, v]


def _ncdhw_conv(x, w_oidhw, **kw):
    out = lax.conv_general_dilated(
        jnp.transpose(x, (0, 2, 3, 4, 1)),
        jnp.transpose(w_oidhw, (2, 3, 4, 1, 0)),
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
        **kw,
    )
    return jnp.transpose(out, (0, 4, 1, 2, 3))


@register_op(
    "conv3d_transpose", inputs=["Input", "Filter"], outputs=["Output"]
)
def _conv3d_transpose(ctx, op, ins):
    x, w = ins["Input"][0], ins["Filter"][0]  # w: [in_c, out_c/g, kd, kh, kw]
    strides = _triple(op.attr("strides", [1, 1, 1]))
    p = _triple(op.attr("paddings", [0, 0, 0]))
    g = op.attr("groups", 1) or 1
    in_c, oc_g, kd, kh, kw = w.shape
    pads = [
        (kd - 1 - p[0], kd - 1 - p[0]),
        (kh - 1 - p[1], kh - 1 - p[1]),
        (kw - 1 - p[2], kw - 1 - p[2]),
    ]
    w_t = jnp.flip(w, axis=(2, 3, 4)).reshape(g, in_c // g, oc_g, kd, kh, kw)
    w_t = w_t.transpose(0, 2, 1, 3, 4, 5).reshape(
        g * oc_g, in_c // g, kd, kh, kw
    )
    out = _ncdhw_conv(
        x,
        w_t,
        window_strides=[1, 1, 1],
        padding=pads,
        lhs_dilation=strides,
        feature_group_count=g,
    )
    return {"Output": [out]}


@register_op(
    "depthwise_conv2d_transpose", inputs=["Input", "Filter"], outputs=["Output"]
)
def _depthwise_conv2d_transpose(ctx, op, ins):
    from .nn import _conv2d_transpose

    op.attrs.setdefault("groups", ins["Input"][0].shape[1])
    return _conv2d_transpose(ctx, op, ins)


@register_op("pool3d", inputs=["X"], outputs=["Out"])
def _pool3d(ctx, op, ins):
    x = ins["X"][0]
    ptype = op.attr("pooling_type", "max")
    if op.attr("global_pooling", False):
        red = jnp.max if ptype == "max" else jnp.mean
        return {"Out": [red(x, axis=(2, 3, 4), keepdims=True)]}
    ksize = _triple(op.attr("ksize"))
    strides = _triple(op.attr("strides", [1, 1, 1]))
    p = _triple(op.attr("paddings", [0, 0, 0]))
    if op.attr("adaptive", False):
        n, c, d, h, w = x.shape
        od, oh, ow = ksize
        xr = x.reshape(n, c, od, d // od, oh, h // oh, ow, w // ow)
        red = jnp.max if ptype == "max" else jnp.mean
        return {"Out": [red(xr, axis=(3, 5, 7))]}
    pads = [(0, 0), (0, 0)] + [(pi, pi) for pi in p]
    dims = (1, 1, *ksize)
    strd = (1, 1, *strides)
    if ptype == "max":
        return {"Out": [lax.reduce_window(x, -jnp.inf, lax.max, dims, strd, pads)]}
    summed = lax.reduce_window(x, 0.0, lax.add, dims, strd, pads)
    if op.attr("exclusive", True) and any(p):
        counts = lax.reduce_window(
            jnp.ones_like(x), 0.0, lax.add, dims, strd, pads
        )
        return {"Out": [summed / counts]}
    return {"Out": [summed / math.prod(ksize)]}


def _paired_max_reduce(x, idx, dims, strd, pads):
    """reduce_window over (value, index) pairs: max by value, min index on
    ties (the reference kernel's scan order) — one XLA variadic reduce."""

    def reducer(a, b):
        av, ai = a
        bv, bi = b
        take_b = (bv > av) | ((bv == av) & (bi < ai))
        return (
            jnp.where(take_b, bv, av),
            jnp.where(take_b, bi, ai),
        )

    return lax.reduce_window(
        (x, idx),
        (jnp.asarray(-jnp.inf, x.dtype), jnp.asarray(jnp.inf, idx.dtype)),
        reducer,
        dims,
        strd,
        pads,
    )


@register_op(
    "max_pool3d_with_index", inputs=["X"], outputs=["Out", "Mask"],
    differentiable=False,
)
def _max_pool3d_with_index(ctx, op, ins):
    x = ins["X"][0]
    ksize = _triple(op.attr("ksize"))
    strides = _triple(op.attr("strides", [1, 1, 1]))
    p = _triple(op.attr("paddings", [0, 0, 0]))
    pads = [(0, 0), (0, 0)] + [(pi, pi) for pi in p]
    dims = (1, 1, *ksize)
    strd = (1, 1, *strides)
    n, c, d, h, w = x.shape
    flat = jnp.broadcast_to(
        jnp.arange(d * h * w, dtype=jnp.float32).reshape(1, 1, d, h, w), x.shape
    )
    mx_val, mx_idx = _paired_max_reduce(x, flat, dims, strd, pads)
    return {"Out": [mx_val], "Mask": [mx_idx.astype(jnp.int32)]}


@register_op("unpool", inputs=["X", "Indices"], outputs=["Out"])
def _unpool(ctx, op, ins):
    """unpool_op.cc (max-unpool2d): scatter pooled values back to the
    argmax positions recorded by max_pool2d_with_index."""
    x, idx = ins["X"][0], ins["Indices"][0]
    n, c, h, w = x.shape
    oh, ow = op.attr("unpooled_height", 0), op.attr("unpooled_width", 0)
    if not oh:
        ksize = _pair(op.attr("ksize"))
        strides = _pair(op.attr("strides", ksize))
        oh, ow = h * strides[0], w * strides[1]
    flat = jnp.zeros((n, c, oh * ow), x.dtype)
    out = flat.at[
        jnp.arange(n)[:, None, None],
        jnp.arange(c)[None, :, None],
        idx.reshape(n, c, -1),
    ].set(x.reshape(n, c, -1))
    return {"Out": [out.reshape(n, c, oh, ow)]}


def _adaptive_bin_matrix(size, bins):
    """Static [bins, size] 0/1 membership: bin i covers
    floor(i*size/bins) .. ceil((i+1)*size/bins) (spp_op.h's adaptive
    windows — never empty, unlike fixed kernel+pad reshaping)."""
    m = np.zeros((bins, size), np.float32)
    for i in range(bins):
        lo = (i * size) // bins
        hi = -(-((i + 1) * size) // bins)  # ceil
        m[i, lo:max(hi, lo + 1)] = 1.0
    return m


@register_op("spp", inputs=["X"], outputs=["Out"])
def _spp(ctx, op, ins):
    """spp_op.cc spatial pyramid pooling: adaptive pools at 1,2,..,2^(L-1)
    bins per side, flattened and concatenated. Bins come from static
    membership matrices so every bin is non-empty for any feature size."""
    x = ins["X"][0]
    n, c, h, w = x.shape
    L = op.attr("pyramid_height", 1)
    ptype = op.attr("pooling_type", "max")
    outs = []
    for lvl in range(L):
        bins = 2 ** lvl
        mh = jnp.asarray(_adaptive_bin_matrix(h, bins))  # [bins, h]
        mw = jnp.asarray(_adaptive_bin_matrix(w, bins))  # [bins, w]
        if ptype == "max":
            # mask-max over rows then cols
            t = jnp.max(
                jnp.where(mh[None, None, :, :, None] > 0, x[:, :, None], -jnp.inf),
                axis=3,
            )  # [n, c, bins, w]
            pooled = jnp.max(
                jnp.where(mw[None, None, None, :, :] > 0, t[:, :, :, None], -jnp.inf),
                axis=4,
            )  # [n, c, bins, bins]
        else:
            s = jnp.einsum("nchw,ih,jw->ncij", x, mh, mw)
            cnt = jnp.outer(mh.sum(1), mw.sum(1))  # [bins, bins]
            pooled = s / cnt
        outs.append(pooled.reshape(n, -1))
    return {"Out": [jnp.concatenate(outs, axis=1)]}


# ---------------------------------------------------------------------------
# interpolation modes (interpolate_op.cc; nearest/bilinear live in nn.py)
# ---------------------------------------------------------------------------


def _interp_size(op, in_sizes):
    scale = op.attr("scale", 0.0) or 0.0
    names = ["out_d", "out_h", "out_w"][-len(in_sizes):]
    out = []
    for name, s in zip(names, in_sizes):
        o = op.attr(name, 0) or 0
        if not o:
            o = int(s * scale)
        out.append(o)
    return out


@register_op("linear_interp", inputs=["X"], outputs=["Out"])
def _linear_interp(ctx, op, ins):
    x = ins["X"][0]  # [N, C, W]
    (ow,) = _interp_size(op, x.shape[2:])
    return {
        "Out": [jax.image.resize(x, (*x.shape[:2], ow), method="linear")]
    }


@register_op("bicubic_interp", inputs=["X"], outputs=["Out"])
def _bicubic_interp(ctx, op, ins):
    x = ins["X"][0]
    oh, ow = _interp_size(op, x.shape[2:])
    return {
        "Out": [jax.image.resize(x, (*x.shape[:2], oh, ow), method="cubic")]
    }


@register_op("trilinear_interp", inputs=["X"], outputs=["Out"])
def _trilinear_interp(ctx, op, ins):
    x = ins["X"][0]  # [N, C, D, H, W]
    od, oh, ow = _interp_size(op, x.shape[2:])
    return {
        "Out": [
            jax.image.resize(x, (*x.shape[:2], od, oh, ow), method="linear")
        ]
    }


@register_op("affine_grid", inputs=["Theta", "OutputShape"], outputs=["Output"])
def _affine_grid(ctx, op, ins):
    """affine_grid_op.cc: sampling grid for a spatial transformer. Grid
    coords are normalized to [-1, 1]; align_corners semantics follow the
    reference default (True)."""
    theta = ins["Theta"][0]  # [N, 2, 3]
    shape = op.attr("output_shape", None)
    if not shape:
        # XLA needs a static grid shape: a runtime OutputShape tensor is
        # only usable when it is a trace-time constant (weak check via
        # concrete_or_error keeps the error actionable)
        shape = jax.core.concrete_or_error(
            np.asarray, ins["OutputShape"][0],
            "affine_grid needs a static output shape under jit; pass the "
            "output_shape attr (layers.affine_grid does) instead of a "
            "computed OutputShape tensor.",
        )
    n, c, h, w = [int(v) for v in shape]
    align = op.attr("align_corners", True)
    if align:
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
    else:
        ys = (jnp.arange(h) + 0.5) * 2.0 / h - 1.0
        xs = (jnp.arange(w) + 0.5) * 2.0 / w - 1.0
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [H, W, 3]
    out = jnp.einsum("hwk,njk->nhwj", base, theta)  # [N, H, W, 2]
    return {"Output": [out]}


# ---------------------------------------------------------------------------
# similarity_focus (similarity_focus_op.cc): for each (axis-)slice, mark
# the channels where that slice attains the per-position max; output is a
# 0/1 focus mask of X's shape.
# ---------------------------------------------------------------------------


def _greedy_focus_mask(m):
    """m: [A, B]. The reference's procedure (similarity_focus_op.h): walk
    values in descending order, tag a position when neither its row nor its
    column is already tagged. lax.scan over the sorted order — each row and
    column contributes at most one tag."""
    a, b = m.shape
    order = jnp.argsort(-m.reshape(-1))

    def step(carry, k):
        row_used, col_used, mask = carry
        i = order[k] // b
        j = order[k] % b
        take = (~row_used[i]) & (~col_used[j])
        return (
            row_used.at[i].set(row_used[i] | take),
            col_used.at[j].set(col_used[j] | take),
            mask.at[i, j].set(jnp.where(take, 1.0, mask[i, j])),
        ), None

    (_, _, mask), _ = lax.scan(
        step,
        (jnp.zeros((a,), bool), jnp.zeros((b,), bool), jnp.zeros((a, b))),
        jnp.arange(a * b),
    )
    return mask


@register_op("similarity_focus", inputs=["X"], outputs=["Out"])
def _similarity_focus(ctx, op, ins):
    x = ins["X"][0]  # [N, C, A, B]
    axis = op.attr("axis", 1)
    indexes = op.attr("indexes", [0])
    if axis != 1:
        # reference supports axis in {1, 2, 3}; normalize to channel-axis
        x = jnp.moveaxis(x, axis, 1)
    masks = []
    for idx in indexes:
        masks.append(jax.vmap(_greedy_focus_mask)(x[:, idx]))
    mask = jnp.clip(sum(masks), 0.0, 1.0)  # [N, A, B]
    out = jnp.broadcast_to(mask[:, None].astype(x.dtype), x.shape)
    if axis != 1:
        out = jnp.moveaxis(out, 1, axis)
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# CTR batched FC family (batch_fc_op.cc, rank_attention_op.cc)
# ---------------------------------------------------------------------------


@register_op("batch_fc", inputs=["Input", "W", "Bias"], outputs=["Out"])
def _batch_fc(ctx, op, ins):
    x, w, b = ins["Input"][0], ins["W"][0], ins["Bias"][0]
    # [S, B, in] @ [S, in, out] + [S, 1, out] — one bmm on the MXU vs the
    # reference's per-slot cublas loop (batch_fc_op.cu:30)
    return {"Out": [jnp.einsum("sbi,sio->sbo", x, w) + b]}


@register_op(
    "rank_attention",
    inputs=["X", "RankOffset", "RankParam"],
    outputs=["Out", "InputHelp", "InsRank"],
)
def _rank_attention(ctx, op, ins):
    """rank_attention_op.cu: every instance picks per-(own-rank, other-rank)
    weight blocks from RankParam and averages x @ W_block over its valid
    interaction pairs. RankOffset row: [ins_rank, idx0, rank0, idx1, rank1,
    ...] with -1 padding (CTR position-bias modeling)."""
    x = ins["X"][0]  # [N, D]
    offset = ins["RankOffset"][0].astype(jnp.int32)  # [N, 1+2*M]
    param = ins["RankParam"][0]  # [max_rank*max_rank*D, out]
    max_rank = op.attr("MaxRank", 3)
    out_dim = param.shape[-1]
    n, d = x.shape
    m = (offset.shape[1] - 1) // 2
    ins_rank = offset[:, 0]  # [N]
    other_rank = offset[:, 2::2]  # [N, M] (-1 = absent)
    valid = (other_rank >= 0) & (ins_rank[:, None] >= 0)
    # block index of pair (ins_rank i, other_rank j): (i*max_rank + j)
    blk = jnp.clip(ins_rank[:, None] * max_rank + other_rank, 0,
                   max_rank * max_rank - 1)
    w = param.reshape(max_rank * max_rank, d, out_dim)
    wsel = w[blk]  # [N, M, D, out]
    y = jnp.einsum("nd,nmdo->nmo", x, wsel)
    y = jnp.where(valid[..., None], y, 0.0)
    cnt = jnp.maximum(valid.sum(axis=1, keepdims=True), 1)
    out = y.sum(axis=1) / cnt
    return {
        "Out": [out],
        "InputHelp": [x],
        "InsRank": [ins_rank.astype(x.dtype)[:, None]],
    }


@register_op("fsp", inputs=["X", "Y"], outputs=["Out"])
def _fsp(ctx, op, ins):
    """fsp_op.cc (flow-of-solution-procedure matrix for distillation):
    Out[b] = X_flat @ Y_flat^T / (H*W)."""
    x, y = ins["X"][0], ins["Y"][0]
    h, w = x.shape[2], x.shape[3]
    return {
        "Out": [
            jnp.einsum("bchw,bdhw->bcd", x, y) / (h * w)
        ]
    }


# ---------------------------------------------------------------------------
# deformable ops: bilinear sampling at learned offsets. The CUDA kernels
# (deformable_conv_op.cu modulated_deformable_im2col) become one dense
# gather-weighted sum; everything stays static-shape.
# ---------------------------------------------------------------------------


def _gather_nchw(x, yi, xi):
    """x [C,H,W], yi/xi [S,OH,OW] int -> [C,S,OH,OW]"""
    return x[:, yi, xi]


def _deform_sample(x, py, px):
    """x [N,C,H,W]; py/px [N,S,OH,OW] -> [N,C,S,OH,OW] bilinear, 0 outside."""
    n, c, h, w = x.shape
    y0 = jnp.floor(py)
    x0 = jnp.floor(px)
    wy1 = py - y0
    wx1 = px - x0
    acc = None
    for dy, wy in ((0.0, 1 - wy1), (1.0, wy1)):
        for dx, wx in ((0.0, 1 - wx1), (1.0, wx1)):
            yy = y0 + dy
            xx = x0 + dx
            inside = (yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1)
            yi = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xi = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            g = jax.vmap(_gather_nchw)(x, yi, xi)  # [N,C,S,OH,OW]
            wgt = (wy * wx * inside.astype(x.dtype))[:, None]
            acc = g * wgt if acc is None else acc + g * wgt
    return acc


def _deformable_conv_impl(ctx, op, ins, modulated):
    x, offset, w = ins["Input"][0], ins["Offset"][0], ins["Filter"][0]
    mask = ins.get("Mask", [None])[0] if modulated else None
    kh, kw = w.shape[2], w.shape[3]
    sh, sw = _pair(op.attr("strides", [1, 1]))
    ph, pw = _pair(op.attr("paddings", [0, 0]))
    dh, dw = _pair(op.attr("dilations", [1, 1]))
    groups = op.attr("groups", 1) or 1
    dg = op.attr("deformable_groups", 1) or 1
    n, c, h, wd = x.shape
    oh, ow = offset.shape[2], offset.shape[3]
    # offsets: [N, 2*dg*kh*kw, OH, OW] -> y/x per (dg, kh*kw)
    off = offset.reshape(n, dg, kh * kw, 2, oh, ow)
    off_y = off[:, :, :, 0]
    off_x = off[:, :, :, 1]
    kyy = np.repeat(np.arange(kh) * dh, kw)  # [kh*kw]
    kxx = np.tile(np.arange(kw) * dw, kh)
    py = (
        off_y
        + jnp.asarray(
            (np.arange(oh) * sh - ph)[None, None, None, :, None]
            + kyy[None, None, :, None, None]
        )
    )  # [N, dg, kh*kw, OH, OW]
    px = (
        off_x
        + jnp.asarray(
            (np.arange(ow) * sw - pw)[None, None, None, None, :]
            + kxx[None, None, :, None, None]
        )
    )
    cg = c // dg  # channels per deformable group
    outs = []
    for g in range(dg):  # dg is small (1-4) and static
        xg = x[:, g * cg:(g + 1) * cg]
        sampled = _deform_sample(
            xg, py[:, g], px[:, g]
        )  # [N, cg, kh*kw, OH, OW]
        if mask is not None:
            mg = mask.reshape(n, dg, kh * kw, oh, ow)[:, g]
            sampled = sampled * mg[:, None]
        outs.append(sampled)
    cols = jnp.concatenate(outs, axis=1)  # [N, C, kh*kw, OH, OW]
    # grouped conv as einsum: w [OC, C/groups, kh, kw]
    oc = w.shape[0]
    wg = w.reshape(groups, oc // groups, c // groups, kh * kw)
    colsg = cols.reshape(n, groups, c // groups, kh * kw, oh, ow)
    out = jnp.einsum("ngckhw,gock->ngohw", colsg, wg).reshape(n, oc, oh, ow)
    return {"Output": [out]}


@register_op(
    "deformable_conv",
    inputs=["Input", "Offset", "Mask", "Filter"],
    outputs=["Output"],
)
def _deformable_conv(ctx, op, ins):
    return _deformable_conv_impl(ctx, op, ins, modulated=True)


@register_op(
    "deformable_conv_v1",
    inputs=["Input", "Offset", "Filter"],
    outputs=["Output"],
)
def _deformable_conv_v1(ctx, op, ins):
    return _deformable_conv_impl(ctx, op, ins, modulated=False)


# ---------------------------------------------------------------------------
# position-sensitive / precise ROI pooling (psroi_pool_op.cc,
# prroi_pool_op.cc, deformable_psroi_pooling_op.cu). Fixed sample grids
# per bin (roi_align-style) replace data-dependent bin loops.
# ---------------------------------------------------------------------------


def _roi_bin_sample(x_img, roi, ph, pw, scale, samples):
    """x_img [C,H,W]; roi [4] (x1,y1,x2,y2) in image coords; fixed
    samples x samples bilinear grid per bin, averaged -> [C, ph, pw]."""
    c, h, w = x_img.shape
    x1, y1, x2, y2 = roi[0] * scale, roi[1] * scale, roi[2] * scale, roi[3] * scale
    rh = jnp.maximum(y2 - y1, 0.1) / ph
    rw = jnp.maximum(x2 - x1, 0.1) / pw
    s = samples
    iy = (jnp.arange(s) + 0.5) / s
    ix = (jnp.arange(s) + 0.5) / s
    by = y1 + rh * (jnp.arange(ph)[:, None] + iy[None, :])  # [ph, s]
    bx = x1 + rw * (jnp.arange(pw)[:, None] + ix[None, :])  # [pw, s]
    py = jnp.broadcast_to(by[:, None, :, None], (ph, pw, s, s))
    px = jnp.broadcast_to(bx[None, :, None, :], (ph, pw, s, s))
    y0 = jnp.floor(py)
    x0 = jnp.floor(px)
    wy1 = py - y0
    wx1 = px - x0
    acc = 0.0
    for dy, wy in ((0.0, 1 - wy1), (1.0, wy1)):
        for dx, wx in ((0.0, 1 - wx1), (1.0, wx1)):
            yy = jnp.clip(y0 + dy, 0, h - 1).astype(jnp.int32)
            xx = jnp.clip(x0 + dx, 0, w - 1).astype(jnp.int32)
            inside = ((y0 + dy >= 0) & (y0 + dy <= h - 1)
                      & (x0 + dx >= 0) & (x0 + dx <= w - 1))
            g = x_img[:, yy, xx]  # [C, ph, pw, s, s]
            acc = acc + g * (wy * wx * inside)[None]
    vals = acc.mean(axis=(-2, -1))  # [C, ph, pw]
    return vals


@register_op(
    "psroi_pool", inputs=["X", "ROIs", "RoisNum"], outputs=["Out"]
)
def _psroi_pool(ctx, op, ins):
    x = ins["X"][0]
    rois = ins["ROIs"][0].astype(jnp.float32)
    ph = op.attr("pooled_height", 1)
    pw = op.attr("pooled_width", 1)
    out_c = op.attr("output_channels")
    scale = op.attr("spatial_scale", 1.0)
    from .detection import _roi_batch_idx

    R = rois.shape[0]
    batch_idx = _roi_batch_idx(
        ins.get("RoisNum", [None])[0], R, x.shape[0], ctx.abstract
    )

    def one(roi, bi):
        vals = _roi_bin_sample(x[bi], roi, ph, pw, scale, 2)
        # position-sensitive channel select: bin (i,j) of output channel k
        # reads input channel k*ph*pw + i*pw + j
        sel = vals.reshape(out_c, ph * pw, ph, pw)
        pos = jnp.arange(ph * pw).reshape(ph, pw)
        return sel[:, pos, jnp.arange(ph)[:, None], jnp.arange(pw)[None, :]]

    out = jax.vmap(one)(rois, batch_idx)
    return {"Out": [out]}


@register_op(
    "prroi_pool", inputs=["X", "ROIs", "BatchRoINums"], outputs=["Out"]
)
def _prroi_pool(ctx, op, ins):
    """Precise ROI pooling: exact bilinear integral over each bin. The
    fixed-grid average (4x4 samples/bin) converges to the same integral and
    keeps shapes static (prroi_pool_op.cc computes it analytically on CPU)."""
    x = ins["X"][0]
    rois = ins["ROIs"][0].astype(jnp.float32)
    ph = op.attr("pooled_height", 1)
    pw = op.attr("pooled_width", 1)
    scale = op.attr("spatial_scale", 1.0)
    from .detection import _roi_batch_idx

    R = rois.shape[0]
    batch_idx = _roi_batch_idx(
        ins.get("BatchRoINums", [None])[0], R, x.shape[0], ctx.abstract
    )

    def one(roi, bi):
        return _roi_bin_sample(x[bi], roi, ph, pw, scale, 4)

    return {"Out": [jax.vmap(one)(rois, batch_idx)]}


@register_op(
    "deformable_psroi_pooling",
    inputs=["Input", "ROIs", "Trans"],
    outputs=["Output", "TopCount"],
)
def _deformable_psroi_pooling(ctx, op, ins):
    x = ins["Input"][0]
    rois = ins["ROIs"][0].astype(jnp.float32)
    trans = ins.get("Trans", [None])[0]
    ph = op.attr("pooled_height", 1)
    pw = op.attr("pooled_width", 1)
    out_c = op.attr("output_dim")
    scale = op.attr("spatial_scale", 1.0)
    trans_std = op.attr("trans_std", 0.1)
    no_trans = op.attr("no_trans", False) or trans is None
    from .detection import _roi_batch_idx

    R = rois.shape[0]
    batch_idx = _roi_batch_idx(None, R, x.shape[0], ctx.abstract)

    def one(i, roi, bi):
        img = x[bi]
        c, h, w = img.shape
        x1, y1 = roi[0] * scale, roi[1] * scale
        rw = jnp.maximum((roi[2] - roi[0]) * scale, 0.1) / pw
        rh = jnp.maximum((roi[3] - roi[1]) * scale, 0.1) / ph
        if no_trans:
            dy = dx = jnp.zeros((ph, pw))
        else:
            # trans [R, 2, ph, pw]: learned per-bin shifts in roi units
            dy = trans[i, 0] * trans_std * rh * ph
            dx = trans[i, 1] * trans_std * rw * pw
        s = 2
        off = (jnp.arange(s) + 0.5) / s
        py = (y1 + rh * (jnp.arange(ph)[:, None] + off[None, :]))  # [ph,s]
        px = (x1 + rw * (jnp.arange(pw)[:, None] + off[None, :]))  # [pw,s]
        py = py[:, None, :, None] + dy[:, :, None, None]  # [ph,pw,s,1]
        px = px[None, :, None, :] + dx[:, :, None, None]  # [ph,pw,1,s]
        py = jnp.broadcast_to(py, (ph, pw, s, s))
        px = jnp.broadcast_to(px, (ph, pw, s, s))
        y0 = jnp.floor(py)
        x0 = jnp.floor(px)
        wy1, wx1 = py - y0, px - x0
        acc = 0.0
        for ddy, wy in ((0.0, 1 - wy1), (1.0, wy1)):
            for ddx, wx in ((0.0, 1 - wx1), (1.0, wx1)):
                yy = jnp.clip(y0 + ddy, 0, h - 1).astype(jnp.int32)
                xx = jnp.clip(x0 + ddx, 0, w - 1).astype(jnp.int32)
                inside = ((y0 + ddy >= 0) & (y0 + ddy <= h - 1)
                          & (x0 + ddx >= 0) & (x0 + ddx <= w - 1))
                acc = acc + img[:, yy, xx] * (wy * wx * inside)[None]
        vals = acc.mean(axis=(-2, -1))  # [C, ph, pw]
        sel = vals.reshape(out_c, ph * pw, ph, pw)
        pos = jnp.arange(ph * pw).reshape(ph, pw)
        return sel[:, pos, jnp.arange(ph)[:, None], jnp.arange(pw)[None, :]]

    out = jax.vmap(one)(jnp.arange(R), rois, batch_idx)
    top = jnp.ones((R, out_c, ph, pw), x.dtype)
    return {"Output": [out], "TopCount": [top]}


# ---------------------------------------------------------------------------
# text-matching convs (PyramidDNN family: var_conv_2d_op.cc,
# match_matrix_tensor_op.cc, tree_conv_op.cc). LoD-variable images become
# padded dense tensors + masks.
# ---------------------------------------------------------------------------


@register_op(
    "match_matrix_tensor", inputs=["X", "Y", "W"], outputs=["Out", "Tmp"]
)
def _match_matrix_tensor(ctx, op, ins):
    x, y, w = ins["X"][0], ins["Y"][0], ins["W"][0]
    # x [B, Lx, D1], y [B, Ly, D2], w [D1, T, D2]
    tmp = jnp.einsum("bld,dte->blte", x, w)
    out = jnp.einsum("blte,bme->btlm", tmp, y)  # [B, T, Lx, Ly]
    return {"Out": [out], "Tmp": [tmp]}


@register_op("var_conv_2d", inputs=["X", "ROW", "COLUMN", "W"], outputs=["Out", "Col"])
def _var_conv_2d(ctx, op, ins):
    """var_conv_2d_op.cc: conv over per-sample variable-size 1-channel
    match images. Padded-dense form: X [B, H, W] with masks via ROW/COLUMN
    lengths handled upstream; plain conv here."""
    x, w = ins["X"][0], ins["W"][0]
    oc = op.attr("OutputChannel")
    ic = op.attr("InputChannel", 1)
    kh, kw = op.attr("KernelH", 3), op.attr("KernelW", 3)
    sh, sw = op.attr("StrideH", 1), op.attr("StrideW", 1)
    if x.ndim == 3:
        x = x[:, None]  # [B, 1, H, W]
    wf = w.reshape(oc, ic, kh, kw)
    out = _nhwc_conv(
        x, wf,
        window_strides=[sh, sw],
        padding=[((kh - 1) // 2,) * 2, ((kw - 1) // 2,) * 2],
    )
    return {"Out": [out], "Col": [x]}


@register_op(
    "tree_conv", inputs=["NodesVector", "EdgeSet", "Filter"], outputs=["Out"]
)
def _tree_conv(ctx, op, ins):
    """tree_conv_op.cc (tree-based convolution, TBCNN): each node aggregates
    its continuous-binary-tree neighborhood with position-interpolated
    weights Wt/Wl/Wr. Dense form: adjacency from EdgeSet [B, E, 2]
    (parent<-child), max_depth 2 window."""
    nodes = ins["NodesVector"][0]  # [B, N, D]
    edges = ins["EdgeSet"][0].astype(jnp.int32)  # [B, E, 2]
    filt = ins["Filter"][0]  # [D, 3, out, num_filters] per reference
    b, n, d = nodes.shape
    wt, wl, wr = filt[:, 0], filt[:, 1], filt[:, 2]  # [D, out, F]

    # adjacency: A[b, p, c] = 1 for each edge (p, c)
    def adj_one(e):
        a = jnp.zeros((n, n))
        return a.at[e[:, 0], e[:, 1]].set(1.0)

    A = jax.vmap(adj_one)(edges)
    deg = jnp.maximum(A.sum(-1, keepdims=True), 1.0)
    child_mean = (A @ nodes) / deg  # [B, N, D]
    out = (
        jnp.einsum("bnd,dof->bnof", nodes, wt)
        + 0.5 * jnp.einsum("bnd,dof->bnof", child_mean, wl)
        + 0.5 * jnp.einsum("bnd,dof->bnof", child_mean, wr)
    )
    b_, n_, o_, f_ = out.shape
    return {"Out": [jnp.tanh(out).reshape(b_, n_, o_ * f_)]}


# ---------------------------------------------------------------------------
# lstmp (lstmp_op.cc: LSTM with recurrent projection, Google LVCSR) and
# attention_lstm (attention_lstm_op.cc fused CTR attention + LSTM)
# ---------------------------------------------------------------------------


@register_op(
    "lstmp",
    inputs=["X", "WIH", "WHH", "ProjWeight", "Bias", "H0", "C0", "SeqLen"],
    outputs=["Projection", "Out", "LastH", "LastC"],
)
def _lstmp(ctx, op, ins):
    from .rnn import _seq_mask

    x = ins["X"][0]  # [B, T, D]
    wih, whh = ins["WIH"][0], ins["WHH"][0]  # [4H, D], [4H, P]
    wproj = ins["ProjWeight"][0]  # [H, P]
    bias = ins.get("Bias", [None])[0]
    B, T, D = x.shape
    H = wproj.shape[0]
    P = wproj.shape[1]
    h0 = ins.get("H0", [None])[0]
    c0 = ins.get("C0", [None])[0]
    r0 = jnp.zeros((B, P), x.dtype) if h0 is None else h0
    c0 = jnp.zeros((B, H), x.dtype) if c0 is None else c0
    lens = ins.get("SeqLen", [None])[0]
    xs = jnp.swapaxes(x, 0, 1)
    xproj = jnp.einsum("tbd,gd->tbg", xs, wih)
    if bias is not None:
        xproj = xproj + bias
    mask = _seq_mask(lens, B, T)

    def step(carry, inp):
        r, c = carry
        xp, m = inp
        gates = xp + r @ whh.T
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        c_new = f * c + i * jnp.tanh(g)
        h_new = o * jnp.tanh(c_new)
        r_new = h_new @ wproj
        act_p = op.attr("proj_activation", "identity")
        if act_p == "tanh":
            r_new = jnp.tanh(r_new)
        r_out = m * r_new + (1 - m) * r
        c_out = m * c_new + (1 - m) * c
        return (r_out, c_out), (r_out, h_new * m)

    (r_last, c_last), (rs, hs) = lax.scan(step, (r0, c0), (xproj, mask))
    return {
        "Projection": [jnp.swapaxes(rs, 0, 1)],
        "Out": [jnp.swapaxes(hs, 0, 1)],
        "LastH": [r_last],
        "LastC": [c_last],
    }


@register_op(
    "attention_lstm",
    inputs=[
        "X", "C0", "H0", "AttentionWeight", "AttentionBias",
        "AttentionScalar", "AttentionScalarBias", "LSTMWeight", "LSTMBias",
        "SeqLen",
    ],
    outputs=["Hidden", "Cell"],
)
def _attention_lstm(ctx, op, ins):
    """attention_lstm_op.cc: at every step, attention over the whole input
    sequence conditioned on the previous cell state selects a context
    vector that feeds one LSTM step. Padded re-derivation of the fused CPU
    kernel: scores = scalar * act(concat(x_j, c_prev) @ Wa + ba) + bs."""
    from .rnn import _seq_mask

    x = ins["X"][0]  # [B, T, D]
    c0 = ins["C0"][0]
    h0 = ins.get("H0", [None])[0]
    wa = ins["AttentionWeight"][0]  # [D + C, 1]
    ba = ins.get("AttentionBias", [None])[0]
    ws = ins.get("AttentionScalar", [None])[0]
    bs = ins.get("AttentionScalarBias", [None])[0]
    wl = ins["LSTMWeight"][0]  # [D + H, 4H]
    bl = ins.get("LSTMBias", [None])[0]
    B, T, D = x.shape
    H = wl.shape[1] // 4
    h0 = jnp.zeros((B, H), x.dtype) if h0 is None else h0
    lens = ins.get("SeqLen", [None])[0]
    mask = _seq_mask(lens, B, T)  # [T, B, 1]
    seq_mask = jnp.swapaxes(mask, 0, 1)[..., 0]  # [B, T]

    def step(carry, _):
        h, c = carry
        # attention: score each x_j against current cell state
        cexp = jnp.broadcast_to(c[:, None, :], (B, T, c.shape[-1]))
        feat = jnp.concatenate([x, cexp], axis=-1)  # [B, T, D+C]
        e = jnp.tanh(feat @ wa + (ba if ba is not None else 0.0))[..., 0]
        if ws is not None:
            e = ws.reshape(()) * e + (bs.reshape(()) if bs is not None else 0.0)
        e = jnp.where(seq_mask > 0, e, -1e9)
        alpha = jax.nn.softmax(e, axis=-1)  # [B, T]
        context = jnp.einsum("bt,btd->bd", alpha, x)
        inp = jnp.concatenate([context, h], axis=-1)
        gates = inp @ wl + (bl if bl is not None else 0.0)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        c_new = f * c + i * jnp.tanh(g)
        h_new = o * jnp.tanh(c_new)
        return (h_new, c_new), h_new

    (h_last, c_last), hs = lax.scan(step, (h0, c0), None, length=T)
    return {"Hidden": [jnp.swapaxes(hs, 0, 1)], "Cell": [c_last]}
