"""Operator library: importing this package registers every op emitter.

The registry (framework.registry) is the TPU-native analogue of the
reference's OpRegistry (op_registry.h); modules here cover the kernel surface
of paddle/fluid/operators/ that the BASELINE workloads need.
"""

from . import (  # noqa: F401
    _helpers,
    activation,
    amp_ops,
    beam_search,
    collective,
    control_flow,
    crf,
    ctr_ops,
    detection,
    detection_ext,
    fused,
    kv_cache,
    loss_ext,
    math,
    math_ext,
    metrics,
    nn,
    nn_ext,
    optimizer_ops,
    quant_ops,
    random,
    rnn,
    sparse,
    tensor_ext,
    tensor_ops,
)

# parallelism ops live beside their collectives implementation
from ..parallel import moe as _moe_ops  # noqa: F401,E402
from ..parallel import ring_attention as _ring_ops  # noqa: F401,E402

from ..framework.registry import registered_ops  # noqa: F401
