"""Operator library: importing this package registers every op emitter.

The registry (framework.registry) is the TPU-native analogue of the
reference's OpRegistry (op_registry.h); modules here cover the kernel surface
of paddle/fluid/operators/ that the BASELINE workloads need.
"""

from . import (  # noqa: F401
    _helpers,
    activation,
    amp_ops,
    collective,
    math,
    metrics,
    nn,
    optimizer_ops,
    random,
    tensor_ops,
)

from ..framework.registry import registered_ops  # noqa: F401
