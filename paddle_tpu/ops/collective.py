"""Collective communication ops.

TPU-native replacement for the reference's NCCL collective ops
(operators/collective/c_allreduce_op.h:33-112, c_broadcast_op, c_allgather_op,
c_reducescatter_op, collective_helper.h): each op emits an XLA collective
(psum/all_gather/psum_scatter/ppermute/all_to_all). Under the Executor's SPMD
mode the block runs inside jax.shard_map over a Mesh, so these lower to ICI
collectives; ring construction/topology is XLA's job (no ring_id/comm maps).

Outside a mesh (single-chip run) every collective degrades to identity /
no-op, which is also the reference's nranks==1 behavior.

The reference's ring_id attr maps to our "axis_name" attr (default "dp"): a
named mesh axis replaces a communicator ring. c_sync_*_stream ops are no-ops:
XLA's dataflow ordering replaces stream synchronization.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.registry import register_op


def _axis(ctx, op):
    """Mesh axis this collective runs over, or None when not under shard_map."""
    name = op.attr("axis_name", "dp")
    return name if name in ctx.mesh_axes else None


def _record(ctx, kind, x, ax):
    """Count the collective and its per-shard payload bytes by kind.

    Emitters run at TRACE time, so these counters advance once per program
    compile (per collective op in the block), not once per device step —
    the right granularity for "how much ICI traffic does one step issue",
    since the compiled step replays the same collectives every run.

    When the Executor attached a ``ctx.wire_stats`` holder, the site also
    accumulates its single-traversal ring wire estimate (payload x
    (n-1)/n) there — the per-executable wire total behind the
    ``perf.step_attribution`` cross-check, available even when the full
    cost model declines the program."""
    if ax is None:
        return
    from .. import observability as _obs
    from ..resilience.faults import fault_point

    # chaos seam: an armed "collective.dispatch" fault aborts the trace,
    # modeling a peer dropping out mid-compile (EQuARX-style collective
    # layer failures); surfaced to the Executor as a typed error
    fault_point("collective.dispatch")
    _obs.add(f"collective.{kind}")
    try:
        nbytes = int(x.size) * x.dtype.itemsize
    except (AttributeError, TypeError):
        return
    _obs.add(f"collective.{kind}.bytes", nbytes)
    if ctx is not None and getattr(ctx, "wire_stats", None) is not None:
        n = int(ctx.axis_sizes.get(ax, 1))
        if n > 1:
            ctx.wire_stats["bytes"] += nbytes * (n - 1) / n


def _register_allreduce(op_type, reducer):
    @register_op(op_type, inputs=["X"], outputs=["Out"], differentiable=False)
    def emit(ctx, op, ins):
        x = ins["X"][0]
        ax = _axis(ctx, op)
        _record(ctx, op_type, x, ax)
        return {"Out": [x if ax is None else reducer(x, ax)]}

    return emit


_register_allreduce("c_allreduce_sum", lambda x, ax: lax.psum(x, ax))
_register_allreduce("c_allreduce_max", lambda x, ax: lax.pmax(x, ax))
_register_allreduce("c_allreduce_min", lambda x, ax: lax.pmin(x, ax))
_register_allreduce(
    "c_allreduce_prod", lambda x, ax: jnp.exp(lax.psum(jnp.log(x), ax))
)
_register_allreduce("allreduce", lambda x, ax: lax.psum(x, ax))


@register_op("mp_allreduce_sum", inputs=["X"], outputs=["Out"])
def _mp_allreduce_sum(ctx, op, ins):
    """DIFFERENTIABLE in-graph allreduce (reference
    operators/collective/c_allreduce_op.h with use_model_parallel — the
    forward-graph allreduce of tensor/sequence parallelism, unlike
    c_allreduce_sum which the transpilers append post-backward). Under
    shard_map psum transposes to psum, so each replica's unit cotangent
    would arrive axis_size-fold; the correction keeps the forward value
    while scaling the cotangent down (same trick as pipeline.py:196)."""
    x = ins["X"][0]
    ax = _axis(ctx, op)
    _record(ctx, "mp_allreduce_sum", x, ax)
    if ax is None:
        return {"Out": [x]}
    n = ctx.axis_sizes[ax]
    total = lax.psum(x, ax)
    return {"Out": [total / n + lax.stop_gradient(total * (n - 1) / n)]}


@register_op("c_broadcast", inputs=["X"], outputs=["Out"], differentiable=False)
def _c_broadcast(ctx, op, ins):
    x = ins["X"][0]
    ax = _axis(ctx, op)
    _record(ctx, "c_broadcast", x, ax)
    if ax is None:
        return {"Out": [x]}
    root = op.attr("root", 0)
    idx = lax.axis_index(ax)
    src = jnp.where(idx == root, x, jnp.zeros_like(x))
    return {"Out": [lax.psum(src, ax)]}


@register_op("c_allgather", inputs=["X"], outputs=["Out"], differentiable=False)
def _c_allgather(ctx, op, ins):
    x = ins["X"][0]
    ax = _axis(ctx, op)
    _record(ctx, "c_allgather", x, ax)
    if ax is None:
        return {"Out": [x]}
    out = lax.all_gather(x, ax)  # [nranks, ...]
    return {"Out": [out.reshape((-1,) + x.shape[1:])]}


@register_op(
    "c_reducescatter", inputs=["X"], outputs=["Out"], differentiable=False
)
def _c_reducescatter(ctx, op, ins):
    x = ins["X"][0]
    ax = _axis(ctx, op)
    _record(ctx, "c_reducescatter", x, ax)
    if ax is None:
        return {"Out": [x]}
    return {"Out": [lax.psum_scatter(x, ax, scatter_dimension=0, tiled=True)]}


@register_op("alltoall", inputs=["X"], outputs=["Out"], differentiable=False)
def _alltoall(ctx, op, ins):
    x = ins["X"][0]
    ax = _axis(ctx, op)
    _record(ctx, "alltoall", x, ax)
    if ax is None:
        return {"Out": [x]}
    n = lax.axis_size(ax)
    xs = x.reshape((n, x.shape[0] // n) + x.shape[1:])
    out = lax.all_to_all(xs, ax, split_axis=0, concat_axis=0, tiled=False)
    return {"Out": [out.reshape(x.shape)]}


@register_op(
    "collective_permute", inputs=["X"], outputs=["Out"], differentiable=False
)
def _collective_permute(ctx, op, ins):
    x = ins["X"][0]
    ax = _axis(ctx, op)
    _record(ctx, "collective_permute", x, ax)
    if ax is None:
        return {"Out": [x]}
    n = lax.axis_size(ax)
    shift = op.attr("shift", 1)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return {"Out": [lax.ppermute(x, ax, perm)]}


@register_op(
    "c_allreduce_any", inputs=["X"], outputs=["Out"], differentiable=False
)
def _c_allreduce_any(ctx, op, ins):
    """Cross-rank logical OR (max over int cast) — the AMP FoundInfinite
    reduction of the sharded weight update: after a reduce-scatter each
    rank checks finiteness of only ITS 1/N grad shard, so the loss-scale
    automaton must see "any rank saw a non-finite" or the ranks' scales
    silently diverge (the ZeRO analog of the reference's nccl allreduce
    on found_inf)."""
    x = ins["X"][0]
    ax = _axis(ctx, op)
    _record(ctx, "c_allreduce_any", x, ax)
    if ax is None:
        return {"Out": [x]}
    return {"Out": [lax.pmax(x.astype(jnp.int32), ax).astype(x.dtype)]}


# ---------------------------------------------------------------------------
# ZeRO-style weight-update sharding collectives (arXiv:2004.13336) with an
# opt-in EQuARX-style block-quantized wire format (arXiv:2506.17615).
#
# Data layout contract (parallel/transpiler.py ShardedWeightUpdate is the
# only producer): gradients/optimizer state travel as FLAT [pad_len]
# vectors, pad_len a multiple of nranks (and of quant_block when
# quantized); the dp-sharded state vars are declared at global [pad_len]
# with spec ("dp",) so each rank's shard_map body sees its [pad_len/n]
# shard. Outside a mesh both ops degrade to the identity pipeline
# (flatten+pad / unpad+reshape), which is also the single-chip math.
# ---------------------------------------------------------------------------


def _quant_precision(quant, dtype):
    if quant and quant != "none":
        return quant
    return {"float32": "fp32", "bfloat16": "bf16", "float16": "fp16",
            "float64": "fp64"}.get(str(jnp.dtype(dtype)), str(dtype))


def _record_zero(ctx, kind, op, payload_elems, dtype, ax, n):
    """Count a sharded-update collective and its estimated ring WIRE bytes
    (payload x (n-1)/n, plus per-block scale overhead when quantized) by
    kind and precision: collective.bytes.reduce_scatter_int8 etc. Trace-
    time granularity, like _record (once per compiled collective site);
    the exact wire estimate also lands in ``ctx.wire_stats`` when the
    Executor attached the per-executable attribution holder."""
    if ax is None:
        return
    from .. import observability as _obs
    from ..resilience.faults import fault_point

    fault_point("collective.dispatch")
    quant = op.attr("quant", "none")
    block = int(op.attr("quant_block", 256) or 256)
    if quant and quant != "none":
        payload = payload_elems * 1.0 + (payload_elems / block) * 4.0
        precision = quant
    else:
        payload = float(payload_elems) * jnp.dtype(dtype).itemsize
        precision = _quant_precision(None, dtype)
    wire = int(payload * (n - 1) / n) if n > 1 else 0
    _obs.add(f"collective.{kind}")
    _obs.add(f"collective.bytes.{kind}_{precision}", wire)
    if ctx is not None and getattr(ctx, "wire_stats", None) is not None:
        ctx.wire_stats["bytes"] += wire


def _block_quantize(x, block):
    """int8-quantize `x` (fp, last dim a multiple of `block`) in blocks
    with per-block fp32 abs-max scales. Returns (q int8 same shape,
    scales fp32 [..., nblocks])."""
    xb = x.reshape(x.shape[:-1] + (x.shape[-1] // block, block))
    xb = xb.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xb), axis=-1) / 127.0
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(xb / safe[..., None]), -127, 127).astype(jnp.int8)
    return q.reshape(x.shape), safe


def _block_dequantize(q, scales, block):
    """fp32 dequantization of :func:`_block_quantize` output."""
    qb = q.reshape(q.shape[:-1] + (q.shape[-1] // block, block))
    return (qb.astype(jnp.float32) * scales[..., None]).reshape(q.shape)


def _record_bucket(members, payload_bytes):
    """Count one bucketed collective site: how many buckets the compiled
    step issues and how many payload bytes ride in them. Trace-time
    granularity like every other collective counter (once per compiled
    site, which the step replays)."""
    from .. import observability as _obs

    _obs.add("collective.buckets")
    _obs.add("collective.bucket_bytes", int(payload_bytes))
    _obs.add("collective.bucket_members", int(members))


@register_op(
    "c_bucket_allreduce_sum", inputs=["X"], outputs=["Out"],
    differentiable=False,
)
def _c_bucket_allreduce_sum(ctx, op, ins):
    """Bucketed gradient allreduce (the DP overlap schedule): flatten and
    concatenate the member gradients (optional 1/N scale folded in), issue
    ONE psum over the bucket, split the reduced buffer back per member.
    Elementwise sums are unchanged by concatenation, so the fp32 result is
    BITWISE the per-grad c_allreduce_sum sequence — the bucket only
    changes how many collectives the wire sees and how early each fires.
    Bucket membership and order are part of the cross-rank contract
    (analysis/collectives.py carries them in the site kind)."""
    # no None-filtering: every member slot must hold a real gradient, and
    # dropping one would silently misalign the split-back below
    xs = list(ins["X"])
    ax = _axis(ctx, op)
    scale = op.attr("scale", None)
    if scale is not None:
        xs = [x * jnp.asarray(scale, x.dtype) for x in xs]
    if ax is None:
        return {"Out": list(xs)}
    sizes = [int(x.size) for x in xs]
    flat = jnp.concatenate([x.reshape(-1) for x in xs])
    _record(ctx, "c_bucket_allreduce_sum", flat, ax)
    _record_bucket(len(xs), int(flat.size) * flat.dtype.itemsize)
    total = lax.psum(flat, ax)
    out, off = [], 0
    for x, n in zip(xs, sizes):
        out.append(total[off:off + n].reshape(x.shape))
        off += n
    return {"Out": out}


@register_op(
    "zero_reduce_scatter", inputs=["X"], outputs=["Out"],
    differentiable=False,
)
def _zero_reduce_scatter(ctx, op, ins):
    """Flatten + optional scale + pad a gradient to [pad_len], then
    reduce-scatter it over `axis_name`: each rank ends with the globally
    summed [pad_len/n] shard it will update. quant="int8" swaps the
    fp-wire psum_scatter for block-quantized all_to_all + fp32-accumulated
    local sum (EQuARX: quantize per hop, accumulate full precision)."""
    x = ins["X"][0]
    ax = _axis(ctx, op)
    pad_len = int(op.attr("pad_len"))
    scale = op.attr("scale", None)
    quant = op.attr("quant", "none") or "none"
    block = int(op.attr("quant_block", 256) or 256)
    flat = x.reshape(-1)
    if scale is not None:
        flat = flat * jnp.asarray(scale, flat.dtype)
    if pad_len > flat.shape[0]:
        flat = jnp.pad(flat, (0, pad_len - flat.shape[0]))
    n = int(ctx.axis_sizes.get(ax, 1)) if ax is not None else 1
    _record_zero(ctx, "reduce_scatter", op, pad_len, flat.dtype, ax, n)
    if ax is None:
        return {"Out": [flat]}
    if quant == "none":
        return {"Out": [
            lax.psum_scatter(flat, ax, scatter_dimension=0, tiled=True)
        ]}
    # int8 path: quantize each destination rank's shard in blocks, exchange
    # int8 payload + fp32 per-block scales, dequantize and SUM IN FP32
    shards = flat.reshape(n, pad_len // n)
    q, scales = _block_quantize(shards, block)
    q = lax.all_to_all(q, ax, split_axis=0, concat_axis=0, tiled=False)
    scales = lax.all_to_all(
        scales, ax, split_axis=0, concat_axis=0, tiled=False
    )
    acc = jnp.sum(_block_dequantize(q, scales, block), axis=0)
    return {"Out": [acc.astype(x.dtype)]}


@register_op(
    "zero_bucket_reduce_scatter", inputs=["X"], outputs=["Out"],
    differentiable=False,
)
def _zero_bucket_reduce_scatter(ctx, op, ins):
    """Bucketed ZeRO gradient reduce-scatter: every member gradient is
    flattened + scaled + padded to its own [pad_len_i] exactly like
    zero_reduce_scatter, then the members' per-rank shards are interleaved
    into ONE [sum(pad)] exchange — rank r's slice of the bucket is the
    concatenation of the members' rank-r shards, so each output shard is
    elementwise identical to the per-grad op's. One collective per bucket
    instead of one per gradient; the bucket fires as soon as its LAST
    member gradient is produced (transpiler), so earlier buckets' wire
    time hides behind the remaining backward compute.

    quant="int8" runs the same EQuARX block-quantized exchange as
    zero_reduce_scatter; every member pad is aligned to nranks*quant_block
    (ShardedWeightUpdate._pad_len), so quant blocks never straddle member
    boundaries and the per-block scales equal the per-grad path's.

    Exchange layout: members sharing a pad length STACK into one
    [m, n, pad/n] buffer — a contiguous concatenation of their flat
    [pad] vectors viewed rank-major, zero data movement beyond the copy —
    and scatter over the rank dim in ONE collective; distinct pad lengths
    within a bucket each get their own stack. An interleaved single-buffer
    layout would need a strided transpose of the whole bucket per step,
    which costs more than the collectives it saves."""
    # no None-filtering: members zip pairwise against pad_lens and the
    # declared Out shards, so a dropped slot would shift every later
    # member onto the wrong pad/output
    xs = list(ins["X"])
    ax = _axis(ctx, op)
    pad_lens = [int(p) for p in op.attr("pad_lens")]
    scale = op.attr("scale", None)
    quant = op.attr("quant", "none") or "none"
    block = int(op.attr("quant_block", 256) or 256)
    flats = []
    for x, pad in zip(xs, pad_lens):
        flat = x.reshape(-1)
        if scale is not None:
            flat = flat * jnp.asarray(scale, flat.dtype)
        if pad > flat.shape[0]:
            flat = jnp.pad(flat, (0, pad - flat.shape[0]))
        flats.append(flat)
    total = sum(pad_lens)
    n = int(ctx.axis_sizes.get(ax, 1)) if ax is not None else 1
    dtype = flats[0].dtype if flats else jnp.float32
    _record_zero(ctx, "bucket_reduce_scatter", op, total, dtype, ax, n)
    if ax is not None:
        _record_bucket(len(xs), total * jnp.dtype(dtype).itemsize)
    if ax is None:
        return {"Out": flats}
    # group members by pad length (deterministic from pad_lens, so the
    # grouping is rank-uniform by construction)
    groups = {}
    for i, pad in enumerate(pad_lens):
        groups.setdefault(pad, []).append(i)
    out = [None] * len(flats)
    for pad, idxs in groups.items():
        k = pad // n
        stacked = jnp.stack([flats[i] for i in idxs]).reshape(
            len(idxs), n, k
        )
        if quant == "none":
            shards = lax.psum_scatter(
                stacked, ax, scatter_dimension=1, tiled=True
            )  # [m, 1, k]: rank r holds the summed member rows r
        else:
            q, scales = _block_quantize(stacked, block)
            q = lax.all_to_all(
                q, ax, split_axis=1, concat_axis=1, tiled=True
            )
            scales = lax.all_to_all(
                scales, ax, split_axis=1, concat_axis=1, tiled=True
            )
            deq = _block_dequantize(
                q.reshape(len(idxs), n, k), scales, block
            )
            shards = jnp.sum(deq, axis=1, keepdims=True).astype(dtype)
        shards = shards.reshape(len(idxs), k)
        for j, i in enumerate(idxs):
            out[i] = shards[j]
    return {"Out": out}


@register_op(
    "zero_all_gather", inputs=["X"], outputs=["Out"], differentiable=False
)
def _zero_all_gather(ctx, op, ins):
    """All-gather a rank's updated [pad_len/n] parameter shard back to the
    full parameter: concatenate shards, drop padding, reshape to `shape`.
    quant="int8" ships the shards block-quantized (the EQuARX trade: the
    replicated working copy is transport-quantized; the rank's own master
    shard keeps full precision)."""
    x = ins["X"][0]
    ax = _axis(ctx, op)
    shape = tuple(int(d) for d in op.attr("shape"))
    pad_len = int(op.attr("pad_len"))
    numel = 1
    for d in shape:
        numel *= d
    quant = op.attr("quant", "none") or "none"
    block = int(op.attr("quant_block", 256) or 256)
    n = int(ctx.axis_sizes.get(ax, 1)) if ax is not None else 1
    _record_zero(ctx, "all_gather", op, pad_len, x.dtype, ax, n)
    if ax is None:
        full = x
    elif quant == "none":
        full = lax.all_gather(x, ax, tiled=True)
    else:
        q, scales = _block_quantize(x, block)
        q = lax.all_gather(q, ax, tiled=True)
        scales = lax.all_gather(scales, ax, tiled=True)
        full = _block_dequantize(q, scales, block).astype(x.dtype)
    return {"Out": [full[:numel].reshape(shape)]}


@register_op(
    "zero_pad_flatten", inputs=["X"], outputs=["Out"], differentiable=False
)
def _zero_pad_flatten(ctx, op, ins):
    """Startup-side init of a sharded-update state var: flatten X and
    zero-pad to [pad_len] (the global flat layout zero_reduce_scatter /
    zero_all_gather exchange). Runs meshless in the startup program; the
    executor's SPMD staging slices each rank's shard out afterwards."""
    x = ins["X"][0]
    pad_len = int(op.attr("pad_len"))
    flat = x.reshape(-1)
    if pad_len > flat.shape[0]:
        flat = jnp.pad(flat, (0, pad_len - flat.shape[0]))
    return {"Out": [flat]}


@register_op("c_identity", inputs=["X"], outputs=["Out"])
def _c_identity(ctx, op, ins):
    return {"Out": [ins["X"][0]]}


def _register_noop(op_type, io=("X", "Out")):
    @register_op(op_type, inputs=[io[0]], outputs=[io[1]], differentiable=False)
    def emit(ctx, op, ins):
        vals = ins.get(io[0], [])
        return {io[1]: list(vals)}

    return emit


# stream sync is meaningless under XLA's dataflow ordering; kept for API parity
_register_noop("c_sync_calc_stream")
_register_noop("c_sync_comm_stream")


@register_op("c_comm_init_all", inputs=[], outputs=[], differentiable=False)
def _c_comm_init_all(ctx, op, ins):
    return {}


@register_op("barrier", inputs=["X"], outputs=["Out"], differentiable=False)
def _barrier(ctx, op, ins):
    x = ins["X"][0] if ins.get("X") and ins["X"][0] is not None else jnp.zeros([1])
    ax = _axis(ctx, op)
    _record(ctx, "barrier", None, ax)  # zero-payload sync: count the op, no bytes
    if ax is None:
        return {"Out": [x]}
    return {"Out": [x + 0 * lax.psum(jnp.zeros([1], x.dtype), ax)]}
