"""Collective communication ops.

TPU-native replacement for the reference's NCCL collective ops
(operators/collective/c_allreduce_op.h:33-112, c_broadcast_op, c_allgather_op,
c_reducescatter_op, collective_helper.h): each op emits an XLA collective
(psum/all_gather/psum_scatter/ppermute/all_to_all). Under the Executor's SPMD
mode the block runs inside jax.shard_map over a Mesh, so these lower to ICI
collectives; ring construction/topology is XLA's job (no ring_id/comm maps).

Outside a mesh (single-chip run) every collective degrades to identity /
no-op, which is also the reference's nranks==1 behavior.

The reference's ring_id attr maps to our "axis_name" attr (default "dp"): a
named mesh axis replaces a communicator ring. c_sync_*_stream ops are no-ops:
XLA's dataflow ordering replaces stream synchronization.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.registry import register_op


def _axis(ctx, op):
    """Mesh axis this collective runs over, or None when not under shard_map."""
    name = op.attr("axis_name", "dp")
    return name if name in ctx.mesh_axes else None


def _record(kind, x, ax):
    """Count the collective and its per-shard payload bytes by kind.

    Emitters run at TRACE time, so these counters advance once per program
    compile (per collective op in the block), not once per device step —
    the right granularity for "how much ICI traffic does one step issue",
    since the compiled step replays the same collectives every run."""
    if ax is None:
        return
    from .. import observability as _obs
    from ..resilience.faults import fault_point

    # chaos seam: an armed "collective.dispatch" fault aborts the trace,
    # modeling a peer dropping out mid-compile (EQuARX-style collective
    # layer failures); surfaced to the Executor as a typed error
    fault_point("collective.dispatch")
    _obs.add(f"collective.{kind}")
    try:
        nbytes = int(x.size) * x.dtype.itemsize
    except (AttributeError, TypeError):
        return
    _obs.add(f"collective.{kind}.bytes", nbytes)


def _register_allreduce(op_type, reducer):
    @register_op(op_type, inputs=["X"], outputs=["Out"], differentiable=False)
    def emit(ctx, op, ins):
        x = ins["X"][0]
        ax = _axis(ctx, op)
        _record(op_type, x, ax)
        return {"Out": [x if ax is None else reducer(x, ax)]}

    return emit


_register_allreduce("c_allreduce_sum", lambda x, ax: lax.psum(x, ax))
_register_allreduce("c_allreduce_max", lambda x, ax: lax.pmax(x, ax))
_register_allreduce("c_allreduce_min", lambda x, ax: lax.pmin(x, ax))
_register_allreduce(
    "c_allreduce_prod", lambda x, ax: jnp.exp(lax.psum(jnp.log(x), ax))
)
_register_allreduce("allreduce", lambda x, ax: lax.psum(x, ax))


@register_op("mp_allreduce_sum", inputs=["X"], outputs=["Out"])
def _mp_allreduce_sum(ctx, op, ins):
    """DIFFERENTIABLE in-graph allreduce (reference
    operators/collective/c_allreduce_op.h with use_model_parallel — the
    forward-graph allreduce of tensor/sequence parallelism, unlike
    c_allreduce_sum which the transpilers append post-backward). Under
    shard_map psum transposes to psum, so each replica's unit cotangent
    would arrive axis_size-fold; the correction keeps the forward value
    while scaling the cotangent down (same trick as pipeline.py:196)."""
    x = ins["X"][0]
    ax = _axis(ctx, op)
    _record("mp_allreduce_sum", x, ax)
    if ax is None:
        return {"Out": [x]}
    n = ctx.axis_sizes[ax]
    total = lax.psum(x, ax)
    return {"Out": [total / n + lax.stop_gradient(total * (n - 1) / n)]}


@register_op("c_broadcast", inputs=["X"], outputs=["Out"], differentiable=False)
def _c_broadcast(ctx, op, ins):
    x = ins["X"][0]
    ax = _axis(ctx, op)
    _record("c_broadcast", x, ax)
    if ax is None:
        return {"Out": [x]}
    root = op.attr("root", 0)
    idx = lax.axis_index(ax)
    src = jnp.where(idx == root, x, jnp.zeros_like(x))
    return {"Out": [lax.psum(src, ax)]}


@register_op("c_allgather", inputs=["X"], outputs=["Out"], differentiable=False)
def _c_allgather(ctx, op, ins):
    x = ins["X"][0]
    ax = _axis(ctx, op)
    _record("c_allgather", x, ax)
    if ax is None:
        return {"Out": [x]}
    out = lax.all_gather(x, ax)  # [nranks, ...]
    return {"Out": [out.reshape((-1,) + x.shape[1:])]}


@register_op(
    "c_reducescatter", inputs=["X"], outputs=["Out"], differentiable=False
)
def _c_reducescatter(ctx, op, ins):
    x = ins["X"][0]
    ax = _axis(ctx, op)
    _record("c_reducescatter", x, ax)
    if ax is None:
        return {"Out": [x]}
    return {"Out": [lax.psum_scatter(x, ax, scatter_dimension=0, tiled=True)]}


@register_op("alltoall", inputs=["X"], outputs=["Out"], differentiable=False)
def _alltoall(ctx, op, ins):
    x = ins["X"][0]
    ax = _axis(ctx, op)
    _record("alltoall", x, ax)
    if ax is None:
        return {"Out": [x]}
    n = lax.axis_size(ax)
    xs = x.reshape((n, x.shape[0] // n) + x.shape[1:])
    out = lax.all_to_all(xs, ax, split_axis=0, concat_axis=0, tiled=False)
    return {"Out": [out.reshape(x.shape)]}


@register_op(
    "collective_permute", inputs=["X"], outputs=["Out"], differentiable=False
)
def _collective_permute(ctx, op, ins):
    x = ins["X"][0]
    ax = _axis(ctx, op)
    _record("collective_permute", x, ax)
    if ax is None:
        return {"Out": [x]}
    n = lax.axis_size(ax)
    shift = op.attr("shift", 1)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return {"Out": [lax.ppermute(x, ax, perm)]}


@register_op("c_identity", inputs=["X"], outputs=["Out"])
def _c_identity(ctx, op, ins):
    return {"Out": [ins["X"][0]]}


def _register_noop(op_type, io=("X", "Out")):
    @register_op(op_type, inputs=[io[0]], outputs=[io[1]], differentiable=False)
    def emit(ctx, op, ins):
        vals = ins.get(io[0], [])
        return {io[1]: list(vals)}

    return emit


# stream sync is meaningless under XLA's dataflow ordering; kept for API parity
_register_noop("c_sync_calc_stream")
_register_noop("c_sync_comm_stream")


@register_op("c_comm_init_all", inputs=[], outputs=[], differentiable=False)
def _c_comm_init_all(ctx, op, ins):
    return {}


@register_op("barrier", inputs=["X"], outputs=["Out"], differentiable=False)
def _barrier(ctx, op, ins):
    x = ins["X"][0] if ins.get("X") and ins["X"][0] is not None else jnp.zeros([1])
    ax = _axis(ctx, op)
    _record("barrier", None, ax)  # zero-payload sync: count the op, no bytes
    if ax is None:
        return {"Out": [x]}
    return {"Out": [x + 0 * lax.psum(jnp.zeros([1], x.dtype), ax)]}
